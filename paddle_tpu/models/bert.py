"""BERT/ERNIE-base encoder — static-graph builder (BASELINE config 3).

Reference parity target: ERNIE-1.0/BERT-base pretraining recipe (the
reference framework trains it through PaddleNLP on the same op set: matmul,
layer_norm, softmax, lookup_table, dropout, gelu — SURVEY §2.1 op library).

TPU-native: one traced program; attention is batched matmuls on the MXU;
sequence dim fixed per bucket. Tensor-parallel variant annotates qkv/ffn
params with shard_spec for GSPMD (parallel/tensor_parallel.py applies specs).
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.initializer import NormalInitializer, ConstantInitializer
from paddle_tpu.param_attr import ParamAttr


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attn_dropout: float = 0.1
    initializer_range: float = 0.02
    # TPU-native: tensor-parallel axis name (None = no TP annotations)
    tp_axis: Optional[str] = None
    # TPU-native: fused memory-efficient attention (Pallas kernel on TPU)
    # instead of the materialized-scores matmul/softmax/matmul pattern
    use_flash_attention: bool = True

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


def _attr(cfg: BertConfig, name: str, shard_spec=None):
    return ParamAttr(name=name,
                     initializer=NormalInitializer(0.0, cfg.initializer_range),
                     shard_spec=shard_spec)


def _tp(cfg: BertConfig, *spec):
    """Build a PartitionSpec-style tuple only when TP is on."""
    if cfg.tp_axis is None:
        return None
    return tuple(s if s != "tp" else cfg.tp_axis for s in spec)


def encoder_layer(cfg: BertConfig, x, attn_mask, idx: int, is_test=False):
    """One transformer block: MHA + FFN, post-LN (BERT style)."""
    h = cfg.hidden_size
    nh, hd = cfg.num_heads, cfg.head_dim
    pre = f"encoder_{idx}"

    # qkv fused projection: [h, 3h] sharded on output dim under TP
    qkv = layers.fc(x, 3 * h, num_flatten_dims=2,
                    param_attr=_attr(cfg, f"{pre}.qkv.w", _tp(cfg, None, "tp")),
                    bias_attr=ParamAttr(name=f"{pre}.qkv.b",
                                        initializer=ConstantInitializer(0.0),
                                        shard_spec=_tp(cfg, "tp")))
    q, k, v = layers.split(qkv, 3, dim=2)

    if cfg.use_flash_attention:
        # packed [B, T, H] call — the head split/merge happens inside the
        # fused op, keeping the graph free of reshape/transpose ops
        ctxv = layers.flash_attention(q, k, v, attn_mask,
                                      dropout_prob=cfg.attn_dropout,
                                      is_test=is_test,
                                      num_heads=nh)  # [B, T, H]
    else:
        def heads(t, name):
            t = layers.reshape(t, [0, -1, nh, hd], name=name)
            return layers.transpose(t, [0, 2, 1, 3])  # [B, nh, T, hd]

        q, k, v = (heads(q, f"{pre}.q"), heads(k, f"{pre}.k"),
                   heads(v, f"{pre}.v"))
        scores = layers.matmul(q, k, transpose_y=True, alpha=1.0 / math.sqrt(hd))
        # mask: [B,1,1,T] additive
        scores = layers.elementwise_add(scores, layers.unsqueeze(attn_mask, [1]))
        probs = layers.softmax(scores)
        if cfg.attn_dropout > 0:
            probs = layers.dropout(probs, cfg.attn_dropout, is_test=is_test,
                                   dropout_implementation="upscale_in_train")
        ctxv = layers.matmul(probs, v)  # [B, nh, T, hd]
        ctxv = layers.transpose(ctxv, [0, 2, 1, 3])
        ctxv = layers.reshape(ctxv, [0, -1, nh * hd])
    # output proj: input dim sharded under TP (row-parallel)
    attn_out = layers.fc(ctxv, h, num_flatten_dims=2,
                         param_attr=_attr(cfg, f"{pre}.attn_out.w", _tp(cfg, "tp", None)),
                         bias_attr=ParamAttr(name=f"{pre}.attn_out.b",
                                             initializer=ConstantInitializer(0.0)))
    if cfg.hidden_dropout > 0:
        attn_out = layers.dropout(attn_out, cfg.hidden_dropout, is_test=is_test,
                                  dropout_implementation="upscale_in_train")
    x = layers.layer_norm(layers.elementwise_add(x, attn_out), begin_norm_axis=2,
                          param_attr=ParamAttr(name=f"{pre}.ln1.scale",
                                               initializer=ConstantInitializer(1.0)),
                          bias_attr=ParamAttr(name=f"{pre}.ln1.bias",
                                              initializer=ConstantInitializer(0.0)))

    ffn1 = layers.fc(x, cfg.ffn_size, num_flatten_dims=2, act="gelu",
                     param_attr=_attr(cfg, f"{pre}.ffn1.w", _tp(cfg, None, "tp")),
                     bias_attr=ParamAttr(name=f"{pre}.ffn1.b",
                                         initializer=ConstantInitializer(0.0),
                                         shard_spec=_tp(cfg, "tp")))
    ffn2 = layers.fc(ffn1, h, num_flatten_dims=2,
                     param_attr=_attr(cfg, f"{pre}.ffn2.w", _tp(cfg, "tp", None)),
                     bias_attr=ParamAttr(name=f"{pre}.ffn2.b",
                                         initializer=ConstantInitializer(0.0)))
    if cfg.hidden_dropout > 0:
        ffn2 = layers.dropout(ffn2, cfg.hidden_dropout, is_test=is_test,
                              dropout_implementation="upscale_in_train")
    return layers.layer_norm(layers.elementwise_add(x, ffn2), begin_norm_axis=2,
                             param_attr=ParamAttr(name=f"{pre}.ln2.scale",
                                                  initializer=ConstantInitializer(1.0)),
                             bias_attr=ParamAttr(name=f"{pre}.ln2.bias",
                                                 initializer=ConstantInitializer(0.0)))


def embeddings(cfg: BertConfig, src_ids, pos_ids, sent_ids, is_test=False):
    tok = layers.embedding(src_ids, [cfg.vocab_size, cfg.hidden_size],
                           param_attr=_attr(cfg, "word_embedding", _tp(cfg, "tp", None)))
    pos = layers.embedding(pos_ids, [cfg.max_position, cfg.hidden_size],
                           param_attr=_attr(cfg, "pos_embedding"))
    sent = layers.embedding(sent_ids, [cfg.type_vocab_size, cfg.hidden_size],
                            param_attr=_attr(cfg, "sent_embedding"))
    emb = layers.elementwise_add(layers.elementwise_add(tok, pos), sent)
    emb = layers.layer_norm(emb, begin_norm_axis=2,
                            param_attr=ParamAttr(name="emb.ln.scale",
                                                 initializer=ConstantInitializer(1.0)),
                            bias_attr=ParamAttr(name="emb.ln.bias",
                                                initializer=ConstantInitializer(0.0)))
    if cfg.hidden_dropout > 0:
        emb = layers.dropout(emb, cfg.hidden_dropout, is_test=is_test,
                             dropout_implementation="upscale_in_train")
    return emb


def bert_encoder(cfg: BertConfig, src_ids, pos_ids, sent_ids, input_mask,
                 is_test=False):
    """input_mask: [B, T] float (1 = token). Returns sequence output [B,T,H]."""
    emb = embeddings(cfg, src_ids, pos_ids, sent_ids, is_test)
    # additive mask [B,1,T]: (mask-1)*10000 → 0 for keep, -10000 for pad
    # (the packed flash path consumes [B,1,T]; the dense path re-expands)
    neg = layers.scale(layers.elementwise_add(input_mask,
                                              layers.fill_constant([1], "float32", -1.0)),
                       scale=10000.0)
    mask4 = layers.unsqueeze(neg, [1])
    x = emb
    # each transformer block is one remat unit: under remat_policy
    # "minimal"/"full" the whole block's forward is recomputed in the
    # backward pass instead of keeping its activations resident
    from ..core.program import remat_unit
    for i in range(cfg.num_layers):
        with remat_unit(f"bert_layer_{i}"):
            x = encoder_layer(cfg, x, mask4, i, is_test)
    return x


def bert_pretrain_loss(cfg: BertConfig, seq_out, mlm_labels, input_mask):
    """Masked-LM loss over all positions (labels = -100 to ignore), plus
    tied-embedding decoding is approximated with its own output matrix."""
    logits = layers.fc(seq_out, cfg.vocab_size, num_flatten_dims=2,
                       param_attr=_attr(cfg, "mlm_out.w", _tp(cfg, None, "tp")),
                       bias_attr=ParamAttr(name="mlm_out.b",
                                           initializer=ConstantInitializer(0.0),
                                           shard_spec=_tp(cfg, "tp")))
    loss = layers.softmax_with_cross_entropy(logits, mlm_labels, ignore_index=-100)
    # mean over non-ignored tokens
    valid = layers.cast(layers.not_equal(
        mlm_labels, layers.fill_constant([1], "int64", -100)), "float32")
    total = layers.reduce_sum(layers.elementwise_mul(loss, valid))
    denom = layers.reduce_sum(valid)
    return layers.elementwise_div(total, denom)


def build_pretrain_program(cfg: BertConfig, batch_size: int, seq_len: int,
                           optimizer_factory=None, is_test=False):
    """Build (main, startup, feeds, fetch) for a full pretrain step."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = layers.data("src_ids", [seq_len], dtype="int64")
        pos = layers.data("pos_ids", [seq_len], dtype="int64")
        sent = layers.data("sent_ids", [seq_len], dtype="int64")
        mask = layers.data("input_mask", [seq_len], dtype="float32")
        labels = layers.data("mlm_labels", [seq_len, 1], dtype="int64")
        seq_out = bert_encoder(cfg, src, pos, sent, mask, is_test)
        loss = bert_pretrain_loss(cfg, seq_out, labels, mask)
        if optimizer_factory is not None:
            opt = optimizer_factory()
            opt.minimize(loss)
    return main, startup, ["src_ids", "pos_ids", "sent_ids", "input_mask", "mlm_labels"], loss


def param_count(cfg: BertConfig) -> int:
    h, f, v = cfg.hidden_size, cfg.ffn_size, cfg.vocab_size
    per_layer = 3 * h * h + 3 * h + h * h + h + 2 * (2 * h) + h * f + f + f * h + h
    emb = v * h + cfg.max_position * h + cfg.type_vocab_size * h + 2 * h
    head = h * v + v
    return cfg.num_layers * per_layer + emb + head
