"""DeepFM CTR (BASELINE config 5; Criteo-style high-dim sparse lookup_table).

Reference capability replaced: the pserver sparse-embedding path
(distributed_lookup_table + parameter_prefetch.cc) becomes a HBM-resident
embedding table shardable over the mesh model axis (Parameter.shard_spec),
with XLA all-to-all doing the row exchange GSPMD-style.
"""
from __future__ import annotations

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.initializer import UniformInitializer
from paddle_tpu.param_attr import ParamAttr


def deepfm(sparse_ids, dense_feats, vocab_size: int, num_fields: int,
           embed_dim: int = 16, hidden_sizes=(400, 400, 400),
           shard_axis=None, is_sparse: bool = False):
    """sparse_ids: [B, num_fields] int64; dense_feats: [B, num_dense].

    is_sparse=True (opt-in) routes the table gradients through SelectedRows
    rows (lookup_table_op.cc sparse path) — O(batch·dim) gradient work
    instead of a dense [vocab, dim] scatter per step. Opt-in because only
    sgd/adam have SelectedRows kernels (grad clipping and other optimizers
    need dense grads), matching the reference's constraint."""
    spec = (shard_axis, None) if shard_axis else None
    # first-order weights
    w1 = layers.embedding(sparse_ids, [vocab_size, 1], is_sparse=is_sparse,
                          param_attr=ParamAttr(name="fm_w1",
                                               initializer=UniformInitializer(-1e-4, 1e-4),
                                               shard_spec=spec))
    first_order = layers.reduce_sum(w1, dim=[1, 2], keep_dim=False)

    # second-order: embeddings [B, F, D]
    emb = layers.embedding(sparse_ids, [vocab_size, embed_dim],
                           is_sparse=is_sparse,
                           param_attr=ParamAttr(name="fm_emb",
                                                initializer=UniformInitializer(-1e-2, 1e-2),
                                                shard_spec=spec))
    sum_sq = layers.square(layers.reduce_sum(emb, dim=[1]))
    sq_sum = layers.reduce_sum(layers.square(emb), dim=[1])
    second_order = layers.scale(
        layers.reduce_sum(layers.elementwise_sub(sum_sq, sq_sum), dim=[1]), scale=0.5)

    # deep part
    deep = layers.reshape(emb, [0, num_fields * embed_dim])
    deep = layers.concat([deep, dense_feats], axis=1)
    for i, hs in enumerate(hidden_sizes):
        deep = layers.fc(deep, hs, act="relu", name=f"deep_{i}")
    deep_out = layers.fc(deep, 1, name="deep_out")

    logit = layers.elementwise_add(
        layers.elementwise_add(layers.unsqueeze(first_order, [1]),
                               layers.unsqueeze(second_order, [1])),
        deep_out)
    return logit


def build_train_program(vocab_size=100000, num_fields=26, num_dense=13,
                        embed_dim=16, lr=1e-3, shard_axis=None,
                        is_sparse=False, embedding_optimizer=None):
    """embedding_optimizer="sgd" puts the two Criteo-scale tables on plain
    SGD while the dense net keeps Adam — the reference's CTR practice
    (Downpour sparse tables run their own one-state rule while the dense
    net runs a full optimizer). On TPU this matters doubly: XLA lowers a
    sparse table update as an O(table) scatter pass (measured 10.9 ms per
    [33M,16] f32 scatter on v5e regardless of sorted/unique hints), so
    Adam's three table passes (param+moment1+moment2) cost 3x what SGD's
    one pass does."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("sparse_ids", [num_fields], dtype="int64")
        dense = layers.data("dense", [num_dense])
        label = layers.data("label", [1])
        logit = deepfm(ids, dense, vocab_size, num_fields, embed_dim,
                       shard_axis=shard_axis, is_sparse=is_sparse)
        loss = layers.mean(
            layers.sigmoid_cross_entropy_with_logits(logit, label))
        prob = layers.sigmoid(logit)
        if embedding_optimizer is None:
            fluid.optimizer.Adam(lr).minimize(loss)
        else:
            if embedding_optimizer != "sgd":
                raise ValueError(
                    f"embedding_optimizer={embedding_optimizer!r}: only "
                    "'sgd' is supported (one-state table updates)")
            adam = fluid.optimizer.Adam(lr)
            sgd = fluid.optimizer.SGD(lr)
            # ONE backward pass, gradients split across the two rules
            params_grads = adam.backward(loss)
            table_names = {"fm_w1", "fm_emb"}
            table_pg = [pg for pg in params_grads
                        if pg[0].name in table_names]
            dense_pg = [pg for pg in params_grads
                        if pg[0].name not in table_names]
            adam.apply_gradients(dense_pg)
            sgd.apply_gradients(table_pg)
    return main, startup, ["sparse_ids", "dense", "label"], loss, prob
