"""DeepFM CTR (BASELINE config 5; Criteo-style high-dim sparse lookup_table).

Reference capability replaced: the pserver sparse-embedding path
(distributed_lookup_table + parameter_prefetch.cc) becomes a HBM-resident
embedding table shardable over the mesh model axis (Parameter.shard_spec),
with XLA all-to-all doing the row exchange GSPMD-style; the reference's
O(touched-rows) sparse-apply cost model (selected_rows_functor.cc MergeAdd +
optimizers/adagrad_op.cc sparse kernels) is restored by the deferred-row
update ring (ops/deferred_rows.py) instead of XLA's O(table) scatter.
"""
from __future__ import annotations

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.initializer import UniformInitializer
from paddle_tpu.param_attr import ParamAttr


def deepfm(sparse_ids, dense_feats, vocab_size: int, num_fields: int,
           embed_dim: int = 16, hidden_sizes=(400, 400, 400),
           shard_axis=None, is_sparse: bool = False,
           fused_table: bool = False, state_mult: int = 1,
           row_packed: bool = False):
    """sparse_ids: [B, num_fields] int64; dense_feats: [B, num_dense].

    is_sparse=True (opt-in) routes the table gradients through SelectedRows
    rows (lookup_table_op.cc sparse path) — O(batch·dim) gradient work
    instead of a dense [vocab, dim] scatter per step. Opt-in because only
    sgd/adam/adagrad have SelectedRows kernels (grad clipping and other
    optimizers need dense grads), matching the reference's constraint.

    fused_table=True stores the first-order weights as column `embed_dim`
    of a single [vocab, embed_dim+1] table (one gather + one sparse-update
    stream instead of two — a TPU-native fusion; the math is identical to
    the reference's separate [vocab,1] + [vocab,D] tables since the two
    lookups always share their ids).

    state_mult>1 widens the table rows to carry the deferred-row
    optimizer's moment state in-row (the Downpour g2sum layout — see
    ops/deferred_rows.py): 2 for adagrad, 3 for adam. The model reads
    only the visible [:embed_dim+1] columns.
    """
    spec = (shard_axis, None) if shard_axis else None
    if state_mult > 1 and not fused_table:
        raise ValueError("state_mult>1 (deferred moment state) requires "
                         "fused_table=True")
    if fused_table:
        from paddle_tpu.initializer import RowPackInitializer
        vis = embed_dim + 1
        init = (RowPackInitializer(vis, vis * state_mult, -1e-2, 1e-2)
                if row_packed else UniformInitializer(-1e-2, 1e-2))
        both = layers.embedding(
            sparse_ids, [vocab_size, vis * state_mult], is_sparse=is_sparse,
            row_pack=row_packed,
            param_attr=ParamAttr(name="fm_t", initializer=init,
                                 shard_spec=spec))
        if state_mult > 1:
            both = layers.slice(both, axes=[2], starts=[0], ends=[vis])
        w1 = layers.slice(both, axes=[2], starts=[embed_dim],
                          ends=[embed_dim + 1])
        emb = layers.slice(both, axes=[2], starts=[0], ends=[embed_dim])
    else:
        # first-order weights
        w1 = layers.embedding(sparse_ids, [vocab_size, 1], is_sparse=is_sparse,
                              param_attr=ParamAttr(name="fm_w1",
                                                   initializer=UniformInitializer(-1e-4, 1e-4),
                                                   shard_spec=spec))
        emb = layers.embedding(sparse_ids, [vocab_size, embed_dim],
                               is_sparse=is_sparse,
                               param_attr=ParamAttr(name="fm_emb",
                                                    initializer=UniformInitializer(-1e-2, 1e-2),
                                                    shard_spec=spec))
    first_order = layers.reduce_sum(w1, dim=[1, 2], keep_dim=False)

    # second-order: embeddings [B, F, D]
    sum_sq = layers.square(layers.reduce_sum(emb, dim=[1]))
    sq_sum = layers.reduce_sum(layers.square(emb), dim=[1])
    second_order = layers.scale(
        layers.reduce_sum(layers.elementwise_sub(sum_sq, sq_sum), dim=[1]), scale=0.5)

    # deep part
    deep = layers.reshape(emb, [0, num_fields * embed_dim])
    deep = layers.concat([deep, dense_feats], axis=1)
    for i, hs in enumerate(hidden_sizes):
        deep = layers.fc(deep, hs, act="relu", name=f"deep_{i}")
    deep_out = layers.fc(deep, 1, name="deep_out")

    logit = layers.elementwise_add(
        layers.elementwise_add(layers.unsqueeze(first_order, [1]),
                               layers.unsqueeze(second_order, [1])),
        deep_out)
    return logit


_TABLE_NAMES = {"fm_w1", "fm_emb", "fm_t"}


def _table_optimizer(kind, lr, deferred_rows, packed_rows):
    if kind == "sgd":
        return fluid.optimizer.SGD(lr, deferred_rows=deferred_rows,
                                   packed_rows=packed_rows)
    if kind == "adagrad":
        return fluid.optimizer.Adagrad(lr, deferred_rows=deferred_rows,
                                       packed_rows=packed_rows)
    if kind == "adam":
        return fluid.optimizer.Adam(lr, deferred_rows=deferred_rows,
                                    packed_rows=packed_rows)
    raise ValueError(
        f"embedding_optimizer={kind!r}: expected one of sgd/adagrad/adam")


def build_train_program(vocab_size=100000, num_fields=26, num_dense=13,
                        embed_dim=16, lr=1e-3, shard_axis=None,
                        is_sparse=False, embedding_optimizer=None,
                        deferred_rows=None, fused_table=False,
                        packed_rows=None, hidden_sizes=(400, 400, 400)):
    """embedding_optimizer="sgd"/"adagrad"/"adam" puts the Criteo-scale
    table(s) on their own rule while the dense net keeps Adam — the
    reference's CTR practice (Downpour sparse tables run their own rule
    while the dense net runs a full optimizer).

    deferred_rows={"rows_per_step": B*num_fields[, "segments": K]} routes
    the table updates through the deferred-row ring (O(touched rows) per
    step + one amortized fold pass every K steps) instead of XLA's
    O(table) scatter — see ops/deferred_rows.py. Requires is_sparse=True
    and an embedding_optimizer choice.
    """
    state_mult = 1
    if deferred_rows is not None or packed_rows is not None:
        if not (is_sparse and fused_table):
            raise ValueError(
                "deferred_rows/packed_rows need is_sparse=True "
                "(SelectedRows grads) and fused_table=True (single lookup "
                "site per table)")
        state_mult = {"sgd": 1, "adagrad": 2, "adam": 3}.get(
            embedding_optimizer, 1)
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("sparse_ids", [num_fields], dtype="int64")
        dense = layers.data("dense", [num_dense])
        label = layers.data("label", [1])
        logit = deepfm(ids, dense, vocab_size, num_fields, embed_dim,
                       hidden_sizes=hidden_sizes,
                       shard_axis=shard_axis, is_sparse=is_sparse,
                       fused_table=fused_table, state_mult=state_mult,
                       row_packed=packed_rows is not None)
        loss = layers.mean(
            layers.sigmoid_cross_entropy_with_logits(logit, label))
        prob = layers.sigmoid(logit)
        if embedding_optimizer is None:
            if deferred_rows is not None or packed_rows is not None:
                raise ValueError(
                    "deferred_rows/packed_rows need embedding_optimizer")
            fluid.optimizer.Adam(lr).minimize(loss)
        else:
            adam = fluid.optimizer.Adam(lr)
            table_opt = _table_optimizer(embedding_optimizer, lr,
                                         deferred_rows, packed_rows)
            # ONE backward pass, gradients split across the two rules
            params_grads = adam.backward(loss)
            table_pg = [pg for pg in params_grads
                        if pg[0].name in _TABLE_NAMES]
            dense_pg = [pg for pg in params_grads
                        if pg[0].name not in _TABLE_NAMES]
            adam.apply_gradients(dense_pg)
            table_opt.apply_gradients(table_pg)
            main._deferred_table_optimizer = table_opt
    return main, startup, ["sparse_ids", "dense", "label"], loss, prob
