"""LeNet / MNIST (BASELINE config 1; reference book/test_recognize_digits.py)."""
from __future__ import annotations

import paddle_tpu as fluid
from paddle_tpu import layers


def lenet5(img, num_classes: int = 10):
    conv1 = layers.conv2d(img, num_filters=6, filter_size=5, padding=2, act="relu")
    pool1 = layers.pool2d(conv1, pool_size=2, pool_stride=2)
    conv2 = layers.conv2d(pool1, num_filters=16, filter_size=5, act="relu")
    pool2 = layers.pool2d(conv2, pool_size=2, pool_stride=2)
    fc1 = layers.fc(pool2, size=120, act="relu")
    fc2 = layers.fc(fc1, size=84, act="relu")
    return layers.fc(fc2, size=num_classes)


def mlp(img, num_classes: int = 10):
    h = layers.fc(img, 200, act="relu")
    h = layers.fc(h, 200, act="relu")
    return layers.fc(h, num_classes)


def build_train_program(lr: float = 1e-3, net=lenet5):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", [1, 28, 28])
        label = layers.data("label", [1], dtype="int64")
        logits = net(img)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        fluid.optimizer.Adam(lr).minimize(loss)
    return main, startup, ["img", "label"], loss, acc
