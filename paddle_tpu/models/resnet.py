"""ResNet-50 (BASELINE config 2; reference image_classification recipe —
conv-heavy MXU workload)."""
from __future__ import annotations

import os

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.param_attr import ParamAttr


def _fusion_on() -> bool:
    """PDTPU_CONV_BN_FUSION routes the bottleneck 1×1 conv+BN(+residual+relu)
    tails through the fused op ("pallas" or "xla" picks its lowering; unset
    keeps the historical unfused graph)."""
    return os.environ.get("PDTPU_CONV_BN_FUSION", "") not in ("", "0", "off")


def conv_bn(x, filters, ksize, stride=1, act=None, name="conv", is_test=False,
            residual=None):
    if ksize == 1 and _fusion_on():
        return layers.fused_conv_bn(
            x, filters, stride=stride, act=act, residual=residual,
            is_test=is_test, param_attr=ParamAttr(name=f"{name}.w"),
            bn_param_attr=ParamAttr(name=f"{name}.bn.scale"),
            bn_bias_attr=ParamAttr(name=f"{name}.bn.bias"),
            moving_mean_name=f"{name}.bn.mean",
            moving_variance_name=f"{name}.bn.var")
    conv = layers.conv2d(x, filters, ksize, stride=stride,
                         padding=(ksize - 1) // 2, bias_attr=False,
                         param_attr=ParamAttr(name=f"{name}.w"))
    bn = layers.batch_norm(conv, act=act if residual is None else None,
                           is_test=is_test,
                           param_attr=ParamAttr(name=f"{name}.bn.scale"),
                           bias_attr=ParamAttr(name=f"{name}.bn.bias"),
                           moving_mean_name=f"{name}.bn.mean",
                           moving_variance_name=f"{name}.bn.var")
    if residual is None:
        return bn
    out = layers.elementwise_add(bn, residual)
    return layers.relu(out) if act == "relu" else out


def bottleneck(x, filters, stride, name, is_test=False):
    shortcut = x
    in_c = x.shape[1]
    out_c = filters * 4
    if _fusion_on():
        # the shortcut is built first so the `.c` fused op can fold the
        # residual add + relu into its epilogue (one HBM pass for the tail)
        if stride != 1 or in_c != out_c:
            shortcut = conv_bn(x, out_c, 1, stride=stride, name=f"{name}.sc",
                               is_test=is_test)
        y = conv_bn(x, filters, 1, act="relu", name=f"{name}.a",
                    is_test=is_test)
        y = conv_bn(y, filters, 3, stride=stride, act="relu",
                    name=f"{name}.b", is_test=is_test)
        return conv_bn(y, out_c, 1, act="relu", name=f"{name}.c",
                       is_test=is_test, residual=shortcut)
    y = conv_bn(x, filters, 1, act="relu", name=f"{name}.a", is_test=is_test)
    y = conv_bn(y, filters, 3, stride=stride, act="relu", name=f"{name}.b", is_test=is_test)
    y = conv_bn(y, out_c, 1, name=f"{name}.c", is_test=is_test)
    if stride != 1 or in_c != out_c:
        shortcut = conv_bn(x, out_c, 1, stride=stride, name=f"{name}.sc", is_test=is_test)
    return layers.relu(layers.elementwise_add(y, shortcut))


_LAYOUT = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}


def _s2d_stem(img, is_test):
    """Space-to-depth stem (the MLPerf TPU ResNet trick): pad 224->230,
    rearrange 2x2 spatial blocks into channels ([B,3,230,230] ->
    [B,12,115,115]) and run a 4x4 stride-1 conv — the exact function
    family of the padded 7x7 stride-2 conv (an 8x8 kernel on 2x2 blocks),
    but with C_in=12 instead of 3, which wastes 4x less of the MXU's
    8-sublane input tiling. Measured on v5e: 1.05 ms vs 1.35 ms fwd+bwd
    for the stem at batch 128."""
    x = layers.pad(img, [0, 0, 0, 0, 3, 3, 3, 3])
    x = layers.space_to_depth(x, 2)
    conv = layers.conv2d(x, 64, 4, stride=1, padding=0, bias_attr=False,
                         param_attr=ParamAttr(name="stem.w"))
    return layers.batch_norm(conv, act="relu", is_test=is_test,
                             param_attr=ParamAttr(name="stem.bn.scale"),
                             bias_attr=ParamAttr(name="stem.bn.bias"),
                             moving_mean_name="stem.bn.mean",
                             moving_variance_name="stem.bn.var")


def resnet(img, depth: int = 50, num_classes: int = 1000, is_test: bool = False,
           stem_s2d: bool = False):
    blocks = _LAYOUT[depth]
    if stem_s2d:
        x = _s2d_stem(img, is_test)
    else:
        x = conv_bn(img, 64, 7, stride=2, act="relu", name="stem",
                    is_test=is_test)
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1)
    filters = [64, 128, 256, 512]
    from ..core.program import remat_unit
    for stage, (n, f) in enumerate(zip(blocks, filters)):
        for i in range(n):
            stride = 2 if i == 0 and stage > 0 else 1
            # one remat unit per bottleneck (remat_policy "minimal"/"full")
            with remat_unit(f"res{stage}.{i}"):
                x = bottleneck(x, f, stride, name=f"res{stage}.{i}",
                               is_test=is_test)
    x = layers.pool2d(x, global_pooling=True, pool_type="avg")
    return layers.fc(x, num_classes, param_attr=ParamAttr(name="fc.w"),
                     bias_attr=ParamAttr(name="fc.b"))


def build_train_program(depth=50, num_classes=1000, lr=0.1, momentum=0.9,
                        img_shape=(3, 224, 224)):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", list(img_shape))
        label = layers.data("label", [1], dtype="int64")
        logits = resnet(img, depth, num_classes)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        fluid.optimizer.Momentum(lr, momentum).minimize(loss)
    return main, startup, ["img", "label"], loss, acc
