"""Transformer-big NMT (BASELINE config 4; WMT14 en-de).

The reference handles variable-length batches with LoDTensors; the TPU-native
representation is length-bucketed padded batches + masks (SURVEY §7 hard part
1) — each bucket compiles once, preserving the padding-free efficiency claim.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.initializer import NormalInitializer, ConstantInitializer
from paddle_tpu.param_attr import ParamAttr


@dataclass
class TransformerConfig:
    src_vocab: int = 32000
    tgt_vocab: int = 32000
    d_model: int = 1024       # transformer-big
    n_heads: int = 16
    d_ff: int = 4096
    n_enc: int = 6
    n_dec: int = 6
    dropout: float = 0.1
    max_len: int = 256

    @property
    def head_dim(self):
        return self.d_model // self.n_heads


def _attr(name):
    return ParamAttr(name=name, initializer=NormalInitializer(0.0, 0.02))


def _mha(cfg, q_in, kv_in, mask, name, is_test=False, cache=None, seg=None,
         causal=False):
    d, nh, hd = cfg.d_model, cfg.n_heads, cfg.head_dim
    q = layers.fc(q_in, d, num_flatten_dims=2, param_attr=_attr(f"{name}.q.w"),
                  bias_attr=False)
    k = layers.fc(kv_in, d, num_flatten_dims=2, param_attr=_attr(f"{name}.k.w"),
                  bias_attr=False)
    v = layers.fc(kv_in, d, num_flatten_dims=2, param_attr=_attr(f"{name}.v.w"),
                  bias_attr=False)

    if seg is not None:
        # block-sparse packed-segment path: visibility comes from the
        # segment-id rows themselves (seg = (q_seg, k_seg)) instead of the
        # dense additive [B,1,Tq,Tk] mask — fully-padded key blocks are
        # skipped in the kernel grids (ops/pallas_kernels/flash_attention.py)
        q_seg, k_seg = seg
        out = layers.flash_attention_sparse(
            q, k, v, nh, q_seg, k_seg, causal=causal,
            dropout_prob=cfg.dropout, is_test=is_test)
        return layers.fc(out, d, num_flatten_dims=2,
                         param_attr=_attr(f"{name}.o.w"), bias_attr=False)

    def heads(t):
        return layers.transpose(layers.reshape(t, [0, -1, nh, hd]), [0, 2, 1, 3])

    qh, kh, vh = heads(q), heads(k), heads(v)
    scores = layers.matmul(qh, kh, transpose_y=True, alpha=1.0 / math.sqrt(hd))
    if mask is not None:
        scores = layers.elementwise_add(scores, mask)
    probs = layers.softmax(scores)
    if cfg.dropout > 0:
        probs = layers.dropout(probs, cfg.dropout, is_test=is_test,
                               dropout_implementation="upscale_in_train")
    out = layers.matmul(probs, vh)
    out = layers.reshape(layers.transpose(out, [0, 2, 1, 3]), [0, -1, d])
    return layers.fc(out, d, num_flatten_dims=2, param_attr=_attr(f"{name}.o.w"),
                     bias_attr=False)


def _ffn(cfg, x, name, is_test=False):
    h = layers.fc(x, cfg.d_ff, num_flatten_dims=2, act="relu",
                  param_attr=_attr(f"{name}.ffn1.w"),
                  bias_attr=ParamAttr(name=f"{name}.ffn1.b",
                                      initializer=ConstantInitializer(0.0)))
    if cfg.dropout > 0:
        h = layers.dropout(h, cfg.dropout, is_test=is_test,
                           dropout_implementation="upscale_in_train")
    return layers.fc(h, cfg.d_model, num_flatten_dims=2,
                     param_attr=_attr(f"{name}.ffn2.w"),
                     bias_attr=ParamAttr(name=f"{name}.ffn2.b",
                                         initializer=ConstantInitializer(0.0)))


def _ln(x, name):
    return layers.layer_norm(x, begin_norm_axis=2,
                             param_attr=ParamAttr(name=f"{name}.scale",
                                                  initializer=ConstantInitializer(1.0)),
                             bias_attr=ParamAttr(name=f"{name}.bias",
                                                 initializer=ConstantInitializer(0.0)))


def _residual(cfg, x, sub, is_test=False):
    if cfg.dropout > 0:
        sub = layers.dropout(sub, cfg.dropout, is_test=is_test,
                             dropout_implementation="upscale_in_train")
    return layers.elementwise_add(x, sub)


def _pos_encoding_np(max_len, d_model):
    pos = np.arange(max_len)[:, None]
    i = np.arange(d_model)[None, :]
    angle = pos / np.power(10000.0, (2 * (i // 2)) / d_model)
    enc = np.zeros((max_len, d_model), dtype="float32")
    enc[:, 0::2] = np.sin(angle[:, 0::2])
    enc[:, 1::2] = np.cos(angle[:, 1::2])
    return enc


def _embed(cfg, ids, vocab, name, is_test=False, pos=None):
    emb = layers.embedding(ids, [vocab, cfg.d_model], param_attr=_attr(name))
    emb = layers.scale(emb, scale=math.sqrt(cfg.d_model))
    seq_len = ids.shape[1] if ids.shape and len(ids.shape) > 1 and ids.shape[1] > 0 else cfg.max_len
    if pos is not None:
        # packed rows: positions restart per segment, so gather the
        # sinusoid table by explicit per-token position ids. Size the
        # table to cover the row length too: XLA gather CLAMPS
        # out-of-range indices silently, so a table shorter than the
        # longest packed sentence would give its tail tokens the same
        # (last-row) encoding with no error.
        table = layers.assign(
            _pos_encoding_np(max(cfg.max_len, seq_len), cfg.d_model))
        pe = layers.gather(table, layers.reshape(pos, [-1]))
        pe = layers.reshape(pe, [-1, seq_len, cfg.d_model])
    else:
        pe = layers.assign(_pos_encoding_np(seq_len, cfg.d_model))
    emb = layers.elementwise_add(emb, pe)  # broadcast [T,D] over batch
    if cfg.dropout > 0:
        emb = layers.dropout(emb, cfg.dropout, is_test=is_test,
                             dropout_implementation="upscale_in_train")
    return emb


def encoder(cfg, src_ids, src_mask, is_test=False, pos=None, seg=None):
    from ..core.program import remat_unit
    x = _embed(cfg, src_ids, cfg.src_vocab, "src_embedding", is_test, pos=pos)
    self_seg = (seg, seg) if seg is not None else None
    for i in range(cfg.n_enc):
        name = f"enc_{i}"
        # one remat unit per encoder layer (remat_policy "minimal"/"full")
        with remat_unit(name):
            x = _ln(_residual(cfg, x, _mha(cfg, x, x, src_mask, f"{name}.self", is_test,
                                           seg=self_seg),
                              is_test), f"{name}.ln1")
            x = _ln(_residual(cfg, x, _ffn(cfg, x, name, is_test), is_test), f"{name}.ln2")
    return x


def decoder(cfg, tgt_ids, enc_out, self_mask, cross_mask, is_test=False,
            pos=None, tgt_seg=None, src_seg=None):
    from ..core.program import remat_unit
    x = _embed(cfg, tgt_ids, cfg.tgt_vocab, "tgt_embedding", is_test, pos=pos)
    sparse = tgt_seg is not None
    self_seg = (tgt_seg, tgt_seg) if sparse else None
    cross_seg = (tgt_seg, src_seg) if sparse else None
    for i in range(cfg.n_dec):
        name = f"dec_{i}"
        with remat_unit(name):
            x = _ln(_residual(cfg, x, _mha(cfg, x, x, self_mask, f"{name}.self", is_test,
                                           seg=self_seg, causal=sparse),
                              is_test), f"{name}.ln1")
            x = _ln(_residual(cfg, x, _mha(cfg, x, enc_out, cross_mask, f"{name}.cross", is_test,
                                           seg=cross_seg),
                              is_test), f"{name}.ln2")
            x = _ln(_residual(cfg, x, _ffn(cfg, x, name, is_test), is_test), f"{name}.ln3")
    return layers.fc(x, cfg.tgt_vocab, num_flatten_dims=2,
                     param_attr=_attr("out_proj.w"), bias_attr=False)


def build_train_program(cfg: TransformerConfig, src_len: int, tgt_len: int,
                        lr=1e-3, is_test=False, optimizer_factory=None,
                        packed=False, attn="dense"):
    """Masks are fed as additive float tensors (0 keep / -1e4 drop).

    Bucketed (default): src_mask [B,1,1,Ts] (pad); tgt self-mask
    [B,1,Tt,Tt] (causal+pad); cross attention reuses src_mask.

    ``packed=True`` (reader.pack_by_tokens rows — VERDICT r3 #2): several
    sentences share a row, so every mask is segment-block-diagonal and
    FULL rank: src_mask [B,1,Ts,Ts], tgt_mask [B,1,Tt,Tt], a separate
    cross_mask [B,1,Tt,Ts], plus per-token position ids (positions
    restart at each packed sentence) fed as src_pos/tgt_pos.

    ``attn="sparse"`` (packed only): the dense masks never exist — the
    segment-id rows themselves are fed (src_seg/tgt_seg [B,T] int32) and
    attention runs through the block-sparse flash kernels, which skip
    fully-padded key blocks in the fwd and bwd grids. Hard segment masking
    (exact zeros) instead of additive -1e4."""
    if attn not in ("dense", "sparse"):
        raise ValueError(f"attn must be 'dense' or 'sparse', got {attn!r}")
    if attn == "sparse" and not packed:
        raise ValueError("attn='sparse' requires packed=True (the segment "
                         "descriptor comes from pack_by_tokens rows)")
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = layers.data("src_ids", [src_len], dtype="int64")
        tgt = layers.data("tgt_ids", [tgt_len], dtype="int64")
        lbl = layers.data("lbl_ids", [tgt_len, 1], dtype="int64")
        src_seg = tgt_seg = None
        if packed and attn == "sparse":
            src_seg = layers.data("src_seg", [src_len], dtype="int32")
            tgt_seg = layers.data("tgt_seg", [tgt_len], dtype="int32")
            src_mask = tgt_mask = cross_mask = None
            src_pos = layers.data("src_pos", [src_len], dtype="int64")
            tgt_pos = layers.data("tgt_pos", [tgt_len], dtype="int64")
        elif packed:
            src_mask = layers.data("src_mask", [1, src_len, src_len])
            tgt_mask = layers.data("tgt_mask", [1, tgt_len, tgt_len])
            cross_mask = layers.data("cross_mask", [1, tgt_len, src_len])
            src_pos = layers.data("src_pos", [src_len], dtype="int64")
            tgt_pos = layers.data("tgt_pos", [tgt_len], dtype="int64")
        else:
            src_mask = layers.data("src_mask", [1, 1, src_len])
            tgt_mask = layers.data("tgt_mask", [1, tgt_len, tgt_len])
            cross_mask, src_pos, tgt_pos = src_mask, None, None
        enc_out = encoder(cfg, src, src_mask, is_test, pos=src_pos,
                          seg=src_seg)
        logits = decoder(cfg, tgt, enc_out, tgt_mask, cross_mask, is_test,
                         pos=tgt_pos, tgt_seg=tgt_seg, src_seg=src_seg)
        loss_tok = layers.softmax_with_cross_entropy(logits, lbl, ignore_index=0)
        valid = layers.cast(layers.not_equal(
            lbl, layers.fill_constant([1], "int64", 0)), "float32")
        loss = layers.elementwise_div(
            layers.reduce_sum(layers.elementwise_mul(loss_tok, valid)),
            layers.reduce_sum(valid))
        opt = (optimizer_factory() if optimizer_factory
               else fluid.optimizer.Adam(lr))
        opt.minimize(loss)
    feeds = ["src_ids", "tgt_ids", "lbl_ids"]
    if packed and attn == "sparse":
        feeds += ["src_seg", "tgt_seg", "src_pos", "tgt_pos"]
    elif packed:
        feeds += ["src_mask", "tgt_mask", "cross_mask", "src_pos", "tgt_pos"]
    else:
        feeds += ["src_mask", "tgt_mask"]
    return main, startup, feeds, loss


def length_buckets(lengths, buckets=(32, 64, 128, 256)):
    """Bucketing helper replacing LoD batching: map raw lengths to the
    smallest bucket ≥ len (one XLA compilation per bucket)."""
    out = []
    for L in lengths:
        for b in buckets:
            if L <= b:
                out.append(b)
                break
        else:
            out.append(buckets[-1])
    return out
