"""ctypes bindings for the native runtime (lazy-built via make).

Reference analog: the pybind `core` module surface for DataFeed/
LoDTensorBlockingQueue (pybind/reader_py.cc, data_set_py.cc). Falls back to
pure-Python implementations when no C++ toolchain is available.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libpaddle_tpu_native.so")
_SRC = os.path.join(_DIR, "src", "dataloader.cc")
_SRC2 = os.path.join(_DIR, "src", "ckptio.cc")

_lib = None
_build_error: Optional[str] = None


def _ensure_built():
    global _lib, _build_error
    if _lib is not None or _build_error is not None:
        return _lib
    try:
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC2)):
            subprocess.run(["make", "-C", _DIR], check=True,
                           capture_output=True, text=True)
        lib = ctypes.CDLL(_SO)
        lib.ptdl_create.restype = ctypes.c_void_p
        lib.ptdl_create.argtypes = [ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
                                    ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
        lib.ptdl_next.restype = ctypes.c_longlong
        lib.ptdl_next.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_uint8), ctypes.c_longlong]
        lib.ptdl_queue_size.restype = ctypes.c_longlong
        lib.ptdl_queue_size.argtypes = [ctypes.c_void_p]
        lib.ptdl_destroy.argtypes = [ctypes.c_void_p]
        lib.ptq_create.restype = ctypes.c_void_p
        lib.ptq_create.argtypes = [ctypes.c_int]
        lib.ptq_push.restype = ctypes.c_int
        lib.ptq_push.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
                                 ctypes.c_longlong]
        lib.ptq_pop.restype = ctypes.c_longlong
        lib.ptq_pop.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
                                ctypes.c_longlong]
        lib.ptq_close.argtypes = [ctypes.c_void_p]
        lib.ptq_destroy.argtypes = [ctypes.c_void_p]
        lib.ptck_open.restype = ctypes.c_void_p
        lib.ptck_open.argtypes = [ctypes.c_char_p]
        lib.ptck_write_tensor.restype = ctypes.c_int
        lib.ptck_write_tensor.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_void_p, ctypes.c_uint64]
        lib.ptck_close.restype = ctypes.c_int
        lib.ptck_close.argtypes = [ctypes.c_void_p]
        lib.ptck_read_open.restype = ctypes.c_void_p
        lib.ptck_read_open.argtypes = [ctypes.c_char_p]
        lib.ptck_count.restype = ctypes.c_int64
        lib.ptck_count.argtypes = [ctypes.c_void_p]
        lib.ptck_entry_meta.restype = ctypes.c_int64
        lib.ptck_entry_meta.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
        lib.ptck_entry_data.restype = ctypes.c_int
        lib.ptck_entry_data.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                        ctypes.c_void_p, ctypes.c_uint64]
        lib.ptck_read_close.argtypes = [ctypes.c_void_p]
        _lib = lib
    except Exception as e:  # no toolchain / build failure → python fallback
        _build_error = str(e)
        _lib = None
    return _lib


def available() -> bool:
    return _ensure_built() is not None


def build_error() -> Optional[str]:
    _ensure_built()
    return _build_error


def _decode_sample(buf: np.ndarray) -> List[np.ndarray]:
    """Decode the wire format (see dataloader.cc) into per-slot arrays."""
    out = []
    mv = memoryview(buf)
    num_slots = int(np.frombuffer(mv[:4], dtype="<u4")[0])
    off = 4
    for _ in range(num_slots):
        dtype = mv[off]
        off += 1
        n = int(np.frombuffer(mv[off:off + 4], dtype="<u4")[0])
        off += 4
        if dtype == 0:
            arr = np.frombuffer(mv[off:off + 4 * n], dtype="<f4").copy()
            off += 4 * n
        else:
            arr = np.frombuffer(mv[off:off + 8 * n], dtype="<i8").copy()
            off += 8 * n
        out.append(arr)
    return out


class NativeDataLoader:
    """Multi-threaded MultiSlot file loader (data_feed.cc analog)."""

    MAX_SAMPLE = 1 << 22  # 4 MiB per sample

    def __init__(self, files: Sequence[str], slot_types: str,
                 num_threads: int = 4, capacity: int = 1024):
        lib = _ensure_built()
        self._lib = lib
        self._files = list(files)
        self._slot_types = slot_types
        self._handle = None
        if lib is not None:
            arr = (ctypes.c_char_p * len(self._files))(
                *[f.encode() for f in self._files])
            self._handle = lib.ptdl_create(arr, len(self._files),
                                           slot_types.encode(), num_threads,
                                           capacity)
            self._buf = np.empty(self.MAX_SAMPLE, dtype=np.uint8)
        else:
            self._py_iter = self._python_reader()

    def _python_reader(self):
        for path in self._files:
            with open(path) as f:
                for line in f:
                    parts = line.split()
                    if not parts:
                        continue
                    out, i, ok = [], 0, True
                    for t in self._slot_types:
                        if i >= len(parts):
                            ok = False
                            break
                        n = int(parts[i])
                        i += 1
                        vals = parts[i:i + n]
                        i += n
                        if len(vals) != n:
                            ok = False
                            break
                        out.append(np.asarray(vals, dtype="float32" if t == "f" else "int64"))
                    if ok:
                        yield out

    def __iter__(self):
        if self._handle is None:
            yield from self._py_iter
            return
        lib = self._lib
        buf_ptr = self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        while True:
            n = lib.ptdl_next(self._handle, buf_ptr, self.MAX_SAMPLE)
            if n == 0:
                break
            if n < 0:
                continue  # oversized sample dropped
            yield _decode_sample(self._buf[:n])

    def close(self):
        if self._handle is not None:
            self._lib.ptdl_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Native checkpoint bundle IO (src/ckptio.cc — save_combine_op.cc analog)
# ---------------------------------------------------------------------------

def write_bundle(path: str, arrays) -> bool:
    """Write {name: np.ndarray} as one framed binary bundle via the C++
    writer (buffered stdio + fsync). Returns False when the native lib is
    unavailable or any write fails (caller falls back to pickle)."""
    lib = _ensure_built()
    if lib is None:
        return False
    h = lib.ptck_open(path.encode())
    if not h:
        return False
    ok = True
    for name, arr in arrays.items():
        a = np.ascontiguousarray(arr)
        dims = (ctypes.c_int64 * max(a.ndim, 1))(*(a.shape or (0,)))
        rc = lib.ptck_write_tensor(
            h, str(name).encode(), str(a.dtype).encode(), a.ndim, dims,
            a.ctypes.data_as(ctypes.c_void_p), a.nbytes)
        if rc != 0:
            ok = False
            break
    if lib.ptck_close(h) != 0:
        ok = False
    return ok


def read_bundle(path: str):
    """Read a bundle back as {name: np.ndarray}; None if the native lib is
    unavailable or the file isn't a PTCK bundle."""
    lib = _ensure_built()
    if lib is None:
        return None
    h = lib.ptck_read_open(path.encode())
    if not h:
        return None
    try:
        out = {}
        n = lib.ptck_count(h)
        name_buf = ctypes.create_string_buffer(4096)
        dtype_buf = ctypes.create_string_buffer(64)
        dims_buf = (ctypes.c_int64 * 16)()
        ndim = ctypes.c_int()
        for i in range(n):
            nbytes = lib.ptck_entry_meta(h, i, name_buf, 4096, dtype_buf, 64,
                                         dims_buf, 16, ctypes.byref(ndim))
            if nbytes < 0:
                return None
            shape = tuple(dims_buf[d] for d in range(ndim.value))
            arr = np.empty(shape, dtype=np.dtype(dtype_buf.value.decode()))
            if nbytes != arr.nbytes:
                # truncated/corrupt entry: the C side only rejects
                # nbytes > capacity, so a SHORT payload would otherwise
                # fill part of np.empty and return uninitialized tail bytes
                return None
            buf = arr if arr.nbytes else np.empty(1, np.uint8)
            if lib.ptck_entry_data(
                    h, i, buf.ctypes.data_as(ctypes.c_void_p),
                    max(arr.nbytes, 1)) != 0:
                return None
            out[name_buf.value.decode()] = arr
        return out
    finally:
        lib.ptck_read_close(h)
