// Native checkpoint bundle IO.
//
// Reference analog: save_op.cc / save_combine_op.cc — the C++ runtime
// streams each persistable tensor to disk in a framed binary format
// (SerializeToStream, framework/lod_tensor.cc). This is the TPU build's
// equivalent: a single-file bundle of named raw tensors written with
// buffered stdio off the Python thread, committed durably
// (fflush+fsync+rename happens on the caller's temp→final path protocol).
//
// Format (little-endian):
//   magic  "PTCK1\n"
//   repeat per tensor:
//     u32 name_len, bytes name
//     u32 dtype_len, bytes dtype (numpy dtype str, e.g. "float32")
//     u32 ndim, i64 dims[ndim]
//     u64 nbytes, raw data
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <unistd.h>

namespace {

constexpr char kMagic[] = "PTCK1\n";

struct Writer {
  FILE* f = nullptr;
};

struct Entry {
  std::string name;
  std::string dtype;
  std::vector<int64_t> dims;
  uint64_t nbytes = 0;
  long offset = 0;  // file offset of the raw data
};

struct Reader {
  FILE* f = nullptr;
  std::vector<Entry> entries;
};

bool write_all(FILE* f, const void* p, size_t n) {
  return fwrite(p, 1, n, f) == n;
}

bool read_all(FILE* f, void* p, size_t n) {
  return fread(p, 1, n, f) == n;
}

}  // namespace

extern "C" {

void* ptck_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  if (!write_all(f, kMagic, sizeof(kMagic) - 1)) {
    fclose(f);
    return nullptr;
  }
  auto* w = new Writer();
  w->f = f;
  return w;
}

int ptck_write_tensor(void* handle, const char* name, const char* dtype,
                      int ndim, const int64_t* dims, const void* data,
                      uint64_t nbytes) {
  auto* w = static_cast<Writer*>(handle);
  if (!w || !w->f) return -1;
  uint32_t name_len = static_cast<uint32_t>(strlen(name));
  uint32_t dtype_len = static_cast<uint32_t>(strlen(dtype));
  uint32_t nd = static_cast<uint32_t>(ndim);
  if (!write_all(w->f, &name_len, 4) || !write_all(w->f, name, name_len) ||
      !write_all(w->f, &dtype_len, 4) || !write_all(w->f, dtype, dtype_len) ||
      !write_all(w->f, &nd, 4) ||
      (ndim > 0 && !write_all(w->f, dims, sizeof(int64_t) * ndim)) ||
      !write_all(w->f, &nbytes, 8) ||
      (nbytes > 0 && !write_all(w->f, data, nbytes))) {
    return -1;
  }
  return 0;
}

// flush + fsync; rename-to-final stays with the Python caller so the
// temp→durable protocol is shared with the pickle fallback
int ptck_close(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  if (!w) return -1;
  int rc = 0;
  if (w->f) {
    if (fflush(w->f) != 0) rc = -1;
    if (fsync(fileno(w->f)) != 0) rc = -1;
    if (fclose(w->f) != 0) rc = -1;
  }
  delete w;
  return rc;
}

void* ptck_read_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  char magic[sizeof(kMagic)] = {0};
  if (!read_all(f, magic, sizeof(kMagic) - 1) ||
      memcmp(magic, kMagic, sizeof(kMagic) - 1) != 0) {
    fclose(f);
    return nullptr;
  }
  auto* r = new Reader();
  r->f = f;
  while (true) {
    uint32_t name_len = 0;
    if (fread(&name_len, 1, 4, f) != 4) break;  // clean EOF
    Entry e;
    e.name.resize(name_len);
    uint32_t dtype_len = 0, nd = 0;
    if (!read_all(f, e.name.data(), name_len) ||
        !read_all(f, &dtype_len, 4)) {
      goto corrupt;
    }
    e.dtype.resize(dtype_len);
    if (!read_all(f, e.dtype.data(), dtype_len) || !read_all(f, &nd, 4)) {
      goto corrupt;
    }
    e.dims.resize(nd);
    if (nd > 0 && !read_all(f, e.dims.data(), sizeof(int64_t) * nd)) {
      goto corrupt;
    }
    if (!read_all(f, &e.nbytes, 8)) goto corrupt;
    e.offset = ftell(f);
    if (fseek(f, static_cast<long>(e.nbytes), SEEK_CUR) != 0) goto corrupt;
    r->entries.push_back(std::move(e));
  }
  return r;
corrupt:
  fclose(f);
  delete r;
  return nullptr;
}

int64_t ptck_count(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  return r ? static_cast<int64_t>(r->entries.size()) : -1;
}

// meta query: copies name/dtype into caller buffers, returns nbytes
int64_t ptck_entry_meta(void* handle, int64_t i, char* name_buf,
                        int name_cap, char* dtype_buf, int dtype_cap,
                        int64_t* dims_buf, int dims_cap, int* ndim_out) {
  auto* r = static_cast<Reader*>(handle);
  if (!r || i < 0 || i >= static_cast<int64_t>(r->entries.size())) return -1;
  const Entry& e = r->entries[i];
  if (static_cast<int>(e.name.size()) + 1 > name_cap ||
      static_cast<int>(e.dtype.size()) + 1 > dtype_cap ||
      static_cast<int>(e.dims.size()) > dims_cap) {
    return -1;
  }
  snprintf(name_buf, name_cap, "%s", e.name.c_str());
  snprintf(dtype_buf, dtype_cap, "%s", e.dtype.c_str());
  for (size_t d = 0; d < e.dims.size(); ++d) dims_buf[d] = e.dims[d];
  *ndim_out = static_cast<int>(e.dims.size());
  return static_cast<int64_t>(e.nbytes);
}

int ptck_entry_data(void* handle, int64_t i, void* out, uint64_t cap) {
  auto* r = static_cast<Reader*>(handle);
  if (!r || i < 0 || i >= static_cast<int64_t>(r->entries.size())) return -1;
  const Entry& e = r->entries[i];
  if (cap < e.nbytes) return -1;
  if (fseek(r->f, e.offset, SEEK_SET) != 0) return -1;
  if (e.nbytes > 0 && !read_all(r->f, out, e.nbytes)) return -1;
  return 0;
}

void ptck_read_close(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  if (r) {
    if (r->f) fclose(r->f);
    delete r;
  }
}

}  // extern "C"
