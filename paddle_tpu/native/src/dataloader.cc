// Native data-loading runtime for paddle_tpu.
//
// Reference analog: paddle/fluid/framework/data_feed.cc (MultiSlotDataFeed
// pipe-command text parsing), framework/blocking_queue.h, and
// operators/reader/buffered_reader.cc (background prefetch threads).
//
// Exposes a C API consumed from Python via ctypes: a bounded MPMC blocking
// queue of serialized samples + a multi-threaded file reader/parser for the
// MultiSlot text format ("<len> v1 v2 ... per slot, space separated").
//
// Sample wire format pushed to the queue (little endian):
//   uint32 num_slots
//   per slot: uint8 dtype (0=f32, 1=i64), uint32 len, payload bytes
//
// Build: make -C paddle_tpu/native  (g++ -O2 -fPIC -shared -pthread)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fstream>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Buffer {
  std::vector<uint8_t> data;
};

class BlockingQueue {
 public:
  explicit BlockingQueue(size_t capacity) : capacity_(capacity) {}

  bool Push(Buffer&& item) {
    std::unique_lock<std::mutex> lk(mu_);
    not_full_.wait(lk, [&] { return q_.size() < capacity_ || closed_; });
    if (closed_) return false;
    q_.push_back(std::move(item));
    not_empty_.notify_one();
    return true;
  }

  // Returns false when queue is closed AND drained.
  bool Pop(Buffer* out) {
    std::unique_lock<std::mutex> lk(mu_);
    not_empty_.wait(lk, [&] { return !q_.empty() || closed_; });
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    not_full_.notify_one();
    return true;
  }

  void Close() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    not_empty_.notify_all();
    not_full_.notify_all();
  }

  size_t Size() {
    std::lock_guard<std::mutex> lk(mu_);
    return q_.size();
  }

 private:
  size_t capacity_;
  bool closed_ = false;
  std::deque<Buffer> q_;
  std::mutex mu_;
  std::condition_variable not_full_, not_empty_;
};

struct Loader {
  BlockingQueue queue;
  std::vector<std::string> files;
  std::string slot_types;  // per-slot: 'f' float32 | 'i' int64
  std::atomic<size_t> next_file{0};
  std::atomic<int> live_workers{0};
  std::vector<std::thread> workers;

  Loader(size_t cap) : queue(cap) {}
};

void AppendU32(std::vector<uint8_t>* v, uint32_t x) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(&x);
  v->insert(v->end(), p, p + 4);
}

// Parse one MultiSlot-format line into the wire format. Returns false on
// malformed input (silently skipped, matching the reference's tolerant
// parser).
bool ParseLine(const std::string& line, const std::string& slot_types,
               std::vector<uint8_t>* out) {
  std::istringstream is(line);
  out->clear();
  AppendU32(out, static_cast<uint32_t>(slot_types.size()));
  for (char t : slot_types) {
    long long len;
    if (!(is >> len) || len < 0) return false;
    out->push_back(t == 'f' ? 0 : 1);
    AppendU32(out, static_cast<uint32_t>(len));
    if (t == 'f') {
      for (long long i = 0; i < len; ++i) {
        float v;
        if (!(is >> v)) return false;
        const uint8_t* p = reinterpret_cast<const uint8_t*>(&v);
        out->insert(out->end(), p, p + 4);
      }
    } else {
      for (long long i = 0; i < len; ++i) {
        int64_t v;
        if (!(is >> v)) return false;
        const uint8_t* p = reinterpret_cast<const uint8_t*>(&v);
        out->insert(out->end(), p, p + 8);
      }
    }
  }
  return true;
}

void WorkerLoop(Loader* ld) {
  while (true) {
    size_t idx = ld->next_file.fetch_add(1);
    if (idx >= ld->files.size()) break;
    std::ifstream f(ld->files[idx]);
    if (!f.is_open()) continue;
    std::string line;
    std::vector<uint8_t> wire;
    while (std::getline(f, line)) {
      if (line.empty()) continue;
      if (!ParseLine(line, ld->slot_types, &wire)) continue;
      Buffer b;
      b.data = wire;
      if (!ld->queue.Push(std::move(b))) return;  // closed
    }
  }
  if (ld->live_workers.fetch_sub(1) == 1) {
    ld->queue.Close();  // last worker out: signal end of data
  }
}

}  // namespace

extern "C" {

void* ptdl_create(const char** files, int nfiles, const char* slot_types,
                  int num_threads, int capacity) {
  Loader* ld = new Loader(static_cast<size_t>(capacity));
  for (int i = 0; i < nfiles; ++i) ld->files.emplace_back(files[i]);
  ld->slot_types = slot_types;
  int n = num_threads > 0 ? num_threads : 1;
  ld->live_workers = n;
  for (int i = 0; i < n; ++i) ld->workers.emplace_back(WorkerLoop, ld);
  return ld;
}

// Pops one sample; copies up to buf_cap bytes into buf. Returns the sample
// size in bytes, 0 on end-of-data, -1 if buf too small (sample is dropped).
long long ptdl_next(void* handle, uint8_t* buf, long long buf_cap) {
  Loader* ld = static_cast<Loader*>(handle);
  Buffer b;
  if (!ld->queue.Pop(&b)) return 0;
  long long n = static_cast<long long>(b.data.size());
  if (n > buf_cap) return -1;
  std::memcpy(buf, b.data.data(), b.data.size());
  return n;
}

long long ptdl_queue_size(void* handle) {
  return static_cast<long long>(static_cast<Loader*>(handle)->queue.Size());
}

void ptdl_destroy(void* handle) {
  Loader* ld = static_cast<Loader*>(handle);
  ld->queue.Close();
  for (auto& t : ld->workers) {
    if (t.joinable()) t.join();
  }
  delete ld;
}

// -- standalone blocking queue (LoDTensorBlockingQueue analog) --------------

void* ptq_create(int capacity) { return new BlockingQueue(capacity); }

int ptq_push(void* h, const uint8_t* data, long long len) {
  Buffer b;
  b.data.assign(data, data + len);
  return static_cast<BlockingQueue*>(h)->Push(std::move(b)) ? 1 : 0;
}

long long ptq_pop(void* h, uint8_t* buf, long long buf_cap) {
  Buffer b;
  if (!static_cast<BlockingQueue*>(h)->Pop(&b)) return 0;
  long long n = static_cast<long long>(b.data.size());
  if (n > buf_cap) return -1;
  std::memcpy(buf, b.data.data(), b.data.size());
  return n;
}

void ptq_close(void* h) { static_cast<BlockingQueue*>(h)->Close(); }

void ptq_destroy(void* h) { delete static_cast<BlockingQueue*>(h); }

}  // extern "C"
