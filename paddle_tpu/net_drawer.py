"""fluid.net_drawer (reference net_drawer.py draw_graph) over the
debugger's graphviz emitters."""
from __future__ import annotations

from . import debugger as _debugger

__all__ = ["draw_graph"]


def draw_graph(startup_program, main_program, **kwargs):
    """net_drawer.py draw_graph: emit graphviz dot for the main program
    (startup accepted for API parity; its init ops aren't drawn)."""
    path = kwargs.get("graph_path") or kwargs.get("path")
    dot = _debugger.program_to_dot(main_program)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot
