"""Composite network helpers (reference python/paddle/fluid/nets.py).

Same five public helpers as the reference — simple_img_conv_pool (:28),
img_conv_group (:136), sequence_conv_pool (:249), glu (:307),
scaled_dot_product_attention (:345) — composed from this framework's layers.
Differences from the reference are TPU-design consequences:
- sequence helpers take an explicit `length` Variable (LoD metadata rides a
  dense tensor here, see layers/sequence.py);
- scaled_dot_product_attention keeps the reference's shape contract but the
  computation lowers to one fused XLA attention (and can be swapped for the
  Pallas flash kernel via layers.flash_attention by callers that need it).
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

from . import layers


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    """conv2d → pool2d (reference nets.py:28)."""
    conv_out = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=conv_stride, padding=conv_padding, dilation=conv_dilation,
        groups=conv_groups, param_attr=param_attr, bias_attr=bias_attr,
        act=act)
    return layers.pool2d(
        input=conv_out, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, pool_padding=pool_padding,
        global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """The VGG block: N×(conv[+bn][+dropout]) → pool (reference
    nets.py:136)."""
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def _extend(obj):
        if not hasattr(obj, "__len__"):
            return [obj] * len(conv_num_filter)
        assert len(obj) == len(conv_num_filter)
        return obj

    conv_padding = _extend(conv_padding)
    conv_filter_size = _extend(conv_filter_size)
    param_attr = _extend(param_attr)
    conv_with_batchnorm = _extend(conv_with_batchnorm)
    conv_batchnorm_drop_rate = _extend(conv_batchnorm_drop_rate)

    for i in range(len(conv_num_filter)):
        local_conv_act = conv_act
        if conv_with_batchnorm[i]:
            local_conv_act = None
        tmp = layers.conv2d(
            input=tmp, num_filters=conv_num_filter[i],
            filter_size=conv_filter_size[i], padding=conv_padding[i],
            param_attr=param_attr[i], act=local_conv_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            drop_rate = conv_batchnorm_drop_rate[i]
            if abs(drop_rate) > 1e-5:
                tmp = layers.dropout(x=tmp, dropout_prob=drop_rate)

    return layers.pool2d(input=tmp, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, length,
                       param_attr=None, act="sigmoid", pool_type="max",
                       bias_attr=None):
    """sequence_conv → sequence_pool (reference nets.py:249). `length` is
    the per-row valid-length Variable (TPU replacement for LoD)."""
    conv_out = layers.sequence_conv(
        input=input, num_filters=num_filters, filter_size=filter_size,
        length=length, param_attr=param_attr, bias_attr=bias_attr, act=act)
    return layers.sequence_pool(input=conv_out, pool_type=pool_type,
                                length=length)


def glu(input, dim: int = -1):
    """Gated linear unit: split → a ⊙ σ(b) (reference nets.py:307)."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(x=a, y=layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads: int = 1,
                                 dropout_rate: float = 0.0):
    """Multi-head scaled-dot-product attention over [B, T, D] tensors
    (reference nets.py:345). Returns [B, Tq, D_v]."""
    if len(queries.shape) != 3 or len(keys.shape) != 3 or len(values.shape) != 3:
        raise ValueError("inputs must be 3-D [batch, seq, dim]")
    if queries.shape[-1] != keys.shape[-1]:
        raise ValueError("queries and keys must have the same hidden size")
    if keys.shape[1] != values.shape[1]:
        raise ValueError("keys and values must share the sequence length")
    if queries.shape[-1] % num_heads != 0:
        raise ValueError("num_heads must evenly divide the hidden size")

    q, k, v = queries, keys, values
    if num_heads > 1:
        def split_heads(x):
            b, t, dm = x.shape
            x = layers.reshape(x, [b, t, num_heads, dm // num_heads])
            return layers.transpose(x, [0, 2, 1, 3])     # [B, H, T, d]
        q, k, v = split_heads(q), split_heads(k), split_heads(v)

    import math
    scaled_q = layers.scale(q, scale=1.0 / math.sqrt(q.shape[-1]))
    product = layers.matmul(scaled_q, k, transpose_y=True)
    weights = layers.softmax(product)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate)
    ctx = layers.matmul(weights, v)
    if num_heads > 1:
        b, t = queries.shape[0], queries.shape[1]
        dv = values.shape[-1]
        ctx = layers.transpose(ctx, [0, 2, 1, 3])
        ctx = layers.reshape(ctx, [b, t, dv])
    return ctx
