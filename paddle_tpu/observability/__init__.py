"""paddle_tpu.observability — unified runtime telemetry.

Reference analog: the reference framework's observability pipeline was
RecordEvent/DeviceTracer (platform/profiler.h:166) streaming into
profiler.proto, converted to chrome://tracing by ``tools/timeline.py``,
plus the sorted per-op profiler summary. The TPU build splits the same
capability along its natural seam:

- **Registry** (registry.py) — process-wide, thread-safe counters /
  gauges / histograms (labeled, percentile snapshots), with a
  Prometheus-style text exporter, JSON dump, and composition: per-server
  `serving.Metrics` registries attach as children so ONE
  ``get_registry().snapshot()`` shows executor cache hits/misses,
  compile time, and serving latency together.
- **trace_span / Tracer** (tracer.py) — host-side nested wall-clock
  spans per thread, exported as chrome-trace JSON (chrome://tracing /
  Perfetto). Device-side tracing stays with jax.profiler (XPlane);
  ``paddle_tpu.profiler.record_event`` records into BOTH so host spans
  and XPlane annotations line up, and
  ``python -m paddle_tpu.tools.timeline`` merges/summarizes the files.
- **RecompileWatchdog** (watchdog.py) — the executor reports every
  executable-cache miss; past a threshold the watchdog warns once,
  naming exactly which feed's shape/dtype diverged between the cached
  and the new signature (the actionable diagnosis of a recompile storm).

Quick start::

    from paddle_tpu import observability as obs

    with obs.trace_span("train/epoch", epoch=e):
        exe.run(main, feed=..., fetch_list=[loss])

    print(obs.get_registry().report())           # text table
    obs.get_registry().dump_json("metrics.json") # registry export
    obs.get_tracer().export_chrome_trace("host_trace.json")
"""
from .memory import (device_memory_stats,  # noqa: F401
                     per_device_state_bytes, record_state_memory)
from .registry import (Counter, Gauge, Histogram, Registry,  # noqa: F401
                       get_registry)
from .tracer import Tracer, get_tracer, trace_span  # noqa: F401
from .watchdog import (RecompileWarning, RecompileWatchdog,  # noqa: F401
                       diff_signatures, get_watchdog)

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "get_registry",
    "device_memory_stats", "per_device_state_bytes", "record_state_memory",
    "Tracer", "get_tracer", "trace_span",
    "RecompileWarning", "RecompileWatchdog", "diff_signatures",
    "get_watchdog",
]
