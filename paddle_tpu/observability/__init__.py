"""paddle_tpu.observability — unified runtime telemetry.

Reference analog: the reference framework's observability pipeline was
RecordEvent/DeviceTracer (platform/profiler.h:166) streaming into
profiler.proto, converted to chrome://tracing by ``tools/timeline.py``,
plus the sorted per-op profiler summary. The TPU build splits the same
capability along its natural seam:

- **Registry** (registry.py) — process-wide, thread-safe counters /
  gauges / histograms (labeled, percentile snapshots), with a
  Prometheus-style text exporter, JSON dump, and composition: per-server
  `serving.Metrics` registries attach as children so ONE
  ``get_registry().snapshot()`` shows executor cache hits/misses,
  compile time, and serving latency together.
- **trace_span / Tracer** (tracer.py) — host-side nested wall-clock
  spans per thread, exported as chrome-trace JSON (chrome://tracing /
  Perfetto). Device-side tracing stays with jax.profiler (XPlane);
  ``paddle_tpu.profiler.record_event`` records into BOTH so host spans
  and XPlane annotations line up, and
  ``python -m paddle_tpu.tools.timeline`` merges/summarizes the files.
- **RecompileWatchdog** (watchdog.py) — the executor reports every
  executable-cache miss; past a threshold the watchdog warns once,
  naming exactly which feed's shape/dtype diverged between the cached
  and the new signature (the actionable diagnosis of a recompile storm).
- **IntrospectionServer** (http.py) — stdlib HTTP server exposing the
  live process: ``/metrics`` (Prometheus text), ``/metrics.json``,
  ``/healthz`` (pluggable named checks), ``/debug/steps``,
  ``/debug/flight``. Start with ``serve_introspection(port)`` or by
  setting ``PDTPU_INTROSPECT_PORT``. While ``run_elastic`` runs it
  carries the ``elastic/progress`` (wedge detection,
  ``PDTPU_WEDGE_TIMEOUT``) and ``elastic/checkpoint`` (save in flight /
  writer died) checks; the crash-consistency stack also feeds the
  registry — ``checkpoint/fallback_steps``, ``checkpoint/write_retries``,
  ``elastic/guard_degraded``, and ``faults/injected{site,action}`` from
  the ``paddle_tpu.faults`` chaos harness.
- **StepProfiler** (steps.py) — one structured record per executor
  dispatch (wall time, signature, compile flag, dataio queue/h2d,
  fetch wait, device memory) in a rolling window, with a median/MAD
  straggler detector feeding ``steps/anomalies{reason=...}``.
- **FlightRecorder** (flight.py) — bounded ring of step records +
  warning events; on ``XlaRuntimeError``/``RESOURCE_EXHAUSTED`` the
  dispatch sites dump a post-mortem (steps, registry snapshot, device
  memory, compiled signatures, watchdog state) to ``PDTPU_FLIGHT_DIR``
  before re-raising.
- **SloEngine / AlertManager** (slo.py / alerts.py) — the judgment
  layer over the sensor plane: declarative `SloSpec`s compiled into
  recording rules evaluated on every `FederatedScraper` sweep, the
  standard multi-window multi-burn-rate page/warn formulation, a
  pending→firing→resolved alert state machine publishing
  ``ALERTS{alertname,severity,alertstate}``, pluggable sinks (file /
  webhook / callback — the autoscaler hook), an ``/alerts`` endpoint,
  an ``alerts`` health check, and alert-triggered flight dumps.
- **MetricsHistory / ProfileTrigger** (history.py / profile_trigger.py)
  — the root-cause loop: a bounded ring TSDB recording every scraper
  sweep (raw + 10 s + 120 s tiers, LRU memory cap, ``/history``
  endpoint, optional JSONL spill via ``PDTPU_HISTORY_DIR``), and an
  anomaly-triggered profiler that captures a bounded trace window on
  ``slow_step``/``recompile``/page events, diffs the per-kernel table
  against a recorded golden, and enriches the firing alert with the
  culprit kernels + the surrounding history window.
  ``tools/postmortem.py`` bundles all of it into one report.

Quick start::

    from paddle_tpu import observability as obs

    with obs.trace_span("train/epoch", epoch=e):
        exe.run(main, feed=..., fetch_list=[loss])

    print(obs.get_registry().report())           # text table
    obs.get_registry().dump_json("metrics.json") # registry export
    obs.get_tracer().export_chrome_trace("host_trace.json")
"""
from . import calibrate  # noqa: F401
from . import context  # noqa: F401
from . import federate  # noqa: F401
from . import perf  # noqa: F401
from .alerts import (Alert, AlertFiringError, AlertManager,  # noqa: F401
                     FileSink, WebhookSink, get_alert_manager,
                     install_alert_manager)
from .calibrate import Calibration, get_calibration  # noqa: F401
from .context import TraceContext  # noqa: F401
from .federate import (FederatedScraper, ScrapeTarget,  # noqa: F401
                       get_scraper, install_scraper)
from .flight import (FlightRecorder, get_flight_recorder,  # noqa: F401
                     is_oom, register_dump_section,
                     unregister_dump_section)
from .history import (MetricsHistory, get_history,  # noqa: F401
                      install_history)
from .http import (IntrospectionServer, maybe_serve_from_env,  # noqa: F401
                   register_health_check, run_health_checks,
                   serve_introspection, stop_introspection,
                   unregister_health_check)
from .memory import (device_memory_stats,  # noqa: F401
                     per_device_state_bytes, record_state_memory)
from .perf import CostLedger, ProgramCost, attribute, get_ledger  # noqa: F401
from .profile_trigger import (ProfileTrigger, get_trigger,  # noqa: F401
                              golden_path, install_trigger,
                              record_golden)
from .registry import (Counter, Gauge, Histogram, Registry,  # noqa: F401
                       get_registry, render_prometheus)
from .slo import (BURN_RATE_WINDOWS, SloEngine, SloSpec,  # noqa: F401
                  default_slos)
from .steps import StepProfiler, get_step_profiler  # noqa: F401
from .tracer import (Tracer, get_tracer, server_span,  # noqa: F401
                     start_trace, trace_span)
from .watchdog import (RecompileWarning, RecompileWatchdog,  # noqa: F401
                       diff_signatures, get_watchdog)

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "get_registry",
    "render_prometheus",
    "Calibration", "get_calibration", "calibrate",
    "CostLedger", "ProgramCost", "attribute", "get_ledger", "perf",
    "TraceContext", "context",
    "FederatedScraper", "ScrapeTarget", "install_scraper", "get_scraper",
    "device_memory_stats", "per_device_state_bytes", "record_state_memory",
    "Tracer", "get_tracer", "trace_span", "start_trace", "server_span",
    "RecompileWarning", "RecompileWatchdog", "diff_signatures",
    "get_watchdog",
    "FlightRecorder", "get_flight_recorder", "is_oom",
    "register_dump_section", "unregister_dump_section",
    "StepProfiler", "get_step_profiler",
    "IntrospectionServer", "serve_introspection", "stop_introspection",
    "maybe_serve_from_env", "register_health_check",
    "unregister_health_check", "run_health_checks",
    "SloSpec", "SloEngine", "default_slos", "BURN_RATE_WINDOWS",
    "Alert", "AlertManager", "AlertFiringError", "FileSink",
    "WebhookSink", "install_alert_manager", "get_alert_manager",
    "MetricsHistory", "install_history", "get_history",
    "ProfileTrigger", "install_trigger", "get_trigger",
    "golden_path", "record_golden",
]
