"""Alert lifecycle engine: pending → firing → resolved, with sinks.

`slo.SloEngine` decides *whether* a condition is breached each sweep;
this module owns everything that happens after: the per-alert state
machine (a condition must hold for ``for_s`` before it pages, a resolved
alert lingers ``resolved_hold_s`` so operators see what just cleared),
fan-out to pluggable sinks (JSONL file, webhook POST, or a plain
callable — the callable sink is the autoscaler hook of ROADMAP item 4),
``ALERTS{alertname,severity,alertstate}`` exposition series in the
Prometheus convention, a ``/alerts`` introspection document, an
``alerts`` health check (degraded while a warn-severity alert fires,
failing on page severity), and an alert-triggered `FlightRecorder`
post-mortem dump so every page ships its own forensics.

The manager is deliberately dumb about *what* it is alerting on: `update`
takes (name, severity, labels, active) and nothing else drives state.
That keeps it reusable for hand-rolled conditions (tests, operators)
alongside the SLO engine, and makes the state machine testable with an
injected clock (`now=`).
"""
from __future__ import annotations

import collections
import json
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional

from .flight import get_flight_recorder
from .registry import Registry, get_registry
from .tracer import get_tracer

__all__ = ["Alert", "AlertManager", "AlertFiringError", "FileSink",
           "WebhookSink", "install_alert_manager", "get_alert_manager"]

Registry.describe(
    "ALERTS", "1 for every live alert; labels alertname/severity/"
    "alertstate plus the alert's own labels (Prometheus convention)")
Registry.describe(
    "alerts/sink_errors", "alert sink deliveries that raised (the event "
    "is dropped for that sink only)")
Registry.describe(
    "alerts/transitions", "alert state-machine transitions, by to-state")


class AlertFiringError(Exception):
    """Never raised — the synthetic 'exception' a firing alert hands to
    `FlightRecorder.record_failure` so the page's post-mortem dump rides
    the existing forensics pipeline."""


def _label_items(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Alert:
    """One live alert instance: (name, severity, labels) plus lifecycle
    timestamps. State is one of pending / firing / resolved."""

    __slots__ = ("name", "severity", "labels", "state", "since",
                 "pending_at", "fired_at", "resolved_at", "value",
                 "annotations", "dump_path")

    def __init__(self, name: str, severity: str, labels: dict, now: float):
        self.name = name
        self.severity = severity
        self.labels = dict(labels)
        self.state = "pending"
        self.since = now          # start of the current condition episode
        self.pending_at = now
        self.fired_at: Optional[float] = None
        self.resolved_at: Optional[float] = None
        self.value: Optional[float] = None
        self.annotations: dict = {}
        self.dump_path: Optional[str] = None

    def doc(self) -> dict:
        return {"name": self.name, "severity": self.severity,
                "labels": dict(self.labels), "state": self.state,
                "since": self.since, "pending_at": self.pending_at,
                "fired_at": self.fired_at, "resolved_at": self.resolved_at,
                "value": self.value, "annotations": dict(self.annotations),
                "dump_path": self.dump_path}


class FileSink:
    """Append one JSON line per alert event (fire/resolve) to `path`."""

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = threading.Lock()

    def __call__(self, event: dict) -> None:
        line = json.dumps(event, default=str)
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")


class WebhookSink:
    """POST each alert event as JSON to `url` (stdlib urllib; short
    timeout so a dead receiver cannot stall the sweep)."""

    def __init__(self, url: str, timeout: float = 2.0):
        self.url = str(url)
        self.timeout = float(timeout)

    def __call__(self, event: dict) -> None:
        data = json.dumps(event, default=str).encode("utf-8")
        req = urllib.request.Request(
            self.url, data=data,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout):
            pass


class AlertManager:
    """The state machine + fan-out. One instance per process (installed
    via `install_alert_manager`); the SLO engine calls `update` for each
    compiled rule every sweep.

    Transitions (evaluated inside `update`, clock injectable via `now`):

        (absent)  --active-->  pending   (condition seen, not yet for_s)
        pending   --for_s-->   firing    (sinks notified; page-severity
                                          alerts also write a flight dump)
        pending   --clear-->   (removed silently — never fired)
        firing    --clear-->   resolved  (sinks notified)
        resolved  --active-->  firing    (re-fire, same episode record)
        resolved  --hold-->    (removed after resolved_hold_s)

    ``for_s=0`` fires on the first active update — the bench chaos cell
    relies on this to page within two scrape sweeps.
    """

    def __init__(self, for_s: float = 0.0, resolved_hold_s: float = 300.0,
                 sinks=(), flight_dump_severities=("page",),
                 registry: Optional[Registry] = None):
        self.for_s = float(for_s)
        self.resolved_hold_s = float(resolved_hold_s)
        self.flight_dump_severities = tuple(flight_dump_severities)
        self._sinks: List[Callable[[dict], None]] = list(sinks)
        self._enrichers: List[Callable[[Alert], Optional[dict]]] = []
        self._lock = threading.Lock()
        self._alerts: Dict[tuple, Alert] = {}
        self._recent: collections.deque = collections.deque(maxlen=256)
        self._reg = registry if registry is not None else get_registry()
        self._c_sink_err = self._reg.counter("alerts/sink_errors")

    # ------------------------------------------------------------- sinks
    def add_sink(self, sink: Callable[[dict], None]) -> Callable:
        """Register a callable receiving every fire/resolve event dict.
        This is the autoscaler's subscription point (ROADMAP item 4):
        an actuator passes a callback here and keys on
        ``event["name"]`` / ``event["labels"]``."""
        self._sinks.append(sink)
        return sink

    def add_enricher(self, fn: Callable[[Alert], Optional[dict]]) -> Callable:
        """Register a callable receiving every newly-FIRING Alert before
        sinks and the flight dump run: a returned dict merges into the
        alert's annotations, so the firing event ships with it. This is
        how the ProfileTrigger attaches culprit kernels + the /history
        window to a page (root-cause loop); enricher exceptions are
        swallowed — attribution is best-effort, paging is not."""
        self._enrichers.append(fn)
        return fn

    def _emit(self, event: dict) -> None:
        self._recent.append(dict(event))
        for sink in list(self._sinks):
            try:
                sink(dict(event))
            except Exception:
                self._c_sink_err.inc()

    # ------------------------------------------------------- state machine
    def update(self, name: str, active: bool, severity: str = "page",
               labels: Optional[dict] = None, value: Optional[float] = None,
               annotations: Optional[dict] = None,
               now: Optional[float] = None) -> Optional[Alert]:
        """Advance one alert's state machine with the condition's current
        truth value. Returns the live Alert (None once removed).
        Sink delivery and flight dumps happen after the lock is
        released, so a slow webhook cannot stall concurrent updates and
        a sink may safely call back into the manager."""
        now = time.monotonic() if now is None else float(now)
        labels = dict(labels or {})
        key = (name, severity, _label_items(labels))
        fired: Optional[Alert] = None
        events: List[dict] = []
        went_pending = False
        with self._lock:
            a = self._alerts.get(key)
            if a is None and active:
                a = Alert(name, severity, labels, now)
                self._alerts[key] = a
                self._set_state_gauge(a, None)
                self._reg.counter("alerts/transitions", to="pending").inc()
                went_pending = True
            if a is not None:
                if value is not None:
                    a.value = value
                if annotations:
                    a.annotations.update(annotations)
                if active:
                    if (a.state == "pending"
                            and now - a.pending_at >= self.for_s):
                        self._fire_locked(a, now, events)
                        fired = a
                    elif a.state == "resolved":
                        # condition came back while we held the resolved
                        # record: re-fire the same episode
                        a.resolved_at = None
                        self._fire_locked(a, now, events)
                        fired = a
                else:
                    if a.state == "pending":
                        # never fired: vanish silently
                        self._remove_locked(key, a)
                    elif a.state == "firing":
                        prev = a.state
                        a.state = "resolved"
                        a.resolved_at = now
                        self._set_state_gauge(a, prev)
                        self._reg.counter("alerts/transitions",
                                          to="resolved").inc()
                        events.append(self._event(a, "resolved", now))
            self._prune_locked(now)
            live = self._alerts.get(key)
        # alert timeline in merged fleet traces: one instant per state
        # transition, right next to the spans that explain it
        tracer = get_tracer()
        if went_pending:
            tracer.instant("alerts/pending",
                           {"alert": name, "severity": severity})
        for ev in events:
            tracer.instant(f"alerts/{ev['event']}",
                           {"alert": ev["name"],
                            "severity": ev["severity"]})
        if fired is not None and self._enrichers:
            # root-cause enrichment BEFORE the dump and the sinks, so
            # both carry the attribution
            for fn in list(self._enrichers):
                try:
                    extra = fn(fired)
                except Exception:
                    extra = None
                if extra:
                    fired.annotations.update(extra)
            for ev in events:
                if ev["event"] == "firing" and ev["name"] == fired.name:
                    ev["annotations"] = dict(fired.annotations)
        if (fired is not None
                and fired.severity in self.flight_dump_severities
                and fired.dump_path is None):
            fired.dump_path = self._flight_dump(fired)
            for ev in events:
                if ev["event"] == "firing" and ev["name"] == fired.name:
                    ev["dump_path"] = fired.dump_path
        for ev in events:
            self._emit(ev)
        return live

    def _fire_locked(self, a: Alert, now: float, events: List[dict]) -> None:
        prev = a.state
        a.state = "firing"
        a.fired_at = now
        self._set_state_gauge(a, prev)
        self._reg.counter("alerts/transitions", to="firing").inc()
        events.append(self._event(a, "firing", now))

    def _flight_dump(self, a: Alert) -> Optional[str]:
        """Every page ships its own post-mortem: reuse the OOM forensics
        pipeline with a synthetic exception naming the alert."""
        try:
            exc = AlertFiringError(
                f"alert {a.name} firing (severity={a.severity}, "
                f"labels={a.labels})")
            return get_flight_recorder().record_failure(exc, context={
                "where": "alerts", "alert": a.name,
                "severity": a.severity, "labels": dict(a.labels),
                "value": a.value, "annotations": dict(a.annotations)})
        except Exception:
            return None

    def _event(self, a: Alert, what: str, now: float) -> dict:
        return {"event": what, "t": now, "wall_t": time.time(),
                "name": a.name, "severity": a.severity,
                "labels": dict(a.labels), "value": a.value,
                "annotations": dict(a.annotations),
                "since": a.since, "dump_path": a.dump_path}

    # ----------------------------------------------------- ALERTS series
    def _alerts_labels(self, a: Alert, state: str) -> dict:
        out = dict(a.labels)
        out.update(alertname=a.name, severity=a.severity, alertstate=state)
        return out

    def _set_state_gauge(self, a: Alert, prev_state: Optional[str]) -> None:
        if prev_state is not None:
            self._reg.remove("ALERTS", **self._alerts_labels(a, prev_state))
        self._reg.gauge("ALERTS", **self._alerts_labels(a, a.state)).set(1)

    def _remove_locked(self, key: tuple, a: Alert) -> None:
        self._reg.remove("ALERTS", **self._alerts_labels(a, a.state))
        self._alerts.pop(key, None)

    def _prune_locked(self, now: float) -> None:
        for key, a in list(self._alerts.items()):
            if (a.state == "resolved" and a.resolved_at is not None
                    and now - a.resolved_at >= self.resolved_hold_s):
                self._remove_locked(key, a)

    # ------------------------------------------------------- introspection
    def alerts(self, state: Optional[str] = None,
               severity: Optional[str] = None) -> List[Alert]:
        with self._lock:
            out = list(self._alerts.values())
        if state is not None:
            out = [a for a in out if a.state == state]
        if severity is not None:
            out = [a for a in out if a.severity == severity]
        return out

    def firing(self, severity: Optional[str] = None) -> List[Alert]:
        return self.alerts(state="firing", severity=severity)

    def recent_events(self, n: int = 64) -> List[dict]:
        """Most recent fire/resolve events, oldest first — the alert
        timeline `tools/postmortem.py` bundles."""
        out = list(self._recent)
        return out[-int(n):] if n else out

    def doc(self) -> dict:
        """The ``/alerts`` endpoint document."""
        with self._lock:
            alerts = [a.doc() for a in self._alerts.values()]
        order = {"firing": 0, "pending": 1, "resolved": 2}
        alerts.sort(key=lambda d: (order.get(d["state"], 9), d["name"]))
        return {"alerts": alerts,
                "firing": sum(1 for d in alerts if d["state"] == "firing"),
                "pending": sum(1 for d in alerts if d["state"] == "pending"),
                "resolved": sum(
                    1 for d in alerts if d["state"] == "resolved"),
                "recent_events": self.recent_events(32)}

    def health_check(self):
        """/healthz ``alerts`` check: failing while any page-severity
        alert fires, degraded for any other firing severity."""
        firing = self.firing()
        if not firing:
            return "ok"
        names = ",".join(sorted({a.name for a in firing}))
        if any(a.severity == "page" for a in firing):
            return ("failing", f"page alerts firing: {names}")
        return ("degraded", f"alerts firing: {names}")

    def clear(self) -> None:
        """Drop every live alert and its ALERTS series (tests)."""
        with self._lock:
            for key, a in list(self._alerts.items()):
                self._remove_locked(key, a)


# process-wide manager: what /alerts and the healthz check answer from
_installed: Optional[AlertManager] = None
_install_lock = threading.Lock()


def install_alert_manager(mgr: Optional[AlertManager]):
    """Make `mgr` the process-wide alert manager: the ``/alerts``
    endpoint serves its `doc()` and /healthz gains the ``alerts`` check.
    None uninstalls both. Returns the manager."""
    global _installed
    from .http import register_health_check, unregister_health_check
    with _install_lock:
        _installed = mgr
    if mgr is None:
        unregister_health_check("alerts")
    else:
        register_health_check("alerts", mgr.health_check)
    return mgr


def get_alert_manager() -> Optional[AlertManager]:
    with _install_lock:
        return _installed
