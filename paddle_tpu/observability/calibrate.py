"""Chip floor calibration: measured matmul/stream rates, cached on disk.

Promoted out of bench.py (where `_measure_floors` ran once per bench
invocation, and before that once per *section*): the two microbenches
that anchor every roofline statement the runtime makes — a chained
8192² bf16 matmul ladder for the MXU rate and a 256 Mi-element
elementwise chain for the HBM stream rate — now live behind one shared
`get_calibration()` with an on-disk cache keyed by (device kind, host),
so a machine measures its floors once and every later process (bench
sections, subprocess children, the perf ledger, the roofline CLI) reads
the same numbers.

Measurement protocol (unchanged from bench.py — see the docstring on
`measure_floors`): both microbenches CHAIN the work inside one jit
(lax.scan / dependent matmuls) and rates are read from the xplane trace
per-kernel device durations, NOT host timers. On this tunnel runtime
`block_until_ready` acks before device completion and a single dispatch
carries ~4 ms of latency, so unchained host-timed micro-numbers are
garbage; host-timed chains are distorted by ~1 ms/iteration of
while-loop runtime overhead and XLA fuses unrolled elementwise chains
into one kernel.

Cache location: ``PDTPU_CALIBRATION_DIR`` (default
``~/.cache/paddle_tpu/calibration``), one JSON file per
``{device_kind}_{hostname}``. `get_calibration(recalibrate=True)` (the
``bench.py --recalibrate`` escape hatch) bypasses both the process memo
and the disk cache and rewrites the file.

Sources, in the `Calibration.source` field:

- ``measured``    — trace-derived rates from a live TPU run
- ``fallback``    — TPU but no trace captured; conservative rates
- ``placeholder`` — non-TPU backend (CPU smoke): nominal rates so the
  roofline math stays finite and deterministic
- ``cache``       — loaded from disk (whatever source wrote it)
"""
from __future__ import annotations

import json
import os
import re
import socket
import threading
import time
from dataclasses import asdict, dataclass
from typing import Optional, Tuple

__all__ = ["Calibration", "get_calibration", "measure_floors",
           "peak_flops", "cache_path", "reset"]

# v5e bf16 peak; CPU placeholder for non-TPU smoke runs (moved verbatim
# from bench._peak_flops)
_PEAK_TPU_BF16 = 197e12
_PEAK_CPU = 1e12

_FALLBACK_TPU = (60.0, 350.0)      # trace unavailable on TPU
_PLACEHOLDER_CPU = (1.0, 10.0)     # non-TPU nominal rates


@dataclass
class Calibration:
    """One machine's measured (or assumed) chip floors."""

    device_kind: str
    on_tpu: bool
    matmul_tflops: float
    stream_gbs: float
    peak_flops: float
    source: str            # "measured" | "fallback" | "placeholder" | "cache"
    measured_at: float = 0.0
    host: str = ""

    @property
    def floors(self) -> Tuple[float, float]:
        """The (matmul_tflops, stream_gbs) tuple bench.py threads around."""
        return (self.matmul_tflops, self.stream_gbs)

    def to_dict(self) -> dict:
        return asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "Calibration":
        return Calibration(
            device_kind=str(d["device_kind"]), on_tpu=bool(d["on_tpu"]),
            matmul_tflops=float(d["matmul_tflops"]),
            stream_gbs=float(d["stream_gbs"]),
            peak_flops=float(d["peak_flops"]), source=str(d["source"]),
            measured_at=float(d.get("measured_at", 0.0)),
            host=str(d.get("host", "")))


def peak_flops(on_tpu: bool) -> float:
    return _PEAK_TPU_BF16 if on_tpu else _PEAK_CPU


def _device_kind() -> Tuple[str, bool]:
    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu" or "tpu" in str(dev).lower()
    kind = getattr(dev, "device_kind", None) or dev.platform
    return str(kind), on_tpu


def _cache_dir() -> str:
    return (os.environ.get("PDTPU_CALIBRATION_DIR")
            or os.path.expanduser("~/.cache/paddle_tpu/calibration"))


def cache_path(device_kind: Optional[str] = None,
               host: Optional[str] = None) -> str:
    """Cache file for this (device kind, host) — one floor set per
    machine, shared by every process on it."""
    if device_kind is None:
        device_kind, _ = _device_kind()
    host = host or socket.gethostname()
    key = re.sub(r"[^A-Za-z0-9._-]", "_", f"{device_kind}_{host}")
    return os.path.join(_cache_dir(), f"{key}.json")


def measure_floors(on_tpu: bool) -> Tuple[float, float, str]:
    """Run the two microbenches and return
    (matmul_tflops, stream_gbs, source).

    Chained work + trace-derived kernel times, per the module docstring.
    Non-TPU backends get nominal placeholder rates without dispatching
    anything — the CPU numbers would be meaningless and slow to get.
    """
    if not on_tpu:
        return (*_PLACEHOLDER_CPU, "placeholder")
    import glob
    import gzip
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    a = jax.random.normal(jax.random.PRNGKey(0), (8192, 8192), jnp.bfloat16)

    @jax.jit
    def mm_chain(a):
        def body(c, _):
            return c @ a, None
        y, _ = lax.scan(body, a, None, length=10)
        return y

    x = jax.random.normal(jax.random.PRNGKey(1), (256 * 1024 * 1024,),
                          jnp.bfloat16)

    @jax.jit
    def add_chain(x):
        def body(c, _):
            return c * jnp.bfloat16(1.0001) + jnp.bfloat16(1e-3), None
        y, _ = lax.scan(body, x, None, length=20)
        return y

    def leaf_kernel_us(run):
        """Trace one run; sum device-side LEAF kernel time (drop the
        `while` loop-overhead span, the jit_* parent spans, and step
        markers — only actual kernels count)."""
        tdir = tempfile.mkdtemp(prefix="pdtpu_floors_")
        with jax.profiler.trace(tdir):
            run()
        traces = glob.glob(tdir + "/plugins/profile/*/*.trace.json.gz")
        if not traces:
            return 0.0
        with gzip.open(traces[0]) as f:
            tr = json.load(f)
        dev_pids = {e["pid"] for e in tr["traceEvents"]
                    if e.get("ph") == "M" and e.get("name") == "process_name"
                    and "TPU" in e["args"].get("name", "")}
        total = 0.0
        for e in tr["traceEvents"]:
            nm = e.get("name", "")
            if (e.get("ph") == "X" and e.get("pid") in dev_pids
                    and nm != "while" and not nm.startswith("jit_")
                    and not nm.isdigit()):
                total += e.get("dur", 0.0)
        return total

    for f in (lambda: mm_chain(a), lambda: add_chain(x)):  # compile
        np.asarray(jax.device_get(
            jax.tree_util.tree_leaves(f())[0].ravel()[:1]))
    mm_us = leaf_kernel_us(
        lambda: np.asarray(jax.device_get(mm_chain(a)[:1, :1])))
    add_us = leaf_kernel_us(
        lambda: np.asarray(jax.device_get(add_chain(x)[:1])))
    if not mm_us or not add_us:  # trace unavailable: conservative fallback
        return (*_FALLBACK_TPU, "fallback")
    mm_rate = 10 * 2 * 8192**3 / (mm_us * 1e-6)
    stream = 20 * 2 * x.size * 2 / (add_us * 1e-6)
    return mm_rate / 1e12, stream / 1e9, "measured"


_lock = threading.Lock()
_memo: Optional[Calibration] = None


def reset() -> None:
    """Drop the in-process memo (tests; does not touch the disk cache)."""
    global _memo
    with _lock:
        _memo = None


def get_calibration(recalibrate: bool = False) -> Calibration:
    """THE calibration for this machine: process memo → disk cache →
    fresh measurement (which also writes the cache). `recalibrate=True`
    bypasses memo and cache and rewrites the file."""
    global _memo
    with _lock:
        if _memo is not None and not recalibrate:
            return _memo
        kind, on_tpu = _device_kind()
        path = cache_path(kind)
        if not recalibrate:
            cached = _load(path, kind)
            if cached is not None:
                _memo = cached
                return _memo
        mm, stream, source = measure_floors(on_tpu)
        calib = Calibration(
            device_kind=kind, on_tpu=on_tpu, matmul_tflops=float(mm),
            stream_gbs=float(stream), peak_flops=peak_flops(on_tpu),
            source=source, measured_at=time.time(),
            host=socket.gethostname())
        _store(path, calib)
        _memo = calib
        return _memo


def _load(path: str, device_kind: str) -> Optional[Calibration]:
    try:
        with open(path) as f:
            d = json.load(f)
        if d.get("device_kind") != device_kind:
            return None
        c = Calibration.from_dict(d)
        c.source = "cache"
        return c
    except Exception:
        return None


def _store(path: str, calib: Calibration) -> None:
    # best-effort: an unwritable cache dir must never fail a run
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(calib.to_dict(), f, indent=1)
        os.replace(tmp, path)
    except Exception:
        pass
