"""Distributed trace context: the identity a request carries across
process boundaries.

A `TraceContext` is three ids — ``trace_id`` (the whole request),
``span_id`` (the current operation), ``parent_id`` (the operation that
caused it) — plus nothing else: no baggage, no sampling flags. It rides
the existing JSON header of the PS wire protocol and the fleet worker
RPC as a ``"trace"`` dict (``{"trace_id", "span_id"}``, plus
``"retry": n`` on re-sent frames), so propagation costs one small dict
per RPC and zero new dependencies.

The active context is thread-local. Crossing an explicit thread hop
(pool fan-out in `ShardedTable`, the serving batcher queue, a replica's
RPC pool) requires capturing `current()` on the submitting thread and
re-activating it with `use(ctx)` on the worker thread — thread-locals
don't follow work items on their own, and the hop points in this
codebase each do so explicitly.

stdlib-only on purpose: pserver processes import this via
``ps.transport`` and must stay JAX-free.
"""
from __future__ import annotations

import os
import threading

from typing import Optional

__all__ = ["TraceContext", "current", "use", "new_trace", "from_wire"]


def _new_id() -> str:
    return os.urandom(8).hex()


class TraceContext:
    """Immutable (trace_id, span_id, parent_id) triple."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: Optional[str] = None,
                 parent_id: Optional[str] = None):
        self.trace_id = str(trace_id)
        self.span_id = str(span_id) if span_id else _new_id()
        self.parent_id = str(parent_id) if parent_id else None

    def child(self) -> "TraceContext":
        """A new span in the same trace, parented to this one."""
        return TraceContext(self.trace_id, _new_id(), self.span_id)

    def to_wire(self) -> dict:
        """The RPC header payload. Deliberately minimal: the receiver
        only needs the trace and the sender's span to parent to."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def args(self) -> dict:
        """Chrome-trace ``args`` fields — what the fleet timeline merger
        keys on to pair client and server spans."""
        a = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id:
            a["parent_id"] = self.parent_id
        return a

    def __repr__(self):
        return (f"TraceContext(trace={self.trace_id} span={self.span_id} "
                f"parent={self.parent_id})")


def new_trace() -> TraceContext:
    """Root context for a fresh trace (no parent)."""
    return TraceContext(os.urandom(16).hex(), _new_id(), None)


def from_wire(wire) -> Optional[TraceContext]:
    """Server-side adoption of an incoming ``"trace"`` header: a FRESH
    span in the sender's trace, parented to the sender's span. Returns
    None for absent/malformed headers — tracing is best-effort and must
    never fail an RPC."""
    if not isinstance(wire, dict):
        return None
    tid, sid = wire.get("trace_id"), wire.get("span_id")
    if not (isinstance(tid, str) and tid and isinstance(sid, str) and sid):
        return None
    return TraceContext(tid, _new_id(), sid)


# -- thread-local active context ------------------------------------------

_tls = threading.local()


def current() -> Optional[TraceContext]:
    """The context active on this thread, or None."""
    return getattr(_tls, "ctx", None)


def _activate(ctx: Optional[TraceContext]):
    """Set `ctx` as this thread's active context; returns a token for
    `_restore`. Activating None is a no-op that still returns a token."""
    prev = getattr(_tls, "ctx", None)
    if ctx is not None:
        _tls.ctx = ctx
    return (ctx is not None, prev)


def _restore(token) -> None:
    changed, prev = token
    if changed:
        _tls.ctx = prev


class use:
    """``with use(ctx):`` — activate a captured context on this thread
    (the thread-hop idiom). ``use(None)`` is a no-op, so call sites
    don't need to branch on whether a trace is active."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx
        self._token = None

    def __enter__(self):
        self._token = _activate(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        _restore(self._token)
        self._token = None
        return False
