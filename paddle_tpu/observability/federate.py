"""Fleet-wide metrics federation: one scrape of every process.

Since the PS/fleet PRs the runtime is many processes — pserver shards,
serving worker subprocesses, the coordinator — each with its own
process-local `Registry`. This module is the aggregation point: a
`FederatedScraper` holds a list of `ScrapeTarget`s (one per process),
pulls each one's structured series (`Registry.series()` shape), and
re-exports the union with ``process``/``role``(/``shard``) labels
appended through the SAME exposition renderer the local ``/metrics``
endpoint uses (`registry.render_prometheus`), so federated output obeys
identical name-sanitization and label-escaping rules.

Three target kinds, matching how each process can actually be reached:

* ``http`` — a process running the introspection server
  (``PDTPU_INTROSPECT_PORT``): ``GET /metrics/series`` (structured),
  falling back to parsing the flat ``/metrics.json`` snapshot for
  pre-PR-13 processes;
* ``ps`` — a pserver: the ``metrics`` op of the PS wire protocol
  (pservers have no HTTP server and must stay JAX-free — the transport
  op costs nothing they don't already have);
* ``call`` — anything reachable as a Python callable returning a series
  list: the local registry, a `ThreadReplica`/`ProcessReplica`
  (both expose ``.metrics()``), a test stub.

Derived autoscaler signals (ROADMAP #5): every ``scrape_once()`` also
distills the merged series into the gauges an autoscaler keys on —
per-shard pull p99, per-process serving queue depth, straggler/anomaly
counts, shard recovery counts, shards currently down — published into
the coordinator's own registry under ``autoscale/*`` so they ride the
normal ``/metrics`` export and the ``/fleet`` endpoint alike.

Off the hot path by construction: scraping happens on this thread (or
the optional 1 Hz background thread via ``start()``), touches workers
only through their existing metrics surfaces, and records its own cost
in ``fleet/scrape_ms`` — the bench asserts the delta on the training
step is noise (<1%).
"""
from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

from typing import Callable, List, Optional

from .registry import Registry, get_registry, render_prometheus

__all__ = ["ScrapeTarget", "FederatedScraper", "install_scraper",
           "get_scraper"]

Registry.describe("autoscale/ps_pull_p99_ms",
                  "worst per-shard PS pull p99 seen across the fleet")
Registry.describe("autoscale/queue_depth",
                  "serving queue depth per process")
Registry.describe("autoscale/stragglers",
                  "step anomaly count summed across the fleet")
Registry.describe("autoscale/recoveries",
                  "PS shard recovery count summed across the fleet")
Registry.describe("autoscale/shards_down",
                  "PS shards currently reporting down")
Registry.describe("autoscale/targets_unreachable",
                  "scrape targets that failed this sweep")
Registry.describe("fleet/scrape_ms", "federated sweep duration")


def _series_from_snapshot(snap: dict) -> List[dict]:
    """Best-effort conversion of a flat ``/metrics.json`` snapshot
    (``name{k="v",...}`` keys) back into series dicts — the fallback for
    processes that predate ``/metrics/series``. Label values containing
    quotes won't round-trip perfectly; structured scraping is the fix,
    this keeps old workers visible rather than dark."""
    out: List[dict] = []
    for key, v in snap.items():
        name, labels = key, {}
        if key.endswith("}") and "{" in key:
            name, inner = key.split("{", 1)
            for part in inner[:-1].split('",'):
                if "=" not in part:
                    continue
                k, val = part.split("=", 1)
                labels[k.strip()] = val.strip().strip('"')
        if isinstance(v, dict):
            out.append({"name": name, "type": "summary", "labels": labels,
                        "summary": dict(v)})
        else:
            # flat snapshots don't distinguish counter from gauge; gauge
            # is the lossless guess (no monotonicity claim)
            out.append({"name": name, "type": "gauge", "labels": labels,
                        "value": v})
    return out


class ScrapeTarget:
    """One process to scrape. Build via the classmethods."""

    def __init__(self, name: str, role: str, kind: str,
                 address: str = "", shard: Optional[int] = None,
                 fn: Optional[Callable[[], list]] = None):
        self.name = str(name)
        self.role = str(role)
        self.kind = kind
        self.address = address
        self.shard = shard
        self._fn = fn

    @classmethod
    def http(cls, base_url: str, name: str = "", role: str = "worker"):
        """A process with the introspection HTTP server."""
        base = base_url.rstrip("/")
        return cls(name or base, role, "http", address=base)

    @classmethod
    def ps(cls, endpoint: str, shard: int, name: str = ""):
        """A pserver, via the transport ``metrics`` op."""
        return cls(name or f"pserver:{endpoint}", "pserver", "ps",
                   address=endpoint, shard=int(shard))

    @classmethod
    def call(cls, fn: Callable[[], list], name: str, role: str):
        """Anything that can hand over a series list directly: the local
        registry, a fleet replica handle, a test stub."""
        return cls(name, role, "call", fn=fn)

    @classmethod
    def local(cls, name: str = "coordinator", role: str = "coordinator"):
        return cls.call(lambda: get_registry().series(deep=True),
                        name, role)

    def extra_labels(self) -> tuple:
        extra = (("process", self.name), ("role", self.role))
        if self.shard is not None:
            extra += (("shard", str(self.shard)),)
        return extra

    def scrape(self, timeout: float) -> List[dict]:
        if self.kind == "call":
            return list(self._fn())
        if self.kind == "ps":
            from ..ps.transport import SocketClient
            c = SocketClient(self.address, timeout=timeout, retries=0)
            try:
                return c.metrics()
            finally:
                c.close()
        # http: structured endpoint first, flat snapshot as fallback
        try:
            with urllib.request.urlopen(self.address + "/metrics/series",
                                        timeout=timeout) as resp:
                return json.load(resp)
        except urllib.error.HTTPError:
            with urllib.request.urlopen(self.address + "/metrics.json",
                                        timeout=timeout) as resp:
                return _series_from_snapshot(json.load(resp))


def _series_value(series: List[dict], name: str, field: str = "value"):
    """Sum of `field` over every series named `name` (labels ignored)."""
    vals = [s.get(field) for s in series if s.get("name") == name]
    vals = [v for v in vals if isinstance(v, (int, float))]
    return sum(vals) if vals else None


class FederatedScraper:
    """Scrapes every target, merges, re-labels, derives the autoscaler
    signals. `scrape_once()` is the whole protocol; `start()` runs it on
    a background thread at `interval_s` for continuously-fresh gauges.
    """

    def __init__(self, targets=(), interval_s: float = 1.0,
                 timeout: float = 2.0):
        self.targets: List[ScrapeTarget] = list(targets)
        self.interval_s = float(interval_s)
        self.timeout = float(timeout)
        self._lock = threading.Lock()
        self._last: Optional[dict] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._listeners: List[Callable[[dict], None]] = []
        # label sets published into autoscale/* on the previous sweep,
        # so _signals can retire gauges whose source target vanished
        self._prev_pull_shards: set = set()
        self._prev_queue_procs: set = set()
        reg = get_registry()
        self._h_scrape = reg.histogram("fleet/scrape_ms")
        self._c_failed = reg.counter("fleet/scrape_failures")

    def add_target(self, target: ScrapeTarget) -> ScrapeTarget:
        """Add a target; a target with the SAME name replaces the old
        one (re-adding a bounced worker must not double-count it)."""
        with self._lock:
            self.targets = ([t for t in self.targets
                             if t.name != target.name] + [target])
        return target

    def remove_target(self, name: str) -> bool:
        """Drop the target named `name`; its derived ``autoscale/*``
        gauges retire on the next sweep. Returns True if found."""
        with self._lock:
            before = len(self.targets)
            self.targets = [t for t in self.targets if t.name != name]
            return len(self.targets) != before

    def add_sweep_listener(self, fn: Callable[[dict], None]) -> Callable:
        """Call ``fn(doc)`` with every completed sweep document — the
        SLO engine's subscription point. Listener exceptions are
        swallowed (an alerting bug must not kill the scrape loop)."""
        self._listeners.append(fn)
        return fn

    # ------------------------------------------------------------- scraping
    def scrape_once(self) -> dict:
        """One federated sweep: the ``/fleet`` document. Always returns —
        per-target failures are recorded (``ok: false`` + error string),
        never raised, so one dead worker can't take down the scrape."""
        t0 = time.perf_counter()
        results = []
        with self._lock:
            targets = list(self.targets)
        for t in targets:
            s0 = time.perf_counter()
            try:
                series = t.scrape(self.timeout)
                ok, err = True, None
            except Exception as e:
                series, ok, err = [], False, f"{type(e).__name__}: {e}"
                self._c_failed.inc()
            results.append({
                "process": t.name, "role": t.role, "shard": t.shard,
                "ok": ok, "error": err,
                "scrape_ms": (time.perf_counter() - s0) * 1e3,
                "series": series,
            })
        doc = {"t": time.time(),
               "targets": results,
               "ok": all(r["ok"] for r in results),
               "signals": self._signals(results)}
        self._h_scrape.observe((time.perf_counter() - t0) * 1e3)
        with self._lock:
            self._last = doc
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(doc)
            except Exception:
                pass  # a listener bug must not kill the scrape loop
        return doc

    def last(self) -> Optional[dict]:
        with self._lock:
            return self._last

    # ------------------------------------------------------------ rendering
    def prometheus_text(self, refresh: bool = False) -> str:
        """The whole fleet in exposition format: each target's series
        rendered with its ``process``/``role``(/``shard``) labels
        appended, via the same renderer as local ``/metrics``."""
        doc = None if refresh else self.last()
        if doc is None:
            doc = self.scrape_once()
        chunks = []
        for r in doc["targets"]:
            t_extra = (("process", r["process"]), ("role", r["role"]))
            if r["shard"] is not None:
                t_extra += (("shard", str(r["shard"])),)
            chunks.append(render_prometheus(r["series"],
                                            extra_labels=t_extra))
        return "".join(chunks)

    # ------------------------------------------------- autoscaler signals
    def _signals(self, results: List[dict]) -> dict:
        """Distill the merged scrape into the ROADMAP-5 decision gauges
        and publish them into the local registry (``autoscale/*``)."""
        reg = get_registry()
        pull_p99: dict = {}      # shard label -> worst p99 seen
        queue_depth: dict = {}   # process -> depth
        stragglers = 0.0
        recoveries = 0.0
        shards_down = 0
        for r in results:
            if not r["ok"]:
                continue
            for s in r["series"]:
                name = s.get("name")
                if name == "ps/shard_pull_ms":
                    sh = (s.get("labels") or {}).get("shard", "?")
                    p99 = (s.get("summary") or {}).get("p99")
                    if isinstance(p99, (int, float)):
                        pull_p99[sh] = max(pull_p99.get(sh, 0.0),
                                           float(p99))
                elif name == "serving/queue_depth":
                    v = s.get("value")
                    if isinstance(v, (int, float)):
                        queue_depth[r["process"]] = (
                            queue_depth.get(r["process"], 0.0) + float(v))
                elif name == "steps/anomalies":
                    v = s.get("value")
                    if isinstance(v, (int, float)):
                        stragglers += float(v)
                elif name == "ps/recoveries":
                    v = s.get("value")
                    if isinstance(v, (int, float)):
                        recoveries += float(v)
                elif name == "ps/shard_up":
                    if not s.get("value"):
                        shards_down += 1
        # retire per-label gauges whose source vanished this sweep — a
        # removed shard/process must not linger as a live-looking sample
        for sh in self._prev_pull_shards - set(pull_p99):
            reg.remove("autoscale/ps_pull_p99_ms", shard=sh)
        for proc in self._prev_queue_procs - set(queue_depth):
            reg.remove("autoscale/queue_depth", process=proc)
        self._prev_pull_shards = set(pull_p99)
        self._prev_queue_procs = set(queue_depth)
        for sh, v in pull_p99.items():
            reg.gauge("autoscale/ps_pull_p99_ms", shard=sh).set(v)
        for proc, v in queue_depth.items():
            reg.gauge("autoscale/queue_depth", process=proc).set(v)
        reg.gauge("autoscale/stragglers").set(stragglers)
        reg.gauge("autoscale/recoveries").set(recoveries)
        reg.gauge("autoscale/shards_down").set(shards_down)
        reg.gauge("autoscale/targets_unreachable").set(
            sum(1 for r in results if not r["ok"]))
        return {
            "ps_pull_p99_ms": pull_p99,
            "queue_depth": queue_depth,
            "stragglers": stragglers,
            "recoveries": recoveries,
            "shards_down": shards_down,
            "targets_unreachable": sum(
                1 for r in results if not r["ok"]),
        }

    # ---------------------------------------------------- background thread
    def start(self) -> "FederatedScraper":
        """Scrape at `interval_s` on a daemon thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-scraper", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception:
                pass  # scrape_once already accounts per-target failures
            self._stop.wait(self.interval_s)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


# the scraper the coordinator's HTTP plane serves from /fleet
_installed: Optional[FederatedScraper] = None
_install_lock = threading.Lock()


def install_scraper(scraper: Optional[FederatedScraper]):
    """Make `scraper` the one the introspection server's ``/fleet``
    endpoint answers from (None uninstalls). Returns the scraper."""
    global _installed
    with _install_lock:
        _installed = scraper
    return scraper


def get_scraper() -> Optional[FederatedScraper]:
    with _install_lock:
        return _installed
