"""Flight recorder: crash forensics for OOM / XLA runtime failures.

The reference framework's profiler could reconstruct a timeline *after*
a run finished, but a device OOM kills the process with a bare
``RESOURCE_EXHAUSTED`` and no context — which step tipped over, what the
queue and memory looked like, which signatures were resident. The
flight recorder keeps a bounded in-memory ring of the most recent step
records (fed by `steps.StepProfiler`) and warning-level events; when a
dispatch site (`Executor.run`, `DynamicBatcher.dispatch`, bench
sections) catches an `XlaRuntimeError` / ``RESOURCE_EXHAUSTED`` it calls
`record_failure(exc)` to write a post-mortem JSON dump — last-N step
records, a deep registry snapshot, per-device memory stats, and any
registered forensic sections (compiled-signature cache keys, watchdog
state) — before re-raising the original exception unchanged.

Dump destination is ``PDTPU_FLIGHT_DIR``; without it the dump is kept
in memory only (``last_dump``) and still served at ``/debug/flight``.
Ring sizes: ``PDTPU_FLIGHT_STEPS`` (default 64) step records, 128
events.

The dump directory itself is capped: alert-triggered dumps (PR 17) made
writes routine, so after each write the recorder deletes oldest-first
past ``PDTPU_FLIGHT_MAX_DUMPS`` (default 32) files or
``PDTPU_FLIGHT_MAX_MB`` (default 256) total, counting deletions in
``flight/dumps_pruned``. The dump just written is never pruned.
"""
from __future__ import annotations

import collections
import contextlib
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, Optional

__all__ = ["FlightRecorder", "get_flight_recorder", "is_oom",
           "register_dump_section", "unregister_dump_section"]

logger = logging.getLogger("paddle_tpu.observability.flight")

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")
_OOM_TYPE_NAMES = ("XlaRuntimeError", "JaxRuntimeError")


def is_oom(exc: BaseException) -> bool:
    """True for failures the flight recorder should dump on: jax/XLA
    runtime errors and anything carrying a RESOURCE_EXHAUSTED marker."""
    msg = str(exc)
    if any(m in msg for m in _OOM_MARKERS):
        return True
    for klass in type(exc).__mro__:
        if klass.__name__ in _OOM_TYPE_NAMES:
            return True
    return False


# Forensic dump sections: other layers register a callable producing a
# JSON-safe value; flight.py stays import-cycle-free (the executor
# imports us, never the reverse).
_sections_lock = threading.Lock()
_sections: Dict[str, Callable[[], object]] = {}


def register_dump_section(name: str, fn: Callable[[], object]) -> None:
    """Include ``fn()`` under ``sections[name]`` in every flight dump.
    The callable must not raise for long — errors are captured inline."""
    with _sections_lock:
        _sections[name] = fn


def unregister_dump_section(name: str) -> None:
    with _sections_lock:
        _sections.pop(name, None)


def _collect_sections() -> dict:
    with _sections_lock:
        items = list(_sections.items())
    out = {}
    for name, fn in items:
        try:
            out[name] = fn()
        except Exception as e:  # a broken provider must not mask the OOM
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    return out


def _per_device_memory() -> dict:
    """memory_stats() for every local device (missing on CPU -> {})."""
    out: dict = {}
    try:
        import jax
        for dev in jax.local_devices():
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if stats:
                out[str(dev)] = dict(stats)
    except Exception:
        pass
    return out


class FlightRecorder:
    """Bounded ring of step records + warning events, dumped on failure."""

    def __init__(self, step_cap: Optional[int] = None, event_cap: int = 128):
        if step_cap is None:
            step_cap = int(os.environ.get("PDTPU_FLIGHT_STEPS", "64"))
        self._lock = threading.Lock()
        self._steps = collections.deque(maxlen=max(1, int(step_cap)))
        self._events = collections.deque(maxlen=max(1, int(event_cap)))
        self._dump_seq = 0
        self.last_dump: Optional[dict] = None
        self.last_dump_path: Optional[str] = None

    # -- feeding the ring --------------------------------------------------
    def note_step(self, record: dict) -> None:
        with self._lock:
            self._steps.append(record)

    def note_event(self, level: str, message: str, **ctx) -> None:
        ev = {"t": time.time(), "level": level, "message": message}
        if ctx:
            ev.update(ctx)
        with self._lock:
            self._events.append(ev)

    def contents(self) -> dict:
        """Current ring contents (served at /debug/flight)."""
        with self._lock:
            return {"steps": list(self._steps),
                    "events": list(self._events),
                    "last_dump_path": self.last_dump_path,
                    "last_dump": self.last_dump}

    # -- post-mortem -------------------------------------------------------
    def record_failure(self, exc: BaseException,
                       context: Optional[dict] = None) -> Optional[str]:
        """Assemble a post-mortem dump; write it to PDTPU_FLIGHT_DIR when
        set. Returns the dump path (None when kept in memory only).
        Never raises: forensics must not replace the original error."""
        try:
            return self._record_failure(exc, context)
        except Exception as e:
            logger.warning("flight dump failed: %s: %s",
                           type(e).__name__, e)
            return None

    def _record_failure(self, exc, context) -> Optional[str]:
        from .registry import get_registry
        from .watchdog import get_watchdog
        with self._lock:
            steps = list(self._steps)
            events = list(self._events)
            self._dump_seq += 1
            seq = self._dump_seq
        dump = {
            "time": time.time(),
            "pid": os.getpid(),
            "exception": {"type": type(exc).__name__,
                          "message": str(exc)[:4000]},
            "context": dict(context or {}),
            "steps": steps,
            "events": events,
            "registry": get_registry().snapshot(deep=True),
            "device_memory": _per_device_memory(),
            "sections": _collect_sections(),
            "watchdog": get_watchdog().state(),
        }
        path = None
        flight_dir = os.environ.get("PDTPU_FLIGHT_DIR")
        if flight_dir:
            os.makedirs(flight_dir, exist_ok=True)
            fname = (f"flight_{os.getpid()}_"
                     f"{int(dump['time'] * 1000)}_{seq}.json")
            path = os.path.join(flight_dir, fname)
            with open(path, "w") as f:
                json.dump(dump, f, indent=2, default=str)
            self._prune_dumps(flight_dir, keep=path)
        with self._lock:
            self.last_dump = dump
            self.last_dump_path = path
        logger.warning(
            "flight recorder: %s during %s — post-mortem %s "
            "(%d step records, %d events)",
            dump["exception"]["type"],
            dump["context"].get("where", "<unknown>"),
            path or "kept in memory (set PDTPU_FLIGHT_DIR to persist)",
            len(steps), len(events))
        return path

    def _prune_dumps(self, flight_dir: str, keep: str) -> None:
        """Oldest-first retention over the dump directory: alert-driven
        dumps must not fill the disk over a long incident. Never touches
        `keep` (the dump just written); failures are swallowed."""
        try:
            max_dumps = max(1, int(
                os.environ.get("PDTPU_FLIGHT_MAX_DUMPS", "32")))
            max_bytes = int(float(
                os.environ.get("PDTPU_FLIGHT_MAX_MB", "256")) * 1024 * 1024)
            entries = []
            for f in os.listdir(flight_dir):
                if not (f.startswith("flight_") and f.endswith(".json")):
                    continue
                p = os.path.join(flight_dir, f)
                try:
                    st = os.stat(p)
                    entries.append((st.st_mtime, p, st.st_size))
                except OSError:
                    continue
            entries.sort()  # oldest first (pid in the name breaks lexical)
            total = sum(sz for _, _, sz in entries)
            pruned = 0
            for _, p, sz in entries:
                if len(entries) - pruned <= max_dumps and total <= max_bytes:
                    break
                if p == keep:
                    continue
                try:
                    os.unlink(p)
                    pruned += 1
                    total -= sz
                except OSError:
                    pass
            if pruned:
                from .registry import get_registry
                get_registry().counter("flight/dumps_pruned").inc(pruned)
        except Exception:
            pass

    @contextlib.contextmanager
    def guard(self, where: str, **ctx):
        """Wrap a device-dispatch site: on OOM, dump then re-raise the
        ORIGINAL exception unchanged (bare raise)."""
        try:
            yield
        except BaseException as e:
            if is_oom(e):
                self.record_failure(e, context={"where": where, **ctx})
            raise

    def reset(self) -> None:
        with self._lock:
            self._steps.clear()
            self._events.clear()
            self.last_dump = None
            self.last_dump_path = None


_recorder = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    """THE process-wide flight recorder all dispatch sites report into."""
    return _recorder
