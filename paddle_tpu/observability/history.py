"""Bounded in-memory metrics history: the ring TSDB under every page.

Everything the registry exports is a point-in-time snapshot; a page
arrives *after* the interesting part. `MetricsHistory` subscribes to
the `FederatedScraper` sweep stream (or samples the local registry on
its own thread when no scraper runs) and keeps the recent trajectory of
every numeric series in memory, in three tiers:

* ``raw``  — every sweep sample, per-series ring
  (``PDTPU_HISTORY_POINTS``, default 512 points);
* ``mid``  — 10 s buckets of (mean, min, max, count), ~1 h;
* ``long`` — 120 s buckets, ~24 h.

so a 1 Hz scrape keeps full resolution for the last ~8 minutes and a
degrading-but-honest summary for a day — the window a post-mortem
actually reads. Series identity is ``(name, labels, field)``: counters
and gauges contribute a ``value`` field, histograms/summaries
contribute ``p50``/``p99``/``count`` (the fields the SLO engine and the
ops console key on — storing all seven summary fields triples memory
for columns nobody queries).

Memory is bounded twice: per-series rings have fixed maxlen, and the
whole store is capped at ``PDTPU_HISTORY_MAX_MB`` (default 8) /
``PDTPU_HISTORY_MAX_SERIES`` (default 2048) with LRU series eviction —
a label-cardinality explosion evicts the series nobody touched rather
than growing without bound. The cap is enforced against a conservative
per-point byte estimate (``history/est_bytes`` gauge; the tracemalloc
test holds the real footprint under the same cap).

Set ``PDTPU_HISTORY_DIR`` to additionally spill one compact JSONL line
per sweep into size-capped rotating segments
(``PDTPU_HISTORY_SEGMENT_MB``, default 16; ``PDTPU_HISTORY_MAX_SEGMENTS``,
default 8, oldest deleted) so the lead-up to a crash survives process
death. `tools/metrics_lint.py --history DIR` lints the segments;
`tools/postmortem.py` bundles them.

Query via `MetricsHistory.query()` or the ``/history`` HTTP endpoint
(`observability/http.py`): series-prefix filter + time window +
tier + max_points.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Callable, List, Optional, Tuple

from .registry import Registry, get_registry

__all__ = ["MetricsHistory", "install_history", "get_history"]

Registry.describe("history/points", "raw points currently held")
Registry.describe("history/series", "distinct series currently held")
Registry.describe("history/est_bytes",
                  "estimated history memory footprint")
Registry.describe("history/evicted_series",
                  "series dropped by the LRU memory cap")
Registry.describe("history/sweeps", "sweeps recorded into history")
Registry.describe("history/segments_rotated",
                  "JSONL spill segments rotated out")

# conservative CPython cost estimates the memory cap is enforced with:
# a raw point is a (float, float) tuple in a deque slot; an aggregate
# point is a 5-float tuple. Real footprints measure smaller.
_RAW_POINT_BYTES = 120
_AGG_POINT_BYTES = 176
_SERIES_OVERHEAD_BYTES = 1024

# summary fields worth a timeline (see module docstring)
_SUMMARY_FIELDS = ("p50", "p99", "count")

_TIERS = {"raw": 0, "mid": 1, "long": 2}


class _Tier:
    """One downsampling tier: fixed-width time buckets folded into
    (bucket_t, mean, min, max, count) tuples in a bounded ring."""

    __slots__ = ("width", "ring", "_open")

    def __init__(self, width_s: float, maxlen: int):
        self.width = float(width_s)
        self.ring: collections.deque = collections.deque(maxlen=maxlen)
        self._open: Optional[list] = None  # [t, sum, min, max, count]

    def add(self, t: float, v: float) -> None:
        bt = t - (t % self.width)
        o = self._open
        if o is not None and o[0] == bt:
            o[1] += v
            o[2] = min(o[2], v)
            o[3] = max(o[3], v)
            o[4] += 1
            return
        if o is not None:
            self.ring.append((o[0], o[1] / o[4], o[2], o[3], o[4]))
        self._open = [bt, v, v, v, 1]

    def points(self) -> list:
        out = [[t, round(mean, 6), mn, mx, n]
               for t, mean, mn, mx, n in self.ring]
        o = self._open
        if o is not None:
            out.append([o[0], round(o[1] / o[4], 6), o[2], o[3], o[4]])
        return out

    def __len__(self) -> int:
        return len(self.ring) + (1 if self._open is not None else 0)


class _Series:
    __slots__ = ("raw", "mid", "long")

    def __init__(self, raw_points: int, mid_points: int, long_points: int):
        self.raw: collections.deque = collections.deque(maxlen=raw_points)
        self.mid = _Tier(10.0, mid_points)
        self.long = _Tier(120.0, long_points)

    def add(self, t: float, v: float) -> None:
        self.raw.append((t, v))
        self.mid.add(t, v)
        self.long.add(t, v)

    def est_bytes(self) -> int:
        return (_SERIES_OVERHEAD_BYTES
                + len(self.raw) * _RAW_POINT_BYTES
                + (len(self.mid) + len(self.long)) * _AGG_POINT_BYTES)


def _label_key(labels: Optional[dict]) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class MetricsHistory:
    """The bounded ring TSDB. `observe_sweep(doc)` records one
    `FederatedScraper` sweep; `attach(scraper)` subscribes; `query()`
    reads a window back out. Thread-safe throughout."""

    def __init__(self, raw_points: Optional[int] = None,
                 max_mb: Optional[float] = None,
                 max_series: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 registry: Optional[Registry] = None):
        env = os.environ
        if raw_points is None:
            raw_points = int(env.get("PDTPU_HISTORY_POINTS", "512"))
        if max_mb is None:
            max_mb = float(env.get("PDTPU_HISTORY_MAX_MB", "8"))
        if max_series is None:
            max_series = int(env.get("PDTPU_HISTORY_MAX_SERIES", "2048"))
        if spill_dir is None:
            spill_dir = env.get("PDTPU_HISTORY_DIR") or None
        self.raw_points = max(8, int(raw_points))
        self.max_bytes = int(max_mb * 1024 * 1024)
        self.max_series = max(16, int(max_series))
        self.mid_points = 360   # 10 s * 360 = 1 h
        self.long_points = 720  # 120 s * 720 = 24 h
        self._reg = registry if registry is not None else get_registry()
        self._lock = threading.Lock()
        # LRU on write: oldest-written series evicted first under the cap
        self._series: "collections.OrderedDict[tuple, _Series]" = \
            collections.OrderedDict()
        self._est_bytes = 0
        self._sweeps = 0
        self._started: Optional[float] = None
        # local-sampler thread state (used when no scraper runs)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # JSONL spill
        self.spill_dir = spill_dir
        self.segment_bytes = int(float(
            env.get("PDTPU_HISTORY_SEGMENT_MB", "16")) * 1024 * 1024)
        self.max_segments = max(1, int(
            env.get("PDTPU_HISTORY_MAX_SEGMENTS", "8")))
        self._spill_fh = None
        self._spill_path: Optional[str] = None
        self._spill_seq = 0

    # ------------------------------------------------------------ recording
    def attach(self, scraper) -> "MetricsHistory":
        """Subscribe to a `FederatedScraper`'s sweep stream."""
        scraper.add_sweep_listener(self.observe_sweep)
        return self

    def observe_sweep(self, doc: dict) -> None:
        """Record one sweep document (`FederatedScraper.scrape_once`
        shape). Each target's series land with the target's
        process/role(/shard) labels merged in, so fleet-wide history
        keys match fleet-wide exposition."""
        t = doc.get("t")
        if not isinstance(t, (int, float)):
            t = time.time()
        flat: List[tuple] = []
        for r in doc.get("targets", ()):
            if not r.get("ok"):
                continue
            extra = {"process": r.get("process"), "role": r.get("role")}
            if r.get("shard") is not None:
                extra["shard"] = str(r["shard"])
            for s in r.get("series", ()):
                self._flatten(s, extra, flat)
        self._record(t, flat)

    def observe_local(self, now: Optional[float] = None) -> None:
        """Record one snapshot of the local registry (scraper-less
        processes: a single-host trainer, a test)."""
        t = time.time() if now is None else float(now)
        flat: List[tuple] = []
        for s in self._reg.series(deep=True):
            self._flatten(s, None, flat)
        self._record(t, flat)

    @staticmethod
    def _flatten(s: dict, extra: Optional[dict], out: List[tuple]) -> None:
        name = s.get("name")
        if not name:
            return
        # scrape-source labels are DEFAULTS: a series' own process/role
        # label (e.g. autoscale/queue_depth{process=...}) must win over
        # the label of the target it was scraped through
        labels = {k: v for k, v in (extra or {}).items() if v is not None}
        labels.update(s.get("labels") or {})
        lk = _label_key(labels)
        if s.get("type") == "summary":
            summ = s.get("summary") or {}
            for f in _SUMMARY_FIELDS:
                v = summ.get(f)
                if isinstance(v, (int, float)):
                    out.append(((name, lk, f), float(v)))
        else:
            v = s.get("value")
            if isinstance(v, (int, float)):
                out.append(((name, lk, "value"), float(v)))

    def _record(self, t: float, flat: List[tuple]) -> None:
        evicted = 0
        with self._lock:
            if self._started is None:
                self._started = t
            self._sweeps += 1
            for key, v in flat:
                ser = self._series.get(key)
                if ser is None:
                    ser = _Series(self.raw_points, self.mid_points,
                                  self.long_points)
                    self._series[key] = ser
                else:
                    self._est_bytes -= ser.est_bytes()
                    self._series.move_to_end(key)
                ser.add(t, v)
                self._est_bytes += ser.est_bytes()
            while (len(self._series) > self.max_series
                   or self._est_bytes > self.max_bytes):
                if len(self._series) <= 1:
                    break
                _, old = self._series.popitem(last=False)
                self._est_bytes -= old.est_bytes()
                evicted += 1
            est = self._est_bytes
            nser = len(self._series)
            npts = sum(len(s.raw) for s in self._series.values())
        reg = self._reg
        reg.counter("history/sweeps").inc()
        reg.gauge("history/series").set(nser)
        reg.gauge("history/points").set(npts)
        reg.gauge("history/est_bytes").set(est)
        if evicted:
            reg.counter("history/evicted_series").inc(evicted)
        if self.spill_dir:
            self._spill(t, flat)

    # ---------------------------------------------------------- JSONL spill
    def _spill(self, t: float, flat: List[tuple]) -> None:
        """One compact JSONL line per sweep; rotate segments by size,
        delete oldest past `max_segments`. Spill failures are swallowed:
        history must survive a full disk."""
        try:
            line = json.dumps({
                "t": round(t, 3),
                "series": [{"name": k[0], "labels": dict(k[1]),
                            "field": k[2], "v": v} for k, v in flat],
            }, separators=(",", ":"))
            with self._lock:
                fh = self._ensure_segment(len(line) + 1)
                fh.write(line + "\n")
                fh.flush()
        except Exception:
            pass

    def _ensure_segment(self, nbytes: int):
        """Open/rotate the active segment (caller holds the lock)."""
        if (self._spill_fh is not None
                and self._spill_fh.tell() + nbytes <= self.segment_bytes):
            return self._spill_fh
        if self._spill_fh is not None:
            self._spill_fh.close()
            self._spill_fh = None
            self._reg.counter("history/segments_rotated").inc()
        os.makedirs(self.spill_dir, exist_ok=True)
        self._spill_seq += 1
        self._spill_path = os.path.join(
            self.spill_dir,
            f"history_{os.getpid()}_{self._spill_seq:05d}.jsonl")
        self._spill_fh = open(self._spill_path, "a")
        self._prune_segments()
        return self._spill_fh

    def _prune_segments(self) -> None:
        segs = sorted(
            f for f in os.listdir(self.spill_dir)
            if f.startswith("history_") and f.endswith(".jsonl"))
        for f in segs[:-self.max_segments]:
            try:
                os.unlink(os.path.join(self.spill_dir, f))
            except OSError:
                pass

    # ---------------------------------------------------- local sampler
    def start_local(self, interval_s: float = 1.0) -> "MetricsHistory":
        """Sample the local registry at `interval_s` on a daemon thread
        — the scraper-less deployment's sweep source (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.is_set():
                try:
                    self.observe_local()
                except Exception:
                    pass
                self._stop.wait(interval_s)

        self._thread = threading.Thread(target=_loop,
                                        name="metrics-history",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with self._lock:
            if self._spill_fh is not None:
                self._spill_fh.close()
                self._spill_fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -------------------------------------------------------------- reading
    def query(self, prefix: str = "", start: Optional[float] = None,
              end: Optional[float] = None, tier: str = "raw",
              max_points: int = 512) -> List[dict]:
        """Windowed read. Returns a list of
        ``{"name", "labels", "field", "tier", "points"}`` dicts —
        raw points are ``[t, v]`` pairs; mid/long points are
        ``[bucket_t, mean, min, max, count]``. `max_points` keeps the
        newest points of each series. Copies under the lock: readers
        never see a ring mid-append."""
        if tier not in _TIERS:
            raise ValueError(f"unknown tier {tier!r}; "
                             f"one of {sorted(_TIERS)}")
        mp = max(1, int(max_points))
        out: List[dict] = []
        with self._lock:
            for (name, lk, field), ser in self._series.items():
                if prefix and not name.startswith(prefix):
                    continue
                if tier == "raw":
                    pts = [[t, v] for t, v in ser.raw]
                elif tier == "mid":
                    pts = ser.mid.points()
                else:
                    pts = ser.long.points()
                if start is not None:
                    pts = [p for p in pts if p[0] >= start]
                if end is not None:
                    pts = [p for p in pts if p[0] <= end]
                if not pts:
                    continue
                out.append({"name": name, "labels": dict(lk),
                            "field": field, "tier": tier,
                            "points": pts[-mp:]})
        out.sort(key=lambda s: (s["name"], sorted(s["labels"].items()),
                                s["field"]))
        return out

    def window(self, center: float, half_width_s: float = 30.0,
               prefix: str = "", max_points: int = 256) -> dict:
        """The post-mortem cut: every series around a moment in time.
        Attached to alert events by the ProfileTrigger."""
        return {
            "center_t": round(center, 3),
            "half_width_s": half_width_s,
            "series": self.query(prefix=prefix,
                                 start=center - half_width_s,
                                 end=center + half_width_s,
                                 max_points=max_points),
        }

    def stats(self) -> dict:
        with self._lock:
            return {
                "series": len(self._series),
                "raw_points": sum(len(s.raw)
                                  for s in self._series.values()),
                "est_bytes": self._est_bytes,
                "max_bytes": self.max_bytes,
                "max_series": self.max_series,
                "sweeps": self._sweeps,
                "started_t": self._started,
                "spill_dir": self.spill_dir,
                "spill_path": self._spill_path,
            }


# the history the introspection server's /history endpoint answers from
_installed: Optional[MetricsHistory] = None
_install_lock = threading.Lock()


def install_history(history: Optional[MetricsHistory]):
    """Make `history` the one ``/history`` answers from (None
    uninstalls). Returns the history."""
    global _installed
    with _install_lock:
        _installed = history
    return history


def get_history() -> Optional[MetricsHistory]:
    with _install_lock:
        return _installed
