"""HTTP introspection plane: /metrics, /healthz, /debug endpoints.

A production serving or training process needs a live scrape surface —
the reference framework deployments exported QPS/latency via external
RPC metrics and were probed by the fleet's health checker. This module
is the stdlib-only equivalent: a daemon-threaded `ThreadingHTTPServer`
(no new dependencies) exposing the process registry, step records, and
flight-recorder contents:

    GET /metrics         Prometheus text exposition (Registry.prometheus_text)
    GET /metrics.json    deep registry snapshot as JSON
    GET /metrics/series  structured series list (Registry.series) — what
                         the federation scraper consumes
    GET /fleet           one federated scrape of every process (requires
                         an installed federate.FederatedScraper; 404
                         otherwise, 503 when any target is unreachable)
    GET /alerts          live alert states (requires an installed
                         alerts.AlertManager; 404 otherwise)
    GET /history         windowed metrics history (requires an installed
                         history.MetricsHistory; 404 otherwise) —
                         ?prefix= series-name prefix, ?start=/?end= unix
                         seconds, ?window=SECS (newest window shortcut),
                         ?tier=raw|mid|long, ?max_points=N
    GET /healthz         named health checks, ok/degraded/failing
                         aggregation (200 for ok/degraded, 503 for failing)
    GET /debug/steps     recent StepProfiler records (?n=50 to limit)
    GET /debug/flight    flight-recorder ring + last post-mortem dump

Start explicitly with ``obs.serve_introspection(port)`` (0 = ephemeral)
or implicitly by setting ``PDTPU_INTROSPECT_PORT`` — the Executor and
InferenceServer both call `maybe_serve_from_env()` at construction, so
exporting the variable is all a deployment needs. The server is
process-wide and idempotent: repeat calls return the running instance.

Health checks are pluggable: ``register_health_check(name, fn)`` where
``fn() -> "ok" | (status, detail)``; the serving tier registers queue
depth / deadline-miss / worker-liveness checks, which is what makes
`InferenceServer` directly usable behind k8s liveness/readiness probes
(see docs/migration.md "Production monitoring").
"""
from __future__ import annotations

import http.server
import json
import logging
import os
import threading
import urllib.parse
from typing import Callable, Dict, Optional, Tuple

from . import federate
from .flight import get_flight_recorder
from .registry import get_registry
from .steps import get_step_profiler

__all__ = ["IntrospectionServer", "serve_introspection",
           "stop_introspection", "maybe_serve_from_env",
           "register_health_check", "unregister_health_check",
           "run_health_checks"]

logger = logging.getLogger("paddle_tpu.observability.http")

_STATUS_ORDER = {"ok": 0, "degraded": 1, "failing": 2}

_health_lock = threading.Lock()
_health_checks: Dict[str, Callable] = {}


def register_health_check(name: str, fn: Callable) -> None:
    """Add a named check to /healthz. `fn` returns ``"ok"`` /
    ``"degraded"`` / ``"failing"`` or a ``(status, detail)`` tuple; a
    raising check reports as failing with the error as detail."""
    with _health_lock:
        _health_checks[name] = fn


def unregister_health_check(name: str) -> None:
    with _health_lock:
        _health_checks.pop(name, None)


def run_health_checks() -> Tuple[str, dict]:
    """(overall, {name: {"status", "detail"}}). Aggregation: failing >
    degraded > ok; no registered checks means ok (process is up and
    answering)."""
    with _health_lock:
        checks = list(_health_checks.items())
    overall = "ok"
    detail: dict = {}
    for name, fn in checks:
        try:
            res = fn()
            if isinstance(res, tuple):
                status, info = res[0], (res[1] if len(res) > 1 else "")
            else:
                status, info = str(res), ""
            if status not in _STATUS_ORDER:
                status, info = "failing", f"bad check result {res!r}"
        except Exception as e:
            status, info = "failing", f"{type(e).__name__}: {e}"
        detail[name] = {"status": status, "detail": str(info)}
        if _STATUS_ORDER[status] > _STATUS_ORDER[overall]:
            overall = status
    return overall, detail


class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = "pdtpu-introspect/1"

    def log_message(self, fmt, *args):  # route away from stderr
        logger.debug("%s %s", self.address_string(), fmt % args)

    def _send(self, code: int, body, ctype: str) -> None:
        data = body if isinstance(body, bytes) else body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code: int, obj) -> None:
        self._send(code, json.dumps(obj, indent=2, default=str),
                   "application/json")

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
        parsed = urllib.parse.urlparse(self.path)
        path = parsed.path.rstrip("/") or "/"
        try:
            if path == "/metrics":
                text = get_registry().prometheus_text(deep=True)
                scraper = federate.get_scraper()
                if scraper is not None and scraper.last() is not None:
                    # coordinator /metrics carries the fleet too — each
                    # federated series is distinct via its process label
                    text += scraper.prometheus_text()
                self._send(200, text,
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/metrics.json":
                self._send_json(200, get_registry().snapshot(deep=True))
            elif path == "/metrics/series":
                self._send_json(200, get_registry().series(deep=True))
            elif path == "/fleet":
                scraper = federate.get_scraper()
                if scraper is None:
                    self._send(404, "no FederatedScraper installed "
                                    "(observability.federate."
                                    "install_scraper)\n", "text/plain")
                else:
                    doc = scraper.scrape_once()
                    self._send_json(200 if doc["ok"] else 503, doc)
            elif path == "/alerts":
                from . import alerts  # deferred: alerts imports us
                mgr = alerts.get_alert_manager()
                if mgr is None:
                    self._send(404, "no AlertManager installed "
                                    "(observability.alerts."
                                    "install_alert_manager)\n",
                               "text/plain")
                else:
                    self._send_json(200, mgr.doc())
            elif path == "/history":
                from . import history as history_mod  # deferred import
                hist = history_mod.get_history()
                if hist is None:
                    self._send(404, "no MetricsHistory installed "
                                    "(observability.history."
                                    "install_history)\n", "text/plain")
                else:
                    qs = urllib.parse.parse_qs(parsed.query)

                    def _qf(key):
                        try:
                            return float(qs[key][0])
                        except (KeyError, ValueError, IndexError):
                            return None

                    start, end = _qf("start"), _qf("end")
                    window = _qf("window")
                    if window is not None and start is None:
                        import time as _time
                        start = _time.time() - window
                    tier = (qs.get("tier", ["raw"])[0] or "raw")
                    mp = _qf("max_points")
                    try:
                        series = hist.query(
                            prefix=qs.get("prefix", [""])[0],
                            start=start, end=end, tier=tier,
                            max_points=int(mp) if mp else 512)
                    except ValueError as ve:
                        self._send(400, f"{ve}\n", "text/plain")
                    else:
                        self._send_json(200, {"stats": hist.stats(),
                                              "series": series})
            elif path == "/healthz":
                overall, detail = run_health_checks()
                code = 200 if overall in ("ok", "degraded") else 503
                self._send_json(code, {"status": overall, "checks": detail})
            elif path == "/debug/steps":
                qs = urllib.parse.parse_qs(parsed.query)
                n = None
                if qs.get("n"):
                    try:
                        n = int(qs["n"][0])
                    except ValueError:
                        n = None
                self._send_json(
                    200, {"records": get_step_profiler().records(n)})
            elif path == "/debug/flight":
                self._send_json(200, get_flight_recorder().contents())
            elif path == "/":
                self._send(200, "paddle_tpu introspection: /metrics "
                                "/metrics.json /metrics/series /fleet "
                                "/alerts /history /healthz /debug/steps "
                                "/debug/flight\n", "text/plain")
            else:
                self._send(404, f"no such endpoint: {path}\n", "text/plain")
        except Exception as e:  # endpoint bug must not kill the server
            logger.warning("introspection handler error on %s: %s",
                           path, e)
            try:
                self._send(500, f"{type(e).__name__}: {e}\n", "text/plain")
            except Exception:
                pass


class IntrospectionServer:
    """One ThreadingHTTPServer on a daemon thread; ``port=0`` binds an
    ephemeral port (read it back from ``.port`` after start)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._host = host
        self._requested_port = int(port)
        self._server: Optional[http.server.ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "IntrospectionServer":
        if self._server is not None:
            return self
        srv = http.server.ThreadingHTTPServer(
            (self._host, self._requested_port), _Handler)
        srv.daemon_threads = True
        self._server = srv
        self._thread = threading.Thread(
            target=srv.serve_forever, name="pdtpu-introspect", daemon=True)
        self._thread.start()
        logger.info("introspection server listening on http://%s:%d",
                    self._host, self.port)
        return self

    @property
    def running(self) -> bool:
        return self._server is not None

    @property
    def port(self) -> int:
        if self._server is None:
            return self._requested_port
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def stop(self) -> None:
        srv, self._server = self._server, None
        thread, self._thread = self._thread, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        if thread is not None:
            thread.join(timeout=5)


_server_lock = threading.Lock()
_server: Optional[IntrospectionServer] = None


def serve_introspection(port: Optional[int] = None,
                        host: str = "127.0.0.1") -> IntrospectionServer:
    """Start (or return) the process-wide introspection server.
    ``port=None`` falls back to ``PDTPU_INTROSPECT_PORT``, then 0
    (ephemeral). Idempotent: a second call returns the running server
    regardless of the requested port."""
    global _server
    with _server_lock:
        if _server is not None and _server.running:
            return _server
        if port is None:
            port = int(os.environ.get("PDTPU_INTROSPECT_PORT", "0"))
        _server = IntrospectionServer(port=port, host=host).start()
        return _server


def stop_introspection() -> None:
    """Shut the process-wide server down (tests / clean exit)."""
    global _server
    with _server_lock:
        srv, _server = _server, None
    if srv is not None:
        srv.stop()


def maybe_serve_from_env() -> Optional[IntrospectionServer]:
    """Start the server iff ``PDTPU_INTROSPECT_PORT`` is set — called by
    `Executor.__init__` and `InferenceServer.start()` so a deployment
    only needs the env var. No-op (returns None) when unset."""
    port = os.environ.get("PDTPU_INTROSPECT_PORT")
    if not port:
        return None
    try:
        return serve_introspection(int(port))
    except (ValueError, OSError) as e:
        logger.warning("PDTPU_INTROSPECT_PORT=%r: cannot start "
                       "introspection server: %s", port, e)
        return None
