"""Device-memory gauges: what ZeRO sharding actually buys.

Two gauges, sampled by the executor after each compiling dispatch (once
per executable signature — cheap, and that is exactly when layouts can
have changed):

- ``memory/state_bytes_per_device`` — bytes of model state (parameters,
  optimizer accumulators, master weights) resident on ONE device: each
  leaf contributes its per-device shard size, so a replicated leaf counts
  in full and a dp-sharded leaf counts ~1/dp. This is the number
  ``ShardingStrategy.stage1/stage2`` shrinks.
- ``memory/hbm_bytes_in_use`` — the allocator's ``bytes_in_use`` for the
  first local device. TPU/GPU backends report it; CPU's allocator has no
  stats, so the gauge is simply absent there.

Reference analog: the reference framework surfaced allocator occupancy
through ``memory_optimize`` logs and gperf tooling; here it is a registry
gauge next to the executor counters.
"""
from __future__ import annotations

from typing import Iterable, Optional

from .registry import get_registry

__all__ = [
    "device_memory_stats",
    "per_device_state_bytes",
    "record_state_memory",
]


def device_memory_stats(device=None) -> Optional[dict]:
    """`memory_stats()` of `device` (default: first local device), or None
    when the backend exposes no allocator stats (CPU)."""
    import jax

    try:
        device = device or jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    return dict(stats) if stats else None


def _leaf_bytes_on_device(v) -> int:
    """Bytes `v` occupies on the first device that holds a shard of it."""
    shards = getattr(v, "addressable_shards", None)
    if not shards:
        return int(getattr(v, "nbytes", 0))
    first = min(shards,
                key=lambda s: getattr(getattr(s, "device", None), "id", 0))
    return int(getattr(first.data, "nbytes", 0))


def per_device_state_bytes(leaves: Iterable) -> int:
    """Sum of per-device shard bytes across `leaves` — the one-device
    footprint of the model state under its current shardings."""
    return sum(_leaf_bytes_on_device(v) for v in leaves)


def record_state_memory(leaves: Optional[Iterable] = None,
                        device=None) -> dict:
    """Set the memory gauges; returns what was recorded. Never raises —
    sampling must not take down a training dispatch."""
    reg = get_registry()
    out = {}
    if leaves is not None:
        try:
            b = per_device_state_bytes(leaves)
        except Exception:
            b = None
        if b is not None:
            reg.gauge("memory/state_bytes_per_device").set(b)
            out["state_bytes_per_device"] = b
    stats = device_memory_stats(device)
    if stats and stats.get("bytes_in_use") is not None:
        reg.gauge("memory/hbm_bytes_in_use").set(int(stats["bytes_in_use"]))
        out["hbm_bytes_in_use"] = int(stats["bytes_in_use"])
    return out
