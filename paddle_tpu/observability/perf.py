"""Perf-attribution ledger: per-(program, signature) cost accounting.

The reference framework's platform layer made performance a first-class
runtime surface (profiler.h per-op timers, sorted kernel summaries);
this reproduction had the equivalent knowledge scattered across five
hand-rolled roofline calculations inside bench.py, so the *runtime*
could never say how close a compiled program runs to the hardware.

This module closes that gap. At compile time the dispatch sites
(`Executor.run`, `Executor.run_batched`/`train_scanned`,
`CompiledProgram._run`) register what one dispatch of the executable
costs, in extraction-preference order:

1. **XLA's own numbers** — ``cost_analysis()`` (flops, bytes accessed,
   transcendentals) and ``memory_analysis()`` (per-device
   arg+temp+output−alias bytes) from the AOT ``Compiled`` object where
   one exists (the `_AutoLayoutStep` fast path), or from a trace-only
   ``Lowered`` for the lazy-jit paths (``source="xla"`` /
   ``"lowered"``).
2. **Analytic fallback** — for backends that return nothing: matmul /
   conv flops walked from the Program IR (×3 when the program carries a
   backward pass) plus a state/feed byte count (``source="analytic"``).

At dispatch time `StepProfiler.record` joins each wall time with the
ledger entry and the shared chip floors from
:mod:`~paddle_tpu.observability.calibrate`, emitting live per-program
gauges into the process registry — visible on ``/metrics``,
``/metrics.json``, flight dumps, and federation like every other
series:

- ``perf/achieved_tflops{program,sig}``
- ``perf/achieved_gbs{program,sig}``
- ``perf/mfu{program,sig}``         (vs the chip's peak flops)
- ``perf/roofline_fraction{program,sig}`` (vs max(matmul, stream) floor)

Caveats the numbers inherit from XLA's cost model: ``bytes accessed``
counts VMEM-staged re-reads, so achieved GB/s (and hence the roofline
fraction of a memory-bound program) can legitimately exceed the
measured stream floor; ``flops`` is model flops, not MXU-padded flops.
See docs/migration.md "Performance attribution".

``PDTPU_PERF_LEDGER=0`` disables registration and dispatch-time
attribution entirely; ``PDTPU_PERF_TRACE_COST=0`` skips the trace-only
``Lowered`` extraction on the lazy-jit paths (the one path whose
extraction is not free — it re-traces the step function once per
compile).
"""
from __future__ import annotations

import collections
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from . import calibrate
from .registry import get_registry

__all__ = ["ProgramCost", "CostLedger", "get_ledger", "attribute",
           "cost_from_executable", "analytic_cost", "enabled"]

_MAX_ENTRIES = 256


def enabled() -> bool:
    return os.environ.get("PDTPU_PERF_LEDGER", "1") != "0"


def trace_cost_enabled() -> bool:
    return enabled() and os.environ.get("PDTPU_PERF_TRACE_COST", "1") != "0"


@dataclass
class ProgramCost:
    """What ONE dispatch of an executable costs. For scan dispatches
    (`steps` > 1) the numbers cover the whole K-step scan."""

    flops: float = 0.0
    bytes_accessed: float = 0.0
    transcendentals: float = 0.0
    peak_bytes: Optional[int] = None   # per-device arg+temp+out−alias
    source: str = "none"               # "xla" | "lowered" | "analytic"
    steps: int = 1
    label: Optional[str] = None
    last: Dict[str, float] = field(default_factory=dict)  # last attribution

    def to_dict(self) -> dict:
        d = {"flops": self.flops, "bytes_accessed": self.bytes_accessed,
             "transcendentals": self.transcendentals,
             "peak_bytes": self.peak_bytes, "source": self.source,
             "steps": self.steps}
        if self.label:
            d["label"] = self.label
        if self.last:
            d["last"] = dict(self.last)
        return d


# -- extraction --------------------------------------------------------------

def cost_from_executable(executable) -> Optional[dict]:
    """flops / bytes_accessed / transcendentals from an XLA ``Compiled``
    or ``Lowered`` object, or None when the backend returns nothing
    (TPU PJRT raises Unimplemented on some versions; older jax returns a
    list of per-partition dicts)."""
    if executable is None:
        return None
    try:
        ca = executable.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {"flops": float(ca.get("flops", 0.0) or 0.0),
           "bytes_accessed": float(ca.get("bytes accessed", 0.0) or 0.0),
           "transcendentals": float(ca.get("transcendentals", 0.0) or 0.0)}
    if out["flops"] <= 0.0 and out["bytes_accessed"] <= 0.0:
        return None
    return out


def memory_from_executable(executable) -> Optional[int]:
    """Per-device live-byte estimate from ``memory_analysis()``
    (arg+temp+output−alias, the planner's formula), or None."""
    try:
        ma = executable.memory_analysis()
        est = (int(ma.argument_size_in_bytes) + int(ma.temp_size_in_bytes)
               + int(ma.output_size_in_bytes) - int(ma.alias_size_in_bytes))
        return max(est, 0)
    except Exception:
        return None


def _var_nbytes(v, batch: Optional[int]) -> int:
    import jax
    import numpy as np

    if v.shape is None:
        return 0
    shp = [int(d) if int(d) > 0 else int(batch or 1) for d in v.shape]
    try:
        itemsize = jax.dtypes.canonicalize_dtype(v.dtype).itemsize
    except Exception:
        itemsize = 4
    return int(np.prod(shp)) * int(itemsize) if shp else int(itemsize)


def analytic_cost(program, feed: Optional[Dict[str, Any]] = None) -> dict:
    """Analytic cost of one dispatch from the Program IR, for backends
    whose cost model returns nothing.

    flops: 2mnk per matmul/mul, 2·out·k²·cin per conv2d (forward),
    tripled when the program carries a backward pass (any `*_grad` op or
    `@GRAD` output). bytes: feeds + persistables (params read fwd+bwd
    and written by the update when training) + one write per op output
    whose shape is known. A deliberate lower bound — activations that
    XLA rematerializes or stages through VMEM are not modeled — and the
    entry says ``analytic`` so consumers can weigh it accordingly.
    """
    import numpy as np

    batch = None
    for a in (feed or {}).values():
        shp = getattr(a, "shape", None)
        if shp:
            batch = int(shp[0])
            break

    blk = program.global_block()

    def shape_of(name):
        v = blk._find_var_recursive(name)
        if v is None or v.shape is None:
            return None
        return [int(d) if int(d) > 0 else int(batch or 1) for d in v.shape]

    fwd_flops = 0.0
    out_bytes = 0.0
    has_bwd = False
    for b in program.blocks:
        for op in b.ops:
            t = op.type
            if t.endswith("_grad"):
                has_bwd = True
            if t in ("mul", "matmul", "matmul_v2"):
                xs = op.input("X") or op.input_names()[:1]
                ys = op.input("Y") or op.input_names()[1:2]
                sx = shape_of(xs[0]) if xs else None
                sy = shape_of(ys[0]) if ys else None
                if sx and sy and len(sy) >= 2:
                    m = int(np.prod(sx[:-1]))
                    k = sx[-1]
                    n = sy[-1]
                    fwd_flops += 2.0 * m * k * n
            elif t == "conv2d":
                outs = op.output("Output") or op.output_names()[:1]
                fils = op.input("Filter") or []
                so = shape_of(outs[0]) if outs else None
                sf = shape_of(fils[0]) if fils else None
                if so and sf and len(sf) == 4:
                    # filter [cout, cin, kh, kw]; out [b, cout, oh, ow]
                    fwd_flops += (2.0 * np.prod(so)
                                  * sf[1] * sf[2] * sf[3])
            for name in op.output_names():
                s = shape_of(name)
                if s:
                    v = blk._find_var_recursive(name)
                    out_bytes += _var_nbytes(v, batch) if v is not None \
                        else 0
            if any(n.endswith("@GRAD") for n in op.output_names()):
                has_bwd = True

    state_bytes = sum(_var_nbytes(v, batch) for v in program.list_vars()
                      if v.persistable)
    feed_bytes = sum(int(getattr(a, "nbytes", 0) or 0)
                     for a in (feed or {}).values())
    mult = 3.0 if has_bwd else 1.0
    # params: read fwd (+ read bwd + update write when training)
    bytes_accessed = (feed_bytes + state_bytes * (3.0 if has_bwd else 1.0)
                      + out_bytes)
    return {"flops": fwd_flops * mult, "bytes_accessed": bytes_accessed,
            "transcendentals": 0.0}


# -- attribution -------------------------------------------------------------

def attribute(*, flops: float = 0.0, bytes_accessed: float = 0.0,
              seconds: float, calib: Optional[calibrate.Calibration] = None
              ) -> dict:
    """Join a cost with a wall time against the calibrated chip floors.

    Returns achieved_tflops / achieved_gbs / mfu / roofline_fraction /
    bound. roofline_fraction is floor_time/actual_time where the floor
    is max(flops at the measured matmul rate, bytes at the measured
    stream rate); it is NOT capped at 1.0 here — XLA's bytes_accessed
    includes VMEM re-reads, so honest fractions can exceed unity (cap at
    presentation time if a bounded number is wanted).
    """
    calib = calib or calibrate.get_calibration()
    seconds = max(float(seconds), 1e-12)
    tfs = flops / seconds / 1e12
    gbs = bytes_accessed / seconds / 1e9
    mm_s = flops / (calib.matmul_tflops * 1e12)
    st_s = bytes_accessed / (calib.stream_gbs * 1e9)
    floor_s = max(mm_s, st_s)
    return {
        "achieved_tflops": tfs,
        "achieved_gbs": gbs,
        "mfu": flops / seconds / calib.peak_flops,
        "roofline_fraction": floor_s / seconds,
        "bound": "matmul" if mm_s >= st_s else "memory",
    }


# -- the ledger --------------------------------------------------------------

def _pkey(program_id) -> str:
    if isinstance(program_id, str):
        return program_id
    return f"0x{program_id:x}"


class CostLedger:
    """Bounded map (program, sig) → :class:`ProgramCost`, with
    dispatch-time attribution into the registry."""

    def __init__(self, registry=None, max_entries: int = _MAX_ENTRIES):
        self._reg = registry
        self._max = int(max_entries)
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[tuple, ProgramCost]" = \
            collections.OrderedDict()
        self._pass_reports: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self._dump_registered = False
        self._pass_dump_registered = False

    def _registry(self):
        return self._reg if self._reg is not None else get_registry()

    # -- registration (compile time) ------------------------------------
    def register(self, program_id, sig: Optional[str], *,
                 executable=None, program=None,
                 feed: Optional[Dict[str, Any]] = None,
                 steps: int = 1, label: Optional[str] = None
                 ) -> Optional[ProgramCost]:
        """Record what one dispatch costs. Tries `executable`
        (``Compiled`` or ``Lowered``) first, then the analytic IR walk;
        registers nothing when both come up empty or the ledger is
        disabled. Never raises — a cost-model failure must not break a
        dispatch site."""
        if not enabled():
            return None
        try:
            cost = cost_from_executable(executable)
            if cost is not None:
                source = ("xla" if hasattr(executable, "memory_analysis")
                          else "lowered")
            elif program is not None:
                cost = analytic_cost(program, feed)
                source = "analytic"
                if steps > 1:
                    # analytic counts ONE step; a scan executable runs K
                    cost = {k: v * steps for k, v in cost.items()}
            else:
                return None
            if cost["flops"] <= 0.0 and cost["bytes_accessed"] <= 0.0:
                return None
            entry = ProgramCost(
                flops=cost["flops"], bytes_accessed=cost["bytes_accessed"],
                transcendentals=cost.get("transcendentals", 0.0),
                peak_bytes=memory_from_executable(executable),
                source=source, steps=int(steps), label=label)
            with self._lock:
                self._entries[(_pkey(program_id), sig)] = entry
                while len(self._entries) > self._max:
                    self._entries.popitem(last=False)
                if not self._dump_registered:
                    self._dump_registered = True
                    register_dump = None
                    try:
                        from .flight import register_dump_section
                        register_dump = register_dump_section
                    except Exception:
                        pass
                    if register_dump is not None:
                        register_dump("perf_ledger", self.snapshot)
            return entry
        except Exception:
            return None

    def get(self, program_id, sig: Optional[str]) -> Optional[ProgramCost]:
        with self._lock:
            return self._entries.get((_pkey(program_id), sig))

    # -- pass attribution (compile time, from ir.PassPipeline) -----------
    def record_passes(self, label: str, report: dict) -> None:
        """Record one PassPipeline run: the per-pass cost-delta report
        keyed by the program label, exported as ``ir/pass_*`` gauges and
        the ``ir_passes`` flight-dump section. Never raises."""
        if not enabled():
            return
        try:
            with self._lock:
                self._pass_reports[label] = report
                while len(self._pass_reports) > self._max:
                    self._pass_reports.popitem(last=False)
                if not self._pass_dump_registered:
                    self._pass_dump_registered = True
                    try:
                        from .flight import register_dump_section
                        register_dump_section("ir_passes", self.pass_reports)
                    except Exception:
                        pass
            reg = self._registry()
            for rec in report.get("passes", ()):
                labels = {"program": label, "ir_pass": rec["pass"]}
                reg.gauge("ir/pass_flops_delta", **labels).set(
                    rec.get("flops_delta", 0.0))
                reg.gauge("ir/pass_bytes_delta", **labels).set(
                    rec.get("bytes_delta", 0.0))
                reg.gauge("ir/pass_ops_removed", **labels).set(
                    rec.get("ops_before", 0) - rec.get("ops_after", 0))
        except Exception:
            pass

    def pass_reports(self) -> dict:
        """label → the PassPipeline report recorded for that program."""
        with self._lock:
            return {k: dict(v) for k, v in self._pass_reports.items()}

    # -- attribution (dispatch time) ------------------------------------
    def on_dispatch(self, program_id, sig: Optional[str], wall_ms: float
                    ) -> Optional[dict]:
        """Attribute one non-compile dispatch against its ledger entry;
        sets the live ``perf/*`` gauges and returns the attribution (or
        None when there is no entry)."""
        if not enabled():
            return None
        entry = self.get(program_id, sig)
        if entry is None or wall_ms <= 0.0:
            return None
        try:
            att = attribute(flops=entry.flops,
                            bytes_accessed=entry.bytes_accessed,
                            seconds=wall_ms / 1e3)
        except Exception:
            return None
        entry.last = {k: round(v, 6) for k, v in att.items()
                      if isinstance(v, float)}
        reg = self._registry()
        labels = {"program": _pkey(program_id)}
        if sig is not None:
            labels["sig"] = sig
        reg.gauge("perf/achieved_tflops", **labels).set(
            att["achieved_tflops"])
        reg.gauge("perf/achieved_gbs", **labels).set(att["achieved_gbs"])
        reg.gauge("perf/mfu", **labels).set(att["mfu"])
        reg.gauge("perf/roofline_fraction", **labels).set(
            att["roofline_fraction"])
        return att

    def annotate_record(self, rec: dict) -> None:
        """StepProfiler hook: join a step record with its ledger entry —
        non-compile records gain ``achieved_tflops`` (plus ``mfu`` when
        the entry has real flops) and the gauges update. Mutates `rec`
        in place; never raises."""
        if rec.get("compile") or "program" not in rec:
            return
        try:
            att = self.on_dispatch(rec["program"], rec.get("sig"),
                                   float(rec.get("wall_ms", 0.0)))
        except Exception:
            return
        if att is None:
            return
        rec["achieved_tflops"] = round(att["achieved_tflops"], 4)
        if att["mfu"] > 0.0:
            rec["mfu"] = round(att["mfu"], 4)

    # -- introspection ---------------------------------------------------
    def snapshot(self) -> dict:
        """Flight-dump / debug view: every entry with its last
        attribution."""
        with self._lock:
            return {f"{p} {s or ''}".strip(): e.to_dict()
                    for (p, s), e in self._entries.items()}

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self._pass_reports.clear()


_ledger = CostLedger()


def get_ledger() -> CostLedger:
    """THE process-wide cost ledger the dispatch sites register into."""
    return _ledger
