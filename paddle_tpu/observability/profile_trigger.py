"""Anomaly-triggered profiling: the capture half of the root-cause loop.

Detection (StepProfiler anomalies, burn-rate pages) and attribution
(`tools/roofline.py` kernel tables) existed as separate facilities; the
evidence that explains a page was only on disk if a human happened to
be running the profiler. `ProfileTrigger` closes that gap: it arms
``jax.profiler`` the moment a ``slow_step``/``recompile`` anomaly or a
page-severity alert appears, captures a bounded trace window (the next
few dispatches), tabulates it per-kernel, diffs against a recorded
*golden* trace, and hands the top movers + the surrounding metrics
history to the alert that is about to page — so the page arrives
already naming the culprit kernels, zero human-in-the-loop.

Safety rails (always-on profiling in production must be boring):

* kill switch — ``PDTPU_PROFILE_ON_ANOMALY=0`` disables arming
  entirely;
* cooldown — at most one capture per ``PDTPU_PROFILE_COOLDOWN_S``
  (default 60 s);
* rate cap — at most ``PDTPU_PROFILE_MAX_CAPTURES`` (default 12)
  captures per rolling hour;
* bounded window — the trace stops after ``window_steps`` further
  dispatches or ``window_s`` seconds, whichever comes first, so a
  stalled program cannot leave the profiler running.

Skipped arms are counted in ``profiler/skipped{reason=...}``; captures
in ``profiler/captures{trigger=...}``.

Golden traces are per-machine like `calibrate.py` floors: one JSON per
(device kind, host) under ``PDTPU_GOLDEN_DIR`` (default
``~/.cache/paddle_tpu/golden``), written by `record_golden()` (also a
CLI: ``python -m paddle_tpu.tools.roofline --save-golden``) during a
known-healthy run. Without a golden, attribution falls back to the
capture's own top-k kernels — still a named culprit, just without the
"vs healthy" delta.

The profiler backend is injectable (`profiler=` — anything with
``start(logdir)``/``stop()``) so the gating semantics are testable
without JAX tracing a single op.
"""
from __future__ import annotations

import collections
import json
import os
import re
import shutil
import socket
import tempfile
import threading
import time
from typing import Callable, List, Optional

from .registry import Registry, get_registry

__all__ = ["ProfileTrigger", "install_trigger", "get_trigger",
           "golden_path", "record_golden"]

Registry.describe("profiler/captures",
                  "anomaly-triggered trace captures, by trigger")
Registry.describe("profiler/skipped",
                  "arm requests skipped, by reason "
                  "(disabled/cooldown/cap/busy/start_failed)")
Registry.describe("profiler/capture_ms", "trace capture duration")
Registry.describe("profiler/golden_recorded",
                  "golden traces recorded to the disk cache")

# spans the python host tracer emits that can never be a device culprit
_HOST_SPAN_RE = re.compile(r"(^\$)|(\.py:\d+)|(^PjitFunction)"
                           r"|(^TfrtCpu)|(Execute)")

# pure runtime plumbing — never a culprit of EITHER kind. Distinct from
# _HOST_SPAN_RE: a host span from user/framework code (a data loader, a
# fault probe, a lock the trainer actually contends on) IS a legitimate
# root cause when the device kernels didn't move; threading internals,
# the profiler's own machinery, and per-dispatch runtime bookkeeping
# are not.
_NOISE_SPAN_RE = re.compile(
    r"threading\.py|profiler\.py|contextlib\.py|importlib|<unknown>"
    r"|<string>|^\$?tempfile\.py|^DevicePut$|^ParseArguments$"
    r"|^ThreadpoolListener|^PjitFunction|^TfrtCpu|Execute"
    # span names carry only file BASENAMES, so an __init__.py frame
    # names no package at all — uninformative as a culprit, and in
    # practice it is the stdlib logging machinery reacting to the
    # anomaly's own warning line inside every capture window
    r"|^\$?__init__\.py:\d+")


def _is_host_span(name: str) -> bool:
    return bool(_HOST_SPAN_RE.search(name))


def _is_noise_span(name: str) -> bool:
    return bool(_NOISE_SPAN_RE.search(name))


# ----------------------------------------------------------- golden store
def _golden_dir() -> str:
    return (os.environ.get("PDTPU_GOLDEN_DIR")
            or os.path.expanduser("~/.cache/paddle_tpu/golden"))


def golden_path(device_kind: Optional[str] = None,
                host: Optional[str] = None) -> str:
    """Golden-trace cache file for this (device kind, host) — keyed the
    same way as `calibrate.py` floors."""
    if device_kind is None:
        from .calibrate import _device_kind
        device_kind, _ = _device_kind()
    host = host or socket.gethostname()
    key = re.sub(r"[^A-Za-z0-9._-]", "_", f"{device_kind}_{host}")
    return os.path.join(_golden_dir(), f"{key}.json")


def load_golden(path: Optional[str] = None) -> Optional[dict]:
    try:
        with open(path or golden_path()) as f:
            d = json.load(f)
        return d if isinstance(d.get("table"), dict) else None
    except Exception:
        return None


def save_golden(table: dict, path: Optional[str] = None,
                note: str = "") -> str:
    path = path or golden_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    doc = {"t": time.time(), "note": note, "table": table}
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, default=str)
    get_registry().counter("profiler/golden_recorded").inc()
    return path


def record_golden(run_step: Callable[[], None], steps: int = 2,
                  path: Optional[str] = None, note: str = "") -> str:
    """Capture `run_step` under the profiler during a known-healthy run
    and persist its kernel table as THE golden for this machine."""
    from ..tools import roofline
    table = roofline.capture_kernel_table(run_step, _floors(), steps=steps)
    if "error" in table:
        raise RuntimeError(f"golden capture failed: {table['error']}")
    return save_golden(table, path=path, note=note)


def _floors() -> tuple:
    """(mm_tflops, stream_gbs) from the calibration cache; permissive
    fallback so attribution still tabulates on an uncalibrated box."""
    try:
        from .calibrate import get_calibration
        return get_calibration().floors()
    except Exception:
        return (1.0, 10.0)


class _JaxProfiler:
    """The real backend: jax.profiler start/stop_trace."""

    def start(self, logdir: str) -> None:
        import jax
        jax.profiler.start_trace(logdir)

    def stop(self) -> None:
        import jax
        jax.profiler.stop_trace()


class ProfileTrigger:
    """Arms a bounded trace capture on anomalies/pages and turns the
    capture into a kernel-level attribution. See module docstring."""

    def __init__(self, profiler=None, window_steps: int = 2,
                 window_s: float = 5.0,
                 cooldown_s: Optional[float] = None,
                 max_captures_per_h: Optional[int] = None,
                 topk: int = 5,
                 history_half_width_s: float = 30.0,
                 registry: Optional[Registry] = None):
        env = os.environ
        if cooldown_s is None:
            cooldown_s = float(env.get("PDTPU_PROFILE_COOLDOWN_S", "60"))
        if max_captures_per_h is None:
            max_captures_per_h = int(
                env.get("PDTPU_PROFILE_MAX_CAPTURES", "12"))
        self.profiler = profiler if profiler is not None else _JaxProfiler()
        self.window_steps = max(1, int(window_steps))
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.max_captures_per_h = max(1, int(max_captures_per_h))
        self.topk = int(topk)
        self.history_half_width_s = float(history_half_width_s)
        self.enrich_wait_s = float(
            env.get("PDTPU_PROFILE_ENRICH_WAIT_S", "8"))
        self._reg = registry if registry is not None else get_registry()
        self._lock = threading.Lock()
        self._capturing = False
        self._capture_times: collections.deque = collections.deque(maxlen=64)
        self._steps_seen = 0
        self._window_done = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._last: Optional[dict] = None
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- gating
    @staticmethod
    def enabled() -> bool:
        return os.environ.get("PDTPU_PROFILE_ON_ANOMALY", "1") != "0"

    def arm(self, reason: str, anomaly_t: Optional[float] = None):
        """Request a capture. Returns the capture thread when armed,
        None when gated (the skip reason lands in
        ``profiler/skipped{reason=...}``)."""
        now = time.time()
        if not self.enabled():
            self._reg.counter("profiler/skipped", reason="disabled").inc()
            return None
        with self._lock:
            if self._capturing:
                self._reg.counter("profiler/skipped", reason="busy").inc()
                return None
            if (self._capture_times
                    and now - self._capture_times[-1] < self.cooldown_s):
                self._reg.counter("profiler/skipped",
                                  reason="cooldown").inc()
                return None
            recent = [t for t in self._capture_times if now - t < 3600.0]
            if len(recent) >= self.max_captures_per_h:
                self._reg.counter("profiler/skipped", reason="cap").inc()
                return None
            self._capturing = True
            self._capture_times.append(now)
            self._steps_seen = 0
            self._window_done.clear()
            self._idle.clear()
        self._reg.counter("profiler/captures", trigger=reason).inc()
        t = threading.Thread(target=self._capture, name="profile-capture",
                             args=(reason, anomaly_t or now), daemon=True)
        with self._lock:
            self._thread = t
        t.start()
        return t

    # ------------------------------------------------------- subscriptions
    def on_record(self, rec: dict) -> None:
        """StepProfiler per-record listener: closes the capture window
        after `window_steps` further dispatches."""
        with self._lock:
            if not self._capturing:
                return
            self._steps_seen += 1
            if self._steps_seen >= self.window_steps:
                self._window_done.set()

    def on_anomaly(self, rec: dict, reason: str) -> None:
        """StepProfiler anomaly listener: the arming signal."""
        self.arm(reason, anomaly_t=rec.get("t"))

    def enrich_alert(self, alert) -> Optional[dict]:
        """AlertManager enricher: page-severity alerts get (and if
        needed, trigger) the current attribution before the event is
        emitted. Blocks up to `enrich_wait_s` for an in-flight capture
        so the firing event deterministically carries the culprits."""
        if alert.severity != "page":
            return None
        with self._lock:
            idle = not self._capturing
        if idle:
            # no capture in flight: try to get one (cooldown/cap gating
            # applies — when gated we fall back to the last attribution)
            self.arm(f"alert:{alert.name}")
        self._idle.wait(self.enrich_wait_s)
        att = self.last_attribution()
        if not att or att.get("error"):
            return None
        out = {"culprit_kernels": att.get("culprit_kernels"),
               "attribution_t": att.get("t"),
               "attribution_trigger": att.get("trigger")}
        if att.get("trace_diff") is not None:
            out["trace_diff"] = att["trace_diff"]
        if att.get("history") is not None:
            out["history"] = att["history"]
        return out

    def attach(self, step_profiler=None, alert_manager=None
               ) -> "ProfileTrigger":
        """Wire into the detection layer: StepProfiler records +
        anomalies, AlertManager enrichment. Also registers the
        ``profile_trigger`` flight-dump section."""
        if step_profiler is not None:
            step_profiler.add_listener(self.on_record)
            step_profiler.add_anomaly_listener(self.on_anomaly)
        if alert_manager is not None:
            alert_manager.add_enricher(self.enrich_alert)
        from .flight import register_dump_section
        register_dump_section("profile_trigger", self.doc)
        return self

    # ------------------------------------------------------------ capture
    def _capture(self, reason: str, anomaly_t: float) -> None:
        t0 = time.time()
        logdir = tempfile.mkdtemp(prefix="pdtpu_profile_")
        att: dict = {"t": anomaly_t, "trigger": reason}
        try:
            try:
                self.profiler.start(logdir)
            except Exception as e:
                self._reg.counter("profiler/skipped",
                                  reason="start_failed").inc()
                att["error"] = f"start_trace: {type(e).__name__}: {e}"
                return
            self._window_done.wait(self.window_s)
            try:
                self.profiler.stop()
            except Exception as e:
                att["error"] = f"stop_trace: {type(e).__name__}: {e}"
                return
            try:
                att.update(self._attribute(logdir, anomaly_t))
            except Exception as e:
                att["error"] = f"attribution: {type(e).__name__}: {e}"
        finally:
            att["capture_ms"] = round((time.time() - t0) * 1e3, 1)
            self._reg.histogram("profiler/capture_ms").observe(
                att["capture_ms"])
            shutil.rmtree(logdir, ignore_errors=True)
            with self._lock:
                self._last = att
                self._capturing = False
            self._idle.set()

    def _attribute(self, logdir: str, anomaly_t: float) -> dict:
        """Trace dir → kernel table → golden diff → culprits + the
        surrounding history window."""
        from ..tools import roofline
        tr = roofline.load_trace(logdir)
        table = roofline.kernel_table(tr, _floors(),
                                      steps=max(1, self.window_steps),
                                      cutoff_ms=0.0)
        if "error" in table:
            return {"error": table["error"]}
        out: dict = {"kernel_table_top": table["kernels"][:self.topk],
                     "device_ms_per_step": table.get("device_ms_per_step")}
        golden = load_golden()
        culprits: List[dict] = []
        if golden is not None:
            diff = roofline.diff_tables(golden["table"], table,
                                        topk=max(self.topk, 8))
            out["trace_diff"] = {
                "golden_t": golden.get("t"),
                "delta_ms_per_step": diff.get("delta_ms_per_step"),
                "movers": diff.get("movers", [])[:self.topk],
                "only_in_capture": diff.get("only_in_b", [])[:self.topk],
            }
            host_culprits: List[dict] = []
            for m in diff.get("movers", ()):
                nm = m.get("kernel", "")
                if m.get("delta_ms", 0) <= 0 or _is_noise_span(nm):
                    continue
                if _is_host_span(nm):
                    # device kernels can be clean while the step still
                    # regressed: a host-side stall (loader, lock, fault
                    # probe) is then the truthful culprit — rank it
                    # after any device mover
                    host_culprits.append(
                        {"kernel": nm, "delta_ms": m["delta_ms"],
                         "ms": m.get("ms_b"),
                         "why": "host-side regression vs golden"})
                else:
                    culprits.append({"kernel": nm,
                                     "delta_ms": m["delta_ms"],
                                     "ms": m.get("ms_b"),
                                     "why": "regressed vs golden"})
            culprits.extend(host_culprits)
            for nm in diff.get("only_in_b", ()):
                if not _is_noise_span(nm):
                    culprits.append({"kernel": nm,
                                     "why": "new vs golden"})
        if not culprits:
            # no golden (or nothing moved): the capture's own heaviest
            # device kernels are still a named starting point
            why = ("top by time (nothing moved vs golden)"
                   if golden is not None else "top by time (no golden)")
            for k in table["kernels"]:
                if not (_is_host_span(k["kernel"])
                        or _is_noise_span(k["kernel"])):
                    culprits.append({"kernel": k["kernel"], "ms": k["ms"],
                                     "why": why})
                if len(culprits) >= self.topk:
                    break
        out["culprit_kernels"] = culprits[:self.topk]
        from .history import get_history
        hist = get_history()
        if hist is not None:
            out["history"] = hist.window(
                anomaly_t, half_width_s=self.history_half_width_s)
        return out

    # ------------------------------------------------------------- reading
    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until no capture is in flight (bench/test sync)."""
        return self._idle.wait(timeout)

    def last_attribution(self) -> Optional[dict]:
        with self._lock:
            return self._last

    def doc(self) -> dict:
        with self._lock:
            last = dict(self._last) if self._last else None
        if last is not None:
            # flight dumps don't need the full history window re-embedded
            last.pop("history", None)
        return {"capturing": not self._idle.is_set(),
                "captures": len(self._capture_times),
                "window_steps": self.window_steps,
                "cooldown_s": self.cooldown_s,
                "max_captures_per_h": self.max_captures_per_h,
                "last": last}


# process-wide trigger (mirrors install_scraper/install_history)
_installed: Optional[ProfileTrigger] = None
_install_lock = threading.Lock()


def install_trigger(trigger: Optional[ProfileTrigger]):
    """Make `trigger` the process-wide one (None uninstalls)."""
    global _installed
    with _install_lock:
        _installed = trigger
    return trigger


def get_trigger() -> Optional[ProfileTrigger]:
    with _install_lock:
        return _installed
