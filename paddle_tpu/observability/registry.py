"""Process-wide metrics registry: counters, gauges, histograms.

Reference analog: the reference framework's per-op profiler kept sorted
aggregate tables in the C++ profiler singleton (platform/profiler.cc,
PrintProfiler) and serving deployments exported QPS/latency through
external RPC metrics. Here the registry is one in-process object that
every layer writes into — the executor's compile/cache accounting, the
serving tier's request counters, user code via `get_registry()` — so a
single export shows the whole runtime.

Design:
- each metric holds one small lock (contention is per-metric, not
  registry-wide); the registry lock is touched only on first-use creation;
- `Histogram` keeps a fixed-size ring of recent observations, and every
  read (percentile/snapshot) copies the ring UNDER the lock before
  computing, so concurrent `observe()` calls can never corrupt a
  percentile read;
- metrics may carry labels (``counter("compile", sig="ab12")``) — the
  registry keys on (name, sorted label items) and exporters render
  ``name{sig="ab12"}``;
- registries compose: a child registry (e.g. one server's
  ``serving.Metrics``) attaches to the process registry by weakref, and
  a deep `snapshot()` / `prometheus_text()` merges children in — counters
  and gauges sum, histograms merge at the sample level.
"""
from __future__ import annotations

import json
import threading
import weakref
from typing import Dict, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "get_registry",
           "render_prometheus"]


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


# Module-level help-text store (`Registry.describe`): metric name ->
# one-line doc, shared by every registry in the process so federated and
# local exposition emit identical ``# HELP`` lines. Keyed on the RAW
# name (pre-sanitization), matching how callers register metrics.
_HELP: Dict[str, str] = {}
_HELP_LOCK = threading.Lock()


def _prom_help_text(text: str) -> str:
    """HELP-line escaping per the exposition spec: backslash and
    newline only (double quotes are legal in help text)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _prom_metric_name(name: str) -> str:
    """Map to the exposition-spec metric-name charset
    ``[a-zA-Z_:][a-zA-Z0-9_:]*`` (we also fold ``:`` to ``_`` — the
    spec reserves colons for recording rules)."""
    s = "".join(ch if (ch.isalnum() and ch.isascii()) or ch == "_"
                else "_" for ch in name)
    if not s or s[0].isdigit():
        s = "_" + s
    return s


def _prom_label_name(name: str) -> str:
    """Label-name charset ``[a-zA-Z_][a-zA-Z0-9_]*``."""
    return _prom_metric_name(name)


def _prom_label_value(value) -> str:
    """Escape per the exposition spec: backslash, double quote, and
    newline inside quoted label values."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labelstr(items, extra=()) -> str:
    items = tuple(items) + tuple(extra)
    if not items:
        return ""
    return ("{" + ",".join(
        f'{_prom_label_name(k)}="{_prom_label_value(v)}"'
        for k, v in items) + "}")


def _fmt_name(name: str, label_items: tuple) -> str:
    if not label_items:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in label_items)
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonic counter (requests, batches, cache hits/misses)."""

    def __init__(self, name: str, labels: Optional[dict] = None):
        self.name = name
        self.labels = dict(labels or {})
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (queue depth, device count)."""

    def __init__(self, name: str, labels: Optional[dict] = None):
        self.name = name
        self.labels = dict(labels or {})
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, d: float) -> None:
        with self._lock:
            self._value += float(d)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


def _percentiles_from(data: List[float], sums: Tuple[int, float],
                      lo, hi) -> dict:
    n, s = sums

    def pct(p):
        if not data:
            return None
        return data[max(0, min(len(data) - 1,
                               int(round(p / 100.0 * (len(data) - 1)))))]

    return {"count": n, "mean": (s / n) if n else None,
            "min": lo, "max": hi,
            "p50": pct(50), "p95": pct(95), "p99": pct(99)}


class Histogram:
    """Observation stream with all-time count/sum/min/max and percentiles
    over a fixed ring of the most recent `cap` observations.

    Snapshot/percentile reads are copy-on-read: the ring is copied while
    the lock is held and all sorting/ranking happens on the copy, so a
    reader can never observe (or cause) a half-updated ring while writer
    threads `observe()` concurrently."""

    def __init__(self, name: str, cap: int = 8192,
                 labels: Optional[dict] = None):
        self.name = name
        self.labels = dict(labels or {})
        self._lock = threading.Lock()
        self._ring: List[float] = []
        self._cap = int(cap)
        self._idx = 0
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)
            if len(self._ring) < self._cap:
                self._ring.append(v)
            else:
                self._ring[self._idx] = v
                self._idx = (self._idx + 1) % self._cap

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def _state(self) -> tuple:
        """(count, sum, min, max, ring-copy) — one consistent read."""
        with self._lock:
            return (self._count, self._sum, self._min, self._max,
                    list(self._ring))

    def percentile(self, p: float) -> Optional[float]:
        """Nearest-rank percentile (p in [0, 100]) over the retained ring."""
        data = sorted(self._state()[4])
        if not data:
            return None
        rank = max(0, min(len(data) - 1,
                          int(round(p / 100.0 * (len(data) - 1)))))
        return data[rank]

    def snapshot(self) -> dict:
        n, s, lo, hi, ring = self._state()
        return _percentiles_from(sorted(ring), (n, s), lo, hi)


def _merge_hist_states(states: List[tuple]) -> dict:
    n = sum(st[0] for st in states)
    s = sum(st[1] for st in states)
    los = [st[2] for st in states if st[2] is not None]
    his = [st[3] for st in states if st[3] is not None]
    data = sorted(v for st in states for v in st[4])
    return _percentiles_from(data, (n, s),
                             min(los) if los else None,
                             max(his) if his else None)


class Registry:
    """Named metric registry; metrics are created on first use so hot
    paths never need None-checks. Thread-safe throughout."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[tuple, Counter] = {}
        self._gauges: Dict[tuple, Gauge] = {}
        self._histograms: Dict[tuple, Histogram] = {}
        # child registries (weak: a GC'd server's metrics drop out of the
        # deep export automatically)
        self._children: "weakref.WeakSet[Registry]" = weakref.WeakSet()

    # -- creation ----------------------------------------------------------
    def counter(self, name: str, **labels) -> Counter:
        key = (name, _label_key(labels))
        with self._lock:
            m = self._counters.get(key)
            if m is None:
                m = self._counters[key] = Counter(name, labels)
            return m

    def gauge(self, name: str, **labels) -> Gauge:
        key = (name, _label_key(labels))
        with self._lock:
            m = self._gauges.get(key)
            if m is None:
                m = self._gauges[key] = Gauge(name, labels)
            return m

    def histogram(self, name: str, cap: int = 8192, **labels) -> Histogram:
        key = (name, _label_key(labels))
        with self._lock:
            m = self._histograms.get(key)
            if m is None:
                m = self._histograms[key] = Histogram(name, cap, labels)
            return m

    # -- documentation -----------------------------------------------------
    @staticmethod
    def describe(name: str, help_text: str) -> None:
        """Attach a one-line doc to a metric name; `render_prometheus`
        emits it as a ``# HELP`` line (described series only). Process-
        wide (module-level store), so it applies to every registry and
        to federated re-rendering alike."""
        with _HELP_LOCK:
            _HELP[str(name)] = str(help_text)

    @staticmethod
    def help_for(name: str) -> Optional[str]:
        with _HELP_LOCK:
            return _HELP.get(str(name))

    # -- removal -----------------------------------------------------------
    def remove(self, name: str, **labels) -> bool:
        """Drop the exact (name, labels) series from this registry, all
        three kinds. Returns True if anything was removed. The federated
        scraper uses this to retire ``autoscale/*`` gauges whose source
        target vanished, so a removed shard's last reading doesn't
        linger forever as a live-looking sample."""
        key = (name, _label_key(labels))
        removed = False
        with self._lock:
            for d in (self._counters, self._gauges, self._histograms):
                if d.pop(key, None) is not None:
                    removed = True
        return removed

    def remove_matching(self, name: str) -> int:
        """Drop every series with metric name `name`, any label set.
        Returns the number of series removed."""
        n = 0
        with self._lock:
            for d in (self._counters, self._gauges, self._histograms):
                for key in [k for k in d if k[0] == name]:
                    del d[key]
                    n += 1
        return n

    # -- composition -------------------------------------------------------
    def attach(self, child: "Registry") -> "Registry":
        """Include `child`'s metrics in this registry's deep exports.
        Held by weakref: detaches automatically when the child dies."""
        if child is self:
            raise ValueError("a registry cannot attach to itself")
        self._children.add(child)
        return child

    def _collect(self, deep: bool, _seen=None):
        """All (key, metric) tuples of self (+ children when deep), as
        three lists: counters, gauges, histograms."""
        _seen = _seen if _seen is not None else set()
        if id(self) in _seen:  # cycle guard: A attached to B attached to A
            return [], [], []
        _seen.add(id(self))
        with self._lock:
            cs = list(self._counters.items())
            gs = list(self._gauges.items())
            hs = list(self._histograms.items())
            children = list(self._children) if deep else []
        for ch in children:
            c2, g2, h2 = ch._collect(deep, _seen)
            cs += c2
            gs += g2
            hs += h2
        return cs, gs, hs

    # -- export ------------------------------------------------------------
    def snapshot(self, deep: bool = True) -> dict:
        """One plain dict of everything — counters/gauges as numbers,
        histograms as summary dicts. With deep=True, attached child
        registries merge in: counters/gauges with the same name+labels
        sum; histograms merge at the sample level (percentiles over the
        union of retained rings)."""
        cs, gs, hs = self._collect(deep)
        out: dict = {}
        for key, c in cs:
            name = _fmt_name(*key)
            out[name] = out.get(name, 0) + c.value
        for key, g in gs:
            name = _fmt_name(*key)
            out[name] = out.get(name, 0.0) + g.value
        by_name: Dict[str, list] = {}
        for key, h in hs:
            by_name.setdefault(_fmt_name(*key), []).append(h._state())
        for name, states in by_name.items():
            out[name] = (_percentiles_from(sorted(states[0][4]),
                                           states[0][:2], *states[0][2:4])
                         if len(states) == 1 else _merge_hist_states(states))
        return out

    def dump_json(self, path: str, deep: bool = True) -> dict:
        snap = self.snapshot(deep)
        with open(path, "w") as f:
            json.dump(snap, f, indent=2, sort_keys=True)
        return snap

    def series(self, deep: bool = True) -> List[dict]:
        """Structured export: one dict per series, JSON- and
        PS-transport-safe (plain str/int/float/None values only), so a
        federation scraper — HTTP ``/metrics/series`` or the pserver
        ``metrics`` transport op — gets labels as DATA instead of
        parsing them back out of flat ``name{k="v"}`` snapshot keys.

        Shapes::

            {"name", "type": "counter"|"gauge", "labels": {...}, "value"}
            {"name", "type": "summary", "labels": {...},
             "summary": {count, sum, mean, min, max, p50, p95, p99}}

        Same merge semantics as `snapshot`/`prometheus_text`: duplicate
        keys across attached children sum (counters/gauges) or merge at
        the sample level (histograms)."""
        cs, gs, hs = self._collect(deep)
        out: List[dict] = []
        merged_c: Dict[tuple, int] = {}
        for key, c in cs:
            merged_c[key] = merged_c.get(key, 0) + c.value
        for (name, items), v in sorted(merged_c.items()):
            out.append({"name": name, "type": "counter",
                        "labels": dict(items), "value": v})
        merged_g: Dict[tuple, float] = {}
        for key, g in gs:
            merged_g[key] = merged_g.get(key, 0.0) + g.value
        for (name, items), v in sorted(merged_g.items()):
            out.append({"name": name, "type": "gauge",
                        "labels": dict(items), "value": v})
        merged_h: Dict[tuple, list] = {}
        for key, h in hs:
            merged_h.setdefault(key, []).append(h._state())
        for (name, items), states in sorted(merged_h.items()):
            summ = _merge_hist_states(states)
            summ["sum"] = sum(st[1] for st in states)
            out.append({"name": name, "type": "summary",
                        "labels": dict(items), "summary": summ})
        return out

    def prometheus_text(self, deep: bool = True) -> str:
        """Prometheus text exposition format. Histograms render as
        summaries (quantile labels + _count/_sum). Metric/label names
        are sanitized to the spec charsets and label values escaped
        (backslash, double quote, newline), so hostile values like a
        feed signature ``x:f32[8,128]`` cannot produce an unscrapeable
        page. Implemented as `render_prometheus(self.series(deep))` so
        local and federated output share one renderer by construction."""
        return render_prometheus(self.series(deep))

    def report(self, deep: bool = False) -> str:
        """Human-readable text table of the snapshot."""
        snap = self.snapshot(deep)
        lines = [f"{'metric':<36}{'value':>44}"]
        for name in sorted(snap):
            v = snap[name]
            if isinstance(v, dict):
                parts = []
                for k in ("count", "mean", "p50", "p95", "p99", "max"):
                    x = v.get(k)
                    if x is None:
                        continue
                    parts.append(f"{k}={x:.3f}" if isinstance(x, float)
                                 else f"{k}={x}")
                v = " ".join(parts) or "-"
            lines.append(f"{name:<36}{str(v):>44}")
        return "\n".join(lines)

    def reset(self) -> None:
        """Drop every metric (tests / long-lived processes rolling over);
        attached children are kept but their metrics are untouched."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


def render_prometheus(series: List[dict], extra_labels=()) -> str:
    """Render a `Registry.series()`-shaped list in the exposition text
    format: ``# TYPE`` line once per metric name, counters then gauges
    then summaries, each group sorted by (name, labels). `extra_labels`
    ((key, value) pairs) are appended to every sample's label set — the
    federation exporter passes ``process``/``role``/``shard`` here —
    and go through the SAME name sanitization and value escaping as
    local labels, so federated output cannot diverge from local output.
    """
    extra = tuple(extra_labels)
    groups: Dict[str, list] = {"counter": [], "gauge": [], "summary": []}
    for s in series:
        t = s.get("type")
        if t not in groups:
            continue
        items = _label_key(s.get("labels") or {})
        groups[t].append(((s["name"], items), s))
    lines: List[str] = []
    typed = set()
    for kind in ("counter", "gauge", "summary"):
        for (name, items), s in sorted(groups[kind], key=lambda kv: kv[0]):
            pname = _prom_metric_name(name)
            if pname not in typed:
                typed.add(pname)
                help_text = Registry.help_for(name)
                if help_text is not None:
                    lines.append(
                        f"# HELP {pname} {_prom_help_text(help_text)}")
                lines.append(f"# TYPE {pname} {kind}")
            if kind == "summary":
                summ = s.get("summary") or {}
                for q, k in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                    if summ.get(k) is not None:
                        lines.append(
                            f"{pname}"
                            f"{_prom_labelstr(items, extra + (('quantile', q),))}"
                            f" {summ[k]}")
                lines.append(f"{pname}_count{_prom_labelstr(items, extra)} "
                             f"{summ.get('count', 0)}")
                lines.append(f"{pname}_sum{_prom_labelstr(items, extra)} "
                             f"{summ.get('sum', 0.0)}")
            else:
                lines.append(f"{pname}{_prom_labelstr(items, extra)} "
                             f"{s.get('value', 0)}")
    return "\n".join(lines) + ("\n" if lines else "")


_default = Registry()


def get_registry() -> Registry:
    """THE process-wide registry: executor, serving, and user metrics all
    land here (serving `Metrics` instances attach as children)."""
    return _default
