"""Declarative SLOs evaluated over the federated scrape, with
multi-window multi-burn-rate alerting.

PRs 2/5/13/15 built the sensor plane — per-process registries, the
`FederatedScraper` merging every pserver/worker/replica into one
document, derived ``autoscale/*`` gauges, live ``perf/*`` roofline
numbers. This module is the judgment layer on top: an `SloSpec` says
*what good looks like* ("p99 pull latency under 100 ms per shard",
"serving error ratio under 0.1%", "rows visible in serving within 2 s
of publish"), and an `SloEngine` compiles the specs into recording
rules evaluated on every scrape sweep, maintaining the standard SRE
multi-window burn-rate formulation:

    burn rate = (observed bad fraction) / (error budget)
    page  when burn(1h)  > 14.4  AND burn(5m)  > 14.4
    warn  when burn(6h)  >  6.0  AND burn(30m) >  6.0

The AND of a long and a short window is what makes this both fast and
quiet: a hard outage pushes the short window to enormous burn within a
sweep or two (pages immediately), while a slow leak has to sustain
long enough to move the 1 h window (no flapping on blips); recovery
clears the short window first, resolving the page promptly.

Wall-clock windows are impractical in tests and bench chaos cells, so
the engine takes a ``window_scale``: the *rule* stays "1 h / 5 m" (and
is labelled that way in the ``slo/burn_rate{window=...}`` recording
gauges), but the engine evaluates it over ``window * scale`` seconds.
The bench kills a pserver and proves the page fires within two sweeps
at scale ~1/720 — identical code path, compressed time.

Indicator modes:

* ``min_above``  — bad when value < bound (availability ``ps/shard_up``,
  ``perf/mfu`` floors);
* ``max_below``  — bad when value > bound (latency p99 / step-time
  ceilings; ``field`` picks the summary percentile);
* ``age_below``  — the metric is a unix-time "freshness clock" gauge
  (``staleness/last_visible_ts``); bad when now − value > bound. This
  is what makes train→serve staleness alertable: when delta flow
  stalls, no new e2e histogram samples arrive at all, but the clock's
  age grows without bound;
* ``ratio``      — error/total counter pair; each sweep contributes
  bad = Δerror/Δtotal weighted by Δtotal (request-weighted burn, the
  canonical availability SLI).

``group_by`` evaluates the spec per distinct label value (per shard,
per tenant, per table) so the resulting alert's labels *name the
offender* — the bench asserts the flight dump of a pserver SIGKILL
carries the dead shard's id.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Tuple

from .registry import Registry, get_registry

__all__ = ["SloSpec", "SloEngine", "default_slos", "BURN_RATE_WINDOWS"]

# (severity, long_window_s, short_window_s, burn_rate_threshold) — the
# standard SRE multiwindow table (SNIPPETS-independent; Google SRE
# workbook chapter 5 values).
BURN_RATE_WINDOWS: Tuple[tuple, ...] = (
    ("page", 3600.0, 300.0, 14.4),
    ("warn", 21600.0, 1800.0, 6.0),
)

Registry.describe(
    "slo/bad_fraction",
    "recording rule: this sweep's bad fraction per SLO (and group)")
Registry.describe(
    "slo/burn_rate",
    "recording rule: error-budget burn rate per SLO over each alert "
    "window (window label names the logical, unscaled window)")
Registry.describe(
    "staleness/e2e_ms",
    "true train-to-serve staleness: trainer push to visible in the "
    "serving row cache, per delta row")
Registry.describe(
    "staleness/last_visible_ts",
    "freshness clock: unix time of the last delta batch applied to the "
    "serving cache; its age is what DeltaStaleness alerts on")

_MODES = ("min_above", "max_below", "age_below", "ratio")


def _wlabel(seconds: float) -> str:
    s = int(seconds)
    if s % 3600 == 0:
        return f"{s // 3600}h"
    if s % 60 == 0:
        return f"{s // 60}m"
    return f"{s}s"


class SloSpec:
    """One service-level objective, declaratively.

    Parameters
    ----------
    name : alert name (``PsShardAvailability`` style — what pages).
    metric : series name of the indicator (for ``ratio``, the error
        counter; ``total_metric`` holds the denominator).
    mode : one of ``min_above`` / ``max_below`` / ``age_below`` /
        ``ratio`` (see module doc).
    bound : threshold for the threshold modes (same unit as the metric;
        seconds for ``age_below``). Unused for ``ratio``.
    objective : target good fraction; the error budget is
        ``1 - objective`` and burn rates are measured against it.
    field : ``"value"`` for counters/gauges or a summary key
        (``"p99"``, ``"p95"``, ``"mean"``) for histogram series.
    group_by : evaluate per distinct value of this label (alert labels
        carry it), or None for one global series.
    match : optional label subset a series must carry to count.
    missing : ``"ignore"`` (no observation when the metric is absent —
        the default) or ``"bad"`` (absence of a previously-seen group
        counts as a bad sample: a target that stops reporting is
        treated as out of SLO).
    description : human text, carried into alert annotations.
    """

    def __init__(self, name: str, metric: str, mode: str,
                 bound: Optional[float] = None, objective: float = 0.999,
                 field: str = "value", group_by: Optional[str] = None,
                 total_metric: Optional[str] = None,
                 match: Optional[dict] = None, missing: str = "ignore",
                 description: str = ""):
        if mode not in _MODES:
            raise ValueError(f"mode must be one of {_MODES}, got {mode!r}")
        if mode == "ratio" and not total_metric:
            raise ValueError("ratio mode requires total_metric")
        if mode != "ratio" and bound is None:
            raise ValueError(f"mode {mode!r} requires a bound")
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), got {objective}")
        if missing not in ("ignore", "bad"):
            raise ValueError(f"missing must be 'ignore'|'bad', "
                             f"got {missing!r}")
        self.name = str(name)
        self.metric = str(metric)
        self.mode = mode
        self.bound = None if bound is None else float(bound)
        self.objective = float(objective)
        self.field = str(field)
        self.group_by = group_by
        self.total_metric = total_metric
        self.match = dict(match or {})
        self.missing = missing
        self.description = str(description)

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    # ------------------------------------------------------ factory sugar
    @classmethod
    def floor(cls, name, metric, bound, **kw):
        """Bad when the metric drops below `bound` (availability, MFU)."""
        return cls(name, metric, "min_above", bound=bound, **kw)

    @classmethod
    def ceiling(cls, name, metric, bound, **kw):
        """Bad when the metric exceeds `bound` (queue depth, step time)."""
        return cls(name, metric, "max_below", bound=bound, **kw)

    @classmethod
    def latency(cls, name, metric, budget_ms, field="p99",
                objective=0.99, **kw):
        """Bad when the chosen percentile exceeds `budget_ms`."""
        return cls(name, metric, "max_below", bound=float(budget_ms),
                   field=field, objective=objective, **kw)

    @classmethod
    def freshness(cls, name, metric, budget_ms, objective=0.999, **kw):
        """`metric` is a unix-time gauge stamped on each update; bad
        when its age exceeds `budget_ms`."""
        return cls(name, metric, "age_below", bound=float(budget_ms) / 1e3,
                   objective=objective, **kw)

    @classmethod
    def ratio(cls, name, error_metric, total_metric, objective=0.999, **kw):
        """Request-weighted error-ratio SLI over a counter pair."""
        return cls(name, error_metric, "ratio", total_metric=total_metric,
                   objective=objective, **kw)

    def doc(self) -> dict:
        return {"name": self.name, "metric": self.metric, "mode": self.mode,
                "bound": self.bound, "objective": self.objective,
                "field": self.field, "group_by": self.group_by,
                "total_metric": self.total_metric, "missing": self.missing,
                "description": self.description}


def _flatten(doc) -> List[dict]:
    """Fleet doc (or plain series list) -> one series list with each
    target's process/role/shard labels merged in (series' own labels
    win on collision), so ``group_by="process"`` etc. work."""
    if isinstance(doc, list):
        return doc
    out: List[dict] = []
    for r in doc.get("targets", ()):
        base = {"process": r.get("process"), "role": r.get("role")}
        if r.get("shard") is not None:
            base["shard"] = str(r["shard"])
        for s in r.get("series", ()):
            labels = dict(base)
            labels.update(s.get("labels") or {})
            s2 = dict(s)
            s2["labels"] = labels
            out.append(s2)
    return out


def _series_field(s: dict, field: str):
    if field == "value":
        v = s.get("value")
        if v is None and s.get("summary"):
            v = s["summary"].get("mean")
        return v if isinstance(v, (int, float)) else None
    summ = s.get("summary") or {}
    v = summ.get(field)
    return v if isinstance(v, (int, float)) else None


class SloEngine:
    """Evaluates a list of `SloSpec`s over each federated sweep and
    drives an `alerts.AlertManager`. Attach to a scraper via
    ``engine.attach(scraper)`` (rides `add_sweep_listener`) or call
    ``observe(doc)`` directly."""

    def __init__(self, specs, alert_manager=None, window_scale: float = 1.0,
                 windows=BURN_RATE_WINDOWS,
                 registry: Optional[Registry] = None):
        self.specs: List[SloSpec] = list(specs)
        names = [s.name for s in self.specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.window_scale = float(window_scale)
        self.windows = tuple(windows)
        self._am = alert_manager
        self._reg = registry if registry is not None else get_registry()
        self._lock = threading.Lock()
        # (spec.name, group) -> deque[(t, bad, weight)]
        self._rings: Dict[tuple, "collections.deque"] = {}
        # ratio mode: (spec.name, group) -> (prev_err, prev_total)
        self._prev: Dict[tuple, Tuple[float, float]] = {}
        self._max_window = (max(w[1] for w in self.windows)
                            * self.window_scale)

    # --------------------------------------------------------- evaluation
    def observe(self, doc, now: Optional[float] = None,
                now_wall: Optional[float] = None) -> dict:
        """One sweep: evaluate every spec against `doc` (a ``/fleet``
        document or a plain series list), update rings, recording
        gauges, and the alert manager. `now` is the monotonic rule
        clock (injectable for tests); `now_wall` the wall clock used by
        ``age_below`` freshness rules."""
        now = time.monotonic() if now is None else float(now)
        now_wall = time.time() if now_wall is None else float(now_wall)
        flat = _flatten(doc)
        out = {}
        with self._lock:
            for spec in self.specs:
                out[spec.name] = self._observe_spec(spec, flat, now,
                                                    now_wall)
        return out

    def _observe_spec(self, spec: SloSpec, flat: List[dict],
                      now: float, now_wall: float) -> dict:
        samples = self._evaluate(spec, flat, now_wall)
        known = {g for (n, g) in self._rings if n == spec.name}
        if spec.missing == "bad":
            for g in known - set(samples):
                samples[g] = (1.0, 1.0, None)
        for group, (bad, weight, _val) in samples.items():
            if weight <= 0:
                continue
            ring = self._rings.setdefault(
                (spec.name, group), collections.deque())
            ring.append((now, bad, weight))
        # evaluate every group that still has samples in its ring (a
        # vanished group keeps decaying until its ring drains, so its
        # alert resolves rather than freezing in the firing state)
        result = {}
        for key in [k for k in list(self._rings) if k[0] == spec.name]:
            group = key[1]
            ring = self._rings[key]
            horizon = now - self._max_window - 1e-9
            while ring and ring[0][0] < horizon:
                ring.popleft()
            glabels = {spec.group_by: group} if spec.group_by else {}
            if not ring:
                del self._rings[key]
                self._reg.remove("slo/bad_fraction", slo=spec.name,
                                 **glabels)
                for _, long_s, short_s, _ in self.windows:
                    for w in (long_s, short_s):
                        self._reg.remove("slo/burn_rate", slo=spec.name,
                                         window=_wlabel(w), **glabels)
                if self._am is not None:
                    for severity, _, _, _ in self.windows:
                        self._am.update(
                            spec.name, False, severity=severity,
                            labels={"slo": spec.name, **glabels}, now=now)
                continue
            cur_bad = samples.get(group, (ring[-1][1], 0, None))[0]
            raw_val = samples.get(group, (None, 0, None))[2]
            self._reg.gauge("slo/bad_fraction", slo=spec.name,
                            **glabels).set(cur_bad)
            burns = {}
            for severity, long_s, short_s, threshold in self.windows:
                b_long = self._burn(ring, now, long_s * self.window_scale,
                                    spec.budget)
                b_short = self._burn(ring, now,
                                     short_s * self.window_scale,
                                     spec.budget)
                burns[severity] = (b_long, b_short)
                self._reg.gauge("slo/burn_rate", slo=spec.name,
                                window=_wlabel(long_s),
                                **glabels).set(b_long)
                self._reg.gauge("slo/burn_rate", slo=spec.name,
                                window=_wlabel(short_s),
                                **glabels).set(b_short)
                if self._am is not None:
                    active = b_long > threshold and b_short > threshold
                    ann = {"slo": spec.description or spec.name,
                           "objective": spec.objective,
                           "bound": spec.bound,
                           "metric": spec.metric,
                           f"burn_{_wlabel(long_s)}": round(b_long, 3),
                           f"burn_{_wlabel(short_s)}": round(b_short, 3)}
                    if raw_val is not None:
                        ann["value"] = raw_val
                    self._am.update(
                        spec.name, active, severity=severity,
                        labels={"slo": spec.name, **glabels},
                        value=round(b_short, 3), annotations=ann, now=now)
            result[group] = {"bad": cur_bad, "burns": burns,
                             "value": raw_val}
        return result

    @staticmethod
    def _burn(ring, now: float, window: float, budget: float) -> float:
        lo = now - window
        n = w = 0.0
        for t, bad, weight in reversed(ring):
            if t < lo:
                break
            n += bad * weight
            w += weight
        if w <= 0:
            return 0.0
        return (n / w) / max(budget, 1e-9)

    def _evaluate(self, spec: SloSpec, flat: List[dict],
                  now_wall: float) -> Dict[str, tuple]:
        """group -> (bad_fraction, weight, raw_value) for this sweep."""

        def matches(s, metric):
            if s.get("name") != metric:
                return False
            labels = s.get("labels") or {}
            return all(labels.get(k) == str(v)
                       for k, v in spec.match.items())

        def group_of(s):
            if spec.group_by is None:
                return ""
            g = (s.get("labels") or {}).get(spec.group_by)
            return None if g is None else str(g)

        out: Dict[str, tuple] = {}
        if spec.mode == "ratio":
            errs: Dict[str, float] = {}
            tots: Dict[str, float] = {}
            for s in flat:
                g = group_of(s)
                if g is None:
                    continue
                v = _series_field(s, "value")
                if v is None:
                    continue
                if matches(s, spec.metric):
                    errs[g] = errs.get(g, 0.0) + v
                elif matches(s, spec.total_metric):
                    tots[g] = tots.get(g, 0.0) + v
            for g, tot in tots.items():
                err = errs.get(g, 0.0)
                prev = self._prev.get((spec.name, g))
                self._prev[(spec.name, g)] = (err, tot)
                if prev is None:
                    continue
                d_err, d_tot = err - prev[0], tot - prev[1]
                if d_tot <= 0 or d_err < 0:  # idle sweep / counter reset
                    continue
                frac = min(1.0, d_err / d_tot)
                out[g] = (frac, d_tot, frac)
            return out

        # threshold modes: aggregate matching series per group, worst wins
        vals: Dict[str, float] = {}
        for s in flat:
            if not matches(s, spec.metric):
                continue
            g = group_of(s)
            if g is None:
                continue
            v = _series_field(s, spec.field)
            if v is None:
                continue
            if g in vals:
                # worst-case merge: lowest for floors/freshness clocks,
                # highest for ceilings
                vals[g] = (min(vals[g], v)
                           if spec.mode in ("min_above", "age_below")
                           else max(vals[g], v))
            else:
                vals[g] = float(v)
        for g, v in vals.items():
            if spec.mode == "min_above":
                bad = 1.0 if v < spec.bound else 0.0
                out[g] = (bad, 1.0, v)
            elif spec.mode == "max_below":
                bad = 1.0 if v > spec.bound else 0.0
                out[g] = (bad, 1.0, v)
            else:  # age_below: v is a unix timestamp
                age = max(0.0, now_wall - v)
                bad = 1.0 if age > spec.bound else 0.0
                out[g] = (bad, 1.0, age)
        return out

    # -------------------------------------------------------------- wiring
    def attach(self, scraper) -> "SloEngine":
        """Evaluate on every `FederatedScraper` sweep."""
        scraper.add_sweep_listener(self.observe)
        return self

    def status(self) -> dict:
        """Current burn state per (slo, group) — the ops console reads
        this shape out of the recording gauges when remote, or directly
        here in-process."""
        with self._lock:
            keys = sorted(self._rings)
        return {"specs": [s.doc() for s in self.specs],
                "window_scale": self.window_scale,
                "groups": [{"slo": n, "group": g} for n, g in keys]}


def default_slos(serving_p99_ms: float = 50.0,
                 ps_pull_p99_ms: float = 100.0,
                 staleness_budget_ms: float = 2000.0,
                 step_time_ms: Optional[float] = None,
                 mfu_floor: Optional[float] = None) -> List[SloSpec]:
    """The stock objectives over this runtime's own metric names —
    serving latency/availability per tenant, PS pull p99 and liveness
    per shard, train→serve delta freshness per table, and optional
    training step-time / MFU floors (opt-in: their budgets are
    model-specific). See docs/migration.md "SLOs and alerting"."""
    specs = [
        SloSpec.floor(
            "PsShardAvailability", "ps/shard_up", 1.0, group_by="shard",
            objective=0.999,
            description="every PS shard answers health pings"),
        SloSpec.latency(
            "PsPullLatency", "ps/shard_pull_ms", ps_pull_p99_ms,
            group_by="shard", objective=0.99,
            description="per-shard pull p99 under budget"),
        SloSpec.ratio(
            "ServingAvailability", "serving/errors", "serving/requests",
            objective=0.999,
            description="serving error ratio within budget"),
        SloSpec.latency(
            "ServingTenantLatency", "fleet/tenant_latency_ms",
            serving_p99_ms, group_by="tenant", objective=0.99,
            description="per-tenant serving p99 under budget"),
        SloSpec.ratio(
            "ServingTenantAvailability", "fleet/tenant_throttled",
            "fleet/tenant_requests", group_by="tenant", objective=0.999,
            description="per-tenant admission within budget"),
        SloSpec.freshness(
            "DeltaStaleness", "staleness/last_visible_ts",
            staleness_budget_ms, group_by="table", objective=0.999,
            description="train-to-serve delta visibility within the "
                        "staleness budget"),
        # the root-cause loop's paging signal: when straggler steps blow
        # this budget, the page arrives pre-annotated with culprit
        # kernels by the installed ProfileTrigger (see
        # docs/migration.md "The root-cause loop")
        SloSpec.ratio(
            "StepAnomalyRatio", "steps/anomalies", "steps/total",
            objective=0.99,
            description="straggler-step ratio within budget"),
    ]
    if step_time_ms is not None:
        specs.append(SloSpec.latency(
            "TrainStepTime", "steps/wall_ms", step_time_ms,
            objective=0.99,
            description="training step wall-time p99 under budget"))
    if mfu_floor is not None:
        specs.append(SloSpec.floor(
            "MfuFloor", "perf/mfu", mfu_floor, objective=0.99,
            description="model FLOPs utilization above floor"))
    return specs
