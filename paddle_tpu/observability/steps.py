"""Per-step profiler: one structured record per Executor.run dispatch.

The reference framework's profiler emitted one RecordEvent per op; a
jit-compiled executor's natural grain is the *step* — one device
dispatch of the fused program. `StepProfiler.record()` is called by the
executor after every dispatch with the wall time and identity of the
step; the profiler enriches the record with whatever the rest of the
runtime already published to the registry (dataio h2d time and prefetch
queue depth when a DeviceLoader is attached, last fetch wait, device
memory in use), keeps a rolling window for ``/debug/steps``, forwards
each record to the flight recorder's ring, and runs a straggler
detector over it.

Straggler detection is median/MAD (median absolute deviation): robust
to the long right tail of step times, no assumption of normality, and
immune to the detector's own anomalies polluting the baseline the way a
mean/stddev would. Baselines are kept per (program, signature) stream
so interleaving train/eval programs cannot trip false positives on each
other. A step is anomalous when it exceeds
``median + k * 1.4826 * MAD`` (k=6) *and* 1.5x the median (guards the
near-zero-MAD case where every step is metronome-identical). Compile
steps are excluded from the baseline; a compile arriving after the
stream was steady is itself flagged (``reason="recompile"``) since a
mid-run recompile is the other classic straggler source. Anomalies
increment ``steps/anomalies{reason=...}`` and log one structured
warning line naming the step and its deviation.

Window size: ``PDTPU_STEP_WINDOW`` (default 512).

Environment sampling is rate-limited: gauge reads are cheap but
``device_memory_stats`` is a runtime call, and at deepfm's ~1 ms steps
sampling on every dispatch measurably slowed the hot loop (BENCH_r05's
0.957x regression vs r04). One dispatch in ``PDTPU_STEP_SAMPLE_EVERY``
(default 16) takes a fresh sample; the others stamp the cached values,
so every record still carries the environment fields at the cost of up
to 15 dispatches of staleness. The first record after construction or
``reset()`` always samples fresh.
"""
from __future__ import annotations

import collections
import logging
import os
import threading
import time
from typing import Deque, Dict, Optional

from .flight import get_flight_recorder
from .registry import Registry, get_registry
from .tracer import get_tracer

__all__ = ["StepProfiler", "get_step_profiler"]

logger = logging.getLogger("paddle_tpu.observability.steps")

# Detector constants: 1.4826 scales MAD to a stddev-equivalent for a
# normal distribution; k=6 ~ "six sigma" on the robust scale.
_MAD_TO_SIGMA = 1.4826
_MAX_STREAMS = 64  # bound the per-(program, sig) baseline table


class StepProfiler:
    """Rolling window of step records + median/MAD straggler detector."""

    def __init__(self, window: Optional[int] = None, k: float = 6.0,
                 min_samples: int = 20,
                 registry: Optional[Registry] = None):
        if window is None:
            window = int(os.environ.get("PDTPU_STEP_WINDOW", "512"))
        window = max(8, int(window))
        self.k = float(k)
        self.min_samples = int(min_samples)
        self._reg = registry if registry is not None else get_registry()
        self._lock = threading.Lock()
        self._records: Deque[dict] = collections.deque(maxlen=window)
        # steady (non-compile) wall_ms per (program, sig) stream
        self._baselines: "collections.OrderedDict[tuple, Deque[float]]" = \
            collections.OrderedDict()
        self._step = 0
        self._sample_every = max(
            1, int(os.environ.get("PDTPU_STEP_SAMPLE_EVERY", "16")))
        self._sample_tick = 0
        self._env_cache: dict = {}
        # subscription points (ProfileTrigger): called OUTSIDE the lock
        self._listeners: list = []
        self._anomaly_listeners: list = []

    def add_listener(self, fn) -> "StepProfiler":
        """Call ``fn(rec)`` after every record (outside the lock).
        Listener exceptions are swallowed — observability plumbing must
        not kill the hot loop."""
        self._listeners.append(fn)
        return self

    def add_anomaly_listener(self, fn) -> "StepProfiler":
        """Call ``fn(rec, reason)`` on every slow_step/recompile anomaly
        (outside the lock; exceptions swallowed) — the ProfileTrigger's
        arming signal."""
        self._anomaly_listeners.append(fn)
        return self

    def remove_listener(self, fn) -> "StepProfiler":
        """Detach `fn` from both listener lists (missing is fine) — the
        teardown half of add_listener/add_anomaly_listener for harnesses
        that wire a ProfileTrigger temporarily."""
        for lst in (self._listeners, self._anomaly_listeners):
            while fn in lst:
                lst.remove(fn)
        return self

    # -- environment sampling ---------------------------------------------
    def _sample_environment(self, rec: dict) -> None:
        """Pull dataio / fetch / memory context other layers already
        published. A fresh sample runs once per `_sample_every` records
        (the tick is a plain int — a rare racy double-sample is harmless);
        in between, records get the cached fields, keeping the hot-loop
        cost O(1) dict-update."""
        tick = self._sample_tick
        self._sample_tick = tick + 1
        if tick % self._sample_every:
            rec.update(self._env_cache)
            return
        env: dict = {}
        self._sample_fresh(env)
        self._env_cache = env
        rec.update(env)

    def _sample_fresh(self, rec: dict) -> None:
        reg = self._reg
        try:
            if reg.counter("dataio/batches").value > 0:
                rec["queue_depth"] = int(
                    reg.gauge("dataio/prefetch_queue_depth").value)
                rec["h2d_ms"] = round(
                    reg.gauge("dataio/last_h2d_ms").value, 3)
            wait = reg.gauge("executor/last_fetch_wait_ms").value
            if wait > 0.0:
                rec["fetch_wait_ms"] = round(wait, 3)
        except Exception:
            pass
        try:
            from .memory import device_memory_stats
            stats = device_memory_stats()
            if stats and stats.get("bytes_in_use") is not None:
                rec["mem_bytes_in_use"] = int(stats["bytes_in_use"])
        except Exception:
            pass

    # -- recording ---------------------------------------------------------
    def record(self, wall_ms: float, *, program_id: Optional[int] = None,
               sig: Optional[str] = None, compiled: bool = False,
               steps: int = 1, sample_env: bool = True, **extra) -> dict:
        """Record one dispatch; returns the (possibly annotated) record.
        `compiled` marks a trace+compile dispatch (excluded from the
        straggler baseline); `steps` > 1 for run_batched dispatches."""
        rec: dict = {
            "t": round(time.time(), 3),
            "wall_ms": round(float(wall_ms), 3),
            "compile": bool(compiled),
        }
        if program_id is not None:
            rec["program"] = f"0x{program_id:x}"
        if sig is not None:
            rec["sig"] = sig
        if steps != 1:
            rec["steps_in_dispatch"] = int(steps)
        if extra:
            rec.update(extra)
        if sample_env:
            self._sample_environment(rec)
        if program_id is not None:
            # perf-attribution join: when the dispatched program has a
            # cost-ledger entry, the record gains achieved_tflops (and
            # the live perf/* gauges update) — so /debug/steps and
            # straggler anomalies carry utilization context. Lazy import:
            # perf depends only on registry/calibrate, never on steps.
            try:
                from . import perf
                perf.get_ledger().annotate_record(rec)
            except Exception:
                pass

        stream = (rec.get("program"), rec.get("sig"))
        anomaly = None
        with self._lock:
            self._step += 1
            rec["step"] = self._step
            base = self._baselines.get(stream)
            if base is None:
                base = collections.deque(maxlen=self._records.maxlen)
                self._baselines[stream] = base
                while len(self._baselines) > _MAX_STREAMS:
                    self._baselines.popitem(last=False)
            if compiled:
                if len(base) >= self.min_samples:
                    anomaly = ("recompile", None, None, None)
            else:
                if len(base) >= self.min_samples:
                    med, sigma = _median_sigma(base)
                    per_step = float(wall_ms) / max(1, int(steps))
                    if (per_step > med + self.k * sigma
                            and per_step > 1.5 * med):
                        dev = (per_step - med) / sigma if sigma > 0 else 0.0
                        anomaly = ("slow_step", med, sigma, dev)
                base.append(float(wall_ms) / max(1, int(steps)))
            if anomaly is not None:
                rec["anomaly"] = anomaly[0]
                if anomaly[3] is not None:
                    rec["deviation"] = round(anomaly[3], 1)
            self._records.append(rec)

        self._reg.counter("steps/total").inc()
        self._reg.histogram("steps/wall_ms").observe(float(wall_ms))
        if anomaly is not None:
            reason, med, sigma, dev = anomaly
            self._reg.counter("steps/anomalies", reason=reason).inc()
            if reason == "slow_step":
                msg = (f"slow step: step={rec['step']} "
                       f"wall_ms={rec['wall_ms']:.2f} "
                       f"median_ms={med:.2f} sigma_ms={sigma:.3f} "
                       f"deviation={dev:.1f}x "
                       f"program={rec.get('program', '?')} "
                       f"sig={rec.get('sig', '?')}")
            else:
                msg = (f"mid-run recompile: step={rec['step']} "
                       f"compile_ms={rec['wall_ms']:.2f} "
                       f"program={rec.get('program', '?')} "
                       f"sig={rec.get('sig', '?')} — feed shape/dtype "
                       f"drifted after a steady window")
            logger.warning(msg)
            get_flight_recorder().note_event("warning", msg,
                                             reason=reason,
                                             step=rec["step"])
            # instant event too: a merged fleet timeline shows WHERE the
            # straggler detector fired, not just that a counter moved
            iargs = {"reason": reason, "step": rec["step"],
                     "wall_ms": rec["wall_ms"]}
            if anomaly[3] is not None:
                iargs["deviation"] = round(anomaly[3], 1)
            get_tracer().instant(f"steps/{reason}", iargs)
        get_flight_recorder().note_step(rec)
        if anomaly is not None and self._anomaly_listeners:
            for fn in list(self._anomaly_listeners):
                try:
                    fn(rec, anomaly[0])
                except Exception:
                    pass
        if self._listeners:
            for fn in list(self._listeners):
                try:
                    fn(rec)
                except Exception:
                    pass
        return rec

    # -- reading -----------------------------------------------------------
    def records(self, n: Optional[int] = None) -> list:
        """Most recent records, oldest first (served at /debug/steps)."""
        with self._lock:
            out = list(self._records)
        return out[-int(n):] if n else out

    @property
    def step(self) -> int:
        with self._lock:
            return self._step

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._baselines.clear()
            self._step = 0
            self._sample_tick = 0
            self._env_cache = {}


def _median_sigma(samples) -> tuple:
    """(median, robust sigma) of the baseline window; sigma is floored
    at max(2% of median, 0.05ms) so a metronome-steady stream can't
    produce a hair-trigger threshold."""
    data = sorted(samples)
    med = _median(data)
    mad = _median(sorted(abs(x - med) for x in data))
    sigma = _MAD_TO_SIGMA * mad
    return med, max(sigma, 0.02 * med, 0.05)


def _median(sorted_data) -> float:
    n = len(sorted_data)
    mid = n // 2
    if n % 2:
        return float(sorted_data[mid])
    return (sorted_data[mid - 1] + sorted_data[mid]) / 2.0


_profiler = StepProfiler()


def get_step_profiler() -> StepProfiler:
    """THE process-wide step profiler the Executor records into."""
    return _profiler
