"""Host-side span tracer with chrome-trace export.

Reference analog: RecordEvent + DeviceTracer (platform/profiler.h:166,
device_tracer.cc) collected host/device event streams that
``tools/timeline.py`` converted to chrome://tracing JSON. Device-side
tracing belongs to jax.profiler (XPlane); this module is the HOST side:
wall-clock spans recorded per thread with proper nesting, exported as
chrome-trace JSON that loads directly in chrome://tracing or
https://ui.perfetto.dev — and mergeable with a converted XPlane trace via
``python -m paddle_tpu.tools.timeline``.

Usage::

    from paddle_tpu.observability import trace_span, get_tracer

    with trace_span("train/step", step=i):
        ...

    @trace_span("load_batch")
    def load_batch(...): ...

    get_tracer().export_chrome_trace("host_trace.json")

Spans are recorded as B/E (begin/end) event pairs, which chrome-trace
nests by timestamp per thread — the context-manager protocol guarantees
every B gets its E even when the body raises. Overhead per span is one
``perf_counter`` call and one lock-protected list append at each end;
when the tracer is disabled (``get_tracer().enabled = False``) a span is
a no-op.

Distributed traces: when a `context.TraceContext` is active on the
thread, `trace_span` derives a child context for its duration and stamps
``trace_id``/``span_id``/``parent_id`` into the span's args — the keys
``tools/timeline.py --fleet`` uses to stitch per-process traces into one
timeline. With no active context the span records exactly as before
(zero id-generation cost on untraced hot paths). `start_trace` roots a
new trace (used by the fleet router per routed request and the PS tier
per training step); `server_span` adopts an incoming RPC ``"trace"``
header on the serving side.
"""
from __future__ import annotations

import functools
import json
import os
import threading
import time
from typing import Dict, List, Optional

from . import context as _ctx

__all__ = ["Tracer", "get_tracer", "trace_span", "start_trace",
           "server_span"]

# one process-wide timebase so spans from every thread share a clock;
# chrome trace wants microseconds
_T0 = time.perf_counter()


def _now_us() -> float:
    return (time.perf_counter() - _T0) * 1e6


class Tracer:
    """Collects completed span events; bounded so an unobserved long-running
    process cannot grow without limit (past `max_events` new events are
    dropped and counted in `dropped`)."""

    def __init__(self, max_events: int = 200_000):
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._thread_names: Dict[int, str] = {}
        self.max_events = int(max_events)
        self.dropped = 0
        self.enabled = True
        # shows as the track title in merged fleet timelines; worker /
        # pserver entrypoints set their role here
        self.process_name = "paddle_tpu host"

    # -- recording ---------------------------------------------------------
    def _emit(self, ev: dict) -> None:
        tid = ev["tid"]
        with self._lock:
            if tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(ev)

    def begin(self, name: str, args: Optional[dict] = None) -> None:
        ev = {"name": name, "ph": "B", "ts": _now_us(),
              "pid": os.getpid(), "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._emit(ev)

    def end(self, name: str) -> None:
        self._emit({"name": name, "ph": "E", "ts": _now_us(),
                    "pid": os.getpid(), "tid": threading.get_ident()})

    def instant(self, name: str, args: Optional[dict] = None) -> None:
        """One timestamped marker (chrome-trace 'i' event)."""
        if not self.enabled:
            return
        ev = {"name": name, "ph": "i", "s": "t", "ts": _now_us(),
              "pid": os.getpid(), "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._emit(ev)

    # -- export ------------------------------------------------------------
    def export_chrome_trace(self, path: Optional[str] = None) -> dict:
        """Chrome-trace JSON object ({"traceEvents": [...]}); written to
        `path` when given. Loadable in chrome://tracing and Perfetto."""
        with self._lock:
            events = list(self._events)
            names = dict(self._thread_names)
        pid = os.getpid()
        meta: List[dict] = [
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": self.process_name}}]
        for tid, tname in sorted(names.items()):
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": tname}})
        trace = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
        if path is not None:
            with open(path, "w") as f:
                json.dump(trace, f)
        return trace

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._thread_names.clear()
            self.dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


_tracer = Tracer()


def get_tracer() -> Tracer:
    """The process-wide host tracer every `trace_span` records into."""
    return _tracer


class trace_span:
    """Record one named wall-clock span: context manager AND decorator.

    ::

        with trace_span("executor/compile", sig=digest):
            ...

        @trace_span("serve")          # span per call, named "serve"
        def serve(...): ...

    Keyword arguments become chrome-trace `args` (visible on click in the
    trace viewer). Spans nest naturally per thread; the end event is
    emitted even when the body raises.

    When a distributed `TraceContext` is active on the thread, the span
    becomes a child span of it: a derived context is activated for the
    span's duration and its ids are stamped into the args.
    """

    __slots__ = ("name", "args", "_entered", "_ctx_token")

    def __init__(self, name: str, **args):
        self.name = name
        self.args = args or None
        self._entered = False
        self._ctx_token = None

    def _span_ctx(self):
        """The context this span should record under, or None. Overridden
        by the rooting/adopting subclasses."""
        parent = _ctx.current()
        return parent.child() if parent is not None else None

    def __enter__(self):
        t = _tracer
        if t.enabled:
            ctx = self._span_ctx()
            args = self.args
            if ctx is not None:
                self._ctx_token = _ctx._activate(ctx)
                args = dict(args) if args else {}
                args.update(ctx.args())
            self._entered = True
            t.begin(self.name, args)
        return self

    def __exit__(self, *exc):
        if self._entered:
            self._entered = False
            _tracer.end(self.name)
        if self._ctx_token is not None:
            _ctx._restore(self._ctx_token)
            self._ctx_token = None
        return False

    def __call__(self, fn):
        name, args = self.name, self.args or {}
        cls = type(self)

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with cls(name, **args):
                return fn(*a, **kw)

        return wrapper


class start_trace(trace_span):
    """Root span of a new distributed trace: activates a fresh
    `TraceContext` (new trace_id, no parent) for the span's duration, so
    everything beneath it — nested spans, RPCs to pservers and fleet
    workers, their server-side spans — shares one trace_id. If a trace
    is already active this degrades to a plain child `trace_span`
    (nested roots don't fork the trace)."""

    __slots__ = ()

    def _span_ctx(self):
        parent = _ctx.current()
        return parent.child() if parent is not None else _ctx.new_trace()


class server_span(trace_span):
    """Server-side RPC span: adopts the ``"trace"`` header dict from an
    incoming frame (see `context.from_wire`), parenting this process's
    span to the client's RPC span. With no/malformed header it records
    as a plain local span.

    ::

        with server_span(f"ps/{op}", msg.get("trace"), op=op):
            out = dispatch(op, msg)
    """

    __slots__ = ("_wire",)

    def __init__(self, name: str, wire, **args):
        super().__init__(name, **args)
        self._wire = wire

    def _span_ctx(self):
        ctx = _ctx.from_wire(self._wire)
        if ctx is None:
            return super()._span_ctx()
        return ctx
