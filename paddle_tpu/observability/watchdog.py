"""Executor recompile watchdog: detect and diagnose recompilation storms.

A define-then-run XLA executor compiles one executable per (program,
feed signature). The dominant hidden cost in production is a feed whose
shape or dtype drifts — every step then pays a full trace+compile
(seconds) instead of a cache hit (microseconds), and nothing in the
output says why. The reference framework never had this failure mode
(op-by-op executors don't compile), which is exactly why a TPU port
needs a watchdog for it.

`RecompileWatchdog.record_compile(key, feed_sig)` is called by the
executor on every executable-cache miss. When one program key has
compiled more than `threshold` times, a single `RecompileWarning` is
emitted that names the exact feed keys whose shape/dtype diverged
between the previous and the new signature — the actionable part
("pad/bucket feed 'x'") rather than just "slow".

Threshold default is 8, overridable with PDTPU_RECOMPILE_THRESHOLD (0
disables the warning; compiles are still counted in the registry).
"""
from __future__ import annotations

import os
import threading
import warnings
from typing import Dict, List, Optional

__all__ = ["RecompileWarning", "RecompileWatchdog", "get_watchdog"]


class RecompileWarning(UserWarning):
    """One program recompiled beyond the watchdog threshold."""


def _sig_dict(feed_sig) -> dict:
    """feed_signature tuple of (name, shape, dtype) -> {name: (shape, dtype)}."""
    return {name: (shape, dtype) for name, shape, dtype in feed_sig}


def diff_signatures(prev, new) -> List[str]:
    """Human-readable list of diverging feed keys between two
    `core.executor.feed_signature` tuples."""
    a, b = _sig_dict(prev), _sig_dict(new)
    out: List[str] = []
    for name in sorted(set(a) | set(b)):
        if name not in b:
            out.append(f"feed {name!r} removed (was "
                       f"shape={a[name][0]} dtype={a[name][1]})")
        elif name not in a:
            out.append(f"feed {name!r} added "
                       f"(shape={b[name][0]} dtype={b[name][1]})")
        elif a[name] != b[name]:
            (ash, adt), (bsh, bdt) = a[name], b[name]
            parts = []
            if ash != bsh:
                parts.append(f"shape {ash} -> {bsh}")
            if adt != bdt:
                parts.append(f"dtype {adt} -> {bdt}")
            out.append(f"feed {name!r} changed " + ", ".join(parts))
    return out


class _Entry:
    __slots__ = ("count", "last_sig", "warned", "diverging")

    def __init__(self):
        self.count = 0
        self.last_sig = None
        self.warned = False
        self.diverging: Dict[str, int] = {}  # feed key -> times it diverged


class RecompileWatchdog:
    """Per-program compile counting + signature-diff diagnosis."""

    def __init__(self, threshold: Optional[int] = None):
        if threshold is None:
            threshold = int(os.environ.get("PDTPU_RECOMPILE_THRESHOLD", "8"))
        self.threshold = threshold
        self._lock = threading.Lock()
        self._entries: Dict[object, _Entry] = {}

    def record_compile(self, key, feed_sig, label: str = "program") -> bool:
        """Count one executable compile for program `key` with `feed_sig`.
        Returns True the first time `key` is seen (so the caller can hook
        lifetime cleanup, e.g. weakref.finalize -> `forget`). Emits ONE
        RecompileWarning per key once compiles exceed the threshold."""
        with self._lock:
            ent = self._entries.get(key)
            fresh = ent is None
            if fresh:
                ent = self._entries[key] = _Entry()
            ent.count += 1
            diag: List[str] = []
            if ent.last_sig is not None and ent.last_sig != feed_sig:
                diag = diff_signatures(ent.last_sig, feed_sig)
                for name in _diverging_names(ent.last_sig, feed_sig):
                    ent.diverging[name] = ent.diverging.get(name, 0) + 1
            prev_sig = ent.last_sig
            ent.last_sig = feed_sig
            warn_now = (self.threshold > 0 and not ent.warned
                        and ent.count > self.threshold)
            if warn_now:
                ent.warned = True
                count = ent.count
                hot = sorted(ent.diverging.items(), key=lambda kv: -kv[1])
        if warn_now:
            detail = ("; ".join(diag) if diag else
                      "signature identical to the previous compile — the "
                      "recompiles come from program/fetch changes, not feeds")
            hot_txt = ("" if not hot else
                       " Most-diverging feeds so far: "
                       + ", ".join(f"{n!r} ({c}x)" for n, c in hot[:3]) + ".")
            warnings.warn(RecompileWarning(
                f"{label} recompiled {count} times (threshold "
                f"{self.threshold}) — every compile costs a full XLA "
                f"trace+compile. Last change: {detail}.{hot_txt} Pad or "
                f"bucket the offending feeds to a fixed set of shapes "
                f"(see reader.bucket_by_sequence_length / serving "
                f"batch buckets)."), stacklevel=3)
        return fresh

    def compile_count(self, key) -> int:
        with self._lock:
            ent = self._entries.get(key)
            return ent.count if ent is not None else 0

    def state(self) -> dict:
        """JSON-safe view of every tracked program (flight-dump section):
        compile count, warned flag, and per-feed divergence counts."""
        with self._lock:
            return {
                "threshold": self.threshold,
                "programs": [
                    {"key": repr(key)[:200],
                     "compiles": ent.count,
                     "warned": ent.warned,
                     "diverging_feeds": dict(ent.diverging)}
                    for key, ent in self._entries.items()
                ],
            }

    def forget(self, key) -> None:
        """Drop a program's entry (hooked to program GC by the executor so
        a recycled id() cannot inherit a dead program's compile count)."""
        with self._lock:
            self._entries.pop(key, None)

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()


def _diverging_names(prev, new) -> List[str]:
    a, b = _sig_dict(prev), _sig_dict(new)
    return [n for n in set(a) | set(b) if a.get(n) != b.get(n)]


_watchdog = RecompileWatchdog()


def get_watchdog() -> RecompileWatchdog:
    """The process-wide watchdog the Executor reports compiles to."""
    return _watchdog
