"""TPU-native operator library.

Reference analog: ``paddle/fluid/operators/`` (~505 REGISTER_OPERATOR sites,
SURVEY §2.1). Each module registers pure-JAX implementations into the op
registry; XLA owns kernels, fusion, and layout — there is no per-device kernel
variant dimension (the CPU/CUDA/MKLDNN kernel axis of op_registry.h collapses).

Importing this package registers every op.
"""
from . import (  # noqa: F401
    activation_ops,
    beam_ops,
    collective_ops,
    compare_ops,
    control_flow_ops,
    coverage_ops,
    crf_ops,
    deferred_rows,
    detection_ops,
    framework_ops,
    fused_ops,
    fusion_ops,
    math_ops,
    metric_ops,
    misc_ops,
    moe_ops,
    nn_ops,
    optimizer_ops,
    parity_ops,
    pipeline_ops,
    quant_ops,
    reduce_ops,
    rnn_ops,
    sampled_ops,
    sequence_ops,
    tensor_ops,
    vision_ops,
)
from .eager import call as eager_call  # noqa: F401
