"""Activation ops.

Reference analog: ``paddle/fluid/operators/activation_op.cc`` (~30 activations
registered through a functor table). All map to VPU element-wise code via XLA;
grads come from jax.vjp instead of hand-written GradFunctors.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import one


def _act(name, fn):
    @register_op(name)
    def _impl(ctx, inputs, attrs, _fn=fn):
        (x,) = inputs["X"]
        return one(_fn(x, attrs))
    return _impl


_act("relu", lambda x, a: jax.nn.relu(x))
_act("sigmoid", lambda x, a: jax.nn.sigmoid(x))
_act("tanh", lambda x, a: jnp.tanh(x))
_act("softplus", lambda x, a: jax.nn.softplus(x))
_act("softsign", lambda x, a: jax.nn.soft_sign(x))
_act("relu6", lambda x, a: jnp.clip(x, 0.0, a.get("threshold", 6.0)))
_act("leaky_relu", lambda x, a: jax.nn.leaky_relu(x, a.get("alpha", 0.02)))
_act("elu", lambda x, a: jax.nn.elu(x, a.get("alpha", 1.0)))
_act("gelu", lambda x, a: jax.nn.gelu(x, approximate=a.get("approximate", False)))
_act("swish", lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x))
_act("hard_swish", lambda x, a: x * jnp.clip(
    x + a.get("offset", 3.0), 0.0, a.get("threshold", 6.0)) / a.get("scale", 6.0))
_act("hard_sigmoid", lambda x, a: jnp.clip(a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0))
_act("logsigmoid", lambda x, a: jax.nn.log_sigmoid(x))
_act("tanh_shrink", lambda x, a: x - jnp.tanh(x))
_act("softshrink", lambda x, a: jnp.where(
    x > a.get("lambda", 0.5), x - a.get("lambda", 0.5),
    jnp.where(x < -a.get("lambda", 0.5), x + a.get("lambda", 0.5), 0.0)))
_act("hard_shrink", lambda x, a: jnp.where(jnp.abs(x) > a.get("threshold", 0.5), x, 0.0))
_act("thresholded_relu", lambda x, a: jnp.where(x > a.get("threshold", 1.0), x, 0.0))
_act("stanh", lambda x, a: a.get("scale_b", 1.7159) * jnp.tanh(a.get("scale_a", 0.67) * x))
_act("mish", lambda x, a: x * jnp.tanh(jax.nn.softplus(x)))
_act("silu", lambda x, a: jax.nn.silu(x))
_act("exp_act", lambda x, a: jnp.exp(x))


@register_op("prelu")
def _prelu(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (alpha,) = inputs["Alpha"]
    mode = attrs.get("mode", "all")
    if mode == "channel" and alpha.ndim == 1:
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    return one(jnp.where(x > 0, x, alpha * x))


@register_op("softmax")
def _softmax(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jax.nn.softmax(x, axis=attrs.get("axis", -1)))


@register_op("log_softmax")
def _log_softmax(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jax.nn.log_softmax(x, axis=attrs.get("axis", -1)))


@register_op("maxout")
def _maxout(ctx, inputs, attrs):
    (x,) = inputs["X"]
    groups = attrs["groups"]
    n, c = x.shape[0], x.shape[1]
    rest = x.shape[2:]
    return one(jnp.max(x.reshape((n, c // groups, groups) + rest), axis=2))
