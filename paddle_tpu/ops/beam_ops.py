"""Beam-search ops — per-step expansion and final backtrack decode.

Reference analog: ``paddle/fluid/operators/beam_search_op.cc`` (one step:
expand candidates, prune to beam width, LoD bookkeeping for parent links) and
``beam_search_decode_op.cc`` (walk sentence trees backwards to emit token
sequences). The reference threads beams through LoD levels; the TPU-native
redesign keeps dense ``[batch, beam, ...]`` tensors with parent indices
stored per step — static shapes, gather/top_k on device, usable inside
`lax.while_loop` decoding loops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import opt_input

NEG = -1e9


@register_op("beam_search", differentiable=False)
def _beam_search(ctx, inputs, attrs):
    """One expansion step.

    inputs: Scores [batch, beam, vocab] log-probs of this step,
            PreScores [batch, beam] accumulated log-probs,
            PreFinished [batch, beam] (0/1) — finished beams only propagate
            their end-token continuation (reference prunes them via LoD).
    attrs: beam_size, end_id.
    outputs: SelectedIds [batch, beam] int64, SelectedScores [batch, beam]
             accumulated, ParentIdx [batch, beam] int64 (which previous beam
             each selected candidate extends), Finished [batch, beam].
    """
    (scores,) = inputs["Scores"]
    (pre_scores,) = inputs["PreScores"]
    pre_fin = opt_input(inputs, "PreFinished")
    beam = attrs["beam_size"]
    end_id = attrs["end_id"]

    batch, cur_beam, vocab = scores.shape
    if pre_fin is None:
        pre_fin = jnp.zeros((batch, cur_beam), bool)
    else:
        pre_fin = pre_fin.astype(bool)

    # Finished beams: force the only continuation to be end_id with score 0
    # (so the accumulated score is carried unchanged).
    fin_row = jnp.full((vocab,), NEG, scores.dtype).at[end_id].set(0.0)
    step = jnp.where(pre_fin[..., None], fin_row[None, None, :], scores)
    total = pre_scores[..., None] + step                      # [b, cur, V]

    flat = total.reshape(batch, cur_beam * vocab)
    top_scores, top_idx = lax.top_k(flat, beam)               # [b, beam]
    parent = (top_idx // vocab).astype(jnp.int64)
    ids = (top_idx % vocab).astype(jnp.int64)
    finished = jnp.take_along_axis(pre_fin, parent, axis=1) | (ids == end_id)
    return {"SelectedIds": [ids], "SelectedScores": [top_scores],
            "ParentIdx": [parent], "Finished": [finished]}


@register_op("beam_search_decode", differentiable=False)
def _beam_search_decode(ctx, inputs, attrs):
    """Backtrack stored steps into token sequences.

    inputs: Ids [T, batch, beam] int64 selected ids per step,
            ParentIdx [T, batch, beam] int64,
            Scores [batch, beam] final accumulated scores.
    outputs: SentenceIds [batch, beam, T] (tokens after each beam's path is
             followed back; positions past end_id keep end_id),
             SentenceScores [batch, beam].
    """
    (ids,) = inputs["Ids"]
    (parents,) = inputs["ParentIdx"]
    (scores,) = inputs["Scores"]
    T, batch, beam = ids.shape

    def back(cursor, step):
        step_ids, step_parents = step                        # [b, beam]
        tok = jnp.take_along_axis(step_ids, cursor, axis=1)
        prev = jnp.take_along_axis(step_parents, cursor, axis=1)
        return prev, tok

    init = jnp.tile(jnp.arange(beam, dtype=jnp.int64)[None, :], (batch, 1))
    _, toks = lax.scan(back, init, (ids, parents), reverse=True)
    sentences = jnp.transpose(toks, (1, 2, 0))               # [b, beam, T]
    return {"SentenceIds": [sentences], "SentenceScores": [scores]}
