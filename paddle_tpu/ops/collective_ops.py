"""Collective ops (`c_*` family).

Reference analog: ``paddle/fluid/operators/collective/`` — c_allreduce_{sum,
max,min,prod}, c_broadcast, c_allgather, c_reducescatter, c_comm_init,
c_gen_nccl_id, c_sync_*_stream (each with a `ring_id` selecting an NCCL comm).

TPU-native redesign: collectives are XLA ICI primitives (psum/all_gather/
ppermute) bound to *named mesh axes* instead of NCCL rings — `ring_id` maps to
an axis name. Inside a pjit/GSPMD program these ops only make sense under
shard_map (per-device code); at the graph level GSPMD inserts collectives
automatically from shardings, so these ops are mainly used by the shard_map-
based parallel library (paddle_tpu.parallel). When no mesh axis is bound
(single-device trace) they are identity, matching single-process reference
behavior. c_comm_init/c_gen_nccl_id have no equivalent: `jax.distributed`
bootstraps multi-host.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import one


def _axis(ctx, attrs):
    """Resolve the mesh axis for a collective: explicit attr wins, else the
    ring_id indexes ctx.mesh axis names (ring 0 → first axis)."""
    name = attrs.get("axis_name")
    if name:
        return name
    ring = attrs.get("ring_id", 0)
    if ctx.mesh is not None and len(ctx.mesh.axis_names) > ring:
        return ctx.mesh.axis_names[ring]
    return None


def _in_shard_map(axis):
    if axis is None:
        return False
    try:
        lax.axis_index(axis)
        return True
    except (NameError, Exception):
        return False


def _collective(name, fn):
    @register_op(name, differentiable=False)
    def _impl(ctx, inputs, attrs, _fn=fn):
        (x,) = inputs["X"]
        axis = _axis(ctx, attrs)
        if axis is None or not _in_shard_map(axis):
            return one(x)  # single-device / GSPMD context: identity
        return one(_fn(x, axis))
    return _impl


_collective("c_allreduce_sum", lambda x, a: lax.psum(x, a))
_collective("c_allreduce_max", lambda x, a: lax.pmax(x, a))
_collective("c_allreduce_min", lambda x, a: lax.pmin(x, a))
_collective("c_allreduce_prod", lambda x, a: jnp.exp(lax.psum(jnp.log(x), a)))
_collective("allreduce", lambda x, a: lax.psum(x, a))
_collective("c_allgather", lambda x, a: lax.all_gather(x, a, tiled=True))
_collective("c_reducescatter", lambda x, a: lax.psum_scatter(x, a, tiled=True))


@register_op("c_broadcast", differentiable=False)
def _c_broadcast(ctx, inputs, attrs):
    (x,) = inputs["X"]
    axis = _axis(ctx, attrs)
    if axis is None or not _in_shard_map(axis):
        return one(x)
    root = attrs.get("root", 0)
    idx = lax.axis_index(axis)
    size = lax.axis_size(axis) if hasattr(lax, "axis_size") else lax.psum(1, axis)
    src = jnp.where(idx == root, x, jnp.zeros_like(x))
    return one(lax.psum(src, axis))


@register_op("c_sync_calc_stream", differentiable=False)
def _c_sync_calc(ctx, inputs, attrs):
    return one(inputs["X"][0])  # XLA orders ops by data deps; no streams


@register_op("c_sync_comm_stream", differentiable=False)
def _c_sync_comm(ctx, inputs, attrs):
    return one(inputs["X"][0])
