"""Shared helpers for op implementations."""
from __future__ import annotations

import jax.numpy as jnp


def bcast_y(x, y, axis: int = -1):
    """Paddle elementwise broadcast rule (operators/elementwise/
    elementwise_op_function.h): `y`'s shape is aligned to `x` starting at
    `axis`; axis==-1 means align trailing dims (numpy rule)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if x.ndim == y.ndim or y.ndim == 0:
        return y
    if axis == -1:
        axis = x.ndim - y.ndim
    new_shape = (1,) * axis + y.shape + (1,) * (x.ndim - axis - y.ndim)
    return y.reshape(new_shape)


def one(outs):
    return {"Out": [outs]}


def opt_input(inputs, slot):
    """Optional input slot: missing key or empty list -> None."""
    vs = inputs.get(slot) or [None]
    return vs[0]


def length_mask(length, B, T, dtype):
    """Padded-sequence validity mask [B, T]: 1.0 where t < length[b].
    length=None means all positions valid (the padded+mask stand-in for the
    reference's LoD metadata)."""
    if length is None:
        return jnp.ones((B, T), dtype)
    return (jnp.arange(T)[None, :] < length.reshape(-1, 1)).astype(dtype)


# Shared activation-name → jax fn map (activation_op.cc functor registry).
# Used by fused ops, rnn cells, and fuse passes; "" / "identity" = no-op.
def _identity(x):
    return x


def act_map():
    import jax
    return {
        "": _identity,
        "identity": _identity,
        "relu": jax.nn.relu,
        "tanh": jnp.tanh,
        "sigmoid": jax.nn.sigmoid,
        "gelu": jax.nn.gelu,
    }
