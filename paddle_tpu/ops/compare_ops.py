"""Comparison + logical ops (reference operators/controlflow/compare_op.cc,
logical_op.cc, isfinite_op.cc)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op
from .common import bcast_y, one


def _cmp(name, fn):
    @register_op(name, differentiable=False)
    def _impl(ctx, inputs, attrs, _fn=fn):
        (x,) = inputs["X"]
        (y,) = inputs["Y"]
        return one(_fn(x, bcast_y(x, y, attrs.get("axis", -1))))
    return _impl


_cmp("equal", jnp.equal)
_cmp("not_equal", jnp.not_equal)
_cmp("less_than", jnp.less)
_cmp("less_equal", jnp.less_equal)
_cmp("greater_than", jnp.greater)
_cmp("greater_equal", jnp.greater_equal)
_cmp("logical_and", jnp.logical_and)
_cmp("logical_or", jnp.logical_or)
_cmp("logical_xor", jnp.logical_xor)


@register_op("logical_not", differentiable=False)
def _logical_not(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.logical_not(x))


@register_op("isfinite", differentiable=False)
def _isfinite(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.all(jnp.isfinite(x)))


@register_op("isinf", differentiable=False)
def _isinf(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.isinf(x))


@register_op("isnan", differentiable=False)
def _isnan(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.isnan(x))
