"""Control-flow ops.

Reference analog: ``paddle/fluid/operators/controlflow/`` (while_op.cc,
conditional_block_op.cc) and recurrent_op.cc — block-attribute ops interpreted
by the executor.

TPU-native redesign: data-dependent Python control flow cannot live inside a
traced program, so these lower to `lax.while_loop` / `lax.cond` / `lax.scan`
over sub-blocks lowered as pure functions. `static_rnn` (lax.scan) is the
differentiable path (reference StaticRNN); `while` is provided for parity and
is non-differentiable (as in most real uses: inference decoding loops).
"""
from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


def _lower_subblock(ctx, block, env_names: List[str]):
    """Build a pure fn: tuple(vals for env_names) -> same, by running block."""
    from ..core.executor import _run_block, ExecContext

    def fn(vals):
        env = dict(zip(env_names, vals))
        sub = ExecContext(None, is_test=ctx.is_test, mesh=ctx.mesh)
        _run_block(block, env, sub)
        return tuple(env[n] for n in env_names)

    return fn


@register_op("while",
             differentiable=lambda attrs: attrs.get("max_iters") is not None)
def _while(ctx, inputs, attrs):
    """while_op.cc parity. Two lowerings:

    - unbounded (no `max_iters`): lax.while_loop — data-dependent trip
      count, non-differentiable (inference decoding loops);
    - bounded (`max_iters=N`): a fixed-length lax.scan of masked updates —
      the loop body runs N times and each carried value only advances while
      the condition still holds. Reverse-mode differentiable, which is what
      gives the reference's WhileGradOp (while_op.cc) capability a
      TPU-native answer: trained dynamic decoders with a known bound.
    """
    block = attrs["sub_block"]
    loop_vars: List[str] = attrs["loop_vars"]
    cond_name: str = attrs["cond_name"]
    max_iters = attrs.get("max_iters")
    xs = inputs["X"]
    body = _lower_subblock(ctx, block, loop_vars)
    cond_idx = loop_vars.index(cond_name)

    if max_iters is None:
        def cond_fn(vals):
            return vals[cond_idx].reshape(()).astype(bool)

        out = lax.while_loop(cond_fn, lambda v: body(v), tuple(xs))
        return {"Out": list(out)}

    def step(vals, _):
        alive = vals[cond_idx].reshape(()).astype(bool)
        new = body(vals)
        merged = tuple(
            jnp.where(alive, n.astype(v.dtype) if hasattr(n, "astype") else n, v)
            for n, v in zip(new, vals))
        return merged, None

    out, _ = lax.scan(step, tuple(xs), None, length=int(max_iters))
    return {"Out": list(out)}


@register_op("conditional_block", differentiable=False)
def _conditional_block(ctx, inputs, attrs):
    """conditional_block_op.cc parity via lax.cond; both branches must produce
    the declared outputs (false branch passes through defaults)."""
    block = attrs["sub_block"]
    var_names: List[str] = attrs["var_names"]
    (cond,) = inputs["Cond"]
    xs = inputs["X"]
    body = _lower_subblock(ctx, block, var_names)
    out = lax.cond(cond.reshape(()).astype(bool), body, lambda v: tuple(v), tuple(xs))
    return {"Out": list(out)}


@register_op("static_rnn")
def _static_rnn(ctx, inputs, attrs):
    """StaticRNN / recurrent_op.cc parity via lax.scan — differentiable.

    Sequence inputs are [B, T, ...] scanned over T; states carry across steps.
    attrs: sub_block, state_names (pre names), state_out_names (post names),
    seq_in_names, out_names (per-step outputs collected along T).
    """
    block = attrs["sub_block"]
    state_names = attrs["state_names"]
    state_out_names = attrs["state_out_names"]
    seq_in_names = attrs["seq_in_names"]
    out_names = attrs["out_names"]
    param_names = attrs.get("param_names", [])

    states = inputs["State"]
    seqs = inputs["Seq"]
    params = inputs.get("Param", [])

    from ..core.executor import _run_block, ExecContext

    def step(carry, xt):
        env = dict(zip(state_names, carry))
        env.update(zip(seq_in_names, xt))
        env.update(zip(param_names, params))
        sub = ExecContext(None, is_test=ctx.is_test, mesh=ctx.mesh)
        _run_block(block, env, sub)
        new_carry = tuple(env[n] for n in state_out_names)
        ys = tuple(env[n] for n in out_names)
        return new_carry, ys

    seqs_tfirst = tuple(jnp.swapaxes(s, 0, 1) for s in seqs)
    final_states, ys = lax.scan(step, tuple(states), seqs_tfirst)
    outs = [jnp.swapaxes(y, 0, 1) for y in ys]
    return {"Out": outs, "FinalState": list(final_states)}


@register_op("cond", differentiable=False)
def _cond(ctx, inputs, attrs):
    """Two-branch functional cond (paddle 2.x layers.cond capability;
    reference expresses it as paired conditional_block ops). Each branch is a
    sub-block lowered to a pure fn over its own captured environment; both
    must produce the same number/shape of outputs (lax.cond contract)."""
    (pred,) = inputs["Pred"]
    t_in = inputs.get("TrueIn", [])
    f_in = inputs.get("FalseIn", [])
    tb, fb = attrs["true_block"], attrs["false_block"]
    t_env, f_env = attrs["true_env_names"], attrs["false_env_names"]
    t_out, f_out = attrs["true_out_names"], attrs["false_out_names"]

    from ..core.executor import _run_block, ExecContext

    def mk(block, env_names, out_names, vals):
        def fn(_):
            env = dict(zip(env_names, vals))
            sub = ExecContext(None, is_test=ctx.is_test, mesh=ctx.mesh)
            _run_block(block, env, sub)
            return tuple(env[n] for n in out_names)
        return fn

    out = lax.cond(pred.reshape(()).astype(bool),
                   mk(tb, t_env, t_out, t_in), mk(fb, f_env, f_out, f_in),
                   operand=None)
    return {"Out": list(out)}


@register_op("switch", differentiable=False)
def _switch(ctx, inputs, attrs):
    """First-matching-case switch (layers/control_flow.py Switch parity —
    the lr-schedule workhorse). Cases + optional default are sub-blocks that
    write a shared carried var set; lowered to lax.switch on the index of the
    first true condition."""
    conds = inputs["Conds"]
    xs = inputs["X"]
    case_blocks = attrs["case_blocks"]
    default_block = attrs.get("default_block")
    var_names = attrs["var_names"]

    from ..core.executor import _run_block, ExecContext

    def mk(block):
        def fn(vals):
            if block is None:
                return tuple(vals)
            env = dict(zip(var_names, vals))
            sub = ExecContext(None, is_test=ctx.is_test, mesh=ctx.mesh)
            _run_block(block, env, sub)
            return tuple(env[n] for n in var_names)
        return fn

    branches = [mk(b) for b in case_blocks] + [mk(default_block)]
    flags = jnp.stack([c.reshape(()).astype(bool) for c in conds])
    first = jnp.argmax(flags)                       # first True (or 0)
    idx = jnp.where(flags.any(), first, len(case_blocks))
    out = lax.switch(idx, branches, tuple(xs))
    return {"Out": list(out)}


@register_op("select")
def _select(ctx, inputs, attrs):
    """Rowwise/elementwise select (IfElse merge): Out = where(Cond, X, Y).
    Cond broadcasts from [B,1] over trailing dims."""
    (cond,) = inputs["Cond"]
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    c = cond.astype(bool)
    while c.ndim < x.ndim:
        c = c[..., None]
    # collapse trailing singleton mismatch ([B,1] vs [B,D])
    c = jnp.broadcast_to(c, x.shape)
    return {"Out": [jnp.where(c, x, y)]}


# ---- tensor-array ops (LoDTensorArray capability, dense redesign) --------
# Reference: lod_tensor_array ops (array_write/read, lod_array_length,
# controlflow/while users). XLA needs static shapes, so an "array" is a
# preallocated [max_len, ...] buffer var plus an int64 length scalar,
# updated via dynamic_update_slice — usable inside while loops.

@register_op("array_write", nondiff_inputs=["I", "Length"])
def _array_write(ctx, inputs, attrs):
    (arr,) = inputs["Array"]
    (i,) = inputs["I"]
    (x,) = inputs["X"]
    (n,) = inputs["Length"]
    idx = i.reshape(()).astype(jnp.int32)
    new = lax.dynamic_update_index_in_dim(arr, x.astype(arr.dtype), idx, 0)
    return {"Out": [new], "LengthOut": [jnp.maximum(n, (idx + 1).astype(n.dtype))]}


@register_op("array_read", nondiff_inputs=["I"])
def _array_read(ctx, inputs, attrs):
    (arr,) = inputs["Array"]
    (i,) = inputs["I"]
    idx = i.reshape(()).astype(jnp.int32)
    return {"Out": [lax.dynamic_index_in_dim(arr, idx, 0, keepdims=False)]}


@register_op("array_length", differentiable=False)
def _array_length(ctx, inputs, attrs):
    (n,) = inputs["Length"]
    return {"Out": [n.reshape((1,)).astype(jnp.int64)]}
