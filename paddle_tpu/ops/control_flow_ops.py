"""Control-flow ops.

Reference analog: ``paddle/fluid/operators/controlflow/`` (while_op.cc,
conditional_block_op.cc) and recurrent_op.cc — block-attribute ops interpreted
by the executor.

TPU-native redesign: data-dependent Python control flow cannot live inside a
traced program, so these lower to `lax.while_loop` / `lax.cond` / `lax.scan`
over sub-blocks lowered as pure functions. `static_rnn` (lax.scan) is the
differentiable path (reference StaticRNN); `while` is provided for parity and
is non-differentiable (as in most real uses: inference decoding loops).
"""
from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


def _lower_subblock(ctx, block, env_names: List[str]):
    """Build a pure fn: tuple(vals for env_names) -> same, by running block."""
    from ..core.executor import _run_block, ExecContext

    def fn(vals):
        env = dict(zip(env_names, vals))
        sub = ExecContext(None, is_test=ctx.is_test, mesh=ctx.mesh)
        _run_block(block, env, sub)
        return tuple(env[n] for n in env_names)

    return fn


@register_op("while", differentiable=False)
def _while(ctx, inputs, attrs):
    """while_op.cc parity via lax.while_loop. Carried vars are the declared
    loop vars (attr 'loop_vars'); Condition is a scalar bool var name."""
    block = attrs["sub_block"]
    loop_vars: List[str] = attrs["loop_vars"]
    cond_name: str = attrs["cond_name"]
    xs = inputs["X"]
    body = _lower_subblock(ctx, block, loop_vars)

    cond_idx = loop_vars.index(cond_name)

    def cond_fn(vals):
        return vals[cond_idx].reshape(()).astype(bool)

    out = lax.while_loop(cond_fn, lambda v: body(v), tuple(xs))
    return {"Out": list(out)}


@register_op("conditional_block", differentiable=False)
def _conditional_block(ctx, inputs, attrs):
    """conditional_block_op.cc parity via lax.cond; both branches must produce
    the declared outputs (false branch passes through defaults)."""
    block = attrs["sub_block"]
    var_names: List[str] = attrs["var_names"]
    (cond,) = inputs["Cond"]
    xs = inputs["X"]
    body = _lower_subblock(ctx, block, var_names)
    out = lax.cond(cond.reshape(()).astype(bool), body, lambda v: tuple(v), tuple(xs))
    return {"Out": list(out)}


@register_op("static_rnn")
def _static_rnn(ctx, inputs, attrs):
    """StaticRNN / recurrent_op.cc parity via lax.scan — differentiable.

    Sequence inputs are [B, T, ...] scanned over T; states carry across steps.
    attrs: sub_block, state_names (pre names), state_out_names (post names),
    seq_in_names, out_names (per-step outputs collected along T).
    """
    block = attrs["sub_block"]
    state_names = attrs["state_names"]
    state_out_names = attrs["state_out_names"]
    seq_in_names = attrs["seq_in_names"]
    out_names = attrs["out_names"]
    param_names = attrs.get("param_names", [])

    states = inputs["State"]
    seqs = inputs["Seq"]
    params = inputs.get("Param", [])

    from ..core.executor import _run_block, ExecContext

    def step(carry, xt):
        env = dict(zip(state_names, carry))
        env.update(zip(seq_in_names, xt))
        env.update(zip(param_names, params))
        sub = ExecContext(None, is_test=ctx.is_test, mesh=ctx.mesh)
        _run_block(block, env, sub)
        new_carry = tuple(env[n] for n in state_out_names)
        ys = tuple(env[n] for n in out_names)
        return new_carry, ys

    seqs_tfirst = tuple(jnp.swapaxes(s, 0, 1) for s in seqs)
    final_states, ys = lax.scan(step, tuple(states), seqs_tfirst)
    outs = [jnp.swapaxes(y, 0, 1) for y in ys]
    return {"Out": outs, "FinalState": list(final_states)}
