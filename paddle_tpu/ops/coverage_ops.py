"""Final coverage ops — the last reference op families without a kernel.

Reference analogs: brelu (activation_op.cc BRelu), pool_op.cc adaptive 3-D
path, chunk_eval_op.cc/.h (IOB-family chunk F1), hash_op.cc (multi-seed
mod-space hashing), unique_op.cc / unique_with_counts_op.cc,
scatter_nd_op (via scatter_nd_add on zeros), isfinite_op.cc variants
(has_inf / has_nan), fill_any_like (ones_like tensor.py).

TPU notes: unique is inherently dynamic-shaped in the reference; here the
output keeps the static input length with the tail padded by the first
unique value, plus an explicit `Count` scalar — the padded+length idiom
every LoD replacement in this build uses. chunk_eval computes span
boundaries with a reverse scan (next-end index per position) so chunk
matching is static-shape; hash uses a different (but deterministic)
integer mix than the reference's xxHash — same contract: stable ids in
[0, mod_by).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.registry import register_op
from .common import one


@register_op("brelu")
def _brelu(ctx, inputs, attrs):
    (x,) = inputs["X"]
    t_min = attrs.get("t_min", 0.0)
    t_max = attrs.get("t_max", 24.0)
    return one(jnp.clip(x, t_min, t_max))


@register_op("adaptive_pool3d")
def _adaptive_pool3d(ctx, inputs, attrs):
    (x,) = inputs["X"]
    ksize = attrs.get("pooling_size", attrs.get("ksize"))
    od, oh, ow = (ksize if isinstance(ksize, (list, tuple)) else [ksize] * 3)
    ptype = attrs.get("pooling_type", "avg")
    n, c, d, h, w = x.shape
    if d % od == 0 and h % oh == 0 and w % ow == 0:
        x6 = x.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
        return one(jnp.mean(x6, axis=(3, 5, 7)) if ptype == "avg"
                   else jnp.max(x6, axis=(3, 5, 7)))
    from .nn_ops import _adaptive_bins
    red = jnp.mean if ptype == "avg" else jnp.max
    planes = []
    for ds, de in _adaptive_bins(d, od):
        rows = []
        for hs, he in _adaptive_bins(h, oh):
            cols = [red(x[:, :, ds:de, hs:he, ws:we], axis=(2, 3, 4))
                    for ws, we in _adaptive_bins(w, ow)]
            rows.append(jnp.stack(cols, axis=-1))
        planes.append(jnp.stack(rows, axis=-2))
    return one(jnp.stack(planes, axis=-3))


@register_op("has_inf", differentiable=False)
def _has_inf(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.isinf(x).any().reshape(1))


@register_op("has_nan", differentiable=False)
def _has_nan(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.isnan(x).any().reshape(1))


@register_op("ones_like", differentiable=False)
def _ones_like(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return {"Out": [jnp.ones_like(x)]}


@register_op("scatter_nd", differentiable=False, nondiff_inputs=["Index"])
def _scatter_nd(ctx, inputs, attrs):
    """scatter_nd = scatter_nd_add onto zeros of attr `shape`."""
    (index,) = inputs["Index"]
    (updates,) = inputs["Updates"]
    shape = tuple(attrs["shape"])
    zeros = jnp.zeros(shape, updates.dtype)
    idx_dims = index.shape[-1]
    dnums = lax.ScatterDimensionNumbers(
        update_window_dims=tuple(range(index.ndim - 1, updates.ndim)),
        inserted_window_dims=tuple(range(idx_dims)),
        scatter_dims_to_operand_dims=tuple(range(idx_dims)))
    out = lax.scatter_add(zeros, index, updates, dnums)
    return {"Out": [out]}


@register_op("hash", differentiable=False)
def _hash(ctx, inputs, attrs):
    """hash_op.cc: num_hash independent hashes of each id row into
    [0, mod_by). Deterministic multiplicative mixing (splitmix-style)
    instead of the reference's xxHash — same stable-id contract."""
    (x,) = inputs["X"]
    num_hash = int(attrs.get("num_hash", 1))
    mod_by = int(attrs.get("mod_by", 1))
    ids = x.astype(jnp.uint32).reshape(x.shape[0], -1)
    # combine the columns of each row into one key
    key = jnp.zeros((x.shape[0],), jnp.uint32)
    for c in range(ids.shape[1]):
        key = key * jnp.uint32(1000003) + ids[:, c]
    outs = []
    for s in range(num_hash):
        h = key + jnp.uint32((0x9E3779B9 * (s + 1)) & 0xFFFFFFFF)
        h = (h ^ (h >> 16)) * jnp.uint32(0x85EBCA6B)
        h = (h ^ (h >> 13)) * jnp.uint32(0xC2B2AE35)
        h = h ^ (h >> 16)
        outs.append((h % jnp.uint32(mod_by)).astype(jnp.int64))
    return {"Out": [jnp.stack(outs, axis=1)[:, :, None]]}


def _unique_impl(x):
    flat = x.reshape(-1)
    n = flat.shape[0]
    uniq, idx, count = jnp.unique(flat, size=n, fill_value=flat[0],
                                  return_inverse=True, return_counts=True)
    # count is 0 for fill slots → number of real uniques
    num = jnp.sum(count > 0)
    return uniq, idx.reshape(-1), count, num


@register_op("unique", differentiable=False)
def _unique(ctx, inputs, attrs):
    """unique_op.cc. Static-shape redesign: `Out` keeps the input length
    (tail slots repeat the first element), `Index` is the inverse map, and
    the extra `Count` scalar says how many leading slots are real."""
    (x,) = inputs["X"]
    uniq, idx, _, num = _unique_impl(x)
    dtype = attrs.get("dtype", "int32")
    it = jnp.int64 if "64" in str(dtype) else jnp.int32
    return {"Out": [uniq], "Index": [idx.astype(it)],
            "Count": [num.reshape(1).astype(it)]}


@register_op("unique_with_counts", differentiable=False)
def _unique_with_counts(ctx, inputs, attrs):
    (x,) = inputs["X"]
    uniq, idx, count, num = _unique_impl(x)
    dtype = attrs.get("dtype", "int32")
    it = jnp.int64 if "64" in str(dtype) else jnp.int32
    return {"Out": [uniq], "Index": [idx.astype(it)],
            "Counts": [count.astype(it)],
            "Count": [num.reshape(1).astype(it)]}


def _chunk_bounds(tags, num_chunk_types, scheme, lengths):
    """(start, end, type) flags per position for IOB/IOE/IOBES/plain tag
    encodings (chunk_eval_op.h tag layout: tag = type * num_tag_types +
    tag_pos; `outside` = num_chunk_types * num_tag_types)."""
    n_tag = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[scheme]
    outside = num_chunk_types * n_tag
    t = tags
    valid = (jnp.arange(t.shape[1])[None, :] <
             lengths.reshape(-1, 1)) & (t < outside)
    typ = jnp.where(valid, t // n_tag, -1)
    pos = jnp.where(valid, t % n_tag, -1)
    prev_typ = jnp.concatenate(
        [jnp.full_like(typ[:, :1], -1), typ[:, :-1]], 1)
    nxt_typ = jnp.concatenate(
        [typ[:, 1:], jnp.full_like(typ[:, :1], -1)], 1)
    if scheme == "IOB":         # pos 0 = B, 1 = I
        start = valid & ((pos == 0) | (typ != prev_typ))
        prev_pos = jnp.concatenate(
            [jnp.full_like(pos[:, :1], -1), pos[:, :-1]], 1)
        nxt_pos = jnp.concatenate(
            [pos[:, 1:], jnp.full_like(pos[:, :1], -1)], 1)
        end = valid & ((nxt_typ != typ) | (nxt_pos == 0))
    elif scheme == "IOE":       # pos 0 = I, 1 = E
        end = valid & ((pos == 1) | (typ != nxt_typ))
        start = valid & (typ != prev_typ)
    elif scheme == "IOBES":     # 0=B 1=I 2=E 3=S
        start = valid & ((pos == 0) | (pos == 3))
        end = valid & ((pos == 2) | (pos == 3))
    else:                       # plain: maximal same-type runs
        start = valid & (typ != prev_typ)
        end = valid & (typ != nxt_typ)
    return start, end, typ, valid


@register_op("chunk_eval", differentiable=False)
def _chunk_eval(ctx, inputs, attrs):
    """chunk_eval_op.cc: precision/recall/F1 over labeled chunks. Spans are
    matched statically: per position, a reverse scan yields the index of
    the chunk end at-or-after it; a label chunk counts as correct when the
    inference starts a chunk at the same position with the same type and
    both scans agree on the end."""
    (inf,) = inputs["Inference"]
    (lab,) = inputs["Label"]
    length = inputs.get("Length", [None])[0]
    num_chunk_types = int(attrs["num_chunk_types"])
    scheme = attrs.get("chunk_scheme", "IOB")
    b = inf.shape[0] if inf.ndim > 1 else 1
    inf2 = inf.reshape(b, -1).astype(jnp.int32)
    lab2 = lab.reshape(b, -1).astype(jnp.int32)
    tlen = inf2.shape[1]
    lengths = (length.reshape(-1).astype(jnp.int32) if length is not None
               else jnp.full((b,), tlen, jnp.int32))

    si, ei, ti, _ = _chunk_bounds(inf2, num_chunk_types, scheme, lengths)
    sl, el, tl_, _ = _chunk_bounds(lab2, num_chunk_types, scheme, lengths)

    def next_end(end):
        # reverse scan: index of the first end flag at or after each pos
        rev = jnp.flip(end, axis=1)
        idx = jnp.flip(lax.associative_scan(
            jnp.maximum, jnp.where(rev, jnp.arange(tlen)[None, :], -1),
            axis=1), axis=1)
        return tlen - 1 - idx  # back to forward indexing; -1→ tlen (none)

    ne_i, ne_l = next_end(ei), next_end(el)
    correct = si & sl & (ti == tl_) & (ne_i == ne_l)
    n_inf = jnp.sum(si).astype(jnp.int64)
    n_lab = jnp.sum(sl).astype(jnp.int64)
    n_cor = jnp.sum(correct).astype(jnp.int64)
    p = n_cor / jnp.maximum(n_inf, 1)
    r = n_cor / jnp.maximum(n_lab, 1)
    f1 = 2 * p * r / jnp.maximum(p + r, 1e-12)
    f32 = jnp.float32
    return {"Precision": [p.astype(f32).reshape(1)],
            "Recall": [r.astype(f32).reshape(1)],
            "F1-Score": [f1.astype(f32).reshape(1)],
            "NumInferChunks": [n_inf.reshape(1)],
            "NumLabelChunks": [n_lab.reshape(1)],
            "NumCorrectChunks": [n_cor.reshape(1)]}
