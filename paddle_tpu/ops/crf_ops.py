"""Linear-chain CRF ops — log-likelihood + Viterbi decoding.

Reference analog: ``paddle/fluid/operators/linear_chain_crf_op.cc`` (forward
algorithm, alpha recursion, hand-written grad kernel) and
``crf_decoding_op.cc`` (Viterbi). The reference stores the transition matrix
as [D+2, D]: row 0 = start weights, row 1 = end weights, rows 2.. = the
[D, D] pairwise transitions — the same layout is kept here so parameters are
interchangeable.

TPU-native redesign: padded [B, T] batches + length mask instead of LoD;
forward algorithm is a `lax.scan` of log-sum-exp updates (differentiable via
the vjp tape, replacing the hand-written linear_chain_crf_grad kernel);
Viterbi is a scan carrying argmax backpointers with a reverse scan backtrack.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import length_mask, opt_input

NEG = -1e30


def _split_transition(trans):
    start_w, end_w, pairwise = trans[0], trans[1], trans[2:]
    return start_w, end_w, pairwise


@register_op("linear_chain_crf", nondiff_inputs=["Label", "Length"])
def _linear_chain_crf(ctx, inputs, attrs):
    """Emission [B,T,D], Transition [D+2,D], Label [B,T] (or [B,T,1]),
    Length [B]. Returns LogLikelihood [B,1] (reference returns per-sequence
    log-likelihood = path score - log partition)."""
    (emission,) = inputs["Emission"]
    (trans,) = inputs["Transition"]
    (label,) = inputs["Label"]
    length = opt_input(inputs, "Length")

    if label.ndim == 3:
        label = label[..., 0]
    label = label.astype(jnp.int32)
    B, T, D = emission.shape
    start_w, end_w, pairwise = _split_transition(trans)

    if length is None:
        length = jnp.full((B,), T, jnp.int32)
    else:
        length = length.reshape(-1).astype(jnp.int32)
    mask = length_mask(length, B, T, emission.dtype)

    # ---- log partition via forward algorithm -----------------------------
    alpha0 = start_w[None, :] + emission[:, 0, :]          # [B,D]

    def fwd(alpha, em_m):
        em_t, m_t = em_m                                    # [B,D], [B]
        # logsumexp over previous tag: alpha[b,i] + pairwise[i,j]
        scores = alpha[:, :, None] + pairwise[None, :, :]   # [B,D,D]
        new = jax.nn.logsumexp(scores, axis=1) + em_t
        m = m_t[:, None]
        return alpha * (1 - m) + new * m, None

    ems = jnp.swapaxes(emission, 0, 1)[1:]                  # [T-1,B,D]
    ms = jnp.swapaxes(mask, 0, 1)[1:]                       # [T-1,B]
    alpha, _ = lax.scan(fwd, alpha0, (ems, ms))
    log_z = jax.nn.logsumexp(alpha + end_w[None, :], axis=-1)   # [B]

    # ---- gold path score -------------------------------------------------
    t_idx = jnp.arange(T)
    em_score = jnp.sum(
        jnp.take_along_axis(emission, label[..., None], axis=-1)[..., 0] * mask,
        axis=-1)
    prev_l, next_l = label[:, :-1], label[:, 1:]
    trans_score = jnp.sum(pairwise[prev_l, next_l] * mask[:, 1:], axis=-1)
    last_idx = jnp.maximum(length - 1, 0)
    last_label = jnp.take_along_axis(label, last_idx[:, None], axis=1)[:, 0]
    path = em_score + trans_score + start_w[label[:, 0]] + end_w[last_label]

    ll = (path - log_z).reshape(B, 1)
    return {"LogLikelihood": [ll], "EmissionExps": [jnp.exp(emission)],
            "TransitionExps": [jnp.exp(trans)], "Alpha": [alpha]}


@register_op("crf_decoding", differentiable=False)
def _crf_decoding(ctx, inputs, attrs):
    """Viterbi decode. Emission [B,T,D], Transition [D+2,D], Length [B],
    optional Label for scoring mode (reference: outputs 0/1 correctness per
    position when Label given). ViterbiPath [B,T] int64 (padded positions 0).
    """
    (emission,) = inputs["Emission"]
    (trans,) = inputs["Transition"]
    length = opt_input(inputs, "Length")
    label = opt_input(inputs, "Label")

    B, T, D = emission.shape
    start_w, end_w, pairwise = _split_transition(trans)
    if length is None:
        length = jnp.full((B,), T, jnp.int32)
    else:
        length = length.reshape(-1).astype(jnp.int32)
    mask = jnp.arange(T)[None, :] < length[:, None]          # [B,T] bool

    alpha0 = start_w[None, :] + emission[:, 0, :]

    def fwd(alpha, em_m):
        em_t, m_t = em_m
        scores = alpha[:, :, None] + pairwise[None, :, :]    # [B,D,D]
        best_prev = jnp.argmax(scores, axis=1)               # [B,D]
        new = jnp.max(scores, axis=1) + em_t
        m = m_t[:, None]
        alpha_next = jnp.where(m, new, alpha)
        # backpointer for masked steps: identity (tag points to itself)
        bp = jnp.where(m, best_prev, jnp.arange(D)[None, :])
        return alpha_next, bp

    ems = jnp.swapaxes(emission, 0, 1)[1:]
    ms = jnp.swapaxes(mask, 0, 1)[1:]
    alpha, bps = lax.scan(fwd, alpha0, (ems, ms))            # bps [T-1,B,D]
    last_tag = jnp.argmax(alpha + end_w[None, :], axis=-1)   # [B]

    def back(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, tag

    first_tag, tags_rev = lax.scan(back, last_tag, bps, reverse=True)
    # tags_rev[t] is the tag at position t+1; prepend position-0 tag
    path = jnp.concatenate([first_tag[None, :], tags_rev], axis=0)  # [T,B]
    path = jnp.swapaxes(path, 0, 1)
    path = jnp.where(mask, path, 0).astype(jnp.int64)
    out = {"ViterbiPath": [path]}
    if label is not None:
        if label.ndim == 3:
            label = label[..., 0]
        correct = (path == label.astype(jnp.int64)) & mask
        out["ViterbiPath"] = [correct.astype(jnp.int64)]
    return out
