"""Deferred row updates — O(touched-rows) sparse embedding optimization.

Reference analog: the SelectedRows sparse-apply path
(``paddle/fluid/operators/optimizers/sgd_op.cc`` SelectedRows branch,
``adagrad_op.cc`` SparseAdagradFunctor merge+row-update, ``adam_op.cc``
SparseAdamFunctor lazy_mode, ``math/selected_rows_functor.cc`` MergeAdd)
whose cost is O(touched rows), and the Downpour sparse-table row layout
that stores the accumulator next to the embedding in the same row
(g2sum in pslib's DownpourSparseTable — here the optional "state columns"
of the table). XLA has no in-place row scatter: ``table.at[ids].add(rows)``
lowers to a full read+write pass over the table (measured ~10.9 ms per
[33.5M,16] f32 table on v5e regardless of how few rows are touched), so a
literal translation pays O(table) per step — a cost-model regression vs
the reference.

TPU-native redesign, built from measured v5e access costs (random row
gathers ~10-30 ns/row; element gathers/scatters into sub-GB arrays
~5-13 ns; per-row DMA scatter impossible — Mosaic requires 128-lane
aligned slices; binary search dead — 17 rounds x 1.7M scalar gathers
measured 208 ms):

- a position table ``postab [V] int32`` maps id -> index of its LATEST
  pending entry (-1 = none): the pending "join" is ONE element gather.
- an append-only log of pending entries: ``log_ids [C]``,
  ``log_raw [C, Dt]`` (per-step deltas, folded into the table later) and
  ``log_cum [C, Dt]`` (cumulative delta since the last fold, what readers
  add to the base row). A re-touched id appends a NEW entry whose cum
  includes the old one; postab moves to it; the shadowed entry stays and
  is still correct for the fold (raw deltas add).
- every lookup returns ``base[ids] + log_cum[postab[ids]]`` — the exact
  serial-update value regardless of fold cadence. The fold (its own
  program, run by the executor epilogue every K steps) scatter-adds all
  raw deltas into the table in ONE amortized O(table) pass, clears
  postab, and resets the log.
- the deferred optimizer op performs NO large random access at all: the
  lookup op additionally outputs its gathered current rows and cum rows,
  and the optimizer reuses them through the step's unique-merge
  permutation (all small-array ops), computing deltas against exact
  current values — which makes the scheme EXACT (not stale) for SGD,
  Adagrad, and lazy Adam; deltas compose additively by construction.
- optimizer moment state lives in extra columns of the same table row
  ("state columns", the Downpour g2sum layout): one gather, one log, one
  fold pass serve param and moments together. The model slices the
  visible columns ``[:vis]`` after the lookup.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from ..core.selected_rows import SelectedRows
from ..observability.registry import get_registry
from .pallas_kernels import sparse_adagrad as _fused_adagrad

SENTINEL = 2**31 - 1

# Trace-time counter (one inc per compile of a program that took the fused
# branch): lets tests and production assert the Pallas path did not silently
# deactivate — an env flip or a shape outside `supports()` would otherwise
# degrade deepfm back to the scatter path with no signal.
_FUSED_SPARSE = get_registry().counter("optimizer/fused_sparse_updates")


# ---------------------------------------------------------------------------
# forward join (used by the lookup_table kernel)
# ---------------------------------------------------------------------------

def lookup_join(postab, log_cum, base_rows, q):
    """Current rows for query ids: base gather + postab-indexed cum rows.

    postab: [V] int32; log_cum: [C, Lw] (row width padded to a 128-lane
    multiple — lane-aligned rows gather ~5x faster than the narrow
    column-major layout XLA must use for the un-paddable base table);
    base_rows: [Q, Dt] (= table[q]); q: [Q] int32.
    Returns (cur_rows [Q, Dt], cum_rows [Q, Dt]).
    """
    dt = base_rows.shape[-1]
    lw = log_cum.shape[-1]
    pos = postab[q]                                     # [Q] element gather
    hit = (pos >= 0)[:, None]
    cum_full = jnp.take(log_cum, pos.clip(0), axis=0)   # [Q, Lw] row gather
    if lw > dt:
        # narrow via a 0/1 projection dot (exact in f32): a plain slice
        # gets fused INTO the gather as slice_sizes=(1,dt), which XLA
        # lowers as a serial while loop (measured 187 ms); full-row
        # gathers vectorize (measured ~1 ms)
        proj = jnp.eye(lw, dt, dtype=log_cum.dtype)
        cum = jax.lax.dot_general(
            cum_full, proj, (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST)
    else:
        cum = cum_full
    cum = jnp.where(hit, cum, 0)
    return base_rows + cum.astype(base_rows.dtype), cum


# ---------------------------------------------------------------------------
# unique-merge (MergeAdd parity, selected_rows_functor.cc)
# ---------------------------------------------------------------------------

def uniq_merge(ids, rows, r):
    """Combine duplicate ids; also return a representative original
    position per unique id (for reusing forward-gathered rows).

    ids [Q], rows [Q, D] -> (uids [r] ascending + SENTINEL pads,
    utot [r, D] summed rows, rep [r] original index of one occurrence).
    r >= Q required (static capacity, checked at trace time).
    """
    qn = ids.shape[0]
    d = rows.shape[-1]
    if qn > r:
        raise ValueError(
            f"deferred rows_per_step={r} is smaller than this step's "
            f"{qn} lookup rows — raise rows_per_step (static capacity)")
    if qn == 0:
        # the segment machinery below needs >= 1 element (`first` would be
        # [1] against 0 rows); an empty batch is all pads by definition
        return (jnp.full((r,), SENTINEL, jnp.int32),
                jnp.zeros((r, d), rows.dtype),
                jnp.zeros((r,), jnp.int32))
    order = jnp.argsort(ids)
    sids = ids[order]
    srows = rows[order]
    first = jnp.concatenate([jnp.ones((1,), bool), sids[1:] != sids[:-1]])
    seg = (jnp.cumsum(first) - 1).astype(jnp.int32)
    nu = seg[-1] + 1
    utot = jnp.zeros((qn, d), srows.dtype).at[seg].add(srows)
    rep = jnp.full((qn,), 0, jnp.int32).at[seg].max(order.astype(jnp.int32))
    # unique ids via the representative positions — an O(r) element gather
    # from the small id array instead of a second O(r) scatter
    uids = jnp.where(jnp.arange(qn) < nu, ids[rep], SENTINEL)
    if qn < r:
        uids = jnp.concatenate([uids, jnp.full((r - qn,), SENTINEL, jnp.int32)])
        utot = jnp.concatenate([utot, jnp.zeros((r - qn, d), utot.dtype)])
        rep = jnp.concatenate([rep, jnp.zeros((r - qn,), jnp.int32)])
    return uids, utot, rep


def _grad_rows(g):
    if not isinstance(g, SelectedRows):
        raise TypeError(
            "deferred-row optimizer ops need a SelectedRows gradient "
            "(embedding built with is_sparse=True); got a dense array")
    return g.ids.astype(jnp.int32), g.rows


# ---------------------------------------------------------------------------
# shared optimizer-op machinery
# ---------------------------------------------------------------------------

def _deferred_common(inputs, attrs):
    """Returns (uids [R], utot [R,vis], cur_u [R,Dt], cum_u [R,Dt],
    valid [R,1], plus the log/postab state) — zero large random accesses:
    current and cum rows come from the lookup's outputs via the
    unique-merge permutation."""
    (g,) = inputs["Grad"]
    (fwd_rows,) = inputs["FwdRows"]
    (fwd_cum,) = inputs["FwdCum"]
    (postab,) = inputs["PendingPos"]
    (log_ids,) = inputs["LogIds"]
    (count,) = inputs["Count"]
    r = int(attrs["rows_per_step"])
    vis = int(attrs["vis"])
    dt = fwd_rows.shape[-1]
    ids, grows = _grad_rows(g)
    if grows.shape[-1] not in (vis, dt):
        raise ValueError(
            f"deferred op: grad rows have {grows.shape[-1]} cols, "
            f"expected vis={vis} (or padded {dt})")
    (log_raw,) = inputs["LogRaw"]
    cdt = log_raw.dtype  # compute dtype follows the table/log precision
    uids, utot, rep = uniq_merge(ids, grows[:, :vis].astype(cdt), r)
    flat_rows = fwd_rows.reshape(-1, dt)
    flat_cum = fwd_cum.reshape(-1, dt)
    if flat_rows.shape[0] != ids.shape[0]:
        raise ValueError(
            f"deferred op: FwdRows carries {flat_rows.shape[0]} rows but "
            f"the gradient has {ids.shape[0]} — the rewrite requires the "
            f"single lookup site's output")
    cur_u = flat_rows[rep].astype(cdt)                  # [R, Dt] small gather
    cum_u = flat_cum[rep].astype(cdt)
    valid = (uids != SENTINEL)[:, None]
    return (uids, utot, rep, cur_u, cum_u, valid,
            postab, log_ids, count, r, vis, dt)


def _append(inputs, outputs_extra, postab, log_ids, count, uids, raw_new,
            cum_new, valid):
    """Append the step's entries at [count, count+R) and repoint postab.
    Contract: the fold epilogue runs before the log wraps (the optimizer
    attaches it at cadence C/R); entries are never overwritten live."""
    (log_raw,) = inputs["LogRaw"]
    (log_cum,) = inputs["LogCum"]
    c = count.reshape(()).astype(jnp.int32)
    z = jnp.zeros((), jnp.int32)
    r, dt = raw_new.shape
    lw = log_raw.shape[-1]
    raw_new = jnp.where(valid, raw_new, 0).astype(log_raw.dtype)
    cum_new = jnp.where(valid, cum_new, 0).astype(log_cum.dtype)
    if lw > dt:  # lane-padded log rows (see lookup_join)
        pad = jnp.zeros((r, lw - dt), log_raw.dtype)
        raw_new = jnp.concatenate([raw_new, pad], axis=-1)
        cum_new = jnp.concatenate([cum_new, pad], axis=-1)
    out = {
        "LogIdsOut": [lax.dynamic_update_slice(log_ids, uids, (c,))],
        "LogRawOut": [lax.dynamic_update_slice(log_raw, raw_new, (c, z))],
        "LogCumOut": [lax.dynamic_update_slice(log_cum, cum_new, (c, z))],
        "PendingPosOut": [postab.at[uids].set(
            c + jnp.arange(r, dtype=jnp.int32), mode="drop")],
        "CountOut": [count + r],
    }
    out.update(outputs_extra)
    return out


def _lr(inputs):
    (lr,) = inputs["LearningRate"]
    return lr.reshape(())


# ---------------------------------------------------------------------------
# optimizer ops
# ---------------------------------------------------------------------------

@register_op("sgd_row_deferred", differentiable=False)
def _sgd_row_deferred(ctx, inputs, attrs):
    """sgd_op.cc SelectedRows branch, deferred: delta = -lr * merged_g."""
    (uids, utot, rep, cur_u, cum_u, valid, postab, log_ids, count,
     r, vis, dt) = _deferred_common(inputs, attrs)
    delta = -_lr(inputs) * utot
    return _append(inputs, {}, postab, log_ids, count, uids,
                   delta, cum_u + delta, valid)


@register_op("adagrad_row_deferred", differentiable=False)
def _adagrad_row_deferred(ctx, inputs, attrs):
    """adagrad_op.cc SparseAdagradFunctor, deferred: G rides in state
    columns [vis:2vis] of the row (Downpour g2sum layout); touched rows
    advance G += g^2 and p -= lr*g/(sqrt(G)+eps) against exact current
    values."""
    (uids, utot, rep, cur_u, cum_u, valid, postab, log_ids, count,
     r, vis, dt) = _deferred_common(inputs, attrs)
    if dt != 2 * vis:
        raise ValueError(
            f"adagrad_row_deferred: table row has {dt} cols, expected "
            f"2*vis={2*vis} (param | accumulator state columns)")
    eps = attrs.get("epsilon", 1e-6)
    g_now = cur_u[:, vis:]
    g_delta = utot * utot
    g_new = g_now + g_delta
    p_delta = -_lr(inputs) * utot / (jnp.sqrt(g_new) + eps)
    raw = jnp.concatenate([p_delta, g_delta], axis=-1)
    return _append(inputs, {}, postab, log_ids, count, uids,
                   raw, cum_u + raw, valid)


@register_op("adam_row_deferred", differentiable=False)
def _adam_row_deferred(ctx, inputs, attrs):
    """adam_op.cc SparseAdamFunctor lazy_mode, deferred: m/v ride in state
    columns [vis:2vis] / [2vis:3vis]; only touched rows advance m/v (the
    reference's lazy semantics); beta powers advance every step as
    scalars."""
    (uids, utot, rep, cur_u, cum_u, valid, postab, log_ids, count,
     r, vis, dt) = _deferred_common(inputs, attrs)
    if dt != 3 * vis:
        raise ValueError(
            f"adam_row_deferred: table row has {dt} cols, expected "
            f"3*vis={3*vis} (param | moment1 | moment2 state columns)")
    (b1p,) = inputs["Beta1Pow"]
    (b2p,) = inputs["Beta2Pow"]
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr_t = _lr(inputs) * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(()))
    m_now = cur_u[:, vis:2 * vis]
    v_now = cur_u[:, 2 * vis:]
    m_new = b1 * m_now + (1 - b1) * utot
    v_new = b2 * v_now + (1 - b2) * utot * utot
    p_delta = -lr_t * m_new / (jnp.sqrt(v_new) + eps)
    raw = jnp.concatenate([p_delta, m_new - m_now, v_new - v_now], axis=-1)
    return _append(inputs, {"Beta1PowOut": [b1p * b1],
                            "Beta2PowOut": [b2p * b2]},
                   postab, log_ids, count, uids, raw, cum_u + raw, valid)


# ---------------------------------------------------------------------------
# fold
# ---------------------------------------------------------------------------

@register_op("deferred_fold", differentiable=False)
def _deferred_fold(ctx, inputs, attrs):
    """Fold all pending raw deltas into the table: ONE O(table) streaming
    scatter pass, amortized over K steps by the executor epilogue cadence.
    Shadowed (superseded) entries are safe — raw deltas add; sentinel ids
    are out of bounds and dropped. Clears postab and resets the log.
    Semantically a pure representation change: reads are exact before and
    after (base+cum == base')."""
    (p,) = inputs["Param"]
    (log_ids,) = inputs["LogIds"]
    (log_raw,) = inputs["LogRaw"]
    (log_cum,) = inputs["LogCum"]
    (postab,) = inputs["PendingPos"]
    (count,) = inputs["Count"]
    dt = p.shape[-1]
    return {
        "ParamOut": [p.at[log_ids].add(
            log_raw[:, :dt].astype(p.dtype), mode="drop")],
        "PendingPosOut": [jnp.full_like(postab, -1)],
        "LogIdsOut": [jnp.full_like(log_ids, SENTINEL)],
        # stale log rows are unreachable once log_ids is sentinel and
        # postab is cleared — pass them through instead of zeroing 1.7GB
        "LogRawOut": [log_raw],
        "LogCumOut": [log_cum],
        "CountOut": [jnp.zeros_like(count)],
    }


@register_op("deferred_init_state_cols", differentiable=False)
def _deferred_init_state_cols(ctx, inputs, attrs):
    """Startup-time init of a table's state columns (Downpour g2sum layout):
    keep the visible [:vis] initializer output, fill [vis:] with the
    moment initial value (adagrad initial_accumulator_value / adam 0)."""
    (p,) = inputs["Param"]
    vis = int(attrs["vis"])
    val = attrs.get("value", 0.0)
    state = jnp.full((p.shape[0], p.shape[1] - vis), val, p.dtype)
    return {"ParamOut": [jnp.concatenate([p[:, :vis], state], axis=-1)]}


# ---------------------------------------------------------------------------
# packed row-major tables — direct O(touched-rows) updates
# ---------------------------------------------------------------------------
#
# The deferred log above amortizes the scatter *pass*, but measurement shows
# XLA's scatter into the narrow table costs ~6.4 ns per touched ELEMENT
# regardless of batching (the [V,D] f32 table is forced into a column-major
# {0,1} layout because a row-major tile would pad D -> 128 and 8x the
# memory; every row update then writes D scattered lines). The fix is to
# make the rows physically contiguous WITHOUT the f32 padding blowup:
# bit-split each f32 into two u16 lanes and store the table as
# [V, 128] uint16 ({1,0}, lane-aligned, zero padding waste for up to 64
# packed f32 values — param + moment state columns in one row, the same
# Downpour row layout). Measured on v5e: full-row gathers 1.07 ms and
# scatter-SET row updates 7.4 ms per 106k rows, vs 4.6 ms / ~23 ms on the
# column-major f32 table — so each step can simply gather, compute the
# exact optimizer update, and scatter the new rows back: serial-exact
# semantics with no pending state at all.

PACK_LANES = 128  # u16 lanes per packed row (64 f32 values max)


def pack_rows(x, lanes=PACK_LANES):
    """[N, D] f32 -> [N, lanes] uint16 (bit-exact; zero-padded)."""
    n, d = x.shape
    u = lax.bitcast_convert_type(x, jnp.uint16).reshape(n, 2 * d)
    if 2 * d > lanes:
        raise ValueError(f"pack_rows: {d} f32 values need {2*d} u16 lanes "
                         f"> {lanes}")
    if 2 * d < lanes:
        u = jnp.concatenate(
            [u, jnp.zeros((n, lanes - 2 * d), jnp.uint16)], axis=-1)
    return u


def unpack_rows(u, d):
    """[N, lanes] uint16 -> [N, d] f32 (bit-exact)."""
    n = u.shape[0]
    return lax.bitcast_convert_type(
        u[:, :2 * d].reshape(n, d, 2), jnp.float32)


@register_op("rowpack_init", differentiable=False)
def _rowpack_init(ctx, inputs, attrs):
    """Initialize a packed table: visible columns ~ U(low, high), state
    columns = state_value, packed to [V, lanes] uint16.

    Assembled in row chunks with an in-place fori/DUS loop — generating
    the whole table in f32 first would transiently need ~2.5x the packed
    size (OOM at Criteo scale). The final chunk's DUS start is clamped, so
    a remainder chunk re-draws some earlier rows — fine for random init."""
    v = int(attrs["height"])
    vis = int(attrs["vis"])
    dt = int(attrs["dt"])
    low, high = attrs.get("low", -0.1), attrs.get("high", 0.1)
    sv = attrs.get("state_value", 0.0)
    cs = min(v, 1 << 20)
    n_chunks = -(-v // cs)
    key = ctx.rng()

    def chunk(i):
        visv = jax.random.uniform(
            jax.random.fold_in(key, i), (cs, vis), jnp.float32, low, high)
        rows = (jnp.concatenate(
            [visv, jnp.full((cs, dt - vis), sv, jnp.float32)], axis=-1)
            if dt > vis else visv)
        return pack_rows(rows)

    out = jnp.zeros((v, PACK_LANES), jnp.uint16)

    def body(i, acc):
        start = jnp.minimum(i * cs, v - cs).astype(jnp.int32)
        return lax.dynamic_update_slice(
            acc, chunk(i), (start, jnp.zeros((), jnp.int32)))

    return {"Out": [lax.fori_loop(0, n_chunks, body, out)]}


@register_op("rowpack_init_state_cols", differentiable=False)
def _rowpack_init_state_cols(ctx, inputs, attrs):
    """Startup-time re-init of a PACKED table's state columns: unpack each
    row chunk, overwrite cols [vis:dt] with the optimizer's initial value
    (adagrad initial_accumulator_value / adam 0), repack. Emitted by the
    packed-rows optimizer setup so state columns are well-defined no
    matter what the table initializer wrote there (a uniform init in the
    G columns would make adagrad take sqrt of a negative sum)."""
    (p,) = inputs["Param"]
    vis = int(attrs["vis"])
    dt = int(attrs["dt"])
    val = attrs.get("value", 0.0)
    v = p.shape[0]
    cs = min(v, 1 << 20)
    n_chunks = -(-v // cs)

    def body(i, acc):
        start = jnp.minimum(i * cs, v - cs).astype(jnp.int32)
        z = jnp.zeros((), jnp.int32)
        chunk = lax.dynamic_slice(acc, (start, z), (cs, acc.shape[1]))
        rows = unpack_rows(chunk, dt)
        rows = jnp.concatenate(
            [rows[:, :vis], jnp.full((cs, dt - vis), val, jnp.float32)],
            axis=-1)
        return lax.dynamic_update_slice(acc, pack_rows(rows), (start, z))

    return {"ParamOut": [lax.fori_loop(0, n_chunks, body, p)]}


def _packed_common(inputs, attrs):
    """uniq-merge the SelectedRows grad and pull current rows out of the
    lookup's forward output (no additional large gathers)."""
    (g,) = inputs["Grad"]
    (fwd_rows,) = inputs["FwdRows"]
    r = int(attrs["rows_per_step"])
    vis = int(attrs["vis"])
    dt = fwd_rows.shape[-1]
    ids, grows = _grad_rows(g)
    uids, utot, rep = uniq_merge(ids, grows[:, :vis].astype(jnp.float32), r)
    cur_u = fwd_rows.reshape(-1, dt)[rep].astype(jnp.float32)
    valid = (uids != SENTINEL)[:, None]
    return uids, utot, cur_u, valid, vis, dt


def _packed_write(p, uids, new_rows):
    return p.at[uids].set(pack_rows(new_rows), mode="drop",
                          unique_indices=True)


@register_op("sgd_row_packed", differentiable=False)
def _sgd_row_packed(ctx, inputs, attrs):
    """sgd_op.cc SelectedRows branch on a packed table: touched rows get
    p -= lr * merged_g, written back as one row-major scatter-set."""
    (p,) = inputs["Param"]
    uids, utot, cur_u, valid, vis, dt = _packed_common(inputs, attrs)
    new = jnp.where(valid, cur_u[:, :vis] - _lr(inputs) * utot, cur_u[:, :vis])
    return {"ParamOut": [_packed_write(p, uids, new)]}


@register_op("adagrad_row_packed", differentiable=False)
def _adagrad_row_packed(ctx, inputs, attrs):
    """adagrad_op.cc SparseAdagradFunctor on a packed table: G rides in
    the state columns; touched rows advance G += g^2,
    p -= lr*g/(sqrt(G)+eps); one gather (forward, reused) + one
    scatter-set per step.

    When the fused Pallas kernel is available (TPU, or the interpreter
    under test) and the op was not built with ``fused=False``, the whole
    gather→update→scatter round trip collapses into one
    `sparse_adagrad.fused_adagrad_update` pass: the kernel reads each
    touched packed row straight from the table (same bytes FwdRows was
    gathered from — the table is unmodified between forward and
    optimizer within a step), applies the identical Adagrad math, and
    writes it back through an input/output alias instead of an XLA
    scatter. Bitwise-identical to the branch below."""
    (p,) = inputs["Param"]
    eps = attrs.get("epsilon", 1e-6)
    vis = int(attrs["vis"])
    if attrs.get("fused", True) and _fused_adagrad.enabled(vis, p.shape[-1]):
        (g,) = inputs["Grad"]
        r = int(attrs["rows_per_step"])
        ids, grows = _grad_rows(g)
        uids, utot, _rep = uniq_merge(
            ids, grows[:, :vis].astype(jnp.float32), r)
        _FUSED_SPARSE.inc()
        return {"ParamOut": [_fused_adagrad.fused_adagrad_update(
            p, uids, utot, _lr(inputs), vis=vis, eps=eps)]}
    uids, utot, cur_u, valid, vis, dt = _packed_common(inputs, attrs)
    g_new = cur_u[:, vis:2 * vis] + utot * utot
    p_new = cur_u[:, :vis] - _lr(inputs) * utot / (jnp.sqrt(g_new) + eps)
    rows = jnp.where(valid, jnp.concatenate([p_new, g_new], axis=-1),
                     cur_u[:, :2 * vis])
    return {"ParamOut": [_packed_write(p, uids, rows)]}


@register_op("adam_row_packed", differentiable=False)
def _adam_row_packed(ctx, inputs, attrs):
    """adam_op.cc SparseAdamFunctor lazy_mode on a packed table: m/v ride
    in state columns; beta powers advance per step as scalars."""
    (p,) = inputs["Param"]
    uids, utot, cur_u, valid, vis, dt = _packed_common(inputs, attrs)
    (b1p,) = inputs["Beta1Pow"]
    (b2p,) = inputs["Beta2Pow"]
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr_t = _lr(inputs) * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(()))
    m_new = b1 * cur_u[:, vis:2 * vis] + (1 - b1) * utot
    v_new = b2 * cur_u[:, 2 * vis:3 * vis] + (1 - b2) * utot * utot
    p_new = cur_u[:, :vis] - lr_t * m_new / (jnp.sqrt(v_new) + eps)
    rows = jnp.where(valid, jnp.concatenate([p_new, m_new, v_new], axis=-1),
                     cur_u[:, :3 * vis])
    return {"ParamOut": [_packed_write(p, uids, rows)],
            "Beta1PowOut": [b1p * b1], "Beta2PowOut": [b2p * b2]}
