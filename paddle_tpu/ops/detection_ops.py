"""Detection ops — a TPU-friendly subset of operators/detection/ (15.3k LoC in
the reference: yolo, ssd priors, roi_align/pool, nms, ...). Static-shape
variants of the most-used ops; the NMS family returns fixed-size padded
results (XLA cannot produce dynamic row counts).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import one


@register_op("box_coder", differentiable=False)
def _box_coder(ctx, inputs, attrs):
    (prior_box,) = inputs["PriorBox"]
    (target_box,) = inputs["TargetBox"]
    code_type = attrs.get("code_type", "encode_center_size")
    pw = prior_box[:, 2] - prior_box[:, 0]
    ph = prior_box[:, 3] - prior_box[:, 1]
    px = prior_box[:, 0] + pw / 2
    py = prior_box[:, 1] + ph / 2
    if code_type == "encode_center_size":
        tw = target_box[:, 2] - target_box[:, 0]
        th = target_box[:, 3] - target_box[:, 1]
        tx = target_box[:, 0] + tw / 2
        ty = target_box[:, 1] + th / 2
        out = jnp.stack([(tx - px) / pw, (ty - py) / ph,
                         jnp.log(tw / pw), jnp.log(th / ph)], axis=-1)
    else:
        t = target_box
        ox = px + pw * t[..., 0]
        oy = py + ph * t[..., 1]
        ow = pw * jnp.exp(t[..., 2])
        oh = ph * jnp.exp(t[..., 3])
        out = jnp.stack([ox - ow / 2, oy - oh / 2, ox + ow / 2, oy + oh / 2], axis=-1)
    return {"OutputBox": [out]}


@register_op("iou_similarity", differentiable=False)
def _iou_similarity(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    area_x = (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
    area_y = (y[:, 2] - y[:, 0]) * (y[:, 3] - y[:, 1])
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0, None)
    inter = wh[..., 0] * wh[..., 1]
    return one(inter / (area_x[:, None] + area_y[None, :] - inter + 1e-10))


@register_op("prior_box", differentiable=False)
def _prior_box(ctx, inputs, attrs):
    (feat,) = inputs["Input"]
    (image,) = inputs["Image"]
    min_sizes = attrs["min_sizes"]
    max_sizes = attrs.get("max_sizes", [])
    ratios = attrs.get("aspect_ratios", [1.0])
    flip = attrs.get("flip", False)
    step = attrs.get("step_w", 0.0)
    offset = attrs.get("offset", 0.5)
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = step or iw / w
    step_h = attrs.get("step_h", 0.0) or ih / h
    ars = list(ratios)
    if flip:
        ars += [1.0 / r for r in ratios if r != 1.0]
    boxes = []
    cx = (jnp.arange(w) + offset) * step_w
    cy = (jnp.arange(h) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)
    for ms in min_sizes:
        for ar in ars:
            bw = ms * (ar ** 0.5) / 2
            bh = ms / (ar ** 0.5) / 2
            boxes.append(jnp.stack([(cxg - bw) / iw, (cyg - bh) / ih,
                                    (cxg + bw) / iw, (cyg + bh) / ih], axis=-1))
        for mx in max_sizes:
            s = (ms * mx) ** 0.5 / 2
            boxes.append(jnp.stack([(cxg - s) / iw, (cyg - s) / ih,
                                    (cxg + s) / iw, (cyg + s) / ih], axis=-1))
    out = jnp.clip(jnp.stack(boxes, axis=2).reshape(h, w, -1, 4), 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(attrs.get("variances", [0.1, 0.1, 0.2, 0.2])), out.shape)
    return {"Boxes": [out], "Variances": [var]}


@register_op("roi_align", nondiff_inputs=["ROIs"])
def _roi_align(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (rois,) = inputs["ROIs"]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    n_rois = rois.shape[0]
    c = x.shape[1]
    # per-ROI source image: optional RoisBatch input [N] (replaces the
    # reference's LoD offsets); absent → all ROIs from image 0
    batch_map = inputs.get("RoisBatch", [jnp.zeros((n_rois,), dtype=jnp.int32)])[0]

    def pool_one(roi, batch_idx):
        x1, y1, x2, y2 = roi[0] * scale, roi[1] * scale, roi[2] * scale, roi[3] * scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        ys = y1 + (jnp.arange(ph) + 0.5) * rh / ph
        xs = x1 + (jnp.arange(pw) + 0.5) * rw / pw
        yg, xg = jnp.meshgrid(ys, xs, indexing="ij")
        y0 = jnp.clip(jnp.floor(yg).astype(jnp.int32), 0, x.shape[2] - 2)
        x0 = jnp.clip(jnp.floor(xg).astype(jnp.int32), 0, x.shape[3] - 2)
        wy = yg - y0
        wx = xg - x0
        img = jnp.take(x, batch_idx, axis=0)
        v00 = img[:, y0, x0]
        v01 = img[:, y0, x0 + 1]
        v10 = img[:, y0 + 1, x0]
        v11 = img[:, y0 + 1, x0 + 1]
        return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                + v10 * wy * (1 - wx) + v11 * wy * wx)

    out = jax.vmap(pool_one)(rois, batch_map)
    return one(out.reshape(n_rois, c, ph, pw))


@register_op("yolo_box", differentiable=False)
def _yolo_box(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (img_size,) = inputs["ImgSize"]
    anchors = attrs["anchors"]
    class_num = attrs["class_num"]
    conf_thresh = attrs.get("conf_thresh", 0.01)
    downsample = attrs.get("downsample_ratio", 32)
    n, _, h, w = x.shape
    na = len(anchors) // 2
    x = x.reshape(n, na, 5 + class_num, h, w)
    grid_x = jnp.arange(w).reshape(1, 1, 1, w)
    grid_y = jnp.arange(h).reshape(1, 1, h, 1)
    bx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / h
    aw = jnp.asarray(anchors[0::2]).reshape(1, na, 1, 1)
    ah = jnp.asarray(anchors[1::2]).reshape(1, na, 1, 1)
    bw = jnp.exp(x[:, :, 2]) * aw / (downsample * w)
    bh = jnp.exp(x[:, :, 3]) * ah / (downsample * h)
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    ih = img_size[:, 0].reshape(n, 1, 1, 1).astype(x.dtype)
    iw = img_size[:, 1].reshape(n, 1, 1, 1).astype(x.dtype)
    boxes = jnp.stack([(bx - bw / 2) * iw, (by - bh / 2) * ih,
                       (bx + bw / 2) * iw, (by + bh / 2) * ih], axis=-1)
    boxes = boxes.reshape(n, -1, 4)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    mask = (conf.reshape(n, -1, 1) > conf_thresh).astype(x.dtype)
    return {"Boxes": [boxes * mask], "Scores": [scores * mask]}
