"""Detection ops — a TPU-friendly subset of operators/detection/ (15.3k LoC in
the reference: yolo, ssd priors, roi_align/pool, nms, ...). Static-shape
variants of the most-used ops; the NMS family returns fixed-size padded
results (XLA cannot produce dynamic row counts).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import one


@register_op("box_coder", differentiable=False)
def _box_coder(ctx, inputs, attrs):
    (prior_box,) = inputs["PriorBox"]
    (target_box,) = inputs["TargetBox"]
    code_type = attrs.get("code_type", "encode_center_size")
    pw = prior_box[:, 2] - prior_box[:, 0]
    ph = prior_box[:, 3] - prior_box[:, 1]
    px = prior_box[:, 0] + pw / 2
    py = prior_box[:, 1] + ph / 2
    if code_type == "encode_center_size":
        tw = target_box[:, 2] - target_box[:, 0]
        th = target_box[:, 3] - target_box[:, 1]
        tx = target_box[:, 0] + tw / 2
        ty = target_box[:, 1] + th / 2
        out = jnp.stack([(tx - px) / pw, (ty - py) / ph,
                         jnp.log(tw / pw), jnp.log(th / ph)], axis=-1)
    else:
        t = target_box
        ox = px + pw * t[..., 0]
        oy = py + ph * t[..., 1]
        ow = pw * jnp.exp(t[..., 2])
        oh = ph * jnp.exp(t[..., 3])
        out = jnp.stack([ox - ow / 2, oy - oh / 2, ox + ow / 2, oy + oh / 2], axis=-1)
    return {"OutputBox": [out]}


@register_op("iou_similarity", differentiable=False)
def _iou_similarity(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    area_x = (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
    area_y = (y[:, 2] - y[:, 0]) * (y[:, 3] - y[:, 1])
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0, None)
    inter = wh[..., 0] * wh[..., 1]
    return one(inter / (area_x[:, None] + area_y[None, :] - inter + 1e-10))


@register_op("prior_box", differentiable=False)
def _prior_box(ctx, inputs, attrs):
    (feat,) = inputs["Input"]
    (image,) = inputs["Image"]
    min_sizes = attrs["min_sizes"]
    max_sizes = attrs.get("max_sizes", [])
    ratios = attrs.get("aspect_ratios", [1.0])
    flip = attrs.get("flip", False)
    step = attrs.get("step_w", 0.0)
    offset = attrs.get("offset", 0.5)
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = step or iw / w
    step_h = attrs.get("step_h", 0.0) or ih / h
    ars = list(ratios)
    if flip:
        ars += [1.0 / r for r in ratios if r != 1.0]
    boxes = []
    cx = (jnp.arange(w) + offset) * step_w
    cy = (jnp.arange(h) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)
    for ms in min_sizes:
        for ar in ars:
            bw = ms * (ar ** 0.5) / 2
            bh = ms / (ar ** 0.5) / 2
            boxes.append(jnp.stack([(cxg - bw) / iw, (cyg - bh) / ih,
                                    (cxg + bw) / iw, (cyg + bh) / ih], axis=-1))
        for mx in max_sizes:
            s = (ms * mx) ** 0.5 / 2
            boxes.append(jnp.stack([(cxg - s) / iw, (cyg - s) / ih,
                                    (cxg + s) / iw, (cyg + s) / ih], axis=-1))
    out = jnp.clip(jnp.stack(boxes, axis=2).reshape(h, w, -1, 4), 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(attrs.get("variances", [0.1, 0.1, 0.2, 0.2])), out.shape)
    return {"Boxes": [out], "Variances": [var]}


@register_op("roi_align", nondiff_inputs=["ROIs"])
def _roi_align(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (rois,) = inputs["ROIs"]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    n_rois = rois.shape[0]
    c = x.shape[1]
    # per-ROI source image: optional RoisBatch input [N] (replaces the
    # reference's LoD offsets); absent → all ROIs from image 0
    batch_map = inputs.get("RoisBatch", [jnp.zeros((n_rois,), dtype=jnp.int32)])[0]

    def pool_one(roi, batch_idx):
        x1, y1, x2, y2 = roi[0] * scale, roi[1] * scale, roi[2] * scale, roi[3] * scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        ys = y1 + (jnp.arange(ph) + 0.5) * rh / ph
        xs = x1 + (jnp.arange(pw) + 0.5) * rw / pw
        yg, xg = jnp.meshgrid(ys, xs, indexing="ij")
        y0 = jnp.clip(jnp.floor(yg).astype(jnp.int32), 0, x.shape[2] - 2)
        x0 = jnp.clip(jnp.floor(xg).astype(jnp.int32), 0, x.shape[3] - 2)
        wy = yg - y0
        wx = xg - x0
        img = jnp.take(x, batch_idx, axis=0)
        v00 = img[:, y0, x0]
        v01 = img[:, y0, x0 + 1]
        v10 = img[:, y0 + 1, x0]
        v11 = img[:, y0 + 1, x0 + 1]
        return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                + v10 * wy * (1 - wx) + v11 * wy * wx)

    out = jax.vmap(pool_one)(rois, batch_map)
    return one(out.reshape(n_rois, c, ph, pw))


@register_op("yolo_box", differentiable=False)
def _yolo_box(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (img_size,) = inputs["ImgSize"]
    anchors = attrs["anchors"]
    class_num = attrs["class_num"]
    conf_thresh = attrs.get("conf_thresh", 0.01)
    downsample = attrs.get("downsample_ratio", 32)
    n, _, h, w = x.shape
    na = len(anchors) // 2
    x = x.reshape(n, na, 5 + class_num, h, w)
    grid_x = jnp.arange(w).reshape(1, 1, 1, w)
    grid_y = jnp.arange(h).reshape(1, 1, h, 1)
    bx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / h
    aw = jnp.asarray(anchors[0::2]).reshape(1, na, 1, 1)
    ah = jnp.asarray(anchors[1::2]).reshape(1, na, 1, 1)
    bw = jnp.exp(x[:, :, 2]) * aw / (downsample * w)
    bh = jnp.exp(x[:, :, 3]) * ah / (downsample * h)
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    ih = img_size[:, 0].reshape(n, 1, 1, 1).astype(x.dtype)
    iw = img_size[:, 1].reshape(n, 1, 1, 1).astype(x.dtype)
    boxes = jnp.stack([(bx - bw / 2) * iw, (by - bh / 2) * ih,
                       (bx + bw / 2) * iw, (by + bh / 2) * ih], axis=-1)
    boxes = boxes.reshape(n, -1, 4)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    mask = (conf.reshape(n, -1, 1) > conf_thresh).astype(x.dtype)
    return {"Boxes": [boxes * mask], "Scores": [scores * mask]}


# ---------------------------------------------------------------------------
# SSD / RCNN detection family (static-shape, padded-output redesigns of
# operators/detection/: multiclass_nms_op.cc, anchor_generator_op.cc,
# density_prior_box_op.cc, roi_pool_op.cc, generate_proposals_op.cc,
# box_clip_op.cc, bipartite_match_op.cc, target_assign_op.cc,
# sigmoid_focal_loss_op.cc, mine_hard_examples_op.cc,
# polygon_box_transform_op.cc, box_decoder_and_assign_op.cc, psroi_pool_op.cc)
# ---------------------------------------------------------------------------

def _nms_single(boxes, scores, iou_thr, score_thr, top_k):
    """Greedy NMS over one class: returns keep mask [N] (static shapes)."""
    n = boxes.shape[0]
    areas = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) * \
        jnp.maximum(boxes[:, 3] - boxes[:, 1], 0)
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    inter = jnp.prod(jnp.maximum(rb - lt, 0), axis=-1)
    iou = inter / jnp.maximum(areas[:, None] + areas[None, :] - inter, 1e-10)

    order = jnp.argsort(-scores)
    iou_o = iou[order][:, order]
    valid = scores[order] > score_thr

    def body(keep, i):
        sup = jnp.any(jnp.where(jnp.arange(n) < i,
                                keep & (iou_o[i] > iou_thr), False))
        k = valid[i] & jnp.logical_not(sup)
        return keep.at[i].set(k), None

    keep0 = jnp.zeros(n, bool)
    keep, _ = jax.lax.scan(body, keep0, jnp.arange(n))
    if top_k > 0:
        rank = jnp.cumsum(keep) - 1
        keep = keep & (rank < top_k)
    # un-sort back to original order
    inv = jnp.zeros(n, jnp.int32).at[order].set(jnp.arange(n))
    return keep[inv]


@register_op("multiclass_nms", differentiable=False)
def _multiclass_nms(ctx, inputs, attrs):
    """multiclass_nms_op.cc, padded: BBoxes [N, M, 4], Scores [N, C, M] →
    Out [N, keep_top_k, 6] rows (label, score, x1, y1, x2, y2), padded with
    label = -1 (the reference emits variable-row LoD; XLA needs static)."""
    (bboxes,) = inputs["BBoxes"]
    (scores,) = inputs["Scores"]
    score_thr = attrs.get("score_threshold", 0.0)
    nms_thr = attrs.get("nms_threshold", 0.3)
    nms_top_k = int(attrs.get("nms_top_k", -1))
    keep_top_k = int(attrs.get("keep_top_k", -1))
    bg = int(attrs.get("background_label", 0))
    n, c, m = scores.shape
    if keep_top_k <= 0:
        keep_top_k = m

    def per_image(bb, sc):
        rows = []
        for cls in range(c):
            if cls == bg:
                continue
            keep = _nms_single(bb, sc[cls], nms_thr, score_thr, nms_top_k)
            s = jnp.where(keep, sc[cls], -1.0)
            rows.append(jnp.concatenate(
                [jnp.full((m, 1), float(cls)), s[:, None], bb], axis=1))
        allr = jnp.concatenate(rows, axis=0)          # [(C-?)·M, 6]
        order = jnp.argsort(-allr[:, 1])
        top = allr[order[:keep_top_k]]
        lab = jnp.where(top[:, 1] > -1.0, top[:, 0], -1.0)
        return jnp.concatenate([lab[:, None], top[:, 1:]], axis=1)

    out = jax.vmap(per_image)(bboxes, scores)
    return {"Out": [out]}


@register_op("anchor_generator", differentiable=False)
def _anchor_generator(ctx, inputs, attrs):
    """anchor_generator_op.cc: per-pixel anchors for an FPN level."""
    (x,) = inputs["Input"]
    sizes = [float(s) for s in attrs["anchor_sizes"]]
    ratios = [float(r) for r in attrs["aspect_ratios"]]
    stride = [float(s) for s in attrs["stride"]]
    offset = attrs.get("offset", 0.5)
    var = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    h, w = x.shape[-2], x.shape[-1]
    cx = (jnp.arange(w) + offset) * stride[0]
    cy = (jnp.arange(h) + offset) * stride[1]
    boxes = []
    for r in ratios:
        for s in sizes:
            aw = s * (r ** 0.5)
            ah = s / (r ** 0.5)
            boxes.append((aw, ah))
    gx, gy = jnp.meshgrid(cx, cy)                      # [H, W]
    anchors = jnp.stack([
        jnp.stack([gx - aw / 2, gy - ah / 2, gx + aw / 2, gy + ah / 2], -1)
        for aw, ah in boxes], axis=2)                  # [H, W, A, 4]
    variances = jnp.broadcast_to(jnp.asarray(var, jnp.float32),
                                 anchors.shape)
    return {"Anchors": [anchors], "Variances": [variances]}


@register_op("density_prior_box", differentiable=False)
def _density_prior_box(ctx, inputs, attrs):
    """density_prior_box_op.cc: dense multi-density SSD priors."""
    (x,) = inputs["Input"]
    (img,) = inputs["Image"]
    fixed_sizes = [float(s) for s in attrs["fixed_sizes"]]
    fixed_ratios = [float(r) for r in attrs["fixed_ratios"]]
    densities = [int(d) for d in attrs["densities"]]
    sw = attrs.get("step_w", 0.0)
    sh = attrs.get("step_h", 0.0)
    offset = attrs.get("offset", 0.5)
    clip = attrs.get("clip", False)
    var = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    fh, fw = x.shape[-2], x.shape[-1]
    ih, iw = img.shape[-2], img.shape[-1]
    step_w = sw if sw > 0 else iw / fw
    step_h = sh if sh > 0 else ih / fh
    pris = []
    for size, density in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * (ratio ** 0.5)
            bh = size / (ratio ** 0.5)
            dstep_w = step_w / density
            dstep_h = step_h / density
            for di in range(density):
                for dj in range(density):
                    pris.append((bw, bh,
                                 (dj + 0.5) * dstep_w - step_w / 2,
                                 (di + 0.5) * dstep_h - step_h / 2))
    cx = (jnp.arange(fw) + offset) * step_w
    cy = (jnp.arange(fh) + offset) * step_h
    gx, gy = jnp.meshgrid(cx, cy)
    out = jnp.stack([
        jnp.stack([(gx + dx - bw / 2) / iw, (gy + dy - bh / 2) / ih,
                   (gx + dx + bw / 2) / iw, (gy + dy + bh / 2) / ih], -1)
        for bw, bh, dx, dy in pris], axis=2)           # [H, W, P, 4]
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    variances = jnp.broadcast_to(jnp.asarray(var, jnp.float32), out.shape)
    return {"Boxes": [out], "Variances": [variances]}


@register_op("roi_pool", nondiff_inputs=["ROIs"])
def _roi_pool(ctx, inputs, attrs):
    """roi_pool_op.cc: max pooling of each ROI into pooled_h × pooled_w."""
    (x,) = inputs["X"]
    (rois,) = inputs["ROIs"]          # [R, 5] (batch_idx, x1, y1, x2, y2)
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = x.shape

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = x[b]                                     # [C, H, W]
        ys = jnp.arange(h)
        xs = jnp.arange(w)
        iy = jnp.clip(((ys[None, :] - y1) * ph) // rh, -1, ph)   # bin of row
        ix = jnp.clip(((xs[None, :] - x1) * pw) // rw, -1, pw)
        out = jnp.full((c, ph, pw), -jnp.inf)
        for bin_y in range(ph):
            for bin_x in range(pw):
                my = ((ys >= y1) & (ys <= y2) & (iy[0] == bin_y))
                mx = ((xs >= x1) & (xs <= x2) & (ix[0] == bin_x))
                mask = my[:, None] & mx[None, :]
                v = jnp.where(mask[None], img, -jnp.inf).max((1, 2))
                out = out.at[:, bin_y, bin_x].set(v)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return {"Out": [jax.vmap(one_roi)(rois.astype(jnp.float32))]}


@register_op("psroi_pool", nondiff_inputs=["ROIs"])
def _psroi_pool(ctx, inputs, attrs):
    """psroi_pool_op.cc: position-sensitive average ROI pooling."""
    (x,) = inputs["X"]
    (rois,) = inputs["ROIs"]
    oc = int(attrs["output_channels"])
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = x.shape

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = roi[1] * scale
        y1 = roi[2] * scale
        x2 = roi[3] * scale
        y2 = roi[4] * scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        img = x[b]
        ys = jnp.arange(h) + 0.5
        xs = jnp.arange(w) + 0.5
        out = jnp.zeros((oc, ph, pw))
        for by in range(ph):
            for bx in range(pw):
                ys0 = y1 + by * rh / ph
                ys1 = y1 + (by + 1) * rh / ph
                xs0 = x1 + bx * rw / pw
                xs1 = x1 + (bx + 1) * rw / pw
                my = (ys >= ys0) & (ys < ys1)
                mx = (xs >= xs0) & (xs < xs1)
                mask = (my[:, None] & mx[None, :]).astype(x.dtype)
                cnt = jnp.maximum(mask.sum(), 1.0)
                # all oc position-sensitive channels of this bin in one
                # strided gather (keeps the trace O(ph·pw), not O(oc·ph·pw))
                chans = (jnp.arange(oc) * ph + by) * pw + bx
                vals = (img[chans] * mask[None]).sum((1, 2)) / cnt
                out = out.at[:, by, bx].set(vals)
        return out

    return {"Out": [jax.vmap(one_roi)(rois.astype(jnp.float32))]}


@register_op("box_clip", differentiable=False)
def _box_clip(ctx, inputs, attrs):
    (boxes,) = inputs["Input"]
    (im_info,) = inputs["ImInfo"]          # [N, 3] (h, w, scale)
    h = im_info[:, 0] - 1.0
    w = im_info[:, 1] - 1.0
    shape = (-1,) + (1,) * (boxes.ndim - 1)
    x1 = jnp.clip(boxes[..., 0::4], 0, w.reshape(shape)[..., 0:1])
    y1 = jnp.clip(boxes[..., 1::4], 0, h.reshape(shape)[..., 0:1])
    x2 = jnp.clip(boxes[..., 2::4], 0, w.reshape(shape)[..., 0:1])
    y2 = jnp.clip(boxes[..., 3::4], 0, h.reshape(shape)[..., 0:1])
    out = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(boxes.shape)
    return {"Output": [out]}


@register_op("bipartite_match", differentiable=False)
def _bipartite_match(ctx, inputs, attrs):
    """bipartite_match_op.cc: greedy max bipartite matching on a [N, M]
    distance matrix (rows = ground truth, cols = priors)."""
    (dist,) = inputs["DistMat"]
    match_type = attrs.get("match_type", "bipartite")
    overlap_thr = attrs.get("dist_threshold", 0.5)
    n, m = dist.shape

    def body(carry, _):
        d, row_match, col_match = carry
        flat = jnp.argmax(d)
        i, j = flat // m, flat % m
        ok = d[i, j] > 0
        row_match = jnp.where(ok, row_match.at[i].set(j), row_match)
        col_match = jnp.where(ok, col_match.at[j].set(i), col_match)
        d = jnp.where(ok, d.at[i, :].set(-1.0).at[:, j].set(-1.0), d)
        return (d, row_match, col_match), None

    init = (dist, jnp.full(n, -1, jnp.int32), jnp.full(m, -1, jnp.int32))
    (_, _, col_match), _ = jax.lax.scan(body, init, None, length=min(n, m))
    col_dist = jnp.where(col_match >= 0,
                         dist[jnp.maximum(col_match, 0), jnp.arange(m)], 0.0)
    if match_type == "per_prediction":
        best_row = jnp.argmax(dist, axis=0)
        best = dist[best_row, jnp.arange(m)]
        extra = (col_match < 0) & (best > overlap_thr)
        col_match = jnp.where(extra, best_row.astype(jnp.int32), col_match)
        col_dist = jnp.where(extra, best, col_dist)
    return {"ColToRowMatchIndices": [col_match[None]],
            "ColToRowMatchDist": [col_dist[None]]}


@register_op("target_assign", differentiable=False)
def _target_assign(ctx, inputs, attrs):
    """target_assign_op.cc: scatter per-prior targets from matched rows."""
    (x,) = inputs["X"]                 # [N?, M_gt, K] gt boxes/labels
    (match,) = inputs["MatchIndices"]  # [N, M_prior]
    mismatch_value = attrs.get("mismatch_value", 0)
    xe = x if x.ndim == 3 else x[None]
    gathered = jnp.take_along_axis(
        xe, jnp.maximum(match, 0)[..., None].astype(jnp.int32), axis=1)
    out = jnp.where((match >= 0)[..., None], gathered,
                    jnp.asarray(mismatch_value, x.dtype))
    wt = (match >= 0).astype(jnp.float32)[..., None]
    return {"Out": [out], "OutWeight": [wt]}


@register_op("sigmoid_focal_loss", nondiff_inputs=["Label", "FgNum"])
def _sigmoid_focal_loss(ctx, inputs, attrs):
    """sigmoid_focal_loss_op.cc: RetinaNet focal loss over [N, C] logits;
    Label [N, 1] in [0, C] (0 = background), FgNum normalizer."""
    (x,) = inputs["X"]
    (label,) = inputs["Label"]
    (fg,) = inputs["FgNum"]
    gamma = attrs.get("gamma", 2.0)
    alpha = attrs.get("alpha", 0.25)
    n, c = x.shape
    lab = label.reshape(-1).astype(jnp.int32)
    t = (lab[:, None] == (jnp.arange(c)[None, :] + 1)).astype(x.dtype)
    p = jax.nn.sigmoid(x)
    ce = jnp.maximum(x, 0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))
    w = t * alpha * jnp.power(1 - p, gamma) + \
        (1 - t) * (1 - alpha) * jnp.power(p, gamma)
    fgn = jnp.maximum(fg.reshape(()).astype(x.dtype), 1.0)
    return {"Out": [w * ce / fgn]}


@register_op("mine_hard_examples", differentiable=False)
def _mine_hard_examples(ctx, inputs, attrs):
    """mine_hard_examples_op.cc (max_negative mining): keep the top
    neg_pos_ratio·#pos highest-loss negatives per image."""
    (cls_loss,) = inputs["ClsLoss"]
    (match,) = inputs["MatchIndices"]
    ratio = attrs.get("neg_pos_ratio", 3.0)
    neg = match < 0
    npos = jnp.sum(match >= 0, axis=1)
    nneg = jnp.minimum((npos * ratio).astype(jnp.int32),
                       jnp.sum(neg, axis=1))
    loss = jnp.where(neg, cls_loss.reshape(match.shape), -jnp.inf)
    order = jnp.argsort(-loss, axis=1)
    rank = jnp.zeros_like(order).at[
        jnp.arange(order.shape[0])[:, None], order].set(
        jnp.broadcast_to(jnp.arange(order.shape[1])[None], order.shape))
    sel = neg & (rank < nneg[:, None])
    return {"NegIndices": [sel.astype(jnp.int32)],
            "UpdatedMatchIndices": [jnp.where(sel, -1, match)]}


@register_op("polygon_box_transform", differentiable=False)
def _polygon_box_transform(ctx, inputs, attrs):
    """polygon_box_transform_op.cc: offset channels → absolute coords
    (in[n, 2k, h, w]: even channels += col·4, odd += row·4 where active)."""
    (x,) = inputs["Input"]
    n, c, h, w = x.shape
    cols = jnp.broadcast_to(jnp.arange(w)[None, :] * 4.0, (h, w))
    rows = jnp.broadcast_to(jnp.arange(h)[:, None] * 4.0, (h, w))
    add = jnp.stack([cols if i % 2 == 0 else rows for i in range(c)])
    return {"Output": [jnp.where(x != 0, add[None] - x, 0.0)]}


@register_op("box_decoder_and_assign", differentiable=False)
def _box_decoder_and_assign(ctx, inputs, attrs):
    """box_decoder_and_assign_op.cc: decode per-class deltas, pick the
    highest-scoring class's box per prior."""
    (prior,) = inputs["PriorBox"]       # [M, 4]
    (pvar,) = inputs["PriorBoxVar"]     # [M, 4]
    (target,) = inputs["TargetBox"]     # [M, 4·C]
    (score,) = inputs["BoxScore"]       # [M, C]
    m, c = score.shape
    pw = prior[:, 2] - prior[:, 0] + 1.0
    phh = prior[:, 3] - prior[:, 1] + 1.0
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + phh * 0.5
    t = target.reshape(m, c, 4) * pvar[:, None, :]
    cx = t[..., 0] * pw[:, None] + pcx[:, None]
    cy = t[..., 1] * phh[:, None] + pcy[:, None]
    bw = jnp.exp(t[..., 2]) * pw[:, None]
    bh = jnp.exp(t[..., 3]) * phh[:, None]
    dec = jnp.stack([cx - bw / 2, cy - bh / 2,
                     cx + bw / 2 - 1, cy + bh / 2 - 1], -1)  # [M, C, 4]
    best = jnp.argmax(score[:, 1:], axis=1) + 1              # skip bg
    assigned = jnp.take_along_axis(
        dec, best[:, None, None].repeat(4, -1), axis=1)[:, 0]
    return {"DecodeBox": [dec.reshape(m, c * 4)],
            "OutputAssignBox": [assigned]}


@register_op("generate_proposals", differentiable=False)
def _generate_proposals(ctx, inputs, attrs):
    """generate_proposals_op.cc, padded: decode anchors with deltas, clip,
    NMS, emit post_nms_topN rows per image (padded by lowest scores)."""
    (scores,) = inputs["Scores"]        # [N, A, H, W]
    (deltas,) = inputs["BboxDeltas"]    # [N, 4A, H, W]
    (im_info,) = inputs["ImInfo"]       # [N, 3]
    (anchors,) = inputs["Anchors"]      # [H, W, A, 4]
    variances = inputs.get("Variances")
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_thr = attrs.get("nms_thresh", 0.7)
    n = scores.shape[0]
    a = anchors.shape[2]
    hw = anchors.shape[0] * anchors.shape[1]
    anc = anchors.reshape(hw * a, 4)
    var = (variances[0].reshape(hw * a, 4) if variances
           else jnp.ones((hw * a, 4), jnp.float32))

    def per_image(sc, dl, info):
        s = sc.transpose(1, 2, 0).reshape(-1)                 # [HWA]
        d = dl.reshape(a, 4, *dl.shape[1:]).transpose(2, 3, 0, 1).reshape(-1, 4)
        aw = anc[:, 2] - anc[:, 0] + 1.0
        ah = anc[:, 3] - anc[:, 1] + 1.0
        acx = anc[:, 0] + aw / 2
        acy = anc[:, 1] + ah / 2
        dv = d * var
        cx = dv[:, 0] * aw + acx
        cy = dv[:, 1] * ah + acy
        bw = jnp.exp(jnp.minimum(dv[:, 2], 10.0)) * aw
        bh = jnp.exp(jnp.minimum(dv[:, 3], 10.0)) * ah
        boxes = jnp.stack([cx - bw / 2, cy - bh / 2,
                           cx + bw / 2 - 1, cy + bh / 2 - 1], -1)
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, info[1] - 1),
            jnp.clip(boxes[:, 1], 0, info[0] - 1),
            jnp.clip(boxes[:, 2], 0, info[1] - 1),
            jnp.clip(boxes[:, 3], 0, info[0] - 1)], -1)
        k = min(pre_n, s.shape[0])
        pn = min(post_n, k)   # small feature maps: fewer anchors than topN
        top_s, top_i = jax.lax.top_k(s, k)
        top_b = boxes[top_i]
        keep = _nms_single(top_b, top_s, nms_thr, -jnp.inf, pn)
        sel_s = jnp.where(keep, top_s, -jnp.inf)
        out_s, oi = jax.lax.top_k(sel_s, pn)
        ob = top_b[oi]
        if pn < post_n:       # pad to the declared static output size
            pad = post_n - pn
            ob = jnp.concatenate([ob, jnp.zeros((pad, 4), ob.dtype)])
            out_s = jnp.concatenate([out_s, jnp.full((pad,), -jnp.inf)])
        return ob, out_s

    rois, rscores = jax.vmap(per_image)(scores, deltas, im_info)
    return {"RpnRois": [rois], "RpnRoiProbs": [rscores]}


# ---------------------------------------------------------------------------
# Round-2 detection family: RPN/RetinaNet target assignment, FPN routing,
# YOLOv3 loss, mAP metric. References: rpn_target_assign_op.cc,
# retinanet_detection_output_op.cc, collect_fpn_proposals_op.cc,
# distribute_fpn_proposals_op.cc, generate_proposal_labels_op.cc,
# yolov3_loss_op.cc, detection_map_op.cc. All static-shape: samplers emit
# fixed-size index/target tensors padded with -1 / zeros, the XLA-friendly
# stand-in for the reference's dynamic LoD row counts.
# ---------------------------------------------------------------------------


def _iou_matrix(a, b):
    """Pairwise IoU [Na, Nb] for corner-format boxes."""
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * jnp.maximum(a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-10)


def _encode_deltas(anchors, gt):
    """Box → regression-delta encoding shared by RPN/RetinaNet assign."""
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + aw * 0.5
    acy = anchors[:, 1] + ah * 0.5
    gw = gt[:, 2] - gt[:, 0] + 1.0
    gh = gt[:, 3] - gt[:, 1] + 1.0
    gcx = gt[:, 0] + gw * 0.5
    gcy = gt[:, 1] + gh * 0.5
    return jnp.stack([(gcx - acx) / aw, (gcy - acy) / ah,
                      jnp.log(jnp.maximum(gw / aw, 1e-10)),
                      jnp.log(jnp.maximum(gh / ah, 1e-10))], axis=-1)


def _topk_mask(score, mask, k):
    """Boolean mask selecting (up to) the k highest-`score` entries of `mask`."""
    s = jnp.where(mask, score, -jnp.inf)
    order = jnp.argsort(-s)
    rank = jnp.zeros(s.shape[0], jnp.int32).at[order].set(jnp.arange(s.shape[0]))
    return mask & (rank < k)


@register_op("rpn_target_assign", differentiable=False)
def _rpn_target_assign(ctx, inputs, attrs):
    """rpn_target_assign_op.cc: label anchors as fg (IoU>pos_thr or per-gt
    argmax) / bg (IoU<neg_thr), subsample to a fixed batch, emit per-anchor
    labels [-1 ignore / 0 bg / 1 fg] and bbox regression targets (dense
    [N, A, ...] — static-shape form of the reference's gathered LoD rows)."""
    (anchors,) = inputs["Anchor"]          # [A, 4]
    (gt_boxes,) = inputs["GtBoxes"]        # [N, G, 4] (zero rows padded)
    batch = int(attrs.get("rpn_batch_size_per_im", 256))
    fg_frac = attrs.get("rpn_fg_fraction", 0.5)
    pos_thr = attrs.get("rpn_positive_overlap", 0.7)
    neg_thr = attrs.get("rpn_negative_overlap", 0.3)

    def per_image(gt, key):
        valid_gt = (gt[:, 2] > gt[:, 0]) & (gt[:, 3] > gt[:, 1])
        iou = jnp.where(valid_gt[None, :], _iou_matrix(anchors, gt), -1.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        # per-gt argmax anchors are always fg; .max (logical-or) so a
        # padding gt whose argmax ties to the same anchor can't clear it
        gt_best_anchor = jnp.argmax(iou, axis=0)                   # [G]
        forced = jnp.zeros(anchors.shape[0], bool)
        forced = forced.at[gt_best_anchor].max(valid_gt)
        fg = forced | (best_iou >= pos_thr)
        bg = (best_iou < neg_thr) & (best_iou >= 0) & ~fg
        # subsample with random tie-break scores
        kf, kb = jax.random.split(key)
        n_fg = int(batch * fg_frac)
        fg = _topk_mask(jax.random.uniform(kf, (anchors.shape[0],)), fg, n_fg)
        n_bg = batch - n_fg
        bg = _topk_mask(jax.random.uniform(kb, (anchors.shape[0],)), bg, n_bg)
        labels = jnp.where(fg, 1, jnp.where(bg, 0, -1)).astype(jnp.int32)
        tgt = _encode_deltas(anchors, gt[best_gt])
        tgt = jnp.where(fg[:, None], tgt, 0.0)
        # gather indices (reference ScoreIndex/LocationIndex contract):
        # sampled-anchor positions, valid entries first, padded with 0 —
        # mask padding via TargetLabel (padded rows have label -1 there)
        prio = jnp.where(fg, 2.0, jnp.where(bg, 1.0, 0.0))
        _, score_idx = jax.lax.top_k(prio, batch)
        score_idx = jnp.where((fg | bg)[score_idx], score_idx, 0).astype(jnp.int32)
        _, loc_idx = jax.lax.top_k(jnp.where(fg, 1.0, 0.0), n_fg)
        loc_idx = jnp.where(fg[loc_idx], loc_idx, 0).astype(jnp.int32)
        return labels, tgt, score_idx, loc_idx

    n = gt_boxes.shape[0]
    n_fg = int(batch * fg_frac)
    keys = jax.random.split(ctx.rng(), n)
    labels, targets, score_idx, loc_idx = jax.vmap(per_image)(gt_boxes, keys)
    return {"ScoreIndex": [score_idx], "LocationIndex": [loc_idx],
            "TargetLabel": [labels], "TargetBBox": [targets],
            "BBoxInsideWeight": [(labels == 1).astype(jnp.float32)]}


@register_op("retinanet_target_assign", differentiable=False)
def _retinanet_target_assign(ctx, inputs, attrs):
    """retinanet_target_assign (rpn_target_assign_op.cc:~500): like RPN
    assign but no subsampling (focal loss owns the imbalance) and class
    labels come from GtLabels."""
    (anchors,) = inputs["Anchor"]
    (gt_boxes,) = inputs["GtBoxes"]        # [N, G, 4]
    (gt_labels,) = inputs["GtLabels"]      # [N, G]
    pos_thr = attrs.get("positive_overlap", 0.5)
    neg_thr = attrs.get("negative_overlap", 0.4)

    def per_image(gt, gl):
        valid_gt = (gt[:, 2] > gt[:, 0]) & (gt[:, 3] > gt[:, 1])
        iou = jnp.where(valid_gt[None, :], _iou_matrix(anchors, gt), -1.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        gt_best_anchor = jnp.argmax(iou, axis=0)
        forced = jnp.zeros(anchors.shape[0], bool).at[gt_best_anchor].max(valid_gt)
        fg = forced | (best_iou >= pos_thr)
        bg = (best_iou < neg_thr) & (best_iou >= 0) & ~fg
        cls = jnp.where(fg, gl[best_gt].astype(jnp.int32), jnp.where(bg, 0, -1))
        tgt = jnp.where(fg[:, None], _encode_deltas(anchors, gt[best_gt]), 0.0)
        return cls, tgt, fg

    labels, targets, fg = jax.vmap(per_image)(gt_boxes, gt_labels)
    fg_num = jnp.maximum(jnp.sum(fg, axis=1), 1).astype(jnp.int32)
    return {"TargetLabel": [labels], "TargetBBox": [targets],
            "BBoxInsideWeight": [fg.astype(jnp.float32)], "ForegroundNumber": [fg_num]}


@register_op("retinanet_detection_output", differentiable=False)
def _retinanet_detection_output(ctx, inputs, attrs):
    """retinanet_detection_output_op.cc: decode per-FPN-level (score, delta,
    anchor) triples, take per-level top-k, merge, class-wise NMS → padded
    [N, keep_top_k, 6] (label, score, x1, y1, x2, y2)."""
    scores_l = inputs["Scores"]            # list of [N, A_l, C]
    deltas_l = inputs["BBoxes"]            # list of [N, A_l, 4]
    anchors_l = inputs["Anchors"]          # list of [A_l, 4]
    (im_info,) = inputs["ImInfo"]          # [N, 3]
    score_thr = attrs.get("score_threshold", 0.05)
    nms_top_k = int(attrs.get("nms_top_k", 1000))
    keep_top_k = int(attrs.get("keep_top_k", 100))
    nms_thr = attrs.get("nms_threshold", 0.3)

    def decode(anchors, deltas):
        aw = anchors[:, 2] - anchors[:, 0] + 1.0
        ah = anchors[:, 3] - anchors[:, 1] + 1.0
        acx = anchors[:, 0] + aw * 0.5
        acy = anchors[:, 1] + ah * 0.5
        cx = deltas[:, 0] * aw + acx
        cy = deltas[:, 1] * ah + acy
        w = jnp.exp(jnp.minimum(deltas[:, 2], 10.0)) * aw
        h = jnp.exp(jnp.minimum(deltas[:, 3], 10.0)) * ah
        return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)

    def per_image(scs, dls, info):
        # scs/dls: tuples with one [A_l, C] / [A_l, 4] entry per FPN level
        boxes_all, scores_all = [], []
        for sc, dl, anc in zip(scs, dls, anchors_l):
            k = min(nms_top_k, sc.shape[0])
            flat = jnp.max(sc, axis=1)                   # best class per anchor
            _, idx = jax.lax.top_k(flat, k)
            boxes_all.append(decode(anc[idx], dl[idx]))
            scores_all.append(sc[idx])
        boxes = jnp.concatenate(boxes_all)               # [M, 4]
        scores = jnp.concatenate(scores_all)             # [M, C]
        boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, info[1] - 1),
                           jnp.clip(boxes[:, 1], 0, info[0] - 1),
                           jnp.clip(boxes[:, 2], 0, info[1] - 1),
                           jnp.clip(boxes[:, 3], 0, info[0] - 1)], -1)
        outs = []
        C = scores.shape[1]
        for c in range(C):
            keep = _nms_single(boxes, scores[:, c], nms_thr, score_thr, keep_top_k)
            s = jnp.where(keep, scores[:, c], -1.0)
            outs.append(jnp.concatenate(
                [jnp.full((s.shape[0], 1), float(c)), s[:, None], boxes], -1))
        det = jnp.concatenate(outs)                      # [C*M, 6]
        _, top = jax.lax.top_k(det[:, 1], keep_top_k)
        return det[top]

    # vmap over the batch axis of every level tensor at once (the levels
    # stay a python tuple; anchors are per-level constants closed over)
    det = jax.vmap(per_image)(tuple(scores_l), tuple(deltas_l), im_info)
    return one(det)


@register_op("collect_fpn_proposals", differentiable=False)
def _collect_fpn_proposals(ctx, inputs, attrs):
    """collect_fpn_proposals_op.cc: concat per-level (rois, scores), keep
    global post_nms_topN by score. Padded [N, topN, 4]."""
    rois_l = inputs["MultiLevelRois"]      # list of [N, R_l, 4]
    scores_l = inputs["MultiLevelScores"]  # list of [N, R_l]
    post_n = int(attrs.get("post_nms_topN", 1000))
    rois = jnp.concatenate(rois_l, axis=1)
    scores = jnp.concatenate(scores_l, axis=1)
    k = min(post_n, scores.shape[1])
    top_s, idx = jax.lax.top_k(scores, k)
    out = jnp.take_along_axis(rois, idx[..., None], axis=1)
    return {"FpnRois": [out], "RoisNum": [jnp.sum(top_s > -jnp.inf, 1).astype(jnp.int32)]}


@register_op("distribute_fpn_proposals", differentiable=False)
def _distribute_fpn_proposals(ctx, inputs, attrs):
    """distribute_fpn_proposals_op.cc: route each RoI to FPN level
    lvl = floor(refer_level + log2(sqrt(area)/refer_scale)); emit per-level
    roi tensors (same static shape, non-members zeroed + mask) and the
    restore index."""
    (rois,) = inputs["FpnRois"]            # [R, 4]
    min_l = int(attrs.get("min_level", 2))
    max_l = int(attrs.get("max_level", 5))
    refer_l = int(attrs.get("refer_level", 4))
    refer_s = float(attrs.get("refer_scale", 224))
    w = jnp.maximum(rois[:, 2] - rois[:, 0], 0.0)
    h = jnp.maximum(rois[:, 3] - rois[:, 1], 0.0)
    scale = jnp.sqrt(w * h)
    lvl = jnp.floor(refer_l + jnp.log2(scale / refer_s + 1e-8))
    lvl = jnp.clip(lvl, min_l, max_l).astype(jnp.int32)
    outs, masks = [], []
    for l in range(min_l, max_l + 1):
        m = lvl == l
        outs.append(jnp.where(m[:, None], rois, 0.0))
        masks.append(m)
    # restore index against OUR uncompacted layout: original row i lives at
    # row (lvl_i - min_level) * R + i of concat(MultiFpnRois), so
    # gather(concat(MultiFpnRois), RestoreIndex) == FpnRois
    r = rois.shape[0]
    restore = ((lvl - min_l) * r + jnp.arange(r, dtype=jnp.int32)).astype(jnp.int32)
    return {"MultiFpnRois": outs,
            "MultiLevelMask": [jnp.stack(masks)],
            "RestoreIndex": [restore]}


@register_op("generate_proposal_labels", differentiable=False)
def _generate_proposal_labels(ctx, inputs, attrs):
    """generate_proposal_labels_op.cc: sample a fixed-size batch of RoIs per
    image against GT (fg if IoU>=fg_thr, bg if lo<=IoU<hi), emit class labels
    + encoded bbox targets, fg-padded with background."""
    (rois,) = inputs["RpnRois"]            # [N, R, 4]
    (gt_boxes,) = inputs["GtBoxes"]        # [N, G, 4]
    (gt_classes,) = inputs["GtClasses"]    # [N, G]
    batch = int(attrs.get("batch_size_per_im", 512))
    fg_frac = attrs.get("fg_fraction", 0.25)
    fg_thr = attrs.get("fg_thresh", 0.5)
    bg_hi = attrs.get("bg_thresh_hi", 0.5)
    bg_lo = attrs.get("bg_thresh_lo", 0.0)
    num_classes = int(attrs.get("class_nums", 81))

    def per_image(r, gt, gc, key):
        valid_gt = (gt[:, 2] > gt[:, 0]) & (gt[:, 3] > gt[:, 1])
        # reference appends gt boxes to the candidate set
        cand = jnp.concatenate([r, gt])
        iou = jnp.where(valid_gt[None, :], _iou_matrix(cand, gt), -1.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        fg = best_iou >= fg_thr
        bg = (best_iou < bg_hi) & (best_iou >= bg_lo)
        kf, kb = jax.random.split(key)
        n_fg = int(batch * fg_frac)
        fg = _topk_mask(jax.random.uniform(kf, fg.shape), fg, n_fg)
        bg = _topk_mask(jax.random.uniform(kb, bg.shape), bg, batch - n_fg)
        sel = fg | bg
        # deterministic static gather: fg first then bg, padded w/ zeros
        prio = jnp.where(fg, 2.0, jnp.where(bg, 1.0, 0.0))
        _, idx = jax.lax.top_k(prio, batch)
        picked = sel[idx]
        out_rois = jnp.where(picked[:, None], cand[idx], 0.0)
        cls = jnp.where(fg[idx], gc[best_gt[idx]].astype(jnp.int32),
                        jnp.where(bg[idx], 0, -1))
        tgt = _encode_deltas(cand[idx], gt[best_gt[idx]])
        tgt = jnp.where(fg[idx][:, None], tgt, 0.0)
        # per-class one-hot expanded targets like bbox_head expects
        w = jax.nn.one_hot(jnp.maximum(cls, 0), num_classes, dtype=jnp.float32)
        w = w * fg[idx][:, None].astype(jnp.float32)
        return out_rois, cls, tgt, w

    n = rois.shape[0]
    keys = jax.random.split(ctx.rng(), n)
    out_rois, labels, targets, weights = jax.vmap(per_image)(
        rois, gt_boxes, gt_classes, keys)
    return {"Rois": [out_rois], "LabelsInt32": [labels],
            "BboxTargets": [targets], "BboxInsideWeights": [weights],
            "BboxOutsideWeights": [weights]}


@register_op("yolov3_loss")
def _yolov3_loss(ctx, inputs, attrs):
    """yolov3_loss_op.cc: single-scale YOLOv3 loss — BCE on objectness &
    class probs, MSE-style (x,y via BCE, w,h via L1) on coordinates for
    responsible anchors. GTBox is [N, B, 4] in (cx, cy, w, h) normalized
    coords, zero rows = padding."""
    (x,) = inputs["X"]                     # [N, A*(5+C), H, W]
    (gt_box,) = inputs["GTBox"]            # [N, B, 4]
    (gt_label,) = inputs["GTLabel"]        # [N, B]
    anchors = attrs["anchors"]             # flat [w0,h0,w1,h1,...] (pixels)
    mask = attrs.get("anchor_mask", list(range(len(anchors) // 2)))
    class_num = int(attrs["class_num"])
    ignore_thresh = attrs.get("ignore_thresh", 0.7)
    downsample = int(attrs.get("downsample_ratio", 32))

    n, _, h, w = x.shape
    na = len(mask)
    input_size = downsample * h
    pred = x.reshape(n, na, 5 + class_num, h, w)
    px = jax.nn.sigmoid(pred[:, :, 0])
    py = jax.nn.sigmoid(pred[:, :, 1])
    pw = pred[:, :, 2]
    ph = pred[:, :, 3]
    pobj = pred[:, :, 4]
    pcls = pred[:, :, 5:]                  # [N, A, C, H, W]

    aw = jnp.asarray([anchors[2 * m] for m in mask], jnp.float32)
    ah = jnp.asarray([anchors[2 * m + 1] for m in mask], jnp.float32)
    all_aw = jnp.asarray(anchors[0::2], jnp.float32)
    all_ah = jnp.asarray(anchors[1::2], jnp.float32)

    gx, gy = jnp.meshgrid(jnp.arange(w, dtype=jnp.float32),
                          jnp.arange(h, dtype=jnp.float32))
    # decoded predicted boxes (normalized) for the ignore-mask IoU test
    bx = (px + gx[None, None]) / w
    by = (py + gy[None, None]) / h
    bw = jnp.exp(jnp.clip(pw, -10, 10)) * aw[None, :, None, None] / input_size
    bh = jnp.exp(jnp.clip(ph, -10, 10)) * ah[None, :, None, None] / input_size

    valid = (gt_box[..., 2] > 0) & (gt_box[..., 3] > 0)   # [N, B]

    def wh_iou(w1, h1, w2, h2):
        inter = jnp.minimum(w1, w2) * jnp.minimum(h1, h2)
        return inter / jnp.maximum(w1 * h1 + w2 * h2 - inter, 1e-10)

    # responsible anchor per gt: best wh-IoU over ALL anchors, must be in mask
    g_w = gt_box[..., 2] * input_size
    g_h = gt_box[..., 3] * input_size
    an_iou = wh_iou(g_w[..., None], g_h[..., None],
                    all_aw[None, None, :], all_ah[None, None, :])  # [N,B,Atot]
    best_a = jnp.argmax(an_iou, axis=-1)                           # [N, B]
    mask_arr = jnp.asarray(mask, jnp.int32)
    in_mask = (best_a[..., None] == mask_arr[None, None, :])       # [N,B,A]
    gi = jnp.clip((gt_box[..., 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gt_box[..., 1] * h).astype(jnp.int32), 0, h - 1)

    # scatter gt info onto the [A, H, W] grid — one vectorized scatter per
    # target tensor over the flattened [B, A] (gt, anchor) pairs; inactive
    # pairs get an out-of-range anchor index and mode='drop' discards them
    def per_image(vld, inm, gix, gjy, gb, gl):
        on = vld[:, None] & inm                              # [B, A]
        ai = jnp.broadcast_to(jnp.arange(na)[None, :], on.shape)
        a_sel = jnp.where(on, ai, na).reshape(-1)            # na == dropped
        gj_f = jnp.broadcast_to(gjy[:, None], on.shape).reshape(-1)
        gi_f = jnp.broadcast_to(gix[:, None], on.shape).reshape(-1)
        sel = (a_sel, gj_f, gi_f)

        def scat(vals):
            v = jnp.broadcast_to(vals, on.shape).reshape(-1)
            return jnp.zeros((na, h, w)).at[sel].set(v, mode="drop")

        obj = scat(1.0)
        tx = scat(gb[:, None, 0] * w - gix[:, None])
        ty = scat(gb[:, None, 1] * h - gjy[:, None])
        tw = scat(jnp.log(jnp.maximum(
            gb[:, None, 2] * input_size / aw[None, :], 1e-9)))
        th = scat(jnp.log(jnp.maximum(
            gb[:, None, 3] * input_size / ah[None, :], 1e-9)))
        tscale = scat(2.0 - (gb[:, None, 2] * gb[:, None, 3]))
        cls_f = jnp.broadcast_to(
            jnp.clip(gl, 0, class_num - 1)[:, None], on.shape).reshape(-1)
        tcls = jnp.zeros((na, class_num, h, w)).at[
            (a_sel, cls_f, gj_f, gi_f)].set(1.0, mode="drop")
        return obj, tx, ty, tw, th, tscale, tcls

    obj, tx, ty, tw_t, th_t, tscale, tcls = jax.vmap(per_image)(
        valid, in_mask, gi, gj, gt_box, gt_label)

    # ignore mask: predicted boxes with IoU > thresh vs any gt are not negatives
    def box_iou_vs_gt(bxi, byi, bwi, bhi, gb, vld):
        p = jnp.stack([bxi - bwi / 2, byi - bhi / 2,
                       bxi + bwi / 2, byi + bhi / 2], -1).reshape(-1, 4)
        g = jnp.stack([gb[:, 0] - gb[:, 2] / 2, gb[:, 1] - gb[:, 3] / 2,
                       gb[:, 0] + gb[:, 2] / 2, gb[:, 1] + gb[:, 3] / 2], -1)
        iou = jnp.where(vld[None, :], _iou_matrix(p, g), 0.0)
        return jnp.max(iou, axis=1).reshape(bxi.shape)

    best_iou = jax.vmap(box_iou_vs_gt)(bx, by, bw, bh, gt_box, valid)
    noobj = (obj == 0) & (best_iou <= ignore_thresh)

    def bce(logit, target):
        return jnp.maximum(logit, 0) - logit * target + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    loss_xy = tscale * obj * (bce(pred[:, :, 0], tx) + bce(pred[:, :, 1], ty))
    loss_wh = tscale * obj * (jnp.abs(pw - tw_t) + jnp.abs(ph - th_t))
    loss_obj = obj * bce(pobj, 1.0) + noobj * bce(pobj, 0.0)
    loss_cls = obj[:, :, None] * bce(pcls, tcls)
    loss = (loss_xy.sum((1, 2, 3)) + loss_wh.sum((1, 2, 3)) +
            loss_obj.sum((1, 2, 3)) + loss_cls.sum((1, 2, 3, 4)))
    return {"Loss": [loss]}


@register_op("detection_map", differentiable=False)
def _detection_map(ctx, inputs, attrs):
    """detection_map_op.cc: mAP over padded detections [N, D, 6]
    (label, score, box) vs gt [N, G, 5] (label, box). 'integral' or '11point'
    average precision, single-batch (no accumulated state)."""
    (dets,) = inputs["DetectRes"]
    (gts,) = inputs["Label"]
    iou_thr = attrs.get("overlap_threshold", 0.5)
    ap_type = attrs.get("ap_type", "integral")
    class_num = int(attrs.get("class_num", 21))

    N, D, _ = dets.shape
    G = gts.shape[1]
    aps = []
    gt_valid = gts[..., 3] > gts[..., 1]      # non-degenerate box
    for c in range(1, class_num):
        c_det = dets[..., 0] == c
        c_gt = gt_valid & (gts[..., 0] == c)
        npos = jnp.sum(c_gt)

        def per_image(det, dmask, gt, gmask):
            iou = _iou_matrix(det[:, 2:6], gt[:, 1:5])
            iou = jnp.where(gmask[None, :], iou, -1.0)
            order = jnp.argsort(-det[:, 1])

            def body(used, i):
                d = order[i]
                best = jnp.argmax(jnp.where(used, -1.0, iou[d]))
                ok = dmask[d] & (iou[d, best] >= iou_thr) & ~used[best]
                return used.at[best].set(used[best] | ok), \
                    jnp.where(dmask[d], jnp.where(ok, 1.0, -1.0), 0.0)

            used0 = jnp.zeros(G, bool)
            _, tp_fp = jax.lax.scan(body, used0, jnp.arange(D))
            return det[order, 1], tp_fp        # scores sorted desc, ±1 flags

        scores, flags = jax.vmap(per_image)(dets, c_det, gts, c_gt)
        scores = scores.reshape(-1)
        flags = flags.reshape(-1)
        order = jnp.argsort(-scores)
        f = flags[order]
        tp = jnp.cumsum(f == 1.0)
        fp = jnp.cumsum(f == -1.0)
        recall = tp / jnp.maximum(npos, 1)
        precision = tp / jnp.maximum(tp + fp, 1)
        if ap_type == "11point":
            pts = [jnp.max(jnp.where(recall >= t, precision, 0.0))
                   for t in jnp.linspace(0, 1, 11)]
            ap = jnp.mean(jnp.stack(pts))
        else:
            dr = jnp.diff(recall, prepend=0.0)
            ap = jnp.sum(precision * dr)
        aps.append(jnp.where(npos > 0, ap, jnp.nan))
    aps = jnp.stack(aps)
    m_ap = jnp.nanmean(aps)
    return {"MAP": [jnp.where(jnp.isnan(m_ap), 0.0, m_ap)]}
