"""Detection ops — a TPU-friendly subset of operators/detection/ (15.3k LoC in
the reference: yolo, ssd priors, roi_align/pool, nms, ...). Static-shape
variants of the most-used ops; the NMS family returns fixed-size padded
results (XLA cannot produce dynamic row counts).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import one


@register_op("box_coder", differentiable=False)
def _box_coder(ctx, inputs, attrs):
    (prior_box,) = inputs["PriorBox"]
    (target_box,) = inputs["TargetBox"]
    code_type = attrs.get("code_type", "encode_center_size")
    pw = prior_box[:, 2] - prior_box[:, 0]
    ph = prior_box[:, 3] - prior_box[:, 1]
    px = prior_box[:, 0] + pw / 2
    py = prior_box[:, 1] + ph / 2
    if code_type == "encode_center_size":
        tw = target_box[:, 2] - target_box[:, 0]
        th = target_box[:, 3] - target_box[:, 1]
        tx = target_box[:, 0] + tw / 2
        ty = target_box[:, 1] + th / 2
        out = jnp.stack([(tx - px) / pw, (ty - py) / ph,
                         jnp.log(tw / pw), jnp.log(th / ph)], axis=-1)
    else:
        t = target_box
        ox = px + pw * t[..., 0]
        oy = py + ph * t[..., 1]
        ow = pw * jnp.exp(t[..., 2])
        oh = ph * jnp.exp(t[..., 3])
        out = jnp.stack([ox - ow / 2, oy - oh / 2, ox + ow / 2, oy + oh / 2], axis=-1)
    return {"OutputBox": [out]}


@register_op("iou_similarity", differentiable=False)
def _iou_similarity(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    area_x = (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
    area_y = (y[:, 2] - y[:, 0]) * (y[:, 3] - y[:, 1])
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0, None)
    inter = wh[..., 0] * wh[..., 1]
    return one(inter / (area_x[:, None] + area_y[None, :] - inter + 1e-10))


@register_op("prior_box", differentiable=False)
def _prior_box(ctx, inputs, attrs):
    (feat,) = inputs["Input"]
    (image,) = inputs["Image"]
    min_sizes = attrs["min_sizes"]
    max_sizes = attrs.get("max_sizes", [])
    ratios = attrs.get("aspect_ratios", [1.0])
    flip = attrs.get("flip", False)
    step = attrs.get("step_w", 0.0)
    offset = attrs.get("offset", 0.5)
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = step or iw / w
    step_h = attrs.get("step_h", 0.0) or ih / h
    ars = list(ratios)
    if flip:
        ars += [1.0 / r for r in ratios if r != 1.0]
    boxes = []
    cx = (jnp.arange(w) + offset) * step_w
    cy = (jnp.arange(h) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)
    for ms in min_sizes:
        for ar in ars:
            bw = ms * (ar ** 0.5) / 2
            bh = ms / (ar ** 0.5) / 2
            boxes.append(jnp.stack([(cxg - bw) / iw, (cyg - bh) / ih,
                                    (cxg + bw) / iw, (cyg + bh) / ih], axis=-1))
        for mx in max_sizes:
            s = (ms * mx) ** 0.5 / 2
            boxes.append(jnp.stack([(cxg - s) / iw, (cyg - s) / ih,
                                    (cxg + s) / iw, (cyg + s) / ih], axis=-1))
    out = jnp.clip(jnp.stack(boxes, axis=2).reshape(h, w, -1, 4), 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(attrs.get("variances", [0.1, 0.1, 0.2, 0.2])), out.shape)
    return {"Boxes": [out], "Variances": [var]}


@register_op("roi_align", nondiff_inputs=["ROIs"])
def _roi_align(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (rois,) = inputs["ROIs"]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    n_rois = rois.shape[0]
    c = x.shape[1]
    # per-ROI source image: optional RoisBatch input [N] (replaces the
    # reference's LoD offsets); absent → all ROIs from image 0
    batch_map = inputs.get("RoisBatch", [jnp.zeros((n_rois,), dtype=jnp.int32)])[0]

    def pool_one(roi, batch_idx):
        x1, y1, x2, y2 = roi[0] * scale, roi[1] * scale, roi[2] * scale, roi[3] * scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        ys = y1 + (jnp.arange(ph) + 0.5) * rh / ph
        xs = x1 + (jnp.arange(pw) + 0.5) * rw / pw
        yg, xg = jnp.meshgrid(ys, xs, indexing="ij")
        y0 = jnp.clip(jnp.floor(yg).astype(jnp.int32), 0, x.shape[2] - 2)
        x0 = jnp.clip(jnp.floor(xg).astype(jnp.int32), 0, x.shape[3] - 2)
        wy = yg - y0
        wx = xg - x0
        img = jnp.take(x, batch_idx, axis=0)
        v00 = img[:, y0, x0]
        v01 = img[:, y0, x0 + 1]
        v10 = img[:, y0 + 1, x0]
        v11 = img[:, y0 + 1, x0 + 1]
        return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                + v10 * wy * (1 - wx) + v11 * wy * wx)

    out = jax.vmap(pool_one)(rois, batch_map)
    return one(out.reshape(n_rois, c, ph, pw))


@register_op("yolo_box", differentiable=False)
def _yolo_box(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (img_size,) = inputs["ImgSize"]
    anchors = attrs["anchors"]
    class_num = attrs["class_num"]
    conf_thresh = attrs.get("conf_thresh", 0.01)
    downsample = attrs.get("downsample_ratio", 32)
    n, _, h, w = x.shape
    na = len(anchors) // 2
    x = x.reshape(n, na, 5 + class_num, h, w)
    grid_x = jnp.arange(w).reshape(1, 1, 1, w)
    grid_y = jnp.arange(h).reshape(1, 1, h, 1)
    bx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / h
    aw = jnp.asarray(anchors[0::2]).reshape(1, na, 1, 1)
    ah = jnp.asarray(anchors[1::2]).reshape(1, na, 1, 1)
    bw = jnp.exp(x[:, :, 2]) * aw / (downsample * w)
    bh = jnp.exp(x[:, :, 3]) * ah / (downsample * h)
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    ih = img_size[:, 0].reshape(n, 1, 1, 1).astype(x.dtype)
    iw = img_size[:, 1].reshape(n, 1, 1, 1).astype(x.dtype)
    boxes = jnp.stack([(bx - bw / 2) * iw, (by - bh / 2) * ih,
                       (bx + bw / 2) * iw, (by + bh / 2) * ih], axis=-1)
    boxes = boxes.reshape(n, -1, 4)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    mask = (conf.reshape(n, -1, 1) > conf_thresh).astype(x.dtype)
    return {"Boxes": [boxes * mask], "Scores": [scores * mask]}


# ---------------------------------------------------------------------------
# SSD / RCNN detection family (static-shape, padded-output redesigns of
# operators/detection/: multiclass_nms_op.cc, anchor_generator_op.cc,
# density_prior_box_op.cc, roi_pool_op.cc, generate_proposals_op.cc,
# box_clip_op.cc, bipartite_match_op.cc, target_assign_op.cc,
# sigmoid_focal_loss_op.cc, mine_hard_examples_op.cc,
# polygon_box_transform_op.cc, box_decoder_and_assign_op.cc, psroi_pool_op.cc)
# ---------------------------------------------------------------------------

def _nms_single(boxes, scores, iou_thr, score_thr, top_k):
    """Greedy NMS over one class: returns keep mask [N] (static shapes)."""
    n = boxes.shape[0]
    areas = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) * \
        jnp.maximum(boxes[:, 3] - boxes[:, 1], 0)
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    inter = jnp.prod(jnp.maximum(rb - lt, 0), axis=-1)
    iou = inter / jnp.maximum(areas[:, None] + areas[None, :] - inter, 1e-10)

    order = jnp.argsort(-scores)
    iou_o = iou[order][:, order]
    valid = scores[order] > score_thr

    def body(keep, i):
        sup = jnp.any(jnp.where(jnp.arange(n) < i,
                                keep & (iou_o[i] > iou_thr), False))
        k = valid[i] & jnp.logical_not(sup)
        return keep.at[i].set(k), None

    keep0 = jnp.zeros(n, bool)
    keep, _ = jax.lax.scan(body, keep0, jnp.arange(n))
    if top_k > 0:
        rank = jnp.cumsum(keep) - 1
        keep = keep & (rank < top_k)
    # un-sort back to original order
    inv = jnp.zeros(n, jnp.int32).at[order].set(jnp.arange(n))
    return keep[inv]


@register_op("multiclass_nms", differentiable=False)
def _multiclass_nms(ctx, inputs, attrs):
    """multiclass_nms_op.cc, padded: BBoxes [N, M, 4], Scores [N, C, M] →
    Out [N, keep_top_k, 6] rows (label, score, x1, y1, x2, y2), padded with
    label = -1 (the reference emits variable-row LoD; XLA needs static)."""
    (bboxes,) = inputs["BBoxes"]
    (scores,) = inputs["Scores"]
    score_thr = attrs.get("score_threshold", 0.0)
    nms_thr = attrs.get("nms_threshold", 0.3)
    nms_top_k = int(attrs.get("nms_top_k", -1))
    keep_top_k = int(attrs.get("keep_top_k", -1))
    bg = int(attrs.get("background_label", 0))
    n, c, m = scores.shape
    if keep_top_k <= 0:
        keep_top_k = m

    def per_image(bb, sc):
        rows = []
        for cls in range(c):
            if cls == bg:
                continue
            keep = _nms_single(bb, sc[cls], nms_thr, score_thr, nms_top_k)
            s = jnp.where(keep, sc[cls], -1.0)
            rows.append(jnp.concatenate(
                [jnp.full((m, 1), float(cls)), s[:, None], bb], axis=1))
        allr = jnp.concatenate(rows, axis=0)          # [(C-?)·M, 6]
        order = jnp.argsort(-allr[:, 1])
        top = allr[order[:keep_top_k]]
        lab = jnp.where(top[:, 1] > -1.0, top[:, 0], -1.0)
        return jnp.concatenate([lab[:, None], top[:, 1:]], axis=1)

    out = jax.vmap(per_image)(bboxes, scores)
    return {"Out": [out]}


@register_op("anchor_generator", differentiable=False)
def _anchor_generator(ctx, inputs, attrs):
    """anchor_generator_op.cc: per-pixel anchors for an FPN level."""
    (x,) = inputs["Input"]
    sizes = [float(s) for s in attrs["anchor_sizes"]]
    ratios = [float(r) for r in attrs["aspect_ratios"]]
    stride = [float(s) for s in attrs["stride"]]
    offset = attrs.get("offset", 0.5)
    var = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    h, w = x.shape[-2], x.shape[-1]
    cx = (jnp.arange(w) + offset) * stride[0]
    cy = (jnp.arange(h) + offset) * stride[1]
    boxes = []
    for r in ratios:
        for s in sizes:
            aw = s * (r ** 0.5)
            ah = s / (r ** 0.5)
            boxes.append((aw, ah))
    gx, gy = jnp.meshgrid(cx, cy)                      # [H, W]
    anchors = jnp.stack([
        jnp.stack([gx - aw / 2, gy - ah / 2, gx + aw / 2, gy + ah / 2], -1)
        for aw, ah in boxes], axis=2)                  # [H, W, A, 4]
    variances = jnp.broadcast_to(jnp.asarray(var, jnp.float32),
                                 anchors.shape)
    return {"Anchors": [anchors], "Variances": [variances]}


@register_op("density_prior_box", differentiable=False)
def _density_prior_box(ctx, inputs, attrs):
    """density_prior_box_op.cc: dense multi-density SSD priors."""
    (x,) = inputs["Input"]
    (img,) = inputs["Image"]
    fixed_sizes = [float(s) for s in attrs["fixed_sizes"]]
    fixed_ratios = [float(r) for r in attrs["fixed_ratios"]]
    densities = [int(d) for d in attrs["densities"]]
    sw = attrs.get("step_w", 0.0)
    sh = attrs.get("step_h", 0.0)
    offset = attrs.get("offset", 0.5)
    clip = attrs.get("clip", False)
    var = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    fh, fw = x.shape[-2], x.shape[-1]
    ih, iw = img.shape[-2], img.shape[-1]
    step_w = sw if sw > 0 else iw / fw
    step_h = sh if sh > 0 else ih / fh
    pris = []
    for size, density in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * (ratio ** 0.5)
            bh = size / (ratio ** 0.5)
            dstep_w = step_w / density
            dstep_h = step_h / density
            for di in range(density):
                for dj in range(density):
                    pris.append((bw, bh,
                                 (dj + 0.5) * dstep_w - step_w / 2,
                                 (di + 0.5) * dstep_h - step_h / 2))
    cx = (jnp.arange(fw) + offset) * step_w
    cy = (jnp.arange(fh) + offset) * step_h
    gx, gy = jnp.meshgrid(cx, cy)
    out = jnp.stack([
        jnp.stack([(gx + dx - bw / 2) / iw, (gy + dy - bh / 2) / ih,
                   (gx + dx + bw / 2) / iw, (gy + dy + bh / 2) / ih], -1)
        for bw, bh, dx, dy in pris], axis=2)           # [H, W, P, 4]
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    variances = jnp.broadcast_to(jnp.asarray(var, jnp.float32), out.shape)
    return {"Boxes": [out], "Variances": [variances]}


@register_op("roi_pool", nondiff_inputs=["ROIs"])
def _roi_pool(ctx, inputs, attrs):
    """roi_pool_op.cc: max pooling of each ROI into pooled_h × pooled_w."""
    (x,) = inputs["X"]
    (rois,) = inputs["ROIs"]          # [R, 5] (batch_idx, x1, y1, x2, y2)
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = x.shape

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = x[b]                                     # [C, H, W]
        ys = jnp.arange(h)
        xs = jnp.arange(w)
        iy = jnp.clip(((ys[None, :] - y1) * ph) // rh, -1, ph)   # bin of row
        ix = jnp.clip(((xs[None, :] - x1) * pw) // rw, -1, pw)
        out = jnp.full((c, ph, pw), -jnp.inf)
        for bin_y in range(ph):
            for bin_x in range(pw):
                my = ((ys >= y1) & (ys <= y2) & (iy[0] == bin_y))
                mx = ((xs >= x1) & (xs <= x2) & (ix[0] == bin_x))
                mask = my[:, None] & mx[None, :]
                v = jnp.where(mask[None], img, -jnp.inf).max((1, 2))
                out = out.at[:, bin_y, bin_x].set(v)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return {"Out": [jax.vmap(one_roi)(rois.astype(jnp.float32))]}


@register_op("psroi_pool", nondiff_inputs=["ROIs"])
def _psroi_pool(ctx, inputs, attrs):
    """psroi_pool_op.cc: position-sensitive average ROI pooling."""
    (x,) = inputs["X"]
    (rois,) = inputs["ROIs"]
    oc = int(attrs["output_channels"])
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = x.shape

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = roi[1] * scale
        y1 = roi[2] * scale
        x2 = roi[3] * scale
        y2 = roi[4] * scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        img = x[b]
        ys = jnp.arange(h) + 0.5
        xs = jnp.arange(w) + 0.5
        out = jnp.zeros((oc, ph, pw))
        for by in range(ph):
            for bx in range(pw):
                ys0 = y1 + by * rh / ph
                ys1 = y1 + (by + 1) * rh / ph
                xs0 = x1 + bx * rw / pw
                xs1 = x1 + (bx + 1) * rw / pw
                my = (ys >= ys0) & (ys < ys1)
                mx = (xs >= xs0) & (xs < xs1)
                mask = (my[:, None] & mx[None, :]).astype(x.dtype)
                cnt = jnp.maximum(mask.sum(), 1.0)
                # all oc position-sensitive channels of this bin in one
                # strided gather (keeps the trace O(ph·pw), not O(oc·ph·pw))
                chans = (jnp.arange(oc) * ph + by) * pw + bx
                vals = (img[chans] * mask[None]).sum((1, 2)) / cnt
                out = out.at[:, by, bx].set(vals)
        return out

    return {"Out": [jax.vmap(one_roi)(rois.astype(jnp.float32))]}


@register_op("box_clip", differentiable=False)
def _box_clip(ctx, inputs, attrs):
    (boxes,) = inputs["Input"]
    (im_info,) = inputs["ImInfo"]          # [N, 3] (h, w, scale)
    h = im_info[:, 0] - 1.0
    w = im_info[:, 1] - 1.0
    shape = (-1,) + (1,) * (boxes.ndim - 1)
    x1 = jnp.clip(boxes[..., 0::4], 0, w.reshape(shape)[..., 0:1])
    y1 = jnp.clip(boxes[..., 1::4], 0, h.reshape(shape)[..., 0:1])
    x2 = jnp.clip(boxes[..., 2::4], 0, w.reshape(shape)[..., 0:1])
    y2 = jnp.clip(boxes[..., 3::4], 0, h.reshape(shape)[..., 0:1])
    out = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(boxes.shape)
    return {"Output": [out]}


@register_op("bipartite_match", differentiable=False)
def _bipartite_match(ctx, inputs, attrs):
    """bipartite_match_op.cc: greedy max bipartite matching on a [N, M]
    distance matrix (rows = ground truth, cols = priors)."""
    (dist,) = inputs["DistMat"]
    match_type = attrs.get("match_type", "bipartite")
    overlap_thr = attrs.get("dist_threshold", 0.5)
    n, m = dist.shape

    def body(carry, _):
        d, row_match, col_match = carry
        flat = jnp.argmax(d)
        i, j = flat // m, flat % m
        ok = d[i, j] > 0
        row_match = jnp.where(ok, row_match.at[i].set(j), row_match)
        col_match = jnp.where(ok, col_match.at[j].set(i), col_match)
        d = jnp.where(ok, d.at[i, :].set(-1.0).at[:, j].set(-1.0), d)
        return (d, row_match, col_match), None

    init = (dist, jnp.full(n, -1, jnp.int32), jnp.full(m, -1, jnp.int32))
    (_, _, col_match), _ = jax.lax.scan(body, init, None, length=min(n, m))
    col_dist = jnp.where(col_match >= 0,
                         dist[jnp.maximum(col_match, 0), jnp.arange(m)], 0.0)
    if match_type == "per_prediction":
        best_row = jnp.argmax(dist, axis=0)
        best = dist[best_row, jnp.arange(m)]
        extra = (col_match < 0) & (best > overlap_thr)
        col_match = jnp.where(extra, best_row.astype(jnp.int32), col_match)
        col_dist = jnp.where(extra, best, col_dist)
    return {"ColToRowMatchIndices": [col_match[None]],
            "ColToRowMatchDist": [col_dist[None]]}


@register_op("target_assign", differentiable=False)
def _target_assign(ctx, inputs, attrs):
    """target_assign_op.cc: scatter per-prior targets from matched rows."""
    (x,) = inputs["X"]                 # [N?, M_gt, K] gt boxes/labels
    (match,) = inputs["MatchIndices"]  # [N, M_prior]
    mismatch_value = attrs.get("mismatch_value", 0)
    xe = x if x.ndim == 3 else x[None]
    gathered = jnp.take_along_axis(
        xe, jnp.maximum(match, 0)[..., None].astype(jnp.int32), axis=1)
    out = jnp.where((match >= 0)[..., None], gathered,
                    jnp.asarray(mismatch_value, x.dtype))
    wt = (match >= 0).astype(jnp.float32)[..., None]
    return {"Out": [out], "OutWeight": [wt]}


@register_op("sigmoid_focal_loss", nondiff_inputs=["Label", "FgNum"])
def _sigmoid_focal_loss(ctx, inputs, attrs):
    """sigmoid_focal_loss_op.cc: RetinaNet focal loss over [N, C] logits;
    Label [N, 1] in [0, C] (0 = background), FgNum normalizer."""
    (x,) = inputs["X"]
    (label,) = inputs["Label"]
    (fg,) = inputs["FgNum"]
    gamma = attrs.get("gamma", 2.0)
    alpha = attrs.get("alpha", 0.25)
    n, c = x.shape
    lab = label.reshape(-1).astype(jnp.int32)
    t = (lab[:, None] == (jnp.arange(c)[None, :] + 1)).astype(x.dtype)
    p = jax.nn.sigmoid(x)
    ce = jnp.maximum(x, 0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))
    w = t * alpha * jnp.power(1 - p, gamma) + \
        (1 - t) * (1 - alpha) * jnp.power(p, gamma)
    fgn = jnp.maximum(fg.reshape(()).astype(x.dtype), 1.0)
    return {"Out": [w * ce / fgn]}


@register_op("mine_hard_examples", differentiable=False)
def _mine_hard_examples(ctx, inputs, attrs):
    """mine_hard_examples_op.cc (max_negative mining): keep the top
    neg_pos_ratio·#pos highest-loss negatives per image."""
    (cls_loss,) = inputs["ClsLoss"]
    (match,) = inputs["MatchIndices"]
    ratio = attrs.get("neg_pos_ratio", 3.0)
    neg = match < 0
    npos = jnp.sum(match >= 0, axis=1)
    nneg = jnp.minimum((npos * ratio).astype(jnp.int32),
                       jnp.sum(neg, axis=1))
    loss = jnp.where(neg, cls_loss.reshape(match.shape), -jnp.inf)
    order = jnp.argsort(-loss, axis=1)
    rank = jnp.zeros_like(order).at[
        jnp.arange(order.shape[0])[:, None], order].set(
        jnp.broadcast_to(jnp.arange(order.shape[1])[None], order.shape))
    sel = neg & (rank < nneg[:, None])
    return {"NegIndices": [sel.astype(jnp.int32)],
            "UpdatedMatchIndices": [jnp.where(sel, -1, match)]}


@register_op("polygon_box_transform", differentiable=False)
def _polygon_box_transform(ctx, inputs, attrs):
    """polygon_box_transform_op.cc: offset channels → absolute coords
    (in[n, 2k, h, w]: even channels += col·4, odd += row·4 where active)."""
    (x,) = inputs["Input"]
    n, c, h, w = x.shape
    cols = jnp.broadcast_to(jnp.arange(w)[None, :] * 4.0, (h, w))
    rows = jnp.broadcast_to(jnp.arange(h)[:, None] * 4.0, (h, w))
    add = jnp.stack([cols if i % 2 == 0 else rows for i in range(c)])
    return {"Output": [jnp.where(x != 0, add[None] - x, 0.0)]}


@register_op("box_decoder_and_assign", differentiable=False)
def _box_decoder_and_assign(ctx, inputs, attrs):
    """box_decoder_and_assign_op.cc: decode per-class deltas, pick the
    highest-scoring class's box per prior."""
    (prior,) = inputs["PriorBox"]       # [M, 4]
    (pvar,) = inputs["PriorBoxVar"]     # [M, 4]
    (target,) = inputs["TargetBox"]     # [M, 4·C]
    (score,) = inputs["BoxScore"]       # [M, C]
    m, c = score.shape
    pw = prior[:, 2] - prior[:, 0] + 1.0
    phh = prior[:, 3] - prior[:, 1] + 1.0
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + phh * 0.5
    t = target.reshape(m, c, 4) * pvar[:, None, :]
    cx = t[..., 0] * pw[:, None] + pcx[:, None]
    cy = t[..., 1] * phh[:, None] + pcy[:, None]
    bw = jnp.exp(t[..., 2]) * pw[:, None]
    bh = jnp.exp(t[..., 3]) * phh[:, None]
    dec = jnp.stack([cx - bw / 2, cy - bh / 2,
                     cx + bw / 2 - 1, cy + bh / 2 - 1], -1)  # [M, C, 4]
    best = jnp.argmax(score[:, 1:], axis=1) + 1              # skip bg
    assigned = jnp.take_along_axis(
        dec, best[:, None, None].repeat(4, -1), axis=1)[:, 0]
    return {"DecodeBox": [dec.reshape(m, c * 4)],
            "OutputAssignBox": [assigned]}


@register_op("generate_proposals", differentiable=False)
def _generate_proposals(ctx, inputs, attrs):
    """generate_proposals_op.cc, padded: decode anchors with deltas, clip,
    NMS, emit post_nms_topN rows per image (padded by lowest scores)."""
    (scores,) = inputs["Scores"]        # [N, A, H, W]
    (deltas,) = inputs["BboxDeltas"]    # [N, 4A, H, W]
    (im_info,) = inputs["ImInfo"]       # [N, 3]
    (anchors,) = inputs["Anchors"]      # [H, W, A, 4]
    variances = inputs.get("Variances")
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_thr = attrs.get("nms_thresh", 0.7)
    n = scores.shape[0]
    a = anchors.shape[2]
    hw = anchors.shape[0] * anchors.shape[1]
    anc = anchors.reshape(hw * a, 4)
    var = (variances[0].reshape(hw * a, 4) if variances
           else jnp.ones((hw * a, 4), jnp.float32))

    def per_image(sc, dl, info):
        s = sc.transpose(1, 2, 0).reshape(-1)                 # [HWA]
        d = dl.reshape(a, 4, *dl.shape[1:]).transpose(2, 3, 0, 1).reshape(-1, 4)
        aw = anc[:, 2] - anc[:, 0] + 1.0
        ah = anc[:, 3] - anc[:, 1] + 1.0
        acx = anc[:, 0] + aw / 2
        acy = anc[:, 1] + ah / 2
        dv = d * var
        cx = dv[:, 0] * aw + acx
        cy = dv[:, 1] * ah + acy
        bw = jnp.exp(jnp.minimum(dv[:, 2], 10.0)) * aw
        bh = jnp.exp(jnp.minimum(dv[:, 3], 10.0)) * ah
        boxes = jnp.stack([cx - bw / 2, cy - bh / 2,
                           cx + bw / 2 - 1, cy + bh / 2 - 1], -1)
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, info[1] - 1),
            jnp.clip(boxes[:, 1], 0, info[0] - 1),
            jnp.clip(boxes[:, 2], 0, info[1] - 1),
            jnp.clip(boxes[:, 3], 0, info[0] - 1)], -1)
        k = min(pre_n, s.shape[0])
        pn = min(post_n, k)   # small feature maps: fewer anchors than topN
        top_s, top_i = jax.lax.top_k(s, k)
        top_b = boxes[top_i]
        keep = _nms_single(top_b, top_s, nms_thr, -jnp.inf, pn)
        sel_s = jnp.where(keep, top_s, -jnp.inf)
        out_s, oi = jax.lax.top_k(sel_s, pn)
        ob = top_b[oi]
        if pn < post_n:       # pad to the declared static output size
            pad = post_n - pn
            ob = jnp.concatenate([ob, jnp.zeros((pad, 4), ob.dtype)])
            out_s = jnp.concatenate([out_s, jnp.full((pad,), -jnp.inf)])
        return ob, out_s

    rois, rscores = jax.vmap(per_image)(scores, deltas, im_info)
    return {"RpnRois": [rois], "RpnRoiProbs": [rscores]}
