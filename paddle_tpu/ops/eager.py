"""Eager op dispatch — the dygraph analog of PreparedOp.

Reference analog: ``paddle/fluid/imperative/prepared_operator.h`` — run a
single op immediately using the same kernel library as the static graph,
with a per-(op, dtype/shape) prepared-kernel cache so repeated dispatches
skip setup. Here that cache is a ``jax.jit`` executable per
(op_type, input signature, attrs, is_test): the first call traces and
compiles, later calls are ONE XLA execution instead of N primitive
dispatches (SURVEY §7 "op-by-op jit cache" mitigation; VERDICT r3 #9).
`call()` executes a registered op impl eagerly on jax.Arrays; the dygraph
Tracer wraps it with vjp-taping for autograd (imperative/tracer.cc:35).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import numpy as np

from ..core.executor import ExecContext


_eager_ctx: Optional[ExecContext] = None
_eager_seed = [0]
_rng_counter = [0]
_jit_cache: Dict = {}

# ops that must NOT run under the jit cache: host-side effects, program
# sub-blocks, or value-dependent python control flow inside the impl
_NO_JIT = frozenset({
    "print", "py_func", "save", "save_combine", "load", "load_combine",
    "while", "cond", "conditional_block", "conditional_block_infer",
    "switch", "recurrent", "static_rnn", "pipeline", "pipeline_hetero",
    "feed", "fetch", "read", "delete_var", "py_reader",
    # output shape depends on input VALUES — unjittable by construction
    "range", "linspace", "where_index", "unique", "unique_with_counts",
})


def _ctx() -> ExecContext:
    global _eager_ctx
    if _eager_ctx is None:
        _eager_ctx = ExecContext(jax.random.PRNGKey(_eager_seed[0]))
    return _eager_ctx


def set_eager_seed(seed: int):
    global _eager_ctx
    _eager_seed[0] = seed
    _rng_counter[0] = 0
    _eager_ctx = ExecContext(jax.random.PRNGKey(seed))


def _attrs_key(attrs: Dict):
    try:
        return tuple(sorted(
            (k, tuple(v) if isinstance(v, (list, tuple)) else v)
            for k, v in attrs.items()
            if isinstance(v, (int, float, bool, str))
            or (isinstance(v, (list, tuple))
                and all(isinstance(x, (int, float, bool, str)) for x in v))))
    except Exception:
        return None


def _prepare(op_type: str, inputs: Dict[str, List],
             attrs: Optional[Dict], is_test: bool,
             seed: Optional[int] = None):
    """Resolve the (fwd_jit, bwd_jit, out_struct) cache entry for this
    dispatch (plus the flat input list), or None when the op/inputs must
    take the direct path. fwd_jit takes (rng_counter, *flat_arrays) and
    returns a flat tuple; bwd_jit takes (rng_counter, cotangents,
    *flat_arrays) and recomputes the forward inside the jit so the
    backward is also ONE cached executable. out_struct fills on the first
    execution. Dropout keys advance through the host-side counter folded
    into the seed INSIDE the jit — no per-call host-side split."""
    import os

    from ..core import registry

    attrs = attrs or {}
    if op_type in _NO_JIT or os.environ.get("PDTPU_EAGER_JIT") == "0":
        return None
    akey = _attrs_key(attrs)
    if akey is None or len(akey) != len(attrs):
        return None  # non-scalar attr (e.g. a sub-block) → direct path
    slots = sorted(inputs)
    flat = []
    sig = []
    for s in slots:
        for v in inputs[s]:
            if not isinstance(v, jax.Array):
                return None  # SelectedRows / host values → direct path
            flat.append(v)
            sig.append((s, v.shape, str(v.dtype)))
    counts = tuple((s, len(inputs[s])) for s in slots)
    seed = _eager_seed[0] if seed is None else seed
    key = (op_type, tuple(sig), akey, bool(is_test), seed)
    entry = _jit_cache.get(key)
    if entry is None:
        opdef = registry.get_op(op_type)
        out_struct: List = []

        def fn(counter, *flat_vals):
            pos = 0
            ins = {}
            for s, c in counts:
                ins[s] = list(flat_vals[pos:pos + c])
                pos += c
            k = jax.random.fold_in(jax.random.PRNGKey(seed), counter)
            ctx = ExecContext(k, is_test=is_test)
            out = opdef.fn(ctx, ins, dict(attrs))
            out_struct.clear()
            out_struct.extend((s, len(out[s])) for s in sorted(out))
            return tuple(v for s, _ in out_struct for v in out[s])

        def bwd(counter, cots, *flat_vals):
            # recompute-forward backward: tracing happens ONCE (jit), so a
            # steady-state grad dispatch is one executable launch — the
            # extra forward FLOPs are cheap next to per-primitive dispatch
            _, vjp = jax.vjp(lambda *f: fn(counter, *f), *flat_vals)
            return vjp(tuple(cots))

        entry = (jax.jit(fn), jax.jit(bwd), out_struct)
        _jit_cache[key] = entry
    fwd_jit, bwd_jit, struct = entry
    return fwd_jit, bwd_jit, struct, flat


def _next_counter() -> np.uint32:
    c = _rng_counter[0]
    _rng_counter[0] += 1
    return np.uint32(c)


def _unflatten(struct, flat_out):
    out = {}
    i = 0
    for s, n in struct:
        out[s] = list(flat_out[i:i + n])
        i += n
    return out


def vjp_call(op_type: str, inputs: Dict[str, List],
             attrs: Optional[Dict], is_test: bool,
             seed: Optional[int] = None,
             counter: Optional[int] = None):
    """Cached-jit dispatch with a vjp, for the dygraph tracer's grad path:
    returns (out {slot: [arrays]}, flat_inputs, vjp_fn over flat inputs),
    or None for the direct path. Forward AND backward are cached jit
    executables (the backward recomputes the forward from the saved
    primal inputs — the flash-attention trade, applied to dispatch cost:
    no per-call tracing survives in steady state)."""
    prep = _prepare(op_type, inputs, attrs, is_test, seed=seed)
    if prep is None:
        return None
    fwd_jit, bwd_jit, struct, flat = prep
    c = _next_counter() if counter is None else np.uint32(counter)
    flat_out = fwd_jit(c, *flat)

    def vjp_fn(cots):
        return bwd_jit(c, tuple(cots), *flat)

    return _unflatten(struct, flat_out), flat, vjp_fn


def call(op_type: str, inputs: Dict[str, List], attrs: Optional[Dict] = None,
         is_test: bool = False) -> Dict[str, List]:
    """Run one op eagerly. inputs: slot -> list of jax arrays. Takes the
    per-op jit cache when the op/inputs allow it, else dispatches the impl
    directly."""
    from ..core import registry

    prep = _prepare(op_type, inputs, attrs, is_test)
    if prep is not None:
        fwd_jit, _, struct, flat = prep
        return _unflatten(struct, fwd_jit(_next_counter(), *flat))

    opdef = registry.get_op(op_type)
    ctx = _ctx()
    old = ctx.is_test
    ctx.is_test = is_test
    try:
        return opdef.fn(ctx, inputs, attrs or {})
    finally:
        ctx.is_test = old
