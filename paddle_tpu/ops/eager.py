"""Eager op dispatch — the dygraph analog of PreparedOp.

Reference analog: ``paddle/fluid/imperative/prepared_operator.h`` — run a
single op immediately using the same kernel library as the static graph.
Here, `call()` executes a registered op impl eagerly on jax.Arrays; the
dygraph Tracer wraps it with vjp-taping for autograd (imperative/tracer.cc:35).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax

from ..core.executor import ExecContext


_eager_ctx: Optional[ExecContext] = None
_eager_seed = [0]


def _ctx() -> ExecContext:
    global _eager_ctx
    if _eager_ctx is None:
        _eager_ctx = ExecContext(jax.random.PRNGKey(_eager_seed[0]))
    return _eager_ctx


def set_eager_seed(seed: int):
    global _eager_ctx
    _eager_seed[0] = seed
    _eager_ctx = ExecContext(jax.random.PRNGKey(seed))


def call(op_type: str, inputs: Dict[str, List], attrs: Optional[Dict] = None,
         is_test: bool = False) -> Dict[str, List]:
    """Run one op eagerly. inputs: slot -> list of jax arrays."""
    from ..core import registry

    opdef = registry.get_op(op_type)
    ctx = _ctx()
    old = ctx.is_test
    ctx.is_test = is_test
    try:
        return opdef.fn(ctx, inputs, attrs or {})
    finally:
        ctx.is_test = old
