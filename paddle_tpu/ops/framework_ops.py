"""Framework/runtime ops round 2 — program-level IO, buffer coalescing,
model averaging, LoD workflow machinery.

References: save_op.cc, load_op.cc, save_combine_op.cc, load_combine_op.cc,
coalesce_tensor_op.cc, average_accumulates_op.cc, sync_batch_norm_op.cu,
lod_rank_table_op.cc, lod_tensor_to_array_op.cc, array_to_lod_tensor_op.cc,
split_lod_tensor_op.cc, merge_lod_tensor_op.cc,
reorder_lod_tensor_by_rank_op.cc, shrink_rnn_memory_op.cc,
rnn_memory_helper_op.cc, controlflow/get_places_op.cc, fake_init_op.cc,
delete_var_op.cc.

LoD redesign note: everywhere the reference threads LoD metadata, this
framework threads a padded tensor + integer ``Length [B]``; the "rank
table" becomes an explicit [B, 2] (index, length) tensor sorted by length,
which keeps every consumer static-shape for XLA.
"""
from __future__ import annotations

import pickle

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import io_callback

from ..core.dtypes import convert_dtype
from ..core.registry import register_op
from .common import one, opt_input


# ---------------------------------------------------------------------------
# program-level IO (save/load as ops, like save_op.cc / load_op.cc — the
# Python io.py wrappers remain the main path; these exist so transpiled
# programs carrying save/load ops execute)
# ---------------------------------------------------------------------------

@register_op("save", differentiable=False)
def _save(ctx, inputs, attrs):
    """save_op.cc: stream one var to `file_path`. Ordered io_callback so
    saves are not reordered/DCE'd by XLA."""
    (x,) = inputs["X"]
    path = attrs["file_path"]

    def do_save(arr):
        import os
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            pickle.dump(np.asarray(arr), f)

    io_callback(do_save, None, x, ordered=True)
    return {}


@register_op("load", differentiable=False)
def _load(ctx, inputs, attrs):
    """load_op.cc: read a var saved by `save`. The read happens at trace
    time (the reference's load also runs once, in the startup program);
    re-tracing re-reads."""
    with open(attrs["file_path"], "rb") as f:
        arr = pickle.load(f)
    return one(jnp.asarray(arr))


@register_op("save_combine", differentiable=False)
def _save_combine(ctx, inputs, attrs):
    """save_combine_op.cc: all input vars into one bundle file."""
    xs = inputs["X"]
    path = attrs["file_path"]

    def do_save(*arrs):
        import os
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            pickle.dump([np.asarray(a) for a in arrs], f)

    io_callback(do_save, None, *xs, ordered=True)
    return {}


@register_op("load_combine", differentiable=False)
def _load_combine(ctx, inputs, attrs):
    with open(attrs["file_path"], "rb") as f:
        arrs = pickle.load(f)
    return {"Out": [jnp.asarray(a) for a in arrs]}


@register_op("delete_var", differentiable=False)
def _delete_var(ctx, inputs, attrs):
    """delete_var_op.cc: explicit free. XLA liveness owns buffers here, so
    this is a structural no-op kept for program parity."""
    return {}


@register_op("fake_init", differentiable=False)
def _fake_init(ctx, inputs, attrs):
    """fake_init_op.cc: declare a var without materializing real contents
    (pserver-side init). Emits zeros of the declared shape."""
    shape = tuple(int(s) for s in attrs["shape"])
    return one(jnp.zeros(shape, convert_dtype(attrs.get("dtype", "float32"))))


@register_op("get_places", differentiable=False)
def _get_places(ctx, inputs, attrs):
    """controlflow/get_places_op.cc: enumerate devices. Returns the device
    ordinals as an int32 vector (places are mesh positions here)."""
    n = int(attrs.get("device_count", 0)) or jax.device_count()
    return one(jnp.arange(n, dtype=jnp.int32))


@register_op("coalesce_tensor", differentiable=False)
def _coalesce_tensor(ctx, inputs, attrs):
    """coalesce_tensor_op.cc: pack vars into one contiguous buffer (fused
    all-reduce / fused optimizer feeding). Returns the flat fused buffer
    plus per-var views reshaped back."""
    xs = inputs["Input"]
    flat = jnp.concatenate([x.reshape(-1) for x in xs])
    if attrs.get("set_constant", False):
        flat = jnp.full_like(flat, attrs.get("constant", 0.0))
    outs, pos = [], 0
    for x in xs:
        n = x.size
        outs.append(flat[pos:pos + n].reshape(x.shape))
        pos += n
    return {"Output": outs, "FusedOutput": [flat]}


@register_op("average_accumulates", differentiable=False,
             grad_fn=None)
def _average_accumulates(ctx, inputs, attrs):
    """average_accumulates_op.cc (ModelAverage support): maintain windowed
    parameter sums. sum_1 accumulates current window, sum_2 previous
    windows, sum_3 scratch; on window overflow sums cascade."""
    (param,) = inputs["param"]
    (sum_1,) = inputs["in_sum_1"]
    (sum_2,) = inputs["in_sum_2"]
    (sum_3,) = inputs["in_sum_3"]
    (num_acc,) = inputs["in_num_accumulates"]
    (old_num,) = inputs["in_old_num_accumulates"]
    (num_upd,) = inputs["in_num_updates"]
    avg_win = float(attrs.get("average_window", 0.0))
    max_avg_win = int(attrs.get("max_average_window", 10000))
    min_avg_win = int(attrs.get("min_average_window", 10000))

    num_upd = num_upd + 1
    num_acc = num_acc + 1
    sum_1 = sum_1 + param
    # reference condition (average_accumulates_op.h): window closes when
    # num_acc >= min_average_window AND
    # num_acc >= min(max_average_window, num_updates * average_window)
    nacc = num_acc.astype(jnp.float32)
    done = (nacc >= float(min_avg_win)) & (
        nacc >= jnp.minimum(float(max_avg_win),
                            avg_win * num_upd.astype(jnp.float32)))
    # reference cascade: sum_3 = sum_1 + sum_2; sum_1 = sum_2 = 0;
    # old_num = num_acc (assigned, not accumulated)
    new_sum_3 = jnp.where(done, sum_1 + sum_2, sum_3)
    new_sum_2 = jnp.where(done, jnp.zeros_like(sum_2), sum_2)
    new_sum_1 = jnp.where(done, jnp.zeros_like(sum_1), sum_1)
    new_old = jnp.where(done, num_acc, old_num)
    new_acc = jnp.where(done, jnp.zeros_like(num_acc), num_acc)
    return {"out_sum_1": [new_sum_1], "out_sum_2": [new_sum_2],
            "out_sum_3": [new_sum_3], "out_num_accumulates": [new_acc],
            "out_old_num_accumulates": [new_old],
            "out_num_updates": [num_upd]}


@register_op("sync_batch_norm", nondiff_inputs=["Mean", "Variance"])
def _sync_batch_norm(ctx, inputs, attrs):
    """sync_batch_norm_op.cu capability: under GSPMD data parallelism the
    plain batch_norm already reduces statistics over the GLOBAL batch (the
    jnp.mean lowers to a cross-replica reduction when the batch axis is
    sharded) — so this is the same lowering, kept as its own type for
    program parity with the sync_batch_norm pass."""
    from .nn_ops import _batch_norm
    return _batch_norm(ctx, inputs, attrs)


# ---------------------------------------------------------------------------
# LoD workflow machinery — padded+Length redesign
# ---------------------------------------------------------------------------

@register_op("lod_rank_table", differentiable=False)
def _lod_rank_table(ctx, inputs, attrs):
    """lod_rank_table_op.cc: (index, length) sorted by length desc — the
    metadata DynamicRNN uses to shrink the batch as sequences end."""
    length = opt_input(inputs, "Length")
    (x,) = inputs["X"]
    b = x.shape[0]
    if length is None:
        length = jnp.full((b,), x.shape[1], jnp.int32)
    order = jnp.argsort(-length, stable=True).astype(jnp.int32)
    return one(jnp.stack([order, length[order].astype(jnp.int32)], axis=1))


@register_op("reorder_lod_tensor_by_rank", nondiff_inputs=["RankTable"])
def _reorder_lod_tensor_by_rank(ctx, inputs, attrs):
    """reorder_lod_tensor_by_rank_op.cc: permute batch rows into rank-table
    order (differentiable gather)."""
    (x,) = inputs["X"]
    (table,) = inputs["RankTable"]
    return one(x[table[:, 0]])


@register_op("lod_tensor_to_array", nondiff_inputs=["RankTable"])
def _lod_tensor_to_array(ctx, inputs, attrs):
    """lod_tensor_to_array_op.cc: batch-major [B, T, ...] → time-major
    [T, B, ...] (each t-slice is one "array element"; padding rows carry
    zeros). The static-shape stand-in for the reference's TensorArray of
    shrinking batches."""
    (x,) = inputs["X"]
    return one(jnp.swapaxes(x, 0, 1))


@register_op("array_to_lod_tensor", nondiff_inputs=["RankTable"])
def _array_to_lod_tensor(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.swapaxes(x, 0, 1))


@register_op("split_lod_tensor", nondiff_inputs=["Mask"])
def _split_lod_tensor(ctx, inputs, attrs):
    """split_lod_tensor_op.cc (IfElse input routing): route rows by boolean
    mask. Padded redesign: both branches keep full batch shape with
    non-member rows zeroed; merge_lod_tensor reassembles exactly."""
    (x,) = inputs["X"]
    (mask,) = inputs["Mask"]
    m = mask.reshape(-1).astype(bool)
    shape = (-1,) + (1,) * (x.ndim - 1)
    mm = m.reshape(shape)
    return {"OutTrue": [jnp.where(mm, x, 0)],
            "OutFalse": [jnp.where(mm, 0, x)]}


@register_op("merge_lod_tensor", nondiff_inputs=["Mask"])
def _merge_lod_tensor(ctx, inputs, attrs):
    (xt,) = inputs["InTrue"]
    (xf,) = inputs["InFalse"]
    (mask,) = inputs["Mask"]
    m = mask.reshape(-1).astype(bool)
    mm = m.reshape((-1,) + (1,) * (xt.ndim - 1))
    return one(jnp.where(mm, xt, xf))


@register_op("shrink_rnn_memory", nondiff_inputs=["RankTable", "I"])
def _shrink_rnn_memory(ctx, inputs, attrs):
    """shrink_rnn_memory_op.cc: at step i, only sequences longer than i stay
    active. X arrives in RANK-TABLE order (the output of
    reorder_lod_tensor_by_rank, as in the reference DynamicRNN program), so
    row r corresponds to table row r and the mask is table[:, 1] > i.
    Padded redesign: zero (freeze) the ended rows instead of shrinking the
    leading dim."""
    (x,) = inputs["X"]
    (table,) = inputs["RankTable"]
    (i,) = inputs["I"]
    step = i.reshape(()).astype(jnp.int32)
    active = (table[:, 1] > step).reshape((-1,) + (1,) * (x.ndim - 1))
    return one(jnp.where(active, x, 0))


@register_op("rnn_memory_helper")
def _rnn_memory_helper(ctx, inputs, attrs):
    """rnn_memory_helper_op.cc: identity bridge for RNN state plumbing (its
    grad op fills zeros for missing cotangents — the vjp tape handles that
    here)."""
    (x,) = inputs["X"]
    return one(x)
