"""Fused ops produced by the ir fuse passes.

Reference analog: ``paddle/fluid/operators/fused/`` (fused_elemwise_activation
_op.cc, fc_op via fc_fuse_pass). On TPU these exist so the *traced graph* has
one op where the pattern had two/three — XLA then fuses the arithmetic into a
single kernel around the MXU gemm; autodiff sees one tape entry.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op
from .common import act_map, bcast_y, one, opt_input

_ACTS = act_map()


@register_op("fused_elemwise_activation")
def _fused_elemwise_activation(ctx, inputs, attrs):
    """act(add(x, y)) in one op (fused_elemwise_activation_op.cc)."""
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    binary, unary = attrs["functor_list"]
    y = bcast_y(x, y, attrs.get("axis", -1))
    binop = {"elementwise_add": jnp.add, "elementwise_mul": jnp.multiply}[binary]
    return one(_ACTS[unary](binop(x, y)))


@register_op("fused_fc")
def _fused_fc(ctx, inputs, attrs):
    """gemm + bias + activation as one MXU-shaped unit (fc_fuse_pass.cc)."""
    (x,) = inputs["Input"]
    (w,) = inputs["W"]
    b = opt_input(inputs, "Bias")
    ncol = attrs.get("in_num_col_dims", 1)
    lead = x.shape[:ncol]
    x2 = x.reshape((int(np.prod(lead)) if lead else 1, -1))
    out = jnp.matmul(x2, w)
    if b is not None:
        out = out + b.reshape((1, -1))
    out = _ACTS[attrs.get("activation_type", "")](out)
    return one(out.reshape(lead + (w.shape[-1],)))


@register_op("fused_conv_bn", nondiff_inputs=["Mean", "Variance"])
def _fused_conv_bn(ctx, inputs, attrs):
    """1×1-conv + batch_norm (+relu, +residual) as one op — the training
    analog of the inference conv_bn_fuse pass, for the resnet bottleneck
    tail. Pallas on TPU (or under FORCE_PALLAS_INTERPRET); otherwise an
    XLA composition with the exact math of the separate conv2d +
    batch_norm("xla1") (+elementwise_add+relu) lowerings, bitwise-equal
    end to end. ``PDTPU_CONV_BN_FUSION=xla`` forces the composition."""
    import os

    from jax import lax

    from .pallas_kernels import fused_bn

    (x,) = inputs["Input"]
    (w,) = inputs["Filter"]
    (scale,) = inputs["Scale"]
    (bias,) = inputs["Bias"]
    (mean,) = inputs["Mean"]
    (var,) = inputs["Variance"]
    residual = opt_input(inputs, "Residual")
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    act = attrs.get("act", "")
    stride = int(attrs.get("stride", 1))
    is_test = attrs.get("is_test", False) or ctx.is_test
    mode = os.environ.get("PDTPU_CONV_BN_FUSION", "pallas")
    if w.dtype != x.dtype:
        # AMP casts the activations at the op boundary but doesn't know
        # this op's Filter slot; round the f32 master weight to the
        # compute dtype here — same rounding the unfused conv2d path gets
        # from its inserted cast op (scale/bias/stats stay f32)
        w = w.astype(x.dtype)

    if is_test:
        y, _, _ = fused_bn.conv_bn_xla(x, w, scale, bias, eps, act, stride,
                                       residual, use_mean=mean, use_var=var)
        return {"Y": [y], "MeanOut": [mean], "VarianceOut": [var],
                "SavedMean": [mean], "SavedVariance": [var]}

    use_pallas = (mode != "xla"
                  and fused_bn.conv_bn_supports(x.shape, w.shape, stride)
                  and (fused_bn._on_tpu() or fused_bn.FORCE_PALLAS_INTERPRET))
    if use_pallas:
        y, bmean, bvar = fused_bn.fused_conv_bn_act(
            x, w, scale, bias, eps, act, stride, residual is not None,
            residual)
    else:
        y, bmean, bvar = fused_bn.conv_bn_xla(x, w, scale, bias, eps, act,
                                              stride, residual)
    mean_out = momentum * mean + (1.0 - momentum) * bmean
    var_out = momentum * var + (1.0 - momentum) * bvar
    return {
        "Y": [y],
        "MeanOut": [lax.stop_gradient(mean_out)],
        "VarianceOut": [lax.stop_gradient(var_out)],
        "SavedMean": [lax.stop_gradient(bmean)],
        "SavedVariance": [lax.stop_gradient(bvar)],
    }


@register_op("flash_attention", nondiff_inputs=["BiasQK"])
def _flash_attention(ctx, inputs, attrs):
    """Memory-efficient fused attention (Pallas on TPU, blockwise JAX
    elsewhere). Replaces the matmul→softmax→dropout→matmul chain; see
    ops/pallas_kernels/flash_attention.py."""
    import importlib
    # the package re-exports the flash_attention *function* under the same
    # name, shadowing the submodule — import the module explicitly
    _fa = importlib.import_module(
        "paddle_tpu.ops.pallas_kernels.flash_attention")

    (q,) = inputs["Q"]
    (k,) = inputs["K"]
    (v,) = inputs["V"]
    bias = opt_input(inputs, "BiasQK")
    rate = attrs.get("dropout_prob", 0.0)
    is_test = attrs.get("is_test", False) or ctx.is_test
    key = None
    if rate > 0.0 and not is_test:
        key = ctx.rng()
    if q.ndim == 3:
        # packed [B, T, H] layout — adapted to the folded kernel layout
        # (see the layout note in pallas_kernels/flash_attention.py)
        if "num_heads" not in attrs:
            raise ValueError(
                "flash_attention: 3D (packed [B,T,H]) q/k/v requires the "
                "num_heads attr — pass num_heads= to layers.flash_attention")
        return one(_fa.flash_attention_packed(
            q, k, v, attrs["num_heads"], bias=bias,
            causal=attrs.get("causal", False),
            dropout_rate=0.0 if is_test else rate, dropout_key=key))
    return one(_fa.flash_attention(
        q, k, v, bias=bias, causal=attrs.get("causal", False),
        dropout_rate=0.0 if is_test else rate, dropout_key=key))


@register_op("flash_attention_sparse", nondiff_inputs=["QSeg", "KSeg"])
def _flash_attention_sparse(ctx, inputs, attrs):
    """Block-sparse packed-segment attention: visibility travels as the
    packed segment-id rows instead of a dense [B, 1, Tq, Tk] additive mask,
    and fully-masked K blocks are skipped in the fwd and bwd kernel grids.
    See the block-sparse section of ops/pallas_kernels/flash_attention.py."""
    import importlib
    _fa = importlib.import_module(
        "paddle_tpu.ops.pallas_kernels.flash_attention")

    (q,) = inputs["Q"]
    (k,) = inputs["K"]
    (v,) = inputs["V"]
    (q_seg,) = inputs["QSeg"]
    (k_seg,) = inputs["KSeg"]
    rate = attrs.get("dropout_prob", 0.0)
    is_test = attrs.get("is_test", False) or ctx.is_test
    key = None
    if rate > 0.0 and not is_test:
        key = ctx.rng()
    return one(_fa.flash_attention_packed_sparse(
        q, k, v, attrs["num_heads"], q_seg, k_seg,
        causal=attrs.get("causal", False),
        dropout_rate=0.0 if is_test else rate, dropout_key=key))
