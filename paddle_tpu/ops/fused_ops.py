"""Fused ops produced by the ir fuse passes.

Reference analog: ``paddle/fluid/operators/fused/`` (fused_elemwise_activation
_op.cc, fc_op via fc_fuse_pass). On TPU these exist so the *traced graph* has
one op where the pattern had two/three — XLA then fuses the arithmetic into a
single kernel around the MXU gemm; autodiff sees one tape entry.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op
from .common import act_map, bcast_y, one, opt_input

_ACTS = act_map()


@register_op("fused_elemwise_activation")
def _fused_elemwise_activation(ctx, inputs, attrs):
    """act(add(x, y)) in one op (fused_elemwise_activation_op.cc)."""
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    binary, unary = attrs["functor_list"]
    y = bcast_y(x, y, attrs.get("axis", -1))
    binop = {"elementwise_add": jnp.add, "elementwise_mul": jnp.multiply}[binary]
    return one(_ACTS[unary](binop(x, y)))


@register_op("fused_fc")
def _fused_fc(ctx, inputs, attrs):
    """gemm + bias + activation as one MXU-shaped unit (fc_fuse_pass.cc)."""
    (x,) = inputs["Input"]
    (w,) = inputs["W"]
    b = opt_input(inputs, "Bias")
    ncol = attrs.get("in_num_col_dims", 1)
    lead = x.shape[:ncol]
    x2 = x.reshape((int(np.prod(lead)) if lead else 1, -1))
    out = jnp.matmul(x2, w)
    if b is not None:
        out = out + b.reshape((1, -1))
    out = _ACTS[attrs.get("activation_type", "")](out)
    return one(out.reshape(lead + (w.shape[-1],)))


@register_op("flash_attention", nondiff_inputs=["BiasQK"])
def _flash_attention(ctx, inputs, attrs):
    """Memory-efficient fused attention (Pallas on TPU, blockwise JAX
    elsewhere). Replaces the matmul→softmax→dropout→matmul chain; see
    ops/pallas_kernels/flash_attention.py."""
    import importlib
    # the package re-exports the flash_attention *function* under the same
    # name, shadowing the submodule — import the module explicitly
    _fa = importlib.import_module(
        "paddle_tpu.ops.pallas_kernels.flash_attention")

    (q,) = inputs["Q"]
    (k,) = inputs["K"]
    (v,) = inputs["V"]
    bias = opt_input(inputs, "BiasQK")
    rate = attrs.get("dropout_prob", 0.0)
    is_test = attrs.get("is_test", False) or ctx.is_test
    key = None
    if rate > 0.0 and not is_test:
        key = ctx.rng()
    if q.ndim == 3:
        # packed [B, T, H] layout — adapted to the folded kernel layout
        # (see the layout note in pallas_kernels/flash_attention.py)
        if "num_heads" not in attrs:
            raise ValueError(
                "flash_attention: 3D (packed [B,T,H]) q/k/v requires the "
                "num_heads attr — pass num_heads= to layers.flash_attention")
        return one(_fa.flash_attention_packed(
            q, k, v, attrs["num_heads"], bias=bias,
            causal=attrs.get("causal", False),
            dropout_rate=0.0 if is_test else rate, dropout_key=key))
    return one(_fa.flash_attention(
        q, k, v, bias=bias, causal=attrs.get("causal", False),
        dropout_rate=0.0 if is_test else rate, dropout_key=key))
