"""Fused / specialized sequence-model ops round 2.

References: lstmp_op.cc, warpctc_op.cc, fused/fusion_lstm_op.cc,
fused/fusion_gru_op.cc, fused/fused_embedding_seq_pool_op.cc,
fused/fusion_seqconv_eltadd_relu_op.cc, fused/fusion_seqpool_concat_op.cc,
fused/fusion_seqpool_cvm_concat_op.cc, fused/fusion_repeated_fc_relu_op.cc,
fused/fusion_squared_mat_sub_op.cc, fused/fusion_transpose_flatten_concat_op.cc,
match_matrix_tensor_op.cc, var_conv_2d_op.cc, filter_by_instag_op.cc,
attention_lstm_op.cc, fc_op.cc.

The reference fuses these by hand (jit/xbyak CPU kernels) because its
executor dispatches op-by-op; on TPU the win is different — one *traced* op
keeps the pattern intact for the autodiff tape and lets XLA emit a single
fused kernel around the MXU gemms. Input projections (x @ Wx) are hoisted
out of the recurrence as one big [B*T, D] x [D, kH] matmul — the
MXU-friendly shape — and only the [H, kH] recurrent matmul rides the scan.

Variable-length sequences are padded [B, T, ...] + integer Length [B]
(masked carries), the framework-wide LoD replacement.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import act_map, length_mask, one, opt_input

_ACTS = act_map()

NEG_INF = -1e30


@register_op("fc")
def _fc(ctx, inputs, attrs):
    """fc_op.cc — same lowering as fused_fc (gemm + bias + act)."""
    from .fused_ops import _fused_fc
    return _fused_fc(ctx, inputs, attrs)


@register_op("lstmp", nondiff_inputs=["Length"])
def _lstmp(ctx, inputs, attrs):
    """lstmp_op.cc: LSTM with recurrent projection (Sak et al.). Input
    [B,T,4H] pre-projected, Weight [P,4H] recurrent (P = proj size),
    ProjWeight [H,P]. The carried state is the projection r, not h.
    Outputs Projection [B,T,P], Cell [B,T,H]."""
    (x,) = inputs["Input"]
    (w,) = inputs["Weight"]
    (w_proj,) = inputs["ProjWeight"]
    bias = opt_input(inputs, "Bias")
    length = opt_input(inputs, "Length")
    h0 = opt_input(inputs, "H0")   # actually r0 [B,P]
    c0 = opt_input(inputs, "C0")

    B, T, four_h = x.shape
    H = four_h // 4
    P = w_proj.shape[1]
    gate_act = _ACTS[attrs.get("gate_activation", "sigmoid")]
    cell_act = _ACTS[attrs.get("cell_activation", "tanh")]
    cand_act = _ACTS[attrs.get("candidate_activation", "tanh")]
    proj_act = _ACTS[attrs.get("proj_activation", "tanh")]
    cell_clip = float(attrs.get("cell_clip", 0.0) or 0.0)
    proj_clip = float(attrs.get("proj_clip", 0.0) or 0.0)
    is_reverse = attrs.get("is_reverse", False)
    use_peepholes = attrs.get("use_peepholes", False) and \
        bias is not None and bias.reshape(-1).shape[0] == 7 * H

    r0 = h0 if h0 is not None else jnp.zeros((B, P), x.dtype)
    c0 = c0 if c0 is not None else jnp.zeros((B, H), x.dtype)
    b = None if bias is None else bias.reshape(-1)[: 4 * H]
    if use_peepholes:
        pk = bias.reshape(-1)
        w_ic, w_fc, w_oc = pk[4 * H:5 * H], pk[5 * H:6 * H], pk[6 * H:7 * H]
    mask = length_mask(length, B, T, x.dtype)

    xs = jnp.swapaxes(x, 0, 1)
    ms = jnp.swapaxes(mask, 0, 1)
    if is_reverse:
        xs, ms = xs[::-1], ms[::-1]

    def step(carry, xm):
        r_prev, c_prev = carry
        xt, mt = xm
        gates = xt + r_prev @ w
        if b is not None:
            gates = gates + b
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        if use_peepholes:
            gi = gi + c_prev * w_ic
            gf = gf + c_prev * w_fc
        c_new = gate_act(gf) * c_prev + gate_act(gi) * cand_act(gc)
        if cell_clip > 0:
            c_new = jnp.clip(c_new, -cell_clip, cell_clip)
        if use_peepholes:
            go = go + c_new * w_oc
        h_new = gate_act(go) * cell_act(c_new)
        r_new = proj_act(h_new @ w_proj)
        if proj_clip > 0:
            r_new = jnp.clip(r_new, -proj_clip, proj_clip)
        m = mt.reshape(-1, 1).astype(x.dtype)
        r_new = r_new * m + r_prev * (1 - m)
        c_new = c_new * m + c_prev * (1 - m)
        return (r_new, c_new), (r_new, c_new)

    (_, _), (rs, cs) = lax.scan(step, (r0, c0), (xs, ms))
    if is_reverse:
        rs, cs = rs[::-1], cs[::-1]
    return {"Projection": [jnp.swapaxes(rs, 0, 1)],
            "Cell": [jnp.swapaxes(cs, 0, 1)],
            "Hidden": [jnp.swapaxes(rs, 0, 1)]}


@register_op("warpctc", nondiff_inputs=["Label", "LogitsLength", "LabelLength"])
def _warpctc(ctx, inputs, attrs):
    """warpctc_op.cc capability, reimplemented as the standard log-space CTC
    forward algorithm under lax.scan (differentiable via the vjp tape — the
    reference carries a separate WarpCTCGrad buffer instead).

    Logits [B, T, C] unnormalized, Label [B, L] int32 (padded arbitrarily
    past LabelLength), LogitsLength [B], LabelLength [B]. blank attr.
    Output Loss [B, 1] = -log p(label | logits).
    """
    (logits,) = inputs["Logits"]
    (label,) = inputs["Label"]
    logits_len = opt_input(inputs, "LogitsLength")
    label_len = opt_input(inputs, "LabelLength")
    blank = int(attrs.get("blank", 0))
    norm_by_times = attrs.get("norm_by_times", False)

    B, T, C = logits.shape
    L = label.shape[1]
    S = 2 * L + 1
    if logits_len is None:
        logits_len = jnp.full((B,), T, jnp.int32)
    if label_len is None:
        label_len = jnp.full((B,), L, jnp.int32)
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)

    def per_sample(lp_b, lab, t_len, l_len):
        # extended label: [blank, l0, blank, l1, ..., blank]
        ext = jnp.full((S,), blank, jnp.int32)
        ext = ext.at[1::2].set(lab)
        s_valid = jnp.arange(S) < (2 * l_len + 1)
        # skip-transition allowed into odd (label) positions whose label
        # differs from the label two back
        prev2 = jnp.concatenate([jnp.full((2,), -1, jnp.int32), ext[:-2]])
        can_skip = (ext != blank) & (ext != prev2)

        alpha0 = jnp.full((S,), NEG_INF)
        alpha0 = alpha0.at[0].set(lp_b[0, blank])
        alpha0 = alpha0.at[1].set(
            jnp.where(l_len > 0, lp_b[0, ext[1]], NEG_INF))

        def step(alpha, t):
            a_prev1 = jnp.concatenate([jnp.array([NEG_INF]), alpha[:-1]])
            a_prev2 = jnp.concatenate([jnp.full((2,), NEG_INF), alpha[:-2]])
            a_prev2 = jnp.where(can_skip, a_prev2, NEG_INF)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a_prev1), a_prev2)
            new = merged + lp_b[t, ext]
            new = jnp.where(s_valid, new, NEG_INF)
            # steps past the sample's logit length carry alpha unchanged
            return jnp.where(t < t_len, new, alpha), None

        alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
        end1 = alpha[2 * l_len]          # final blank
        end2 = jnp.where(l_len > 0, alpha[2 * l_len - 1], NEG_INF)
        ll = jnp.logaddexp(end1, end2)
        loss = -ll
        if norm_by_times:
            loss = loss / jnp.maximum(t_len.astype(jnp.float32), 1.0)
        return loss

    loss = jax.vmap(per_sample)(lp, label, logits_len, label_len)
    return {"Loss": [loss.reshape(B, 1)]}


@register_op("fusion_lstm", nondiff_inputs=["Length"])
def _fusion_lstm(ctx, inputs, attrs):
    """fusion_lstm_op.cc: fc + dynamic_lstm in one op. X [B,T,D],
    WeightX [D,4H], WeightH [H,4H], Bias [4H]. The input projection is one
    [B*T, D] x [D, 4H] gemm (MXU-shaped), only the recurrence scans."""
    from .rnn_ops import _lstm
    (x,) = inputs["X"]
    (wx,) = inputs["WeightX"]
    (wh,) = inputs["WeightH"]
    bias = opt_input(inputs, "Bias")
    projected = jnp.einsum("btd,dh->bth", x, wx)
    sub = {"Input": [projected], "Weight": [wh]}
    if bias is not None:
        sub["Bias"] = [bias]
    if inputs.get("Length"):
        sub["Length"] = inputs["Length"]
    if inputs.get("H0"):
        sub["H0"] = inputs["H0"]
    if inputs.get("C0"):
        sub["C0"] = inputs["C0"]
    return _lstm(ctx, sub, attrs)


@register_op("fusion_gru", nondiff_inputs=["Length"])
def _fusion_gru(ctx, inputs, attrs):
    """fusion_gru_op.cc: fc + dynamic_gru in one op."""
    from .rnn_ops import _gru
    (x,) = inputs["X"]
    (wx,) = inputs["WeightX"]
    (wh,) = inputs["WeightH"]
    projected = jnp.einsum("btd,dh->bth", x, wx)
    sub = {"Input": [projected], "Weight": [wh]}
    for slot in ("Bias", "Length", "H0"):
        if inputs.get(slot):
            sub[slot] = inputs[slot]
    return _gru(ctx, sub, attrs)


@register_op("attention_lstm", nondiff_inputs=["Length"])
def _attention_lstm(ctx, inputs, attrs):
    """attention_lstm_op.cc: per output step, score every timestep of X
    against the previous hidden state through a small fc, softmax over time,
    attend, then one LSTM step on the attended vector.

    X [B,T,D]; AttentionWeight [D+H, 1]; LSTMWeight [D+H, 4H];
    LSTMBias [4H]. Outputs Hidden [B,T,H], Cell [B,T,H]."""
    (x,) = inputs["X"]
    (w_att,) = inputs["AttentionWeight"]
    (w_lstm,) = inputs["LSTMWeight"]
    b_att = opt_input(inputs, "AttentionBias")
    b_lstm = opt_input(inputs, "LSTMBias")
    length = opt_input(inputs, "Length")
    B, T, D = x.shape
    H = w_lstm.shape[1] // 4
    mask = length_mask(length, B, T, x.dtype)          # [B, T]
    h0 = opt_input(inputs, "H0")
    c0 = opt_input(inputs, "C0")
    h0 = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)
    c0 = c0 if c0 is not None else jnp.zeros((B, H), x.dtype)
    cand_act = _ACTS[attrs.get("candidate_activation", "tanh")]
    gate_act = _ACTS[attrs.get("gate_activation", "sigmoid")]
    cell_act = _ACTS[attrs.get("cell_activation", "tanh")]

    # the x-part of the attention score is loop-invariant — one [B,T,D]x[D,1]
    # product hoisted out of the scan; only h_prev @ w_att[D:] rides the loop
    x_score = jnp.einsum("btd,dk->btk", x, w_att[:D])[..., 0]   # [B, T]
    w_att_h = w_att[D:]                                         # [H, 1]

    def step(carry, t):
        h_prev, c_prev = carry
        score = x_score + (h_prev @ w_att_h)                    # [B,T]+[B,1]
        if b_att is not None:
            score = score + b_att.reshape(-1)[0]
        score = jnp.where(mask > 0, score, NEG_INF)
        att = jax.nn.softmax(score, axis=-1)
        ctx_vec = jnp.einsum("bt,btd->bd", att, x)             # [B, D]
        gates = jnp.concatenate([ctx_vec, h_prev], -1) @ w_lstm
        if b_lstm is not None:
            gates = gates + b_lstm.reshape(-1)
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        c_new = gate_act(gf) * c_prev + gate_act(gi) * cand_act(gc)
        h_new = gate_act(go) * cell_act(c_new)
        m = mask[:, t].reshape(-1, 1).astype(x.dtype)
        h_new = h_new * m + h_prev * (1 - m)
        c_new = c_new * m + c_prev * (1 - m)
        return (h_new, c_new), (h_new, c_new)

    (_, _), (hs, cs) = lax.scan(step, (h0, c0), jnp.arange(T))
    return {"Hidden": [jnp.swapaxes(hs, 0, 1)],
            "Cell": [jnp.swapaxes(cs, 0, 1)]}


@register_op("fused_embedding_seq_pool", nondiff_inputs=["Ids", "Length"])
def _fused_embedding_seq_pool(ctx, inputs, attrs):
    """fused_embedding_seq_pool_op.cc: embedding lookup + sum-pool over the
    sequence in one op. Ids [B, T] int, W [V, D], Length [B]."""
    (ids,) = inputs["Ids"]
    (w,) = inputs["W"]
    length = opt_input(inputs, "Length")
    if ids.ndim == 3:   # reference sometimes feeds [B, T, 1]
        ids = ids[..., 0]
    B, T = ids.shape
    emb = w[ids]                                        # [B, T, D]
    mask = length_mask(length, B, T, emb.dtype)
    pooled = jnp.einsum("btd,bt->bd", emb, mask)
    combiner = attrs.get("combiner", "sum")
    if combiner == "mean":
        pooled = pooled / jnp.maximum(mask.sum(-1, keepdims=True), 1.0)
    return one(pooled)


@register_op("fusion_seqconv_eltadd_relu", nondiff_inputs=["Length"])
def _fusion_seqconv_eltadd_relu(ctx, inputs, attrs):
    """fusion_seqconv_eltadd_relu_op.cc: sequence_conv + bias + relu."""
    from .sequence_ops import _sequence_conv
    sub = {"X": inputs["X"], "Filter": inputs["Filter"]}
    if inputs.get("Length"):
        sub["Length"] = inputs["Length"]
    out = _sequence_conv(ctx, sub, {
        "contextLength": attrs.get("contextLength", 3),
        "contextStart": attrs.get("contextStart", 0)})["Out"][0]
    (b,) = inputs["Bias"]
    return one(jax.nn.relu(out + b.reshape(1, 1, -1)))


@register_op("fusion_seqpool_concat", nondiff_inputs=["Length"])
def _fusion_seqpool_concat(ctx, inputs, attrs):
    """fusion_seqpool_concat_op.cc: seq-pool each input (delegating to the
    sequence_pool lowering — SUM/AVERAGE/SQRT/MAX/LAST/FIRST all supported),
    concat features. Empty sequences emit pad 0.0 under MAX."""
    from .sequence_ops import _sequence_pool
    xs = inputs["X"]
    lengths = inputs.get("Length") or [None] * len(xs)
    pooltype = attrs.get("pooltype", "SUM").upper()
    outs = []
    for x, ln in zip(xs, lengths):
        if ln is None:
            ln = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
        pooled = _sequence_pool(ctx, {"X": [x], "Length": [ln]},
                                {"pooltype": pooltype})["Out"][0]
        if pooltype == "MAX":
            empty = (ln == 0).reshape((-1,) + (1,) * (pooled.ndim - 1))
            pooled = jnp.where(empty, 0.0, pooled)
        outs.append(pooled)
    return one(jnp.concatenate(outs, axis=-1))


@register_op("fusion_seqpool_cvm_concat", nondiff_inputs=["CVM", "Length"])
def _fusion_seqpool_cvm_concat(ctx, inputs, attrs):
    """fusion_seqpool_cvm_concat_op.cc: seqpool + cvm (show/click feature
    normalization, cvm_op.cc) + concat."""
    pooled = _fusion_seqpool_concat(
        ctx, {"X": inputs["X"], "Length": inputs.get("Length")},
        {"pooltype": attrs.get("pooltype", "SUM")})["Out"][0]
    use_cvm = attrs.get("use_cvm", True)
    if not use_cvm:
        # drop the leading 2 cvm slots of each concatenated block, using
        # each input's own feature width (widths may differ)
        parts, pos = [], 0
        for x in inputs["X"]:
            d = x.shape[-1]
            parts.append(pooled[:, pos + 2:pos + d])
            pos += d
        return one(jnp.concatenate(parts, axis=-1))
    return one(pooled)


@register_op("fusion_repeated_fc_relu")
def _fusion_repeated_fc_relu(ctx, inputs, attrs):
    """fusion_repeated_fc_relu_op.cc: a chain of fc+relu layers in one op
    (final fc has no relu, matching the reference)."""
    (x,) = inputs["X"]
    ws = inputs["W"]
    bs = inputs["Bias"]
    out = x.reshape(x.shape[0], -1)
    for i, (w, b) in enumerate(zip(ws, bs)):
        out = out @ w + b.reshape(1, -1)
        if i < len(ws) - 1:
            out = jax.nn.relu(out)
    return one(out)


@register_op("fusion_squared_mat_sub")
def _fusion_squared_mat_sub(ctx, inputs, attrs):
    """fusion_squared_mat_sub_op.cc: scalar * ((X@Y)^2 - (X^2)@(Y^2)) —
    the pairwise-interaction term of factorization machines."""
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    scalar = attrs.get("scalar", 1.0)
    xy = x @ y
    return one(scalar * (xy * xy - (x * x) @ (y * y)))


@register_op("fusion_transpose_flatten_concat")
def _fusion_transpose_flatten_concat(ctx, inputs, attrs):
    """fusion_transpose_flatten_concat_op.cc: per input transpose →
    flatten(axis) → concat along the concat axis."""
    xs = inputs["X"]
    trans = [int(a) for a in attrs["trans_axis"]]
    flat_axis = int(attrs.get("flatten_axis", 1))
    concat_axis = int(attrs.get("concat_axis", 1))
    outs = []
    for x in xs:
        t = jnp.transpose(x, trans)
        lead = 1
        for s in t.shape[:flat_axis]:
            lead *= s
        outs.append(t.reshape(lead, -1))
    return one(jnp.concatenate(outs, axis=concat_axis))


@register_op("match_matrix_tensor", nondiff_inputs=["LengthX", "LengthY"])
def _match_matrix_tensor(ctx, inputs, attrs):
    """match_matrix_tensor_op.cc: bilinear match of two sequence batches —
    Out[b, t] = X[b] @ W[:, t, :] @ Y[b]^T for each of dim_t channels.
    X [B,Tx,D], Y [B,Ty,D], W [D, dim_t, D] → Out [B, dim_t, Tx, Ty]."""
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    (w,) = inputs["W"]
    lx = opt_input(inputs, "LengthX")
    ly = opt_input(inputs, "LengthY")
    out = jnp.einsum("bxd,dte,bye->btxy", x, w, y)
    B, Tx, Ty = x.shape[0], x.shape[1], y.shape[1]
    mx = length_mask(lx, B, Tx, out.dtype)
    my = length_mask(ly, B, Ty, out.dtype)
    out = out * mx[:, None, :, None] * my[:, None, None, :]
    return {"Out": [out], "Tmp": [jnp.einsum("bxd,dte->bxte", x, w)]}


@register_op("var_conv_2d", nondiff_inputs=["LengthX", "LengthY"])
def _var_conv_2d(ctx, inputs, attrs):
    """var_conv_2d_op.cc: conv over per-sample variable-size 2-D maps (the
    match-matrix output). Padded redesign: X [B, C_in, H, W] with validity
    from LengthX/LengthY masks; W [C_out, C_in*kh*kw]."""
    (x,) = inputs["X"]
    (w,) = inputs["W"]
    lx = opt_input(inputs, "LengthX")
    ly = opt_input(inputs, "LengthY")
    kh = int(attrs.get("kernel_h", 3))
    kw = int(attrs.get("kernel_w", 3))
    sh = int(attrs.get("stride_h", 1))
    sw = int(attrs.get("stride_w", 1))
    B, cin, H, W = x.shape
    mh = length_mask(lx, B, H, x.dtype)
    mw = length_mask(ly, B, W, x.dtype)
    x = x * mh[:, None, :, None] * mw[:, None, None, :]
    cout = w.shape[0]
    wk = w.reshape(cout, cin, kh, kw)
    out = lax.conv_general_dilated(
        x, wk, window_strides=(sh, sw),
        padding=[(kh // 2, kh // 2), (kw // 2, kw // 2)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return one(out)


@register_op("filter_by_instag", differentiable=False)
def _filter_by_instag(ctx, inputs, attrs):
    """filter_by_instag_op.cc: keep rows whose tag set intersects the
    filter tags. Padded redesign: non-matching rows are zeroed in place
    (the reference compacts rows — dynamic shape), LossWeight marks keeps."""
    (ins,) = inputs["Ins"]          # [B, D]
    (ins_tag,) = inputs["Ins_tag"]  # [B, T] int, -1 padded
    (filter_tag,) = inputs["Filter_tag"]   # [K] int
    match = (ins_tag[:, :, None] == filter_tag[None, None, :]).any((1, 2))
    out = jnp.where(match[:, None], ins, 0.0)
    lw = match.astype(ins.dtype).reshape(-1, 1)
    idx = jnp.where(match, jnp.arange(ins.shape[0]), -1).astype(jnp.int32)
    return {"Out": [out], "LossWeight": [lw], "IndexMap": [idx]}
