"""Elementwise + linear-algebra ops.

Reference analog: ``paddle/fluid/operators/elementwise/`` (broadcast + grad),
``matmul_op.cc``, ``mul_op.cc``, ``scale_op.cc``, ``sum_op.cc``,
``clip_op.cc``, ``operators/math/blas.h`` (gemm → MXU via XLA dot_general).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import bcast_y, one


def _elementwise(name, fn):
    @register_op(name)
    def _impl(ctx, inputs, attrs, _fn=fn):
        (x,) = inputs["X"]
        (y,) = inputs["Y"]
        return one(_fn(x, bcast_y(x, y, attrs.get("axis", -1))))
    return _impl


_elementwise("elementwise_add", jnp.add)
_elementwise("elementwise_sub", jnp.subtract)
_elementwise("elementwise_mul", jnp.multiply)
_elementwise("elementwise_div", jnp.divide)
_elementwise("elementwise_min", jnp.minimum)
_elementwise("elementwise_max", jnp.maximum)
_elementwise("elementwise_pow", jnp.power)
_elementwise("elementwise_mod", jnp.mod)
_elementwise("elementwise_floordiv", jnp.floor_divide)


@register_op("scale")
def _scale(ctx, inputs, attrs):
    (x,) = inputs["X"]
    scale = attrs.get("scale", 1.0)
    bias = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        return one(x * scale + bias)
    return one((x + bias) * scale)


@register_op("matmul")
def _matmul(ctx, inputs, attrs):
    """matmul_op.cc semantics: optional transpose flags + alpha, batched via
    leading dims. Lowered to dot_general → MXU."""
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    tx, ty = attrs.get("transpose_X", False), attrs.get("transpose_Y", False)
    alpha = attrs.get("alpha", 1.0)
    if x.ndim == 1:
        x = x[None, :]
    if y.ndim == 1:
        y = y[:, None]
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * alpha
    return one(out)


@register_op("mul")
def _mul(ctx, inputs, attrs):
    """mul_op.cc: flatten X to 2-D at x_num_col_dims, Y at y_num_col_dims,
    then gemm; output keeps X's leading dims."""
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    xnc = attrs.get("x_num_col_dims", 1)
    ync = attrs.get("y_num_col_dims", 1)
    xlead = x.shape[:xnc]
    x2 = x.reshape((-1, np_prod(x.shape[xnc:])))
    y2 = y.reshape((np_prod(y.shape[:ync]), -1))
    out = jnp.matmul(x2, y2)
    return one(out.reshape(xlead + y.shape[ync:]))


def np_prod(t):
    r = 1
    for v in t:
        r *= int(v)
    return r


@register_op("sum")
def _sum(ctx, inputs, attrs):
    xs = inputs["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return one(out)


@register_op("clip")
def _clip(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.clip(x, attrs.get("min"), attrs.get("max")))


@register_op("clip_by_norm")
def _clip_by_norm(ctx, inputs, attrs):
    (x,) = inputs["X"]
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(x * x))
    return one(jnp.where(norm > max_norm, x * (max_norm / norm), x))


def _unary(name, fn, differentiable=True):
    @register_op(name, differentiable=differentiable)
    def _impl(ctx, inputs, attrs, _fn=fn):
        (x,) = inputs["X"]
        return one(_fn(x))
    return _impl


_unary("abs", jnp.abs)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", lambda x: jax.lax.rsqrt(x))
_unary("square", jnp.square)
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("log1p", jnp.log1p)
_unary("ceil", jnp.ceil)
_unary("floor", jnp.floor)
_unary("round", jnp.round)
_unary("reciprocal", lambda x: 1.0 / x)
_unary("sign", jnp.sign)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("asin", jnp.arcsin)
_unary("acos", jnp.arccos)
_unary("atan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("erf", jax.scipy.special.erf)


@register_op("pow")
def _pow(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.power(x, attrs.get("factor", 1.0)))


@register_op("p_norm")
def _p_norm(ctx, inputs, attrs):
    (x,) = inputs["X"]
    p = attrs.get("porder", 2.0)
    axis = attrs.get("axis")
    keepdim = attrs.get("keepdim", False)
    out = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)
    return one(out)


@register_op("squared_l2_norm")
def _squared_l2_norm(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.sum(x * x).reshape((1,)))


@register_op("dot")
def _dot(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    return one(jnp.sum(x * y, axis=-1, keepdims=x.ndim > 1))


@register_op("cumsum")
def _cumsum(ctx, inputs, attrs):
    (x,) = inputs["X"]
    axis = attrs.get("axis", -1)
    if attrs.get("flatten", False):
        x = x.reshape(-1)
        axis = 0
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("reverse", False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
    if attrs.get("exclusive", False):
        out = out - x
    return one(out)
