"""Metric ops (reference operators/metrics/: accuracy_op.cc, auc_op.cc,
precision_recall_op.cc)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op


@register_op("accuracy", differentiable=False)
def _accuracy(ctx, inputs, attrs):
    """accuracy_op.cc: fraction of samples whose top-k indices contain label."""
    (indices,) = inputs["Indices"]
    (label,) = inputs["Label"]
    lab = label[..., 0] if label.ndim == 2 and label.shape[-1] == 1 else label
    correct = jnp.any(indices == lab[:, None], axis=1)
    total = jnp.array(indices.shape[0], dtype=jnp.int32)
    num_correct = jnp.sum(correct.astype(jnp.int32))
    acc = num_correct.astype(jnp.float32) / total.astype(jnp.float32)
    return {"Accuracy": [acc], "Correct": [num_correct], "Total": [total]}


@register_op("auc", differentiable=False)
def _auc(ctx, inputs, attrs):
    """auc_op.cc: streaming AUC via threshold-bucketed confusion counters.
    StatPos/StatNeg are persistable accumulator vars updated each step."""
    (predict,) = inputs["Predict"]
    (label,) = inputs["Label"]
    (stat_pos,) = inputs["StatPos"]
    (stat_neg,) = inputs["StatNeg"]
    num_thresholds = attrs.get("num_thresholds", 4095)
    pos_prob = predict[:, 1] if predict.ndim == 2 and predict.shape[1] == 2 else predict.reshape(-1)
    lab = label.reshape(-1).astype(jnp.float32)
    bucket = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32), 0, num_thresholds)
    pos_hist = jnp.zeros(num_thresholds + 1).at[bucket].add(lab)
    neg_hist = jnp.zeros(num_thresholds + 1).at[bucket].add(1.0 - lab)
    new_pos = stat_pos + pos_hist
    new_neg = stat_neg + neg_hist
    # integrate: walking thresholds high→low accumulates TP/FP
    tp = jnp.cumsum(new_pos[::-1])[::-1]
    fp = jnp.cumsum(new_neg[::-1])[::-1]
    tot_pos = tp[0]
    tot_neg = fp[0]
    # trapezoid over unique thresholds
    tp_prev = jnp.concatenate([tp[1:], jnp.zeros(1)])
    fp_prev = jnp.concatenate([fp[1:], jnp.zeros(1)])
    area = jnp.sum((fp - fp_prev) * (tp + tp_prev) / 2.0)
    auc = jnp.where((tot_pos > 0) & (tot_neg > 0), area / (tot_pos * tot_neg + 1e-12), 0.0)
    return {"AUC": [auc], "StatPosOut": [new_pos], "StatNegOut": [new_neg]}
