"""Misc op families: ranking/margin losses, normalization, image/layout
reshuffles, interpolation, indexed pooling, batch-size-like random fills,
and v2 (XShape-carrying) aliases.

Reference analogs (paddle/fluid/operators/): hinge_loss_op.h, rank_loss_op.h,
modified_huber_loss_op.h, bpr_loss_op.h, teacher_student_sigmoid_loss_op.cc,
center_loss_op.h, squared_l2_distance_op.h, label_smooth_op.h, selu_op.h,
l1_norm_op.h, norm_op.h, minus_op.cc, multiplex_op.cc, reverse_op.cc,
crop_op.h, pad_constant_like_op.h, space_to_depth_op.cc, pixel_shuffle_op.h,
shuffle_channel_op.h, temporal_shift_op.h, unfold_op.h, affine_channel_op.cc,
lrn_op.h, row_conv_op.cc, conv_shift_op.cc, add_position_encoding_op.h,
bilinear_tensor_product_op.h, interpolate_op.h (nearest/bilinear/trilinear),
pool_with_index_op.h, unpool_op.h, spp_op.h, mean_iou_op.h,
grid_sampler_op.h, affine_grid_op.h, spectral_norm_op.h, sampling_id_op.h,
*_batch_size_like ops, reshape_op.cc (reshape2/transpose2/squeeze2/
unsqueeze2 v2 forms with XShape), cross_entropy2 (cross_entropy_op2.h),
get_tensor_from_selected_rows_op.cc, merge_selected_rows_op.cc.

All static-shape, jnp/XLA-native; v2 ops emit the XShape shadow output the
reference uses for in-place reshape grad (here just metadata parity).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.registry import register_op
from .common import one


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

@register_op("hinge_loss", nondiff_inputs=["Labels"])
def _hinge_loss(ctx, inputs, attrs):
    (x,) = inputs["Logits"]
    (y,) = inputs["Labels"]
    return {"Loss": [jnp.maximum(1.0 - x * (2.0 * y - 1.0), 0.0)]}


@register_op("rank_loss", nondiff_inputs=["Label"])
def _rank_loss(ctx, inputs, attrs):
    (label,) = inputs["Label"]
    (left,) = inputs["Left"]
    (right,) = inputs["Right"]
    d = left - right
    return one(jax.nn.softplus(d) - label * d)


@register_op("modified_huber_loss", nondiff_inputs=["Y"])
def _modified_huber_loss(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    v = x * (2.0 * y - 1.0)
    loss = jnp.where(v < -1.0, -4.0 * v,
                     jnp.where(v < 1.0, jnp.square(1.0 - v), 0.0))
    return {"IntermediateVal": [v], "Out": [loss]}


@register_op("bpr_loss", nondiff_inputs=["Label"])
def _bpr_loss(ctx, inputs, attrs):
    """Bayesian personalized ranking: mean over negatives of
    softplus(x_neg − x_pos)."""
    (x,) = inputs["X"]
    (label,) = inputs["Label"]
    n, c = x.shape[0], x.shape[-1]
    idx = label.reshape(n).astype(jnp.int32)
    pos = jnp.take_along_axis(x.reshape(n, c), idx[:, None], axis=1)
    sp = jax.nn.softplus(x.reshape(n, c) - pos)
    mask = jax.nn.one_hot(idx, c, dtype=x.dtype)
    loss = jnp.sum(sp * (1.0 - mask), axis=1, keepdims=True) / (c - 1)
    return {"Y": [loss]}


@register_op("teacher_student_sigmoid_loss", nondiff_inputs=["Label"])
def _ts_sigmoid_loss(ctx, inputs, attrs):
    """teacher_student_sigmoid_loss_op.cc: CTR distillation loss —
    label < -1 → teacher-only, two-part piecewise otherwise."""
    (x,) = inputs["X"]
    (label,) = inputs["Label"]
    soft_max_up = attrs.get("soft_max_up_bound", 15.0)
    soft_max_lo = attrs.get("soft_max_lower_bound", -15.0)
    z = jnp.clip(x, soft_max_lo, soft_max_up)
    sce = jnp.maximum(z, 0.0) - z * jnp.where(label > -1.0, label, 0.0) \
        + jnp.log1p(jnp.exp(-jnp.abs(z)))
    return {"Y": [sce]}


@register_op("squared_l2_distance", nondiff_inputs=[])
def _squared_l2_distance(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    sub = x - jnp.broadcast_to(y, x.shape)
    out = jnp.sum(jnp.square(sub).reshape(x.shape[0], -1), axis=1,
                  keepdims=True)
    return {"sub_result": [sub], "Out": [out]}


@register_op("center_loss", nondiff_inputs=["Label", "Centers",
                                            "CenterUpdateRate"])
def _center_loss(ctx, inputs, attrs):
    """center_loss_op.h: ||x − center_label||²/2 + running center update."""
    (x,) = inputs["X"]
    (label,) = inputs["Label"]
    (centers,) = inputs["Centers"]
    (alpha,) = inputs["CenterUpdateRate"]
    idx = label.reshape(-1).astype(jnp.int32)
    c = centers[idx]
    diff = x - c
    loss = 0.5 * jnp.sum(jnp.square(diff), axis=1, keepdims=True)
    if attrs.get("need_update", True) and not ctx.is_test:
        counts = jnp.zeros(centers.shape[0], x.dtype).at[idx].add(1.0)
        delta = jnp.zeros_like(centers).at[idx].add(diff)
        upd = centers + alpha.reshape(()) * delta / (counts[:, None] + 1.0)
        new_centers = lax.stop_gradient(upd)
    else:
        new_centers = centers
    return {"Loss": [loss], "SampleCenterDiff": [lax.stop_gradient(diff)],
            "CentersOut": [new_centers]}


@register_op("label_smooth", nondiff_inputs=["PriorDist"])
def _label_smooth(ctx, inputs, attrs):
    (x,) = inputs["X"]
    eps = attrs.get("epsilon", 0.0)
    prior = inputs.get("PriorDist")
    if prior:
        return one((1.0 - eps) * x + eps * prior[0])
    return one((1.0 - eps) * x + eps / x.shape[-1])


@register_op("mean_iou", differentiable=False)
def _mean_iou(ctx, inputs, attrs):
    (pred,) = inputs["Predictions"]
    (label,) = inputs["Labels"]
    n = attrs["num_classes"]
    p = pred.reshape(-1).astype(jnp.int32)
    t = label.reshape(-1).astype(jnp.int32)
    inter = jnp.zeros(n, jnp.float32).at[jnp.where(p == t, p, n - 1)].add(
        jnp.where(p == t, 1.0, 0.0))
    area_p = jnp.zeros(n, jnp.float32).at[p].add(1.0)
    area_t = jnp.zeros(n, jnp.float32).at[t].add(1.0)
    union = area_p + area_t - inter
    iou = jnp.where(union > 0, inter / jnp.maximum(union, 1e-9), 0.0)
    valid = (union > 0).sum()
    mean = jnp.sum(iou) / jnp.maximum(valid.astype(jnp.float32), 1.0)
    return {"OutMeanIou": [mean.reshape(1)], "OutWrong": [(area_p - inter)],
            "OutCorrect": [inter]}


# ---------------------------------------------------------------------------
# normalization / elementwise
# ---------------------------------------------------------------------------

@register_op("selu")
def _selu(ctx, inputs, attrs):
    (x,) = inputs["X"]
    scale = attrs.get("scale", 1.0507009873554805)
    alpha = attrs.get("alpha", 1.6732632423543772)
    return one(scale * jnp.where(x > 0, x, alpha * (jnp.exp(x) - 1.0)))


@register_op("l1_norm")
def _l1_norm(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.sum(jnp.abs(x)).reshape(1))


@register_op("norm")
def _norm(ctx, inputs, attrs):
    """norm_op.h: l2-normalize along `axis`; Norm output saves the norms."""
    (x,) = inputs["X"]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-10)
    nrm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": [x / nrm], "Norm": [nrm]}


@register_op("minus")
def _minus(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    return one(x - y)


@register_op("multiplex", nondiff_inputs=["Ids"])
def _multiplex(ctx, inputs, attrs):
    (ids,) = inputs["Ids"]
    xs = inputs["X"]
    stacked = jnp.stack(xs)                        # [k, B, ...]
    sel = ids.reshape(-1).astype(jnp.int32)        # [B]
    return one(stacked[sel, jnp.arange(stacked.shape[1])])


@register_op("reverse")
def _reverse(ctx, inputs, attrs):
    (x,) = inputs["X"]
    axes = attrs.get("axis", [0])
    axes = axes if isinstance(axes, (list, tuple)) else [axes]
    return one(jnp.flip(x, axis=tuple(int(a) for a in axes)))


@register_op("crop")
def _crop(ctx, inputs, attrs):
    (x,) = inputs["X"]
    offsets = attrs.get("offsets")
    shape = attrs.get("shape")
    return one(lax.slice(x, [int(o) for o in offsets],
                         [int(o) + int(s) for o, s in zip(offsets, shape)]))


@register_op("pad_constant_like", nondiff_inputs=["X"])
def _pad_constant_like(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    val = attrs.get("pad_value", 0.0)
    pads = [(0, xd - yd, 0) for xd, yd in zip(x.shape, y.shape)]
    return one(lax.pad(y, jnp.asarray(val, y.dtype), pads))


@register_op("size", differentiable=False)
def _size(ctx, inputs, attrs):
    (x,) = inputs["Input"]
    return one(jnp.asarray(int(np.prod(x.shape) if x.ndim else 1),
                           jnp.int64).reshape(()))


@register_op("is_empty", differentiable=False)
def _is_empty(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.asarray(int(np.prod(x.shape)) == 0).reshape(1))


@register_op("fill", differentiable=False)
def _fill(ctx, inputs, attrs):
    from ..core.dtypes import convert_dtype
    value = np.asarray(attrs["value"], convert_dtype(attrs.get("dtype", "float32")))
    return one(jnp.asarray(value).reshape(attrs["shape"]))


@register_op("fill_any_like", differentiable=False)
def _fill_any_like(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.full_like(x, attrs.get("value", 0.0)))


@register_op("fill_zeros_like2", differentiable=False)
def _fill_zeros_like2(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.zeros_like(x))


@register_op("get_tensor_from_selected_rows", differentiable=False)
def _get_tensor_from_selected_rows(ctx, inputs, attrs):
    (x,) = inputs["X"]
    from ..core.selected_rows import SelectedRows
    return one(x.to_dense() if isinstance(x, SelectedRows) else x)


@register_op("merge_selected_rows", differentiable=False)
def _merge_selected_rows(ctx, inputs, attrs):
    (x,) = inputs["X"]
    from ..core.selected_rows import SelectedRows
    if isinstance(x, SelectedRows):
        ids, rows = x.merged()
        return one(SelectedRows(ids, rows, x.height))
    return one(x)


# ---------------------------------------------------------------------------
# image / layout
# ---------------------------------------------------------------------------

@register_op("space_to_depth")
def _space_to_depth(ctx, inputs, attrs):
    (x,) = inputs["X"]
    bs = int(attrs["blocksize"])
    n, c, h, w = x.shape
    out = x.reshape(n, c, h // bs, bs, w // bs, bs)
    out = out.transpose(0, 3, 5, 1, 2, 4)
    return one(out.reshape(n, c * bs * bs, h // bs, w // bs))


@register_op("pixel_shuffle")
def _pixel_shuffle(ctx, inputs, attrs):
    (x,) = inputs["X"]
    r = int(attrs.get("upscale_factor", 1))
    n, c, h, w = x.shape
    out = x.reshape(n, c // (r * r), r, r, h, w)
    out = out.transpose(0, 1, 4, 2, 5, 3)
    return one(out.reshape(n, c // (r * r), h * r, w * r))


@register_op("shuffle_channel")
def _shuffle_channel(ctx, inputs, attrs):
    (x,) = inputs["X"]
    g = int(attrs.get("group", 1))
    n, c, h, w = x.shape
    return one(x.reshape(n, g, c // g, h, w).transpose(0, 2, 1, 3, 4)
               .reshape(n, c, h, w))


@register_op("temporal_shift")
def _temporal_shift(ctx, inputs, attrs):
    """temporal_shift_op.h: shift 1/shift_ratio of channels ±1 along T."""
    (x,) = inputs["X"]
    t = int(attrs["seg_num"])
    ratio = attrs.get("shift_ratio", 0.25)
    nt, c, h, w = x.shape
    n = nt // t
    v = x.reshape(n, t, c, h, w)
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    pad = jnp.zeros_like(v[:, :1])
    fwd = jnp.concatenate([v[:, 1:, :c1], pad[:, :, :c1]], axis=1)
    bwd = jnp.concatenate([pad[:, :, c1:c2], v[:, :-1, c1:c2]], axis=1)
    keep = v[:, :, c2:]
    return one(jnp.concatenate([fwd, bwd, keep], axis=2).reshape(nt, c, h, w))


@register_op("affine_channel")
def _affine_channel(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (scale,) = inputs["Scale"]
    (bias,) = inputs["Bias"]
    layout = attrs.get("data_layout", "NCHW")
    shape = ([1, -1] + [1] * (x.ndim - 2)) if layout == "NCHW" else None
    if shape is not None:
        return one(x * scale.reshape(shape) + bias.reshape(shape))
    return one(x * scale + bias)


@register_op("lrn")
def _lrn(ctx, inputs, attrs):
    """lrn_op.h local response normalization over channels (NCHW)."""
    (x,) = inputs["X"]
    n = int(attrs.get("n", 5))
    k = attrs.get("k", 2.0)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    return {"Out": [x / jnp.power(mid, beta)], "MidOut": [mid]}


@register_op("add_position_encoding")
def _add_position_encoding(ctx, inputs, attrs):
    """add_position_encoding_op.h: x*alpha + beta*sinusoid(pos)."""
    (x,) = inputs["X"]
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    b, t, d = x.shape
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    i = jnp.arange(d // 2, dtype=jnp.float32)[None, :]
    angle = pos / jnp.power(10000.0, 2.0 * i / d)
    enc = jnp.concatenate([jnp.sin(angle), jnp.cos(angle)], axis=1)
    return one(alpha * x + beta * enc[None].astype(x.dtype))


@register_op("bilinear_tensor_product")
def _bilinear_tensor_product(ctx, inputs, attrs):
    """out[b,k] = x[b]·W_k·y[b] (+ bias)."""
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    (w,) = inputs["Weight"]
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    bias = inputs.get("Bias")
    if bias:
        out = out + bias[0]
    return one(out)


@register_op("conv_shift")
def _conv_shift(ctx, inputs, attrs):
    """conv_shift_op.cc: circular correlation, y length odd ≤ x length."""
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    b, m = x.shape
    n = y.shape[1]
    half = n // 2
    idx = (jnp.arange(m)[:, None] + jnp.arange(n)[None, :] - half) % m
    return one(jnp.einsum("bmn,bn->bm", x[:, idx.reshape(-1)].reshape(b, m, n), y))


@register_op("row_conv")
def _row_conv(ctx, inputs, attrs):
    """row_conv_op.cc (lookahead conv, batch-major [B, T, D] redesign of the
    LoD form): out[t] = Σ_{i<future_len} x[t+i]·w[i]."""
    (x,) = inputs["X"]
    (w,) = inputs["Filter"]          # [future_len, D]
    fl = w.shape[0]
    b, t, d = x.shape
    pad = jnp.concatenate([x, jnp.zeros((b, fl - 1, d), x.dtype)], axis=1)
    out = sum(pad[:, i:i + t] * w[i][None, None, :] for i in range(fl))
    return one(out)


@register_op("grid_sampler")
def _grid_sampler(ctx, inputs, attrs):
    """grid_sampler_op.h: bilinear sampling of x [N,C,H,W] at grid [N,H,W,2]
    (normalized [-1,1] coords, zero padding)."""
    (x,) = inputs["X"]
    (grid,) = inputs["Grid"]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)

    def gather(yi, xi):
        yi_c = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xi_c = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        v = x[jnp.arange(n)[:, None, None], :, yi_c, xi_c]    # [N,Ho,Wo,C]
        ok = ((yi >= 0) & (yi <= h - 1) & (xi >= 0) & (xi <= w - 1))
        return v * ok[..., None].astype(x.dtype)

    wx = gx - x0
    wy = gy - y0
    out = (gather(y0, x0) * ((1 - wx) * (1 - wy))[..., None]
           + gather(y0, x0 + 1) * (wx * (1 - wy))[..., None]
           + gather(y0 + 1, x0) * ((1 - wx) * wy)[..., None]
           + gather(y0 + 1, x0 + 1) * (wx * wy)[..., None])
    return {"Output": [jnp.moveaxis(out, -1, 1)]}


@register_op("affine_grid")
def _affine_grid(ctx, inputs, attrs):
    """affine_grid_op.h: theta [N,2,3] → sampling grid [N,H,W,2]."""
    (theta,) = inputs["Theta"]
    shape = inputs.get("OutputShape")
    if shape:
        hw = np.asarray(shape[0]).reshape(-1)
        h, w = int(hw[-2]), int(hw[-1])
    else:
        os_ = attrs["output_shape"]
        h, w = int(os_[-2]), int(os_[-1])
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gx, gy = jnp.meshgrid(xs, ys)
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)   # [H,W,3]
    out = jnp.einsum("hwk,njk->nhwj", base, theta)
    return {"Output": [out]}


@register_op("spectral_norm")
def _spectral_norm(ctx, inputs, attrs):
    """spectral_norm_op.h: weight / sigma_max via power iteration."""
    (w,) = inputs["Weight"]
    (u,) = inputs["U"]
    (v,) = inputs["V"]
    dim = attrs.get("dim", 0)
    iters = attrs.get("power_iters", 1)
    eps = attrs.get("eps", 1e-12)
    wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    uu, vv = u.reshape(-1), v.reshape(-1)
    for _ in range(max(iters, 0)):
        vv = wm.T @ uu
        vv = vv / (jnp.linalg.norm(vv) + eps)
        uu = wm @ vv
        uu = uu / (jnp.linalg.norm(uu) + eps)
    uu, vv = lax.stop_gradient(uu), lax.stop_gradient(vv)
    sigma = uu @ wm @ vv
    return one(w / sigma)


# ---------------------------------------------------------------------------
# interpolation (interpolate_op.h family)
# ---------------------------------------------------------------------------

def _interp(x, attrs, method):
    out_h = attrs.get("out_h", -1)
    out_w = attrs.get("out_w", -1)
    scale = attrs.get("scale", 0.0)
    n, c = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    if scale and scale > 0:
        tgt = tuple(int(s * scale) for s in spatial)
    elif len(spatial) == 3:
        tgt = (int(attrs.get("out_d", -1)), int(out_h), int(out_w))
    else:
        tgt = (int(out_h), int(out_w))
    align = attrs.get("align_corners", True)
    if method == "nearest":
        # index-map resize (matches the reference's floor rule)
        idxs = []
        for s, t in zip(spatial, tgt):
            ratio = (s - 1) / (t - 1) if (align and t > 1) else s / t
            ix = (jnp.arange(t) * ratio)
            idxs.append((ix + (0.5 if align else 0.0)).astype(jnp.int32).clip(0, s - 1))
        out = x
        for d, ix in enumerate(idxs):
            out = jnp.take(out, ix, axis=2 + d)
        return out
    mth = {"bilinear": "linear", "trilinear": "linear"}[method]
    return jax.image.resize(x, (n, c) + tgt, method=mth)


@register_op("nearest_interp")
def _nearest_interp(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(_interp(x, attrs, "nearest"))


@register_op("bilinear_interp")
def _bilinear_interp(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(_interp(x, attrs, "bilinear"))


@register_op("trilinear_interp")
def _trilinear_interp(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(_interp(x, attrs, "trilinear"))


# ---------------------------------------------------------------------------
# pooling with indices / unpool / spp / pool3d
# ---------------------------------------------------------------------------

def _pool_patches(x, ksize, strides, paddings):
    """[N,C,Ho,Wo,kh*kw] patches (−inf padded) + flat-index helper."""
    n, c, h, w = x.shape
    kh, kw = ksize
    sh, sw = strides
    ph, pw = paddings
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                 constant_values=-jnp.inf)
    ho = (h + 2 * ph - kh) // sh + 1
    wo = (w + 2 * pw - kw) // sw + 1
    patches = []
    flat_idx = []
    for i in range(kh):
        for j in range(kw):
            sub = lax.slice(xp, (0, 0, i, j),
                            (n, c, i + (ho - 1) * sh + 1, j + (wo - 1) * sw + 1),
                            (1, 1, sh, sw))
            patches.append(sub)
            rows = (jnp.arange(ho) * sh + i - ph)[:, None]
            cols = (jnp.arange(wo) * sw + j - pw)[None, :]
            flat_idx.append(jnp.broadcast_to(rows * w + cols, (ho, wo)))
    return jnp.stack(patches, -1), jnp.stack(flat_idx, -1), ho, wo


@register_op("max_pool2d_with_index")
def _max_pool2d_with_index(ctx, inputs, attrs):
    (x,) = inputs["X"]
    ks = [int(k) for k in attrs["ksize"]]
    st = [int(s) for s in attrs.get("strides", ks)]
    pd = [int(p) for p in attrs.get("paddings", [0, 0])]
    if attrs.get("global_pooling", False):
        ks = list(x.shape[2:])
        pd = [0, 0]
    patches, fidx, ho, wo = _pool_patches(x, ks, st, pd)
    arg = jnp.argmax(patches, axis=-1)
    out = jnp.take_along_axis(patches, arg[..., None], axis=-1)[..., 0]
    mask = jnp.take_along_axis(fidx[None, None], arg[..., None], axis=-1)[..., 0]
    return {"Out": [out], "Mask": [lax.stop_gradient(mask.astype(jnp.int32))]}


@register_op("unpool", nondiff_inputs=["Indices"])
def _unpool(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (indices,) = inputs["Indices"]
    oh, ow = [int(v) for v in attrs["unpooled_size"]] \
        if "unpooled_size" in attrs else (None, None)
    n, c, h, w = x.shape
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    idx = indices.reshape(n, c, -1).astype(jnp.int32)
    flat = flat.at[jnp.arange(n)[:, None, None],
                   jnp.arange(c)[None, :, None], idx].add(
        x.reshape(n, c, -1))
    return one(flat.reshape(n, c, oh, ow))


@register_op("spp")
def _spp(ctx, inputs, attrs):
    """spp_op.h spatial pyramid pooling: levels 0..L-1 of (2^l)² bins."""
    (x,) = inputs["X"]
    levels = int(attrs.get("pyramid_height", 1))
    ptype = attrs.get("pooling_type", "max")
    n, c, h, w = x.shape
    outs = []
    for l in range(levels):
        bins = 2 ** l
        ks = (int(np.ceil(h / bins)), int(np.ceil(w / bins)))
        st = ks
        ph = (ks[0] * bins - h + 1) // 2
        pw = (ks[1] * bins - w + 1) // 2
        patches, _, ho, wo = _pool_patches(x, ks, st, (ph, pw))
        if ptype == "max":
            o = jnp.max(patches, axis=-1)
        else:
            cnt = jnp.sum(jnp.isfinite(patches), axis=-1)
            o = jnp.sum(jnp.where(jnp.isfinite(patches), patches, 0.0), -1) \
                / jnp.maximum(cnt, 1)
        outs.append(o.reshape(n, c, -1))
    return one(jnp.concatenate(outs, axis=-1).reshape(n, -1))


@register_op("pool3d")
def _pool3d(ctx, inputs, attrs):
    (x,) = inputs["X"]
    ks = [int(k) for k in attrs["ksize"]]
    st = [int(s) for s in attrs.get("strides", ks)]
    pd = [int(p) for p in attrs.get("paddings", [0, 0, 0])]
    if attrs.get("global_pooling", False):
        ks, pd = list(x.shape[2:]), [0, 0, 0]
    ptype = attrs.get("pooling_type", "max")
    dims = (1, 1) + tuple(ks)
    strides = (1, 1) + tuple(st)
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pd)
    if ptype == "max":
        return one(lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pads))
    s = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
    if attrs.get("exclusive", True):
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
        return one(s / cnt)
    return one(s / float(np.prod(ks)))


# ---------------------------------------------------------------------------
# batch-size-like randoms + sampling
# ---------------------------------------------------------------------------

def _batch_size_like_shape(attrs, ref):
    shape = [int(s) for s in attrs["shape"]]
    in_idx = int(attrs.get("input_dim_idx", 0))
    out_idx = int(attrs.get("output_dim_idx", 0))
    shape[out_idx] = ref.shape[in_idx]
    return shape


@register_op("uniform_random_batch_size_like", differentiable=False)
def _uniform_random_bsl(ctx, inputs, attrs):
    (ref,) = inputs["Input"]
    shape = _batch_size_like_shape(attrs, ref)
    lo, hi = attrs.get("min", -1.0), attrs.get("max", 1.0)
    return one(jax.random.uniform(ctx.rng(), shape, jnp.float32, lo, hi))


@register_op("gaussian_random_batch_size_like", differentiable=False)
def _gaussian_random_bsl(ctx, inputs, attrs):
    (ref,) = inputs["Input"]
    shape = _batch_size_like_shape(attrs, ref)
    mean, std = attrs.get("mean", 0.0), attrs.get("std", 1.0)
    return one(mean + std * jax.random.normal(ctx.rng(), shape, jnp.float32))


@register_op("sampling_id", differentiable=False)
def _sampling_id(ctx, inputs, attrs):
    """sampling_id_op.h: one categorical draw per row of a prob matrix."""
    (x,) = inputs["X"]
    ids = jax.random.categorical(ctx.rng(), jnp.log(jnp.maximum(x, 1e-30)),
                                 axis=-1)
    return one(ids.astype(jnp.int64))


# ---------------------------------------------------------------------------
# v2 aliases (XShape shadow for in-place grad machinery — metadata parity)
# ---------------------------------------------------------------------------

def _with_xshape(out, x):
    return {"Out": [out],
            "XShape": [lax.stop_gradient(jnp.zeros((0,) + x.shape, x.dtype))]}


@register_op("reshape2")
def _reshape2(ctx, inputs, attrs):
    (x,) = inputs["X"]
    shape = list(attrs["shape"])
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return _with_xshape(x.reshape(shape), x)


@register_op("transpose2")
def _transpose2(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return _with_xshape(jnp.transpose(x, attrs["axis"]), x)


@register_op("squeeze2")
def _squeeze2(ctx, inputs, attrs):
    (x,) = inputs["X"]
    axes = attrs.get("axes", [])
    if axes:
        out = x
        for a in sorted((a % x.ndim for a in axes), reverse=True):
            if out.shape[a] == 1:
                out = jnp.squeeze(out, a)
    else:
        out = jnp.squeeze(x)
    return _with_xshape(out, x)


@register_op("unsqueeze2")
def _unsqueeze2(ctx, inputs, attrs):
    (x,) = inputs["X"]
    out = x
    for a in sorted(attrs.get("axes", [])):
        out = jnp.expand_dims(out, a)
    return _with_xshape(out, x)


@register_op("cross_entropy2", nondiff_inputs=["Label"])
def _cross_entropy2(ctx, inputs, attrs):
    """cross_entropy2 (hard label over probs, saves MatchX for grad)."""
    (x,) = inputs["X"]
    (label,) = inputs["Label"]
    idx = label
    if idx.ndim == x.ndim and idx.shape[-1] == 1:
        idx = idx[..., 0]
    match = jnp.take_along_axis(x, idx[..., None].astype(jnp.int32),
                                axis=-1)
    loss = -jnp.log(jnp.maximum(match, 1e-30))
    return {"Y": [loss], "MatchX": [lax.stop_gradient(match)],
            "XShape": [lax.stop_gradient(jnp.zeros((0,) + x.shape, x.dtype))]}
