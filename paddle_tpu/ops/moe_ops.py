"""Mixture-of-Experts framework op.

No reference analog (barrierye/Paddle predates MoE) — this exposes the
expert-parallel machinery of parallel/moe.py to static-graph programs as a
single `moe_ffn` op, the same way the reference exposes composite blocks as
fused ops (e.g. fused_embedding_seq_pool_op.cc). Under a compiled mesh with
an `ep` axis the op dispatches tokens via all-to-all expert parallelism;
otherwise it computes the identical dense path. Fully differentiable via
the executor's vjp tape.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from ..parallel import moe as _moe


@register_op("moe_ffn")
def _moe_ffn(ctx, inputs, attrs):
    (x,) = inputs["X"]                 # [B, T, D] or [N, D]
    (gate_w,) = inputs["GateW"]        # [D, E]
    (w1,) = inputs["W1"]               # [E, D, H]
    (b1,) = inputs["B1"]               # [E, H]
    (w2,) = inputs["W2"]               # [E, H, D]
    (b2,) = inputs["B2"]               # [E, D]
    k = int(attrs.get("k", 2))
    cf = float(attrs.get("capacity_factor", 1.25))
    axis = attrs.get("ep_axis", "ep")
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
           "silu": jax.nn.silu}[attrs.get("act", "gelu")]

    shape = x.shape
    flat = x.reshape(-1, shape[-1])

    mesh = ctx.mesh
    if mesh is not None and axis in mesh.axis_names \
            and gate_w.shape[1] % mesh.shape[axis] == 0 \
            and flat.shape[0] % mesh.shape[axis] == 0:
        y, aux = _moe.moe_ffn_expert_parallel(
            flat, gate_w, w1, b1, w2, b2, mesh, axis=axis, k=k,
            capacity_factor=cf, act=act)
    else:
        y, aux = _moe.moe_ffn(flat, gate_w, w1, b1, w2, b2, k=k,
                              capacity_factor=cf, act=act)
    return {"Out": [y.reshape(shape)], "AuxLoss": [aux]}
