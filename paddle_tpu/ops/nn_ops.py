"""Neural-net ops: conv, pool, norms, dropout, embedding, losses.

Reference analog: ``paddle/fluid/operators/`` conv_op.cc (+conv_cudnn_op.cu),
pool_op.cc, batch_norm_op.cc, layer_norm_op.cc, group_norm_op.cc,
dropout_op.cc, lookup_table_op.cc, cross_entropy_op.cc,
softmax_with_cross_entropy_op.cc, sigmoid_cross_entropy_with_logits_op.cc.

TPU notes: convs lower to lax.conv_general_dilated → MXU; data layout is kept
NCHW at the API (Paddle convention) and XLA's layout assignment picks the
physical HBM layout. Embedding grads become XLA scatter-adds (dense), the
TPU-native replacement for SelectedRows sparse rows (selected_rows.h).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import one


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


# ---------------------------------------------------------------------------
# convolution
# ---------------------------------------------------------------------------

@register_op("conv2d", nondiff_inputs=[])
def _conv2d(ctx, inputs, attrs):
    (x,) = inputs["Input"]
    (w,) = inputs["Filter"]
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dilations = _pair(attrs.get("dilations", [1, 1]))
    groups = int(attrs.get("groups", 1))
    pad_alg = attrs.get("padding_algorithm", "EXPLICIT")
    if pad_alg == "SAME":
        padding = "SAME"
    elif pad_alg == "VALID":
        padding = "VALID"
    else:
        padding = [(pads[0], pads[0]), (pads[1], pads[1])] if len(pads) == 2 else \
            [(pads[0], pads[1]), (pads[2], pads[3])]
    # no preferred_element_type=f32: the MXU accumulates bf16 convs in f32
    # regardless and only rounds the output, while jax 0.9's conv transpose
    # rule mishandles mixed (bf16, f32) operands it would create
    out = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        rhs_dilation=dilations, feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return one(out)


@register_op("depthwise_conv2d")
def _depthwise_conv2d(ctx, inputs, attrs):
    attrs = dict(attrs)
    (x,) = inputs["Input"]
    attrs["groups"] = x.shape[1]
    return _conv2d(ctx, inputs, attrs)


def conv_transpose_nd(x, w, strides, pads, dils, groups, out_pads=None):
    """Shared N-d transposed-conv core (conv_transpose_op.cc semantics:
    out = (i-1)*s - 2p + d*(k-1) + 1, plus per-dim output_padding on the
    trailing edge when `out_pads` is given — the output_size resolver).
    Expressed as a fractionally-strided conv (lhs_dilation) with the kernel
    spatially flipped — the gradient-of-conv formulation XLA lowers well.
    `w` is paddle layout [C_in, C_out/groups, *k]."""
    nd = len(strides)
    ks = w.shape[2:]
    wt = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
    if groups > 1:
        cin, cog = w.shape[0], w.shape[1]
        wt = wt.reshape(groups, cin // groups, cog, *ks)
        wt = jnp.swapaxes(wt, 1, 2).reshape(groups * cog, cin // groups, *ks)
    else:
        wt = jnp.swapaxes(wt, 0, 1)
    out_pads = out_pads or [0] * nd
    pad = [(d * (k - 1) - p, d * (k - 1) - p + op)
           for k, p, d, op in zip(ks, pads, dils, out_pads)]
    dn = {1: ("NCH", "OIH", "NCH"), 2: ("NCHW", "OIHW", "NCHW"),
          3: ("NCDHW", "OIDHW", "NCDHW")}[nd]
    return lax.conv_general_dilated(
        x, wt, window_strides=(1,) * nd, padding=pad,
        lhs_dilation=tuple(strides), rhs_dilation=tuple(dils),
        feature_group_count=groups, dimension_numbers=dn)


def _out_pads_from_output_size(x, w, attrs, nd):
    """Resolve the reference's output_size attr into trailing output
    padding: output_size must lie in [default, default + stride)."""
    output_size = attrs.get("output_size")
    if not output_size:
        return None
    strides = _pair(attrs.get("strides", [1] * nd), nd)
    pads = _pair(attrs.get("paddings", [0] * nd), nd)
    dils = _pair(attrs.get("dilations", [1] * nd), nd)
    ks = w.shape[2:]
    out_pads = []
    for i, want in enumerate(_pair(output_size, nd)):
        default = ((x.shape[2 + i] - 1) * strides[i] - 2 * pads[i]
                   + dils[i] * (ks[i] - 1) + 1)
        extra = int(want) - default
        if not 0 <= extra < strides[i]:
            raise ValueError(
                f"conv_transpose output_size[{i}]={want} must be in "
                f"[{default}, {default + strides[i] - 1}]")
        out_pads.append(extra)
    return out_pads


@register_op("conv2d_transpose")
def _conv2d_transpose(ctx, inputs, attrs):
    (x,) = inputs["Input"]
    (w,) = inputs["Filter"]
    return one(conv_transpose_nd(
        x, w, _pair(attrs.get("strides", [1, 1])),
        _pair(attrs.get("paddings", [0, 0])),
        _pair(attrs.get("dilations", [1, 1])),
        int(attrs.get("groups", 1)),
        out_pads=_out_pads_from_output_size(x, w, attrs, 2)))


@register_op("conv3d")
def _conv3d(ctx, inputs, attrs):
    (x,) = inputs["Input"]
    (w,) = inputs["Filter"]
    strides = _pair(attrs.get("strides", [1, 1, 1]), 3)
    pads = _pair(attrs.get("paddings", [0, 0, 0]), 3)
    dilations = _pair(attrs.get("dilations", [1, 1, 1]), 3)
    groups = int(attrs.get("groups", 1))
    padding = [(p, p) for p in pads]
    out = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        rhs_dilation=dilations, feature_group_count=groups,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    return one(out)


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------

@register_op("pool2d")
def _pool2d(ctx, inputs, attrs):
    (x,) = inputs["X"]
    ptype = attrs.get("pooling_type", "max")
    ksize = _pair(attrs.get("ksize", [2, 2]))
    strides = _pair(attrs.get("strides", ksize))
    pads = _pair(attrs.get("paddings", [0, 0]))
    if attrs.get("global_pooling", False) or attrs.get("adaptive", False) and _pair(attrs.get("ksize")) == (1, 1):
        axis = (2, 3)
        out = jnp.max(x, axis=axis, keepdims=True) if ptype == "max" else jnp.mean(x, axis=axis, keepdims=True)
        return one(out)
    window = (1, 1) + ksize
    strides_full = (1, 1) + strides
    padding = ((0, 0), (0, 0), (pads[0], pads[0]), (pads[1], pads[1]))
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = lax.reduce_window(x, init, lax.max, window, strides_full, padding)
    else:
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides_full, padding)
        if attrs.get("exclusive", True) and (pads[0] or pads[1]):
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(ones, 0.0, lax.add, window, strides_full, padding)
            out = summed / counts
        else:
            out = summed / (ksize[0] * ksize[1])
    return one(out)


def _adaptive_bins(size, out):
    """(start, end) per output bin — torch/paddle adaptive pooling rule."""
    return [(i * size // out, -(-((i + 1) * size) // out))
            for i in range(out)]


@register_op("adaptive_pool2d")
def _adaptive_pool2d(ctx, inputs, attrs):
    (x,) = inputs["X"]
    oh, ow = _pair(attrs["pooling_size"] if "pooling_size" in attrs else attrs["ksize"])
    ptype = attrs.get("pooling_type", "avg")
    n, c, h, w = x.shape
    if h % oh == 0 and w % ow == 0:  # fast path: one reshape-reduce
        x5 = x.reshape(n, c, oh, h // oh, ow, w // ow)
        return one(jnp.mean(x5, axis=(3, 5)) if ptype == "avg"
                   else jnp.max(x5, axis=(3, 5)))
    red = jnp.mean if ptype == "avg" else jnp.max
    rows = []
    for hs, he in _adaptive_bins(h, oh):
        cols = [red(x[:, :, hs:he, ws:we], axis=(2, 3))
                for ws, we in _adaptive_bins(w, ow)]
        rows.append(jnp.stack(cols, axis=-1))
    return one(jnp.stack(rows, axis=-2))


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

@register_op("batch_norm", nondiff_inputs=["Mean", "Variance"])
def _batch_norm(ctx, inputs, attrs):
    """batch_norm_op.cc parity: running-stat update in train, frozen in test.
    When a mesh data axis is active (sync_batch_norm / sync_batch_norm_pass
    analog), XLA computes the batch stats over the *global* batch because the
    reduction is over the sharded batch dim — sync-BN falls out for free."""
    import os
    (x,) = inputs["X"]
    (scale,) = inputs["Scale"]
    (bias,) = inputs["Bias"]
    (mean,) = inputs["Mean"]
    (var,) = inputs["Variance"]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False) or ctx.is_test
    layout = attrs.get("data_layout", "NCHW")
    act = attrs.get("act", "")  # folded by layers.batch_norm (fused-BN path)
    bn_mode = os.environ.get("PDTPU_BN_MODE", "xla1")
    axes = tuple(i for i in range(x.ndim) if i != (1 if layout == "NCHW" else x.ndim - 1))
    ch_axis = 1 if layout == "NCHW" else x.ndim - 1
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]

    if is_test:
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
        saved_mean = mean
        saved_var = var
    else:
        from .pallas_kernels import fused_bn
        # Default lowering is the one-pass XLA stats below; the Pallas fused
        # kernel stays available for experimentation (PDTPU_BN_MODE=pallas)
        # but measured SLOWER end-to-end on v5e (116 ms vs 54 ms ResNet-50
        # step) — XLA's fused sibling-reduction read beats a hand-rolled
        # kernel that fights the conv layouts; see fused_bn.py.
        if (bn_mode.startswith("pallas") and layout == "NCHW"
                and act in ("", "relu")
                and fused_bn.supports(x.shape, x.dtype)
                and (fused_bn._on_tpu() or fused_bn.FORCE_PALLAS_INTERPRET)):
            if bn_mode == "pallas_stats":
                # perf probe only: frozen-stats gradient (no d/dx through
                # the batch statistics)
                bmean, bvar = fused_bn.bn_stats(
                    lax.stop_gradient(x),
                    interpret=fused_bn.FORCE_PALLAS_INTERPRET)
                inv = lax.rsqrt(bvar.reshape(shape) + eps)
                y = ((x.astype(jnp.float32) - bmean.reshape(shape)) * inv
                     * scale.reshape(shape) + bias.reshape(shape))
                if act == "relu":
                    y = jnp.maximum(y, 0.0)
                y = y.astype(x.dtype)
                mean_out = momentum * mean + (1.0 - momentum) * bmean
                var_out = momentum * var + (1.0 - momentum) * bvar
                return {
                    "Y": [y],
                    "MeanOut": [lax.stop_gradient(mean_out)],
                    "VarianceOut": [lax.stop_gradient(var_out)],
                    "SavedMean": [bmean],
                    "SavedVariance": [bvar],
                }
            # One-streaming-pass statistics + fused apply(+relu) Pallas kernel
            # (see fused_bn.py header for the roofline); XLA's lowering reads
            # the activation three times per training BN.
            y, bmean, bvar = fused_bn.fused_bn_act(x, scale, bias, eps, act,
                                                   False)
            mean_out = momentum * mean + (1.0 - momentum) * bmean
            var_out = momentum * var + (1.0 - momentum) * bvar
            return {
                "Y": [y],
                "MeanOut": [lax.stop_gradient(mean_out)],
                "VarianceOut": [lax.stop_gradient(var_out)],
                "SavedMean": [lax.stop_gradient(bmean)],
                "SavedVariance": [lax.stop_gradient(bvar)],
            }
    if not is_test:
        # statistics always in f32 (bf16 accumulation over N·H·W terms would
        # lose digits); x itself stays in its native dtype — the op is
        # AMP-"gray" so a bf16 conv trunk never round-trips through f32 HBM
        if bn_mode == "xla2":
            use_mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
            # two-pass variance (E[(x−μ)²]): exact but costs a second read
            use_var = jnp.var(x.astype(jnp.float32), axis=axes)
        else:
            # one-pass stats: mean and E[x²] are sibling reductions XLA
            # fuses into a single read of x (9% faster ResNet-50 step,
            # measured). f32 accumulation + clamp guards the E[x²]−E[x]²
            # cancellation (cuDNN's training path makes the same trade —
            # batch_norm_op.cu:35).
            xf = x.astype(jnp.float32)
            use_mean = jnp.mean(xf, axis=axes)
            use_var = jnp.maximum(
                jnp.mean(xf * xf, axis=axes) - use_mean * use_mean, 0.0)
        mean_out = momentum * mean + (1.0 - momentum) * use_mean
        var_out = momentum * var + (1.0 - momentum) * use_var
        saved_mean = use_mean
        saved_var = use_var
    inv = lax.rsqrt(use_var.astype(jnp.float32).reshape(shape) + eps)
    y = ((x.astype(jnp.float32) - use_mean.astype(jnp.float32).reshape(shape))
         * inv * scale.reshape(shape) + bias.reshape(shape)).astype(x.dtype)
    if act:
        from .common import act_map
        y = act_map()[act](y)
    return {
        "Y": [y],
        "MeanOut": [lax.stop_gradient(mean_out)],
        "VarianceOut": [lax.stop_gradient(var_out)],
        "SavedMean": [lax.stop_gradient(saved_mean)],
        "SavedVariance": [lax.stop_gradient(saved_var)],
    }


@register_op("layer_norm")
def _layer_norm(ctx, inputs, attrs):
    """Gray-listed under AMP (like batch_norm): accepts bf16 activations and
    computes the statistics/normalization in f32 internally, returning the
    input dtype — black-listing it would bounce every residual-stream
    activation through f32 HBM twice per layer."""
    (x,) = inputs["X"]
    scale = inputs.get("Scale", [None])[0]
    bias = inputs.get("Bias", [None])[0]
    eps = attrs.get("epsilon", 1e-5)
    bna = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(bna, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    norm_shape = (1,) * bna + x.shape[bna:]
    if scale is not None:
        y = y * scale.astype(jnp.float32).reshape(norm_shape)
    if bias is not None:
        y = y + bias.astype(jnp.float32).reshape(norm_shape)
    return {"Y": [y.astype(x.dtype)], "Mean": [mean.squeeze(axes)],
            "Variance": [var.squeeze(axes)]}


@register_op("group_norm")
def _group_norm(ctx, inputs, attrs):
    (x,) = inputs["X"]
    scale = inputs.get("Scale", [None])[0]
    bias = inputs.get("Bias", [None])[0]
    eps = attrs.get("epsilon", 1e-5)
    groups = attrs["groups"]
    nhwc = attrs.get("data_layout", "NCHW") == "NHWC"
    if nhwc:  # normalize in channels-first, restore on the way out
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[0], x.shape[1]
    rest = x.shape[2:]
    xg = x.reshape((n, groups, c // groups) + rest)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
    cshape = (1, c) + (1,) * len(rest)
    if scale is not None:
        y = y * scale.reshape(cshape)
    if bias is not None:
        y = y + bias.reshape(cshape)
    if nhwc:
        y = jnp.moveaxis(y, 1, -1)
    return {"Y": [y], "Mean": [mean.reshape(n, groups)], "Variance": [var.reshape(n, groups)]}


@register_op("instance_norm")
def _instance_norm(ctx, inputs, attrs):
    (x,) = inputs["X"]
    scale = inputs.get("Scale", [None])[0]
    bias = inputs.get("Bias", [None])[0]
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    cshape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(cshape)
    if bias is not None:
        y = y + bias.reshape(cshape)
    return {"Y": [y]}


@register_op("l2_normalize")
def _l2_normalize(ctx, inputs, attrs):
    (x,) = inputs["X"]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-12)
    norm = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True))
    return one(x / jnp.maximum(norm, eps))


# ---------------------------------------------------------------------------
# dropout / embedding
# ---------------------------------------------------------------------------

@register_op("dropout")
def _dropout(ctx, inputs, attrs):
    (x,) = inputs["X"]
    p = attrs.get("dropout_prob", 0.5)
    is_test = attrs.get("is_test", False) or ctx.is_test
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if is_test or p == 0.0:
        # reference dropout_op.cc: at inference, downgrade_in_infer scales by
        # (1-p); upscale_in_train is identity (scaling happened in training).
        y = x * (1.0 - p) if (impl == "downgrade_in_infer" and is_test and p > 0.0) else x
        return {"Out": [y], "Mask": [jnp.ones_like(x)]}
    keep = jax.random.bernoulli(ctx.rng(), 1.0 - p, x.shape)
    mask = keep.astype(x.dtype)
    if impl == "upscale_in_train":
        y = x * mask / (1.0 - p)
    else:
        y = x * mask
    return {"Out": [y], "Mask": [lax.stop_gradient(mask)]}


def _lookup_sparse_grad(attrs):
    """lookup_table_op.cc is_sparse=True GradOpMaker analog: the table's
    cotangent is SelectedRows (ids, dOut rows) — a [vocab, dim] dense
    gradient is never materialized (SURVEY §7 DeepFM-scale hard part)."""
    if not attrs.get("is_sparse"):
        return None  # dense path: generic jax.vjp scatter-add

    def grad(ctx, inputs, attrs2, outputs, out_cots):
        from ..core.selected_rows import SelectedRows

        (w,) = inputs["W"]
        (ids,) = inputs["Ids"]
        (g,) = out_cots["Out"]
        squeeze_last = ids.ndim >= 2 and ids.shape[-1] == 1
        idx = ids[..., 0] if squeeze_last else ids
        flat_ids = idx.reshape(-1).astype(jnp.int32)
        rows = g.reshape(-1, g.shape[-1])
        if not attrs2.get("row_pack_dt"):  # packed tables keep f32 grads
            rows = rows.astype(w.dtype)
        padding_idx = attrs2.get("padding_idx", -1)
        if padding_idx is not None and padding_idx >= 0:
            rows = jnp.where((flat_ids == padding_idx)[:, None], 0.0, rows)
        out = {"W": [SelectedRows(flat_ids, rows, w.shape[0])],
               "Ids": [None]}
        # pending deferred-update state is opt state, not a diff input
        for slot in ("PendingPos", "PendingCum"):
            if slot in inputs:
                out[slot] = [None]
        return out

    return grad


@register_op("lookup_table", nondiff_inputs=["Ids", "PendingPos", "PendingCum"],
             grad_fn=_lookup_sparse_grad)
def _lookup_table(ctx, inputs, attrs):
    """lookup_table_op.cc: W[ids]; padding_idx rows produce zeros. Grad is an
    XLA scatter-add (dense) by default; with is_sparse=True the grad is a
    SelectedRows rows bundle consumed row-wise by sgd/adam/adagrad.

    With PendingPos/PendingCum inputs (wired by a deferred-row optimizer,
    ops/deferred_rows.py), the read adds the postab-indexed pending
    cumulative delta to the base gather, so lookups always see the exact
    serial-update value regardless of fold cadence — the TPU-native analog
    of the reference's distributed_lookup_table prefetch rewrite
    (parameter_prefetch.cc). The extra CumOut output feeds the deferred
    optimizer op, which reuses these gathers instead of issuing its own."""
    (w,) = inputs["W"]
    (ids,) = inputs["Ids"]
    squeeze_last = ids.ndim >= 2 and ids.shape[-1] == 1
    idx = ids[..., 0] if squeeze_last else ids
    rp_dt = attrs.get("row_pack_dt")
    if rp_dt:
        # packed row-major table (ops/deferred_rows.py): [V, 128] uint16
        # holding dt bit-split f32 values per row — full-row gather, then
        # bit-exact unpack
        from .deferred_rows import unpack_rows
        q = idx.reshape(-1).astype(jnp.int32)
        out = unpack_rows(jnp.take(w, q, axis=0), int(rp_dt))
        out = out.reshape(idx.shape + (int(rp_dt),))
    else:
        out = jnp.take(w, idx, axis=0)
    padding_idx = attrs.get("padding_idx", -1)
    if "PendingPos" in inputs:
        from .deferred_rows import lookup_join
        (postab,) = inputs["PendingPos"]
        (log_cum,) = inputs["PendingCum"]
        q = idx.reshape(-1).astype(jnp.int32)
        cur, cum = lookup_join(postab, log_cum, out.reshape(q.shape[0], -1), q)
        shp = idx.shape + (w.shape[-1],)
        out = lax.stop_gradient(cur.reshape(shp) - out) + out
        if padding_idx is not None and padding_idx >= 0:
            out = jnp.where((idx == padding_idx)[..., None], 0.0, out)
        return {"Out": [out],
                "CumOut": [lax.stop_gradient(cum.reshape(shp))]}
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((idx == padding_idx)[..., None], 0.0, out)
    return one(out)


@register_op("lookup_table_v2", nondiff_inputs=["Ids"],
             grad_fn=_lookup_sparse_grad)
def _lookup_table_v2(ctx, inputs, attrs):
    return _lookup_table_impl(ctx, inputs, attrs)


def _lookup_table_impl(ctx, inputs, attrs):
    (w,) = inputs["W"]
    (ids,) = inputs["Ids"]
    out = jnp.take(w, ids, axis=0)
    padding_idx = attrs.get("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    return one(out)


@register_op("one_hot", differentiable=False)
def _one_hot(ctx, inputs, attrs):
    (x,) = inputs["X"]
    depth = attrs["depth"]
    idx = x[..., 0] if x.ndim >= 2 and x.shape[-1] == 1 else x
    return one(jax.nn.one_hot(idx, depth, dtype=jnp.float32))


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

@register_op("cross_entropy", nondiff_inputs=["Label"])
def _cross_entropy(ctx, inputs, attrs):
    """cross_entropy_op.cc: input is a probability distribution (post-softmax).
    Hard labels (int) index; soft labels dot."""
    (x,) = inputs["X"]
    (label,) = inputs["Label"]
    eps = 1e-8
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        loss = _pick_hard_label(jnp.log(x + eps), label, -1,
                                attrs.get("ignore_index", -100))
    return one(loss)


def _pick_hard_label(logp, label, axis, ignore):
    """Index log-probs by integer labels along `axis` (any position).
    label may carry a singleton at the class axis or omit it."""
    ax = axis % logp.ndim
    idx = label
    if idx.ndim == logp.ndim and idx.shape[ax] == 1:
        idx = jnp.squeeze(idx, ax)
    picked = jnp.take_along_axis(logp, jnp.expand_dims(idx.astype(jnp.int32), ax), axis=ax)
    loss = -picked
    if ignore is not None:
        loss = jnp.where(jnp.expand_dims(idx == ignore, ax), 0.0, loss)
    return loss


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _hard_label_ce(logits, idx, axis, ignore):
    """Memory-lean hard-label CE: works on low-precision logits directly
    (f32 reductions in-register), saves only (logits, idx, lse) for the
    backward — never materializes a full-vocab f32 softmax. At BERT's MLM
    head ([B·T, 30k] logits) this halves the HBM traffic of the loss."""
    loss, _ = _hard_label_ce_fwd(logits, idx, axis, ignore)
    return loss


def _hard_label_ce_fwd(logits, idx, axis, ignore):
    ax = axis % logits.ndim
    # max over the native dtype is exact (max of bf16 values IS a bf16), and
    # each .astype(f32) below has exactly one consumer chain so XLA fuses the
    # cast into the reduce — a shared `lf = logits.astype(f32)` would
    # materialize a full-vocab f32 copy (4 GB on the BERT-base MLM head)
    m = jnp.max(logits, axis=ax, keepdims=True)
    sumexp = jnp.sum(jnp.exp(logits.astype(jnp.float32)
                             - m.astype(jnp.float32)),
                     axis=ax, keepdims=True)
    lse = m.astype(jnp.float32) + jnp.log(sumexp)
    picked = jnp.take_along_axis(
        logits, jnp.expand_dims(idx.astype(jnp.int32), ax),
        axis=ax).astype(jnp.float32)
    loss = lse - picked
    if ignore is not None:
        loss = jnp.where(jnp.expand_dims(idx == ignore, ax), 0.0, loss)
    return loss, (logits, idx, lse)


def _hard_label_ce_bwd(axis, ignore, res, g):
    logits, idx, lse = res
    ax = axis % logits.ndim
    p = jnp.exp(logits.astype(jnp.float32) - lse)
    iota = lax.broadcasted_iota(jnp.int32, logits.shape, ax)
    onehot = iota == jnp.expand_dims(idx.astype(jnp.int32), ax)
    gv = g
    if ignore is not None:
        gv = jnp.where(jnp.expand_dims(idx == ignore, ax), 0.0, gv)
    dlogits = ((p - onehot) * gv).astype(logits.dtype)
    return dlogits, None


_hard_label_ce.defvjp(_hard_label_ce_fwd, _hard_label_ce_bwd)


@register_op("softmax_with_cross_entropy", nondiff_inputs=["Label"])
def _softmax_with_cross_entropy(ctx, inputs, attrs):
    (logits,) = inputs["Logits"]
    (label,) = inputs["Label"]
    axis = attrs.get("axis", -1)
    if not attrs.get("soft_label", False):
        ax = axis % logits.ndim
        idx = label
        if idx.ndim == logits.ndim and idx.shape[ax] == 1:
            idx = jnp.squeeze(idx, ax)
        loss = _hard_label_ce(logits, idx, axis,
                              attrs.get("ignore_index", -100))
        # recomputed independently of the loss path → DCE'd when unused
        softmax = jax.nn.softmax(logits.astype(jnp.float32), axis=axis)
        return {"Loss": [loss], "Softmax": [softmax]}
    # soft-label path: the op is AMP-white-listed (inputs may arrive bf16),
    # so upcast — a vocab-length bf16 accumulation would lose ~3 digits
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
    loss = -jnp.sum(label.astype(jnp.float32) * logp, axis=axis,
                    keepdims=True)
    return {"Loss": [loss], "Softmax": [jnp.exp(logp)]}


@register_op("sigmoid_cross_entropy_with_logits", nondiff_inputs=["Label"])
def _sigmoid_ce(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (label,) = inputs["Label"]
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore = attrs.get("ignore_index", -100)
    loss = jnp.where(label == ignore, 0.0, loss)
    if attrs.get("normalize", False):
        n = jnp.maximum(jnp.sum(jnp.where(label != ignore, 1.0, 0.0)), 1.0)
        loss = loss / n
    return one(loss)


@register_op("square_error_cost", nondiff_inputs=["Label"])
def _square_error_cost(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (label,) = inputs["Label"]
    return one(jnp.square(x - label))


@register_op("smooth_l1_loss", nondiff_inputs=["Y"])
def _smooth_l1(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    diff = jnp.abs(x - y)
    loss = jnp.where(diff < 1.0 / s2, 0.5 * s2 * diff * diff, diff - 0.5 / s2)
    loss = jnp.sum(loss.reshape(x.shape[0], -1), axis=1, keepdims=True)
    return {"Out": [loss], "Diff": [x - y]}


@register_op("huber_loss", nondiff_inputs=["Y"])
def _huber(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    delta = attrs.get("delta", 1.0)
    diff = y - x
    ad = jnp.abs(diff)
    loss = jnp.where(ad <= delta, 0.5 * diff * diff, delta * (ad - 0.5 * delta))
    return {"Out": [loss], "Residual": [diff]}


@register_op("kldiv_loss", nondiff_inputs=["Target"])
def _kldiv(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (t,) = inputs["Target"]
    loss = jnp.where(t > 0, t * (jnp.log(t) - x), 0.0)
    red = attrs.get("reduction", "mean")
    if red == "mean":
        loss = jnp.mean(loss)
    elif red == "sum":
        loss = jnp.sum(loss)
    elif red == "batchmean":
        loss = jnp.sum(loss) / x.shape[0]
    return one(loss)


@register_op("log_loss", nondiff_inputs=["Labels"])
def _log_loss(ctx, inputs, attrs):
    (p,) = inputs["Predicted"]
    (y,) = inputs["Labels"]
    eps = attrs.get("epsilon", 1e-4)
    return {"Loss": [-y * jnp.log(p + eps) - (1.0 - y) * jnp.log(1.0 - p + eps)]}


@register_op("margin_rank_loss", nondiff_inputs=["Label"])
def _margin_rank_loss(ctx, inputs, attrs):
    (x1,) = inputs["X1"]
    (x2,) = inputs["X2"]
    (label,) = inputs["Label"]
    margin = attrs.get("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": [out], "Activated": [lax.stop_gradient((out > 0).astype(x1.dtype))]}


@register_op("cos_sim", nondiff_inputs=[])
def _cos_sim(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn + 1e-12)
    return {"Out": [out], "XNorm": [xn], "YNorm": [yn]}
