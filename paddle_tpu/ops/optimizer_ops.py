"""Optimizer update ops.

Reference analog: ``paddle/fluid/operators/optimizers/`` (sgd_op.cc,
momentum_op.cc, adam_op.cc, adagrad_op.cc, rmsprop_op.cc, adadelta_op.cc,
adamax_op.cc, ftrl_op.cc, lamb_op.cc, lars_momentum_op.cc,
decayed_adagrad_op.cc, proximal_gd_op.cc, proximal_adagrad_op.cc).

All are non-differentiable state-update ops: they read Param/Grad/accumulators
and write the updated values to the same var names; the executor's functional
state threading turns this into donated-buffer in-place updates in HBM.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from ..core.selected_rows import SelectedRows


def _lr(inputs):
    (lr,) = inputs["LearningRate"]
    return lr.reshape(()) if hasattr(lr, "reshape") else lr


@register_op("sgd", differentiable=False)
def _sgd(ctx, inputs, attrs):
    (p,) = inputs["Param"]
    (g,) = inputs["Grad"]
    if isinstance(g, SelectedRows):
        # sgd_op.cc SelectedRows kernel: touched rows only (duplicates
        # accumulate in the scatter-add)
        return {"ParamOut": [p.at[g.ids].add(
            (-_lr(inputs)) * g.rows.astype(p.dtype))]}
    return {"ParamOut": [p - _lr(inputs) * g.astype(p.dtype)]}


@register_op("momentum", differentiable=False)
def _momentum(ctx, inputs, attrs):
    (p,) = inputs["Param"]
    (g,) = inputs["Grad"]
    (v,) = inputs["Velocity"]
    mu = attrs["mu"]
    lr = _lr(inputs)
    v_out = mu * v + g
    if attrs.get("use_nesterov", False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": [p_out], "VelocityOut": [v_out]}


@register_op("dgc_momentum", differentiable=False)
def _dgc_momentum(ctx, inputs, attrs):
    """DGC momentum (reference optimizer.py:799 math, program path):
    momentum-correct into the send buffer, top-k select with error
    feedback, sparse parameter update. Dense momentum until
    rampup_begin_step; sparsity then steps through attrs['sparsity'] over
    rampup_step steps. Static shapes throughout: the top-k size is the
    LOOSEST sparsity's k (the schedule's largest keep-set), with tighter
    stages applied as a rank-cutoff mask (each compile sees one k)."""
    (p,) = inputs["Param"]
    (g,) = inputs["Grad"]
    (v,) = inputs["Velocity"]
    (r,) = inputs["Residual"]
    (step,) = inputs["Step"]
    mu = attrs["mu"]
    lr = _lr(inputs)
    sparsity = list(attrs.get("sparsity", [0.999]))
    rampup_begin = attrs.get("rampup_begin_step", 0)
    rampup_step = max(1, attrs.get("rampup_step", 1))
    g = g.astype(p.dtype)
    dense_phase = step.reshape(()) < rampup_begin

    # DGC local gradient clipping (paper §3.2 / reference dgc_clip_by_norm):
    # without it, coordinates that wait ~1/ratio steps between sends
    # accumulate unbounded momentum mass and the sparse update diverges.
    # SPARSE phase only — the dense rampup must behave exactly like plain
    # momentum. clip_norm=0 disables.
    clip = attrs.get("clip_norm", 1.0)
    if clip:
        gn = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
        g_clipped = (g * jnp.minimum(1.0, clip / (gn + 1e-12))).astype(
            p.dtype)
        g = jnp.where(dense_phase, g, g_clipped)

    # momentum correction: local momentum feeds the send buffer
    v_out = mu * v + g
    u = r + v_out

    flat = u.reshape(-1)
    n = flat.shape[0]
    # size k from the LOOSEST sparsity in the schedule (smallest sparsity →
    # largest keep ratio) so ascending rampup stages (e.g. [0.75, ...,
    # 0.999]) can keep more entries than the final stage; the per-stage
    # mask below then trims to the current stage's ratio. Sizing from the
    # final stage would clamp every rampup stage to the final k, collapsing
    # the documented gradual ramp (reference optimizer.py rampup semantics).
    # Steady state must NOT pay the loose k forever, so post-rampup steps
    # take a lax.cond branch that runs top_k at the final (small) k and
    # pads the index list — rampup is a sliver of training; the final
    # sparsity is the hot path.
    loosest_ratio = 1.0 - min(sparsity)
    k = max(1, int(n * loosest_ratio))
    k_final = max(1, int(n * (1.0 - sparsity[-1])))
    absflat = jnp.abs(flat)
    if k_final == k:
        idx = lax.top_k(absflat, k)[1]
    else:
        in_rampup = step.reshape(()) < (rampup_begin + rampup_step)

        def _loose(_):
            return lax.top_k(absflat, k)[1]

        def _final(_):
            # pad with duplicates of the best index; the padded ranks get
            # keep=0 below and the scatter uses .max(), so duplicate
            # writes cannot clear a kept position
            idx_f = lax.top_k(absflat, k_final)[1]
            return jnp.concatenate(
                [idx_f, jnp.broadcast_to(idx_f[:1], (k - k_final,))])

        idx = lax.cond(in_rampup, _loose, _final, None)

    # rampup: current sparsity stage by step count (traced select over the
    # static schedule keeps one compilation)
    stage = jnp.clip((step.reshape(()) - rampup_begin)
                     // max(1, rampup_step // max(1, len(sparsity))),
                     0, len(sparsity) - 1).astype(jnp.int32)
    ratios = jnp.asarray([1.0 - s for s in sparsity], jnp.float32)
    cur_ratio = ratios[stage]
    # keep the top cur_ratio·n entries of the top-k candidates: entries
    # ranked beyond cur_ratio·n are masked out (idx is sorted by |u| desc)
    rank = jnp.arange(k, dtype=jnp.float32)
    keep = (rank < jnp.maximum(1.0, cur_ratio * n)).astype(p.dtype)

    mask = jnp.zeros_like(flat).at[idx].max(keep)
    mask = jnp.where(dense_phase, jnp.ones_like(mask), mask)
    sparse = (flat * mask).reshape(p.shape)
    r_out = (flat * (1.0 - mask)).reshape(p.shape)

    # momentum factor masking (DGC paper §3.2 / reference dgc_op.cc): clear
    # the velocity at SENT positions too, else stale momentum keeps pushing
    # a coordinate long after its accumulated mass was applied — measured
    # divergence without this. Dense phase keeps the full velocity.
    vel_keep = jnp.where(dense_phase, jnp.ones_like(mask), 1.0 - mask)
    v_out = (v_out.reshape(-1) * vel_keep).reshape(p.shape)

    if attrs.get("use_nesterov", False):
        # dense phase must match the momentum op's Nesterov exactly:
        # p − lr·(g + mu·v'); sparse phase applies the selected mass only
        # (Nesterov lookahead is undefined for coordinates not sent)
        p_out = p - lr * jnp.where(dense_phase, g + mu * v_out, sparse)
    else:
        p_out = p - lr * sparse
    return {"ParamOut": [p_out], "VelocityOut": [v_out],
            "ResidualOut": [r_out],
            "StepOut": [step + jnp.ones_like(step)]}


@register_op("lars_momentum", differentiable=False)
def _lars_momentum(ctx, inputs, attrs):
    """lars_momentum_op.cc: layer-wise adaptive rate scaling."""
    (p,) = inputs["Param"]
    (g,) = inputs["Grad"]
    (v,) = inputs["Velocity"]
    mu = attrs["mu"]
    lars_coeff = attrs.get("lars_coeff", 0.001)
    wd = attrs.get("lars_weight_decay", 0.0005)
    eps = attrs.get("epsilon", 0.0)
    lr = _lr(inputs)
    p_norm = jnp.sqrt(jnp.sum(p * p))
    g_norm = jnp.sqrt(jnp.sum(g * g))
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * lars_coeff * p_norm / (g_norm + wd * p_norm + eps),
        lr)
    v_out = mu * v + local_lr * (g + wd * p)
    return {"ParamOut": [p - v_out], "VelocityOut": [v_out]}


@register_op("adam", differentiable=False)
def _adam(ctx, inputs, attrs):
    (p,) = inputs["Param"]
    (g,) = inputs["Grad"]
    (m,) = inputs["Moment1"]
    (v,) = inputs["Moment2"]
    (b1p,) = inputs["Beta1Pow"]
    (b2p,) = inputs["Beta2Pow"]
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(inputs)
    lr_t = lr * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(()))
    if isinstance(g, SelectedRows):
        # adam_op.cc SelectedRows kernel (lazy mode): only touched rows
        # advance; duplicates are merged first (adam is nonlinear in g, so
        # scatter-add of per-occurrence updates would be wrong). merged()
        # broadcasts each id's total to every duplicate position, making the
        # scatter-`set`s deterministic.
        ids, rows = g.merged()
        rows = rows.astype(p.dtype)
        m_r = b1 * m[ids] + (1 - b1) * rows
        v_r = b2 * v[ids] + (1 - b2) * rows * rows
        p_r = p[ids] - lr_t * m_r / (jnp.sqrt(v_r) + eps)
        return {
            "ParamOut": [p.at[ids].set(p_r)],
            "Moment1Out": [m.at[ids].set(m_r)],
            "Moment2Out": [v.at[ids].set(v_r)],
            "Beta1PowOut": [b1p * b1], "Beta2PowOut": [b2p * b2],
        }
    g = g.astype(p.dtype)
    m_out = b1 * m + (1 - b1) * g
    v_out = b2 * v + (1 - b2) * g * g
    p_out = p - lr_t * m_out / (jnp.sqrt(v_out) + eps)
    return {
        "ParamOut": [p_out], "Moment1Out": [m_out], "Moment2Out": [v_out],
        "Beta1PowOut": [b1p * b1], "Beta2PowOut": [b2p * b2],
    }


@register_op("adamw", differentiable=False)
def _adamw(ctx, inputs, attrs):
    """Decoupled weight decay variant (beyond-reference; standard for BERT)."""
    (p,) = inputs["Param"]
    (g,) = inputs["Grad"]
    (m,) = inputs["Moment1"]
    (v,) = inputs["Moment2"]
    (b1p,) = inputs["Beta1Pow"]
    (b2p,) = inputs["Beta2Pow"]
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    wd = attrs.get("coeff", 0.01)
    lr = _lr(inputs)
    g = g.astype(p.dtype)
    m_out = b1 * m + (1 - b1) * g
    v_out = b2 * v + (1 - b2) * g * g
    lr_t = lr * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(()))
    p_out = p - lr_t * (m_out / (jnp.sqrt(v_out) + eps)) - lr * wd * p
    return {
        "ParamOut": [p_out], "Moment1Out": [m_out], "Moment2Out": [v_out],
        "Beta1PowOut": [b1p * b1], "Beta2PowOut": [b2p * b2],
    }


@register_op("adamax", differentiable=False)
def _adamax(ctx, inputs, attrs):
    (p,) = inputs["Param"]
    (g,) = inputs["Grad"]
    (m,) = inputs["Moment"]
    (inf_norm,) = inputs["InfNorm"]
    (b1p,) = inputs["Beta1Pow"]
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(inputs)
    m_out = b1 * m + (1 - b1) * g
    inf_out = jnp.maximum(b2 * inf_norm, jnp.abs(g))
    p_out = p - (lr / (1 - b1p.reshape(()))) * m_out / (inf_out + eps)
    return {"ParamOut": [p_out], "MomentOut": [m_out], "InfNormOut": [inf_out]}


@register_op("adagrad", differentiable=False)
def _adagrad(ctx, inputs, attrs):
    (p,) = inputs["Param"]
    (g,) = inputs["Grad"]
    (m,) = inputs["Moment"]
    eps = attrs.get("epsilon", 1e-6)
    lr = _lr(inputs)
    if isinstance(g, SelectedRows):
        # adagrad_op.cc SparseAdagradFunctor: duplicates merged first
        # (adagrad is nonlinear in g), then touched rows advance
        ids, rows = g.merged()
        rows = rows.astype(p.dtype)
        m_r = m[ids] + rows * rows
        p_r = p[ids] - lr * rows / (jnp.sqrt(m_r) + eps)
        return {"ParamOut": [p.at[ids].set(p_r)],
                "MomentOut": [m.at[ids].set(m_r)]}
    m_out = m + g * g
    return {"ParamOut": [p - lr * g / (jnp.sqrt(m_out) + eps)], "MomentOut": [m_out]}


@register_op("decayed_adagrad", differentiable=False)
def _decayed_adagrad(ctx, inputs, attrs):
    (p,) = inputs["Param"]
    (g,) = inputs["Grad"]
    (m,) = inputs["Moment"]
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    lr = _lr(inputs)
    m_out = decay * m + (1 - decay) * g * g
    return {"ParamOut": [p - lr * g / (jnp.sqrt(m_out) + eps)], "MomentOut": [m_out]}


@register_op("adadelta", differentiable=False)
def _adadelta(ctx, inputs, attrs):
    (p,) = inputs["Param"]
    (g,) = inputs["Grad"]
    (avg_sq_g,) = inputs["AvgSquaredGrad"]
    (avg_sq_u,) = inputs["AvgSquaredUpdate"]
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    g_out = rho * avg_sq_g + (1 - rho) * g * g
    update = -jnp.sqrt((avg_sq_u + eps) / (g_out + eps)) * g
    u_out = rho * avg_sq_u + (1 - rho) * update * update
    return {"ParamOut": [p + update], "AvgSquaredGradOut": [g_out], "AvgSquaredUpdateOut": [u_out]}


@register_op("rmsprop", differentiable=False)
def _rmsprop(ctx, inputs, attrs):
    (p,) = inputs["Param"]
    (g,) = inputs["Grad"]
    (ms,) = inputs["MeanSquare"]
    (mg,) = inputs["MeanGrad"]
    (mom,) = inputs["Moment"]
    rho = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    momentum = attrs.get("momentum", 0.0)
    centered = attrs.get("centered", False)
    lr = _lr(inputs)
    ms_out = rho * ms + (1 - rho) * g * g
    if centered:
        mg_out = rho * mg + (1 - rho) * g
        denom = ms_out - mg_out * mg_out + eps
    else:
        mg_out = mg
        denom = ms_out + eps
    mom_out = momentum * mom + lr * g / jnp.sqrt(denom)
    return {"ParamOut": [p - mom_out], "MeanSquareOut": [ms_out],
            "MeanGradOut": [mg_out], "MomentOut": [mom_out]}


@register_op("ftrl", differentiable=False)
def _ftrl(ctx, inputs, attrs):
    (p,) = inputs["Param"]
    (g,) = inputs["Grad"]
    (sq,) = inputs["SquaredAccumulator"]
    (lin,) = inputs["LinearAccumulator"]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr_power = attrs.get("lr_power", -0.5)
    lr = _lr(inputs)
    new_sq = sq + g * g
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (new_sq ** (-lr_power) - sq ** (-lr_power)) / lr
    lin_out = lin + g - sigma * p
    if lr_power == -0.5:
        x = l2 + jnp.sqrt(new_sq) / lr
    else:
        x = l2 + new_sq ** (-lr_power) / lr
    pre = jnp.clip(lin_out, -l1, l1) - lin_out
    p_out = jnp.where(jnp.abs(lin_out) > l1, pre / x, jnp.zeros_like(p))
    return {"ParamOut": [p_out], "SquaredAccumOut": [new_sq], "LinearAccumOut": [lin_out]}


@register_op("lamb", differentiable=False)
def _lamb(ctx, inputs, attrs):
    """lamb_op.cc: layer-wise adaptation for large batches (BERT-scale)."""
    (p,) = inputs["Param"]
    (g,) = inputs["Grad"]
    (m,) = inputs["Moment1"]
    (v,) = inputs["Moment2"]
    (b1p,) = inputs["Beta1Pow"]
    (b2p,) = inputs["Beta2Pow"]
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    lr = _lr(inputs)
    g = g.astype(p.dtype)
    m_out = b1 * m + (1 - b1) * g
    v_out = b2 * v + (1 - b2) * g * g
    m_hat = m_out / (1 - b1p.reshape(()))
    v_hat = v_out / (1 - b2p.reshape(()))
    r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p
    p_norm = jnp.sqrt(jnp.sum(p * p))
    r_norm = jnp.sqrt(jnp.sum(r * r))
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    return {
        "ParamOut": [p - lr * trust * r], "Moment1Out": [m_out], "Moment2Out": [v_out],
        "Beta1PowOut": [b1p * b1], "Beta2PowOut": [b2p * b2],
    }


@register_op("proximal_gd", differentiable=False)
def _proximal_gd(ctx, inputs, attrs):
    (p,) = inputs["Param"]
    (g,) = inputs["Grad"]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr = _lr(inputs)
    prox = p - lr * g
    p_out = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) / (1.0 + lr * l2)
    return {"ParamOut": [p_out]}
