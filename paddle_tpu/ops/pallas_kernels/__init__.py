"""Pallas TPU kernels for the hot ops.

No reference analog as such — the reference's hot-op strategy is hand-written
CUDA (e.g. softmax_cudnn, fused attention via operators/fused/) plus the x86
JIT library (operators/jit/). On TPU the equivalent of "hand kernel where the
compiler isn't enough" is Pallas; everything else stays plain JAX and lets XLA
fuse. The dispatch idea of operators/jit (pick best impl at runtime) survives
as: pallas kernel on TPU when its constraints hold, blockwise-JAX fallback
everywhere else.
"""
from .flash_attention import flash_attention  # noqa: F401
from .sparse_adagrad import fused_adagrad_update  # noqa: F401
