"""FlashAttention for TPU: online-softmax attention without the T×T tensor.

Replaces the reference's attention pattern (matmul → softmax → dropout →
matmul over a materialized [B,H,T,T] score tensor — PaddleNLP on the SURVEY
§2.1 op set) with a memory-bandwidth-shaped design:

- forward: a Pallas kernel tiles Q into VMEM blocks and streams K/V blocks
  through the MXU, keeping the running max/denominator in VMEM scratch —
  HBM traffic is O(T·D) instead of O(T²); attention-probability dropout is
  generated *inside* the kernel from the on-core PRNG (per-block reseed),
  so no mask tensor ever touches HBM;
- backward: two Pallas kernels recompute p from the saved (q, k, lse)
  blockwise — a dq kernel (grid b×nq×nk, dq accumulated in VMEM) and a
  dk/dv kernel (grid b×nk×nq) — nothing quadratic is stored between fwd
  and bwd. Dropout masks are regenerated bit-identically from the same
  per-(batch, q-block, k-block) seeds;
- a pure-JAX two-pass fallback with identical semantics runs on CPU (tests)
  and for shapes the kernel doesn't tile.

The public entry is `flash_attention(q, k, v, bias, causal, ...)` wrapped in
`jax.custom_vjp`, so the framework's per-op autodiff tape picks up the
memory-efficient backward automatically.

Bias is additive, broadcastable against [B, H, Tq, Tk] — the BERT input mask
([B,1,1,T]) and ALiBi-style biases both fit, and the bias gradient is
returned (reduced over broadcast dims).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# 512² blocks keep the whole [T,T] score tile in VMEM for BERT-scale
# sequence lengths: measured on v5e, bq=bk=512 runs the forward ~2.5× faster
# than 128² (fewer grid steps amortize the per-step DMA + online-softmax
# corrections; the kernel is VPU/exp-bound, so bigger MXU tiles are free)
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_LANES = 128  # TPU lane width: scratch stats are kept lane-replicated
_NEG_INF = -1e30

# Tests may set this to run the Pallas kernels on CPU through the
# interpreter (dropout kernels need pltpu.InterpretParams; the interpreter's
# PRNG returns zeros, so dropout-path numerics are TPU-only).
FORCE_PALLAS_INTERPRET = False


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


try:  # pallas import is deferred-safe: CPU-only envs still import this module
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    pl = pltpu = None
    _HAVE_PALLAS = False

# jax renamed TPUCompilerParams -> CompilerParams across 0.4/0.5
_CompilerParams = (getattr(pltpu, "CompilerParams", None)
                   or getattr(pltpu, "TPUCompilerParams", None)
                   if _HAVE_PALLAS else None)


# ---------------------------------------------------------------------------
# In-kernel dropout: per-(b, q-block, k-block) reseed of the core PRNG, so
# forward and both backward kernels regenerate identical masks regardless of
# their grid iteration order.
# ---------------------------------------------------------------------------

def _keep_mask(seed_ref, block_index, shape, rate):
    # Mosaic supports at most 2 prng_seed values — the caller folds
    # (b, q-block, k-block) into one grid-order-independent index so the
    # same logical block regenerates the same stream in all three kernels.
    pltpu.prng_seed(seed_ref[0], block_index)
    bits = lax.bitcast_convert_type(pltpu.prng_random_bits(shape), jnp.uint32)
    # drop iff bits < rate·2³² → P(keep) = 1 − rate
    return bits >= jnp.uint32(int(round(rate * 4294967296.0)) & 0xFFFFFFFF)


def _block_index(b, iq, ik, nq, nk):
    return (b * nq + iq) * nk + ik


def _seed_from_key(dropout_key):
    if dropout_key is None:
        return jnp.zeros((1,), jnp.int32)
    return jax.random.randint(dropout_key, (1,), 0, np.iinfo(np.int32).max,
                              dtype=jnp.int32)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, sm_scale, causal, block_q, block_k,
                dropout_rate):
    b, iq, ik = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nq, nk = pl.num_programs(1), pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _body():
        # native-dtype operands (bf16 under AMP → bf16 MXU inputs), f32 accum
        q = q_ref[0]                                          # [bq, D]
        k = k_ref[0]                                          # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale    # [bq, bk]
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)           # [bq or 1, bk]
        if causal:
            q_pos = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ik * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

        m_prev = m_ref[:, :1]                                 # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)            # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                                # [bq, bk]
        corr = jnp.exp(m_prev - m_new)                        # [bq, 1]
        # denominator uses the *undropped* probabilities (dropout acts on
        # normalized attention probs; masking/scaling commutes with the
        # final division by l)
        l_new = l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate > 0.0:
            keep = _keep_mask(seed_ref, _block_index(b, iq, ik, nq, nk),
                              (block_q, block_k), dropout_rate)
            p_v = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout_rate))
        else:
            p_v = p
        v_blk = v_ref[0]
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p_v.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # whole block above the diagonal → nothing to do
        @pl.when(ik * block_k <= iq * block_q + block_q - 1)
        def _():
            _body()
    else:
        _body()

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = (m_ref[...] + jnp.log(l_safe)).astype(jnp.float32)


def _flash_fwd_pallas(q, k, v, bias, sm_scale, causal, block_q, block_k,
                      interpret=False, dropout_rate=0.0, seed=None):
    """q,k,v: [BH, T, D] (heads folded); bias: [BH, Tq_or_1, Tk] or None.
    Returns (out [BH,T,D], lse [BH,T])."""
    bh, t, d = q.shape
    block_q, block_k = min(block_q, t), min(block_k, t)
    nq, nk = t // block_q, t // block_k
    if nq == 1 and nk == 1:
        per_q_bias = bias is not None and bias.shape[1] != 1
        group = _pick_group(
            bh, t, d, _tt_bytes_per_head(1, per_q_bias, dropout_rate, t))
        if group > 1:
            return _flash_fwd_pallas_onepass(
                q, k, v, bias, sm_scale, causal, group, interpret=interpret,
                dropout_rate=dropout_rate, seed=seed)
    grid = (bh, nq, nk)

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j, *_: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j, *_: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j, *_: (b, j, 0)),
    ]
    args = [q, k, v]
    if bias is not None:
        if bias.shape[1] != 1:
            in_specs.append(pl.BlockSpec(
                (1, block_q, block_k), lambda b, i, j, *_: (b, i, j)))
        else:
            in_specs.append(pl.BlockSpec(
                (1, 1, block_k), lambda b, i, j, *_: (b, 0, j)))
        args.append(bias)

    body = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                             block_q=block_q, block_k=block_k,
                             dropout_rate=dropout_rate)
    if bias is not None:
        kernel = body
    else:
        def kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m, l):
            body(seed_ref, q_ref, k_ref, v_ref, None, o_ref, lse_ref,
                 acc, m, l)

    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)

    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, block_q, _LANES),
                             lambda b, i, j, *_: (b, i, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, _LANES), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, _LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(seed, *args)
    # lse is sliced compact [BH, T] for the residual: keeping the
    # lane-replicated [BH,T,128] form between fwd and bwd saves a
    # slice→re-broadcast round trip (~2 ms/step) but costs 128× the memory
    # (2.3 GB of residuals on BERT-base b=64) — which forces XLA into far
    # more expensive rematerializations. Memory wins.
    return out, lse[:, :, 0]


# ---------------------------------------------------------------------------
# One-pass grouped kernels (T fits one block, i.e. nq == nk == 1).
#
# The general kernels pay a fixed per-grid-step cost (DMA setup, online-
# softmax stats corrections) on a grid of BH tiny steps — measured 14% MXU
# on BERT-base shapes (BH=768, T=512, D=64). When the whole sequence fits a
# single block the online softmax is unnecessary; these kernels batch G
# heads per grid step (BlockSpec (G, T, D) on the folded layout — leading-
# dim blocking, so no 64-wide minor slicing, unlike the rejected head-
# native path below) and compute plain softmax in one pass. Dropout masks
# are generated PER HEAD with the head's global index, so they are
# identical to the non-grouped kernels' masks (whose block index reduces to
# `b` when nq == nk == 1) — fwd and bwd may even pick different group sizes.
# ---------------------------------------------------------------------------

def _causal_mask_full(t):
    q_pos = lax.broadcasted_iota(jnp.int32, (t, t), 0)
    k_pos = lax.broadcasted_iota(jnp.int32, (t, t), 1)
    return q_pos >= k_pos


def _group_keep_mask(seed_ref, g0, group, t, rate):
    """[G, T, T] keep mask; per-head streams keyed by global head index."""
    rows = []
    for i in range(group):
        rows.append(_keep_mask(seed_ref, g0 * group + i, (t, t), rate))
    return jnp.stack(rows)


def _fwd_kernel_onepass(seed_ref, q_ref, k_ref, v_ref, bias_ref, o_ref,
                        lse_ref, *, sm_scale, causal, dropout_rate, group):
    g0 = pl.program_id(0)
    q, k, v = q_ref[...], k_ref[...], v_ref[...]          # [G, T, D]
    t = q.shape[1]
    s = lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32) * sm_scale
    if bias_ref is not None:
        s = s + bias_ref[...].astype(jnp.float32)         # [G, Tq or 1, T]
    if causal:
        s = jnp.where(_causal_mask_full(t)[None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)                # [G, T, 1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    if dropout_rate > 0.0:
        keep = _group_keep_mask(seed_ref, g0, group, t, dropout_rate)
        p_v = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout_rate))
    else:
        p_v = p
    acc = lax.dot_general(p_v.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
                          preferred_element_type=jnp.float32)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[...] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[...] = jnp.broadcast_to(m + jnp.log(l_safe),
                                    lse_ref.shape).astype(jnp.float32)


def _flash_fwd_pallas_onepass(q, k, v, bias, sm_scale, causal, group,
                              interpret=False, dropout_rate=0.0, seed=None):
    bh, t, d = q.shape
    grid = (bh // group,)
    in_specs = [pl.BlockSpec((group, t, d), lambda b, *_: (b, 0, 0))] * 3
    args = [q, k, v]
    if bias is not None:
        in_specs.append(pl.BlockSpec((group, bias.shape[1], t),
                                     lambda b, *_: (b, 0, 0)))
        args.append(bias)

    body = functools.partial(_fwd_kernel_onepass, sm_scale=sm_scale,
                             causal=causal, dropout_rate=dropout_rate,
                             group=group)
    if bias is not None:
        kernel = body
    else:
        def kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref):
            body(seed_ref, q_ref, k_ref, v_ref, None, o_ref, lse_ref)

    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((group, t, d), lambda b, *_: (b, 0, 0)),
                pl.BlockSpec((group, t, _LANES), lambda b, *_: (b, 0, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, _LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(seed, *args)
    return out, lse[:, :, 0]


def _bwd_kernel_onepass(seed_ref, q_ref, k_ref, v_ref, bias_ref, g_ref,
                        lse_ref, delta_ref, dq_ref, dk_ref, dv_ref,
                        dbias_ref, dbias_col_ref, *, sm_scale, causal,
                        dropout_rate, group):
    g0 = pl.program_id(0)
    q, k, v, g = q_ref[...], k_ref[...], v_ref[...], g_ref[...]  # [G, T, D]
    t = q.shape[1]
    s = lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32) * sm_scale
    if bias_ref is not None:
        s = s + bias_ref[...].astype(jnp.float32)
    if causal:
        s = jnp.where(_causal_mask_full(t)[None], s, _NEG_INF)
    lse = lse_ref[:, :, :1]                                # [G, T, 1]
    p = jnp.exp(s - lse)                                   # [G, T, T]
    if dropout_rate > 0.0:
        keep = _group_keep_mask(seed_ref, g0, group, t, dropout_rate)
        inv = 1.0 / (1.0 - dropout_rate)
        p_d = jnp.where(keep, p, 0.0) * inv
    else:
        p_d = p
    # dv = p_dropᵀ · dO  (contract over q)
    dv = lax.dot_general(p_d.astype(g.dtype), g, (((1,), (1,)), ((0,), (0,))),
                         preferred_element_type=jnp.float32)
    dp = lax.dot_general(g, v, (((2,), (2,)), ((0,), (0,))),
                         preferred_element_type=jnp.float32)  # [G, T, T]
    if dropout_rate > 0.0:
        dp = jnp.where(keep, dp * inv, 0.0)
    ds = p * (dp - delta_ref[:, :, :1])                    # [G, T, T]
    ds_c = ds.astype(q.dtype)
    dk = lax.dot_general(ds_c, q, (((1,), (1,)), ((0,), (0,))),
                         preferred_element_type=jnp.float32)
    dq = lax.dot_general(ds_c, k, (((2,), (1,)), ((0,), (0,))),
                         preferred_element_type=jnp.float32)
    dq_ref[...] = (dq * sm_scale).astype(dq_ref.dtype)
    dk_ref[...] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)
    if dbias_ref is not None:
        dbias_ref[...] = ds.astype(dbias_ref.dtype)
    if dbias_col_ref is not None:
        dbias_col_ref[...] = jnp.sum(ds, axis=1, keepdims=True).astype(
            dbias_col_ref.dtype)


def _bwd_host_prep(q, g, lse, out):
    """Shared residual preprocessing for both backward wrappers.

    delta = Σ_d dO·out; lse/delta are lane-replicated for the kernels. The
    optimization_barrier ties the lse broadcast to g: without the data
    dependency XLA's scheduler hoists every layer's 128-lane-replicated
    broadcast to the start of the backward and keeps them all live
    (~190 MB × layers); a `+ 0*g[0]` tie would instead propagate a single
    inf/NaN to every row."""
    bh, t, _ = q.shape
    gf = g.astype(q.dtype)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    lse, _ = lax.optimization_barrier((lse, gf))
    lse_r = jnp.broadcast_to(lse[:, :, None], (bh, t, _LANES))
    delta_r = jnp.broadcast_to(delta[:, :, None], (bh, t, _LANES))
    return gf, lse_r, delta_r


def _flash_bwd_pallas_onepass(q, k, v, bias, g, lse, out, sm_scale, causal,
                              group, dropout_rate=0.0, seed=None,
                              interpret=False):
    bh, t, d = q.shape
    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    gf, lse_r, delta_r = _bwd_host_prep(q, g, lse, out)

    has_bias = bias is not None
    per_q_bias = has_bias and bias.shape[1] != 1
    col_bias = has_bias and not per_q_bias

    in_specs = [pl.BlockSpec((group, t, d), lambda b, *_: (b, 0, 0))] * 3
    args = [q, k, v]
    if has_bias:
        in_specs.append(pl.BlockSpec((group, bias.shape[1], t),
                                     lambda b, *_: (b, 0, 0)))
        args.append(bias)
    in_specs += [
        pl.BlockSpec((group, t, d), lambda b, *_: (b, 0, 0)),
        pl.BlockSpec((group, t, _LANES), lambda b, *_: (b, 0, 0)),
        pl.BlockSpec((group, t, _LANES), lambda b, *_: (b, 0, 0)),
    ]
    args += [gf, lse_r, delta_r]

    out_specs = [pl.BlockSpec((group, t, d), lambda b, *_: (b, 0, 0))] * 3
    out_shape = [jax.ShapeDtypeStruct((bh, t, d), x.dtype) for x in (q, k, v)]
    if per_q_bias:
        out_specs.append(pl.BlockSpec((group, t, t), lambda b, *_: (b, 0, 0)))
        out_shape.append(jax.ShapeDtypeStruct((bh, t, t), jnp.float32))
    if col_bias:
        out_specs.append(pl.BlockSpec((group, 1, t), lambda b, *_: (b, 0, 0)))
        out_shape.append(jax.ShapeDtypeStruct((bh, 1, t), jnp.float32))

    body = functools.partial(_bwd_kernel_onepass, sm_scale=sm_scale,
                             causal=causal, dropout_rate=dropout_rate,
                             group=group)

    def kernel(seed_ref, *refs):
        n_in = 6 + (1 if has_bias else 0)
        ins, outs = refs[:n_in], refs[n_in:]
        if has_bias:
            q_r, k_r, v_r, b_r, g_r, l_r, d_r = ins
        else:
            (q_r, k_r, v_r, g_r, l_r, d_r), b_r = ins, None
        dq_r, dk_r, dv_r = outs[:3]
        db_r = outs[3] if per_q_bias else None
        dbc_r = outs[3] if col_bias else None
        body(seed_ref, q_r, k_r, v_r, b_r, g_r, l_r, d_r,
             dq_r, dk_r, dv_r, db_r, dbc_r)

    res = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh // group,),
            in_specs=in_specs,
            out_specs=out_specs,
        ),
        out_shape=out_shape,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(seed, *args)
    dq, dk, dv = res[:3]
    dbias = res[3] if has_bias else None
    return dq, dk, dv, dbias


def _tt_bytes_per_head(base, per_q_bias, dropout_rate, t):
    """Bytes of concurrently-live [T, T]-sized per-head buffers: `base` f32
    intermediates (1 fwd: s/p; 3 bwd: p, dp, ds), the per-q bias input and
    (bwd) dbias output, and the 1-byte dropout keep mask."""
    n_f32 = base + (2 if per_q_bias and base > 1 else 1 if per_q_bias else 0)
    mask = t * t if dropout_rate > 0.0 else 0
    return n_f32 * t * t * 4 + mask


def _pick_group(bh, t, d, tt_bytes, budget=10 * 2 ** 20):
    """Heads per grid step for the one-pass kernels. `tt_bytes` is the
    per-head [T, T]-buffer footprint (see _tt_bytes_per_head); exceeding
    the budget falls back to the general blocked kernels, which is always
    correct."""
    for g in (8, 4, 2):
        need = g * (tt_bytes + 6 * t * d * 4 + 2 * t * _LANES * 4)
        if bh % g == 0 and need <= budget:
            return g
    return 1


# ---------------------------------------------------------------------------
# Pallas backward kernels (flash recompute from saved lse)
#
#   delta = Σ_d dO·out                              (precomputed, [BH,T])
#   p  = exp(s − lse)                               (recomputed per block)
#   dv = p_dropᵀ·dO          dp = dO·vᵀ (drop-scaled)
#   ds = p·(dp − delta)      dk = dsᵀ·q·scale       dq = Σ_j ds·k·scale
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref,
                   delta_ref, dq_ref, dbias_ref, dq_acc, *, sm_scale, causal,
                   block_q, block_k, dropout_rate):
    b, iq, ik = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nq, nk = pl.num_programs(1), pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _body():
        q = q_ref[0]                                          # [bq, D]
        k = k_ref[0]                                          # [bk, D]
        v = v_ref[0]                                          # [bk, D]
        g = g_ref[0]                                          # [bq, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale    # [bq, bk]
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            q_pos = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ik * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        lse = lse_ref[0][:, :1]                               # [bq, 1]
        p = jnp.exp(s - lse)                                  # [bq, bk]
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bq, bk]
        if dropout_rate > 0.0:
            keep = _keep_mask(seed_ref, _block_index(b, iq, ik, nq, nk),
                              (block_q, block_k), dropout_rate)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        delta = delta_ref[0][:, :1]                           # [bq, 1]
        ds = p * (dp - delta)                                 # [bq, bk] f32
        if dbias_ref is not None:
            dbias_ref[0] = ds.astype(dbias_ref.dtype)
        ds_c = ds.astype(k.dtype)
        dq_acc[...] += jax.lax.dot_general(
            ds_c, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

    if causal:
        skip = ik * block_k > iq * block_q + block_q - 1

        @pl.when(jnp.logical_not(skip))
        def _():
            _body()

        if dbias_ref is not None:
            @pl.when(skip)
            def _():
                dbias_ref[0] = jnp.zeros_like(dbias_ref[0])
    else:
        _body()

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dbias_col_ref, dk_acc, dv_acc,
                    db_acc, *, sm_scale, causal, block_q, block_k,
                    dropout_rate):
    # grid is (bh, nk, nq): k-block outer, q-block inner
    b, ik, iq = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nk, nq = pl.num_programs(1), pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)
        if db_acc is not None:
            db_acc[...] = jnp.zeros_like(db_acc)

    def _body():
        q = q_ref[0]                                          # [bq, D]
        k = k_ref[0]                                          # [bk, D]
        v = v_ref[0]                                          # [bk, D]
        g = g_ref[0]                                          # [bq, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale    # [bq, bk]
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            q_pos = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ik * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        lse = lse_ref[0][:, :1]
        p = jnp.exp(s - lse)                                  # [bq, bk]
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bq, bk]
        if dropout_rate > 0.0:
            # same (b, iq, ik) index as fwd/dq kernels → identical mask
            keep = _keep_mask(seed_ref,
                              _block_index(b, iq, ik, nq, nk),
                              (block_q, block_k), dropout_rate)
            inv = 1.0 / (1.0 - dropout_rate)
            p_v = jnp.where(keep, p * inv, 0.0)
            dp = jnp.where(keep, dp * inv, 0.0)
        else:
            p_v = p
        # dv += p_vᵀ·g   (contract q rows)
        dv_acc[...] += jax.lax.dot_general(
            p_v.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bk, D]
        delta = delta_ref[0][:, :1]
        ds = p * (dp - delta)                                 # [bq, bk] f32
        ds_c = ds.astype(q.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds_c, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale    # [bk, D]
        if db_acc is not None:
            db_acc[...] += jnp.sum(ds, axis=0, keepdims=True)  # [1, bk]

    if causal:
        @pl.when(ik * block_k <= iq * block_q + block_q - 1)
        def _():
            _body()
    else:
        _body()

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)
        if dbias_col_ref is not None:
            dbias_col_ref[0] = db_acc[...].astype(dbias_col_ref.dtype)


def _flash_bwd_pallas(q, k, v, bias, g, lse, out, sm_scale, causal,
                      block_q, block_k, dropout_rate=0.0, seed=None,
                      interpret=False):
    """Returns (dq, dk, dv, dbias). dbias is [BH,Tq,Tk] f32 for a per-q bias,
    [BH,1,Tk] f32 for a broadcast (mask-like) bias, or None."""
    bh, t, d = q.shape
    block_q, block_k = min(block_q, t), min(block_k, t)
    nq, nk = t // block_q, t // block_k
    if nq == 1 and nk == 1:
        per_q_bias = bias is not None and bias.shape[1] != 1
        group = _pick_group(
            bh, t, d, _tt_bytes_per_head(3, per_q_bias, dropout_rate, t))
        if group > 1:
            return _flash_bwd_pallas_onepass(
                q, k, v, bias, g, lse, out, sm_scale, causal, group,
                dropout_rate=dropout_rate, seed=seed, interpret=interpret)
    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)

    gf, lse_r, delta_r = _bwd_host_prep(q, g, lse, out)

    has_bias = bias is not None
    per_q_bias = has_bias and bias.shape[1] != 1

    # ---- dq kernel: grid (bh, nq, nk) --------------------------------------
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j, *_: (b, i, 0)),   # q
        pl.BlockSpec((1, block_k, d), lambda b, i, j, *_: (b, j, 0)),   # k
        pl.BlockSpec((1, block_k, d), lambda b, i, j, *_: (b, j, 0)),   # v
    ]
    args = [q, k, v]
    if has_bias:
        if per_q_bias:
            in_specs.append(pl.BlockSpec(
                (1, block_q, block_k), lambda b, i, j, *_: (b, i, j)))
        else:
            in_specs.append(pl.BlockSpec(
                (1, 1, block_k), lambda b, i, j, *_: (b, 0, j)))
        args.append(bias)
    in_specs += [
        pl.BlockSpec((1, block_q, d), lambda b, i, j, *_: (b, i, 0)),   # g
        pl.BlockSpec((1, block_q, _LANES), lambda b, i, j, *_: (b, i, 0)),
        pl.BlockSpec((1, block_q, _LANES), lambda b, i, j, *_: (b, i, 0)),
    ]
    args += [gf, lse_r, delta_r]

    out_specs = [pl.BlockSpec((1, block_q, d), lambda b, i, j, *_: (b, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((bh, t, d), q.dtype)]
    if per_q_bias:
        out_specs.append(pl.BlockSpec(
            (1, block_q, block_k), lambda b, i, j, *_: (b, i, j)))
        out_shape.append(jax.ShapeDtypeStruct((bh, t, t), jnp.float32))

    body = functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                             block_q=block_q, block_k=block_k,
                             dropout_rate=dropout_rate)

    def dq_kernel(seed_ref, *refs):
        n_in = 6 + (1 if has_bias else 0)
        ins, outs = refs[:n_in], refs[n_in:]
        if has_bias:
            q_r, k_r, v_r, b_r, g_r, l_r, d_r = ins
        else:
            (q_r, k_r, v_r, g_r, l_r, d_r), b_r = ins, None
        if per_q_bias:
            dq_r, db_r, acc = outs
        else:
            (dq_r, acc), db_r = outs, None
        body(seed_ref, q_r, k_r, v_r, b_r, g_r, l_r, d_r, dq_r, db_r, acc)

    dq_out = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, nq, nk),
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        ),
        out_shape=out_shape,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(seed, *args)
    if per_q_bias:
        dq, dbias = dq_out
    else:
        (dq,), dbias = dq_out, None

    # ---- dk/dv kernel: grid (bh, nk, nq) -----------------------------------
    in_specs2 = [
        pl.BlockSpec((1, block_q, d), lambda b, j, i, *_: (b, i, 0)),   # q
        pl.BlockSpec((1, block_k, d), lambda b, j, i, *_: (b, j, 0)),   # k
        pl.BlockSpec((1, block_k, d), lambda b, j, i, *_: (b, j, 0)),   # v
    ]
    args2 = [q, k, v]
    if has_bias:
        if per_q_bias:
            in_specs2.append(pl.BlockSpec(
                (1, block_q, block_k), lambda b, j, i, *_: (b, i, j)))
        else:
            in_specs2.append(pl.BlockSpec(
                (1, 1, block_k), lambda b, j, i, *_: (b, 0, j)))
        args2.append(bias)
    in_specs2 += [
        pl.BlockSpec((1, block_q, d), lambda b, j, i, *_: (b, i, 0)),   # g
        pl.BlockSpec((1, block_q, _LANES), lambda b, j, i, *_: (b, i, 0)),
        pl.BlockSpec((1, block_q, _LANES), lambda b, j, i, *_: (b, i, 0)),
    ]
    args2 += [gf, lse_r, delta_r]

    col_bias = has_bias and not per_q_bias
    out_specs2 = [
        pl.BlockSpec((1, block_k, d), lambda b, j, i, *_: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i, *_: (b, j, 0)),
    ]
    out_shape2 = [
        jax.ShapeDtypeStruct((bh, t, d), k.dtype),
        jax.ShapeDtypeStruct((bh, t, d), v.dtype),
    ]
    scratch2 = [pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32)]
    if col_bias:
        out_specs2.append(pl.BlockSpec(
            (1, 1, block_k), lambda b, j, i, *_: (b, 0, j)))
        out_shape2.append(jax.ShapeDtypeStruct((bh, 1, t), jnp.float32))
        scratch2.append(pltpu.VMEM((1, block_k), jnp.float32))

    body2 = functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale,
                              causal=causal, block_q=block_q,
                              block_k=block_k, dropout_rate=dropout_rate)

    def dkv_kernel(seed_ref, *refs):
        n_in = 6 + (1 if has_bias else 0)
        ins, rest = refs[:n_in], refs[n_in:]
        if has_bias:
            q_r, k_r, v_r, b_r, g_r, l_r, d_r = ins
        else:
            (q_r, k_r, v_r, g_r, l_r, d_r), b_r = ins, None
        if col_bias:
            dk_r, dv_r, dbc_r, dka, dva, dba = rest
        else:
            (dk_r, dv_r, dka, dva), dbc_r, dba = rest, None, None
        body2(seed_ref, q_r, k_r, v_r, b_r, g_r, l_r, d_r,
              dk_r, dv_r, dbc_r, dka, dva, dba)

    dkv_out = pl.pallas_call(
        dkv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, nk, nq),
            in_specs=in_specs2,
            out_specs=out_specs2,
            scratch_shapes=scratch2,
        ),
        out_shape=out_shape2,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(seed, *args2)
    if col_bias:
        dk, dv, dbias = dkv_out
    else:
        dk, dv = dkv_out

    return dq, dk, dv, dbias


# ---------------------------------------------------------------------------
# Blockwise JAX path (CPU tests / fallback) — same math, two passes
# ---------------------------------------------------------------------------

def _bias_block(bias, j0, bk):
    if bias is None:
        return 0.0
    return lax.dynamic_slice_in_dim(bias, j0, bk, axis=-1).astype(jnp.float32)


def _scores(q, k_blk, bias, j0, causal, sm_scale, bk):
    # q: [BH, Tq, D], k_blk: [BH, bk, D] → s: [BH, Tq, bk]
    # native-dtype operands (bf16 under AMP), f32 accumulation
    s = jnp.einsum("bqd,bkd->bqk", q, k_blk,
                   preferred_element_type=jnp.float32) * sm_scale
    s = s + _bias_block(bias, j0, bk)
    if causal:
        tq = q.shape[1]
        q_pos = lax.broadcasted_iota(jnp.int32, (tq, bk), 0)
        k_pos = j0 + lax.broadcasted_iota(jnp.int32, (tq, bk), 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    return s


def _flash_fwd_jax(q, k, v, bias, sm_scale, causal, block_k,
                   dropout_rate=0.0, dropout_key=None):
    """Two-pass online softmax: pass 1 → (m, lse); pass 2 → output.
    Handles attention-prob dropout (regenerated per block from a folded key,
    so the backward recompute sees identical masks)."""
    bh, t, d = q.shape
    nk = t // block_k

    def pass1(carry, j):
        m, l = carry
        s = _scores(q, lax.dynamic_slice_in_dim(k, j * block_k, block_k, 1),
                    bias, j * block_k, causal, sm_scale, block_k)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        l = l * jnp.exp(m - m_new) + jnp.sum(jnp.exp(s - m_new), -1, keepdims=True)
        return (m_new, l), None

    m0 = jnp.full((bh, t, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bh, t, 1), jnp.float32)
    (m, l), _ = lax.scan(pass1, (m0, l0), jnp.arange(nk))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    lse = (m + jnp.log(l_safe))[..., 0]

    def pass2(acc, j):
        s = _scores(q, lax.dynamic_slice_in_dim(k, j * block_k, block_k, 1),
                    bias, j * block_k, causal, sm_scale, block_k)
        p = jnp.exp(s - lse[..., None])
        p = _apply_dropout(p, dropout_rate, dropout_key, j)
        v_blk = lax.dynamic_slice_in_dim(v, j * block_k, block_k, 1)
        acc = acc + jnp.einsum("bqk,bkd->bqd", p.astype(v_blk.dtype), v_blk,
                               preferred_element_type=jnp.float32)
        return acc, None

    out, _ = lax.scan(pass2, jnp.zeros((bh, t, d), jnp.float32), jnp.arange(nk))
    return out.astype(q.dtype), lse


def _apply_dropout(p, rate, key, block_idx):
    if rate == 0.0 or key is None:
        return p
    keep = jax.random.bernoulli(jax.random.fold_in(key, block_idx),
                                1.0 - rate, p.shape)
    return jnp.where(keep, p / (1.0 - rate), 0.0)


def _flash_bwd_jax(res, g, *, sm_scale, causal, block_k,
                   dropout_rate, has_bias):
    """Flash backward: scan KV blocks, recompute p from (q,k,lse); per block
    dv_j = pᵀ·dO, ds = p∘(dO·vᵀ − D), dk_j = dsᵀ·q, dq += ds·k."""
    q, k, v, bias, dropout_key, out, lse = res
    bh, t, d = q.shape
    nk = t // block_k
    cdt = q.dtype  # MXU operand dtype (bf16 under AMP); accumulations f32
    gc = g.astype(cdt)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                        # [BH,T,1]

    def step(dq, j):
        j0 = j * block_k
        k_blk = lax.dynamic_slice_in_dim(k, j0, block_k, 1)
        v_blk = lax.dynamic_slice_in_dim(v, j0, block_k, 1)
        s = _scores(q, k_blk, bias, j0, causal, sm_scale, block_k)
        p = jnp.exp(s - lse[..., None])                            # [BH,T,bk]
        p_d = _apply_dropout(p, dropout_rate, dropout_key, j)
        dv_j = jnp.einsum("bqk,bqd->bkd", p_d.astype(cdt), gc,
                          preferred_element_type=jnp.float32)
        dp = jnp.einsum("bqd,bkd->bqk", gc, v_blk.astype(cdt),
                        preferred_element_type=jnp.float32)
        if dropout_rate > 0.0 and dropout_key is not None:
            keep = jax.random.bernoulli(
                jax.random.fold_in(dropout_key, j), 1.0 - dropout_rate, p.shape)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        ds = p * (dp - delta)                                      # [BH,T,bk]
        dk_j = jnp.einsum("bqk,bqd->bkd", ds.astype(cdt), q.astype(cdt),
                          preferred_element_type=jnp.float32) * sm_scale
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds.astype(cdt), k_blk.astype(cdt),
                             preferred_element_type=jnp.float32) * sm_scale
        dbias_j = ds if has_bias else None
        return dq, (dk_j, dv_j, dbias_j)

    dq0 = jnp.zeros((bh, t, d), jnp.float32)
    dq, (dk_blocks, dv_blocks, dbias_blocks) = lax.scan(step, dq0, jnp.arange(nk))
    # [nk, BH, bk, d] → [BH, T, d]
    dk = jnp.moveaxis(dk_blocks, 0, 1).reshape(bh, t, d)
    dv = jnp.moveaxis(dv_blocks, 0, 1).reshape(bh, t, d)
    dbias = None
    if has_bias:
        # [nk, BH, Tq, bk] → [BH, Tq, nk, bk] → [BH, Tq, Tk]: the scanned
        # block axis must precede the within-block key axis before reshape
        dbias = jnp.moveaxis(dbias_blocks, 0, 2).reshape(bh, t, t)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), dbias


# ---------------------------------------------------------------------------
# Packed-layout [B, T, H] public entry.
#
# A head-native Pallas path was measured and rejected: Mosaic requires
# 128-divisible (or full) minor block dims, so a per-head 64-wide column
# cannot be a block; head-batched tiles with in-kernel 64-lane slicing ran
# 3× slower than the folded kernels (VPU relayouts), and batched dots with
# batch dims in the middle don't lower at all ("batch dims pos must be 0").
# The packed API therefore adapts to the folded layout — XLA inserts the
# head-split transposes (~5% of a BERT-base step), which is the measured
# optimum on v5e for d=64 heads.
# ---------------------------------------------------------------------------

def _pack_to_folded(x, nh):
    b_, t, hdim = x.shape
    d = hdim // nh
    return x.reshape(b_, t, nh, d).transpose(0, 2, 1, 3).reshape(b_ * nh, t, d)


def _folded_to_pack(x, b_):
    bh, t, d = x.shape
    nh = bh // b_
    return x.reshape(b_, nh, t, d).transpose(0, 2, 1, 3).reshape(b_, t, nh * d)


def flash_attention_packed(q, k, v, num_heads: int, bias=None,
                           causal: bool = False,
                           sm_scale: Optional[float] = None,
                           dropout_rate: float = 0.0, dropout_key=None):
    """Memory-efficient attention on packed [B, T, H] tensors (H = nh·d).

    Adapts to the folded [B·nh, T, d] kernel layout; XLA inserts the
    head-split transposes (see the layout note above — measured optimum for
    d=64 heads on v5e). bias (optional) is the additive [B, 1, T] mask.
    Returns [B, T, H]."""
    b_, t, hdim = q.shape
    if hdim % num_heads:
        raise ValueError(f"hidden {hdim} not divisible by heads {num_heads}")
    d = hdim // num_heads
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(
            f"flash_attention: dropout_rate must be in [0, 1), got "
            f"{dropout_rate}")
    if dropout_rate > 0.0 and dropout_key is None:
        raise ValueError(
            "flash_attention: dropout_rate > 0 requires a dropout_key; "
            "pass one or set dropout_rate=0 for inference")
    if bias is not None:
        if bias.ndim != 3 or bias.shape[1] != 1:
            raise ValueError(
                f"packed flash_attention bias must be [B, 1, T], got "
                f"{bias.shape}")
        bias = jnp.broadcast_to(bias[:, None], (b_, num_heads, 1, t)).reshape(
            b_ * num_heads, 1, t)
    if dropout_rate == 0.0:
        dropout_key = None
    qf, kf, vf = (_pack_to_folded(x, num_heads) for x in (q, k, v))
    out = _flash_core(qf, kf, vf, bias, dropout_key, float(sm_scale),
                      bool(causal), float(dropout_rate))
    return _folded_to_pack(out, b_)


# ---------------------------------------------------------------------------
# Public entry
# ---------------------------------------------------------------------------

def _pick_blocks(t: int):
    bq = next((b for b in (DEFAULT_BLOCK_Q, 256, 128, 64, 32, 16, 8)
               if t % b == 0), None)
    return bq, bq


def _pallas_ok(t: int, d: int) -> bool:
    """Static dispatch decision — must be identical in fwd and bwd so the
    in-kernel dropout masks regenerate consistently."""
    bq, _ = _pick_blocks(t)
    return (_HAVE_PALLAS and (_on_tpu() or FORCE_PALLAS_INTERPRET)
            and bq is not None and bq >= 64 and d % 64 == 0)


def _interpret_arg(dropout_rate: float):
    if not FORCE_PALLAS_INTERPRET or _on_tpu():
        return False
    # dropout kernels call pltpu.prng_*, which only the TPU-semantics
    # interpreter accepts (it returns zero bits — numerics are TPU-only)
    if dropout_rate > 0.0:
        ip = getattr(pltpu, "InterpretParams", None)
        return ip() if ip is not None else True
    return True


def _flash_bwd_block_dispatch(q, k, v, g, lse, out, sm_scale, causal):
    """Block-level backward for the RING path (parallel/ring_attention.py):
    given one resident K/V block and the GLOBAL lse/out/delta residuals,
    return (dq, dk, dv) for that block via the Pallas dq/dkv kernels
    (jax fallback off-TPU). No bias/dropout on the ring path."""
    t, d = q.shape[1], q.shape[2]
    bq, bk = _pick_blocks(t)
    if _pallas_ok(t, d):
        dq, dk, dv, _ = _flash_bwd_pallas(
            q, k, v, None, g, lse, out, sm_scale, causal, bq, bk,
            interpret=_interpret_arg(0.0))
        return dq, dk, dv
    dq, dk, dv, _ = _flash_bwd_jax(
        (q, k, v, None, None, out, lse), g, sm_scale=sm_scale,
        causal=causal, block_k=bk or t, dropout_rate=0.0, has_bias=False)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash_core(q, k, v, bias, dropout_key, sm_scale, causal, dropout_rate):
    out, _ = _flash_fwd_dispatch(q, k, v, bias, dropout_key, sm_scale,
                                 causal, dropout_rate)
    return out


def _flash_fwd_dispatch(q, k, v, bias, dropout_key, sm_scale, causal,
                        dropout_rate):
    t, d = q.shape[1], q.shape[2]
    bq, bk = _pick_blocks(t)
    if _pallas_ok(t, d):
        seed = (_seed_from_key(dropout_key) if dropout_rate > 0.0 else None)
        return _flash_fwd_pallas(q, k, v, bias, sm_scale, causal, bq, bk,
                                 dropout_rate=dropout_rate, seed=seed,
                                 interpret=_interpret_arg(dropout_rate))
    if bq is None:
        raise ValueError(f"flash_attention: seq len {t} has no power-of-two "
                         f"block divisor ≥8; pad the sequence")
    key = dropout_key if dropout_rate > 0.0 else None
    return _flash_fwd_jax(q, k, v, bias, sm_scale, causal, bk,
                          dropout_rate, key)


def _flash_core_fwd(q, k, v, bias, dropout_key, sm_scale, causal, dropout_rate):
    out, lse = _flash_fwd_dispatch(q, k, v, bias, dropout_key, sm_scale,
                                   causal, dropout_rate)
    key = dropout_key if dropout_rate > 0.0 else None
    return out, (q, k, v, bias, key, out, lse)


def _flash_core_bwd(sm_scale, causal, dropout_rate, res, g):
    q, k, v, bias, key, out, lse = res
    t, d = q.shape[1], q.shape[2]
    bq, bk = _pick_blocks(t)
    has_bias = bias is not None
    if _pallas_ok(t, d):
        seed = (_seed_from_key(key) if dropout_rate > 0.0 else None)
        dq, dk, dv, dbias = _flash_bwd_pallas(
            q, k, v, bias, g, lse, out, sm_scale, causal, bq, bk,
            dropout_rate=dropout_rate, seed=seed,
            interpret=_interpret_arg(dropout_rate))
    else:
        dq, dk, dv, dbias = _flash_bwd_jax(
            res, g, sm_scale=sm_scale, causal=causal, block_k=bk,
            dropout_rate=dropout_rate, has_bias=has_bias)
    if has_bias:
        # reduce over broadcast dims back to the bias shape (the pallas
        # col-sum path has already reduced the q axis)
        for ax in range(dbias.ndim):
            if bias.shape[ax] == 1 and dbias.shape[ax] != 1:
                dbias = jnp.sum(dbias, axis=ax, keepdims=True)
        dbias = dbias.astype(bias.dtype)
    dkey = (None if key is None
            else np.zeros(np.shape(key), jax.dtypes.float0))
    return dq, dk, dv, dbias, dkey


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


# ---------------------------------------------------------------------------
# Block-sparse packed-segment attention.
#
# Bucketed-length batches (reader.pack_by_tokens) carried a dense additive
# [B, 1, Tq, Tk] mask through the dense kernels — every fully-padded K block
# still paid its MXU matmul and its HBM DMA. Here visibility travels as a
# COMPACT PER-ROW DESCRIPTOR instead: segment ids are 1-based, contiguous and
# ascending within a packed row (0 = pad tail), so each query token sees
# exactly one contiguous [start, end) range of K positions — two uint16s,
# packed into one int32 as (start << 16) | end. The descriptor is 2·T bytes
# per row instead of Tq·Tk·4 of bias.
#
# From the descriptor the wrapper derives a per-(q-block, k-block) visibility
# table [B, nq, nk] which rides the scalar-prefetch channel; kernels wrap
# their body in `pl.when(vis > 0)`, so fully-masked K blocks are SKIPPED in
# the fwd grid and in both bwd grids — work scales with real tokens, not
# padding. Skipping is numerically invisible by construction: masked
# probabilities are zeroed exactly (`p = where(mask, p, 0)`), so a processed
# fully-masked block contributes exactly 0 to acc/l and leaves the running
# max untouched — bit-identical to never visiting it (the vis table may even
# be all-ones and nothing changes; tests pin this contract). Fully-masked
# rows produce out = 0, lse = −1e30 and zero gradients. Dropout streams are
# keyed by the logical (b, q-block, k-block) index exactly like the dense
# kernels, so masks are identical regardless of skipping and across fwd/bwd.
#
# In-kernel the element mask needs no K-side array at all: k positions are
# an iota, q rows read their packed range from the descriptor (fed
# lane-replicated [B, Tq, 128] — the same trick the lse/delta residuals use —
# and indexed by `b // nh`, so it is stored once per batch row, not per
# head).
# ---------------------------------------------------------------------------

def _pack_se(q_seg, k_seg):
    """[B, Tq], [B, Tk] segment-id rows (1-based contiguous ascending,
    0 = pad) → packed per-q-row K ranges [B, Tq] int32, (start << 16) | end.
    Pad rows get the empty range [0, 0)."""
    if k_seg.shape[1] >= (1 << 15):
        raise ValueError(
            f"block-sparse flash_attention: Tk={k_seg.shape[1]} overflows "
            f"the 16-bit packed range descriptor")
    q_seg = q_seg.astype(jnp.int32)
    k_seg = k_seg.astype(jnp.int32)
    # pad keys (0) must sort AFTER every real segment id
    kk = jnp.where(k_seg > 0, k_seg, jnp.int32(1 << 30))
    start = jax.vmap(
        lambda a, v: jnp.searchsorted(a, v, side="left"))(kk, q_seg)
    end = jax.vmap(
        lambda a, v: jnp.searchsorted(a, v, side="right"))(kk, q_seg)
    start = jnp.where(q_seg > 0, start, 0).astype(jnp.int32)
    end = jnp.where(q_seg > 0, end, 0).astype(jnp.int32)
    return (start << 16) | end


def _compute_block_vis(se, tq, tk, block_q, block_k, causal):
    """Per-(q-block, k-block) visibility [B, nq, nk] int32 from the packed
    descriptor — conservative: a false-positive visible block is numerically
    invisible (the kernels re-apply the element mask and zero masked
    probabilities), so correctness never depends on this table. Tests
    monkeypatch it to all-ones to pin the skip-is-bitwise-free contract."""
    b = se.shape[0]
    nq, nk = tq // block_q, tk // block_k
    start = se >> 16
    end = se & 0xFFFF
    has = start < end
    sblk = jnp.where(has, start, tk).reshape(b, nq, block_q).min(axis=-1)
    eblk = jnp.where(has, end, 0).reshape(b, nq, block_q).max(axis=-1)
    k0 = jnp.arange(nk, dtype=jnp.int32) * block_k                 # [nk]
    vis = ((sblk[:, :, None] < k0[None, None, :] + block_k)
           & (eblk[:, :, None] > k0[None, None, :]))
    if causal:
        # same block-level test as the dense kernels' causal skip
        q_end = jnp.arange(nq, dtype=jnp.int32) * block_q + block_q - 1
        vis &= k0[None, None, :] <= q_end[None, :, None]
    return vis.astype(jnp.int32)


def _sparse_elem_mask(se_ref, iq, ik, block_q, block_k, causal):
    """[bq, bk] bool element mask from the lane-replicated descriptor
    block (k positions are a global iota — no K-side array)."""
    se = se_ref[0]                                        # [bq, 128] int32
    start = lax.shift_right_logical(se, 16)[:, :1]        # [bq, 1]
    end = (se & 0xFFFF)[:, :1]
    k_pos = ik * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = (k_pos >= start) & (k_pos < end)
    if causal:
        q_pos = iq * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        mask &= q_pos >= k_pos
    return mask


def _fwd_kernel_sparse(seed_ref, vis_ref, q_ref, k_ref, v_ref, se_ref, o_ref,
                       lse_ref, acc_ref, m_ref, l_ref, *, sm_scale, causal,
                       block_q, block_k, dropout_rate, nh):
    b, iq, ik = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nq, nk = pl.num_programs(1), pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _body():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        mask = _sparse_elem_mask(se_ref, iq, ik, block_q, block_k, causal)
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # zeroing (not just −1e30) keeps masked columns exact even while m
        # is still at its −1e30 init (exp(0) = 1 would otherwise leak) —
        # this is what makes block skipping bit-identical to processing
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate > 0.0:
            keep = _keep_mask(seed_ref, _block_index(b, iq, ik, nq, nk),
                              (block_q, block_k), dropout_rate)
            p_v = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout_rate))
        else:
            p_v = p
        v_blk = v_ref[0]
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p_v.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(vis_ref[((b // nh) * nq + iq) * nk + ik] > 0)
    def _():
        _body()

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = (m_ref[...] + jnp.log(l_safe)).astype(jnp.float32)


def _flash_fwd_pallas_sparse(q, k, v, se_rep, vis, nh, sm_scale, causal,
                             block_q, block_k, interpret=False,
                             dropout_rate=0.0, seed=None):
    """q, k, v: [B·nh, Tq/Tk, D] folded; se_rep: [B, Tq, 128]
    lane-replicated packed descriptor; vis: flat [B·nq·nk] int32.
    Returns (out, lse [B·nh, Tq])."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    nq, nk = tq // block_q, tk // block_k
    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)

    kernel = functools.partial(_fwd_kernel_sparse, sm_scale=sm_scale,
                               causal=causal, block_q=block_q,
                               block_k=block_k, dropout_rate=dropout_rate,
                               nh=nh)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, nq, nk),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j, *_: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j, *_: (b, j, 0)),
                pl.BlockSpec((1, block_q, _LANES),
                             lambda b, i, j, *_: (b // nh, i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, block_q, _LANES),
                             lambda b, i, j, *_: (b, i, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, _LANES), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tq, _LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(seed, vis, q, k, v, se_rep)
    return out, lse[:, :, 0]


def _bwd_dq_kernel_sparse(seed_ref, vis_ref, q_ref, k_ref, v_ref, se_ref,
                          g_ref, lse_ref, delta_ref, dq_ref, dq_acc, *,
                          sm_scale, causal, block_q, block_k, dropout_rate,
                          nh):
    b, iq, ik = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nq, nk = pl.num_programs(1), pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        g = g_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        mask = _sparse_elem_mask(se_ref, iq, ik, block_q, block_k, causal)
        lse = lse_ref[0][:, :1]
        # fully-masked rows have lse = −1e30 → exp(s − lse) would be
        # exp(0) = 1; the zeroing is load-bearing, same as forward
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _keep_mask(seed_ref, _block_index(b, iq, ik, nq, nk),
                              (block_q, block_k), dropout_rate)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        delta = delta_ref[0][:, :1]
        ds = p * (dp - delta)
        ds_c = ds.astype(k.dtype)
        dq_acc[...] += jax.lax.dot_general(
            ds_c, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

    @pl.when(vis_ref[((b // nh) * nq + iq) * nk + ik] > 0)
    def _():
        _body()

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel_sparse(seed_ref, vis_ref, q_ref, k_ref, v_ref, se_ref,
                           g_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_acc,
                           dv_acc, *, sm_scale, causal, block_q, block_k,
                           dropout_rate, nh):
    # grid is (bh, nk, nq): k-block outer, q-block inner
    b, ik, iq = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nk, nq = pl.num_programs(1), pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        g = g_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        mask = _sparse_elem_mask(se_ref, iq, ik, block_q, block_k, causal)
        lse = lse_ref[0][:, :1]
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            # same (b, iq, ik) index as the fwd/dq kernels → identical mask
            keep = _keep_mask(seed_ref, _block_index(b, iq, ik, nq, nk),
                              (block_q, block_k), dropout_rate)
            inv = 1.0 / (1.0 - dropout_rate)
            p_v = jnp.where(keep, p * inv, 0.0)
            dp = jnp.where(keep, dp * inv, 0.0)
        else:
            p_v = p
        dv_acc[...] += jax.lax.dot_general(
            p_v.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        delta = delta_ref[0][:, :1]
        ds = p * (dp - delta)
        ds_c = ds.astype(q.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds_c, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

    @pl.when(vis_ref[((b // nh) * nq + iq) * nk + ik] > 0)
    def _():
        _body()

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_pallas_sparse(q, k, v, se_rep, vis, nh, g, lse, out, sm_scale,
                             causal, block_q, block_k, dropout_rate=0.0,
                             seed=None, interpret=False):
    """Returns (dq, dk, dv); same skip table as forward steers both grids."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    nq, nk = tq // block_q, tk // block_k
    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    gf, lse_r, delta_r = _bwd_host_prep(q, g, lse, out)

    dq_kernel = functools.partial(
        _bwd_dq_kernel_sparse, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, dropout_rate=dropout_rate, nh=nh)
    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, nq, nk),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j, *_: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j, *_: (b, j, 0)),
                pl.BlockSpec((1, block_q, _LANES),
                             lambda b, i, j, *_: (b // nh, i, 0)),
                pl.BlockSpec((1, block_q, d), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, block_q, _LANES),
                             lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, block_q, _LANES),
                             lambda b, i, j, *_: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, d),
                                   lambda b, i, j, *_: (b, i, 0)),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(seed, vis, q, k, v, se_rep, gf, lse_r, delta_r)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel_sparse, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, dropout_rate=dropout_rate, nh=nh)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, nk, nq),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, j, i, *_: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, j, i, *_: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, j, i, *_: (b, j, 0)),
                pl.BlockSpec((1, block_q, _LANES),
                             lambda b, j, i, *_: (b // nh, i, 0)),
                pl.BlockSpec((1, block_q, d), lambda b, j, i, *_: (b, i, 0)),
                pl.BlockSpec((1, block_q, _LANES),
                             lambda b, j, i, *_: (b, i, 0)),
                pl.BlockSpec((1, block_q, _LANES),
                             lambda b, j, i, *_: (b, i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda b, j, i, *_: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, j, i, *_: (b, j, 0)),
            ],
            scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32)],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(seed, vis, q, k, v, se_rep, gf, lse_r, delta_r)
    return dq, dk, dv


# ---- blockwise JAX path (CPU tests / fallback), same masked math ----------

def _sparse_mask_block(start, end, j0, bk, tq, causal):
    """[BH, Tq, bk] bool mask for the K block at offset j0. start/end:
    [BH, Tq] (per-head-repeated descriptor halves)."""
    k_pos = j0 + lax.broadcasted_iota(jnp.int32, (tq, bk), 1)
    mask = ((k_pos[None] >= start[:, :, None])
            & (k_pos[None] < end[:, :, None]))
    if causal:
        q_pos = lax.broadcasted_iota(jnp.int32, (tq, bk), 0)
        mask &= q_pos[None] >= k_pos[None]
    return mask


def _flash_fwd_jax_sparse(q, k, v, start, end, sm_scale, causal, block_k,
                          dropout_rate=0.0, dropout_key=None):
    bh, tq, d = q.shape
    tk = k.shape[1]
    nk = tk // block_k

    def scores(j):
        k_blk = lax.dynamic_slice_in_dim(k, j * block_k, block_k, 1)
        s = jnp.einsum("bqd,bkd->bqk", q, k_blk,
                       preferred_element_type=jnp.float32) * sm_scale
        mask = _sparse_mask_block(start, end, j * block_k, block_k, tq,
                                  causal)
        return jnp.where(mask, s, _NEG_INF), mask

    def pass1(carry, j):
        m, l = carry
        s, mask = scores(j)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        l = l * jnp.exp(m - m_new) + jnp.sum(p, -1, keepdims=True)
        return (m_new, l), None

    m0 = jnp.full((bh, tq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bh, tq, 1), jnp.float32)
    (m, l), _ = lax.scan(pass1, (m0, l0), jnp.arange(nk))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    lse = (m + jnp.log(l_safe))[..., 0]

    def pass2(acc, j):
        s, mask = scores(j)
        p = jnp.where(mask, jnp.exp(s - lse[..., None]), 0.0)
        p = _apply_dropout(p, dropout_rate, dropout_key, j)
        v_blk = lax.dynamic_slice_in_dim(v, j * block_k, block_k, 1)
        acc = acc + jnp.einsum("bqk,bkd->bqd", p.astype(v_blk.dtype), v_blk,
                               preferred_element_type=jnp.float32)
        return acc, None

    out, _ = lax.scan(pass2, jnp.zeros((bh, tq, d), jnp.float32),
                      jnp.arange(nk))
    return out.astype(q.dtype), lse


def _flash_bwd_jax_sparse(res, g, *, sm_scale, causal, block_k,
                          dropout_rate):
    q, k, v, start, end, dropout_key, out, lse = res
    bh, tq, d = q.shape
    tk = k.shape[1]
    nk = tk // block_k
    cdt = q.dtype
    gc = g.astype(cdt)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)

    def step(dq, j):
        j0 = j * block_k
        k_blk = lax.dynamic_slice_in_dim(k, j0, block_k, 1)
        v_blk = lax.dynamic_slice_in_dim(v, j0, block_k, 1)
        s = jnp.einsum("bqd,bkd->bqk", q, k_blk,
                       preferred_element_type=jnp.float32) * sm_scale
        mask = _sparse_mask_block(start, end, j0, block_k, tq, causal)
        p = jnp.where(mask, jnp.exp(s - lse[..., None]), 0.0)
        p_d = _apply_dropout(p, dropout_rate, dropout_key, j)
        dv_j = jnp.einsum("bqk,bqd->bkd", p_d.astype(cdt), gc,
                          preferred_element_type=jnp.float32)
        dp = jnp.einsum("bqd,bkd->bqk", gc, v_blk.astype(cdt),
                        preferred_element_type=jnp.float32)
        if dropout_rate > 0.0 and dropout_key is not None:
            keep = jax.random.bernoulli(
                jax.random.fold_in(dropout_key, j), 1.0 - dropout_rate,
                p.shape)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        ds = p * (dp - delta)
        dk_j = jnp.einsum("bqk,bqd->bkd", ds.astype(cdt), q.astype(cdt),
                          preferred_element_type=jnp.float32) * sm_scale
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds.astype(cdt),
                             k_blk.astype(cdt),
                             preferred_element_type=jnp.float32) * sm_scale
        return dq, (dk_j, dv_j)

    dq0 = jnp.zeros((bh, tq, d), jnp.float32)
    dq, (dk_blocks, dv_blocks) = lax.scan(step, dq0, jnp.arange(nk))
    dk = jnp.moveaxis(dk_blocks, 0, 1).reshape(bh, tk, d)
    dv = jnp.moveaxis(dv_blocks, 0, 1).reshape(bh, tk, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---- dispatch + custom_vjp ------------------------------------------------

def _sparse_pallas_ok(tq: int, tk: int, d: int,
                      dropout_rate: float = 0.0) -> bool:
    bq, _ = _pick_blocks(tq)
    bk, _ = _pick_blocks(tk)
    if dropout_rate > 0.0 and not _on_tpu() and not hasattr(
            pltpu, "InterpretParams"):
        # the dropout kernels call pltpu.prng_*, which off-TPU needs the
        # TPU-semantics interpreter; older jax doesn't expose it — use the
        # jax fallback there (fwd and bwd agree: both see dropout_rate)
        return False
    return (_HAVE_PALLAS and (_on_tpu() or FORCE_PALLAS_INTERPRET)
            and bq is not None and bk is not None
            and bq >= 64 and bk >= 64 and d % 64 == 0)


def _se_halves_folded(se, nh):
    """Descriptor halves as per-head-repeated [B·nh, Tq] arrays for the jax
    fallback (folded layout is batch-major: index = b·nh + h)."""
    start = jnp.repeat(se >> 16, nh, axis=0)
    end = jnp.repeat(se & 0xFFFF, nh, axis=0)
    return start, end


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_sparse_core(q, k, v, se, dropout_key, nh, sm_scale, causal,
                       dropout_rate):
    out, _ = _flash_sparse_fwd_dispatch(q, k, v, se, dropout_key, nh,
                                        sm_scale, causal, dropout_rate)
    return out


def _flash_sparse_fwd_dispatch(q, k, v, se, dropout_key, nh, sm_scale,
                               causal, dropout_rate):
    tq, d = q.shape[1], q.shape[2]
    tk = k.shape[1]
    bq, _ = _pick_blocks(tq)
    bk, _ = _pick_blocks(tk)
    if _sparse_pallas_ok(tq, tk, d, dropout_rate):
        vis = _compute_block_vis(se, tq, tk, bq, bk, causal).reshape(-1)
        se_rep = jnp.broadcast_to(se[:, :, None],
                                  (se.shape[0], tq, _LANES))
        seed = (_seed_from_key(dropout_key) if dropout_rate > 0.0 else None)
        return _flash_fwd_pallas_sparse(
            q, k, v, se_rep, vis, nh, sm_scale, causal, bq, bk,
            interpret=_interpret_arg(dropout_rate),
            dropout_rate=dropout_rate, seed=seed)
    if bk is None:
        raise ValueError(
            f"flash_attention_sparse: seq len {tk} has no power-of-two "
            f"block divisor ≥8; pad the sequence")
    start, end = _se_halves_folded(se, nh)
    key = dropout_key if dropout_rate > 0.0 else None
    return _flash_fwd_jax_sparse(q, k, v, start, end, sm_scale, causal, bk,
                                 dropout_rate, key)


def _flash_sparse_core_fwd(q, k, v, se, dropout_key, nh, sm_scale, causal,
                           dropout_rate):
    out, lse = _flash_sparse_fwd_dispatch(q, k, v, se, dropout_key, nh,
                                          sm_scale, causal, dropout_rate)
    key = dropout_key if dropout_rate > 0.0 else None
    return out, (q, k, v, se, key, out, lse)


def _flash_sparse_core_bwd(nh, sm_scale, causal, dropout_rate, res, g):
    q, k, v, se, key, out, lse = res
    tq, d = q.shape[1], q.shape[2]
    tk = k.shape[1]
    bq, _ = _pick_blocks(tq)
    bk, _ = _pick_blocks(tk)
    if _sparse_pallas_ok(tq, tk, d, dropout_rate):
        vis = _compute_block_vis(se, tq, tk, bq, bk, causal).reshape(-1)
        se_rep = jnp.broadcast_to(se[:, :, None],
                                  (se.shape[0], tq, _LANES))
        seed = (_seed_from_key(key) if dropout_rate > 0.0 else None)
        dq, dk, dv = _flash_bwd_pallas_sparse(
            q, k, v, se_rep, vis, nh, g, lse, out, sm_scale, causal, bq, bk,
            dropout_rate=dropout_rate, seed=seed,
            interpret=_interpret_arg(dropout_rate))
    else:
        start, end = _se_halves_folded(se, nh)
        dq, dk, dv = _flash_bwd_jax_sparse(
            (q, k, v, start, end, key, out, lse), g, sm_scale=sm_scale,
            causal=causal, block_k=bk, dropout_rate=dropout_rate)
    dse = np.zeros(np.shape(se), jax.dtypes.float0)
    dkey = (None if key is None
            else np.zeros(np.shape(key), jax.dtypes.float0))
    return dq, dk, dv, dse, dkey


_flash_sparse_core.defvjp(_flash_sparse_core_fwd, _flash_sparse_core_bwd)


def flash_attention_packed_sparse(q, k, v, num_heads: int, q_seg, k_seg,
                                  causal: bool = False,
                                  sm_scale: Optional[float] = None,
                                  dropout_rate: float = 0.0,
                                  dropout_key=None):
    """Block-sparse packed-segment attention on [B, T, H] tensors.

    q_seg/k_seg are the packed segment-id rows (reader.pack_by_tokens
    layout: 1-based contiguous ascending ids, 0 = pad tail) — the dense
    additive [B, 1, Tq, Tk] mask never exists. Supports self attention
    (q_seg is k_seg, optionally causal) and cross attention (Tq ≠ Tk).
    Fully-masked rows (pad queries) return exactly 0. Returns [B, T, H]."""
    b_, tq, hdim = q.shape
    tk = k.shape[1]
    if hdim % num_heads:
        raise ValueError(f"hidden {hdim} not divisible by heads {num_heads}")
    d = hdim // num_heads
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(
            f"flash_attention_sparse: dropout_rate must be in [0, 1), got "
            f"{dropout_rate}")
    if dropout_rate > 0.0 and dropout_key is None:
        raise ValueError(
            "flash_attention_sparse: dropout_rate > 0 requires a "
            "dropout_key; pass one or set dropout_rate=0 for inference")
    if causal and tq != tk:
        raise ValueError("flash_attention_sparse: causal requires Tq == Tk")
    if q_seg.shape != (b_, tq) or k_seg.shape != (b_, tk):
        raise ValueError(
            f"flash_attention_sparse: seg shapes {q_seg.shape}/"
            f"{k_seg.shape} do not match q/k [{b_}, {tq}]/[{b_}, {tk}]")
    if dropout_rate == 0.0:
        dropout_key = None
    se = _pack_se(q_seg, k_seg)
    qf, kf, vf = (_pack_to_folded(x, num_heads) for x in (q, k, v))
    out = _flash_sparse_core(qf, kf, vf, se, dropout_key, num_heads,
                             float(sm_scale), bool(causal),
                             float(dropout_rate))
    return _folded_to_pack(out, b_)


def flash_attention(q, k, v, bias=None, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    dropout_rate: float = 0.0, dropout_key=None):
    """Memory-efficient multi-head attention.

    q, k, v: [B, H, T, D]. bias: additive, broadcastable to [B, H, T, T]
    (e.g. the BERT mask [B,1,1,T]). Returns [B, H, T, D].
    """
    b, h, t, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(
            f"flash_attention: dropout_rate must be in [0, 1), got "
            f"{dropout_rate}")
    if dropout_rate > 0.0 and dropout_key is None:
        raise ValueError(
            "flash_attention: dropout_rate > 0 requires a dropout_key; "
            "pass one or set dropout_rate=0 for inference")

    fold = lambda x: x.reshape(b * h, *x.shape[2:])
    qf, kf, vf = fold(q), fold(k), fold(v)
    bias_f = None
    if bias is not None:
        bias_full = jnp.broadcast_to(bias, (b, h, bias.shape[2], t))
        bias_f = bias_full.reshape(b * h, bias.shape[2], t)
    if dropout_rate == 0.0:
        dropout_key = None  # cotangent structure must match the real usage
    out = _flash_core(qf, kf, vf, bias_f, dropout_key, float(sm_scale),
                      bool(causal), float(dropout_rate))
    return out.reshape(b, h, t, d)
