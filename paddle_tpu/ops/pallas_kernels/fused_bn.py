"""Pallas fused training-mode batch norm for TPU.

Reference analog: batch_norm_op.cu:35 (cuDNN BatchNormalizationForwardTraining)
plus fused_bn_add_activation semantics — one statistics pass + one apply pass,
with relu (and the bottleneck residual add) foldable into the apply.

Why this kernel exists (round-3 xplane profiling on v5e): the ResNet-50 train
step is HBM-bound and XLA's per-channel BN reduction fusions sustain only
~140 GB/s (1.5 ms for a 205 MB activation) vs ~450 GB/s for its elementwise
fusions. The kernel streams the activation once for the statistics and once
for the apply.

Layout is the whole game here. XLA keeps conv activations physically
channel-minor on TPU (e.g. bf16[128,256,56,56]{1,0,3,2} — NHWC bytes under an
NCHW logical shape). A kernel that demands row-major NCHW forces a material
transpose around every call (measured: 116 ms vs 54 ms full step — 2× WORSE).
So these kernels operate on the (M, C) = (N·H·W, C) view with channel riding
the lane axis: the logical NCHW→NHWC transpose then lines up with the bytes
XLA already has, per-channel statistics become sublane-axis sums at streaming
bandwidth, and every broadcast is a natural row broadcast.

When C < 128 the (M, C) view would waste the lane axis (C=64 pads to 128 —
half the bandwidth on exactly the stage-1 tensors that dominate traffic), so
the view is folded to (M/k, k·C) with k = 128//C and the k per-channel
partials are combined outside the kernel.

Backward (custom_vjp): a reduction pass producing dbeta=Σg, dgamma=Σg·x̂
(g = dy masked through the fused relu), then a dx pass
`dx = inv·scale·(g − dbeta/m − x̂·dgamma/m)`, emitting dresidual=g for free
when the residual add was fused.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

try:  # pallas import is deferred-safe: CPU-only envs still import this module
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    pl = pltpu = None
    _HAVE_PALLAS = False


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# Tests may set this to run the kernels on CPU through the interpreter.
FORCE_PALLAS_INTERPRET = False

# Per-operand VMEM budget per grid step (bytes); the widest backward pass
# streams five (BM, C) operands (dy, x, y in; dx, dres out) plus double
# buffering inside ~16 MB.
_MAX_BLOCK_BYTES = 1024 * 1024


def supports(x_shape, dtype) -> bool:
    """Static gate for the pallas path: 4-D, lane-friendly C, and enough
    rows that kernel launch overhead amortizes."""
    if not _HAVE_PALLAS:
        return False
    if len(x_shape) != 4:
        return False
    n, c, h, w = x_shape
    if c < 8 or c > 8192 or (c < 128 and 128 % c != 0) or \
            (c >= 128 and c % 128 != 0):
        return False
    m = n * h * w
    k = 128 // c if c < 128 else 1
    mk = m // k
    return m % max(k, 1) == 0 and mk >= 1024 and mk % 8 == 0


def _fold(c):
    """Lane-fold factor k: view (M, C) as (M/k, kC) so the lane axis is
    full when C < 128."""
    return 128 // c if c < 128 else 1


def _pick_bm(mk, ck, itemsize):
    """Sublane block: largest power-of-two divisor of M/k within the
    per-operand byte budget (dtype-aware — f32 blocks are half the rows of
    bf16 ones)."""
    cap = max(8, _MAX_BLOCK_BYTES // (ck * itemsize))
    bm = 8
    while bm * 2 <= cap and mk % (bm * 2) == 0:
        bm *= 2
    return bm


def _nhwc_2d(x):
    """(N, C, H, W) → (M/k, k·C) channel-minor view (bitcast against XLA's
    preferred conv layout, not a material transpose)."""
    n, c, h, w = x.shape
    k = _fold(c)
    return jnp.transpose(x, (0, 2, 3, 1)).reshape(n * h * w // k, k * c)


def _un_nhwc(y2, shape):
    n, c, h, w = shape
    return jnp.transpose(y2.reshape(n, h, w, c), (0, 3, 1, 2))


# ---------------------------------------------------------------------------
# forward: statistics (one streaming pass)
# ---------------------------------------------------------------------------

def _stats_kernel(x_ref, sum_ref, ssq_ref):
    mb = pl.program_id(0)

    @pl.when(mb == 0)
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        ssq_ref[...] = jnp.zeros_like(ssq_ref)

    xf = x_ref[...].astype(jnp.float32)                    # [BM, kC]
    sum_ref[...] += jnp.sum(xf, axis=0, keepdims=True)
    ssq_ref[...] += jnp.sum(xf * xf, axis=0, keepdims=True)


def bn_stats(x, *, interpret=False):
    """Per-channel (mean, var) of NCHW x in one HBM pass. f32 outputs [C].

    One-pass E[x²]−E[x]² with f32 accumulators and a clamp at 0 — the same
    trade cuDNN's training path makes; exactness on adversarially large-mean
    inputs is traded for a single streaming read."""
    n, c, h, w = x.shape
    k = _fold(c)
    x2 = _nhwc_2d(x)
    mk, ck = x2.shape
    bm = _pick_bm(mk, ck, x.dtype.itemsize)
    s, ss = pl.pallas_call(
        _stats_kernel,
        grid=(mk // bm,),
        in_specs=[pl.BlockSpec((bm, ck), lambda mb: (mb, 0))],
        out_specs=[pl.BlockSpec((1, ck), lambda mb: (0, 0)),
                   pl.BlockSpec((1, ck), lambda mb: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((1, ck), jnp.float32),
                   jax.ShapeDtypeStruct((1, ck), jnp.float32)],
        interpret=interpret,
    )(x2)
    m = float(n * h * w)
    s = s.reshape(k, c).sum(axis=0)
    ss = ss.reshape(k, c).sum(axis=0)
    mean = s / m
    var = jnp.maximum(ss / m - mean * mean, 0.0)
    return mean, var


# ---------------------------------------------------------------------------
# forward: apply (+relu, +residual)
# ---------------------------------------------------------------------------

def _apply_kernel(x_ref, mean_ref, isc_ref, bias_ref, *rest, act, has_res):
    if has_res:
        res_ref, y_ref = rest
    else:
        (y_ref,) = rest
    xf = x_ref[...].astype(jnp.float32)
    y = (xf - mean_ref[...]) * isc_ref[...] + bias_ref[...]
    if has_res:
        y = y + res_ref[...].astype(jnp.float32)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    y_ref[...] = y.astype(y_ref.dtype)


def bn_apply(x, mean, inv, scale, bias, *, act="", residual=None,
             interpret=False):
    n, c, h, w = x.shape
    k = _fold(c)
    x2 = _nhwc_2d(x)
    mk, ck = x2.shape
    bm = _pick_bm(mk, ck, x.dtype.itemsize)
    isc = (inv * scale.astype(jnp.float32))
    meanv = jnp.tile(mean.astype(jnp.float32), k).reshape(1, ck)
    iscv = jnp.tile(isc, k).reshape(1, ck)
    biasv = jnp.tile(bias.astype(jnp.float32), k).reshape(1, ck)
    vec = pl.BlockSpec((1, ck), lambda mb: (0, 0))
    big = pl.BlockSpec((bm, ck), lambda mb: (mb, 0))
    args = [x2, meanv, iscv, biasv]
    in_specs = [big, vec, vec, vec]
    if residual is not None:
        args.append(_nhwc_2d(residual))
        in_specs.append(big)
    y2 = pl.pallas_call(
        functools.partial(_apply_kernel, act=act,
                          has_res=residual is not None),
        grid=(mk // bm,),
        in_specs=in_specs,
        out_specs=big,
        out_shape=jax.ShapeDtypeStruct((mk, ck), x.dtype),
        interpret=interpret,
    )(*args)
    return _un_nhwc(y2, x.shape)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_reduce_kernel(dy_ref, x_ref, *rest, act):
    """dbeta = Σ g, dgamma = Σ g·x̂ in one streaming pass.
    g = dy·(y>0) when relu was fused (y passed in), else dy."""
    if act == "relu":
        y_ref, mean_ref, inv_ref, dbeta_ref, dgamma_ref = rest
    else:
        mean_ref, inv_ref, dbeta_ref, dgamma_ref = rest
    mb = pl.program_id(0)

    @pl.when(mb == 0)
    def _init():
        dbeta_ref[...] = jnp.zeros_like(dbeta_ref)
        dgamma_ref[...] = jnp.zeros_like(dgamma_ref)

    g = dy_ref[...].astype(jnp.float32)
    if act == "relu":
        g = jnp.where(y_ref[...].astype(jnp.float32) > 0, g, 0.0)
    xhat = (x_ref[...].astype(jnp.float32) - mean_ref[...]) * inv_ref[...]
    dbeta_ref[...] += jnp.sum(g, axis=0, keepdims=True)
    dgamma_ref[...] += jnp.sum(g * xhat, axis=0, keepdims=True)


def _bwd_dx_kernel(dy_ref, x_ref, *rest, act, has_res, m):
    if act == "relu":
        y_ref = rest[0]
        rest = rest[1:]
    mean_ref, inv_ref, isc_ref, dbeta_ref, dgamma_ref = rest[:5]
    outs = rest[5:]
    g = dy_ref[...].astype(jnp.float32)
    if act == "relu":
        g = jnp.where(y_ref[...].astype(jnp.float32) > 0, g, 0.0)
    xhat = (x_ref[...].astype(jnp.float32) - mean_ref[...]) * inv_ref[...]
    dx = isc_ref[...] * (
        g - dbeta_ref[...] * (1.0 / m) - xhat * (dgamma_ref[...] * (1.0 / m)))
    outs[0][...] = dx.astype(outs[0].dtype)
    if has_res:
        outs[1][...] = g.astype(outs[1].dtype)


# ---------------------------------------------------------------------------
# public fused op with custom_vjp
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_bn_act(x, scale, bias, eps, act, residual_tag, residual=None):
    """Training-mode fused BN: y = act(x̂·scale + bias [+ residual]).

    Returns (y, mean, var) with mean/var the f32 batch statistics (for the
    running-stat update). `residual_tag` statically records whether a
    residual is fused (custom_vjp needs it nondiff)."""
    y, mean, var, _ = _fwd(x, scale, bias, eps, act, residual)
    return y, mean, var


def _fwd(x, scale, bias, eps, act, residual):
    interpret = FORCE_PALLAS_INTERPRET
    mean, var = bn_stats(x, interpret=interpret)
    inv = lax.rsqrt(var + eps)
    y = bn_apply(x, mean, inv, scale, bias, act=act, residual=residual,
                 interpret=interpret)
    return y, mean, var, inv


def _fused_fwd(x, scale, bias, eps, act, residual_tag, residual=None):
    y, mean, var, inv = _fwd(x, scale, bias, eps, act, residual)
    saved_y = y if act == "relu" else None
    return (y, mean, var), (x, scale, mean, inv, saved_y)


def _bn_bwd_2d(dy2, x2, y2, mean, inv, scale, act, has_res, m, k, interpret):
    """2-D core of the fused-BN backward on the channel-minor (M/k, k·C)
    view: one reduction pass (dbeta, dgamma), one dx pass (+dresidual when
    the residual add was fused). mean/inv/scale are per-channel f32 [C];
    returns (dx2, dgamma, dbeta, dres2_or_None). Shared by the plain
    fused-BN vjp and the conv+BN vjp (where x2 is the conv output)."""
    mk, ck = x2.shape
    c = ck // k
    bm = _pick_bm(mk, ck, x2.dtype.itemsize)
    vec = pl.BlockSpec((1, ck), lambda mb: (0, 0))
    big = pl.BlockSpec((bm, ck), lambda mb: (mb, 0))

    meanv = jnp.tile(mean, k).reshape(1, ck)
    invv = jnp.tile(inv, k).reshape(1, ck)

    args = [dy2, x2]
    in_specs = [big, big]
    if act == "relu":
        args.append(y2)
        in_specs.append(big)
    args += [meanv, invv]
    in_specs += [vec, vec]

    dbeta2, dgamma2 = pl.pallas_call(
        functools.partial(_bwd_reduce_kernel, act=act),
        grid=(mk // bm,),
        in_specs=in_specs,
        out_specs=[vec, vec],
        out_shape=[jax.ShapeDtypeStruct((1, ck), jnp.float32),
                   jax.ShapeDtypeStruct((1, ck), jnp.float32)],
        interpret=interpret,
    )(*args)
    dbeta = dbeta2.reshape(k, c).sum(axis=0)
    dgamma = dgamma2.reshape(k, c).sum(axis=0)

    isc = inv * scale.astype(jnp.float32)
    args2 = args + [jnp.tile(isc, k).reshape(1, ck),
                    jnp.tile(dbeta, k).reshape(1, ck),
                    jnp.tile(dgamma, k).reshape(1, ck)]
    in_specs2 = in_specs + [vec, vec, vec]
    out_specs = [big]
    out_shape = [jax.ShapeDtypeStruct((mk, ck), x2.dtype)]
    if has_res:
        out_specs.append(big)
        out_shape.append(jax.ShapeDtypeStruct((mk, ck), x2.dtype))
    outs = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, act=act, has_res=has_res, m=m),
        grid=(mk // bm,),
        in_specs=in_specs2,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*args2)
    return outs[0], dgamma, dbeta, (outs[1] if has_res else None)


def _fused_bwd(eps, act, residual_tag, saved, cots):
    x, scale, mean, inv, saved_y = saved
    dy, _dmean, _dvar = cots  # mean/var feed stop-gradient running stats
    interpret = FORCE_PALLAS_INTERPRET
    n, c, h, w = x.shape
    k = _fold(c)
    m = float(n * h * w)
    y2 = _nhwc_2d(saved_y) if act == "relu" else None
    dx2, dgamma, dbeta, dres2 = _bn_bwd_2d(
        _nhwc_2d(dy), _nhwc_2d(x), y2, mean, inv, scale, act,
        residual_tag, m, k, interpret)
    dx = _un_nhwc(dx2, x.shape)
    dscale = dgamma.astype(scale.dtype)
    dbias = dbeta.astype(scale.dtype)
    dres = _un_nhwc(dres2, x.shape) if residual_tag else None
    return dx, dscale, dbias, dres


fused_bn_act.defvjp(_fused_fwd, _fused_bwd)


# ---------------------------------------------------------------------------
# fused 1×1-conv + BN (+relu, +residual): the bottleneck epilogue kernels
# ---------------------------------------------------------------------------
# A 1×1 conv with stride s is subsample-then-matmul, so on the channel-minor
# (M, C) view the whole bottleneck tail `conv1x1 → BN → (+residual) → relu`
# is one MXU matmul whose statistics ride along in the same streaming pass.
# HBM sees x once and the conv output twice (stats-producing write + apply
# read) instead of the unfused 4–5 passes, and — unlike a standalone Pallas
# BN, which measured 2× WORSE from layout round-trips — the matmul itself
# lives in the kernel, so no transpose traffic is ever materialized.
#
# Layout/padding rules that make this fast (and which `conv_bn_supports`
# enforces): channels ride the lane axis as the full minor dimension of the
# block (always Mosaic-legal; C < 128 merely wastes lanes — only the first
# bottleneck's C=64 input hits this), rows are 8×-tiled on the sublane axis,
# and the weight matrix stays resident in VMEM across the whole grid.

# The (Ci, Co) weight panel must fit VMEM alongside the streaming blocks;
# resnet50's largest is 2048×512 (4 MB f32).
_MAX_W_BYTES = 8 * 1024 * 1024


def conv_bn_supports(x_shape, w_shape, stride) -> bool:
    """Static gate for the fused conv+BN pallas path: 1×1 kernel, stride
    1/2, lane-friendly channel counts, enough output rows to tile."""
    if not _HAVE_PALLAS:
        return False
    if len(x_shape) != 4 or len(w_shape) != 4:
        return False
    n, ci, h, w = x_shape
    co, wci, kh, kw = w_shape
    if (kh, kw) != (1, 1) or wci != ci or stride not in (1, 2):
        return False
    if ci < 8 or co < 8 or ci > 8192 or co > 8192 or ci % 8 or co % 8:
        return False
    if ci * co * 4 > _MAX_W_BYTES:
        return False
    m = n * -(-h // stride) * -(-w // stride)
    return m >= 1024 and m % 8 == 0


def _to2d(x):
    """(N, C, H, W) → (M, C) channel-minor view, no lane fold (the conv
    kernels need C intact as the contraction/output axis)."""
    n, c, h, w = x.shape
    return jnp.transpose(x, (0, 2, 3, 1)).reshape(n * h * w, c)


def _from2d(y2, shape):
    n, c, h, w = shape
    return jnp.transpose(y2.reshape(n, h, w, c), (0, 3, 1, 2))


def _conv_stats_kernel(x_ref, w_ref, y_ref, sum_ref, ssq_ref):
    """One grid step: yf = x·w on the MXU (f32 accumulation), stored in
    activation dtype, with the BN statistics accumulated from the *stored*
    values — matching an unfused conv→BN chain that reads the rounded
    activation back from HBM."""
    mb = pl.program_id(0)

    @pl.when(mb == 0)
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        ssq_ref[...] = jnp.zeros_like(ssq_ref)

    yf = lax.dot_general(x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32)
    yc = yf.astype(y_ref.dtype)
    y_ref[...] = yc
    ys = yc.astype(jnp.float32)
    sum_ref[...] += jnp.sum(ys, axis=0, keepdims=True)
    ssq_ref[...] += jnp.sum(ys * ys, axis=0, keepdims=True)


def _conv_stats(x2, w2, out_dtype, interpret):
    """(M, Ci) @ (Ci, Co) with per-channel (mean, var) of the result in the
    same pass. Returns (y2, mean, var)."""
    mk, ci = x2.shape
    co = w2.shape[1]
    bm = _pick_bm(mk, max(ci, co), max(x2.dtype.itemsize, 2))
    y2, s, ss = pl.pallas_call(
        _conv_stats_kernel,
        grid=(mk // bm,),
        in_specs=[pl.BlockSpec((bm, ci), lambda mb: (mb, 0)),
                  pl.BlockSpec((ci, co), lambda mb: (0, 0))],
        out_specs=[pl.BlockSpec((bm, co), lambda mb: (mb, 0)),
                   pl.BlockSpec((1, co), lambda mb: (0, 0)),
                   pl.BlockSpec((1, co), lambda mb: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((mk, co), out_dtype),
                   jax.ShapeDtypeStruct((1, co), jnp.float32),
                   jax.ShapeDtypeStruct((1, co), jnp.float32)],
        interpret=interpret,
    )(x2, w2)
    mean = s[0] / mk
    var = jnp.maximum(ss[0] / mk - mean * mean, 0.0)
    return y2, mean, var


def _apply2d(x2, mean, inv, scale, bias, act, res2, interpret):
    """BN apply (+act, +residual) on an (M, C) view — reuses the fused-BN
    apply kernel with no lane fold."""
    mk, c = x2.shape
    bm = _pick_bm(mk, c, x2.dtype.itemsize)
    vec = pl.BlockSpec((1, c), lambda mb: (0, 0))
    big = pl.BlockSpec((bm, c), lambda mb: (mb, 0))
    isc = inv * scale.astype(jnp.float32)
    args = [x2, mean.reshape(1, c), isc.reshape(1, c),
            bias.astype(jnp.float32).reshape(1, c)]
    in_specs = [big, vec, vec, vec]
    if res2 is not None:
        args.append(res2)
        in_specs.append(big)
    return pl.pallas_call(
        functools.partial(_apply_kernel, act=act, has_res=res2 is not None),
        grid=(mk // bm,),
        in_specs=in_specs,
        out_specs=big,
        out_shape=jax.ShapeDtypeStruct((mk, c), x2.dtype),
        interpret=interpret,
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def fused_conv_bn_act(x, w, scale, bias, eps, act, stride, residual_tag,
                      residual=None):
    """Fused 1×1-conv + training BN: y = act(BN(conv(x, w)) [+ residual]).

    x is NCHW, w is OIHW with a 1×1 kernel; returns (y, mean, var) with
    mean/var the f32 batch statistics of the conv output (for the
    running-stat update). `residual_tag` statically records whether a
    residual is fused."""
    y, mean, var, _ = _conv_bn_fwd_impl(x, w, scale, bias, eps, act, stride,
                                        residual)
    return y, mean, var


def _conv_bn_fwd_impl(x, w, scale, bias, eps, act, stride, residual):
    interpret = FORCE_PALLAS_INTERPRET
    co = w.shape[0]
    xs = x[:, :, ::stride, ::stride] if stride > 1 else x
    n, _, hs, ws = xs.shape
    x2 = _to2d(xs)
    w2 = jnp.transpose(w.reshape(co, w.shape[1]))
    yc2, mean, var = _conv_stats(x2, w2, x.dtype, interpret)
    inv = lax.rsqrt(var + eps)
    res2 = _to2d(residual) if residual is not None else None
    y2 = _apply2d(yc2, mean, inv, scale, bias, act, res2, interpret)
    y = _from2d(y2, (n, co, hs, ws))
    return y, mean, var, (x2, yc2, y2, inv)


def _conv_bn_fwd(x, w, scale, bias, eps, act, stride, residual_tag,
                 residual=None):
    y, mean, var, (x2, yc2, y2, inv) = _conv_bn_fwd_impl(
        x, w, scale, bias, eps, act, stride, residual)
    saved_y2 = y2 if act == "relu" else None
    return (y, mean, var), (x, w, scale, mean, inv, yc2, saved_y2)


def _conv_bn_bwd(eps, act, stride, residual_tag, saved, cots):
    x, w, scale, mean, inv, yc2, saved_y2 = saved
    dy, _dmean, _dvar = cots
    interpret = FORCE_PALLAS_INTERPRET
    co = w.shape[0]
    dy2 = _to2d(dy)
    m = float(yc2.shape[0])
    # BN half: grads w.r.t. the conv output (and the free residual grad)
    dyc2, dgamma, dbeta, dres2 = _bn_bwd_2d(
        dy2, yc2, saved_y2, mean, inv, scale, act, residual_tag, m, 1,
        interpret)
    # matmul half: XLA's dots are already MXU-shaped — the fusion win is
    # the BN/elementwise traffic, not the gemm, so these stay plain
    xs = x[:, :, ::stride, ::stride] if stride > 1 else x
    x2 = _to2d(xs)
    w2 = jnp.transpose(w.reshape(co, w.shape[1]))
    dx2 = lax.dot_general(dyc2, w2, (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32).astype(x.dtype)
    dw2 = lax.dot_general(x2, dyc2, (((0,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    dx_sub = _from2d(dx2, xs.shape)
    if stride > 1:
        dx = jnp.zeros(x.shape, x.dtype).at[:, :, ::stride, ::stride].set(
            dx_sub)
    else:
        dx = dx_sub
    dw = jnp.transpose(dw2).reshape(w.shape).astype(w.dtype)
    dres = _from2d(dres2, dy.shape) if residual_tag else None
    return (dx, dw, dgamma.astype(scale.dtype), dbeta.astype(scale.dtype),
            dres)


fused_conv_bn_act.defvjp(_conv_bn_fwd, _conv_bn_bwd)


def conv_bn_xla(x, w, scale, bias, eps, act, stride, residual=None,
                use_mean=None, use_var=None):
    """XLA fallback/reference composition with the exact math of the
    separate conv2d + batch_norm("xla1") (+ elementwise_add + relu)
    lowerings — bitwise-equal end to end, which is what makes the fused op
    safe to enable per-model. `use_mean`/`use_var` switch to frozen
    (inference) statistics. Returns (y, mean, var)."""
    y = lax.conv_general_dilated(
        x, w, (stride, stride), [(0, 0), (0, 0)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    xf = y.astype(jnp.float32)
    if use_mean is None:
        mean = jnp.mean(xf, axis=(0, 2, 3))
        var = jnp.maximum(jnp.mean(xf * xf, axis=(0, 2, 3)) - mean * mean,
                          0.0)
    else:
        mean = use_mean.astype(jnp.float32)
        var = use_var.astype(jnp.float32)
    shp = (1, -1, 1, 1)
    inv = lax.rsqrt(var.reshape(shp) + eps)
    out = ((xf - mean.reshape(shp)) * inv * scale.reshape(shp)
           + bias.reshape(shp)).astype(x.dtype)
    if residual is not None:
        out = out + residual
    if act == "relu":
        out = jax.nn.relu(out)
    return out, mean, var
