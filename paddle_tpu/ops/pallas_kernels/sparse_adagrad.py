"""Pallas fused gather-Adagrad-scatter over packed row-major tables.

Reference analog: the SelectedRows Adagrad kernels
(adagrad_op.cu's SparseAdagradFunctor) — one fused pass per touched row
instead of a gather, an elementwise update, and a scatter as three
separate device ops.

The unfused path (`ops/deferred_rows.adagrad_row_packed`) costs three
trips over the touched rows per step: the forward lookup's gather feeds
`FwdRows`, the update math runs on an unpacked copy, and
`_packed_write`'s `at[uids].set` lowers to an XLA scatter that rewrites
the packed table (measured at r04: ~7.4 ms for 106k rows — the deepfm
step's single largest op). This kernel collapses the optimizer half:
for each unique touched row it DMAs the packed `[128] uint16` row into
VMEM, unpacks param+accumulator in-register, applies exact Adagrad
(`g2 += u²; p -= lr·u/(√g2+eps)` — the same update expression as the
unfused math; agreement is exact up to XLA's FMA-contraction freedom,
i.e. ≤1 ULP in the accumulator when the two compilations group the
multiply-add differently), repacks, and writes the row straight back
through an input/output alias of the table, so the table never
round-trips through a scatter.

Grid and aliasing contract (the subtle parts):

- `uids` comes from `uniq_merge`: unique row ids sorted ascending with
  SENTINEL (2³¹−1) padding at the tail, and `utot` the per-row summed
  gradient. One grid step per slot; ids are scalar-prefetched so the
  BlockSpec index_map can steer each step's row DMA.
- The table is aliased in→out (`input_output_aliases`), so every output
  block that Pallas flushes must hold the right bytes. Valid slots write
  the updated row. Sentinel slots must NOT address a fresh row: a write
  to some clamped row racing with an earlier slot's in-flight flush of
  the same row could resurrect stale bytes. Instead the index_map pins
  every tail slot to the LAST valid row (ids are sorted, so the tail is
  one consecutive run): Pallas sees an unchanged block index, skips the
  refetch, keeps the already-updated row in VMEM, and flushes it exactly
  once at the end. Tail slots simply don't touch the output ref.
- `nu` (count of valid ids) is scalar-prefetched for that pinning; the
  degenerate all-sentinel call (nu == 0) pins slot 0 to row 0 and copies
  the fetched row through unchanged.

One row per grid step keeps the kernel latency-bound on tiny 256 B DMAs;
Pallas double-buffers the next row's fetch under the current row's
update, which hides most of it. Batching k scattered rows per step needs
manual `make_async_copy` orchestration — left for a later pass.

CPU tier-1 runs the same kernel under the Pallas interpreter
(`FORCE_PALLAS_INTERPRET = True` in tests); without it, non-TPU backends
fall back to the unfused path via `enabled()`.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

try:  # pallas import is deferred-safe: CPU-only envs still import this module
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    pl = pltpu = None
    _HAVE_PALLAS = False

__all__ = ["fused_adagrad_update", "fused_row_gather", "fused_row_scatter",
           "enabled", "supports", "rows_enabled",
           "FORCE_PALLAS_INTERPRET"]

# Must match ops/deferred_rows.py (not imported: that module imports us).
_PACK_LANES = 128
_SENTINEL = jnp.iinfo(jnp.int32).max


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# Tests may set this to run the kernel on CPU through the interpreter.
FORCE_PALLAS_INTERPRET = False


def supports(vis: int, lanes: int = _PACK_LANES) -> bool:
    """Static shape gate: param+accumulator (2·vis f32 = 4·vis u16 lanes)
    must fit one packed row."""
    return 0 < 4 * int(vis) <= int(lanes)


def enabled(vis: int, lanes: int = _PACK_LANES) -> bool:
    """Full runtime gate for the fused path: pallas importable, shapes
    packable, a backend that can run it (TPU, or interpreter when forced),
    and no `PDTPU_FUSED_SPARSE=0` kill switch."""
    if not _HAVE_PALLAS or not supports(vis, lanes):
        return False
    if os.environ.get("PDTPU_FUSED_SPARSE", "1") == "0":
        return False
    return _on_tpu() or FORCE_PALLAS_INTERPRET


def rows_enabled(lanes: int = _PACK_LANES) -> bool:
    """Gate for the row-maintenance kernels (hot-cache write-back gather /
    admission scatter) — same switches as `enabled` minus the vis-fits
    check: these move whole packed rows, no unpacking."""
    if not _HAVE_PALLAS or lanes <= 0:
        return False
    if os.environ.get("PDTPU_FUSED_SPARSE", "1") == "0":
        return False
    return _on_tpu() or FORCE_PALLAS_INTERPRET


def _unpack(raw, n):
    """(1, 2n) uint16 lanes → (1, n) f32 — bit-identical to
    deferred_rows.unpack_rows on one row."""
    return lax.bitcast_convert_type(
        raw.reshape(1, n, 2), jnp.float32)


def _pack(rows):
    """(1, n) f32 → (1, 2n) uint16 lanes — bit-identical to
    deferred_rows.pack_rows on one row."""
    n = rows.shape[-1]
    return lax.bitcast_convert_type(rows, jnp.uint16).reshape(1, 2 * n)


def _kernel(ids_ref, nu_ref, lr_ref, table_ref, utot_ref, out_ref, *,
            vis, eps):
    i = pl.program_id(0)
    nu = nu_ref[0]
    lanes = out_ref.shape[-1]
    dt = 2 * vis  # packed row payload: [param(vis) | accum(vis)] f32

    @pl.when(i < nu)
    def _update():
        raw = table_ref[...]                      # (1, lanes) u16
        cur = _unpack(raw[:, :2 * dt], dt)        # (1, dt) f32
        u = utot_ref[...]                         # (1, vis) f32
        g_new = cur[:, vis:dt] + u * u
        p_new = cur[:, :vis] - lr_ref[0] * u / (jnp.sqrt(g_new) + eps)
        packed = _pack(jnp.concatenate([p_new, g_new], axis=-1))
        if lanes > 2 * dt:
            # pack_rows zero-fills the spare lanes; match it exactly so a
            # fused row is bitwise-equal to an unfused rewrite of the row
            packed = jnp.concatenate(
                [packed, jnp.zeros((1, lanes - 2 * dt), jnp.uint16)],
                axis=-1)
        out_ref[...] = packed

    # nu == 0: every slot is pinned to row 0; write its bytes through
    # once so the aliased flush is a no-op rewrite, not garbage.
    @pl.when((nu == 0) & (i == 0))
    def _passthrough():
        out_ref[...] = table_ref[...]


def fused_adagrad_update(table, uids, utot, lr, *, vis, eps,
                         interpret=None):
    """Apply exact Adagrad to `table[uids]` in one fused pass.

    table: (V, lanes) uint16 packed rows, payload [param|accum] (dt=2·vis
      f32 each, as produced by deferred_rows.pack_rows).
    uids: (R,) int — unique ascending row ids, SENTINEL-padded tail.
    utot: (R, vis) f32 — summed gradient per unique row.
    lr: scalar learning rate.

    Returns the updated table; the input buffer is donated via
    input/output aliasing.
    """
    v, lanes = table.shape
    if not supports(vis, lanes):
        raise ValueError(
            f"fused_adagrad_update: 2*vis={2 * vis} f32 payload does not "
            f"fit a {lanes}-lane packed row")
    r = int(uids.shape[0])
    if interpret is None:
        interpret = bool(FORCE_PALLAS_INTERPRET) or not _on_tpu()

    uids = uids.astype(jnp.int32)
    nu = jnp.sum(uids != _SENTINEL).astype(jnp.int32).reshape(1)
    lr_arr = jnp.asarray(lr, jnp.float32).reshape(1)
    utot = utot.astype(jnp.float32)

    def _row_map(i, ids, nu_s, lr_s):
        # valid slots → their own row; tail slots pin to the last valid
        # row (consecutive revisit ⇒ no refetch, single final flush);
        # clamp guards the nu == 0 degenerate call.
        j = jnp.minimum(i, jnp.maximum(nu_s[0], 1) - 1)
        return (jnp.clip(ids[j], 0, v - 1), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(r,),
        in_specs=[
            pl.BlockSpec((1, lanes), _row_map),
            pl.BlockSpec((1, int(vis)), lambda i, ids, nu_s, lr_s: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, lanes), _row_map),
    )
    return pl.pallas_call(
        functools.partial(_kernel, vis=int(vis), eps=float(eps)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        # alias the table (4th positional input after the three
        # scalar-prefetch args) onto the single output
        input_output_aliases={3: 0},
        interpret=bool(interpret),
    )(uids, nu, lr_arr, table, utot)


# ---------------------------------------------------------------------------
# Row-maintenance kernels for the hot-row cache (ps/hot_cache.py): move
# whole packed rows between the resident slab and flat buffers with the
# same one-row-per-grid-step DMA steering as the Adagrad kernel. No
# sentinel machinery: callers pad index vectors to a power-of-two bucket
# by REPEATING THE LAST ELEMENT, so tail steps re-address the same block
# — Pallas sees an unchanged block index (no refetch) and rewrites
# identical bytes, which keeps the aliased scatter deterministic and the
# executable set at O(log slab) shapes.
# ---------------------------------------------------------------------------


def _copy_row_kernel(slots_ref, table_ref, out_ref):
    del slots_ref
    out_ref[...] = table_ref[...]


def fused_row_gather(table, slots, *, interpret=None):
    """``out[i] = table[slots[i]]`` — the write-back gather.

    table: (V, lanes) uint16 packed rows. slots: (R,) int — duplicate
    entries are allowed (reads). Returns (R, lanes) uint16.
    """
    v, lanes = table.shape
    r = int(slots.shape[0])
    if interpret is None:
        interpret = bool(FORCE_PALLAS_INTERPRET) or not _on_tpu()
    slots = slots.astype(jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(r,),
        in_specs=[pl.BlockSpec(
            (1, lanes), lambda i, s: (jnp.clip(s[i], 0, v - 1), 0))],
        out_specs=pl.BlockSpec((1, lanes), lambda i, s: (i, 0)),
    )
    return pl.pallas_call(
        _copy_row_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((r, lanes), table.dtype),
        interpret=bool(interpret),
    )(slots, table)


def _scatter_row_kernel(slots_ref, src_ref, table_ref, rows_ref, out_ref):
    del slots_ref, src_ref, table_ref
    out_ref[...] = rows_ref[...]


def fused_row_scatter(table, slots, rows, src=None, *, interpret=None):
    """``table[slots[i]] = rows[src[i]]`` for every grid step — the
    admission scatter, aliased in->out so untouched rows keep their bytes
    without a copy.

    The non-padding prefix of `slots` must be distinct (each output block
    is flushed once); the padded tail must repeat the last (slot, src)
    pair — same block index, identical bytes, a no-op rewrite.
    """
    v, lanes = table.shape
    r = int(slots.shape[0])
    if interpret is None:
        interpret = bool(FORCE_PALLAS_INTERPRET) or not _on_tpu()
    slots = slots.astype(jnp.int32)
    src = (jnp.arange(r, dtype=jnp.int32) if src is None
           else src.astype(jnp.int32))

    def _tbl_map(i, slots_s, src_s):
        return (jnp.clip(slots_s[i], 0, v - 1), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(r,),
        in_specs=[
            pl.BlockSpec((1, lanes), _tbl_map),
            pl.BlockSpec((1, lanes), lambda i, slots_s, src_s:
                         (src_s[i], 0)),
        ],
        out_specs=pl.BlockSpec((1, lanes), _tbl_map),
    )
    return pl.pallas_call(
        _scatter_row_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        # table is the 3rd positional input after the two scalar-prefetch
        # args
        input_output_aliases={2: 0},
        interpret=bool(interpret),
    )(slots, src, table, rows)
