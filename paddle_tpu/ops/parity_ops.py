"""Parity-op sweep round 2 — the remaining implementable reference ops.

References: recurrent_op.cc (recurrent), conditional_block_op.cc
(conditional_block_infer), quantize_op.cc / dequantize_op.cc /
requantize_op.cc (mkldnn int8 trio), fake_quantize_op.cc
(fake_quantize_dequantize_moving_average_abs_max), fused/conv_fusion_op.cc
(conv2d_fusion), fused/fusion_seqexpand_concat_fc_op.cc,
fused/fused_embedding_fc_lstm_op.cc, tree_conv_op.cc,
deformable_psroi_pooling_op.cc, detection/roi_perspective_transform_op.cc,
detection/generate_mask_labels_op.cc, distributed_ops/split_ids_op.cc /
merge_ids_op.cc, split_selected_rows_op.cc, collective/c_comm_init_op.cc,
controlflow/feed_op / fetch_op (framework/feed_fetch_method.cc).

Deliberately NOT registered (declared non-goals, SURVEY §7): the gRPC
pserver runtime (listen_and_serv/send/recv/*_barrier/prefetch/
checkpoint_notify/distributed_lookup_table/lookup_sparse_table),
pslib BoxPS (pull/push_box_sparse), and vendor engines
(tensorrt/anakin/ngraph) — capabilities replaced by GSPMD sharding and XLA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import act_map, one, opt_input

_ACTS = act_map()


# -- framework plumbing ------------------------------------------------------

@register_op("feed", differentiable=False)
def _feed(ctx, inputs, attrs):
    """feed_op: identity — the executor feeds directly, this exists so
    programs serialized with explicit feed ops execute."""
    (x,) = inputs["X"]
    return one(x)


@register_op("fetch", differentiable=False)
def _fetch(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(x)


@register_op("read", differentiable=False)
def _read(ctx, inputs, attrs):
    """reader read_op: passthrough of already-materialized batch tensors
    (the double-buffered reader lives in paddle_tpu.reader / native)."""
    return {"Out": list(inputs.get("X", []))}


@register_op("recurrent")
def _recurrent(ctx, inputs, attrs):
    """recurrent_op.cc: block-per-timestep RNN — same lowering as
    static_rnn (lax.scan over the sub-block), kept as its own type for
    program parity."""
    from .control_flow_ops import _static_rnn
    return _static_rnn(ctx, inputs, attrs)


@register_op("conditional_block_infer", differentiable=False)
def _conditional_block_infer(ctx, inputs, attrs):
    from .control_flow_ops import _conditional_block
    return _conditional_block(ctx, inputs, attrs)


@register_op("merge_lod_tensor_infer", nondiff_inputs=["Mask"])
def _merge_lod_tensor_infer(ctx, inputs, attrs):
    from .framework_ops import _merge_lod_tensor
    return _merge_lod_tensor(ctx, inputs, attrs)


# -- int8 quantization trio (mkldnn int8 path capability) -------------------

@register_op("quantize", differentiable=False)
def _quantize(ctx, inputs, attrs):
    """quantize_op.cc: f32 → int8 with a static scale."""
    (x,) = inputs["Input"]
    scale = attrs.get("Scale", 1.0)
    shift = attrs.get("Shift", 0.0)
    signed = attrs.get("is_negative_input", True)
    if signed:
        q = jnp.clip(jnp.round(x * scale + shift), -128, 127).astype(jnp.int8)
    else:  # asymmetric uint8 path (Shift typically 128)
        q = jnp.clip(jnp.round(x * scale + shift), 0, 255).astype(jnp.uint8)
    return {"Output": [q]}


@register_op("dequantize", differentiable=False)
def _dequantize(ctx, inputs, attrs):
    (x,) = inputs["Input"]
    scale = attrs.get("Scale", 1.0)
    shift = attrs.get("Shift", 0.0)
    return {"Output": [(x.astype(jnp.float32) - shift) / scale]}


@register_op("requantize", differentiable=False)
def _requantize(ctx, inputs, attrs):
    """requantize_op.cc: rescale int8 between two quantization domains."""
    (x,) = inputs["Input"]
    s_in = attrs.get("Scale_in", 1.0)
    s_out = attrs.get("Scale_out", 1.0)
    sh_in = attrs.get("Shift_in", 0.0)
    sh_out = attrs.get("Shift_out", 0.0)
    real = (x.astype(jnp.float32) - sh_in) / s_in
    if x.dtype == jnp.uint8 or sh_out:
        q = jnp.clip(jnp.round(real * s_out + sh_out), 0, 255).astype(jnp.uint8)
    else:
        q = jnp.clip(jnp.round(real * s_out), -128, 127).astype(jnp.int8)
    return {"Output": [q]}


from .quant_ops import _ste_grad  # noqa: E402  (STE for QDQ below)


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             grad_fn=_ste_grad,
             nondiff_inputs=["InScale", "InAccum", "InState"])
def _fake_qdq_moving_avg(ctx, inputs, attrs):
    """fake_quantize_op.cc QDQ variant — our moving-average quantizer
    already emits the quantize→dequantize composition (quant_ops
    _quant_dequant), so this is the same lowering under the reference's
    QDQ op name (STE gradient included via its grad_fn)."""
    from .quant_ops import _fake_quantize_ma_abs_max
    return _fake_quantize_ma_abs_max(ctx, inputs, attrs)


# -- fused vision/sequence composites ---------------------------------------

@register_op("conv2d_fusion")
def _conv2d_fusion(ctx, inputs, attrs):
    """fused/conv_fusion_op.cc: conv + bias + activation (+ residual)."""
    from .nn_ops import _conv2d
    y = _conv2d(ctx, {"Input": inputs["Input"], "Filter": inputs["Filter"]},
                attrs)["Out"][0]
    bias = opt_input(inputs, "Bias")
    if bias is not None:
        y = y + bias.reshape(1, -1, 1, 1)
    resid = opt_input(inputs, "ResidualData")
    if resid is not None:
        y = y + resid
    return {"Output": [_ACTS[attrs.get("activation", "relu")](y)]}


@register_op("fusion_seqexpand_concat_fc", nondiff_inputs=["Length"])
def _fusion_seqexpand_concat_fc(ctx, inputs, attrs):
    """fused/fusion_seqexpand_concat_fc_op.cc: X[0] is [B,T,D0]; the rest
    are per-batch vectors [B,Di] broadcast over T; concat features, fc,
    activation."""
    xs = inputs["X"]
    (w,) = inputs["FCWeight"]
    bias = opt_input(inputs, "FCBias")
    seq = xs[0]
    B, T = seq.shape[0], seq.shape[1]
    feats = [seq] + [jnp.broadcast_to(v[:, None, :], (B, T, v.shape[-1]))
                     for v in xs[1:]]
    h = jnp.concatenate(feats, axis=-1)
    out = jnp.einsum("btd,dh->bth", h, w)
    if bias is not None:
        out = out + bias.reshape(1, 1, -1)
    return one(_ACTS[attrs.get("fc_activation", "relu")](out))


@register_op("fused_embedding_fc_lstm", nondiff_inputs=["Ids", "Length"])
def _fused_embedding_fc_lstm(ctx, inputs, attrs):
    """fused/fused_embedding_fc_lstm_op.cc: the embedding table arrives
    pre-multiplied by the input projection (Embeddings = table @ WeightX,
    folded offline by the fuse pass), so the lookup directly yields the
    4H gate pre-activations; only the recurrence runs."""
    from .rnn_ops import _lstm
    (ids,) = inputs["Ids"]
    (emb,) = inputs["Embeddings"]        # [V, 4H]
    if ids.ndim == 3:
        ids = ids[..., 0]
    gates = emb[ids]                     # [B, T, 4H]
    sub = {"Input": [gates], "Weight": inputs["WeightH"]}
    for slot in ("Bias", "Length", "H0", "C0"):
        if inputs.get(slot):
            sub[slot] = inputs[slot]
    return _lstm(ctx, sub, attrs)


@register_op("tree_conv", nondiff_inputs=["EdgeSet"])
def _tree_conv(ctx, inputs, attrs):
    """tree_conv_op.cc (continuous binary tree convolution, simplified to
    one propagation step): each node aggregates itself + its children
    (normalized) through three role weight matrices W[D, 3, C]
    (self / left-half / right-half of the child list by position).
    NodesVector [B, N, D]; EdgeSet [B, E, 2] int32 (parent, child) rows,
    (-1,-1) padded. Out [B, N, C]."""
    (nodes,) = inputs["NodesVector"]
    (edges,) = inputs["EdgeSet"]
    (w,) = inputs["Filter"]              # [D, 3, C]
    B, N, D = nodes.shape

    def per_sample(x, e):
        parent, child = e[:, 0], e[:, 1]
        valid = parent >= 0
        p = jnp.where(valid, parent, N)
        # child order within each parent decides left/right mix
        ones = jnp.where(valid, 1.0, 0.0)
        adj = jnp.zeros((N + 1, N), x.dtype).at[p, jnp.clip(child, 0, N - 1)].add(ones)
        adj = adj[:N]
        deg = jnp.maximum(adj.sum(1, keepdims=True), 1.0)
        halves = jnp.cumsum(adj, axis=1)
        left = jnp.where(halves <= deg / 2, adj, 0.0)
        right = adj - left
        lmean = (left @ x) / jnp.maximum(left.sum(1, keepdims=True), 1.0)
        rmean = (right @ x) / jnp.maximum(right.sum(1, keepdims=True), 1.0)
        out = (x @ w[:, 0] + lmean @ w[:, 1] + rmean @ w[:, 2])
        return jnp.tanh(out)

    return one(jax.vmap(per_sample)(nodes, edges))


def _bilinear_chw(img, yy, xx):
    """img [C,H,W]; sample at float coords yy/xx [...] → [C, ...]."""
    H, W = img.shape[1], img.shape[2]
    y0 = jnp.floor(yy); x0 = jnp.floor(xx)
    wy = yy - y0; wx = xx - x0
    vals = 0.0
    for yi, wyi in ((y0, 1 - wy), (y0 + 1, wy)):
        for xi, wxi in ((x0, 1 - wx), (x0 + 1, wx)):
            inb = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
            yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
            vals = vals + img[:, yc, xc] * (wyi * wxi * inb)[None]
    return vals


@register_op("deformable_psroi_pooling", nondiff_inputs=["ROIs"])
def _deformable_psroi_pooling(ctx, inputs, attrs):
    """deformable_psroi_pooling_op.cc: position-sensitive RoI pooling with
    learned per-bin offsets (bilinear-sampled, offsets differentiable)."""
    (x,) = inputs["Input"]               # [N, C*P*P, H, W]
    (rois,) = inputs["ROIs"]             # [R, 5] (batch_idx, x1,y1,x2,y2)
    trans = opt_input(inputs, "Trans")   # [R, 2, P, P] offsets or None
    P = int(attrs.get("pooled_height", attrs.get("group_size", 7)))
    PW = int(attrs.get("pooled_width", P))
    spatial_scale = attrs.get("spatial_scale", 1.0)
    trans_std = attrs.get("trans_std", 0.1)
    C = x.shape[1] // (P * PW)

    def per_roi(roi, tr):
        b = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale, roi[2] * spatial_scale, \
            roi[3] * spatial_scale, roi[4] * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1) / PW
        rh = jnp.maximum(y2 - y1, 0.1) / P
        img = x[b].reshape(C, P, PW, x.shape[2], x.shape[3])
        py, px = jnp.meshgrid(jnp.arange(P, dtype=jnp.float32),
                              jnp.arange(PW, dtype=jnp.float32), indexing="ij")
        cy = y1 + (py + 0.5) * rh
        cx = x1 + (px + 0.5) * rw
        if tr is not None:
            cy = cy + tr[1] * trans_std * (y2 - y1)
            cx = cx + tr[0] * trans_std * (x2 - x1)
        # per-bin: sample the (i,j)-th group channel map at the bin center
        def bin_val(i, j):
            sub = img[:, i, j]                         # [C, H, W]
            return _bilinear_chw(sub, cy[i, j], cx[i, j])   # [C]
        vals = jnp.stack([jnp.stack([bin_val(i, j) for j in range(PW)], -1)
                          for i in range(P)], -2)      # [C, P, PW]
        return vals

    if trans is None:
        out = jax.vmap(lambda r: per_roi(r, None))(rois)
    else:
        out = jax.vmap(per_roi)(rois, trans)
    return {"Output": [out], "TopCount": [jnp.ones(out.shape, jnp.int32)]}


@register_op("roi_perspective_transform", nondiff_inputs=["ROIs"])
def _roi_perspective_transform(ctx, inputs, attrs):
    """roi_perspective_transform_op.cc: warp a quadrilateral RoI
    (x1..x4,y1..y4) to a fixed [H, W] output via the 4-point homography,
    bilinear-sampled."""
    (x,) = inputs["X"]                   # [N, C, H, W]
    (rois,) = inputs["ROIs"]             # [R, 9]: batch_idx + 8 quad coords
    oh = int(attrs.get("transformed_height", 8))
    ow = int(attrs.get("transformed_width", 8))
    spatial_scale = attrs.get("spatial_scale", 1.0)

    def homography(quad):
        # map unit rect corners (0,0),(w-1,0),(w-1,h-1),(0,h-1) → quad
        src = jnp.asarray([[0, 0], [ow - 1, 0], [ow - 1, oh - 1], [0, oh - 1]],
                          jnp.float32)
        dst = quad.reshape(4, 2) * spatial_scale
        rows = []
        for k in range(4):
            sx, sy = src[k]
            dx, dy = dst[k, 0], dst[k, 1]
            rows.append(jnp.asarray(
                [sx, sy, 1, 0, 0, 0, 0, 0], jnp.float32))
            rows.append(jnp.asarray(
                [0, 0, 0, sx, sy, 1, 0, 0], jnp.float32))
        A = jnp.stack(rows)
        A = A.at[0::2, 6].set(-src[:, 0] * dst[:, 0])
        A = A.at[0::2, 7].set(-src[:, 1] * dst[:, 0])
        A = A.at[1::2, 6].set(-src[:, 0] * dst[:, 1])
        A = A.at[1::2, 7].set(-src[:, 1] * dst[:, 1])
        b = dst.reshape(-1)   # [dx0,dy0,dx1,dy1,...] matching the row pairs
        h = jnp.linalg.solve(A, b)   # exact 8x8; degenerate quads -> NaN, loud
        return jnp.concatenate([h, jnp.ones((1,))]).reshape(3, 3)

    def per_roi(roi):
        b = roi[0].astype(jnp.int32)
        H = homography(roi[1:])
        gy, gx = jnp.meshgrid(jnp.arange(oh, dtype=jnp.float32),
                              jnp.arange(ow, dtype=jnp.float32), indexing="ij")
        pts = jnp.stack([gx.ravel(), gy.ravel(), jnp.ones(oh * ow)], 0)
        warped = H @ pts
        wx = warped[0] / (warped[2] + 1e-8)
        wy = warped[1] / (warped[2] + 1e-8)
        return _bilinear_chw(x[b], wy, wx).reshape(-1, oh, ow)

    out = jax.vmap(per_roi)(rois)
    return {"Out": [out]}


@register_op("generate_mask_labels", differentiable=False)
def _generate_mask_labels(ctx, inputs, attrs):
    """generate_mask_labels_op.cc (Mask R-CNN targets), bitmap redesign:
    gt masks arrive as binary bitmaps [G, Hm, Wm] (the reference takes COCO
    polygons — rasterization happens in the data pipeline here). For each
    RoI, crop its matched gt mask and resize to [R, R]."""
    (rois,) = inputs["Rois"]             # [M, 4]
    (gt_masks,) = inputs["GtSegms"]      # [G, Hm, Wm] float 0/1
    (match,) = inputs["MatchedGts"]      # [M] int32 index into G
    (labels,) = inputs["LabelsInt32"]    # [M]
    R = int(attrs.get("resolution", 14))

    def per_roi(roi, g, lab):
        m = gt_masks[jnp.clip(g, 0, gt_masks.shape[0] - 1)]
        y = jnp.linspace(roi[1], roi[3], R)
        x = jnp.linspace(roi[0], roi[2], R)
        yy, xx = jnp.meshgrid(y, x, indexing="ij")
        vals = _bilinear_chw(m[None], yy, xx)[0]
        tgt = (vals > 0.5).astype(jnp.float32)
        return jnp.where(lab > 0, tgt, -jnp.ones_like(tgt))

    out = jax.vmap(per_roi)(rois, match, labels)
    return {"MaskRois": [rois], "RoiHasMaskInt32": [(labels > 0).astype(jnp.int32)],
            "MaskInt32": [out]}


# -- pserver-era sharding helpers (kept: useful for sharded embeddings) -----

@register_op("split_ids", differentiable=False)
def _split_ids(ctx, inputs, attrs):
    """split_ids_op.cc: route ids to N shards by id % N. Padded redesign:
    each shard output keeps the full length with non-members replaced by -1
    (the reference emits variable-length shards)."""
    (ids,) = inputs["Ids"]
    shard_num = attrs.get("shard_num")
    if isinstance(shard_num, (list, tuple)):
        n = len(shard_num)
    elif shard_num is not None:
        n = int(shard_num)
    else:
        # reference derives N from the op's declared output arity
        n = getattr(ctx, "out_arity", {}).get("Out") or \
            int(attrs.get("num_shards", 2))
    flat = ids.reshape(-1)
    outs = []
    for s in range(n):
        outs.append(jnp.where(flat % n == s, flat, -1))
    return {"Out": outs}


@register_op("merge_ids", differentiable=False)
def _merge_ids(ctx, inputs, attrs):
    """merge_ids_op.cc: inverse of split_ids — merge per-shard embedding
    rows back into original id order. Ids [B] original, per-shard rows
    aligned with split_ids' padded layout."""
    (ids,) = inputs["Ids"]
    rows = inputs["X"]                   # N tensors [B, D] (padded rows)
    n = len(rows)
    flat = ids.reshape(-1)
    out = jnp.zeros((flat.shape[0], rows[0].shape[-1]), rows[0].dtype)
    for s in range(n):
        sel = (flat % n == s)
        out = out + jnp.where(sel[:, None], rows[s], 0)
    return one(out)


@register_op("split_selected_rows", differentiable=False)
def _split_selected_rows(ctx, inputs, attrs):
    """split_selected_rows_op.cc: slice rows into height_sections."""
    (x,) = inputs["X"]
    sections = [int(s) for s in attrs["height_sections"]]
    outs, pos = [], 0
    for s in sections:
        outs.append(x[pos:pos + s])
        pos += s
    return {"Out": outs}


@register_op("split_byref", differentiable=False)
def _split_byref(ctx, inputs, attrs):
    return _split_selected_rows(ctx, inputs, attrs)


# -- collective bootstrap shims ---------------------------------------------

@register_op("c_comm_init", differentiable=False)
def _c_comm_init(ctx, inputs, attrs):
    """c_comm_init_op.cc: NCCL communicator setup. On TPU the mesh is the
    communicator — jax.distributed.initialize + Mesh construction
    (parallel/env.py) replace id exchange; this op is a structural no-op
    so transpiled startup programs run."""
    return {}


@register_op("c_comm_init_all", differentiable=False)
def _c_comm_init_all(ctx, inputs, attrs):
    return {}


@register_op("c_gen_nccl_id", differentiable=False)
def _c_gen_nccl_id(ctx, inputs, attrs):
    """c_gen_nccl_id_op.cc: emits a dummy id handle — XLA owns transport."""
    return {"Out": [jnp.zeros((1,), jnp.int32)]}
