"""Program-level pipeline-parallel op.

Reference analog: ``python/paddle/fluid/optimizer.py:2677`` PipelineOptimizer
(cuts a user program into sections) executed by PipelineTrainer/SectionWorker
(section_worker.cc:141 — scopes flowing through CPU queues between devices).

TPU-native redesign: the cut stages must be *isomorphic* (the transformer
per-layer case); one template sub-block is kept and its parameters are
stage-stacked, then the whole GPipe schedule (parallel/pipeline.py —
lax.scan over ppermute ring) compiles into the one jitted step and is
differentiable end-to-end, so the backward pipeline and the per-stage
parameter gradients fall out of the vjp tape. Without a `pp` mesh axis the
op degrades to a sequential loop over stages (same math, no pipelining).
"""
from __future__ import annotations

import logging

import jax.numpy as jnp

from ..core.registry import register_op

logger = logging.getLogger(__name__)


def _log_schedule(kind, n, m):
    """Trace-time schedule report: GPipe ticks and bubble fraction (every
    tick runs one full stage on every device, so idle fraction =
    (n-1)/(m+n-1))."""
    ticks = m + n - 1
    logger.info("[pipeline] %s schedule: stages=%d microbatches=%d "
                "ticks=%d bubble_fraction=%.3f", kind, n, m, ticks,
                (n - 1) / ticks if ticks else 0.0)


@register_op("pipeline")
def _pipeline(ctx, inputs, attrs):
    from ..core.executor import ExecContext, _run_block

    (x,) = inputs["X"]
    flat_params = inputs["Params"]          # [stage-major][param]
    n_stages = attrs["n_stages"]
    n_params = attrs["n_params"]
    m = attrs.get("num_microbatches", 1)
    axis = attrs.get("axis", "pp")
    data_axis = attrs.get("data_axis")
    block = attrs["sub_block"]
    in_name = attrs["in_name"]
    out_name = attrs["out_name"]
    param_names = attrs["param_names"]      # template (stage-0) param names
    capture_names = attrs.get("capture_names", [])
    captures = inputs.get("Captures", [])
    b = x.shape[0]

    # captures with a leading batch dim (attention masks etc.) must be
    # microbatched and travel WITH the activation through the ring — at any
    # tick each stage holds a DIFFERENT microbatch; batch-free captures
    # (scalars, tables) are safely closed over. capture_spec overrides the
    # shape heuristic for ambiguous cases (a [T,...] table with T == batch).
    spec = attrs.get("capture_spec") or {}

    def _is_batched(name, c):
        if name in spec:
            return spec[name] == "batched"
        return getattr(c, "ndim", 0) >= 1 and c.shape[0] == b

    batched = [i for i, c in enumerate(captures)
               if _is_batched(capture_names[i], c)]
    static = {capture_names[i]: captures[i]
              for i in range(len(captures)) if i not in batched}
    bc_names = [capture_names[i] for i in batched]

    # one subkey per step from the threaded stream; stages fold in their
    # stage index so dropout masks differ per stage AND advance per step.
    # Each microbatch additionally carries its OWN key through the ring
    # (raw key data rides the payload like a batched capture), so masks
    # differ per (stage, microbatch) — ADVICE r3.
    import jax as _jax
    from jax import lax as _lax
    base_key = ctx.rng() if not ctx.is_test else None

    def stage_fn(params_list, payload, stage_key=None):
        inp, *bcaps = payload
        env = dict(zip(param_names, params_list))
        env.update(static)
        env.update(zip(bc_names, bcaps))
        env[in_name] = inp
        sub = ExecContext(stage_key, is_test=ctx.is_test, mesh=ctx.mesh,
                          amp=ctx.amp)
        _run_block(block, env, sub)
        return (env[out_name], *bcaps)

    mesh = ctx.mesh
    if mesh is None or axis not in mesh.axis_names:
        # no pp axis: sequential stages (identical math, no overlap)
        payload = (x, *[captures[i] for i in batched])
        for s in range(n_stages):
            sk = (None if base_key is None
                  else _jax.random.fold_in(base_key, s))
            payload = stage_fn(
                flat_params[s * n_params:(s + 1) * n_params], payload, sk)
        return {"Out": [payload[0]]}

    if base_key is not None:
        _typed = _jax.dtypes.issubdtype(getattr(base_key, "dtype", None),
                                        _jax.dtypes.prng_key)
        _impl = str(_jax.random.key_impl(base_key)) if _typed else None
        _mkeys = _jax.random.split(base_key, m)
        _mdata = _jax.random.key_data(_mkeys) if _typed else _mkeys

    def staged_fn(params_list, payload):
        if base_key is None:
            return stage_fn(params_list, payload, None)
        # last payload element = this microbatch's raw key data; wrap it,
        # fold in the stage index, and pass the data through unchanged so
        # the NEXT stage sees the same microbatch key after the ppermute
        inp_caps, kd = payload[:-1], payload[-1]
        mk = (_jax.random.wrap_key_data(kd, impl=_impl) if _impl else kd)
        sk = _jax.random.fold_in(mk, _lax.axis_index(axis))
        out = stage_fn(params_list, inp_caps, sk)
        return (*out, kd)

    from ..parallel.pipeline import pipeline_step

    stacked = [jnp.stack([flat_params[s * n_params + j]
                          for s in range(n_stages)])
               for j in range(n_params)]
    if b % m:
        raise ValueError(f"pipeline: batch {b} not divisible by "
                         f"num_microbatches {m}")

    def micro(a):
        return a.reshape((m, b // m) + a.shape[1:])

    xs = (micro(x), *[micro(captures[i]) for i in batched])
    if base_key is not None:
        xs = xs + (_mdata,)
    _log_schedule("GPipe", n_stages, m)
    out = pipeline_step(staged_fn, stacked, xs, mesh, axis,
                        data_axis=data_axis)
    return {"Out": [out.reshape(x.shape)]}


@register_op("pipeline_hetero")
def _pipeline_hetero(ctx, inputs, attrs):
    """Heterogeneous pipeline: per-stage sub-blocks with their own ops,
    params, captures, and boundary shapes (reference heterogeneous sections,
    section_worker.cc:141) — lowered to the lax.switch ppermute ring in
    parallel/pipeline.pipeline_hetero, or a sequential stage loop without a
    `pp` mesh axis."""
    import jax as _jax

    from ..core.executor import ExecContext, _run_block

    (x,) = inputs["X"]
    flat_params = inputs["Params"]
    flat_caps = inputs.get("Captures", [])
    blocks = attrs["sub_blocks"]
    names = attrs["boundary_names"]
    param_names = attrs["param_names"]      # list of per-stage name lists
    cap_names = attrs["capture_names"]
    n_stages = attrs["n_stages"]
    m = attrs.get("num_microbatches", 1)
    axis = attrs.get("axis", "pp")
    spec = attrs.get("capture_spec") or {}
    b = x.shape[0]

    # split the flat input lists back per stage
    ps, cs, pi, ci = [], [], 0, 0
    for k in range(n_stages):
        ps.append(list(flat_params[pi:pi + len(param_names[k])]))
        cs.append(list(flat_caps[ci:ci + len(cap_names[k])]))
        pi += len(param_names[k])
        ci += len(cap_names[k])

    def _is_batched(name, c):
        if name in spec:
            return spec[name] == "batched"
        return getattr(c, "ndim", 0) >= 1 and c.shape[0] == b

    base_key = ctx.rng() if not ctx.is_test else None

    def make_stage(k, micro_caps: bool):
        bnames = [n for n, c in zip(cap_names[k], cs[k]) if _is_batched(n, c)]
        static = {n: c for n, c in zip(cap_names[k], cs[k])
                  if n not in bnames}
        key_k = (None if base_key is None
                 else _jax.random.fold_in(base_key, k))
        # ADVICE r3: each microbatch must see a distinct RNG key, or every
        # scan tick reuses the stage key and dropout masks repeat across
        # microbatches. The pipeline path threads a per-microbatch key in
        # as the LAST capture (split from key_k); the sequential path runs
        # the whole batch once so key_k alone is correct there.
        keyed = micro_caps and key_k is not None

        def fn(params_list, xin, cap_tuple):
            if keyed:
                *cap_vals, mkey = cap_tuple
            else:
                cap_vals, mkey = cap_tuple, key_k
            env = dict(zip(param_names[k], params_list))
            env.update(static)
            env.update(zip(bnames, cap_vals))
            env[names[k]] = xin
            sub = ExecContext(mkey, is_test=ctx.is_test, mesh=ctx.mesh,
                              amp=ctx.amp)
            _run_block(blocks[k], env, sub)
            return env[names[k + 1]]
        micro_keys = _jax.random.split(key_k, m) if keyed else None
        return fn, bnames, micro_keys

    mesh = ctx.mesh
    if mesh is None or axis not in mesh.axis_names:
        y = x
        for k in range(n_stages):
            fn, bnames, _ = make_stage(k, micro_caps=False)
            bvals = tuple(c for n, c in zip(cap_names[k], cs[k])
                          if n in bnames)
            y = fn(ps[k], y, bvals)
        return {"Out": [y]}

    data_axis = attrs.get("data_axis")
    if data_axis is not None and data_axis in mesh.axis_names \
            and mesh.shape[data_axis] > 1:
        import warnings
        warnings.warn(
            f"pipeline_hetero: heterogeneous stages run in a FULLY-manual "
            f"shard_map, so the batch is replicated over the "
            f"{data_axis!r}={mesh.shape[data_axis]} mesh axis (no data "
            f"parallelism inside this pipeline). Use isomorphic stages for "
            f"pp×dp composition, or shrink the mesh to the pp axis.",
            stacklevel=2)
    if b % m:
        raise ValueError(f"pipeline_hetero: batch {b} not divisible by "
                         f"num_microbatches {m}")

    def micro(a):
        return a.reshape((m, b // m) + a.shape[1:])

    from ..parallel.pipeline import pipeline_hetero

    stage_fns, caps_tree = [], []
    for k in range(n_stages):
        fn, bnames, micro_keys = make_stage(k, micro_caps=True)
        stage_fns.append(fn)
        stage_caps = tuple(
            micro(c) for n, c in zip(cap_names[k], cs[k]) if n in bnames)
        if micro_keys is not None:
            stage_caps = stage_caps + (micro_keys,)
        caps_tree.append(stage_caps)
    _log_schedule("GPipe-hetero", n_stages, m)
    out = pipeline_hetero(stage_fns, tuple(ps), micro(x), mesh, axis,
                          caps=tuple(caps_tree))
    return {"Out": [jnp.reshape(out, (b,) + tuple(out.shape[2:]))]}
