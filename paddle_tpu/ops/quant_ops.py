"""Simulated-quantization (QAT) ops.

Reference analogs: ``paddle/fluid/operators/fake_quantize_op.cc`` (
fake_quantize_abs_max, fake_quantize_range_abs_max,
fake_quantize_moving_average_abs_max, fake_channel_wise_quantize_abs_max,
moving_average_abs_max_scale) and ``fake_dequantize_op.cc``.

TPU-native: quant-dequant round trips stay in float (the MXU runs bf16;
int8 inference is simulated), and every fake-quant op uses the
straight-through estimator via the registry's grad_fn hook — the cotangent
passes through the rounding untouched (the reference achieves the same by
registering the ops gradient-free and letting QAT graphs wire grads around
them)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


def _quant_dequant(x, scale, bits):
    bnt = (1 << (bits - 1)) - 1
    s = jnp.maximum(scale, 1e-8)
    return jnp.round(jnp.clip(x / s, -1.0, 1.0) * bnt) / bnt * s


def _ste_grad(attrs):
    """Straight-through estimator: dX = dOut (rounding treated as id)."""
    def grad(ctx, inputs, attrs2, outputs, out_cots):
        g = out_cots["Out"][0]
        return {"X": [g]}
    return grad


@register_op("fake_quantize_abs_max", grad_fn=_ste_grad,
             nondiff_inputs=[])
def _fake_quantize_abs_max(ctx, inputs, attrs):
    (x,) = inputs["X"]
    bits = int(attrs.get("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    return {"Out": [_quant_dequant(x, scale, bits)],
            "OutScale": [lax.stop_gradient(scale.reshape(1))]}


@register_op("fake_channel_wise_quantize_abs_max", grad_fn=_ste_grad)
def _fake_cw_quantize_abs_max(ctx, inputs, attrs):
    (x,) = inputs["X"]
    bits = int(attrs.get("bit_length", 8))
    axes = tuple(range(1, x.ndim))
    scale = jnp.max(jnp.abs(x), axis=axes)          # per out-channel (dim 0)
    shape = (-1,) + (1,) * (x.ndim - 1)
    return {"Out": [_quant_dequant(x, scale.reshape(shape), bits)],
            "OutScale": [lax.stop_gradient(scale)]}


@register_op("fake_quantize_moving_average_abs_max", grad_fn=_ste_grad,
             nondiff_inputs=["InScale", "InAccum", "InState"])
def _fake_quantize_ma_abs_max(ctx, inputs, attrs):
    """activation quant: scale tracked by moving average of |x|max."""
    (x,) = inputs["X"]
    (in_scale,) = inputs["InScale"]
    bits = int(attrs.get("bit_length", 8))
    momentum = attrs.get("moving_rate", 0.9)
    cur = jnp.max(jnp.abs(x))
    if attrs.get("is_test", False) or ctx.is_test:
        scale = in_scale.reshape(())
        new_scale = in_scale
    else:
        scale = momentum * in_scale.reshape(()) + (1.0 - momentum) * cur
        new_scale = scale.reshape(1)
    return {"Out": [_quant_dequant(x, scale, bits)],
            "OutScale": [lax.stop_gradient(jnp.reshape(new_scale, (1,)))]}


@register_op("fake_quantize_range_abs_max", grad_fn=_ste_grad,
             nondiff_inputs=["InScale", "Iter"])
def _fake_quantize_range_abs_max(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (in_scale,) = inputs["InScale"]
    bits = int(attrs.get("bit_length", 8))
    cur = jnp.max(jnp.abs(x))
    if attrs.get("is_test", False) or ctx.is_test:
        scale = in_scale.reshape(())
    else:
        scale = jnp.maximum(in_scale.reshape(()), cur)
    return {"Out": [_quant_dequant(x, scale, bits)],
            "OutScale": [lax.stop_gradient(scale.reshape(1))]}


@register_op("moving_average_abs_max_scale", differentiable=False)
def _moving_average_abs_max_scale(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (in_scale,) = inputs["InScale"]
    momentum = attrs.get("moving_rate", 0.9)
    cur = jnp.max(jnp.abs(x))
    scale = momentum * in_scale.reshape(()) + (1.0 - momentum) * cur
    return {"Out": [x], "OutScale": [scale.reshape(1)]}


@register_op("fake_dequantize_max_abs")
def _fake_dequantize_max_abs(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (scale,) = inputs["Scale"]
    bnt = (1 << (int(attrs.get("max_range_bits", 8)) - 1)) - 1
    max_range = attrs.get("max_range", float(bnt))
    return {"Out": [x * scale.reshape(()) / max_range]}


@register_op("fake_channel_wise_dequantize_max_abs")
def _fake_cw_dequantize_max_abs(ctx, inputs, attrs):
    (x,) = inputs["X"]
    scales = inputs["Scales"]
    quant_bits = attrs.get("quant_bits", [8])
    out = x
    for s, b in zip(scales, quant_bits):
        shape = (-1,) + (1,) * (x.ndim - 1) if s.ndim == 1 and s.shape[0] == x.shape[0] \
            else (1,) * x.ndim
        out = out * s.reshape(shape) / float((1 << (int(b) - 1)) - 1)
    return {"Out": [out]}
