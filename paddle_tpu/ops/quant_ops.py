"""Simulated-quantization (QAT) ops.

Reference analogs: ``paddle/fluid/operators/fake_quantize_op.cc`` (
fake_quantize_abs_max, fake_quantize_range_abs_max,
fake_quantize_moving_average_abs_max, fake_channel_wise_quantize_abs_max,
moving_average_abs_max_scale) and ``fake_dequantize_op.cc``.

TPU-native: quant-dequant round trips stay in float (the MXU runs bf16;
int8 inference is simulated), and every fake-quant op uses the
straight-through estimator via the registry's grad_fn hook — the cotangent
passes through the rounding untouched (the reference achieves the same by
registering the ops gradient-free and letting QAT graphs wire grads around
them)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import act_map, one, opt_input

_ACTS = act_map()


def _quant_dequant(x, scale, bits):
    bnt = (1 << (bits - 1)) - 1
    s = jnp.maximum(scale, 1e-8)
    return jnp.round(jnp.clip(x / s, -1.0, 1.0) * bnt) / bnt * s


def _ste_grad(attrs):
    """Straight-through estimator: dX = dOut (rounding treated as id)."""
    def grad(ctx, inputs, attrs2, outputs, out_cots):
        g = out_cots["Out"][0]
        return {"X": [g]}
    return grad


@register_op("fake_quantize_abs_max", grad_fn=_ste_grad,
             nondiff_inputs=[])
def _fake_quantize_abs_max(ctx, inputs, attrs):
    (x,) = inputs["X"]
    bits = int(attrs.get("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    return {"Out": [_quant_dequant(x, scale, bits)],
            "OutScale": [lax.stop_gradient(scale.reshape(1))]}


@register_op("fake_channel_wise_quantize_abs_max", grad_fn=_ste_grad)
def _fake_cw_quantize_abs_max(ctx, inputs, attrs):
    (x,) = inputs["X"]
    bits = int(attrs.get("bit_length", 8))
    axes = tuple(range(1, x.ndim))
    scale = jnp.max(jnp.abs(x), axis=axes)          # per out-channel (dim 0)
    shape = (-1,) + (1,) * (x.ndim - 1)
    return {"Out": [_quant_dequant(x, scale.reshape(shape), bits)],
            "OutScale": [lax.stop_gradient(scale)]}


@register_op("fake_quantize_moving_average_abs_max", grad_fn=_ste_grad,
             nondiff_inputs=["InScale", "InAccum", "InState"])
def _fake_quantize_ma_abs_max(ctx, inputs, attrs):
    """activation quant: scale tracked by moving average of |x|max."""
    (x,) = inputs["X"]
    (in_scale,) = inputs["InScale"]
    bits = int(attrs.get("bit_length", 8))
    momentum = attrs.get("moving_rate", 0.9)
    cur = jnp.max(jnp.abs(x))
    if attrs.get("is_test", False) or ctx.is_test:
        scale = in_scale.reshape(())
        new_scale = in_scale
    else:
        scale = momentum * in_scale.reshape(()) + (1.0 - momentum) * cur
        new_scale = scale.reshape(1)
    return {"Out": [_quant_dequant(x, scale, bits)],
            "OutScale": [lax.stop_gradient(jnp.reshape(new_scale, (1,)))]}


@register_op("fake_quantize_range_abs_max", grad_fn=_ste_grad,
             nondiff_inputs=["InScale", "Iter"])
def _fake_quantize_range_abs_max(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (in_scale,) = inputs["InScale"]
    bits = int(attrs.get("bit_length", 8))
    cur = jnp.max(jnp.abs(x))
    if attrs.get("is_test", False) or ctx.is_test:
        scale = in_scale.reshape(())
    else:
        scale = jnp.maximum(in_scale.reshape(()), cur)
    return {"Out": [_quant_dequant(x, scale, bits)],
            "OutScale": [lax.stop_gradient(scale.reshape(1))]}


@register_op("moving_average_abs_max_scale", differentiable=False)
def _moving_average_abs_max_scale(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (in_scale,) = inputs["InScale"]
    momentum = attrs.get("moving_rate", 0.9)
    cur = jnp.max(jnp.abs(x))
    scale = momentum * in_scale.reshape(()) + (1.0 - momentum) * cur
    return {"Out": [x], "OutScale": [scale.reshape(1)]}


@register_op("fake_dequantize_max_abs")
def _fake_dequantize_max_abs(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (scale,) = inputs["Scale"]
    bnt = (1 << (int(attrs.get("max_range_bits", 8)) - 1)) - 1
    max_range = attrs.get("max_range", float(bnt))
    return {"Out": [x * scale.reshape(()) / max_range]}


@register_op("fake_channel_wise_dequantize_max_abs")
def _fake_cw_dequantize_max_abs(ctx, inputs, attrs):
    (x,) = inputs["X"]
    scales = inputs["Scales"]
    quant_bits = attrs.get("quant_bits", [8])
    out = x
    for s, b in zip(scales, quant_bits):
        shape = (-1,) + (1,) * (x.ndim - 1) if s.ndim == 1 and s.shape[0] == x.shape[0] \
            else (1,) * x.ndim
        out = out * s.reshape(shape) / float((1 << (int(b) - 1)) - 1)
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# REAL int8 runtime ops (post-training quantization, inference/quant.py).
# Unlike the fake-quant family above, these carry int8 weights and run the
# gemm in int8×int8→int32 (`preferred_element_type`) with a float dequant
# epilogue — on TPU the int8 MXU path at (32, 128) tiles, roughly 2× the
# bf16 macs/cycle. Symmetric scheme throughout:
#   x ≈ xq · sx/127 (per tensor, sx calibrated),  w ≈ wq · sw/127 (per
#   out-channel), so  x@w ≈ (xq@wq) · sx·sw/127².
# Inference-only: registered non-differentiable.
# ---------------------------------------------------------------------------


def _quantize_act(x, scale):
    """float activations → int8 with the calibrated per-tensor scale."""
    inv = 127.0 / jnp.maximum(jnp.float32(scale), 1e-8)
    return jnp.clip(jnp.round(x.astype(jnp.float32) * inv),
                    -127.0, 127.0).astype(jnp.int8)


@register_op("quantized_fc", differentiable=False)
def _quantized_fc(ctx, inputs, attrs):
    """fused_fc rewritten by int8_quantize_pass: quantize the activation
    at the calibrated scale, int8 gemm into int32, dequant by
    sx·sw/127² per out-channel, then float bias + activation."""
    (x,) = inputs["Input"]
    (w,) = inputs["W"]                 # int8 [k, n]
    (w_scale,) = inputs["WScale"]      # f32 [n] (per out-channel abs-max)
    b = opt_input(inputs, "Bias")
    act_scale = float(attrs["act_scale"])
    ncol = int(attrs.get("in_num_col_dims", 1))
    if ncol < 0:                       # matmul-style: all-but-last lead
        ncol = x.ndim - 1
    lead = x.shape[:ncol]
    m = 1
    for d in lead:
        m *= int(d)
    x2 = x.reshape((m, -1))
    xq = _quantize_act(x2, act_scale)
    acc = jnp.matmul(xq, w, preferred_element_type=jnp.int32)
    deq = (act_scale / 127.0) * (w_scale.astype(jnp.float32) / 127.0)
    out = acc.astype(jnp.float32) * deq.reshape((1, -1))
    if b is not None:
        out = out + b.astype(jnp.float32).reshape((1, -1))
    out = _ACTS[attrs.get("activation_type", "")](out)
    return one(out.reshape(tuple(lead) + (w.shape[-1],)))


@register_op("quantized_lookup_table", differentiable=False)
def _quantized_lookup_table(ctx, inputs, attrs):
    """lookup_table(/_v2) rewritten by int8_quantize_pass: gather int8
    rows and dequant with the per-table scale. `squeeze_last` preserves
    lookup_table's trailing-1 id squeeze; `table_scale` is the table's
    abs-max."""
    (w,) = inputs["W"]                 # int8 [V, D]
    (ids,) = inputs["Ids"]
    scale = float(attrs["table_scale"])
    idx = ids
    if attrs.get("squeeze_last") and ids.ndim >= 2 and ids.shape[-1] == 1:
        idx = ids[..., 0]
    out = jnp.take(w, idx, axis=0).astype(jnp.float32) * (scale / 127.0)
    padding_idx = attrs.get("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((idx == padding_idx)[..., None], 0.0, out)
    return one(out)
