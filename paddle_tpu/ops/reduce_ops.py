"""Reduction + arg ops (reference operators/reduce_ops/, mean_op.cc,
argsort/arg_max/arg_min, top_k_op.cc)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import one


def _reduce(name, fn, differentiable=True):
    @register_op(name, differentiable=differentiable)
    def _impl(ctx, inputs, attrs, _fn=fn):
        (x,) = inputs["X"]
        if attrs.get("reduce_all", False):
            dim = None
        else:
            dim = attrs.get("dim", [0])
            dim = tuple(dim) if isinstance(dim, (list, tuple)) else (dim,)
        keep = attrs.get("keep_dim", False)
        return one(_fn(x, axis=dim, keepdims=keep))
    return _impl


_reduce("reduce_sum", jnp.sum)
_reduce("reduce_mean", jnp.mean)
_reduce("reduce_max", jnp.max)
_reduce("reduce_min", jnp.min)
_reduce("reduce_prod", jnp.prod)
_reduce("reduce_any", jnp.any, differentiable=False)
_reduce("reduce_all", jnp.all, differentiable=False)


@register_op("mean")
def _mean(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.mean(x))


@register_op("logsumexp")
def _logsumexp(ctx, inputs, attrs):
    (x,) = inputs["X"]
    axis = attrs.get("dim")
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return one(jax.scipy.special.logsumexp(x, axis=axis, keepdims=attrs.get("keep_dim", False)))


@register_op("arg_max", differentiable=False)
def _arg_max(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.argmax(x, axis=attrs.get("axis", -1)).astype(jnp.int64))


@register_op("arg_min", differentiable=False)
def _arg_min(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.argmin(x, axis=attrs.get("axis", -1)).astype(jnp.int64))


@register_op("argsort", differentiable=False)
def _argsort(ctx, inputs, attrs):
    (x,) = inputs["X"]
    axis = attrs.get("axis", -1)
    descending = attrs.get("descending", False)
    idx = jnp.argsort(-x if descending else x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": [out], "Indices": [idx.astype(jnp.int64)]}


@register_op("top_k")
def _top_k(ctx, inputs, attrs):
    (x,) = inputs["X"]
    k = attrs["k"]
    vals, idx = lax.top_k(x, k)
    return {"Out": [vals], "Indices": [idx.astype(jnp.int64)]}


@register_op("max", differentiable=True)
def _max(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.max(x))


@register_op("frobenius_norm")
def _frobenius_norm(ctx, inputs, attrs):
    (x,) = inputs["X"]
    dim = attrs.get("dim")
    dim = tuple(dim) if isinstance(dim, (list, tuple)) else dim
    return one(jnp.sqrt(jnp.sum(x * x, axis=dim, keepdims=attrs.get("keep_dim", False))))
