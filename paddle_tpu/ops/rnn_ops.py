"""Recurrent-network ops — LSTM / GRU over padded batches via lax.scan.

Reference analog: ``paddle/fluid/operators/lstm_op.cc`` (dynamic_lstm),
``gru_op.cc`` (dynamic_gru), ``gru_unit_op.cc``, ``cudnn_lstm_op.cu.cc``
(multi-layer cudnn lstm). The reference consumes LoDTensors (packed
variable-length rows, math/lstm compute batched by sorted length); the
TPU-native redesign consumes padded ``[B, T, ...]`` tensors plus an integer
``length [B]`` and masks the carry so padded steps are identity — static
shapes for XLA, with `lax.scan` giving a single fused-loop HLO whose per-step
matmuls land on the MXU.

Gate layouts follow the reference weight packing so checkpoints translate:
  LSTM projected input / recurrent weight columns: [i, f, c(candidate), o]
  (math/detail/lstm_kernel.h activation order; lstm_op.cc W shape [H, 4H]).
  GRU weight: [H, 3H] with first 2H = update/reset gates, last H = candidate
  (gru_op.cc weight layout).

All ops here are differentiable through the scan (vjp tape — the functional
equivalent of lstm_grad/gru_grad kernels).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import act_map, length_mask, opt_input

_ACTS = act_map()


def _mask_carry(new, old, mask_t):
    """Keep `new` where the step is inside the sequence, else carry `old`."""
    m = mask_t.reshape(-1, 1).astype(new.dtype)
    return new * m + old * (1.0 - m)


@register_op("lstm", nondiff_inputs=["Length"])
def _lstm(ctx, inputs, attrs):
    """dynamic_lstm: Input [B,T,4H] (already x@Wx projected, as in the
    reference where fc is applied before lstm_op), Weight [H,4H] recurrent,
    Bias [4H] (or [7H] with peepholes), optional H0/C0 [B,H], Length [B].

    Outputs: Hidden [B,T,H], Cell [B,T,H], LastH/LastC [B,H].
    """
    (x,) = inputs["Input"]
    (w,) = inputs["Weight"]
    bias = opt_input(inputs, "Bias")
    length = opt_input(inputs, "Length")
    h0 = opt_input(inputs, "H0")
    c0 = opt_input(inputs, "C0")

    B, T, four_h = x.shape
    H = four_h // 4
    gate_act = _ACTS[attrs.get("gate_activation", "sigmoid")]
    cell_act = _ACTS[attrs.get("cell_activation", "tanh")]
    cand_act = _ACTS[attrs.get("candidate_activation", "tanh")]
    use_peepholes = attrs.get("use_peepholes", False) and bias is not None and bias.shape[-1] == 7 * H
    is_reverse = attrs.get("is_reverse", False)

    if h0 is None:
        h0 = jnp.zeros((B, H), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((B, H), x.dtype)
    mask = length_mask(length, B, T, x.dtype)

    b_gates = None
    if bias is not None:
        b_gates = bias.reshape(-1)[: 4 * H]
    if use_peepholes:
        pk = bias.reshape(-1)
        w_ic, w_fc, w_oc = pk[4 * H:5 * H], pk[5 * H:6 * H], pk[6 * H:7 * H]

    xs = jnp.swapaxes(x, 0, 1)          # [T,B,4H]
    ms = jnp.swapaxes(mask, 0, 1)       # [T,B]
    if is_reverse:
        xs, ms = xs[::-1], ms[::-1]

    def step(carry, xm):
        h_prev, c_prev = carry
        xt, mt = xm
        gates = xt + h_prev @ w
        if b_gates is not None:
            gates = gates + b_gates
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        if use_peepholes:
            gi = gi + c_prev * w_ic
            gf = gf + c_prev * w_fc
        i = gate_act(gi)
        f = gate_act(gf)
        c_new = f * c_prev + i * cand_act(gc)
        if use_peepholes:
            go = go + c_new * w_oc
        o = gate_act(go)
        h_new = o * cell_act(c_new)
        h_new = _mask_carry(h_new, h_prev, mt)
        c_new = _mask_carry(c_new, c_prev, mt)
        return (h_new, c_new), (h_new, c_new)

    (h_last, c_last), (hs, cs) = lax.scan(step, (h0, c0), (xs, ms))
    if is_reverse:
        hs, cs = hs[::-1], cs[::-1]
    hidden = jnp.swapaxes(hs, 0, 1)
    cell = jnp.swapaxes(cs, 0, 1)
    return {"Hidden": [hidden], "Cell": [cell],
            "LastH": [h_last], "LastC": [c_last]}


@register_op("gru", nondiff_inputs=["Length"])
def _gru(ctx, inputs, attrs):
    """dynamic_gru: Input [B,T,3H] projected, Weight [H,3H]
    (first 2H update/reset, last H candidate — gru_op.cc layout),
    Bias [3H], optional H0, Length. Output Hidden [B,T,H], LastH [B,H]."""
    (x,) = inputs["Input"]
    (w,) = inputs["Weight"]
    bias = opt_input(inputs, "Bias")
    length = opt_input(inputs, "Length")
    h0 = opt_input(inputs, "H0")

    B, T, three_h = x.shape
    H = three_h // 3
    gate_act = _ACTS[attrs.get("gate_activation", "sigmoid")]
    cand_act = _ACTS[attrs.get("activation", "tanh")]
    is_reverse = attrs.get("is_reverse", False)
    origin_mode = attrs.get("origin_mode", False)

    w_gates = w[:, : 2 * H]
    w_cand = w[:, 2 * H:]
    if h0 is None:
        h0 = jnp.zeros((B, H), x.dtype)
    mask = length_mask(length, B, T, x.dtype)
    b = None if bias is None else bias.reshape(-1)

    xs = jnp.swapaxes(x, 0, 1)
    ms = jnp.swapaxes(mask, 0, 1)
    if is_reverse:
        xs, ms = xs[::-1], ms[::-1]

    def step(h_prev, xm):
        xt, mt = xm
        xg = xt[:, : 2 * H]
        xc = xt[:, 2 * H:]
        if b is not None:
            xg = xg + b[: 2 * H]
            xc = xc + b[2 * H:]
        uz = gate_act(xg + h_prev @ w_gates)
        u, r = jnp.split(uz, 2, axis=-1)
        c = cand_act(xc + (r * h_prev) @ w_cand)
        if origin_mode:  # h = u*h_prev + (1-u)*c  (original Cho formulation)
            h_new = u * h_prev + (1.0 - u) * c
        else:            # paddle default: h = (1-u)*h_prev + u*c
            h_new = (1.0 - u) * h_prev + u * c
        h_new = _mask_carry(h_new, h_prev, mt)
        return h_new, h_new

    h_last, hs = lax.scan(step, h0, (xs, ms))
    if is_reverse:
        hs = hs[::-1]
    hidden = jnp.swapaxes(hs, 0, 1)
    return {"Hidden": [hidden], "LastH": [h_last]}


@register_op("gru_unit")
def _gru_unit(ctx, inputs, attrs):
    """Single GRU step (gru_unit_op.cc): Input [B,3H] projected, HiddenPrev
    [B,H], Weight [H,3H], Bias [3H]."""
    (x,) = inputs["Input"]
    (h_prev,) = inputs["HiddenPrev"]
    (w,) = inputs["Weight"]
    bias = opt_input(inputs, "Bias")
    H = h_prev.shape[-1]
    gate_act = _ACTS[attrs.get("gate_activation", "sigmoid")]
    cand_act = _ACTS[attrs.get("activation", "tanh")]

    xg, xc = x[:, : 2 * H], x[:, 2 * H:]
    if bias is not None:
        b = bias.reshape(-1)
        xg = xg + b[: 2 * H]
        xc = xc + b[2 * H:]
    uz = gate_act(xg + h_prev @ w[:, : 2 * H])
    u, r = jnp.split(uz, 2, axis=-1)
    c = cand_act(xc + (r * h_prev) @ w[:, 2 * H:])
    h_new = (1.0 - u) * h_prev + u * c
    return {"Hidden": [h_new], "Gate": [jnp.concatenate([u, r], -1)],
            "ResetHiddenPrev": [r * h_prev]}


@register_op("lstm_unit")
def _lstm_unit(ctx, inputs, attrs):
    """Single LSTM step on pre-projected gates (lstm_unit_op.cc):
    X [B,4H] = x@Wx + h@Wh (+b), C_prev [B,H]. Gate order [i,f,c,o]."""
    (gates,) = inputs["X"]
    (c_prev,) = inputs["C_prev"]
    forget_bias = attrs.get("forget_bias", 0.0)
    gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + forget_bias)
    c = f * c_prev + i * jnp.tanh(gc)
    h = jax.nn.sigmoid(go) * jnp.tanh(c)
    return {"C": [c], "H": [h]}


@register_op("cudnn_lstm", nondiff_inputs=["Length"])
def _multilayer_lstm(ctx, inputs, attrs):
    """Multi-layer (optionally bidirectional) LSTM — cudnn_lstm_op.cu.cc
    capability on TPU: stacked scans, XLA-fused. Input [B,T,D] raw (not
    projected); weights passed as flat lists.

    inputs: Input, WeightX (num_dirs*layers entries [Din,4H]), WeightH
    ([H,4H] each), Bias ([4H] each), Length.
    attrs: num_layers, is_bidirec, hidden_size, dropout_prob.
    """
    (x,) = inputs["Input"]
    wxs = inputs["WeightX"]
    whs = inputs["WeightH"]
    biases = inputs.get("Bias", [None] * len(wxs))
    length = opt_input(inputs, "Length")
    num_layers = attrs.get("num_layers", 1)
    bidirec = attrs.get("is_bidirec", False)
    dropout_p = attrs.get("dropout_prob", 0.0)
    num_dirs = 2 if bidirec else 1

    B, T, _ = x.shape
    H = attrs["hidden_size"]

    def run_dir(inp, wx, wh, b, reverse):
        proj = jnp.einsum("btd,dh->bth", inp, wx)
        out = _lstm(ctx, {"Input": [proj], "Weight": [wh],
                          "Bias": [b] if b is not None else [],
                          "Length": [length] if length is not None else []},
                    {"is_reverse": reverse})
        return out["Hidden"][0], out["LastH"][0], out["LastC"][0]

    cur = x
    last_hs, last_cs = [], []
    for layer in range(num_layers):
        outs = []
        for d in range(num_dirs):
            k = layer * num_dirs + d
            hid, lh, lc = run_dir(cur, wxs[k], whs[k], biases[k], d == 1)
            outs.append(hid)
            last_hs.append(lh)
            last_cs.append(lc)
        cur = jnp.concatenate(outs, -1) if num_dirs == 2 else outs[0]
        if dropout_p > 0.0 and not ctx.is_test and layer < num_layers - 1:
            keep = 1.0 - dropout_p
            cur = cur * jax.random.bernoulli(ctx.rng(), keep, cur.shape) / keep
    return {"Out": [cur],
            "LastH": [jnp.stack(last_hs)], "LastC": [jnp.stack(last_cs)]}
