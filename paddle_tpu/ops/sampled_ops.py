"""Sampled / tree classifiers and small aliases.

Reference analogs: nce_op.cc/.h (noise-contrastive estimation),
hierarchical_sigmoid_op.cc + math/matrix_bit_code.h (default complete-tree
bit codes), sample_logits_op.cc (the sampled-softmax building block),
edit_distance_op.h, ctc_align_op.h, proximal_adagrad_op.cc, cvm_op.cc,
data_norm_op.cc, array ops (write_to_array/read_from_array — tensor-array
aliases), tensor_array_to_tensor_op.cc.

TPU notes: samplers draw with the executor's threaded PRNG; bit-code paths
use the reference's default complete binary tree (code = label + num_classes,
walk the high bits), masked to static max depth.
"""
from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.registry import register_op
from .common import one


def _hsig_paths(num_classes: int):
    """Static (index, bit, mask) tables [num_classes, max_depth] for the
    default complete-tree bit code (matrix_bit_code.h SimpleCode):
    code = c + num_classes; at depth d: node = (code >> (d+1)) - 1,
    bit = (code >> d) & 1,走 from the deepest bit down."""
    max_depth = int(_math.floor(_math.log2(2 * num_classes - 1)))
    idx = np.zeros((num_classes, max_depth), np.int32)
    bit = np.zeros((num_classes, max_depth), np.float32)
    msk = np.zeros((num_classes, max_depth), np.float32)
    for c in range(num_classes):
        code = c + num_classes
        length = int(_math.floor(_math.log2(code)))
        for d in range(length):
            shift = length - d - 1
            idx[c, d] = (code >> (shift + 1)) - 1
            bit[c, d] = (code >> shift) & 1
            msk[c, d] = 1.0
    return jnp.asarray(idx), jnp.asarray(bit), jnp.asarray(msk)


@register_op("hierarchical_sigmoid",
             nondiff_inputs=["Label", "PathTable", "PathCode"])
def _hierarchical_sigmoid(ctx, inputs, attrs):
    """hierarchical_sigmoid_op.cc: loss_i =
    Σ_path softplus((1 − 2·bit)·(w_node·x_i + b_node)).

    Default complete binary tree from the label, OR a CUSTOM tree
    (matrix_bit_code.h CustomCode) via PathTable [B, L] (node ids, −1 pad)
    and PathCode [B, L] (branch bits)."""
    (x,) = inputs["X"]
    (w,) = inputs["W"]                     # [num_classes-1, D]
    (label,) = inputs["Label"]
    bias = inputs.get("Bias")
    ptable = inputs.get("PathTable", [None])[0]
    pcode = inputs.get("PathCode", [None])[0]
    num_classes = int(attrs["num_classes"])
    if ptable is not None:
        node_raw = ptable.reshape(ptable.shape[0], -1).astype(jnp.int32)
        msk = (node_raw >= 0).astype(jnp.float32)
        node = jnp.maximum(node_raw, 0)
        bit = pcode.reshape(node.shape).astype(jnp.float32)
    else:
        idx_t, bit_t, msk_t = _hsig_paths(num_classes)
        lab = label.reshape(-1).astype(jnp.int32)
        node = idx_t[lab]                  # [B, L]
        bit = bit_t[lab]
        msk = msk_t[lab]
    wn = w[node]                           # [B, L, D]
    logits = jnp.einsum("bld,bd->bl", wn, x)
    if bias:
        logits = logits + bias[0].reshape(-1)[node]
    z = (1.0 - 2.0 * bit) * logits
    loss = jnp.sum(jnp.where(msk > 0, jax.nn.softplus(z), 0.0),
                   axis=1, keepdims=True)
    pre = jax.nn.sigmoid(logits)           # PreOut parity
    return {"Out": [loss], "PreOut": [pre]}


@register_op("nce", nondiff_inputs=["Label", "SampleWeight",
                                    "CustomDistProbs", "CustomDistAlias",
                                    "CustomDistAliasProbs"])
def _nce(ctx, inputs, attrs):
    """nce_op.h: binary logistic loss on the true class + k uniform noise
    samples (sampler 0 = uniform, the default)."""
    (x,) = inputs["Input"]
    (w,) = inputs["Weight"]                # [num_total_classes, D]
    (label,) = inputs["Label"]
    bias = inputs.get("Bias")
    num_total = int(attrs["num_total_classes"])
    k = int(attrs.get("num_neg_samples", 10))
    sampler = int(attrs.get("sampler", 0))
    b = x.shape[0]
    lab = label.reshape(b, -1).astype(jnp.int32)
    num_true = lab.shape[1]
    if sampler == 1:
        # log_uniform (nce_op.h:51 LogUniformSampler): Zipfian
        # P(c) = log((c+2)/(c+1)) / log(range+1); inverse-CDF draw
        u = jax.random.uniform(ctx.rng(), (b, k))
        rng_log = jnp.log(jnp.float32(num_total + 1))
        neg = jnp.clip(
            (jnp.exp(u * rng_log) - 1.0).astype(jnp.int32), 0,
            num_total - 1)

        def q_of(ids):
            idf = ids.astype(jnp.float32)
            return (jnp.log(idf + 2.0) - jnp.log(idf + 1.0)) / rng_log
    elif sampler == 2:
        # custom_dist: probabilities fed as CustomDistProbs
        (probs,) = inputs["CustomDistProbs"]
        probs = probs.reshape(-1).astype(jnp.float32)
        neg = jax.random.categorical(
            ctx.rng(), jnp.log(probs + 1e-20)[None, :], shape=(b, k)
        ).astype(jnp.int32)

        def q_of(ids):
            return probs[ids]
    else:
        neg = jax.random.randint(ctx.rng(), (b, k), 0, num_total)

        def q_of(ids):
            return jnp.full(ids.shape, 1.0 / num_total, jnp.float32)
    samples = jnp.concatenate([lab, neg], axis=1)       # [B, T+k]
    ws = w[samples]                                     # [B, T+k, D]
    logits = jnp.einsum("btd,bd->bt", ws, x)
    if bias:
        logits = logits + bias[0].reshape(-1)[samples]
    p_true = 1.0 / num_true if num_true else 1.0
    lt = logits[:, :num_true]
    ln = logits[:, num_true:]
    # P(D=1|x) = σ(logit − log(k·q(class))) — q varies per class for the
    # log_uniform/custom samplers, so the correction is per-element
    shift_t = jnp.log(k * q_of(samples[:, :num_true]) + 1e-20)
    shift_n = jnp.log(k * q_of(samples[:, num_true:]) + 1e-20)
    pos = jax.nn.softplus(-(lt - shift_t))
    negl = jax.nn.softplus(ln - shift_n)
    cost = jnp.sum(pos, 1, keepdims=True) * p_true + jnp.sum(negl, 1, keepdims=True)
    return {"Cost": [cost],
            "SampleLogits": [lax.stop_gradient(logits)],
            "SampleLabels": [lax.stop_gradient(samples.astype(jnp.int64))]}


@register_op("sample_logits", nondiff_inputs=["Labels", "CustomizedSamples",
                                              "CustomizedProbabilities"])
def _sample_logits(ctx, inputs, attrs):
    """sample_logits_op.cc: gather logits of [true + uniformly sampled]
    classes, subtract log(q) (the sampled-softmax correction), optionally
    mask accidental hits."""
    (logits,) = inputs["Logits"]           # [B, C]
    (labels,) = inputs["Labels"]           # [B, T]
    s = int(attrs.get("num_samples", 10))
    remove_hits = attrs.get("remove_accidental_hits", True)
    b, c = logits.shape
    lab = labels.reshape(b, -1).astype(jnp.int32)
    t = lab.shape[1]
    sampled = jax.random.randint(ctx.rng(), (b, s), 0, c)
    samples = jnp.concatenate([lab, sampled], axis=1)   # [B, T+S]
    picked = jnp.take_along_axis(logits, samples, axis=1)
    q = jnp.full_like(picked, 1.0 / c)
    out = picked - jnp.log(q)
    if remove_hits:
        hit = (sampled[:, :, None] == lab[:, None, :]).any(-1)  # [B, S]
        mask = jnp.concatenate([jnp.zeros((b, t), bool), hit], axis=1)
        out = jnp.where(mask, out - 1e20, out)
    new_labels = jnp.broadcast_to(jnp.arange(t)[None], (b, t))
    return {"SampledLogits": [out],
            "SampledLabels": [new_labels.astype(jnp.int64)],
            "Samples": [lax.stop_gradient(samples.astype(jnp.int64))],
            "Probabilities": [lax.stop_gradient(q)]}


@register_op("edit_distance", differentiable=False)
def _edit_distance(ctx, inputs, attrs):
    """edit_distance_op.h: Levenshtein distance between padded int rows
    (batch-major redesign of the LoD form; -1 pads terminate a row)."""
    (hyp,) = inputs["Hyps"]
    (ref,) = inputs["Refs"]
    normalized = attrs.get("normalized", True)
    b, m = hyp.shape
    n = ref.shape[1]
    hlen = jnp.sum(hyp >= 0, axis=1)
    rlen = jnp.sum(ref >= 0, axis=1)

    def one(h, r, hl, rl):
        row0 = jnp.arange(n + 1, dtype=jnp.float32)

        def outer(row, i):
            def inner(carry, j):
                row_prev, row_new = carry
                cost = jnp.where(h[i] == r[j], 0.0, 1.0)
                v = jnp.minimum(jnp.minimum(row_new[j] + 1.0,
                                            row_prev[j + 1] + 1.0),
                                row_prev[j] + cost)
                return (row_prev, row_new.at[j + 1].set(v)), None

            init = (row, jnp.zeros(n + 1).at[0].set(i + 1.0))
            (_, new), _ = lax.scan(inner, init, jnp.arange(n))
            return new, new

        _, rows = lax.scan(outer, row0, jnp.arange(m))
        # dp[hl][rl] — select the row at the TRUE hyp length (pads must not
        # participate: a pad could otherwise "substitute" for an insertion
        # and understate the distance)
        table = jnp.concatenate([row0[None], rows], axis=0)   # [m+1, n+1]
        return table[hl, rl]

    dist = jax.vmap(one)(hyp.astype(jnp.int32), ref.astype(jnp.int32),
                         hlen, rlen)
    seq_num = jnp.asarray(b, jnp.int64).reshape(1)
    if normalized:
        dist = dist / jnp.maximum(rlen.astype(jnp.float32), 1.0)
    return {"Out": [dist.reshape(b, 1)], "SequenceNum": [seq_num]}


@register_op("ctc_align", differentiable=False)
def _ctc_align(ctx, inputs, attrs):
    """ctc_align_op.h: collapse repeats then strip blanks; padded output
    (-1 fill) keeps static shapes."""
    (x,) = inputs["Input"]
    blank = int(attrs.get("blank", 0))
    b, t = x.shape
    xi = x.astype(jnp.int32)
    prev = jnp.concatenate([jnp.full((b, 1), -2, jnp.int32), xi[:, :-1]], 1)
    keep = (xi != prev) & (xi != blank)
    pos = jnp.cumsum(keep, axis=1) - 1
    out = jnp.full((b, t), -1, jnp.int32)
    bidx = jnp.broadcast_to(jnp.arange(b)[:, None], (b, t))
    out = out.at[bidx, jnp.where(keep, pos, t - 1)].set(
        jnp.where(keep, xi, -1), mode="drop")
    # ensure padding stays -1 where nothing was written
    return one(out.astype(x.dtype))


@register_op("proximal_adagrad", differentiable=False)
def _proximal_adagrad(ctx, inputs, attrs):
    """proximal_adagrad_op.cc: adagrad step + l1/l2 proximal projection."""
    (p,) = inputs["Param"]
    (m,) = inputs["Moment"]
    (g,) = inputs["Grad"]
    (lr,) = inputs["LearningRate"]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    m_out = m + g * g
    lr_t = lr.reshape(()) / jnp.sqrt(m_out)
    prox = p - lr_t * g
    if l1 > 0:
        prox = jnp.sign(prox) * jnp.maximum(
            jnp.abs(prox) - lr_t * l1, 0.0)
    p_out = prox / (1.0 + lr_t * l2)
    return {"ParamOut": [p_out], "MomentOut": [m_out]}


@register_op("cvm")
def _cvm(ctx, inputs, attrs):
    """cvm_op.cc: CTR show/click feature transform — with use_cvm keep all
    (log show, log click-rate ratio); else strip the 2 lead columns."""
    (x,) = inputs["X"]
    use_cvm = attrs.get("use_cvm", True)
    show = jnp.log(jnp.maximum(x[:, 0:1], 0.0) + 1.0)
    ctr = jnp.log(jnp.maximum(x[:, 1:2], 0.0) + 1.0) - show
    rest = x[:, 2:]
    if use_cvm:
        return {"Y": [jnp.concatenate([show, ctr, rest], axis=1)]}
    return {"Y": [rest]}


@register_op("data_norm")
def _data_norm(ctx, inputs, attrs):
    """data_norm_op.cc: normalize by accumulated batch statistics."""
    (x,) = inputs["X"]
    (size,) = inputs["BatchSize"]
    (bsum,) = inputs["BatchSum"]
    (bsq,) = inputs["BatchSquareSum"]
    eps = attrs.get("epsilon", 1e-4)
    means = bsum / size
    scales = jnp.sqrt(size / (bsq - means * bsum + eps * size))
    return {"Y": [(x - means) * scales], "Means": [means], "Scales": [scales]}


# ---------------------------------------------------------------------------
# tensor-array aliases (reference write_to_array/read_from_array op names)
# ---------------------------------------------------------------------------

@register_op("write_to_array", nondiff_inputs=["I", "Length"])
def _write_to_array(ctx, inputs, attrs):
    from .control_flow_ops import _array_write
    return _array_write(ctx, inputs, attrs)


@register_op("read_from_array", nondiff_inputs=["I"])
def _read_from_array(ctx, inputs, attrs):
    from .control_flow_ops import _array_read
    return _array_read(ctx, inputs, attrs)


@register_op("lod_array_length", differentiable=False)
def _lod_array_length(ctx, inputs, attrs):
    from .control_flow_ops import _array_length
    return _array_length(ctx, inputs, attrs)


@register_op("max_sequence_len", differentiable=False)
def _max_sequence_len(ctx, inputs, attrs):
    """max_sequence_len_op.cc over the padded+mask representation: the
    longest row length from a [B] length vector."""
    (lens,) = inputs["RankTable"]
    return one(jnp.max(lens).reshape(1).astype(jnp.int64))


@register_op("tensor_array_to_tensor")
def _tensor_array_to_tensor(ctx, inputs, attrs):
    """tensor_array_to_tensor_op.cc: stack/concat the [max_len, ...] buffer
    along `axis` (the array is already dense here)."""
    (arr,) = inputs["X"]
    axis = int(attrs.get("axis", 0))
    use_stack = attrs.get("use_stack", False)
    if use_stack:
        return {"Out": [arr], "OutIndex": [jnp.full((arr.shape[0],), 1,
                                                    jnp.int64)]}
    parts = [arr[i] for i in range(arr.shape[0])]
    return {"Out": [jnp.concatenate(parts, axis=axis)],
            "OutIndex": [jnp.asarray([p.shape[axis] for p in parts],
                                     jnp.int64)]}


@register_op("lod_reset")
def _lod_reset(ctx, inputs, attrs):
    """lod_reset_op.h: in the padded+mask redesign LoD is metadata-only —
    values pass through."""
    (x,) = inputs["X"]
    return one(x)
