"""Sequence ops — TPU-native replacement for the LoDTensor machinery.

Reference analog: ``paddle/fluid/operators/sequence_ops/`` (15+ LoD-aware ops
over lod_tensor.h variable-length batches). XLA needs static shapes, so the
TPU-native representation is **padded dense [batch, max_len, ...] + explicit
length/mask vars** (SURVEY §5 long-context note). Each sequence op takes a
Length input instead of reading LoD metadata.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import one


def _mask_from_len(length, maxlen, dtype=jnp.float32):
    return (jnp.arange(maxlen)[None, :] < length.reshape(-1, 1)).astype(dtype)


@register_op("sequence_mask", differentiable=False)
def _sequence_mask(ctx, inputs, attrs):
    (x,) = inputs["X"]
    maxlen = attrs.get("maxlen", -1)
    if maxlen is None or maxlen < 0:
        raise ValueError("sequence_mask on TPU requires static maxlen attr")
    from ..core.dtypes import convert_dtype
    dtype = convert_dtype(attrs.get("out_dtype", "int64"))
    return {"Y": [_mask_from_len(x, maxlen, dtype)]}


@register_op("sequence_pool", nondiff_inputs=["Length"])
def _sequence_pool(ctx, inputs, attrs):
    """sequence_pool_op.cc over padded [B, T, ...] + Length."""
    (x,) = inputs["X"]
    (length,) = inputs["Length"]
    ptype = attrs.get("pooltype", "SUM").upper()
    t = x.shape[1]
    mask = _mask_from_len(length, t, x.dtype)
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    if ptype == "SUM":
        out = jnp.sum(x * mask, axis=1)
    elif ptype == "AVERAGE":
        denom = jnp.maximum(length.reshape((-1,) + (1,) * (x.ndim - 2)).astype(x.dtype), 1)
        out = jnp.sum(x * mask, axis=1) / denom
    elif ptype == "SQRT":
        denom = jnp.sqrt(jnp.maximum(length.reshape((-1,) + (1,) * (x.ndim - 2)).astype(x.dtype), 1))
        out = jnp.sum(x * mask, axis=1) / denom
    elif ptype == "MAX":
        neg = jnp.finfo(x.dtype).min
        out = jnp.max(jnp.where(mask > 0, x, neg), axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(length - 1, 0).astype(jnp.int32).reshape(-1)
        out = jnp.take_along_axis(x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1)[:, 0]
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pooltype {ptype}")
    return one(out)


@register_op("sequence_softmax", nondiff_inputs=["Length"])
def _sequence_softmax(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (length,) = inputs["Length"]
    mask = _mask_from_len(length, x.shape[1], x.dtype)
    logits = jnp.where(mask > 0, x, jnp.finfo(x.dtype).min)
    return one(jax.nn.softmax(logits, axis=1) * mask)


@register_op("sequence_expand", nondiff_inputs=["Length"])
def _sequence_expand(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    return one(jnp.repeat(x, y.shape[1], axis=0).reshape(y.shape[:2] + x.shape[1:]) if x.ndim > 1 else x)


@register_op("sequence_reverse", nondiff_inputs=["Length"])
def _sequence_reverse(ctx, inputs, attrs):
    (x,) = inputs["X"]
    length = inputs.get("Length", [None])[0]
    t = x.shape[1]
    if length is None:
        return {"Y": [jnp.flip(x, axis=1)]}
    idx = jnp.arange(t)[None, :]
    rev = jnp.where(idx < length.reshape(-1, 1), length.reshape(-1, 1) - 1 - idx, idx)
    return {"Y": [jnp.take_along_axis(x, rev.reshape(rev.shape + (1,) * (x.ndim - 2)).astype(jnp.int32), axis=1)]}


@register_op("sequence_concat")
def _sequence_concat(ctx, inputs, attrs):
    xs = inputs["X"]
    return one(jnp.concatenate(xs, axis=1))


@register_op("im2sequence")
def _im2sequence(ctx, inputs, attrs):
    raise NotImplementedError("im2sequence: use conv/patch extraction layers")
