"""Sequence ops — TPU-native replacement for the LoDTensor machinery.

Reference analog: ``paddle/fluid/operators/sequence_ops/`` (15+ LoD-aware ops
over lod_tensor.h variable-length batches). XLA needs static shapes, so the
TPU-native representation is **padded dense [batch, max_len, ...] + explicit
length/mask vars** (SURVEY §5 long-context note). Each sequence op takes a
Length input instead of reading LoD metadata.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import one


def _mask_from_len(length, maxlen, dtype=jnp.float32):
    return (jnp.arange(maxlen)[None, :] < length.reshape(-1, 1)).astype(dtype)


@register_op("sequence_mask", differentiable=False)
def _sequence_mask(ctx, inputs, attrs):
    (x,) = inputs["X"]
    maxlen = attrs.get("maxlen", -1)
    if maxlen is None or maxlen < 0:
        raise ValueError("sequence_mask on TPU requires static maxlen attr")
    from ..core.dtypes import convert_dtype
    dtype = convert_dtype(attrs.get("out_dtype", "int64"))
    return {"Y": [_mask_from_len(x, maxlen, dtype)]}


@register_op("sequence_pool", nondiff_inputs=["Length"])
def _sequence_pool(ctx, inputs, attrs):
    """sequence_pool_op.cc over padded [B, T, ...] + Length."""
    (x,) = inputs["X"]
    (length,) = inputs["Length"]
    ptype = attrs.get("pooltype", "SUM").upper()
    t = x.shape[1]
    mask = _mask_from_len(length, t, x.dtype)
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    if ptype == "SUM":
        out = jnp.sum(x * mask, axis=1)
    elif ptype == "AVERAGE":
        denom = jnp.maximum(length.reshape((-1,) + (1,) * (x.ndim - 2)).astype(x.dtype), 1)
        out = jnp.sum(x * mask, axis=1) / denom
    elif ptype == "SQRT":
        denom = jnp.sqrt(jnp.maximum(length.reshape((-1,) + (1,) * (x.ndim - 2)).astype(x.dtype), 1))
        out = jnp.sum(x * mask, axis=1) / denom
    elif ptype == "MAX":
        neg = jnp.finfo(x.dtype).min
        out = jnp.max(jnp.where(mask > 0, x, neg), axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(length - 1, 0).astype(jnp.int32).reshape(-1)
        out = jnp.take_along_axis(x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1)[:, 0]
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pooltype {ptype}")
    return one(out)


@register_op("sequence_softmax", nondiff_inputs=["Length"])
def _sequence_softmax(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (length,) = inputs["Length"]
    mask = _mask_from_len(length, x.shape[1], x.dtype)
    logits = jnp.where(mask > 0, x, jnp.finfo(x.dtype).min)
    return one(jax.nn.softmax(logits, axis=1) * mask)


@register_op("sequence_expand", nondiff_inputs=["Length"])
def _sequence_expand(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    return one(jnp.repeat(x, y.shape[1], axis=0).reshape(y.shape[:2] + x.shape[1:]) if x.ndim > 1 else x)


@register_op("sequence_reverse", nondiff_inputs=["Length"])
def _sequence_reverse(ctx, inputs, attrs):
    (x,) = inputs["X"]
    length = inputs.get("Length", [None])[0]
    t = x.shape[1]
    if length is None:
        return {"Y": [jnp.flip(x, axis=1)]}
    idx = jnp.arange(t)[None, :]
    rev = jnp.where(idx < length.reshape(-1, 1), length.reshape(-1, 1) - 1 - idx, idx)
    return {"Y": [jnp.take_along_axis(x, rev.reshape(rev.shape + (1,) * (x.ndim - 2)).astype(jnp.int32), axis=1)]}


@register_op("sequence_concat")
def _sequence_concat(ctx, inputs, attrs):
    xs = inputs["X"]
    return one(jnp.concatenate(xs, axis=1))


@register_op("im2sequence")
def _im2sequence(ctx, inputs, attrs):
    """im2sequence_op.h:33: extract kernel patches of NCHW images into
    sequence rows — Out[N·OH·OW, C·kh·kw], rows scanning each image's
    output positions row-major, each row the (C, kh, kw)-ordered patch
    (the im2col layout). Every image yields the same static OH·OW rows —
    the padded-world stand-in for the reference's per-image LoD."""
    (x,) = inputs["X"]
    kh, kw = attrs["kernels"]
    sh, sw = attrs.get("strides", [1, 1])
    pads = list(attrs.get("paddings", [0, 0, 0, 0]))  # up, left, down, right
    n, c, h, w = x.shape
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw),
        [(pads[0], pads[2]), (pads[1], pads[3])],
        dimension_numbers=lax.conv_dimension_numbers(
            x.shape, (1, c, kh, kw), ("NCHW", "OIHW", "NCHW")))
    _, ckk, oh, ow = patches.shape            # feature dim = C·kh·kw
    out = jnp.transpose(patches, (0, 2, 3, 1)).reshape(n * oh * ow, ckk)
    return one(out)


@register_op("sequence_pad", nondiff_inputs=["Length", "PadValue"])
def _sequence_pad(ctx, inputs, attrs):
    """sequence_pad_op.cc: re-pad [B, T, ...] + Length to `padded_length`
    time steps filled with PadValue beyond each length."""
    (x,) = inputs["X"]
    (pad_value,) = inputs["PadValue"]
    (length,) = inputs["Length"]
    padded_len = attrs.get("padded_length", -1)
    t = x.shape[1]
    if padded_len is None or padded_len < 0:
        padded_len = t
    if padded_len >= t:
        pad = [(0, 0), (0, padded_len - t)] + [(0, 0)] * (x.ndim - 2)
        out = jnp.pad(x, pad)
    else:
        out = x[:, :padded_len]
    mask = _mask_from_len(length, padded_len, jnp.bool_)
    mask = mask.reshape(mask.shape + (1,) * (out.ndim - 2))
    pv = jnp.asarray(pad_value, out.dtype).reshape((1, 1) + (1,) * (out.ndim - 2))
    out = jnp.where(mask, out, pv)
    out_len = jnp.minimum(length, padded_len)
    return {"Out": [out], "Length": [out_len]}


@register_op("sequence_unpad", nondiff_inputs=["Length"])
def _sequence_unpad(ctx, inputs, attrs):
    """sequence_unpad_op.cc: drop the pad region. Static shapes keep
    [B, T, ...]; padding positions are zeroed (the dense analog of the
    reference's flattened LoD output)."""
    (x,) = inputs["X"]
    (length,) = inputs["Length"]
    mask = _mask_from_len(length, x.shape[1], x.dtype)
    return one(x * mask.reshape(mask.shape + (1,) * (x.ndim - 2)))


@register_op("sequence_conv", nondiff_inputs=["Length"])
def _sequence_conv(ctx, inputs, attrs):
    """sequence_conv_op.cc: context-window projection over the time axis.
    Gathers a [ctx·D] window per step (zero beyond the sequence) and hits
    the MXU with one [B·T, ctx·D]×[ctx·D, M] matmul — the im2col pattern
    of the reference's math/context_project.h."""
    (x,) = inputs["X"]                      # [B, T, D]
    (filt,) = inputs["Filter"]              # [ctx*D, M]
    length = inputs.get("Length", [None])[0]
    ctx_len = int(attrs.get("contextLength", 3))
    ctx_start = int(attrs.get("contextStart", -((ctx_len - 1) // 2)))
    b, t, d = x.shape
    if length is not None:
        mask = _mask_from_len(length, t, x.dtype)
        x = x * mask[..., None]
    cols = []
    for j in range(ctx_len):
        off = ctx_start + j
        shifted = jnp.roll(x, -off, axis=1)
        idx = jnp.arange(t) + off
        valid = ((idx >= 0) & (idx < t))[None, :, None]
        cols.append(jnp.where(valid, shifted, 0.0))
    windows = jnp.concatenate(cols, axis=-1)            # [B, T, ctx*D]
    out = jnp.einsum("btc,cm->btm", windows, filt,
                     preferred_element_type=jnp.float32).astype(x.dtype)
    if length is not None:
        out = out * mask[..., None]
    return one(out)


@register_op("sequence_slice", nondiff_inputs=["Offset", "Length"])
def _sequence_slice(ctx, inputs, attrs):
    """sequence_slice_op.cc: per-row (offset, length) slice along time.
    Output stays [B, T, ...]; positions ≥ length are zeroed."""
    (x,) = inputs["X"]
    (offset,) = inputs["Offset"]
    (length,) = inputs["Length"]
    t = x.shape[1]
    idx = offset.reshape(-1, 1).astype(jnp.int32) + jnp.arange(t)[None, :]
    idx_c = jnp.clip(idx, 0, t - 1)
    out = jnp.take_along_axis(
        x, idx_c.reshape(idx_c.shape + (1,) * (x.ndim - 2)), axis=1)
    mask = _mask_from_len(length, t, x.dtype)
    return one(out * mask.reshape(mask.shape + (1,) * (x.ndim - 2)))


@register_op("sequence_erase", differentiable=False)
def _sequence_erase(ctx, inputs, attrs):
    """sequence_erase_op.cc: remove tokens ∈ `tokens`, left-compact the
    rest. Fixed-shape: output stays [B, T] zero-padded, new lengths out."""
    (x,) = inputs["X"]                      # [B, T] int
    length = inputs.get("Length", [None])[0]
    tokens = jnp.asarray(attrs.get("tokens", []), x.dtype)
    b, t = x.shape
    in_range = (jnp.arange(t)[None, :] < length.reshape(-1, 1)) \
        if length is not None else jnp.ones((b, t), bool)
    keep = in_range & ~jnp.isin(x, tokens)
    new_pos = jnp.cumsum(keep, axis=1) - 1                # target index
    rows = jnp.broadcast_to(jnp.arange(b)[:, None], (b, t))
    # dropped tokens contribute 0 at the previous kept slot (or index -1,
    # dropped by mode="drop"); kept tokens land left-compacted
    out = jnp.zeros_like(x).at[rows, new_pos].add(
        jnp.where(keep, x, 0), mode="drop")
    new_len = jnp.sum(keep, axis=1).astype(
        length.dtype if length is not None else jnp.int32)
    return {"Out": [out], "Length": [new_len]}


@register_op("sequence_expand_as", nondiff_inputs=["Y", "Length"])
def _sequence_expand_as(ctx, inputs, attrs):
    """sequence_expand_as_op.cc: broadcast each row of X across Y's time
    axis (x_i repeated per step of sequence i), masked by Y's length."""
    (x,) = inputs["X"]                      # [B, ...]
    (y,) = inputs["Y"]                      # [B, T, ...]
    length = inputs.get("Length", [None])[0]
    t = y.shape[1]
    out = jnp.broadcast_to(x[:, None], (x.shape[0], t) + x.shape[1:])
    if length is not None:
        mask = _mask_from_len(length, t, out.dtype)
        out = out * mask.reshape(mask.shape + (1,) * (out.ndim - 2))
    return one(out)


@register_op("sequence_enumerate", differentiable=False)
def _sequence_enumerate(ctx, inputs, attrs):
    """sequence_enumerate_op.cc: sliding win_size-grams along time;
    positions past the end filled with pad_value."""
    (x,) = inputs["X"]                      # [B, T] int
    length = inputs.get("Length", [None])[0]
    win = int(attrs.get("win_size", 2))
    pad_value = attrs.get("pad_value", 0)
    b, t = x.shape
    lens = length.reshape(-1, 1) if length is not None else t
    grams = []
    for j in range(win):
        idx = jnp.arange(t) + j
        shifted = jnp.roll(x, -j, axis=1)
        valid = idx[None, :] < (lens if length is not None else t)
        grams.append(jnp.where(valid, shifted, pad_value))
    return one(jnp.stack(grams, axis=-1))   # [B, T, win]


@register_op("sequence_reshape", nondiff_inputs=["Length"])
def _sequence_reshape(ctx, inputs, attrs):
    """sequence_reshape_op.cc: re-chunk each sequence's row-major stream
    of [T, D] into [T·D/new_dim, new_dim]; tail padding stays contiguous
    so a plain reshape is exact. New length = len·D/new_dim."""
    (x,) = inputs["X"]                      # [B, T, D]
    length = inputs.get("Length", [None])[0]
    new_dim = int(attrs["new_dim"])
    b, t, d = x.shape
    if (t * d) % new_dim:
        raise ValueError(f"sequence_reshape: T*D={t*d} not divisible by "
                         f"new_dim={new_dim}")
    out = x.reshape(b, (t * d) // new_dim, new_dim)
    outs = {"Out": [out]}
    if length is not None:
        outs["Length"] = [(length * d) // new_dim]
    return outs


@register_op("sequence_scatter", nondiff_inputs=["Ids", "Length"])
def _sequence_scatter(ctx, inputs, attrs):
    """sequence_scatter_op.cc: out[b, ids[b,s]] += updates[b,s] for
    s < length[b] (per-sequence scatter-add into a dense row)."""
    (x,) = inputs["X"]                      # [B, N]
    (ids,) = inputs["Ids"]                  # [B, S] int
    (upd,) = inputs["Updates"]              # [B, S]
    length = inputs.get("Length", [None])[0]
    b, s = ids.shape
    if length is not None:
        valid = jnp.arange(s)[None, :] < length.reshape(-1, 1)
        upd = jnp.where(valid, upd, 0)
    rows = jnp.broadcast_to(jnp.arange(b)[:, None], (b, s))
    return one(x.at[rows, ids.astype(jnp.int32)].add(upd))


@register_op("sequence_topk_avg_pooling", differentiable=False,
             nondiff_inputs=["Length"])
def _sequence_topk_avg_pooling(ctx, inputs, attrs):
    """sequence_topk_avg_pooling_op.cc: per (batch, channel), average of
    the top-k values over masked time steps, one column per k in `topks`."""
    (x,) = inputs["X"]                      # [B, C, T]
    length = inputs.get("Length", [None])[0]
    topks = list(attrs.get("topks", [1]))
    b, c, t = x.shape
    if length is not None:
        mask = _mask_from_len(length, t, x.dtype)[:, None, :]
        x = jnp.where(mask > 0, x, jnp.finfo(x.dtype).min)
    sorted_desc = -jnp.sort(-x, axis=-1)                   # [B, C, T]
    cols = []
    for k in topks:
        k = min(int(k), t)
        top = sorted_desc[..., :k]
        if length is not None:
            # only count positions < min(k, len)
            kk = jnp.minimum(length, k).reshape(-1, 1, 1).astype(x.dtype)
            valid = jnp.arange(k)[None, None, :] < kk
            top = jnp.where(valid, top, 0.0)
            cols.append(jnp.sum(top, -1) / jnp.maximum(kk[..., 0], 1))
        else:
            cols.append(jnp.mean(top, -1))
    return one(jnp.stack(cols, axis=-1).reshape(b, c * len(topks)))
