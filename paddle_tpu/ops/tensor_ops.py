"""Tensor manipulation + creation ops.

Reference analog: reshape_op.cc, transpose_op.cc, concat_op.cc, split_op.cc,
stack_op.cc, gather_op.cc, scatter_op.cc, pad_op.cc, cast_op.cc,
fill_constant_op.cc, uniform_random_op.cc, gaussian_random_op.cc, assign_op.cc,
expand_op.cc, slice_op.cc, squeeze_op.cc, unsqueeze_op.cc, shape_op.cc,
range_op.cc, eye_op.cc (SURVEY §2.1 operator library row).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.dtypes import convert_dtype
from ..core.registry import register_op
from .common import one


@register_op("reshape")
def _reshape(ctx, inputs, attrs):
    (x,) = inputs["X"]
    shape = list(attrs["shape"])
    # paddle rule: 0 means copy input dim at that position; -1 infers
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return one(x.reshape(shape))


@register_op("transpose")
def _transpose(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.transpose(x, attrs["axis"]))


@register_op("concat")
def _concat(ctx, inputs, attrs):
    xs = inputs["X"]
    return one(jnp.concatenate(xs, axis=attrs.get("axis", 0)))


@register_op("split")
def _split(ctx, inputs, attrs):
    (x,) = inputs["X"]
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = attrs.get("sections")
    if sections:
        idx = []
        acc = 0
        for s in sections[:-1]:
            acc += s
            idx.append(acc)
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register_op("stack")
def _stack(ctx, inputs, attrs):
    xs = inputs["X"]
    return {"Y": [jnp.stack(xs, axis=attrs.get("axis", 0))]}


@register_op("unstack")
def _unstack(ctx, inputs, attrs):
    (x,) = inputs["X"]
    axis = attrs.get("axis", 0)
    num = attrs.get("num", x.shape[axis])
    outs = [jnp.squeeze(s, axis=axis) for s in jnp.split(x, num, axis=axis)]
    return {"Y": outs}


@register_op("squeeze")
def _squeeze(ctx, inputs, attrs):
    (x,) = inputs["X"]
    axes = attrs.get("axes", [])
    if not axes:
        return one(jnp.squeeze(x))
    return one(jnp.squeeze(x, axis=tuple(axes)))


@register_op("unsqueeze")
def _unsqueeze(ctx, inputs, attrs):
    (x,) = inputs["X"]
    out = x
    for a in sorted(attrs["axes"]):
        out = jnp.expand_dims(out, a)
    return one(out)


@register_op("flatten")
def _flatten(ctx, inputs, attrs):
    (x,) = inputs["X"]
    axis = attrs.get("axis", 1)
    lead = 1
    for s in x.shape[:axis]:
        lead *= s
    return one(x.reshape((lead, -1)))


@register_op("flatten2")
def _flatten2(ctx, inputs, attrs):
    (x,) = inputs["X"]
    axis = attrs.get("axis", 1)
    lead = 1
    for s in x.shape[:axis]:
        lead *= s
    return {"Out": [x.reshape((lead, -1))], "XShape": [jnp.zeros((0,) + x.shape)]}


@register_op("expand")
def _expand(ctx, inputs, attrs):
    (x,) = inputs["X"]
    times = attrs["expand_times"]
    return one(jnp.tile(x, times))


@register_op("expand_as")
def _expand_as(ctx, inputs, attrs, ):
    (x,) = inputs["X"]
    (t,) = inputs["target_tensor"]
    times = [ts // xs for ts, xs in zip(t.shape, x.shape)]
    return one(jnp.tile(x, times))


@register_op("tile")
def _tile(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.tile(x, attrs["repeat_times"]))


@register_op("slice")
def _slice(ctx, inputs, attrs):
    (x,) = inputs["Input"]
    axes = attrs["axes"]
    starts = list(attrs["starts"])
    ends = list(attrs["ends"])
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    return one(x[tuple(idx)])


@register_op("strided_slice")
def _strided_slice(ctx, inputs, attrs):
    (x,) = inputs["Input"]
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(attrs["axes"], attrs["starts"], attrs["ends"], attrs["strides"]):
        idx[a] = slice(s, e, st)
    return one(x[tuple(idx)])


@register_op("gather", nondiff_inputs=["Index"])
def _gather(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (index,) = inputs["Index"]
    idx = index[..., 0] if index.ndim == 2 and index.shape[-1] == 1 else index
    return one(jnp.take(x, idx, axis=attrs.get("axis", 0)))


@register_op("gather_nd", nondiff_inputs=["Index"])
def _gather_nd(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (index,) = inputs["Index"]
    return one(x[tuple(jnp.moveaxis(index, -1, 0))])


@register_op("scatter", nondiff_inputs=["Ids"])
def _scatter(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (ids,) = inputs["Ids"]
    (updates,) = inputs["Updates"]
    idx = ids[..., 0] if ids.ndim == 2 and ids.shape[-1] == 1 else ids
    if attrs.get("overwrite", True):
        return one(x.at[idx].set(updates))
    return one(x.at[idx].add(updates))


@register_op("scatter_nd_add", nondiff_inputs=["Index"])
def _scatter_nd_add(ctx, inputs, attrs):
    (x,) = inputs["X"]
    (index,) = inputs["Index"]
    (updates,) = inputs["Updates"]
    return one(x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates))


@register_op("pad")
def _pad(ctx, inputs, attrs):
    (x,) = inputs["X"]
    p = attrs["paddings"]
    pairs = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return one(jnp.pad(x, pairs, constant_values=attrs.get("pad_value", 0.0)))


@register_op("pad2d")
def _pad2d(ctx, inputs, attrs):
    (x,) = inputs["X"]
    p = attrs["paddings"]  # [top, bottom, left, right]
    mode = attrs.get("mode", "constant")
    pairs = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if mode == "constant":
        return one(jnp.pad(x, pairs, constant_values=attrs.get("pad_value", 0.0)))
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return one(jnp.pad(x, pairs, mode=jmode))


@register_op("cast")
def _cast(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(x.astype(convert_dtype(attrs["out_dtype"])))


@register_op("assign")
def _assign(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(x)


@register_op("shape", differentiable=False)
def _shape(ctx, inputs, attrs):
    (x,) = inputs["Input"]
    return one(jnp.array(x.shape, dtype=jnp.int32))


@register_op("fill_constant", differentiable=False)
def _fill_constant(ctx, inputs, attrs):
    shape = attrs.get("shape", [1])
    from ..core.dtypes import canonical_dtype
    dtype = canonical_dtype(attrs.get("dtype", "float32"))
    return one(jnp.full(shape, attrs.get("value", 0.0), dtype=dtype))


@register_op("fill_constant_batch_size_like", differentiable=False)
def _fill_constant_bsl(ctx, inputs, attrs):
    (ref,) = inputs["Input"]
    shape = list(attrs["shape"])
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    dtype = convert_dtype(attrs.get("dtype", "float32"))
    return one(jnp.full(shape, attrs.get("value", 0.0), dtype=dtype))


@register_op("fill_zeros_like", differentiable=False)
def _fill_zeros_like(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(jnp.zeros_like(x))


@register_op("assign_value", differentiable=False)
def _assign_value(ctx, inputs, attrs):
    values = attrs["values"]
    dtype = convert_dtype(attrs.get("dtype", "float32"))
    return one(jnp.asarray(values, dtype=dtype).reshape(attrs["shape"]))


@register_op("uniform_random", differentiable=False)
def _uniform_random(ctx, inputs, attrs):
    shape = attrs["shape"]
    dtype = convert_dtype(attrs.get("dtype", "float32"))
    lo, hi = attrs.get("min", -1.0), attrs.get("max", 1.0)
    return one(jax.random.uniform(ctx.rng(), shape, dtype=jnp.float32, minval=lo, maxval=hi).astype(dtype))


@register_op("gaussian_random", differentiable=False)
def _gaussian_random(ctx, inputs, attrs):
    shape = attrs["shape"]
    dtype = convert_dtype(attrs.get("dtype", "float32"))
    mean, std = attrs.get("mean", 0.0), attrs.get("std", 1.0)
    return one((mean + std * jax.random.normal(ctx.rng(), shape, dtype=jnp.float32)).astype(dtype))


@register_op("truncated_gaussian_random", differentiable=False)
def _truncated_gaussian_random(ctx, inputs, attrs):
    shape = attrs["shape"]
    dtype = convert_dtype(attrs.get("dtype", "float32"))
    mean, std = attrs.get("mean", 0.0), attrs.get("std", 1.0)
    r = jax.random.truncated_normal(ctx.rng(), -2.0, 2.0, shape, dtype=jnp.float32)
    return one((mean + std * r).astype(dtype))


@register_op("randint", differentiable=False)
def _randint(ctx, inputs, attrs):
    shape = attrs["shape"]
    return one(jax.random.randint(ctx.rng(), shape, attrs.get("low", 0), attrs.get("high"),
                                  dtype=convert_dtype(attrs.get("dtype", "int64"))))


@register_op("range", differentiable=False)
def _range(ctx, inputs, attrs):
    # static-shape requirement: bounds must be trace-time constants — passed
    # via attrs by callers that know them, else concretized from the inputs
    import numpy as np
    start = attrs.get("start")
    end = attrs.get("end")
    step = attrs.get("step")
    dtype = inputs["Start"][0].dtype if inputs.get("Start") else "float32"
    if start is None:
        start = np.asarray(inputs["Start"][0]).item()
    if end is None:
        end = np.asarray(inputs["End"][0]).item()
    if step is None:
        step = np.asarray(inputs["Step"][0]).item()
    return one(jnp.arange(start, end, step, dtype=dtype))


@register_op("linspace", differentiable=False)
def _linspace(ctx, inputs, attrs):
    import numpy as np
    # static num comes via attrs when the caller knows it (the output shape
    # must be trace-time static); start/stop may stay traced
    start = attrs.get("start")
    stop = attrs.get("stop")
    num = attrs.get("num")
    if start is None:
        start = inputs["Start"][0].reshape(())
    if stop is None:
        stop = inputs["Stop"][0].reshape(())
    if num is None:
        num = int(np.asarray(inputs["Num"][0]).item())
    return one(jnp.linspace(start, stop, int(num)))


@register_op("eye", differentiable=False)
def _eye(ctx, inputs, attrs):
    return one(jnp.eye(attrs["num_rows"], attrs.get("num_columns"),
                       dtype=convert_dtype(attrs.get("dtype", "float32"))))


@register_op("diag", differentiable=False)
def _diag(ctx, inputs, attrs):
    (d,) = inputs["Diagonal"]
    return one(jnp.diag(d))


@register_op("shard_index", differentiable=False)
def _shard_index(ctx, inputs, attrs):
    (x,) = inputs["X"]
    index_num = attrs["index_num"]
    nshards = attrs["nshards"]
    shard_id = attrs["shard_id"]
    ignore_value = attrs.get("ignore_value", -1)
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    return one(jnp.where(in_shard, x % shard_size, ignore_value))


@register_op("where", nondiff_inputs=["Condition"])
def _where(ctx, inputs, attrs):
    (cond,) = inputs["Condition"]
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    return one(jnp.where(cond, x, y))


@register_op("where_index", differentiable=False)
def _where_index(ctx, inputs, attrs):
    (cond,) = inputs["Condition"]
    # dynamic-shape op: XLA needs static sizes; return padded indices with a
    # count (TPU-native contract documented in layers.where)
    idx = jnp.stack(jnp.nonzero(cond, size=cond.size, fill_value=-1), axis=-1)
    return one(idx)


@register_op("increment", differentiable=False)
def _increment(ctx, inputs, attrs):
    (x,) = inputs["X"]
    return one(x + attrs.get("step", 1.0))


@register_op("py_func",
             differentiable=lambda attrs: attrs.get("backward_func") is not None)
def _py_func(ctx, inputs, attrs):
    """py_func_op.cc analog — escape hatch to host Python via pure_callback.

    With a ``backward_func`` the op is differentiable, matching the
    reference grad contract (py_func_op.cc:198 PyFuncOpGradDescMaker): the
    backward callable receives (non-skipped forward inputs, non-skipped
    forward outputs, output grads) positionally and returns one grad per
    forward input — ``None`` meaning "input grad not needed" lowers to
    zeros.  Both sides are host callbacks; the pairing is a jax.custom_vjp
    so the tape-walk vjp in the executor differentiates straight through.
    """
    fn = attrs["func"]
    out_shapes = attrs["out_shapes"]
    out_dtypes = [convert_dtype(d) for d in attrs["out_dtypes"]]
    xs = inputs.get("X", [])
    result_shape = [jax.ShapeDtypeStruct(tuple(s), d) for s, d in zip(out_shapes, out_dtypes)]
    bwd = attrs.get("backward_func")
    if bwd is None:
        outs = jax.pure_callback(fn, result_shape, *xs)
        return {"Out": list(outs)}

    # indices of fwd inputs/outputs the backward callable wants
    # (skip_vars_in_backward_input resolved to positions by the layer)
    keep_in = attrs.get("bwd_keep_in")
    keep_out = attrs.get("bwd_keep_out")
    keep_in = list(range(len(xs))) if keep_in is None else list(keep_in)
    keep_out = (list(range(len(result_shape))) if keep_out is None
                else list(keep_out))
    in_sds = tuple(jax.ShapeDtypeStruct(x.shape, x.dtype) for x in xs)

    def _host_bwd(*args):
        grads = bwd(*args)
        if not isinstance(grads, (list, tuple)):
            grads = (grads,)
        if len(grads) != len(in_sds):
            raise ValueError(
                f"py_func backward_func returned {len(grads)} grads for "
                f"{len(in_sds)} forward inputs")
        return tuple(
            np.zeros(sd.shape, sd.dtype) if g is None
            else np.asarray(g, sd.dtype).reshape(sd.shape)
            for g, sd in zip(grads, in_sds))

    @jax.custom_vjp
    def call(*args):
        return tuple(jax.pure_callback(fn, result_shape, *args))

    def call_fwd(*args):
        outs = tuple(jax.pure_callback(fn, result_shape, *args))
        res = (tuple(args[i] for i in keep_in)
               + tuple(outs[i] for i in keep_out))
        return outs, res

    def call_bwd(res, gouts):
        return tuple(jax.pure_callback(_host_bwd, in_sds, *res, *gouts))

    call.defvjp(call_fwd, call_bwd)
    return {"Out": list(call(*xs))}


@register_op("print", differentiable=False)
def _print(ctx, inputs, attrs):
    (x,) = inputs["In"]
    jax.debug.print(attrs.get("message", "") + "{x}", x=x)
    return one(x)
