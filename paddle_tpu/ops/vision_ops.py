"""Vision extras round 2 — transposed 3D/depthwise convs, deformable conv,
unfold (im2col), indexed 3D max-pool, random_crop, FSP matrix.

References: conv_transpose_op.cc (conv3d_transpose / depthwise variants),
deformable_conv_op.cc, unfold_op.cc, pool_with_index_op.cc
(max_pool3d_with_index), random_crop_op.cc, fsp_op.cc. Redesigned on
lax.conv_general_dilated / reduce_window / gather — no im2col scratch
buffers, XLA owns the tiling.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import one


def _tup(v, n=2):
    v = list(v) if isinstance(v, (list, tuple)) else [v]
    if len(v) == 1:
        v = v * n
    return tuple(int(x) for x in v[:n])


@register_op("conv3d_transpose")
def _conv3d_transpose(ctx, inputs, attrs):
    """conv_transpose_op.cc 3-D case — shares the fractionally-strided
    formulation with conv2d_transpose via nn_ops.conv_transpose_nd
    (including output_size → trailing output padding resolution)."""
    from .nn_ops import _out_pads_from_output_size, conv_transpose_nd
    (x,) = inputs["Input"]
    (w,) = inputs["Filter"]        # [C_in, C_out/groups, D, H, W]
    return one(conv_transpose_nd(
        x, w, _tup(attrs.get("strides", [1, 1, 1]), 3),
        _tup(attrs.get("paddings", [0, 0, 0]), 3),
        _tup(attrs.get("dilations", [1, 1, 1]), 3),
        int(attrs.get("groups", 1)),
        out_pads=_out_pads_from_output_size(x, w, attrs, 3)))


@register_op("depthwise_conv2d_transpose")
def _depthwise_conv2d_transpose(ctx, inputs, attrs):
    from .nn_ops import _conv2d_transpose
    attrs = dict(attrs)
    (x,) = inputs["Input"]
    attrs["groups"] = x.shape[1]
    return _conv2d_transpose(ctx, inputs, attrs)


@register_op("unfold")
def _unfold(ctx, inputs, attrs):
    """unfold_op.cc (im2col as an op): [N, C, H, W] →
    [N, C*kh*kw, L] where L = out_h*out_w. Built from
    lax.conv_general_dilated_patches (XLA extracts patches natively)."""
    (x,) = inputs["X"]
    kh, kw = _tup(attrs["kernel_sizes"])
    sh, sw = _tup(attrs.get("strides", [1, 1]))
    pads = attrs.get("paddings", [0, 0, 0, 0])
    if len(pads) == 2:
        pads = [pads[0], pads[1], pads[0], pads[1]]
    dh, dw = _tup(attrs.get("dilations", [1, 1]))
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw),
        [(pads[0], pads[2]), (pads[1], pads[3])],
        rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))   # [N, C*kh*kw, OH, OW]
    n, ckk = patches.shape[0], patches.shape[1]
    return {"Y": [patches.reshape(n, ckk, -1)]}


@register_op("deformable_conv")
def _deformable_conv(ctx, inputs, attrs):
    """deformable_conv_op.cc (DCNv2): sample the input at offset-shifted
    kernel taps with bilinear interpolation × modulation mask, then a 1-step
    matmul against the filter. Gather-based; offsets stay differentiable."""
    (x,) = inputs["Input"]          # [N, C, H, W]
    (offset,) = inputs["Offset"]    # [N, 2*dg*kh*kw, OH, OW]
    (w,) = inputs["Filter"]         # [Cout, C/groups, kh, kw]
    mask = (inputs.get("Mask") or [None])[0]   # [N, dg*kh*kw, OH, OW]
    sh, sw = _tup(attrs.get("strides", [1, 1]))
    ph, pw = _tup(attrs.get("paddings", [0, 0]))
    dh, dw = _tup(attrs.get("dilations", [1, 1]))
    groups = int(attrs.get("groups", 1))
    dg = int(attrs.get("deformable_groups", 1))
    n, c, h, wd = x.shape
    cout, _, kh, kw = w.shape
    oh = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    ow = (wd + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1

    # base sampling grid per tap: [kh*kw, OH, OW]
    oy = jnp.arange(oh) * sh - ph
    ox = jnp.arange(ow) * sw - pw
    ky = jnp.arange(kh) * dh
    kx = jnp.arange(kw) * dw
    base_y = oy[None, :, None] + ky.repeat(kw)[:, None, None]   # [K, OH, 1]
    base_x = ox[None, None, :] + jnp.tile(kx, kh)[:, None, None]

    off = offset.reshape(n, dg, kh * kw, 2, oh, ow)
    py = base_y[None, None] + off[:, :, :, 0]                   # [N, dg, K, OH, OW]
    px = base_x[None, None] + off[:, :, :, 1]

    def bilinear(img, yy, xx):
        """img [C, H, W]; yy/xx [...] → [C, ...]"""
        y0 = jnp.floor(yy); x0 = jnp.floor(xx)
        wy = yy - y0; wx = xx - x0
        vals = 0.0
        for (yi, wyi) in ((y0, 1 - wy), (y0 + 1, wy)):
            for (xi, wxi) in ((x0, 1 - wx), (x0 + 1, wx)):
                inb = (yi >= 0) & (yi < img.shape[1]) & (xi >= 0) & (xi < img.shape[2])
                yc = jnp.clip(yi, 0, img.shape[1] - 1).astype(jnp.int32)
                xc = jnp.clip(xi, 0, img.shape[2] - 1).astype(jnp.int32)
                v = img[:, yc, xc]
                vals = vals + v * (wyi * wxi * inb)[None]
        return vals

    cg = c // dg                     # channels per deformable group

    def per_image(img, yy, xx, mk):
        # sample: for each dg, channels [dg*cg:(dg+1)*cg] share offsets
        cols = []
        for g in range(dg):
            sub = img[g * cg:(g + 1) * cg]                    # [cg, H, W]
            s = bilinear(sub, yy[g], xx[g])                   # [cg, K, OH, OW]
            if mk is not None:
                s = s * mk[g][None]
            cols.append(s)
        return jnp.concatenate(cols)                          # [C, K, OH, OW]

    mk = mask.reshape(n, dg, kh * kw, oh, ow) if mask is not None else None
    cols = jax.vmap(per_image)(x, py, px,
                               mk if mk is not None else jnp.ones((n, dg, kh * kw, oh, ow), x.dtype))
    # cols: [N, C, K, OH, OW] → grouped matmul with w [Cout, C/groups * K]
    cpg = c // groups
    opg = cout // groups
    wg = w.reshape(groups, opg, cpg * kh * kw)
    cols = cols.reshape(n, groups, cpg * kh * kw, oh * ow)
    out = jnp.einsum("gok,ngkl->ngol", wg, cols)
    return {"Output": [out.reshape(n, cout, oh, ow)]}


@register_op("max_pool3d_with_index")
def _max_pool3d_with_index(ctx, inputs, attrs):
    """pool_with_index_op.cc 3-D: max pool + flat argmax index per window."""
    (x,) = inputs["X"]
    ks = _tup(attrs["ksize"], 3)
    st = _tup(attrs.get("strides", ks), 3)
    pd = _tup(attrs.get("paddings", [0, 0, 0]), 3)
    n, c, d, h, w = x.shape
    pad = ((0, 0), (0, 0)) + tuple((p, p) for p in pd)
    dims = (1, 1) + ks
    strides = (1, 1) + st
    out = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)
    # indices: -inf-pad manually (patches pads with 0, which would win over
    # negative inputs), argmax the within-window offset, then reconstruct
    # the flat d*h*w index arithmetically — integer-exact at any size, and
    # outside the grad tape (the max itself carries the gradient)
    xs = lax.stop_gradient(x).reshape(n * c, 1, d, h, w)
    # finite lowest value, not -inf: patches lowers to a one-hot conv and
    # 0 * -inf = nan would poison every padded window
    xs = jnp.pad(xs, ((0, 0), (0, 0)) + tuple((p, p) for p in pd),
                 constant_values=float(jnp.finfo(x.dtype).min))
    xp = lax.conv_general_dilated_patches(
        xs, ks, st, ((0, 0), (0, 0), (0, 0)),
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    k = jnp.argmax(xp, axis=1)                       # [N*C, OD, OH, OW]
    kd, kh, kw = ks
    kd_i = k // (kh * kw)
    kh_i = (k // kw) % kh
    kw_i = k % kw
    od, ohh, oww = out.shape[2:]
    oz = jnp.arange(od)[:, None, None] * st[0] - pd[0]
    oy = jnp.arange(ohh)[None, :, None] * st[1] - pd[1]
    ox = jnp.arange(oww)[None, None, :] * st[2] - pd[2]
    idx = ((oz + kd_i) * h + (oy + kh_i)) * w + (ox + kw_i)
    return {"Out": [out], "Mask": [idx.reshape(out.shape).astype(jnp.int32)]}


@register_op("random_crop", differentiable=False)
def _random_crop(ctx, inputs, attrs):
    """random_crop_op.cc: crop a random window of `shape` from the trailing
    dims of X (per batch element)."""
    (x,) = inputs["X"]
    shape = [int(s) for s in attrs["shape"]]
    nd = len(shape)
    lead = x.shape[:x.ndim - nd]
    maxs = [x.shape[x.ndim - nd + i] - shape[i] for i in range(nd)]
    key = ctx.rng()
    nbatch = 1
    for s in lead:
        nbatch *= s
    keys = jax.random.split(key, nbatch * nd).reshape(nbatch, nd, 2)
    xb = x.reshape((nbatch,) + x.shape[x.ndim - nd:])

    def crop_one(img, ks):
        starts = [jax.random.randint(ks[i], (), 0, maxs[i] + 1) for i in range(nd)]
        return lax.dynamic_slice(img, starts, shape)

    out = jax.vmap(crop_one)(xb, keys)
    return one(out.reshape(lead + tuple(shape)))


@register_op("fsp")
def _fsp(ctx, inputs, attrs):
    """fsp_op.cc (flow-of-solution-procedure matrix for distillation):
    G[i,j] = mean_hw X[:,i,h,w] * Y[:,j,h,w] → [N, Cx, Cy]."""
    (x,) = inputs["X"]
    (y,) = inputs["Y"]
    hw = x.shape[2] * x.shape[3]
    out = jnp.einsum("nchw,ndhw->ncd", x, y) / hw
    return one(out)


@register_op("similarity_focus", differentiable=False)
def _similarity_focus(ctx, inputs, attrs):
    """similarity_focus_op.cc: build a 0/1 focus mask selecting, for each
    (axis, index) slice, the per-channel max positions across the indexed
    slice of X [N, C, H, W]."""
    (x,) = inputs["X"]
    axis = int(attrs.get("axis", 1))
    indexes = [int(i) for i in attrs.get("indexes", [0])]
    n, c, h, w = x.shape
    out = jnp.zeros_like(x)
    for ind in indexes:
        if axis == 1:
            sl = x[:, ind]                          # [N, H, W]
            flat = sl.reshape(n, -1)
            pos = jnp.argmax(flat, axis=1)
            hy, wx = pos // w, pos % w
            mask = jnp.zeros((n, h, w), x.dtype).at[jnp.arange(n), hy, wx].set(1.0)
            out = jnp.maximum(out, mask[:, None, :, :])
        elif axis == 2:
            sl = x[:, :, ind]                       # [N, C, W]
            pos = jnp.argmax(sl, axis=2)            # [N, C]
            mask = jax.nn.one_hot(pos, w, dtype=x.dtype)   # [N, C, W]
            out = jnp.maximum(out, mask[:, :, None, :])
        else:
            sl = x[:, :, :, ind]
            pos = jnp.argmax(sl, axis=2)
            mask = jax.nn.one_hot(pos, h, dtype=x.dtype)
            out = jnp.maximum(out, mask[:, :, :, None])
    return one(out)
