"""Optimizer zoo for static-graph training.

Reference analog: ``python/paddle/fluid/optimizer.py`` (Optimizer base :50 —
minimize → append_backward + _create_optimization_pass; 13 optimizers;
SURVEY §2.3). Accumulators are persistable vars initialized in the startup
program; each param gets one update op consuming ``param@GRAD``.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from .core.backward import append_backward
from .core.dtypes import dtype_str
from .core.program import (Parameter, Program, Variable, default_main_program,
                           default_startup_program, grad_var_name)
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from .regularizer import append_regularization_ops


class Optimizer:
    """Base optimizer (optimizer.py:50)."""

    def __init__(self, learning_rate, regularization=None, name: Optional[str] = None,
                 grad_clip=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name or type(self).__name__
        self._accumulators = {}
        self._lr_var = None
        self.helper = None
        self.type = "optimizer"
        # deferred row updates (ops/deferred_rows.py): set by subclasses
        # that accept the deferred_rows kwarg
        self._deferred_rows = None
        self._deferred_applied = []
        self.fold_program = None
        # packed row-major tables (ops/deferred_rows.py): direct
        # touched-row scatter-set updates, set via the packed_rows kwarg
        self._packed_rows = None

    # -- learning rate -----------------------------------------------------
    def _create_global_learning_rate(self):
        if isinstance(self._learning_rate, Variable):
            self._lr_var = self._learning_rate
            return
        if self._lr_var is not None:
            return
        helper = LayerHelper("learning_rate")
        self._lr_var = helper.create_global_variable(
            shape=[1], dtype="float32",
            name=f"learning_rate_{self._name}",
            initializer=ConstantInitializer(float(self._learning_rate)))

    def _global_learning_rate(self) -> Variable:
        return self._lr_var

    @property
    def current_lr(self):
        from .core.scope import global_scope
        v = global_scope().find_var(self._lr_var.name) if self._lr_var is not None else None
        return None if v is None else np.asarray(v)

    # -- accumulators ------------------------------------------------------
    def _add_accumulator(self, name: str, param: Variable, fill_value: float = 0.0,
                         shape=None, dtype=None) -> Variable:
        key = (name, param.name)
        if key in self._accumulators:
            return self._accumulators[key]
        helper = LayerHelper(f"{self._name}_{name}")
        acc = helper.create_global_variable(
            shape=shape if shape is not None else list(param.shape),
            dtype=dtype or dtype_str(param.dtype),
            name=f"{param.name}_{self._name}_{name}",
            initializer=ConstantInitializer(fill_value))
        # marks the var for ZeRO optimizer-state sharding
        # (compiler._state_sharding) — robust against accumulator naming
        acc.is_optimizer_state = True
        # param-shaped accumulators (moments, velocities) shard over the dp
        # axis under ShardingStrategy; scalar side-state (beta pows, loss
        # scaling counters) must stay replicated — every device reads it
        acc.zero_shardable = (
            shape is None
            and int(np.prod(param.shape or [1])) > 1)
        self._accumulators[key] = acc
        return acc

    def _get_accumulator(self, name, param):
        return self._accumulators[(name, param.name)]

    # -- deferred row updates (ops/deferred_rows.py) -------------------------
    @staticmethod
    def _normalize_deferred(cfg):
        """deferred_rows kwarg: None, or {"rows_per_step": R[, "segments": K]}.
        R must bound the number of lookup rows any single step produces for
        the table (static capacity — checked again at trace time)."""
        if cfg is None:
            return None
        if not isinstance(cfg, dict) or "rows_per_step" not in cfg:
            raise ValueError(
                "deferred_rows must be a dict with at least 'rows_per_step' "
                "(max lookup rows per step), optionally 'segments' "
                f"(fold cadence, default 16); got {cfg!r}")
        return {"segments": int(cfg.get("segments", 16)),
                "rows_per_step": int(cfg["rows_per_step"])}

    def _deferred_sites(self, prog, p):
        return [op for blk in prog.blocks for op in blk.ops
                if op.type in ("lookup_table", "lookup_table_v2")
                and op.inputs.get("W") == [p.name]
                and op.attrs.get("is_sparse")]

    def _packed_site(self, prog, p):
        """The single row_pack lookup site of a packed table, or None."""
        if self._packed_rows is None:
            return None
        sites = [op for op in self._deferred_sites(prog, p)
                 if op.attrs.get("row_pack_dt")]
        if not sites:
            return None
        if len(sites) != 1:
            raise ValueError(
                f"packed_rows: table {p.name!r} has {len(sites)} row_pack "
                f"lookup sites; exactly one is required (its gathered rows "
                f"feed the optimizer op)")
        return sites[0]

    def _packed_io(self, p, g, site, state_init=0.0):
        mult = self._DEFERRED_STATE_MULT[self.type]
        dt = int(site.attrs["row_pack_dt"])
        if dt % mult:
            raise ValueError(
                f"packed_rows: {self.type} stores {mult} column groups per "
                f"row (param{'' if mult == 1 else ' + moment state'}), so "
                f"table {p.name!r} needs row_pack dt divisible by {mult}; "
                f"got dt={dt}. Build the embedding with "
                f"size=[vocab, dim*{mult}] and slice [:, :, :dim]")
        if mult > 1:
            # state columns must start at the optimizer's initial value no
            # matter what the table initializer wrote there (sqrt of a
            # uniform-random G would NaN); honors
            # adagrad initial_accumulator_value
            default_startup_program().global_block().append_op(
                type="rowpack_init_state_cols",
                inputs={"Param": [p.name]}, outputs={"ParamOut": [p.name]},
                attrs={"vis": dt // mult, "dt": dt,
                       "value": float(state_init)})
        inputs = {"Param": [p.name], "Grad": [g.name],
                  "FwdRows": [site.outputs["Out"][0]],
                  "LearningRate": [self._lr_var.name]}
        outputs = {"ParamOut": [p.name]}
        attrs = {"vis": dt // mult,
                 "rows_per_step": int(self._packed_rows["rows_per_step"]),
                 # opt-out knob for the fused Pallas update path
                 # (adagrad_row_packed): packed_rows={"fused": False} pins
                 # the unfused gather+scatter branch regardless of backend
                 "fused": bool(self._packed_rows.get("fused", True))}
        return inputs, outputs, attrs

    # how many column groups the table row carries per optimizer type:
    # param only (sgd), param|G (adagrad), param|m|v (adam) — the Downpour
    # g2sum in-row state layout (pslib DownpourSparseTable)
    _DEFERRED_STATE_MULT = {"sgd": 1, "adagrad": 2, "adam": 3}

    def _deferred_setup(self, block, p, state_init=0.0):
        """Create the postab + append-log state for table `p`, rewrite its
        (single) sparse lookup site to read through it and to export its
        gathered rows (distributed_lookup_table-rewrite analog,
        parameter_prefetch.cc), init the state columns, and record the
        fold inputs. Returns the dict of vars for the optimizer op."""
        cfg = self._deferred_rows
        k, r = cfg["segments"], cfg["rows_per_step"]
        mult = self._DEFERRED_STATE_MULT[self.type]
        dt = int(p.shape[-1])
        if dt % mult:
            raise ValueError(
                f"deferred_rows: {self.type} stores {mult} column groups "
                f"per row (param{'' if mult == 1 else ' + moment state'}), "
                f"so table {p.name!r} needs last dim divisible by {mult}; "
                f"got {dt}. Build the embedding with "
                f"[vocab, dim*{mult}] and slice [:, :, :dim]")
        vis = dt // mult
        c = k * r
        prog = block.program
        sites = self._deferred_sites(prog, p)
        if len(sites) != 1:
            raise ValueError(
                f"deferred_rows: table {p.name!r} has {len(sites)} "
                f"is_sparse lookup sites; the deferred path requires "
                f"exactly one (its gathered rows feed the optimizer op)")
        (site,) = sites
        if site.attrs.get("row_pack_dt"):
            raise ValueError(
                f"deferred_rows: table {p.name!r} was built with "
                f"row_pack=True; row_pack tables require the packed_rows "
                f"optimizer config (direct touched-row scatter updates), "
                f"not deferred_rows")
        helper = LayerHelper(f"{self._name}_deferred")
        postab = helper.create_global_variable(
            [int(p.shape[0])], "int32", name=f"{p.name}@pending_pos",
            initializer=ConstantInitializer(-1))
        log_ids = helper.create_global_variable(
            [c], "int32", name=f"{p.name}@log_ids",
            initializer=ConstantInitializer(2**31 - 1))
        # log rows lane-padded to a 128 multiple: lane-aligned rows gather
        # ~5x faster than the narrow column-major layout the un-paddable
        # base table is stuck with (see ops/deferred_rows.py)
        lw = ((dt + 127) // 128) * 128
        log_raw = helper.create_global_variable(
            [c, lw], dtype_str(p.dtype), name=f"{p.name}@log_raw")
        log_cum = helper.create_global_variable(
            [c, lw], dtype_str(p.dtype), name=f"{p.name}@log_cum")
        count = helper.create_global_variable(
            [1], "int32", name=f"{p.name}@log_count")
        if mult > 1:
            # state columns: overwrite whatever the param initializer
            # produced there with the moment initial value
            startup = default_startup_program()
            startup.global_block().append_op(
                type="deferred_init_state_cols",
                inputs={"Param": [p.name]}, outputs={"ParamOut": [p.name]},
                attrs={"vis": vis, "value": float(state_init)})
        # rewrite the lookup site: read through the pending state and
        # export the gathered current/cum rows for the optimizer op
        cum_var = block.program.global_block().create_var(
            name=f"{p.name}@lookup_cum", shape=[-1, dt], dtype="float32",
            persistable=False, stop_gradient=True)
        site.inputs["PendingPos"] = [postab.name]
        site.inputs["PendingCum"] = [log_cum.name]
        site.outputs["CumOut"] = [cum_var.name]
        prog._bump_version()
        out = {"postab": postab, "log_ids": log_ids, "log_raw": log_raw,
               "log_cum": log_cum, "count": count,
               "fwd_rows": site.outputs["Out"][0], "fwd_cum": cum_var.name,
               "vis": vis}
        self._deferred_applied.append((p, out))
        return out

    def _deferred_io(self, p, g, dv):
        """Common input/output maps for the deferred optimizer ops."""
        inputs = {"Grad": [g.name],
                  "FwdRows": [dv["fwd_rows"]], "FwdCum": [dv["fwd_cum"]],
                  "PendingPos": [dv["postab"].name],
                  "LogIds": [dv["log_ids"].name],
                  "LogRaw": [dv["log_raw"].name],
                  "LogCum": [dv["log_cum"].name],
                  "Count": [dv["count"].name],
                  "LearningRate": [self._lr_var.name]}
        outputs = {"PendingPosOut": [dv["postab"].name],
                   "LogIdsOut": [dv["log_ids"].name],
                   "LogRawOut": [dv["log_raw"].name],
                   "LogCumOut": [dv["log_cum"].name],
                   "CountOut": [dv["count"].name]}
        return inputs, outputs

    def _build_deferred_fold(self, main_prog):
        """One `deferred_fold` op per deferred table in a separate program,
        attached as an executor epilogue at the fold cadence (the pserver
        communicator-cadence analog). Running it is a pure representation
        change (base+pending -> base'+empty) — reads are exact either way;
        it just has to run before the append log wraps."""
        if not self._deferred_applied:
            return
        cfg = self._deferred_rows
        fold = Program()
        blk = fold.global_block()

        def decl(v):
            if blk._find_var_recursive(v.name) is None:
                blk.create_var(name=v.name, shape=list(v.shape),
                               dtype=dtype_str(v.dtype), persistable=True)
            return v.name

        for p, dv in self._deferred_applied:
            inputs = {"Param": [decl(p)],
                      "PendingPos": [decl(dv["postab"])],
                      "LogIds": [decl(dv["log_ids"])],
                      "LogRaw": [decl(dv["log_raw"])],
                      "LogCum": [decl(dv["log_cum"])],
                      "Count": [decl(dv["count"])]}
            outputs = {"ParamOut": [p.name],
                       "PendingPosOut": [dv["postab"].name],
                       "LogIdsOut": [dv["log_ids"].name],
                       "LogRawOut": [dv["log_raw"].name],
                       "LogCumOut": [dv["log_cum"].name],
                       "CountOut": [dv["count"].name]}
            blk.append_op(type="deferred_fold", inputs=inputs,
                          outputs=outputs, attrs={})
        meta = {"count_vars": [dv["count"].name
                               for _, dv in self._deferred_applied],
                "rows_per_step": cfg["rows_per_step"]}
        main_prog._epilogue_programs = (
            list(getattr(main_prog, "_epilogue_programs", []))
            + [(cfg["segments"], fold, meta)])
        self.fold_program = fold

    # -- api ----------------------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, params_grads):
        pass

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set, callbacks)

    def apply_gradients(self, params_grads) -> List:
        prog = default_main_program()
        # update ops go to the CURRENT block so predicated optimizers
        # (GradientMergeOptimizer's conditional_block) contain them;
        # accumulator VARS still live in the global block (persistable)
        block = prog.current_block()
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        params_grads = append_regularization_ops(params_grads, self.regularization)
        self._create_global_learning_rate()
        self._create_accumulators(prog.global_block(),
                                  [p for p, g in params_grads])
        ops = []
        for pg in params_grads:
            ops.append(self._append_optimize_op(block, pg))
        self._finish_update(block, params_grads)
        if self._deferred_rows is not None:
            if not self._deferred_applied:
                raise ValueError(
                    "deferred_rows was set but no parameter has an "
                    "is_sparse lookup_table site — deferred row updates "
                    "need SelectedRows gradients (build the embedding "
                    "with is_sparse=True)")
            self._build_deferred_fold(prog)
        return ops

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None) -> Tuple[List, List]:
        from .core.program import in_dygraph_mode
        if in_dygraph_mode():
            return self._dygraph_minimize(loss, parameter_list)
        params_grads = self.backward(loss, startup_program, parameter_list, no_grad_set)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    # -- dygraph path --------------------------------------------------------
    # Reuses the per-class static op emission on a scratch Program executed
    # eagerly: the scratch program IS the optimizer step (one op per param +
    # accumulator updates), the dygraph analog of apply_gradients. Reference
    # parity: dygraph optimizers share op kernels with static mode
    # (imperative/prepared_operator.h).
    def _dygraph_setup(self, params):
        from .core.executor import ExecContext, _run_block
        from .core.program import Program, grad_var_name, program_guard
        import jax

        # rebuild from scratch: cached lr/accumulator vars belong to the
        # previous scratch program; names are deterministic, so accumulated
        # values transfer via the old-env merge below
        self._dy_jit = None   # executable belongs to the old program
        self._lr_var = None
        self._accumulators = {}
        self._dy_prog = Program()
        dy_startup = Program()
        with program_guard(self._dy_prog, dy_startup):
            block = self._dy_prog.global_block()
            pvars = []
            for p in params:
                pv = block.create_parameter(name=p.name, shape=list(p.shape),
                                            dtype=p.dtype, trainable=True)
                pv.regularizer = getattr(p, "regularizer", None)
                pv.need_clip = getattr(p, "need_clip", True)
                block.create_var(name=grad_var_name(p.name), shape=list(p.shape),
                                 dtype=p.dtype)
                pvars.append(pv)
            # same pipeline as static apply_gradients: clip → regularize → update
            params_grads = [(pv, block.var(grad_var_name(pv.name))) for pv in pvars]
            if self._grad_clip is not None:
                params_grads = self._grad_clip(params_grads)
            params_grads = append_regularization_ops(params_grads, self.regularization)
            self._create_global_learning_rate()
            self._create_accumulators(block, [pg[0] for pg in params_grads])
            for pg in params_grads:
                self._append_optimize_op(block, pg)
        # init accumulators/lr by running the scratch startup program eagerly
        env = {}
        ctx = ExecContext(jax.random.PRNGKey(0))
        _run_block(dy_startup.global_block(), env, ctx)
        # param-list change (e.g. unfreezing): keep accumulated state for
        # params that persist across rebuilds
        old_env = getattr(self, "_dy_env", None)
        if old_env:
            for k, v in old_env.items():
                if k in env:
                    env[k] = v
        self._dy_env = env
        self._dy_param_names = tuple(sorted(p.name for p in params))
        # optimizer update ops are never differentiated: is_test skips the
        # per-step vjp taping in _run_op (hot-path cost)
        from .core.executor import ExecContext
        import jax as _jax
        self._dy_ctx = ExecContext(_jax.random.PRNGKey(0), is_test=True)

    def set_lr(self, value: float):
        """Update the learning rate (works in both modes)."""
        import jax.numpy as jnp
        from .core.scope import global_scope
        if getattr(self, "_dy_env", None) is not None and self._lr_var is not None:
            self._dy_env[self._lr_var.name] = jnp.asarray([float(value)], dtype=jnp.float32)
        elif self._lr_var is not None:
            global_scope().set_var(self._lr_var.name,
                                   jnp.asarray([float(value)], dtype=jnp.float32))
        else:
            self._learning_rate = float(value)

    def state_dict(self):
        """Optimizer state for checkpointing (dygraph: the scratch env;
        static: accumulator vars from the scope)."""
        import numpy as np
        if getattr(self, "_dy_env", None) is not None:
            d = {k: np.asarray(v) for k, v in self._dy_env.items()}
        else:
            from .core.scope import global_scope
            scope = global_scope()
            d = {}
            for (name, pname), acc in self._accumulators.items():
                v = scope.find_var(acc.name)
                if v is not None:
                    d[acc.name] = np.asarray(v)
        d["@optimizer_state@"] = np.asarray(1)
        return d

    def set_state_dict(self, state):
        import jax.numpy as jnp
        state = {k: v for k, v in state.items() if k != "@optimizer_state@"}
        if getattr(self, "_dy_env", None) is not None:
            for k, v in state.items():
                self._dy_env[k] = jnp.asarray(v)
        else:
            from .core.scope import global_scope
            scope = global_scope()
            for k, v in state.items():
                scope.set_var(k, jnp.asarray(v))

    load_state_dict = set_state_dict

    def _dygraph_minimize(self, loss, parameter_list=None):
        from .core.executor import ExecContext, _run_block
        from .core.program import grad_var_name
        from .dygraph.tracer import _active_tracer
        import jax

        params = list(parameter_list if parameter_list is not None
                      else getattr(self, "_parameter_list", None) or [])
        if not params:
            raise ValueError(
                "dygraph minimize needs parameter_list (pass model.parameters())")
        tr = _active_tracer()
        if tr is not None and tr.tape:
            tr.run_backward(loss)
        names = tuple(sorted(p.name for p in params))
        if (getattr(self, "_dy_prog", None) is None
                or getattr(self, "_dy_param_names", None) != names):
            self._dygraph_setup(params)
        import jax.numpy as jnp
        env = self._dy_env
        for p in params:
            env[p.name] = p.value
            env[grad_var_name(p.name)] = (p.grad_value if p.grad_value is not None
                                          else jnp.zeros_like(p.value))
        # jit the whole update block (one executable per param-set) — the
        # dygraph PreparedOp-cache story applied to the optimizer: N
        # per-param update dispatches collapse into one launch. Non-array
        # env entries (SelectedRows sparse grads etc.) fall back to the
        # eager block run.
        arr_env = {n: v for n, v in env.items() if isinstance(v, jax.Array)}
        if len(arr_env) == len(env):
            if getattr(self, "_dy_jit", None) is None:
                block = self._dy_prog.global_block()

                def _upd(e):
                    e = dict(e)
                    _run_block(block, e, ExecContext(None, is_test=True))
                    return e

                self._dy_jit = jax.jit(_upd)
            env = self._dy_env = self._dy_jit(arr_env)
        else:
            _run_block(self._dy_prog.global_block(), env, self._dy_ctx)
        for p in params:
            p.value = env[p.name]
        return [], [(p, p.grad_value) for p in params]


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, regularization=None, name=None,
                 grad_clip=None, deferred_rows=None, packed_rows=None):
        super().__init__(learning_rate, regularization, name, grad_clip)
        self.type = "sgd"
        self._deferred_rows = self._normalize_deferred(deferred_rows)
        self._packed_rows = packed_rows

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        site = self._packed_site(block.program, p)
        if site is not None:
            inputs, outputs, attrs = self._packed_io(p, g, site)
            return block.append_op(type="sgd_row_packed", inputs=inputs,
                                   outputs=outputs, attrs=attrs)
        if (self._deferred_rows is not None
                and self._deferred_sites(block.program, p)):
            dv = self._deferred_setup(block, p)
            inputs, outputs = self._deferred_io(p, g, dv)
            return block.append_op(
                type="sgd_row_deferred", inputs=inputs, outputs=outputs,
                attrs={"vis": dv["vis"],
                       "rows_per_step": self._deferred_rows["rows_per_step"]})
        return block.append_op(
            type="sgd",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "LearningRate": [self._lr_var.name]},
            outputs={"ParamOut": [p.name]}, attrs={})


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 regularization=None, name=None, grad_clip=None):
        super().__init__(learning_rate, regularization, name, grad_clip)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            type="momentum",
            inputs={"Param": [p.name], "Grad": [g.name], "Velocity": [v.name],
                    "LearningRate": [self._lr_var.name]},
            outputs={"ParamOut": [p.name], "VelocityOut": [v.name]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov})


class LarsMomentumOptimizer(Optimizer):
    """optimizer.py:1058 LarsMomentumOptimizer."""

    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, regularization=None, name=None,
                 grad_clip=None):
        super().__init__(learning_rate, regularization, name, grad_clip)
        self.type = "lars_momentum"
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            type="lars_momentum",
            inputs={"Param": [p.name], "Grad": [g.name], "Velocity": [v.name],
                    "LearningRate": [self._lr_var.name]},
            outputs={"ParamOut": [p.name], "VelocityOut": [v.name]},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay})


class _AdamLike(Optimizer):
    op_type = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 regularization=None, name=None, grad_clip=None,
                 deferred_rows=None, packed_rows=None, **kw):
        super().__init__(learning_rate, regularization, name, grad_clip)
        self.type = self.op_type
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._extra_attrs = kw
        if self.op_type != "adam" and (deferred_rows is not None
                                       or packed_rows is not None):
            raise ValueError(
                f"deferred_rows/packed_rows: sparse row-update kernels "
                f"exist for sgd/adagrad/adam only, not {self.op_type!r}")
        self._deferred_rows = self._normalize_deferred(deferred_rows)
        self._packed_rows = packed_rows

    def _adam_deferred_applies(self, prog, p):
        return (self.op_type == "adam" and self._deferred_rows is not None
                and self._deferred_sites(prog, p))

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            if (self._adam_deferred_applies(block.program, p)
                    or self._packed_site(block.program, p) is not None):
                # m/v live in the table's state columns; beta pows stay
                self._add_accumulator("beta1_pow", p, fill_value=self._beta1, shape=[1], dtype="float32")
                self._add_accumulator("beta2_pow", p, fill_value=self._beta2, shape=[1], dtype="float32")
                continue
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow", p, fill_value=self._beta1, shape=[1], dtype="float32")
            self._add_accumulator("beta2_pow", p, fill_value=self._beta2, shape=[1], dtype="float32")

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        b1p = self._get_accumulator("beta1_pow", p)
        b2p = self._get_accumulator("beta2_pow", p)
        attrs = {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon}
        attrs.update(self._extra_attrs)
        site = self._packed_site(block.program, p)
        if site is not None:
            inputs, outputs, pattrs = self._packed_io(p, g, site)
            inputs["Beta1Pow"] = [b1p.name]
            inputs["Beta2Pow"] = [b2p.name]
            outputs["Beta1PowOut"] = [b1p.name]
            outputs["Beta2PowOut"] = [b2p.name]
            attrs.update(pattrs)
            return block.append_op(type="adam_row_packed", inputs=inputs,
                                   outputs=outputs, attrs=attrs)
        if self._adam_deferred_applies(block.program, p):
            dv = self._deferred_setup(block, p)
            inputs, outputs = self._deferred_io(p, g, dv)
            inputs["Beta1Pow"] = [b1p.name]
            inputs["Beta2Pow"] = [b2p.name]
            outputs["Beta1PowOut"] = [b1p.name]
            outputs["Beta2PowOut"] = [b2p.name]
            attrs.update({"vis": dv["vis"],
                          "rows_per_step": self._deferred_rows["rows_per_step"]})
            return block.append_op(
                type="adam_row_deferred", inputs=inputs, outputs=outputs,
                attrs=attrs)
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        return block.append_op(
            type=self.op_type,
            inputs={"Param": [p.name], "Grad": [g.name], "Moment1": [m1.name],
                    "Moment2": [m2.name], "Beta1Pow": [b1p.name], "Beta2Pow": [b2p.name],
                    "LearningRate": [self._lr_var.name]},
            outputs={"ParamOut": [p.name], "Moment1Out": [m1.name], "Moment2Out": [m2.name],
                     "Beta1PowOut": [b1p.name], "Beta2PowOut": [b2p.name]},
            attrs=attrs)


class AdamOptimizer(_AdamLike):
    op_type = "adam"


class AdamWOptimizer(_AdamLike):
    op_type = "adamw"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 weight_decay=0.01, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, coeff=weight_decay, **kw)


class LambOptimizer(_AdamLike):
    """optimizer.py:2103 LambOptimizer."""
    op_type = "lamb"

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon,
                         weight_decay=lamb_weight_decay, **kw)


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, regularization=None, name=None,
                 initial_accumulator_value=0.0, grad_clip=None,
                 deferred_rows=None, packed_rows=None):
        super().__init__(learning_rate, regularization, name, grad_clip)
        self.type = "adagrad"
        self._epsilon = epsilon
        self._initial = initial_accumulator_value
        self._deferred_rows = self._normalize_deferred(deferred_rows)
        self._packed_rows = packed_rows

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            if self._packed_site(block.program, p) is not None or (
                    self._deferred_rows is not None
                    and self._deferred_sites(block.program, p)):
                continue  # G lives in the table's state columns
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        site = self._packed_site(block.program, p)
        if site is not None:
            inputs, outputs, attrs = self._packed_io(
                p, g, site, state_init=self._initial)
            attrs["epsilon"] = self._epsilon
            return block.append_op(type="adagrad_row_packed", inputs=inputs,
                                   outputs=outputs, attrs=attrs)
        if (self._deferred_rows is not None
                and self._deferred_sites(block.program, p)):
            dv = self._deferred_setup(block, p, state_init=self._initial)
            inputs, outputs = self._deferred_io(p, g, dv)
            return block.append_op(
                type="adagrad_row_deferred", inputs=inputs, outputs=outputs,
                attrs={"epsilon": self._epsilon, "vis": dv["vis"],
                       "rows_per_step": self._deferred_rows["rows_per_step"]})
        m = self._get_accumulator("moment", p)
        return block.append_op(
            type="adagrad",
            inputs={"Param": [p.name], "Grad": [g.name], "Moment": [m.name],
                    "LearningRate": [self._lr_var.name]},
            outputs={"ParamOut": [p.name], "MomentOut": [m.name]},
            attrs={"epsilon": self._epsilon})


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, regularization=None,
                 name=None, grad_clip=None):
        super().__init__(learning_rate, regularization, name, grad_clip)
        self.type = "decayed_adagrad"
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        return block.append_op(
            type="decayed_adagrad",
            inputs={"Param": [p.name], "Grad": [g.name], "Moment": [m.name],
                    "LearningRate": [self._lr_var.name]},
            outputs={"ParamOut": [p.name], "MomentOut": [m.name]},
            attrs={"decay": self._decay, "epsilon": self._epsilon})


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, regularization=None,
                 name=None, grad_clip=None):
        super().__init__(learning_rate, regularization, name, grad_clip)
        self.type = "adadelta"
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        g1 = self._get_accumulator("avg_squared_grad", p)
        g2 = self._get_accumulator("avg_squared_update", p)
        return block.append_op(
            type="adadelta",
            inputs={"Param": [p.name], "Grad": [g.name], "AvgSquaredGrad": [g1.name],
                    "AvgSquaredUpdate": [g2.name], "LearningRate": [self._lr_var.name]},
            outputs={"ParamOut": [p.name], "AvgSquaredGradOut": [g1.name],
                     "AvgSquaredUpdateOut": [g2.name]},
            attrs={"epsilon": self._epsilon, "rho": self._rho})


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, regularization=None, name=None, grad_clip=None):
        super().__init__(learning_rate, regularization, name, grad_clip)
        self.type = "rmsprop"
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)
            self._add_accumulator("momentum", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        ms = self._get_accumulator("mean_square", p)
        mg = self._get_accumulator("mean_grad", p)
        mom = self._get_accumulator("momentum", p)
        return block.append_op(
            type="rmsprop",
            inputs={"Param": [p.name], "Grad": [g.name], "MeanSquare": [ms.name],
                    "MeanGrad": [mg.name], "Moment": [mom.name],
                    "LearningRate": [self._lr_var.name]},
            outputs={"ParamOut": [p.name], "MeanSquareOut": [ms.name],
                     "MeanGradOut": [mg.name], "MomentOut": [mom.name]},
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered})


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 regularization=None, name=None, grad_clip=None):
        super().__init__(learning_rate, regularization, name, grad_clip)
        self.type = "adamax"
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow", p, fill_value=self._beta1, shape=[1], dtype="float32")

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        inf = self._get_accumulator("inf_norm", p)
        b1p = self._get_accumulator("beta1_pow", p)
        op = block.append_op(
            type="adamax",
            inputs={"Param": [p.name], "Grad": [g.name], "Moment": [m.name],
                    "InfNorm": [inf.name], "Beta1Pow": [b1p.name],
                    "LearningRate": [self._lr_var.name]},
            outputs={"ParamOut": [p.name], "MomentOut": [m.name], "InfNormOut": [inf.name]},
            attrs={"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon})
        # beta1_pow update (reference appends a scale op per param)
        block.append_op(type="scale", inputs={"X": [b1p.name]},
                        outputs={"Out": [b1p.name]}, attrs={"scale": self._beta1})
        return op


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 regularization=None, name=None, grad_clip=None):
        super().__init__(learning_rate, regularization, name, grad_clip)
        self.type = "ftrl"
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        sq = self._get_accumulator("squared", p)
        lin = self._get_accumulator("linear", p)
        return block.append_op(
            type="ftrl",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "SquaredAccumulator": [sq.name], "LinearAccumulator": [lin.name],
                    "LearningRate": [self._lr_var.name]},
            outputs={"ParamOut": [p.name], "SquaredAccumOut": [sq.name],
                     "LinearAccumOut": [lin.name]},
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power})


class DGCMomentumOptimizer(MomentumOptimizer):
    """Deep Gradient Compression momentum (reference optimizer.py:799 +
    sparse_all_reduce_op_handle.cc), wired into the PROGRAM path.

    Emits a `dgc_momentum` op per parameter implementing the reference's
    update on the global gradient: momentum correction (u = mu·u + g;
    v += u), top-k selection with error feedback (the unsent mass of v
    carries over), and the sparse update p -= lr·topk(v). Before
    `rampup_begin_step` it behaves as dense momentum; sparsity then ramps
    through `sparsity` over `rampup_step` steps (reference schedule).

    TPU note: under GSPMD the per-device partial gradients never exist as
    program tensors (the data-parallel reduction happens inside XLA's
    partitioned matmuls), so the sparsification applies to the GLOBAL
    gradient — identical momentum-correction/error-feedback convergence
    semantics, while the wire-level sparse exchange for DCN topologies
    remains the functional `paddle_tpu.parallel.dgc` transforms
    (dgc_allreduce / sparse_allgather_exchange)."""

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 rampup_step=1, sparsity=(0.999,), use_nesterov=False,
                 clip_norm=1.0, **kw):
        super().__init__(learning_rate, momentum,
                         use_nesterov=use_nesterov, **kw)
        self.type = "dgc_momentum"
        self._rampup_begin = int(rampup_begin_step)
        self._rampup_step = max(1, int(rampup_step))
        self._sparsity = list(sparsity)
        self._clip_norm = float(clip_norm)  # 0 disables the local clip

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)
            self._add_accumulator("dgc_residual", p)
            self._add_accumulator("dgc_step", p, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        r = self._get_accumulator("dgc_residual", p)
        step = self._get_accumulator("dgc_step", p)
        return block.append_op(
            type="dgc_momentum",
            inputs={"Param": [p.name], "Grad": [g.name], "Velocity": [v.name],
                    "Residual": [r.name], "Step": [step.name],
                    "LearningRate": [self._lr_var.name]},
            outputs={"ParamOut": [p.name], "VelocityOut": [v.name],
                     "ResidualOut": [r.name], "StepOut": [step.name]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov,
                   "rampup_begin_step": self._rampup_begin,
                   "rampup_step": self._rampup_step,
                   "sparsity": self._sparsity,
                   "clip_norm": self._clip_norm})


class PipelineOptimizer:
    """Program-level pipeline parallelism (reference optimizer.py:2677).

    ``cut_list`` is the ordered chain of boundary variables
    ``[stage0_input, boundary1, ..., final_output]`` — N stages for N+1
    entries. The ops between consecutive boundaries must be isomorphic
    (same op-type sequence with same-shaped parameters — the
    transformer-by-layers case); ``minimize`` replaces them with ONE
    `pipeline` op holding the stage-0 template sub-block plus every stage's
    parameters, driven by the GPipe schedule in parallel/pipeline.py. The
    reference's CPU scope-queues (section_worker.cc:141) don't exist under
    XLA; the compiled schedule overlaps stages via a ppermute ring instead.

    Usage::

        opt = fluid.optimizer.PipelineOptimizer(
            fluid.optimizer.Adam(1e-4), cut_list=[h0, h1, h2],
            num_microbatches=4)
        opt.minimize(loss)
        prog = fluid.CompiledProgram(main).with_mesh(mesh, data_axis="dp")
    """

    def __init__(self, optimizer, cut_list, num_microbatches: int = 1,
                 axis: str = "pp", data_axis=None, capture_spec=None,
                 queue_size=None, place_list=None, concurrency_list=None,
                 sync_steps=None, start_cpu_core_id=None):
        # trailing args are reference-API compat (scope-queue knobs — moot).
        # capture_spec: {var_name: "batched"|"shared"} override for captured
        # prologue activations — by default a capture whose leading dim
        # equals the batch size is microbatched along with the activations;
        # use "shared" for e.g. a [T, T] table where T happens to equal B.
        if len(cut_list) < 3:
            raise ValueError("cut_list needs [input, boundary..., output] "
                             "(>= 2 stages)")
        self._opt = optimizer
        self._cut = list(cut_list)
        self._m = int(num_microbatches)
        self._axis = axis
        self._data_axis = data_axis
        self._capture_spec = dict(capture_spec or {})

    def _producer_idx(self, ops, name):
        for i in range(len(ops) - 1, -1, -1):
            if name in ops[i].output_names():
                return i
        return -1  # feed/data var: the pipelined region starts at op 0

    def _transform(self, program):
        from .core.program import Operator

        block = program.global_block()
        ops = block.ops
        names = [v.name for v in self._cut]
        bounds = [self._producer_idx(ops, n) for n in names]
        if bounds != sorted(bounds):
            raise ValueError("cut_list variables are not in program order")
        n_stages = len(names) - 1

        # per-stage op ranges: (producer(b_{k}) , producer(b_{k+1})]
        stage_ranges = [(bounds[k] + 1, bounds[k + 1] + 1)
                        for k in range(n_stages)]
        stage_ops = [ops[a:b] for a, b in stage_ranges]

        # isomorphism probe (op-type sequence + attrs): isomorphic stages
        # take the efficient stage-stacked template path; anything else
        # lowers to the heterogeneous per-stage-sub-block path
        # (reference section_worker.cc heterogeneous sections)
        def _iso():
            sig0 = [op.type for op in stage_ops[0]]
            for sops in stage_ops[1:]:
                if [op.type for op in sops] != sig0:
                    return False
                for o0, ok in zip(stage_ops[0], sops):
                    a0, ak = o0.attrs, ok.attrs
                    if a0.keys() != ak.keys() or any(
                            not np.array_equal(a0[k2], ak[k2])
                            if isinstance(a0[k2], np.ndarray)
                            else a0[k2] != ak[k2] for k2 in a0):
                        return False
            return True

        def stage_params(sops):
            seen, out = set(), []
            for op in sops:
                for n in op.input_names():
                    v = block._find_var_recursive(n)
                    if v is not None and v.persistable and n not in seen:
                        seen.add(n)
                        out.append(n)
            return out

        per_stage_params = [stage_params(s) for s in stage_ops]

        def _stackable():
            n_params = len(per_stage_params[0])
            for ps in per_stage_params:
                if len(ps) != n_params:
                    return False
                for a, b in zip(per_stage_params[0], ps):
                    va, vb = block.var(a), block.var(b)
                    if tuple(va.shape or ()) != tuple(vb.shape or ()):
                        return False
            return True

        # captured external activations (e.g. a shared attention mask built
        # in the prologue): read by stage ops, produced outside every stage
        def stage_captures(sops, skip):
            produced = set()
            caps = []
            for op in sops:
                for n in op.input_names():
                    v = block._find_var_recursive(n)
                    if (n not in produced and n not in skip
                            and not (v is not None and v.persistable)
                            and n not in caps):
                        caps.append(n)
                produced.update(op.output_names())
            return caps

        per_stage_caps = [
            stage_captures(sops, set(per_stage_params[k]) | {names[k]})
            for k, sops in enumerate(stage_ops)]
        captures = per_stage_caps[0]

        if not (_iso() and _stackable()
                and all(c == captures for c in per_stage_caps[1:])):
            return self._transform_hetero(program, block, names, stage_ops,
                                          stage_ranges, per_stage_params,
                                          per_stage_caps)
        n_params = len(per_stage_params[0])

        # template sub-block = stage 0's ops, re-homed
        cur = program.current_block_idx
        program.current_block_idx = block.idx
        sub = program.create_block()
        program.rollback()
        program.current_block_idx = cur
        for op in stage_ops[0]:
            op.block = sub
            sub.ops.append(op)

        # splice: remove all stage op ranges, insert the pipeline op
        lo, hi = stage_ranges[0][0], stage_ranges[-1][1]
        flat_params = [p for ps in per_stage_params for p in ps]
        pipe_op = Operator(
            block, "pipeline",
            inputs={"X": [names[0]], "Params": flat_params,
                    "Captures": captures},
            outputs={"Out": [names[-1]]},
            attrs={"sub_block": sub, "n_stages": n_stages,
                   "n_params": n_params, "num_microbatches": self._m,
                   "axis": self._axis, "data_axis": self._data_axis,
                   "in_name": names[0], "out_name": names[1],
                   "param_names": per_stage_params[0],
                   "capture_names": captures,
                   "capture_spec": self._capture_spec})
        block.ops[lo:hi] = [pipe_op]
        program._bump_version()

    def _transform_hetero(self, program, block, names, stage_ops,
                          stage_ranges, per_stage_params, per_stage_caps):
        """Non-isomorphic stages: one sub-block PER stage, lowered to the
        lax.switch ring in parallel/pipeline.pipeline_hetero (reference
        section_worker.cc:141 heterogeneous sections / trainer_desc.proto
        per-section programs)."""
        from .core.program import Operator

        n_stages = len(names) - 1
        subs = []
        cur = program.current_block_idx
        program.current_block_idx = block.idx
        for sops in stage_ops:
            sub = program.create_block()
            program.rollback()
            for op in sops:
                op.block = sub
                sub.ops.append(op)
            subs.append(sub)
        program.current_block_idx = cur

        lo, hi = stage_ranges[0][0], stage_ranges[-1][1]
        flat_params = [p for ps in per_stage_params for p in ps]
        flat_caps = [c for cs in per_stage_caps for c in cs]
        pipe_op = Operator(
            block, "pipeline_hetero",
            inputs={"X": [names[0]], "Params": flat_params,
                    "Captures": flat_caps},
            outputs={"Out": [names[-1]]},
            attrs={"sub_blocks": subs, "n_stages": n_stages,
                   "num_microbatches": self._m,
                   "axis": self._axis, "data_axis": self._data_axis,
                   "boundary_names": names,
                   "param_names": per_stage_params,
                   "capture_names": per_stage_caps,
                   "capture_spec": self._capture_spec})
        block.ops[lo:hi] = [pipe_op]
        program._bump_version()

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        self._transform(loss.block.program)
        return self._opt.minimize(loss, startup_program, parameter_list,
                                  no_grad_set)

    def backward(self, *a, **kw):
        return self._opt.backward(*a, **kw)

    def apply_gradients(self, *a, **kw):
        return self._opt.apply_gradients(*a, **kw)


class GradientMergeOptimizer:
    """Accumulate gradients for k steps, apply the inner optimizer once per
    k with the averaged gradient (DistributedStrategy.gradient_merge
    capability; newer-reference GradientMergeOptimizer semantics).

    TPU-native lowering: per-param accumulator vars + a step counter; the
    inner optimizer's update ops run inside a `conditional_block` guarded by
    (step % k == 0), so XLA compiles the whole thing into one predicated
    step — no host-side control flow."""

    _uid = 0

    def __init__(self, inner_optimizer, k_steps: int = 1, avg: bool = True):
        if k_steps < 1:
            raise ValueError("k_steps must be >= 1")
        self._opt = inner_optimizer
        self._k = int(k_steps)
        self._avg = avg

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .layers import control_flow as cf  # noqa: F401 (While import)
        from .layers import tensor as tensor_layers
        from .layers import ops as ops_layers

        if self._k == 1:
            return self._opt.minimize(loss, startup_program, parameter_list,
                                      no_grad_set)
        program = loss.block.program
        params_grads = self._opt.backward(loss, startup_program,
                                          parameter_list, no_grad_set)
        helper = LayerHelper("gradient_merge")
        # unique per instance: two merged optimizers in one program (e.g.
        # GAN D/G) must not share a counter
        GradientMergeOptimizer._uid += 1
        counter = helper.create_global_variable(
            [1], "int64",
            name=f"gradient_merge_step_{GradientMergeOptimizer._uid}",
            initializer=ConstantInitializer(0.0))
        one_v = tensor_layers.fill_constant([1], "int64", 1)
        k_v = tensor_layers.fill_constant([1], "int64", self._k)
        new_count = ops_layers.elementwise_add(counter, one_v)
        new_count = ops_layers.elementwise_mod(new_count, k_v)
        tensor_layers.assign(new_count, counter)
        apply_now = ops_layers.equal(
            new_count, tensor_layers.fill_constant([1], "int64", 0))

        merged = []
        for p, g in params_grads:
            acc = helper.create_global_variable(
                list(p.shape), p.dtype, name=f"{p.name}@GradientMerge",
                initializer=ConstantInitializer(0.0))
            # the persistent gradient buffer ShardingStrategy.stage2 shards:
            # with grads reduce-scattered to the same layout, accumulation
            # happens shard-local and never materializes replicated
            acc.is_grad_buffer = True
            acc_new = ops_layers.elementwise_add(acc, g)
            tensor_layers.assign(acc_new, acc)
            merged.append((p, acc))

        # predicated apply: inner optimizer ops + accumulator reset run in a
        # sub-block gated on (step % k == 0)
        with cf.ConditionalBlock(apply_now):
            eff = []
            for p, acc in merged:
                g_eff = ops_layers.scale(acc, scale=1.0 / self._k) \
                    if self._avg else acc
                eff.append((p, g_eff))
            optimize_ops = self._opt.apply_gradients(eff)
            for p, acc in merged:
                tensor_layers.assign(ops_layers.scale(acc, scale=0.0), acc)
        return optimize_ops, params_grads

    def backward(self, *a, **kw):
        return self._opt.backward(*a, **kw)


class LookaheadOptimizer:
    """reference optimizer.py:2970 — fast/slow weight lookahead: every k
    steps, slow += alpha·(fast − slow) and fast resets to slow. Lowered the
    same way as GradientMergeOptimizer: a step counter + predicated
    sub-block, compiled into the one jitted step."""

    _uid = 0

    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5):
        if inner_optimizer is None:
            raise ValueError("inner optimizer can not be None")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha must be within [0, 1]")
        if k <= 0:
            raise ValueError("k must be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .layers import control_flow as cf
        from .layers import ops as ops_layers
        from .layers import tensor as tensor_layers

        out = self.inner_optimizer.minimize(
            loss, startup_program=startup_program)
        helper = LayerHelper("lookahead")
        LookaheadOptimizer._uid += 1
        counter = helper.create_global_variable(
            [1], "int64", name=f"lookahead_step_{LookaheadOptimizer._uid}",
            initializer=ConstantInitializer(0.0))
        one_v = tensor_layers.fill_constant([1], "int64", 1)
        new_count = ops_layers.elementwise_add(counter, one_v)
        tensor_layers.assign(new_count, counter)

        from .core.program import default_startup_program
        params = loss.block.program.global_block().all_parameters()
        slows = []
        startup_block = (startup_program
                         or default_startup_program()).global_block()
        for p in params:
            slow = helper.create_global_variable(
                list(p.shape), p.dtype, name=f"{p.name}@SLOW",
                initializer=ConstantInitializer(0.0))
            # slow starts as the INITIAL fast weights (reference seeds the
            # slow copies in the startup program, before any update runs)
            startup_block.append_op(type="assign", inputs={"X": [p.name]},
                                    outputs={"Out": [slow.name]}, attrs={})
            slows.append((p, slow))

        k_v = tensor_layers.fill_constant([1], "int64", self.k)
        sync = ops_layers.equal(
            ops_layers.elementwise_mod(new_count, k_v),
            tensor_layers.fill_constant([1], "int64", 0))
        with cf.ConditionalBlock(sync):
            for p, slow in slows:
                blended = ops_layers.elementwise_add(
                    ops_layers.scale(slow, scale=1.0 - self.alpha),
                    ops_layers.scale(p, scale=self.alpha))
                tensor_layers.assign(blended, slow)
                tensor_layers.assign(blended, p)
        return out

    def backward(self, *a, **kw):
        return self.inner_optimizer.backward(*a, **kw)


class ModelAverage(Optimizer):
    """optimizer.py:2257 — maintain sliding-window parameter averages."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None):
        super().__init__(0.0, regularization, name)
        self.type = "model_average"
        self._window = max_average_window

    def minimize(self, loss, **kw):
        raise TypeError("ModelAverage wraps apply(); call after another optimizer")

    def apply(self):
        import contextlib

        @contextlib.contextmanager
        def _noop():
            yield
        return _noop()

    def restore(self, executor=None):
        pass


class ExponentialMovingAverage:
    """optimizer.py:2447 EMA of parameters, applied at eval time."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._name = name or "ema"
        self._ema_vars = {}

    def update(self):
        prog = default_main_program()
        block = prog.global_block()
        helper = LayerHelper(self._name)
        for p in prog.all_parameters():
            if not p.trainable:
                continue
            ema = helper.create_global_variable(
                list(p.shape), dtype_str(p.dtype), name=f"{p.name}.{self._name}",
                initializer=ConstantInitializer(0.0))
            self._ema_vars[p.name] = ema
            # ema = decay*ema + (1-decay)*p  expressed with scale+sum ops
            tmp1 = helper.create_variable_for_type_inference(p.dtype)
            tmp2 = helper.create_variable_for_type_inference(p.dtype)
            block.append_op(type="scale", inputs={"X": [ema.name]},
                            outputs={"Out": [tmp1.name]}, attrs={"scale": self._decay})
            block.append_op(type="scale", inputs={"X": [p.name]},
                            outputs={"Out": [tmp2.name]}, attrs={"scale": 1.0 - self._decay})
            block.append_op(type="sum", inputs={"X": [tmp1.name, tmp2.name]},
                            outputs={"Out": [ema.name]}, attrs={})

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def _swap():
            from .core.scope import global_scope
            import jax.numpy as jnp
            scope = global_scope()
            saved = {}
            for pname, ema in self._ema_vars.items():
                saved[pname] = scope.find_var(pname)
                ev = scope.find_var(ema.name)
                if ev is not None:
                    scope.set_var(pname, ev)
            try:
                yield
            finally:
                if need_restore:
                    for pname, v in saved.items():
                        scope.set_var(pname, v)
        return _swap()

    def restore(self, executor=None):
        pass


# paddle-style lowercase aliases (fluid.optimizer.SGD etc.)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adamax = AdamaxOptimizer
Adagrad = AdagradOptimizer
AdadeltaOpt = AdadeltaOptimizer
Adadelta = AdadeltaOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
