"""Parallelism library — mesh, collectives, TP/PP/SP, fleet.

Reference analog: the whole distributed stack of SURVEY §2.2 — NCCL infra
(platform/nccl_helper.h), collective ops (operators/collective/), transpilers
(transpiler/collective.py), fleet API (incubate/fleet/), PipelineOptimizer
(optimizer.py:2677). Re-designed TPU-first: named mesh axes + GSPMD shardings
+ shard_map collectives replace NCCL rings and graph rewriting; ring
attention adds the sequence/context-parallel axis the reference lacked
(SURVEY §5 long-context note).
"""
from .collective import (  # noqa: F401
    all_gather,
    all_reduce,
    all_to_all,
    broadcast,
    reduce_scatter,
)
from .checkpoint import (  # noqa: F401
    Checkpointer, load_checkpoint, save_checkpoint)
from .env import get_rank, get_world_size, init_parallel_env  # noqa: F401
from .mesh import DistributedStrategy, auto_mesh, make_mesh  # noqa: F401
from .dgc import dgc_allreduce, sparse_allgather_exchange, top_k_sparsify  # noqa: F401
from .local_sgd import (  # noqa: F401
    average_params, local_sgd_step, replicate_params)
from .moe import (  # noqa: F401
    init_moe_params, moe_ffn, moe_ffn_expert_parallel, top_k_gating)
from .pipeline import GPipe, pipeline_step  # noqa: F401
from .ring_attention import ring_attention, ring_self_attention  # noqa: F401
from .tensor_parallel import (MEGATRON_RULES, annotate_tp,  # noqa: F401
                              annotate_tp_auto, derive_tp_specs)
