"""Async, reshardable, crash-consistent training checkpoints.

Reference analog: save/load ops streamed per var (save_op.cc, load_op.cc;
io.py:487 save_persistables) plus the pserver checkpoint-notify hook
(distributed_ops/checkpoint_notify_op.cc). The reference cannot restore
under a different device topology (SURVEY §5 "no optimizer-state resharding
on topology change"), and a torn or bit-rotted checkpoint file kills the
restore outright; this module fixes both — the TPU-native bar.

Design (orbax-style, self-contained):
- `save` snapshots every persistable var to host (device→host copies are
  started async, then a background thread finishes materialization and
  writes the bundle) — the training loop resumes while the write is in
  flight;
- files are written to a temp name, fsynced, and renamed; a per-file
  SHA-256 **manifest** (``ckpt-<step>.manifest-<rank>.json``) is written
  last as the commit record — a preemption mid-write never corrupts the
  previous checkpoint, and a file torn *after* its rename (power loss,
  bitrot) is caught at restore;
- `restore` verifies the manifest before loading anything; on any
  corruption or partial write it walks back newest→older to the most
  recent checkpoint that verifies (``checkpoint/fallback_steps`` counter,
  warning naming the bad files) instead of raising and dying — a run
  resumes from the last GOOD checkpoint, never from a torn one;
- the background writer retries transient I/O errors with capped
  exponential backoff (``PDTPU_CKPT_RETRIES`` attempts,
  ``PDTPU_CKPT_RETRY_BACKOFF_MS`` base delay) before `wait()` surfaces
  the failure with the step and path;
- bundles store plain host arrays, so `restore` works under ANY mesh: the
  compiler lifts host values into whatever sharding the new topology
  declares (CompiledProgram._run), which is what makes checkpoints
  reshardable across dp/tp splits.

Crash-consistency is testable, not aspirational: ``paddle_tpu.faults``
probes (`ckpt.bundle_write`, `ckpt.rename`, `ckpt.shard_write`,
`ckpt.marker`) sit at every commit edge, and tests/test_elastic.py's
chaos matrix kills the writer at each of them.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time
import warnings
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.executor import _RNG_STATE
from ..core.program import Program, default_main_program
from ..core.scope import Scope, _scope
from ..faults import fault_point
from ..observability.registry import get_registry

_OBS = get_registry()
# restore skipped a bad checkpoint and fell back to an older one
_FALLBACK = _OBS.counter("checkpoint/fallback_steps")
# background writer retried a transient I/O failure
_RETRIES = _OBS.counter("checkpoint/write_retries")


def _is_replicated(v) -> bool:
    """Fully-replicated (or single-device) arrays go in the main bundle;
    anything actually sharded takes the per-shard path."""
    try:
        shards = v.addressable_shards
    except Exception:
        return True
    full = tuple(slice(None) for _ in v.shape)
    return all(tuple(s.index) == full for s in shards)


def _zero_state_var(var) -> bool:
    """ZeRO-shardable state (ShardingStrategy): optimizer accumulators,
    master weights, persistent gradient buffers — tagged at creation — and,
    under stage3 (full-parameter FSDP), the trainable parameters themselves.
    TP parameters (explicit `shard_spec`) are excluded: their layout is a
    deliberate model-parallel split, not a ZeRO annotation, so they keep the
    per-shard save path."""
    if var is None:
        return False
    if (getattr(var, "is_optimizer_state", False)
            or getattr(var, "is_master_weight", False)
            or getattr(var, "is_grad_buffer", False)):
        return True
    return bool(getattr(var, "trainable", False)
                and getattr(var, "persistable", False)
                and getattr(var, "shard_spec", None) is None)


def _snapshot(program: Program, scope: Scope):
    """(replicated_vals, shard_records): shard_records holds
    (var, index, device_buffer) triples for THIS process's addressable,
    replica-0 shards only — a sharded parameter is never all-gathered to
    host on the save path (VERDICT r2 #7; at pod scale the gather would
    materialize every parameter fully on every host).

    Exception: ZeRO-sharded optimizer state that is fully addressable and
    small (≤ PDTPU_CKPT_GATHER_MAX_BYTES, default 64 MiB) is gathered into
    the main bundle — the save gathers, the load re-shards, and the
    checkpoint stays a plain layout-independent bundle with no shard-file
    proliferation for every accumulator of every parameter."""
    import jax
    import jax.numpy as jnp

    gather_max = int(os.environ.get("PDTPU_CKPT_GATHER_MAX_BYTES",
                                    str(64 << 20)))
    pvars = {v.name: v for v in program.list_vars() if v.persistable}
    names = list(pvars)
    out = {}
    shard_records = []
    for n in names:
        v = scope.find_var(n)
        if v is None:
            continue
        if isinstance(v, jax.Array):
            if not _is_replicated(v):
                if (_zero_state_var(pvars.get(n))
                        and v.is_fully_addressable
                        and v.nbytes <= gather_max):
                    arr = np.asarray(v)  # host gather, layout erased
                    shp = tuple(pvars[n].shape or ())
                    if (shp and arr.shape != shp and len(arr.shape) == len(shp)
                            and all(a >= b for a, b in zip(arr.shape, shp))):
                        # ZeRO padding fallback stores the leaf padded to a
                        # dp multiple — persist the declared (logical) shape
                        arr = arr[tuple(slice(0, d) for d in shp)]
                    out[n] = arr
                    continue
                for s in v.addressable_shards:
                    if s.replica_id == 0:  # one copy of each distinct piece
                        # own copy: the next training step DONATES the live
                        # shard buffer while the background thread writes
                        d = jnp.copy(s.data)
                        if hasattr(d, "copy_to_host_async"):
                            try:
                                d.copy_to_host_async()
                            except Exception:
                                pass
                        shard_records.append(
                            (n, tuple((sl.start, sl.stop)
                                      for sl in _norm_index(s.index, v.shape)),
                             tuple(v.shape), str(v.dtype), d))
                continue
            # device-side copy: the training loop's next step DONATES the
            # live buffers, so the background writer must own its own copy;
            # then start the d2h transfer without blocking
            v = jnp.copy(v)
            if hasattr(v, "copy_to_host_async"):
                try:
                    v.copy_to_host_async()
                except Exception:
                    pass
        out[n] = v
    return out, shard_records


def _norm_index(index, shape):
    """Normalize a shard index (tuple of slices, possibly with None
    start/stop) to concrete [start, stop) per dim."""
    out = []
    for sl, dim in zip(index, shape):
        out.append(slice(sl.start or 0,
                         dim if sl.stop is None else sl.stop))
    return out


def _write_bytes(path: str, blob: bytes) -> Tuple[str, int]:
    """Write + fsync `blob` to `path`; returns (sha256 hex, size). The
    fsync keeps the manifest honest: once the hash is recorded the bytes
    it covers are durable, so a post-rename power loss can't produce a
    file that passes size checks but reads back zeros."""
    with open(path, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    return hashlib.sha256(blob).hexdigest(), len(blob)


def _hash_file(path: str) -> Tuple[str, int]:
    h = hashlib.sha256()
    size = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
            size += len(chunk)
    return h.hexdigest(), size


class Checkpointer:
    """`Checkpointer(dirname).save(step)` / `.restore()` over a Program's
    persistables. One background writer thread; `wait()` joins it.

    After a successful `restore()`, ``last_extra`` holds any ``@dataio@*``
    keys the checkpoint carried (the input-pipeline cursor `run_elastic`
    snapshots via ``save(extra=...)``)."""

    def __init__(self, dirname: str, keep: int = 3):
        self.dirname = dirname
        self.keep = keep
        self._thread: Optional[threading.Thread] = None
        # (exception, step, path, attempts) of a failed background write
        self._error: Optional[tuple] = None
        self._current_path: Optional[str] = None
        self.last_extra: Dict[str, object] = {}
        # incremental-checkpoint chain head: the committed full save (or
        # restore) deltas extend — {"step": int, "marks": {table: mark}}.
        # Advanced only by on-commit callbacks / restore, so an aborted
        # write never becomes a delta base.
        self._ps_base: Optional[Dict[str, object]] = None
        os.makedirs(dirname, exist_ok=True)

    def _path(self, step: int) -> str:
        # native bundle when the C++ writer is available, else pickle
        from ..native import available as _native_available
        ext = "ptck" if _native_available() else "pkl"
        return os.path.join(self.dirname, f"ckpt-{step}.{ext}")

    def _existing_path(self, step: int) -> Optional[str]:
        for ext in ("ptck", "pkl"):
            p = os.path.join(self.dirname, f"ckpt-{step}.{ext}")
            if os.path.exists(p):
                return p
        return None

    def _manifest_path(self, step: int, rank) -> str:
        return os.path.join(self.dirname, f"ckpt-{step}.manifest-{rank}.json")

    # -- background write --------------------------------------------------
    def _write(self, step: int, vals: Dict[str, object], shards=(),
               rank: int = 0, on_commit=()):
        """Writer-thread entry: retry transient I/O with capped exponential
        backoff; any residual failure is surfaced by the next wait()/save()
        (a silently lost checkpoint must not look durable). `on_commit`
        callbacks run only after the write fully commits (manifest +
        marker durable) — e.g. PS journal truncation, which must never
        happen for a checkpoint that might not be restorable."""
        retries = int(os.environ.get("PDTPU_CKPT_RETRIES", "3"))
        backoff_ms = float(os.environ.get("PDTPU_CKPT_RETRY_BACKOFF_MS",
                                          "100"))
        attempt = 0
        while True:
            try:
                self._write_impl(step, vals, shards, rank)
                for cb in on_commit:
                    try:
                        cb()
                    except Exception:
                        pass  # commit stands; truncation is best-effort
                return
            except OSError as e:
                # transient filesystem error (NFS blip, EIO, injected
                # fault): every tmp-write/rename in _write_impl is
                # idempotent, so the whole write can simply run again
                path = getattr(e, "filename", None) or self._current_path
                if attempt >= retries:
                    self._error = (e, step, path, attempt)
                    return
                _RETRIES.inc()
                time.sleep(min(backoff_ms * (2 ** attempt), 5000.0) / 1e3)
                attempt += 1
            except BaseException as e:
                self._error = (e, step, self._current_path, attempt)
                return

    def _write_shards(self, step: int, shards, rank: int,
                      manifest: Dict[str, dict]):
        """Per-process shard file + JSON index, both fsync+rename-durable
        and recorded in `manifest`. Each process writes ONLY its
        addressable replica-0 shards; restore merges every rank's index
        (shared-filesystem contract, same as the reference's save_combine
        to a common dirname)."""
        data = {}
        index: Dict[str, dict] = {}
        for name, bounds, shape, dtype, buf in shards:
            key = f"{name}@" + ",".join(f"{a}:{b}" for a, b in bounds)
            data[key] = np.asarray(buf)
            ent = index.setdefault(name, {"shape": list(shape),
                                          "dtype": dtype, "shards": []})
            ent["shards"].append({"key": key,
                                  "bounds": [list(b) for b in bounds]})
        spath = os.path.join(self.dirname, f"ckpt-{step}.shards-{rank}.pkl")
        self._current_path = spath
        digest, size = _write_bytes(spath + ".tmp",
                                    pickle.dumps(data, protocol=4))
        manifest[os.path.basename(spath)] = {"sha256": digest, "bytes": size}
        fault_point("ckpt.shard_write", path=spath + ".tmp")
        os.replace(spath + ".tmp", spath)
        ipath = os.path.join(self.dirname, f"ckpt-{step}.index-{rank}.json")
        self._current_path = ipath
        digest, size = _write_bytes(ipath + ".tmp",
                                    json.dumps(index).encode("utf-8"))
        manifest[os.path.basename(ipath)] = {"sha256": digest, "bytes": size}
        os.replace(ipath + ".tmp", ipath)

    def _write_manifest(self, step: int, rank, manifest: Dict[str, dict]):
        """The commit record: written LAST, after every file it hashes is
        durable under its final name. A step without its manifests is an
        uncommitted (or pre-manifest legacy) checkpoint."""
        mpath = self._manifest_path(step, rank)
        self._current_path = mpath
        blob = json.dumps({"step": step, "rank": rank, "files": manifest},
                          sort_keys=True).encode("utf-8")
        _write_bytes(mpath + ".tmp", blob)
        os.replace(mpath + ".tmp", mpath)

    def _write_impl(self, step: int, vals: Dict[str, object], shards=(),
                    rank: int = 0):
        manifest: Dict[str, dict] = {}
        if shards:
            self._write_shards(step, shards, rank, manifest)
        if rank != 0:
            if manifest:  # this rank's commit record for its shard files
                self._write_manifest(step, rank, manifest)
            return  # replicated vars + marker are rank 0's job
        bundle = {n: np.asarray(v) for n, v in vals.items()}
        path = self._path(step)
        tmp = path + ".tmp"
        self._current_path = path
        if path.endswith(".ptck"):
            # native framed writer (src/ckptio.cc — save_combine_op.cc
            # analog): buffered stdio + fsync off the Python thread
            from ..native import write_bundle
            nb = dict(bundle)
            nb["@step@"] = np.asarray(step, np.int64)
            if write_bundle(tmp, nb):
                digest, size = _hash_file(tmp)
            else:
                # honor write_bundle's documented contract: fall back to
                # pickle rather than losing the checkpoint
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                path = os.path.join(self.dirname, f"ckpt-{step}.pkl")
                tmp = path + ".tmp"
                self._current_path = path
                digest, size = _write_bytes(
                    tmp, pickle.dumps({"step": step, "vars": bundle},
                                      protocol=4))
        else:
            digest, size = _write_bytes(
                tmp, pickle.dumps({"step": step, "vars": bundle},
                                  protocol=4))
        manifest[os.path.basename(path)] = {"sha256": digest, "bytes": size}
        fault_point("ckpt.bundle_write", path=tmp)
        os.replace(tmp, path)  # atomic: never a half-written ckpt-N
        fault_point("ckpt.rename", path=path)
        self._write_manifest(step, 0, manifest)
        marker = os.path.join(self.dirname, "latest")
        self._current_path = marker
        _write_bytes(marker + ".tmp", str(step).encode("ascii"))
        fault_point("ckpt.marker", path=marker + ".tmp")
        os.replace(marker + ".tmp", marker)
        self._gc(step)

    def _gc(self, newest: int):
        steps = sorted(self.all_steps())
        for s in steps[:-self.keep] if self.keep else []:
            if s != newest:
                p = self._existing_path(s)
                if p:
                    try:
                        os.remove(p)
                    except OSError:
                        pass
                for f in os.listdir(self.dirname):
                    if (f.startswith(f"ckpt-{s}.shards-")
                            or f.startswith(f"ckpt-{s}.index-")
                            or f.startswith(f"ckpt-{s}.manifest-")
                            # a delta chain is anchored to its base full
                            # save: once the base is gone the chain can
                            # never replay
                            or f.startswith(f"delta-{s}-")):
                        try:
                            os.remove(os.path.join(self.dirname, f))
                        except OSError:
                            pass

    def all_steps(self):
        out = []
        for f in os.listdir(self.dirname):
            if f.startswith("ckpt-") and (f.endswith(".pkl")
                                          or f.endswith(".ptck")):
                try:
                    out.append(int(f[5:].rsplit(".", 1)[0]))
                except ValueError:
                    pass
        return out

    def latest_step(self) -> Optional[int]:
        marker = os.path.join(self.dirname, "latest")
        if os.path.exists(marker):
            s = None
            try:
                with open(marker) as f:
                    s = int(f.read().strip())
            except (ValueError, OSError):
                # empty or torn marker (crash between open and the rename,
                # or a pre-fsync power loss): fall back to the dir scan
                pass
            if s is not None and self._existing_path(s):
                return s
        steps = self.all_steps()
        return max(steps) if steps else None

    # -- integrity ---------------------------------------------------------
    def verify(self, step: int) -> List[str]:
        """Check every file the step's manifests list (existence, size,
        SHA-256). Returns [] when the step verifies. A step with no
        manifest at all (pre-manifest legacy writer, or a crash after the
        bundle rename but before the commit record) has nothing to check
        against and is trusted as-is — its bundle rename was atomic."""
        problems: List[str] = []
        prefix = f"ckpt-{step}.manifest-"
        for fname in sorted(os.listdir(self.dirname)):
            if not (fname.startswith(prefix) and fname.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.dirname, fname)) as f:
                    listed = json.load(f)["files"]
            except (OSError, ValueError, KeyError) as e:
                problems.append(f"{fname}: unreadable manifest "
                                f"({type(e).__name__}: {e})")
                continue
            for base, ent in sorted(listed.items()):
                p = os.path.join(self.dirname, base)
                try:
                    size = os.path.getsize(p)
                except OSError:
                    problems.append(
                        f"{base}: listed in manifest {fname} but missing")
                    continue
                if int(ent.get("bytes", -1)) != size:
                    problems.append(
                        f"{base}: size {size} != manifest's "
                        f"{ent.get('bytes')} (torn write)")
                    continue
                digest, _ = _hash_file(p)
                if digest != ent.get("sha256"):
                    problems.append(
                        f"{base}: sha256 mismatch vs manifest {fname} "
                        "(corrupt)")
        return problems

    def verified_steps(self) -> List[int]:
        """Every step whose manifest verification passes, newest first —
        the set a serving ModelRegistry may claim lineage from (a torn or
        corrupt training checkpoint never becomes a serving version)."""
        return [s for s in sorted(self.all_steps(), reverse=True)
                if not self.verify(s)]

    # -- incremental (delta) checkpoints ------------------------------------
    def _delta_path(self, base: int, dstep: int) -> str:
        return os.path.join(self.dirname, f"delta-{base}-{dstep}.pkl")

    def _delta_manifest_path(self, base: int, dstep: int) -> str:
        return os.path.join(self.dirname,
                            f"delta-{base}-{dstep}.manifest.json")

    def delta_steps(self, base: int) -> List[int]:
        """Delta steps on disk anchored to full checkpoint `base`,
        ascending (the chain replay order)."""
        out = []
        prefix = f"delta-{base}-"
        for f in os.listdir(self.dirname):
            if f.startswith(prefix) and f.endswith(".pkl"):
                try:
                    out.append(int(f[len(prefix):-len(".pkl")]))
                except ValueError:
                    pass
        return sorted(out)

    def verify_delta(self, base: int, dstep: int) -> List[str]:
        """Manifest check (existence, size, SHA-256) for one delta file;
        [] when it verifies. A delta with no manifest is uncommitted."""
        problems: List[str] = []
        mpath = self._delta_manifest_path(base, dstep)
        try:
            with open(mpath) as f:
                listed = json.load(f)["files"]
        except (OSError, ValueError, KeyError) as e:
            return [f"{os.path.basename(mpath)}: unreadable manifest "
                    f"({type(e).__name__}: {e})"]
        for bname, ent in sorted(listed.items()):
            p = os.path.join(self.dirname, bname)
            try:
                size = os.path.getsize(p)
            except OSError:
                problems.append(f"{bname}: listed in manifest but missing")
                continue
            if int(ent.get("bytes", -1)) != size:
                problems.append(f"{bname}: size {size} != manifest's "
                                f"{ent.get('bytes')} (torn write)")
                continue
            digest, _ = _hash_file(p)
            if digest != ent.get("sha256"):
                problems.append(f"{bname}: sha256 mismatch (corrupt)")
        return problems

    def _delta_chain(self, base: int) -> List[dict]:
        """The longest verifiable prefix of `base`'s delta chain, as
        loaded payload dicts in ascending delta-step order. The walk
        stops at the first unverifiable/unreadable file: every delta
        after a hole is built over state the restore cannot reconstruct,
        so applying it would be silently lossy."""
        chain: List[dict] = []
        for ds in self.delta_steps(base):
            bad = self.verify_delta(base, ds)
            payload = None
            if not bad:
                try:
                    with open(self._delta_path(base, ds), "rb") as f:
                        payload = pickle.load(f)
                except (OSError, EOFError, ValueError,
                        pickle.UnpicklingError) as e:
                    bad = [f"{type(e).__name__}: {e}"]
            if bad:
                warnings.warn(
                    f"delta checkpoint {base}->{ds} in {self.dirname!r} "
                    f"failed verification ({'; '.join(bad)}); stopping the "
                    "delta replay chain here", RuntimeWarning)
                _FALLBACK.inc()
                break
            chain.append(payload)
        return chain

    @staticmethod
    def _apply_delta_chain(chain: List[dict], tname: str,
                           rows: np.ndarray, mark: int):
        """Replay one table's entries from an already-verified chain onto
        the dense `rows` array, in delta order then seq order (scatter-SET
        of absolute rows ⇒ ordered replay is bitwise-exact). Stops at a
        mark discontinuity (a delta whose ``since_mark`` doesn't extend
        the state we hold). Returns (rows, final_mark, deltas_applied)."""
        applied = 0
        for payload in chain:
            blob = (payload.get("tables") or {}).get(tname)
            if blob is None:
                continue
            if int(blob["since_mark"]) != int(mark):
                break
            off = 0
            ids = np.asarray(blob["ids"], np.int64)
            drows = np.asarray(blob["rows"], np.uint16)
            for c in np.asarray(blob["counts"], np.int64).tolist():
                rows[ids[off:off + c]] = drows[off:off + c]
                off += c
            mark = int(blob["mark"])
            applied += 1
        return rows, mark, applied

    def save_delta(self, step: int, ps_tables: Dict[str, object],
                   extra: Optional[Dict[str, object]] = None,
                   blocking: bool = False) -> None:
        """Incremental PS checkpoint: persist only the rows touched since
        the chain head — the journal entries past the last full save's
        (or previous delta's) mark — as ``delta-<base>-<step>.pkl`` plus
        a SHA-256 manifest, committed tmp→fsync→rename like everything
        else. Orders of magnitude smaller than a full dump for a big
        table, so it can run every few seconds on an online trainer.

        Riding the PR 10 journal machinery: each table's flush hook runs
        first (device-dirty rows + queued async pushes land in the
        journal), the snapshot is the journal slice ``(since_mark,
        mark]``, and the journal is truncated to `mark` once — and only
        once — the delta COMMITS, which is what keeps journal memory
        bounded by delta cadence on an unbounded stream.

        Requires a committed full ``save(ps_tables=...)`` (or a
        ``restore``) as the chain base; ``save()`` is the compaction
        point — it rewrites the whole table and starts a fresh chain.
        Restore replays: newest verified full + its chain in order,
        bitwise-exact (see ``restore``/``load_ps_table``)."""
        if not ps_tables:
            raise ValueError("save_delta: ps_tables is required (a delta "
                             "checkpoint IS the PS-table increment)")
        self.wait()  # one write in flight at a time; surfaces prior errors
        base = self._ps_base
        if base is None:
            raise RuntimeError(
                "save_delta: no committed full checkpoint to anchor the "
                "delta chain — call save(ps_tables=...) (or restore) first")
        base_step = int(base["step"])
        marks: Dict[str, int] = dict(base["marks"])  # type: ignore[arg-type]
        tables_blob: Dict[str, dict] = {}
        on_commit = []
        for tname, table in ps_tables.items():
            hook = getattr(table, "flush_hook", None)
            if hook is not None:
                hook()
            since = int(marks.get(tname, 0))
            mark = int(table.journal_mark())
            entries = [e for e in table.journal_entries_since(since)
                       if e[0] <= mark]
            lanes = int(table.lanes)
            if entries:
                ids = np.concatenate([e[1] for e in entries])
                rows = np.concatenate([e[2] for e in entries], axis=0)
            else:
                ids = np.zeros((0,), np.int64)
                rows = np.zeros((0, lanes), np.uint16)
            tables_blob[tname] = {
                "since_mark": since, "mark": mark,
                "seqs": np.asarray([e[0] for e in entries], np.int64),
                "counts": np.asarray([e[1].shape[0] for e in entries],
                                     np.int64),
                "ids": ids, "rows": rows, "lanes": lanes,
                "vocab": int(table.spec.vocab),
            }
            marks[tname] = mark
            on_commit.append(lambda t=table, m=mark: t.journal_truncate(m))
        on_commit.append(lambda s=base_step, m=dict(marks):
                         self._set_ps_base(s, m))
        vals = {k: np.asarray(v) for k, v in (extra or {}).items()}
        self._thread = threading.Thread(
            target=self._write_delta,
            args=(base_step, int(step), tables_blob, vals, on_commit),
            daemon=True)
        self._thread.start()
        if blocking:
            self.wait()

    def _write_delta(self, base_step: int, step: int, tables_blob: dict,
                     vals: dict, on_commit=()):
        """Writer-thread entry for a delta (same retry/commit contract as
        `_write`: manifest last, on_commit only after it is durable)."""
        retries = int(os.environ.get("PDTPU_CKPT_RETRIES", "3"))
        backoff_ms = float(os.environ.get("PDTPU_CKPT_RETRY_BACKOFF_MS",
                                          "100"))
        attempt = 0
        while True:
            try:
                payload = {"base_step": base_step, "step": step,
                           "tables": tables_blob, "extra": vals}
                path = self._delta_path(base_step, step)
                self._current_path = path
                manifest: Dict[str, dict] = {}
                digest, size = _write_bytes(
                    path + ".tmp", pickle.dumps(payload, protocol=4))
                manifest[os.path.basename(path)] = {"sha256": digest,
                                                    "bytes": size}
                fault_point("ckpt.delta_write", path=path + ".tmp")
                os.replace(path + ".tmp", path)
                mpath = self._delta_manifest_path(base_step, step)
                self._current_path = mpath
                blob = json.dumps({"step": step, "base_step": base_step,
                                   "files": manifest},
                                  sort_keys=True).encode("utf-8")
                _write_bytes(mpath + ".tmp", blob)
                os.replace(mpath + ".tmp", mpath)
                for cb in on_commit:
                    try:
                        cb()
                    except Exception:
                        pass  # commit stands; truncation is best-effort
                return
            except OSError as e:
                path = getattr(e, "filename", None) or self._current_path
                if attempt >= retries:
                    self._error = (e, step, path, attempt)
                    return
                _RETRIES.inc()
                time.sleep(min(backoff_ms * (2 ** attempt), 5000.0) / 1e3)
                attempt += 1
            except BaseException as e:
                self._error = (e, step, self._current_path, attempt)
                return

    def _set_ps_base(self, step: int, marks: Dict[str, int]) -> None:
        self._ps_base = {"step": int(step),
                         "marks": {k: int(v) for k, v in marks.items()}}

    def load_ps_table(self, tname: str):
        """Shard-recovery read path: ``(full_rows, journal_mark, step)``
        for PS table `tname` from the newest checkpoint that passes
        integrity verification. Touches no scope and needs no Program —
        it is called from inside the tier's pull/push threads while the
        training loop is blocked on the dead shard. Deliberately does NOT
        ``wait()`` on an in-flight save: an uncommitted step has no
        manifest yet and simply isn't a candidate."""
        psn = f"{tname}@ps"
        failures: List[str] = []
        for st in sorted(set(self.all_steps()), reverse=True):
            path = self._existing_path(st)
            if path is None:
                continue
            bad = self.verify(st)
            if not bad:
                try:
                    if path.endswith(".ptck"):
                        from ..native import read_bundle
                        bundle = read_bundle(path)
                        if bundle is None:
                            raise RuntimeError(
                                f"cannot read native checkpoint {path}")
                    else:
                        with open(path, "rb") as f:
                            bundle = pickle.load(f)["vars"]
                    assembled = self._assemble_shards(st)
                    if psn not in assembled:
                        raise RuntimeError(f"no {psn!r} shards")
                    mark = int(np.asarray(
                        bundle.get(f"@ps_mark@{tname}", 0)).reshape(()))
                    # replay the verified delta chain: a shard recovered
                    # mid-stream gets full ∘ deltas, and the returned mark
                    # is the last delta's so the client replays only the
                    # journal tail past it
                    rows, mark, _ = self._apply_delta_chain(
                        self._delta_chain(st), tname, assembled[psn], mark)
                    return rows, mark, st
                except (RuntimeError, OSError, EOFError, ValueError,
                        pickle.UnpicklingError) as e:
                    bad = [f"{type(e).__name__}: {e}"]
            failures.append(f"step {st}: {'; '.join(bad)}")
            _FALLBACK.inc()
        raise RuntimeError(
            f"ps recovery: no verifiable checkpoint holding table "
            f"{tname!r} in {self.dirname!r}"
            + (f" ({' | '.join(failures)})" if failures else
               " (no checkpoints at all — save one before training so a "
               "restarted shard has a recovery base)"))

    # -- save --------------------------------------------------------------
    def save(self, step: int, program: Optional[Program] = None,
             scope: Optional[Scope] = None, blocking: bool = False,
             extra: Optional[Dict[str, object]] = None,
             ps_tables: Optional[Dict[str, object]] = None):
        """Snapshot now, write in the background (orbax async-save shape).

        `extra` rides in the bundle verbatim (numpy-converted) — e.g.
        ``@dataio@*`` input-pipeline cursors. Keys should start with ``@``
        so they can never collide with a program variable.

        `ps_tables` ({table_name: ps.ShardedTable}) adds the PS embedding
        tier's shards to the same per-rank shard files + manifest path:
        each shard's slice is dumped NOW (snapshot semantics — flush the
        tier's pushers first) under the ``<name>@ps`` key, one record per
        shard, so a shard's bytes ride the identical tmp→fsync→rename +
        SHA-256 commit protocol as a ZeRO-sharded var. The table's push
        journal mark rides along as ``@ps_mark@<name>`` (read back by
        shard recovery) and the journal is truncated to it once — and
        only once — this checkpoint COMMITS."""
        import jax

        program = program or default_main_program()
        scope = scope or _scope()
        self.wait()  # one write in flight at a time
        vals, shards = _snapshot(program, scope)
        shards = list(shards)
        ps_names = []
        on_commit = []
        ps_marks_now: Dict[str, int] = {}
        for tname, table in (ps_tables or {}).items():
            psn = f"{tname}@ps"
            ps_names.append(psn)
            spec, lanes = table.spec, table.lanes
            hook = getattr(table, "flush_hook", None)
            if hook is not None:
                # flush-before-save: the tier writes back device-resident
                # dirty rows (hot cache) and drains its pusher, so the
                # mark taken below covers every update the dumps contain
                hook()
            if hasattr(table, "journal_mark"):
                # mark BEFORE the dumps: an entry with seq <= mark was
                # applied before the caller's flush, so the dumped bytes
                # contain it; a racing push lands at seq > mark and stays
                # journaled (replay is idempotent either way)
                mark = int(table.journal_mark())
                vals[f"@ps_mark@{tname}"] = np.asarray(mark, np.int64)
                ps_marks_now[tname] = mark
                on_commit.append(
                    lambda t=table, m=mark: t.journal_truncate(m))
            for i in range(spec.num_shards):
                lo, hi = spec.bounds(i)
                shards.append((psn, ((lo, hi), (0, lanes)),
                               (spec.vocab, lanes), "uint16",
                               table.dump_shard(i)))
        if ps_names:
            # a committed full save is the new delta-chain head (the
            # compaction point): subsequent save_delta() calls extend it
            on_commit.append(lambda s=int(step), m=dict(ps_marks_now):
                             self._set_ps_base(s, m))
        rank = jax.process_index()
        if ps_names:
            # restore-side coverage check: which PS tables this
            # checkpoint is supposed to contain
            vals["@ps_manifest@"] = np.asarray("\n".join(sorted(ps_names)))
        if rank == 0:
            # manifest of every sharded var name (ADVICE r3): rank 0 sees
            # the GLOBAL sharding of each array even though it holds only
            # its own addressable shards, so it can record which vars must
            # be fully assembled from the per-rank shard files on restore.
            # Without this, a rank whose index file is missing entirely
            # (crash between rank-0's marker write and a slow rank's
            # background write — there is no cross-rank barrier) could
            # leave a var it exclusively held at its init value, silently.
            sharded = [v.name for v in program.list_vars() if v.persistable
                       and v.name not in vals  # gathered ZeRO state is
                       # already in the bundle — not shard-file material
                       and isinstance(scope.find_var(v.name), jax.Array)
                       and not _is_replicated(scope.find_var(v.name))]
            if sharded:
                vals["@shard_manifest@"] = np.asarray(
                    "\n".join(sorted(sharded)))
        rng = scope.find_var(_RNG_STATE)
        if rng is not None:
            if jax.dtypes.issubdtype(getattr(rng, "dtype", None),
                                     jax.dtypes.prng_key):
                # typed keys can't cross numpy; store raw data + impl name
                vals["@rng@"] = np.asarray(jax.random.key_data(rng))
                vals["@rng_impl@"] = np.asarray(
                    str(jax.random.key_impl(rng)))
            else:
                vals["@rng@"] = np.asarray(rng)
        for k, v in (extra or {}).items():
            vals[k] = np.asarray(v)
        self._thread = threading.Thread(
            target=self._write, args=(step, vals, shards, rank, on_commit),
            daemon=True)
        self._thread.start()
        if blocking:
            self.wait()

    def wait(self):
        """Join the in-flight write; re-raises a writer failure naming the
        step and the failing path (a silently lost checkpoint must not
        look durable)."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            (err, step, path, attempts), self._error = self._error, None
            where = f" (path {path!r})" if path else ""
            tried = (f" after {attempts + 1} attempts"
                     if isinstance(err, OSError) and attempts else "")
            raise RuntimeError(
                f"checkpoint write failed at step {step}{where}{tried}"
            ) from err

    # -- restore -----------------------------------------------------------
    def _assemble_shards(self, step: int) -> Dict[str, np.ndarray]:
        """Merge every rank's shard files into full host arrays: works
        under ANY process count / mesh on restore — the reshardable part of
        the contract. Missing coverage raises instead of returning
        silently-partial parameters."""
        out: Dict[str, np.ndarray] = {}
        placed: Dict[str, int] = {}
        for fname in sorted(os.listdir(self.dirname)):
            if not (fname.startswith(f"ckpt-{step}.index-")
                    and fname.endswith(".json")):
                continue
            rank = fname[len(f"ckpt-{step}.index-"):-len(".json")]
            with open(os.path.join(self.dirname, fname)) as f:
                index = json.load(f)
            spath = os.path.join(self.dirname,
                                 f"ckpt-{step}.shards-{rank}.pkl")
            with open(spath, "rb") as f:
                data = pickle.load(f)
            for name, ent in index.items():
                if name not in out:
                    out[name] = np.empty(tuple(ent["shape"],),
                                         dtype=ent["dtype"])
                    placed[name] = 0
                for sh in ent["shards"]:
                    sl = tuple(slice(a, b) for a, b in sh["bounds"])
                    piece = data[sh["key"]]
                    out[name][sl] = piece
                    placed[name] += int(piece.size)
        for name, arr in out.items():
            if placed[name] < arr.size:
                raise RuntimeError(
                    f"checkpoint step {step}: sharded var {name!r} has only "
                    f"{placed[name]}/{arr.size} elements across the rank "
                    f"index files — a rank's shard file is missing")
        return out

    def _load_step(self, step: int, path: str, program: Program):
        """Read + assemble one checkpoint WITHOUT touching the scope: any
        read error or shard-coverage gap surfaces here, before a single
        var is mutated, so the fallback walk never leaves the scope
        half-restored."""
        if path.endswith(".ptck"):
            from ..native import read_bundle
            bundle = read_bundle(path)
            if bundle is None:
                raise RuntimeError(f"cannot read native checkpoint {path}")
            bundle.pop("@step@", None)
            vars_ = bundle
        else:
            with open(path, "rb") as f:
                vars_ = pickle.load(f)["vars"]
        names = {v.name for v in program.list_vars() if v.persistable}
        manifest_raw = vars_.pop("@shard_manifest@", None)
        ps_manifest_raw = vars_.pop("@ps_manifest@", None)
        assembled = self._assemble_shards(step)
        if manifest_raw is not None:
            # backends may round-trip the string as a 0-d or 1-element array
            raw = np.asarray(manifest_raw).ravel()
            expected = set("\n".join(str(x) for x in raw).split("\n"))
            missing = sorted((expected & names) - set(assembled))
            if missing:
                raise RuntimeError(
                    f"checkpoint step {step}: sharded vars {missing} are in "
                    "the save-time manifest but absent from every rank's "
                    "index file — a rank's shard/index files are missing "
                    "(e.g. crash between rank-0's marker write and that "
                    "rank's background shard write)")
        if ps_manifest_raw is not None:
            raw = np.asarray(ps_manifest_raw).ravel()
            expected_ps = set("\n".join(str(x) for x in raw).split("\n"))
            missing_ps = sorted(expected_ps - set(assembled))
            if missing_ps:
                raise RuntimeError(
                    f"checkpoint step {step}: PS tables {missing_ps} are "
                    "in the save-time manifest but absent from every "
                    "rank's index file — the shard files are missing")
        # `@ps`-suffixed names are never program vars, so the `n in names`
        # filter below keeps them out of the scope; they flow back through
        # the fourth return for ShardedTable.load_full
        to_set = {n: arr for n, arr in vars_.items() if n in names}
        to_set.update({n: a for n, a in assembled.items() if n in names})
        rng_key = None
        if "@rng@" in vars_:  # resume the random stream too
            import jax
            import jax.numpy as jnp
            raw = vars_["@rng@"]
            impl = vars_.get("@rng_impl@")
            if impl is not None:
                rng_key = jax.random.wrap_key_data(jnp.asarray(raw),
                                                   impl=str(impl))
            else:
                rng_key = jnp.asarray(raw)
        extra = {k: v for k, v in vars_.items() if k.startswith("@dataio@")}
        ps_marks = {k[len("@ps_mark@"):]: int(np.asarray(v).reshape(()))
                    for k, v in vars_.items()
                    if k.startswith("@ps_mark@")}
        return to_set, rng_key, extra, assembled, ps_marks

    def restore(self, step: Optional[int] = None,
                program: Optional[Program] = None,
                scope: Optional[Scope] = None,
                ps_tables: Optional[Dict[str, object]] = None
                ) -> Optional[int]:
        """Load a checkpoint into the scope as host arrays; the next
        compiled step lifts them into the current mesh's shardings — save
        under dp=8, restore under dp=4×tp=2 just works.

        With ``step=None`` the newest checkpoint that passes integrity
        verification wins: a corrupt/torn candidate is skipped with a
        warning naming the bad files (``checkpoint/fallback_steps``
        counter), and the walk continues to older steps. Only when EVERY
        candidate fails does restore raise. An explicit ``step`` is loaded
        or fails — no silent substitution.

        `ps_tables` ({table_name: ps.ShardedTable}) restores PS embedding
        shards too: the checkpoint's ``<name>@ps`` slices are assembled
        into the full table and re-partitioned onto each table's LIVE
        range spec — restoring onto a different shard count than the save
        just works. Coverage is validated BEFORE the scope or any shard is
        mutated; a candidate missing a requested table falls back like any
        other integrity failure."""
        program = program or default_main_program()
        scope = scope or _scope()
        self.wait()
        self.last_extra = {}
        if step is not None:
            candidates = [step]
        else:
            candidates = sorted(set(self.all_steps()), reverse=True)
        failures: List[str] = []
        for st in candidates:
            path = self._existing_path(st)
            if path is None:
                continue
            bad = self.verify(st)
            loaded = None
            if not bad:
                try:
                    loaded = self._load_step(st, path, program)
                except (RuntimeError, OSError, EOFError, ValueError,
                        pickle.UnpicklingError) as e:
                    bad = [f"{os.path.basename(path)}: "
                           f"{type(e).__name__}: {e}"]
            if not bad and loaded is not None and ps_tables:
                # every requested table must be fully present with the
                # right geometry before ANY state mutates
                assembled = loaded[3]
                for tname, table in ps_tables.items():
                    psn = f"{tname}@ps"
                    want = (table.spec.vocab, table.lanes)
                    if psn not in assembled:
                        bad.append(f"PS table {tname!r}: no {psn!r} "
                                   "shards in this checkpoint")
                    elif assembled[psn].shape != want:
                        bad.append(
                            f"PS table {tname!r}: checkpoint shape "
                            f"{assembled[psn].shape} != live {want}")
            if bad:
                desc = "; ".join(bad)
                failures.append(f"step {st}: {desc}")
                _FALLBACK.inc()
                warnings.warn(
                    f"checkpoint step {st} in {self.dirname!r} failed "
                    f"integrity verification ({desc}); falling back to the "
                    "next older checkpoint", RuntimeWarning)
                continue
            to_set, rng_key, extra, assembled, ps_marks = loaded
            # incremental checkpoints: replay this full save's verified
            # delta chain onto the assembled PS tables BEFORE any state
            # mutates — the restored bytes are full ∘ deltas, bitwise
            # identical to the table at the last committed save_delta
            chain = self._delta_chain(st) if ps_tables else []
            final_marks: Dict[str, int] = {}
            for tname in (ps_tables or {}):
                rows, fmark, _ = self._apply_delta_chain(
                    chain, tname, assembled[f"{tname}@ps"],
                    int(ps_marks.get(tname, 0)))
                assembled[f"{tname}@ps"] = rows
                final_marks[tname] = fmark
            for payload in chain:
                extra.update({k: v for k, v in
                              (payload.get("extra") or {}).items()
                              if k.startswith("@dataio@")})
            for n, arr in to_set.items():
                scope.set_var(n, arr)
            if rng_key is not None:
                scope.set_var(_RNG_STATE, rng_key)
            for tname, table in (ps_tables or {}).items():
                table.load_full(assembled[f"{tname}@ps"])
                if hasattr(table, "journal_reset"):
                    # the live journal (possibly from another process
                    # lifetime) no longer describes deltas over what was
                    # just loaded; re-anchor it at the restored mark —
                    # the last applied delta's, else the full save's
                    table.journal_reset(int(final_marks.get(tname, 0)))
            if ps_tables:
                # restore re-anchors the delta chain: new deltas extend
                # from exactly the state just loaded
                self._set_ps_base(st, final_marks)
            self.last_extra = extra
            return st
        if failures:
            raise RuntimeError(
                f"no verifiable checkpoint in {self.dirname!r}; every "
                "candidate failed integrity verification: "
                + " | ".join(failures))
        return None


def save_checkpoint(dirname: str, step: int, program=None, scope=None,
                    blocking: bool = True):
    ck = Checkpointer(dirname)
    ck.save(step, program=program, scope=scope, blocking=blocking)
    return ck


def load_checkpoint(dirname: str, program=None, scope=None,
                    step: Optional[int] = None):
    return Checkpointer(dirname).restore(step, program=program, scope=scope)
