"""Async, reshardable training checkpoints.

Reference analog: save/load ops streamed per var (save_op.cc, load_op.cc;
io.py:487 save_persistables) plus the pserver checkpoint-notify hook
(distributed_ops/checkpoint_notify_op.cc). The reference cannot restore
under a different device topology (SURVEY §5 "no optimizer-state resharding
on topology change"); this module can — the TPU-native bar.

Design (orbax-style, self-contained):
- `save` snapshots every persistable var to host (device→host copies are
  started async, then a background thread finishes materialization and
  writes the bundle) — the training loop resumes while the write is in
  flight;
- files are written to a temp name and renamed, and the `latest` marker is
  updated only after the bundle is durable — a preemption mid-write never
  corrupts the previous checkpoint;
- bundles store plain host arrays, so `restore` works under ANY mesh: the
  compiler lifts host values into whatever sharding the new topology
  declares (CompiledProgram._run), which is what makes checkpoints
  reshardable across dp/tp splits.
"""
from __future__ import annotations

import os
import pickle
import threading
from typing import Dict, Optional

import numpy as np

from ..core.executor import _RNG_STATE
from ..core.program import Program, default_main_program
from ..core.scope import Scope, _scope


def _is_replicated(v) -> bool:
    """Fully-replicated (or single-device) arrays go in the main bundle;
    anything actually sharded takes the per-shard path."""
    try:
        shards = v.addressable_shards
    except Exception:
        return True
    full = tuple(slice(None) for _ in v.shape)
    return all(tuple(s.index) == full for s in shards)


def _zero_state_var(var) -> bool:
    """ZeRO-shardable state (ShardingStrategy): optimizer accumulators,
    master weights, persistent gradient buffers — tagged at creation — and,
    under stage3 (full-parameter FSDP), the trainable parameters themselves.
    TP parameters (explicit `shard_spec`) are excluded: their layout is a
    deliberate model-parallel split, not a ZeRO annotation, so they keep the
    per-shard save path."""
    if var is None:
        return False
    if (getattr(var, "is_optimizer_state", False)
            or getattr(var, "is_master_weight", False)
            or getattr(var, "is_grad_buffer", False)):
        return True
    return bool(getattr(var, "trainable", False)
                and getattr(var, "persistable", False)
                and getattr(var, "shard_spec", None) is None)


def _snapshot(program: Program, scope: Scope):
    """(replicated_vals, shard_records): shard_records holds
    (var, index, device_buffer) triples for THIS process's addressable,
    replica-0 shards only — a sharded parameter is never all-gathered to
    host on the save path (VERDICT r2 #7; at pod scale the gather would
    materialize every parameter fully on every host).

    Exception: ZeRO-sharded optimizer state that is fully addressable and
    small (≤ PDTPU_CKPT_GATHER_MAX_BYTES, default 64 MiB) is gathered into
    the main bundle — the save gathers, the load re-shards, and the
    checkpoint stays a plain layout-independent bundle with no shard-file
    proliferation for every accumulator of every parameter."""
    import jax
    import jax.numpy as jnp

    gather_max = int(os.environ.get("PDTPU_CKPT_GATHER_MAX_BYTES",
                                    str(64 << 20)))
    pvars = {v.name: v for v in program.list_vars() if v.persistable}
    names = list(pvars)
    out = {}
    shard_records = []
    for n in names:
        v = scope.find_var(n)
        if v is None:
            continue
        if isinstance(v, jax.Array):
            if not _is_replicated(v):
                if (_zero_state_var(pvars.get(n))
                        and v.is_fully_addressable
                        and v.nbytes <= gather_max):
                    arr = np.asarray(v)  # host gather, layout erased
                    shp = tuple(pvars[n].shape or ())
                    if (shp and arr.shape != shp and len(arr.shape) == len(shp)
                            and all(a >= b for a, b in zip(arr.shape, shp))):
                        # ZeRO padding fallback stores the leaf padded to a
                        # dp multiple — persist the declared (logical) shape
                        arr = arr[tuple(slice(0, d) for d in shp)]
                    out[n] = arr
                    continue
                for s in v.addressable_shards:
                    if s.replica_id == 0:  # one copy of each distinct piece
                        # own copy: the next training step DONATES the live
                        # shard buffer while the background thread writes
                        d = jnp.copy(s.data)
                        if hasattr(d, "copy_to_host_async"):
                            try:
                                d.copy_to_host_async()
                            except Exception:
                                pass
                        shard_records.append(
                            (n, tuple((sl.start, sl.stop)
                                      for sl in _norm_index(s.index, v.shape)),
                             tuple(v.shape), str(v.dtype), d))
                continue
            # device-side copy: the training loop's next step DONATES the
            # live buffers, so the background writer must own its own copy;
            # then start the d2h transfer without blocking
            v = jnp.copy(v)
            if hasattr(v, "copy_to_host_async"):
                try:
                    v.copy_to_host_async()
                except Exception:
                    pass
        out[n] = v
    return out, shard_records


def _norm_index(index, shape):
    """Normalize a shard index (tuple of slices, possibly with None
    start/stop) to concrete [start, stop) per dim."""
    out = []
    for sl, dim in zip(index, shape):
        out.append(slice(sl.start or 0,
                         dim if sl.stop is None else sl.stop))
    return out


class Checkpointer:
    """`Checkpointer(dirname).save(step)` / `.restore()` over a Program's
    persistables. One background writer thread; `wait()` joins it."""

    def __init__(self, dirname: str, keep: int = 3):
        self.dirname = dirname
        self.keep = keep
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        os.makedirs(dirname, exist_ok=True)

    def _path(self, step: int) -> str:
        # native bundle when the C++ writer is available, else pickle
        from ..native import available as _native_available
        ext = "ptck" if _native_available() else "pkl"
        return os.path.join(self.dirname, f"ckpt-{step}.{ext}")

    def _existing_path(self, step: int) -> Optional[str]:
        for ext in ("ptck", "pkl"):
            p = os.path.join(self.dirname, f"ckpt-{step}.{ext}")
            if os.path.exists(p):
                return p
        return None

    def _write(self, step: int, vals: Dict[str, object], shards=(),
               rank: int = 0):
        try:
            self._write_impl(step, vals, shards, rank)
        except BaseException as e:  # surfaced by the next wait()/save()
            self._error = e

    def _write_shards(self, step: int, shards, rank: int):
        """Per-process shard file + JSON index, both rename-durable. Each
        process writes ONLY its addressable replica-0 shards; restore
        merges every rank's index (shared-filesystem contract, same as the
        reference's save_combine to a common dirname)."""
        import json

        data = {}
        index: Dict[str, dict] = {}
        for name, bounds, shape, dtype, buf in shards:
            key = f"{name}@" + ",".join(f"{a}:{b}" for a, b in bounds)
            data[key] = np.asarray(buf)
            ent = index.setdefault(name, {"shape": list(shape),
                                          "dtype": dtype, "shards": []})
            ent["shards"].append({"key": key,
                                  "bounds": [list(b) for b in bounds]})
        spath = os.path.join(self.dirname, f"ckpt-{step}.shards-{rank}.pkl")
        with open(spath + ".tmp", "wb") as f:
            pickle.dump(data, f, protocol=4)
        os.replace(spath + ".tmp", spath)
        ipath = os.path.join(self.dirname, f"ckpt-{step}.index-{rank}.json")
        with open(ipath + ".tmp", "w") as f:
            json.dump(index, f)
        os.replace(ipath + ".tmp", ipath)

    def _write_impl(self, step: int, vals: Dict[str, object], shards=(),
                    rank: int = 0):
        if shards:
            self._write_shards(step, shards, rank)
        if rank != 0:
            return  # replicated vars + marker are rank 0's job
        bundle = {n: np.asarray(v) for n, v in vals.items()}
        path = self._path(step)
        tmp = path + ".tmp"
        if path.endswith(".ptck"):
            # native framed writer (src/ckptio.cc — save_combine_op.cc
            # analog): buffered stdio + fsync off the Python thread
            from ..native import write_bundle
            bundle["@step@"] = np.asarray(step, np.int64)
            if not write_bundle(tmp, bundle):
                # honor write_bundle's documented contract: fall back to
                # pickle rather than losing the checkpoint
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                path = os.path.join(self.dirname, f"ckpt-{step}.pkl")
                tmp = path + ".tmp"
                bundle.pop("@step@", None)
                with open(tmp, "wb") as f:
                    pickle.dump({"step": step, "vars": bundle}, f,
                                protocol=4)
        else:
            with open(tmp, "wb") as f:
                pickle.dump({"step": step, "vars": bundle}, f, protocol=4)
        os.replace(tmp, path)  # atomic: never a half-written ckpt-N
        marker = os.path.join(self.dirname, "latest")
        with open(marker + ".tmp", "w") as f:
            f.write(str(step))
        os.replace(marker + ".tmp", marker)
        self._gc(step)

    def _gc(self, newest: int):
        steps = sorted(self.all_steps())
        for s in steps[:-self.keep] if self.keep else []:
            if s != newest:
                p = self._existing_path(s)
                if p:
                    try:
                        os.remove(p)
                    except OSError:
                        pass
                for f in os.listdir(self.dirname):
                    if (f.startswith(f"ckpt-{s}.shards-")
                            or f.startswith(f"ckpt-{s}.index-")):
                        try:
                            os.remove(os.path.join(self.dirname, f))
                        except OSError:
                            pass

    def all_steps(self):
        out = []
        for f in os.listdir(self.dirname):
            if f.startswith("ckpt-") and (f.endswith(".pkl")
                                          or f.endswith(".ptck")):
                try:
                    out.append(int(f[5:].rsplit(".", 1)[0]))
                except ValueError:
                    pass
        return out

    def latest_step(self) -> Optional[int]:
        marker = os.path.join(self.dirname, "latest")
        if os.path.exists(marker):
            with open(marker) as f:
                s = int(f.read().strip())
            if self._existing_path(s):
                return s
        steps = self.all_steps()
        return max(steps) if steps else None

    def save(self, step: int, program: Optional[Program] = None,
             scope: Optional[Scope] = None, blocking: bool = False):
        """Snapshot now, write in the background (orbax async-save shape)."""
        import jax

        program = program or default_main_program()
        scope = scope or _scope()
        self.wait()  # one write in flight at a time
        vals, shards = _snapshot(program, scope)
        rank = jax.process_index()
        if rank == 0:
            # manifest of every sharded var name (ADVICE r3): rank 0 sees
            # the GLOBAL sharding of each array even though it holds only
            # its own addressable shards, so it can record which vars must
            # be fully assembled from the per-rank shard files on restore.
            # Without this, a rank whose index file is missing entirely
            # (crash between rank-0's marker write and a slow rank's
            # background write — there is no cross-rank barrier) could
            # leave a var it exclusively held at its init value, silently.
            sharded = [v.name for v in program.list_vars() if v.persistable
                       and v.name not in vals  # gathered ZeRO state is
                       # already in the bundle — not shard-file material
                       and isinstance(scope.find_var(v.name), jax.Array)
                       and not _is_replicated(scope.find_var(v.name))]
            if sharded:
                vals["@shard_manifest@"] = np.asarray(
                    "\n".join(sorted(sharded)))
        rng = scope.find_var(_RNG_STATE)
        if rng is not None:
            if jax.dtypes.issubdtype(getattr(rng, "dtype", None),
                                     jax.dtypes.prng_key):
                # typed keys can't cross numpy; store raw data + impl name
                vals["@rng@"] = np.asarray(jax.random.key_data(rng))
                vals["@rng_impl@"] = np.asarray(
                    str(jax.random.key_impl(rng)))
            else:
                vals["@rng@"] = np.asarray(rng)
        self._thread = threading.Thread(
            target=self._write, args=(step, vals, shards, rank), daemon=True)
        self._thread.start()
        if blocking:
            self.wait()

    def wait(self):
        """Join the in-flight write; re-raises a writer failure (a silently
        lost checkpoint must not look durable)."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("checkpoint write failed") from err

    def _assemble_shards(self, step: int) -> Dict[str, np.ndarray]:
        """Merge every rank's shard files into full host arrays: works
        under ANY process count / mesh on restore — the reshardable part of
        the contract. Missing coverage raises instead of returning
        silently-partial parameters."""
        import json

        out: Dict[str, np.ndarray] = {}
        meta: Dict[str, dict] = {}
        placed: Dict[str, int] = {}
        for fname in sorted(os.listdir(self.dirname)):
            if not (fname.startswith(f"ckpt-{step}.index-")
                    and fname.endswith(".json")):
                continue
            rank = fname[len(f"ckpt-{step}.index-"):-len(".json")]
            with open(os.path.join(self.dirname, fname)) as f:
                index = json.load(f)
            spath = os.path.join(self.dirname,
                                 f"ckpt-{step}.shards-{rank}.pkl")
            with open(spath, "rb") as f:
                data = pickle.load(f)
            for name, ent in index.items():
                if name not in out:
                    out[name] = np.empty(tuple(ent["shape"],),
                                         dtype=ent["dtype"])
                    meta[name] = ent
                    placed[name] = 0
                for sh in ent["shards"]:
                    sl = tuple(slice(a, b) for a, b in sh["bounds"])
                    piece = data[sh["key"]]
                    out[name][sl] = piece
                    placed[name] += int(piece.size)
        for name, arr in out.items():
            if placed[name] < arr.size:
                raise RuntimeError(
                    f"checkpoint step {step}: sharded var {name!r} has only "
                    f"{placed[name]}/{arr.size} elements across the rank "
                    f"index files — a rank's shard file is missing")
        return out

    def restore(self, step: Optional[int] = None,
                program: Optional[Program] = None,
                scope: Optional[Scope] = None) -> Optional[int]:
        """Load step (default: latest durable) into the scope as host arrays;
        the next compiled step lifts them into the current mesh's shardings —
        save under dp=8, restore under dp=4×tp=2 just works."""
        program = program or default_main_program()
        scope = scope or _scope()
        self.wait()
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        path = self._existing_path(step)
        if path is None:
            return None
        if path.endswith(".ptck"):
            from ..native import read_bundle
            bundle = read_bundle(path)
            if bundle is None:
                raise RuntimeError(f"cannot read native checkpoint {path}")
            bundle.pop("@step@", None)
            payload = {"step": step, "vars": bundle}
        else:
            with open(path, "rb") as f:
                payload = pickle.load(f)
        names = {v.name for v in program.list_vars() if v.persistable}
        manifest_raw = payload["vars"].pop("@shard_manifest@", None)
        for n, arr in payload["vars"].items():
            if n in names:
                scope.set_var(n, arr)
        assembled = self._assemble_shards(step)
        if manifest_raw is not None:
            # backends may round-trip the string as a 0-d or 1-element array
            raw = np.asarray(manifest_raw).ravel()
            expected = set("\n".join(str(x) for x in raw).split("\n"))
            missing = sorted((expected & names) - set(assembled))
            if missing:
                raise RuntimeError(
                    f"checkpoint step {step}: sharded vars {missing} are in "
                    "the save-time manifest but absent from every rank's "
                    "index file — a rank's shard/index files are missing "
                    "(e.g. crash between rank-0's marker write and that "
                    "rank's background shard write)")
        for n, arr in assembled.items():
            if n in names:
                scope.set_var(n, arr)
        if "@rng@" in payload["vars"]:  # resume the random stream too
            import jax
            import jax.numpy as jnp
            raw = payload["vars"]["@rng@"]
            impl = payload["vars"].get("@rng_impl@")
            if impl is not None:
                key = jax.random.wrap_key_data(jnp.asarray(raw),
                                               impl=str(impl))
            else:
                key = jnp.asarray(raw)
            scope.set_var(_RNG_STATE, key)
        return payload["step"]


def save_checkpoint(dirname: str, step: int, program=None, scope=None,
                    blocking: bool = True):
    ck = Checkpointer(dirname)
    ck.save(step, program=program, scope=scope, blocking=blocking)
    return ck


def load_checkpoint(dirname: str, program=None, scope=None,
                    step: Optional[int] = None):
    return Checkpointer(dirname).restore(step, program=program, scope=scope)
