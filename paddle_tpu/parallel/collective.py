"""Functional collectives over named mesh axes.

Reference analog: operators/collective/ c_* ops + python/paddle/fluid/
layers/collective.py (_allreduce:20, _c_broadcast:93, _c_allgather:108,
_c_reducescatter:133). `ring_id` ↔ axis name; NCCL streams/sync ops vanish
(XLA orders by data dependence).

Two usage contexts:
- inside `shard_map` per-device code: these are thin lax wrappers;
- at the array level: `shard_map`-wrapped helpers below take a Mesh and
  return globally-transformed arrays.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # JAX 0.9: jax.shard_map; older: jax.experimental.shard_map
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(f, mesh, in_specs, out_specs, check_vma=False,
              axis_names=None):
    """Thin wrapper; `axis_names` (a subset of mesh axes) makes only those
    axes manual — the rest stay under automatic GSPMD propagation inside the
    body. That is how manual schedules (the GPipe ppermute ring) compose
    with automatic dp/tp sharding in ONE program."""
    if axis_names is not None:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_vma,
                          axis_names=frozenset(axis_names))
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=check_vma)


# -- per-device primitives (use inside shard_map) ---------------------------

def psum(x, axis: str):
    return lax.psum(x, axis)


def pmean(x, axis: str):
    return lax.pmean(x, axis)


def ppermute(x, axis: str, perm):
    return lax.ppermute(x, axis, perm)


def axis_index(axis: str):
    return lax.axis_index(axis)


# -- array-level collectives (build + run a shard_map) ----------------------

def all_reduce(x, mesh: Mesh, axis: str, op: str = "sum"):
    """c_allreduce_{sum,max,min} parity on an axis-sharded array."""
    fns = {"sum": lax.psum, "max": lax.pmax, "min": lax.pmin, "mean": lax.pmean}
    fn = fns[op]
    spec = P(axis)
    return shard_map(lambda v: fn(v, axis), mesh,
                     in_specs=(spec,), out_specs=spec)(x)


def all_gather(x, mesh: Mesh, axis: str, tiled: bool = True):
    """c_allgather parity: gather shards along leading dim."""
    return shard_map(lambda v: lax.all_gather(v, axis, tiled=tiled), mesh,
                     in_specs=(P(axis),), out_specs=P())(x)


def reduce_scatter(x, mesh: Mesh, axis: str):
    """c_reducescatter parity: x replicated → scattered sums."""
    return shard_map(lambda v: lax.psum_scatter(v, axis, tiled=True), mesh,
                     in_specs=(P(),), out_specs=P(axis))(x)


def broadcast(x, mesh: Mesh, axis: str, root: int = 0):
    """c_broadcast parity: root's shard replicated to all."""

    def f(v):
        idx = lax.axis_index(axis)
        src = jnp.where(idx == root, v, jnp.zeros_like(v))
        return lax.psum(src, axis)

    return shard_map(f, mesh, in_specs=(P(axis),), out_specs=P())(x)


def all_to_all(x, mesh: Mesh, axis: str, split_axis: int, concat_axis: int):
    """Ulysses-style head/sequence exchange (no reference analog — new
    capability for sequence parallelism)."""

    def f(v):
        return lax.all_to_all(v, axis, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)

    return shard_map(f, mesh, in_specs=(P(axis),), out_specs=P(axis))(x)


def barrier(mesh: Mesh, axis: Optional[str] = None):
    """fetch_barrier/send_barrier analog: a psum forces a sync point."""
    axes = [axis] if axis else list(mesh.axis_names)
    x = jnp.zeros(())
    for a in axes:
        x = shard_map(lambda v: lax.psum(v, a), mesh,
                      in_specs=(P(),), out_specs=P())(x)
    return jax.block_until_ready(x)
