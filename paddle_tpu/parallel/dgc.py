"""Deep Gradient Compression — top-k sparsified gradient exchange.

Reference analog: the DGC stack (``DGCMomentumOptimizer``
python/paddle/fluid/optimizer.py:799, ``SparseAllReduceOpHandle``
paddle/fluid/framework/details/sparse_all_reduce_op_handle.cc): keep the
top k% of each gradient by magnitude, accumulate the rest locally as an
error-feedback residual, exchange only the sparse entries. (The
reference's additional momentum-correction of the residual is left to
the caller's optimizer state.)

TPU stance: on ICI, dense all-reduce usually wins (the framework's
DGCMomentumOptimizer therefore behaves as Momentum, documented) — but the
capability matters on DCN-connected multi-slice topologies, so the real
algorithm is provided here as a functional transform over `shard_map`:

- per device: residual += grad; pick top-k |residual|; zero them out of
  the residual (the rest carries over — DGC's error feedback);
- exchange: the sparse (values at fixed positions) contribution summed by
  a dense `psum` over a masked tensor. XLA has no sparse collective; the
  masked-dense psum moves the same bytes on wire only when the interconnect
  compresses zeros, so the win here is the ERROR-FEEDBACK SEMANTICS (train
  with 99% sparsified exchange) while staying static-shape. A gather-based
  [k]-value exchange (true bandwidth saving, DCN path) is
  `sparse_allgather_exchange` below.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .collective import shard_map


def top_k_sparsify(g, ratio: float) -> Tuple[jax.Array, jax.Array]:
    """(sparse_grad, new_residual): keep the top `ratio` fraction of |g|,
    the rest becomes the carried residual. Static shapes (k fixed)."""
    flat = g.reshape(-1)
    k = max(1, int(flat.shape[0] * ratio))
    _, idx = lax.top_k(jnp.abs(flat), k)
    mask = jnp.zeros_like(flat).at[idx].set(1.0)
    sparse = (flat * mask).reshape(g.shape)
    return sparse, g - sparse


def dgc_allreduce(grad, residual, mesh: Mesh, axis: str = "dp",
                  ratio: float = 0.01):
    """One DGC exchange: error-feedback accumulate, top-k select, psum.

    Returns (summed_sparse_grad, new_residual) — both per-device arrays
    ([dp, ...] stacked outside shard_map, unsharded inside).
    """

    def f(g, r):
        acc = g + r
        sparse, new_r = top_k_sparsify(acc, ratio)
        return lax.psum(sparse, axis), new_r

    return shard_map(f, mesh, in_specs=(P(axis), P(axis)),
                     out_specs=(P(), P(axis)))(grad, residual)


def sparse_allgather_exchange(grad, residual, mesh: Mesh, axis: str = "dp",
                              ratio: float = 0.01):
    """The DCN-shaped variant: exchange only [k] values + [k] indices via
    all_gather and scatter-add locally — wire bytes are O(k·world), the
    reference SparseAllReduceOpHandle's encoded form."""

    def f(g, r):
        acc = (g + r).reshape(-1)
        k = max(1, int(acc.shape[0] * ratio))
        vals, idx = lax.top_k(jnp.abs(acc), k)
        vals = acc[idx]
        new_r = acc.at[idx].set(0.0).reshape(g.shape)
        all_vals = lax.all_gather(vals, axis)     # [world, k]
        all_idx = lax.all_gather(idx, axis)       # [world, k]
        out = jnp.zeros_like(acc)
        out = out.at[all_idx.reshape(-1)].add(all_vals.reshape(-1))
        return out.reshape(g.shape), new_r

    return shard_map(f, mesh, in_specs=(P(axis), P(axis)),
                     out_specs=(P(), P(axis)))(grad, residual)
