"""Multi-host bootstrap.

Reference analog: `gen_nccl_id` socket exchange (distributed_ops/
gen_nccl_id_op.cc), transpiler nccl2 mode env wiring (PADDLE_TRAINER_ID /
PADDLE_TRAINER_ENDPOINTS — distribute_transpiler.py:259), and launch.py.

TPU-native: `jax.distributed.initialize` replaces the id exchange; env vars
keep the reference names for drop-in launcher compatibility.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def init_parallel_env(coordinator_address: Optional[str] = None,
                      num_processes: Optional[int] = None,
                      process_id: Optional[int] = None) -> bool:
    """Initialize multi-host JAX from args or PADDLE_*-style env vars.
    Returns True if distributed mode is active."""
    global _initialized
    if _initialized:
        return jax.process_count() > 1

    endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
    trainer_id = os.environ.get("PADDLE_TRAINER_ID", "")
    if coordinator_address is None and endpoints:
        coordinator_address = endpoints.split(",")[0]
        num_processes = num_processes or len(endpoints.split(","))
        process_id = process_id if process_id is not None else int(trainer_id or 0)
    if coordinator_address is None:
        return False  # single process
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    return True


def get_world_size() -> int:
    try:
        return jax.process_count()
    except Exception:
        return 1


def get_rank() -> int:
    try:
        return jax.process_index()
    except Exception:
        return 0
