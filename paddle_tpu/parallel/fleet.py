"""Fleet — the high-level distributed-training API.

Reference analog: ``python/paddle/fluid/incubate/fleet/base/fleet_base.py:37``
(Fleet abstract: init/is_worker/run_server/…), role_maker.py:30 (RoleMakerBase,
PaddleCloudRoleMaker env-based, UserDefinedRoleMaker), and the collective
implementation (incubate/fleet/collective/__init__.py:41 CollectiveOptimizer).

TPU-native: only the collective mode exists (pserver mode is a documented
non-goal — SURVEY §2.2 Pslib row); workers are jax processes, the optimizer
wraps the program in a data-parallel CompiledProgram over the fleet mesh.
"""
from __future__ import annotations

import os
from typing import List, Optional

import jax

from ..core.compiler import BuildStrategy, CompiledProgram
from ..core.program import default_main_program
from .env import init_parallel_env
from .mesh import DistributedStrategy, auto_mesh


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER

    def generate_role(self):
        pass

    def is_worker(self) -> bool:
        return self._role == Role.WORKER

    def is_server(self) -> bool:
        return False  # no pservers on TPU

    def is_first_worker(self) -> bool:
        return self.worker_index() == 0

    def worker_num(self) -> int:
        return 1

    def worker_index(self) -> int:
        return 0


class PaddleCloudRoleMaker(RoleMakerBase):
    """Env-var role maker (role_maker.py PaddleCloudRoleMaker parity):
    reads PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS."""

    def __init__(self, is_collective: bool = True):
        super().__init__()
        self._is_collective = is_collective

    def generate_role(self):
        init_parallel_env()

    def worker_num(self) -> int:
        try:
            return jax.process_count()
        except Exception:
            return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))

    def worker_index(self) -> int:
        try:
            return jax.process_index()
        except Exception:
            return int(os.environ.get("PADDLE_TRAINER_ID", 0))


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id: int = 0, role=Role.WORKER,
                 worker_num: int = 1, server_endpoints=None):
        super().__init__()
        self._cur = current_id
        self._num = worker_num
        self._role = role

    def worker_num(self) -> int:
        return self._num

    def worker_index(self) -> int:
        return self._cur


class Fleet:
    """fleet_base.py:37 surface, collective-only."""

    def __init__(self):
        self._role_maker: Optional[RoleMakerBase] = None
        self._strategy: Optional[DistributedStrategy] = None
        self.main_program = None

    def init(self, role_maker: Optional[RoleMakerBase] = None,
             is_collective: bool = True):
        self._role_maker = role_maker or PaddleCloudRoleMaker()
        self._role_maker.generate_role()
        return self

    def is_worker(self) -> bool:
        return self._role_maker is None or self._role_maker.is_worker()

    def is_server(self) -> bool:
        return False

    def is_first_worker(self) -> bool:
        return self._role_maker is None or self._role_maker.is_first_worker()

    def worker_num(self) -> int:
        return self._role_maker.worker_num() if self._role_maker else 1

    def worker_index(self) -> int:
        return self._role_maker.worker_index() if self._role_maker else 0

    def worker_endpoints(self) -> List[str]:
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")

    # collective mode has no servers; these are no-ops for API compat
    def init_worker(self):
        pass

    def init_server(self, *a, **kw):
        pass

    def run_server(self):
        raise RuntimeError("parameter servers are a non-goal on TPU "
                           "(use sharded embeddings — SURVEY §2.2)")

    def stop_worker(self):
        pass

    def barrier_worker(self):
        try:
            if jax.process_count() > 1:
                from .collective import barrier
                from jax.sharding import Mesh
                import numpy as np
                barrier(Mesh(np.array(jax.devices()), ("dp",)))
        except Exception:
            pass

    def distributed_optimizer(self, optimizer, strategy: Optional[DistributedStrategy] = None):
        self._strategy = strategy or DistributedStrategy()
        return DistributedOptimizer(self, optimizer, self._strategy)

    def save_persistables(self, executor, dirname, main_program=None):
        from .. import io
        if self.is_first_worker():
            io.save_persistables(executor, dirname, main_program)

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None):
        from .. import io
        if self.is_first_worker():
            io.save_inference_model(dirname, feeded_var_names, target_vars,
                                    executor, main_program)


class DistributedOptimizer:
    """CollectiveOptimizer parity (fleet/collective/__init__.py:139): wraps a
    regular optimizer; minimize() additionally builds the data-parallel
    CompiledProgram over the strategy mesh."""

    def __init__(self, fleet: Fleet, optimizer, strategy: DistributedStrategy):
        self._fleet = fleet
        self._inner = optimizer
        self._strategy = strategy

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ops, pg = self._inner.minimize(loss, startup_program, parameter_list,
                                       no_grad_set)
        program = loss.block.program
        if self._strategy.tensor_parallel_degree > 1:
            from .tensor_parallel import annotate_tp
            annotate_tp(program)
        mesh = self._strategy.build_mesh()
        self._fleet.main_program = CompiledProgram(program).with_mesh(
            mesh, data_axis="dp", strategy=self._strategy)
        return ops, pg

    def __getattr__(self, name):
        return getattr(self._inner, name)


fleet = Fleet()
