"""Fleet — the high-level distributed-training API.

Reference analog: ``python/paddle/fluid/incubate/fleet/base/fleet_base.py:37``
(Fleet abstract: init/is_worker/run_server/…), role_maker.py:30 (RoleMakerBase,
PaddleCloudRoleMaker env-based, UserDefinedRoleMaker), and the collective
implementation (incubate/fleet/collective/__init__.py:41 CollectiveOptimizer).

TPU-native collective mode: workers are jax processes, the optimizer wraps
the program in a data-parallel CompiledProgram over the fleet mesh. Since
the PS embedding tier landed (paddle_tpu.ps), role makers can also produce
SERVER roles — ``TRAINING_ROLE=PSERVER`` + ``PADDLE_PSERVER_ENDPOINTS``
turn a process into an embedding shard server (``fleet.init_server()`` /
``run_server()``), mirroring the reference's transpiler/pslib launch
environment. Servers never touch jax or the TPU.
"""
from __future__ import annotations

import os
from typing import List, Optional

import jax

from ..core.compiler import BuildStrategy, CompiledProgram
from ..core.program import default_main_program
from .env import init_parallel_env
from .mesh import DistributedStrategy, auto_mesh


class Role:
    WORKER = 1
    SERVER = 2


def _pserver_endpoints_env() -> List[str]:
    """The pserver endpoint list from either env spelling the reference
    launchers used (fleet launch_ps: PADDLE_PSERVERS_IP_PORT_LIST;
    transpiler docs: PADDLE_PSERVER_ENDPOINTS)."""
    raw = (os.environ.get("PADDLE_PSERVER_ENDPOINTS")
           or os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST") or "")
    return [e.strip() for e in raw.split(",") if e.strip()]


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER

    def generate_role(self):
        pass

    def is_worker(self) -> bool:
        return self._role == Role.WORKER

    def is_server(self) -> bool:
        return self._role == Role.SERVER

    def is_first_worker(self) -> bool:
        return self.is_worker() and self.worker_index() == 0

    def worker_num(self) -> int:
        return 1

    def worker_index(self) -> int:
        return 0

    def server_num(self) -> int:
        return len(self.server_endpoints())

    def server_index(self) -> int:
        return 0

    def server_endpoints(self) -> List[str]:
        return []


class PaddleCloudRoleMaker(RoleMakerBase):
    """Env-var role maker (role_maker.py PaddleCloudRoleMaker parity):
    TRAINING_ROLE selects TRAINER vs PSERVER; trainers read
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS,
    servers read PADDLE_PSERVER_ENDPOINTS (or the launcher's
    PADDLE_PSERVERS_IP_PORT_LIST) with the current server resolved from
    PADDLE_PSERVER_ID, or POD_IP:PADDLE_PORT matched against the list."""

    def __init__(self, is_collective: bool = True):
        super().__init__()
        self._is_collective = is_collective
        self._server_eps: List[str] = []
        self._server_idx = 0

    def generate_role(self):
        role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._server_eps = _pserver_endpoints_env()
        if role == "PSERVER":
            self._role = Role.SERVER
            if not self._server_eps:
                raise ValueError(
                    "TRAINING_ROLE=PSERVER but no PADDLE_PSERVER_ENDPOINTS/"
                    "PADDLE_PSERVERS_IP_PORT_LIST in the environment")
            sid = os.environ.get("PADDLE_PSERVER_ID")
            if sid is not None:
                self._server_idx = int(sid)
            else:
                cur = (f"{os.environ.get('POD_IP', '127.0.0.1')}:"
                       f"{os.environ.get('PADDLE_PORT', '')}")
                if cur not in self._server_eps:
                    raise ValueError(
                        f"cannot locate this pserver: {cur!r} is not in "
                        f"the endpoint list {self._server_eps} (set "
                        f"PADDLE_PSERVER_ID, or POD_IP + PADDLE_PORT)")
                self._server_idx = self._server_eps.index(cur)
            if not (0 <= self._server_idx < len(self._server_eps)):
                raise ValueError(
                    f"PADDLE_PSERVER_ID={self._server_idx} out of range "
                    f"for {len(self._server_eps)} endpoints")
            return  # a server must not grab the TPU / jax distributed
        self._role = Role.WORKER
        init_parallel_env()

    def worker_num(self) -> int:
        try:
            return jax.process_count()
        except Exception:
            return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))

    def worker_index(self) -> int:
        try:
            return jax.process_index()
        except Exception:
            return int(os.environ.get("PADDLE_TRAINER_ID", 0))

    def server_index(self) -> int:
        return self._server_idx

    def server_endpoints(self) -> List[str]:
        return list(self._server_eps)


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id: int = 0, role=Role.WORKER,
                 worker_num: int = 1, server_endpoints=None):
        super().__init__()
        self._cur = current_id
        self._num = worker_num
        self._role = role
        self._server_eps = list(server_endpoints or [])

    def worker_num(self) -> int:
        return self._num

    def worker_index(self) -> int:
        return self._cur if self._role == Role.WORKER else 0

    def server_index(self) -> int:
        return self._cur if self._role == Role.SERVER else 0

    def server_endpoints(self) -> List[str]:
        return list(self._server_eps)


class Fleet:
    """fleet_base.py:37 surface, collective-only."""

    def __init__(self):
        self._role_maker: Optional[RoleMakerBase] = None
        self._strategy: Optional[DistributedStrategy] = None
        self.main_program = None
        self._ps_server = None

    def init(self, role_maker: Optional[RoleMakerBase] = None,
             is_collective: bool = True):
        self._role_maker = role_maker or PaddleCloudRoleMaker()
        self._role_maker.generate_role()
        return self

    def is_worker(self) -> bool:
        return self._role_maker is None or self._role_maker.is_worker()

    def is_server(self) -> bool:
        return self._role_maker is not None and self._role_maker.is_server()

    def is_first_worker(self) -> bool:
        return self._role_maker is None or self._role_maker.is_first_worker()

    def worker_num(self) -> int:
        return self._role_maker.worker_num() if self._role_maker else 1

    def worker_index(self) -> int:
        return self._role_maker.worker_index() if self._role_maker else 0

    def worker_endpoints(self) -> List[str]:
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")

    def server_num(self) -> int:
        return self._role_maker.server_num() if self._role_maker else 0

    def server_index(self) -> int:
        return self._role_maker.server_index() if self._role_maker else 0

    def server_endpoints(self) -> List[str]:
        return (self._role_maker.server_endpoints() if self._role_maker
                else [])

    def init_worker(self):
        pass

    def init_server(self, shards=None, endpoint: Optional[str] = None):
        """Stand up this process's embedding shard server (reference
        ``fleet.init_server()``; ``run_server()`` then blocks serving).

        shards: the ``ps.EmbeddingShard`` slices this server hosts — e.g.
        ``ps.make_shards(...)[fleet.server_index()]`` per table. Without
        shards this stays the collective-mode no-op.
        endpoint: bind address; defaults to this server's entry in the
        role maker's endpoint list.
        """
        if shards is None:
            return None
        from ..ps.transport import ShardServer
        if endpoint is None:
            eps = self.server_endpoints()
            if not eps:
                raise RuntimeError(
                    "fleet.init_server: no endpoint given and the role "
                    "maker has no server endpoints (set "
                    "PADDLE_PSERVER_ENDPOINTS / TRAINING_ROLE=PSERVER)")
            endpoint = eps[self.server_index()]
        host, port = endpoint.rsplit(":", 1)
        self._ps_server = ShardServer(shards, host=host, port=int(port))
        return self._ps_server

    def run_server(self):
        """Serve embedding shards until shutdown (blocks)."""
        if self._ps_server is None:
            raise RuntimeError(
                "fleet.run_server: call init_server(shards=...) first "
                "(dense pserver mode remains a non-goal on TPU; only the "
                "paddle_tpu.ps embedding tier has servers)")
        self._ps_server.serve_forever()

    def stop_server(self):
        if self._ps_server is not None:
            self._ps_server.stop()
            self._ps_server = None

    def stop_worker(self):
        pass

    def barrier_worker(self):
        try:
            if jax.process_count() > 1:
                from .collective import barrier
                from jax.sharding import Mesh
                import numpy as np
                barrier(Mesh(np.array(jax.devices()), ("dp",)))
        except Exception:
            pass

    def distributed_optimizer(self, optimizer, strategy: Optional[DistributedStrategy] = None):
        self._strategy = strategy or DistributedStrategy()
        return DistributedOptimizer(self, optimizer, self._strategy)

    def save_persistables(self, executor, dirname, main_program=None):
        from .. import io
        if self.is_first_worker():
            io.save_persistables(executor, dirname, main_program)

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None):
        from .. import io
        if self.is_first_worker():
            io.save_inference_model(dirname, feeded_var_names, target_vars,
                                    executor, main_program)


class DistributedOptimizer:
    """CollectiveOptimizer parity (fleet/collective/__init__.py:139): wraps a
    regular optimizer; minimize() additionally builds the data-parallel
    CompiledProgram over the strategy mesh."""

    def __init__(self, fleet: Fleet, optimizer, strategy: DistributedStrategy):
        self._fleet = fleet
        self._inner = optimizer
        self._strategy = strategy

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ops, pg = self._inner.minimize(loss, startup_program, parameter_list,
                                       no_grad_set)
        program = loss.block.program
        if self._strategy.tensor_parallel_degree > 1:
            from .tensor_parallel import annotate_tp
            annotate_tp(program)
        mesh = self._strategy.build_mesh()
        self._fleet.main_program = CompiledProgram(program).with_mesh(
            mesh, data_axis="dp", strategy=self._strategy)
        return ops, pg

    def __getattr__(self, name):
        return getattr(self._inner, name)


fleet = Fleet()
