"""LocalSGD — periodically-averaged independent replicas.

Reference analog: ``python/paddle/fluid/transpiler/collective.py:269``
(LocalSGD transpiler: snapshot params, train without gradient sync,
all-reduce-average the params every k steps).

TPU-native redesign: GSPMD data parallelism keeps ONE logical replica
(grads all-reduce implicitly), so LocalSGD's "divergent replicas" need the
replica dimension to be explicit: parameters carry a leading [dp] axis and
the whole train step runs under `shard_map` over the dp mesh axis — each
device updates its own replica with NO cross-device traffic; every
`k_steps` a `lax.pmean` averages the replicas (the only collective). This
is the same trade the reference makes (comm every k steps instead of every
step), expressed as sharding instead of graph rewriting.
"""
from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .collective import all_reduce, shard_map


def replicate_params(params, n_replicas: int):
    """Stack each param into [n_replicas, ...] (every replica starts
    identical — the reference's init broadcast)."""
    return jax.tree_util.tree_map(
        lambda p: jnp.broadcast_to(p[None], (n_replicas,) + p.shape), params)


def average_params(params, mesh: Mesh, axis: str = "dp"):
    """The k-step synchronization: mean over the replica axis."""
    return jax.tree_util.tree_map(
        lambda p: all_reduce(p, mesh, axis, op="mean"), params)


def local_sgd_step(grad_fn: Callable, mesh: Mesh, axis: str = "dp",
                   k_steps: int = 4, lr: float = 0.1):
    """Build a LocalSGD step.

    grad_fn(params, batch) -> (loss, grads) for ONE replica's [...] params
    and its [local_batch, ...] shard. Returns step(params, batch, i) over
    stacked [dp, ...] params and [global_batch, ...] data; `i` must be a
    python int — the sync decision is made at TRACE time, so two programs
    are compiled and the local-steps program contains NO parameter
    collective at all (only the scalar loss pmean). That is the point of
    LocalSGD: wire traffic every k-th step only.
    """

    def per_replica(do_sync, params, batch):
        # inside shard_map each leaf keeps a leading dp-extent-1 dim; strip
        # it so grad_fn sees the true per-replica shapes the docstring
        # promises, and restore it on the way out
        local = jax.tree_util.tree_map(lambda p: p[0], params)
        loss, grads = grad_fn(local, batch)
        new_local = jax.tree_util.tree_map(
            lambda p, g: p - lr * g, local, grads)
        if do_sync:
            new_local = jax.tree_util.tree_map(
                lambda p: lax.pmean(p, axis), new_local)
        new_params = jax.tree_util.tree_map(lambda p: p[None], new_local)
        return new_params, lax.pmean(loss, axis)

    def _mapped(do_sync):
        return jax.jit(shard_map(
            partial(per_replica, do_sync), mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=(P(axis), P())))

    step_local, step_sync = _mapped(False), _mapped(True)

    def step(params, batch, i):
        if (int(i) + 1) % k_steps == 0:
            return step_sync(params, batch)
        return step_local(params, batch)

    return step
