"""Mesh construction + strategy knobs.

Reference analog: NCCLContextMap/NCCLCommunicator ring construction
(nccl_helper.h:90,179 — flat + hierarchical + multi-ring) and fleet
DistributedStrategy (incubate/fleet/collective/__init__.py:93).

TPU-native: one `jax.sharding.Mesh` with named axes (dp/tp/pp/sp/ep) over the
physical device grid replaces every ring; XLA routes collectives over ICI
within an axis and DCN across slices — the hierarchical-allreduce topology of
the reference is implicit in device order.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(axis_sizes: Dict[str, int], devices=None) -> Mesh:
    """make_mesh({'dp': 2, 'tp': 4}) over the first prod(sizes) devices."""
    names = tuple(axis_sizes)
    sizes = tuple(axis_sizes[n] for n in names)
    n = int(np.prod(sizes))
    devs = np.array(devices if devices is not None else jax.devices()[:n])
    if devs.size < n:
        raise ValueError(f"need {n} devices for mesh {axis_sizes}, have {devs.size}")
    return Mesh(devs[:n].reshape(sizes), names)


def auto_mesh(dp: Optional[int] = None, tp: int = 1, pp: int = 1, sp: int = 1,
              devices=None) -> Mesh:
    """Fill the dp axis with whatever devices remain after tp/pp/sp."""
    devs = list(devices if devices is not None else jax.devices())
    denom = tp * pp * sp
    if dp is None:
        dp = len(devs) // denom
    axes = {}
    for name, size in (("dp", dp), ("pp", pp), ("tp", tp), ("sp", sp)):
        if size > 1 or name == "dp":
            axes[name] = size
    return make_mesh(axes, devs)


class DistributedStrategy:
    """fleet DistributedStrategy parity — knobs map to mesh/sharding choices
    rather than NCCL ring counts."""

    def __init__(self):
        self.tensor_parallel_degree = 1
        self.pipeline_parallel_degree = 1
        self.sequence_parallel_degree = 1
        self.sharding_degree = 1          # ZeRO-style optimizer sharding
        # ShardingStrategy stage once sharding is on: 1 = state sharding,
        # 2 = state + gradient reduce-scatter, 3 = full-parameter FSDP —
        # parameters live dp-sharded and are all-gathered on use
        # (compiler.ShardingStrategy)
        self.sharding_stage = 1
        self.amp = False
        self.recompute = False            # legacy: jax.checkpoint on blocks
        # remat policy surface (compiler.resolve_remat): None defers to the
        # legacy `recompute` bool; else "none" | "minimal" | "full" | a
        # per-unit predicate `unit_name -> False|True|"minimal"|"full"`
        self.remat_policy = None
        self.gradient_merge_steps = 1     # microbatch accumulation
        # sharded parameter-server embedding tier (paddle_tpu.ps):
        # 0 = tables stay as ordinary in-program params; N >= 1 = range-
        # partition each PS-bound table over N shards
        self.embedding_shards = 0
        # pull prefetch depth (batches converted+pulled ahead of compute;
        # 0 = inline pulls) and push staleness (0 = synchronous exact,
        # k >= 1 = at most k push batches in flight behind compute)
        self.pull_ahead = 1
        self.push_depth = 0
        # device-resident hot-row cache over the PS tier (ps.hot_cache):
        # 0 = stream every touched row per step; N >= 1 = keep N
        # LFU-admitted rows resident in HBM with write-back eviction
        # (PDTPU_PS_HOT_ROWS overrides when left at 0)
        self.hot_rows = 0
        # reference-compat knobs (no-ops on TPU; XLA owns these)
        self.nccl_comm_num = 1
        self.use_hierarchical_allreduce = False
        self.fuse_all_reduce_ops = True

    def build_mesh(self, devices=None) -> Mesh:
        return auto_mesh(tp=self.tensor_parallel_degree,
                         pp=self.pipeline_parallel_degree,
                         sp=self.sequence_parallel_degree,
                         devices=devices)
