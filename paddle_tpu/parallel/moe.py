"""Mixture-of-Experts with expert parallelism over a named mesh axis.

No reference analog: barrierye/Paddle has no MoE/expert-parallel machinery
(its closest sparse-capacity idea is the pserver-sharded embedding,
operators/distributed/parameter_prefetch.cc). This is a new first-class
parallel axis of the TPU build (SURVEY §5 "long-context/parallelism" gap),
designed XLA-first:

- Static capacity dispatch (GShard/Switch style): every shape is fixed at
  trace time — tokens route into an [E, C, D] buffer via one-hot einsums, so
  the MXU does the dispatch and no dynamic shapes leak into the graph.
- Expert parallelism via `lax.all_to_all` inside `shard_map`: tokens are
  sharded over the `ep` axis (the data axis doubles as the expert axis, the
  standard TPU layout), experts are sharded over the same axis; one
  all-to-all sends token slices to their experts' hosts, a second brings
  results home. Both ride ICI.
- Load-balance aux loss (Switch: E * Σ_e f_e·P_e) with globally-psummed
  statistics so the loss is identical no matter how the batch is sharded.

The dense path (`moe_ffn`) and the expert-parallel path
(`moe_ffn_expert_parallel`) compute identical results when capacity is not
exceeded — tested in tests/test_moe.py.
"""
from __future__ import annotations

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .collective import shard_map


class GateOutput(NamedTuple):
    combine: jax.Array   # [N, E, C] float — combine weights (0 where dropped)
    dispatch: jax.Array  # [N, E, C] bool  — dispatch mask
    aux_loss: jax.Array  # []  load-balance loss
    probs: jax.Array     # [N, E] softmax router probabilities


def top_k_gating(x, gate_w, k: int = 2, capacity: int = 0,
                 capacity_factor: float = 1.25, renormalize: bool = True,
                 axis: Optional[str] = None) -> GateOutput:
    """Static-capacity top-k router.

    x: [N, D] tokens, gate_w: [D, E]. Returns combine/dispatch tensors with a
    fixed per-expert capacity C (computed from capacity_factor if capacity is
    0). When `axis` is given (inside shard_map), aux-loss statistics are
    psum-averaged across the axis so the loss matches the unsharded run.
    """
    n, _ = x.shape
    e = gate_w.shape[1]
    if capacity <= 0:
        capacity = max(1, int(math.ceil(k * n / e * capacity_factor)))
    c = capacity

    logits = jnp.dot(x.astype(jnp.float32), gate_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)                      # [N, E]

    gate_vals, gate_idx = lax.top_k(probs, k)                    # [N, k]
    if renormalize:
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

    # Slot-major priority: all slot-0 assignments claim capacity before any
    # slot-1 assignment (GShard ordering).
    combine = jnp.zeros((n, e, c), dtype=jnp.float32)
    counts = jnp.zeros((e,), dtype=jnp.int32)   # tokens already placed per expert
    for j in range(k):
        onehot = jax.nn.one_hot(gate_idx[:, j], e, dtype=jnp.int32)  # [N, E]
        pos = jnp.cumsum(onehot, axis=0) - onehot + counts[None, :]  # [N, E]
        pos_j = jnp.sum(pos * onehot, axis=1)                        # [N]
        keep = pos_j < c
        counts = counts + jnp.sum(onehot, axis=0)
        pos_oh = jax.nn.one_hot(pos_j, c, dtype=jnp.float32)         # [N, C]
        combine = combine + (gate_vals[:, j] * keep)[:, None, None] \
            * onehot.astype(jnp.float32)[:, :, None] * pos_oh[:, None, :]

    dispatch = combine > 0.0

    # Switch load-balance loss on the top-1 assignment.
    top1 = jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32)
    frac_tokens = jnp.mean(top1, axis=0)       # f_e
    frac_probs = jnp.mean(probs, axis=0)       # P_e
    if axis is not None:
        frac_tokens = lax.pmean(frac_tokens, axis)
        frac_probs = lax.pmean(frac_probs, axis)
    aux = e * jnp.sum(frac_tokens * frac_probs)
    return GateOutput(combine.astype(x.dtype), dispatch, aux, probs)


def _expert_ffn(h, w1, b1, w2, b2, act):
    """h: [E_local, C', D]; w1: [E_local, D, H]; w2: [E_local, H, D]."""
    u = jnp.einsum("ecd,edh->ech", h, w1) + b1[:, None, :]
    u = act(u)
    return jnp.einsum("ech,ehd->ecd", u, w2) + b2[:, None, :]


def moe_ffn(x, gate_w, w1, b1, w2, b2, k: int = 2,
            capacity_factor: float = 1.25, act=jax.nn.gelu):
    """Dense (single-device) MoE FFN. x: [N, D] → [N, D], plus aux loss.

    gate_w: [D, E]; w1: [E, D, H]; b1: [E, H]; w2: [E, H, D]; b2: [E, D].
    """
    gate = top_k_gating(x, gate_w, k=k, capacity_factor=capacity_factor)
    expert_in = jnp.einsum(
        "nec,nd->ecd", gate.dispatch.astype(x.dtype), x)         # [E, C, D]
    expert_out = _expert_ffn(expert_in, w1, b1, w2, b2, act)     # [E, C, D]
    y = jnp.einsum("nec,ecd->nd", gate.combine, expert_out)
    return y, gate.aux_loss


def moe_ffn_expert_parallel(x, gate_w, w1, b1, w2, b2, mesh: Mesh,
                            axis: str = "ep", k: int = 2,
                            capacity_factor: float = 1.25, act=jax.nn.gelu):
    """Expert-parallel MoE FFN over `axis`.

    x is sharded on tokens along `axis` ([N, D] global, N/ep per device);
    expert weights are sharded on the expert dim. Two all-to-alls move token
    slices to expert hosts and back. Per-device capacity is computed from
    the *local* token count, so the result equals the dense path run on each
    shard's tokens independently (same router, same weights).
    """
    ep = mesh.shape[axis]
    e = gate_w.shape[1]
    if e % ep != 0:
        raise ValueError(f"num experts {e} not divisible by mesh axis {ep}")

    def local(xs, gw, w1s, b1s, w2s, b2s):
        # xs: [N/ep, D]; expert weights: local shard [E/ep, ...]
        gate = top_k_gating(xs, gw, k=k, capacity_factor=capacity_factor,
                            axis=axis)
        exp_in = jnp.einsum("nec,nd->ecd", gate.dispatch.astype(xs.dtype), xs)
        # [E, C, D] → each device keeps its E/ep experts, gathering every
        # device's token slice along capacity: [E/ep, C*ep, D]
        exp_in = lax.all_to_all(exp_in, axis, split_axis=0, concat_axis=1,
                                tiled=True)
        exp_out = _expert_ffn(exp_in, w1s, b1s, w2s, b2s, act)
        # route results home: [E/ep, C*ep, D] → [E, C, D]
        exp_out = lax.all_to_all(exp_out, axis, split_axis=1, concat_axis=0,
                                 tiled=True)
        y = jnp.einsum("nec,ecd->nd", gate.combine, exp_out)
        return y, gate.aux_loss

    f = shard_map(local, mesh,
                  in_specs=(P(axis), P(), P(axis), P(axis), P(axis), P(axis)),
                  out_specs=(P(axis), P()))
    return f(x, gate_w, w1, b1, w2, b2)


def init_moe_params(rng, d_model: int, d_hidden: int, num_experts: int,
                    dtype=jnp.float32):
    """Convenience initializer returning (gate_w, w1, b1, w2, b2)."""
    k1, k2, k3 = jax.random.split(rng, 3)
    s1 = 1.0 / math.sqrt(d_model)
    s2 = 1.0 / math.sqrt(d_hidden)
    return (
        jax.random.normal(k1, (d_model, num_experts), dtype) * s1,
        jax.random.normal(k2, (num_experts, d_model, d_hidden), dtype) * s1,
        jnp.zeros((num_experts, d_hidden), dtype),
        jax.random.normal(k3, (num_experts, d_hidden, d_model), dtype) * s2,
        jnp.zeros((num_experts, d_model), dtype),
    )
