"""Pipeline parallelism — GPipe over a `pp` mesh axis.

Reference analog: PipelineOptimizer (optimizer.py:2677 — program cut into
sections) + PipelineTrainer/SectionWorker (section_worker.cc:141 — scopes
flowing through CPU queues between device sections).

TPU-native redesign: scope-queues don't exist under XLA; instead every device
holds one stage's parameters (stage-stacked pytree sharded on `pp`), and a
`lax.scan` over M + n - 1 ticks moves activations along the ring with
`ppermute` — the whole schedule compiles into one XLA program,
differentiable end-to-end (grads of ppermute are the reverse permute, so the
backward pipeline falls out of autodiff).
"""
from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .collective import shard_map


def _pipe_local(params, xs, stage_fn, axis: str):
    """Per-device GPipe schedule. params: this stage's params (leading stage
    dim already sliced to 1 by shard_map — squeezed here). xs: a payload
    PYTREE of [M, mb, ...] microbatch arrays; the first leaf is the pipeline
    value, the rest (per-microbatch side inputs like attention masks) travel
    with it through the ring."""
    tmap = jax.tree_util.tree_map
    n = lax.psum(1, axis)
    idx = lax.axis_index(axis)
    params = tmap(lambda p: jnp.squeeze(p, 0), params)
    m = jax.tree_util.tree_leaves(xs)[0].shape[0]

    def step(carry, t):
        buf_in, outbuf = carry
        tc = jnp.clip(t, 0, m - 1)
        x_t = tmap(lambda a: lax.dynamic_index_in_dim(a, tc, 0, keepdims=False), xs)
        inp = tmap(lambda a, b: jnp.where(idx == 0, a, b), x_t, buf_in)
        out = stage_fn(params, inp)
        pos = t - (n - 1)
        write = jnp.logical_and(idx == n - 1, pos >= 0)
        out_x = jax.tree_util.tree_leaves(out)[0]
        upd = lax.dynamic_update_index_in_dim(outbuf, out_x, jnp.clip(pos, 0, m - 1), 0)
        outbuf = jnp.where(write, upd, outbuf)
        perm = [(i, (i + 1) % n) for i in range(n)]
        nxt = tmap(lambda a: lax.ppermute(a, axis, perm), out)
        return (nxt, outbuf), None

    x0 = tmap(lambda a: a[0], xs)
    out_shape = jax.eval_shape(stage_fn, params, x0)
    first = jax.tree_util.tree_leaves(out_shape)[0]
    init = (tmap(lambda s: jnp.zeros(s.shape, s.dtype), out_shape),
            jnp.zeros((m,) + first.shape, first.dtype))
    (_, outbuf), _ = lax.scan(step, init, jnp.arange(m + n - 1))
    # only the last stage holds real outputs; replicate via masked psum
    outbuf = lax.psum(jnp.where(idx == n - 1, outbuf, jnp.zeros_like(outbuf)), axis)
    return outbuf


def pipeline_step(stage_fn: Callable, stacked_params, xs, mesh: Mesh,
                  axis: str = "pp", data_axis: str = None):
    """Run microbatches [M, mb, ...] through n_stages = mesh.shape[axis]
    identical-signature stages. stacked_params: pytree with leading stage dim
    == n_stages. Returns outputs [M, mb, ...].

    `data_axis` (optional): a mesh axis the per-microbatch batch dim is
    sharded over — pp×dp composition; each dp shard runs its own pipeline.

    Constraint (GPipe over a ring): every stage's output shape must equal its
    input shape (standard for transformer blocks)."""
    pspec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    if data_axis is not None and data_axis not in mesh.axis_names:
        raise ValueError(
            f"pipeline_step: data_axis {data_axis!r} is not a mesh axis "
            f"{mesh.axis_names} — a typo here would silently all-gather the "
            f"batch and lose data parallelism")
    one_spec = P(None, data_axis) if data_axis is not None else P()
    xspec = jax.tree_util.tree_map(lambda _: one_spec, xs)
    fn = shard_map(partial(_pipe_local, stage_fn=stage_fn, axis=axis),
                   mesh, in_specs=(pspec, xspec), out_specs=one_spec)
    return fn(stacked_params, xs)


class GPipe:
    """PipelineOptimizer-parity convenience wrapper.

    Usage::

        pipe = GPipe(block_fn, mesh, axis="pp")
        loss = pipe.loss(stacked_params, x_microbatches, loss_fn)
        grads = jax.grad(pipe.loss)(stacked_params, ...)
    """

    def __init__(self, stage_fn: Callable, mesh: Mesh, axis: str = "pp"):
        self.stage_fn = stage_fn
        self.mesh = mesh
        self.axis = axis

    def __call__(self, stacked_params, xs):
        return pipeline_step(self.stage_fn, stacked_params, xs, self.mesh, self.axis)

    def loss(self, stacked_params, xs, loss_fn):
        out = self(stacked_params, xs)
        return loss_fn(out)
