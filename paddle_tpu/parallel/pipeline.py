"""Pipeline parallelism — GPipe over a `pp` mesh axis.

Reference analog: PipelineOptimizer (optimizer.py:2677 — program cut into
sections) + PipelineTrainer/SectionWorker (section_worker.cc:141 — scopes
flowing through CPU queues between device sections).

TPU-native redesign: scope-queues don't exist under XLA; instead every device
holds one stage's parameters (stage-stacked pytree sharded on `pp`), and a
`lax.scan` over M + n - 1 ticks moves activations along the ring with
`ppermute` — the whole schedule compiles into one XLA program,
differentiable end-to-end (grads of ppermute are the reverse permute, so the
backward pipeline falls out of autodiff).
"""
from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .collective import shard_map


def _pipe_local(params, xs, stage_fn, axis: str):
    """Per-device GPipe schedule. params: this stage's params (leading stage
    dim already sliced to 1 by shard_map — squeezed here). xs: a payload
    PYTREE of [M, mb, ...] microbatch arrays; the first leaf is the pipeline
    value, the rest (per-microbatch side inputs like attention masks) travel
    with it through the ring."""
    tmap = jax.tree_util.tree_map
    n = lax.psum(1, axis)
    idx = lax.axis_index(axis)
    params = tmap(lambda p: jnp.squeeze(p, 0), params)
    m = jax.tree_util.tree_leaves(xs)[0].shape[0]

    def step(carry, t):
        buf_in, outbuf = carry
        tc = jnp.clip(t, 0, m - 1)
        x_t = tmap(lambda a: lax.dynamic_index_in_dim(a, tc, 0, keepdims=False), xs)
        inp = tmap(lambda a, b: jnp.where(idx == 0, a, b), x_t, buf_in)
        out = stage_fn(params, inp)
        pos = t - (n - 1)
        write = jnp.logical_and(idx == n - 1, pos >= 0)
        out_x = jax.tree_util.tree_leaves(out)[0]
        upd = lax.dynamic_update_index_in_dim(outbuf, out_x, jnp.clip(pos, 0, m - 1), 0)
        outbuf = jnp.where(write, upd, outbuf)
        perm = [(i, (i + 1) % n) for i in range(n)]
        nxt = tmap(lambda a: lax.ppermute(a, axis, perm), out)
        return (nxt, outbuf), None

    x0 = tmap(lambda a: a[0], xs)
    out_shape = jax.eval_shape(stage_fn, params, x0)
    first = jax.tree_util.tree_leaves(out_shape)[0]
    init = (tmap(lambda s: jnp.zeros(s.shape, s.dtype), out_shape),
            jnp.zeros((m,) + first.shape, first.dtype))
    (_, outbuf), _ = lax.scan(step, init, jnp.arange(m + n - 1))
    # only the last stage holds real outputs; replicate via masked psum
    outbuf = lax.psum(jnp.where(idx == n - 1, outbuf, jnp.zeros_like(outbuf)), axis)
    return outbuf


def pipeline_step(stage_fn: Callable, stacked_params, xs, mesh: Mesh,
                  axis: str = "pp", data_axis: str = None):
    """Run microbatches [M, mb, ...] through n_stages = mesh.shape[axis]
    identical-signature stages. stacked_params: pytree with leading stage dim
    == n_stages. Returns outputs [M, mb, ...].

    `data_axis` (optional): a mesh axis the per-microbatch batch dim is
    sharded over — validated here, but the sharding itself rides GSPMD.

    Only the pipeline axis is MANUAL in the shard_map; every other mesh axis
    (dp, tp, ...) stays automatic inside the stage body, so GSPMD keeps the
    batch dp-sharded and inserts the Megatron tp collectives for shard_spec
    parameters — dp×tp×pp composes in one program instead of one segment
    per axis.

    Constraint (GPipe over a ring): every stage's output shape must equal its
    input shape (standard for transformer blocks)."""
    pspec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    if data_axis is not None and data_axis not in mesh.axis_names:
        raise ValueError(
            f"pipeline_step: data_axis {data_axis!r} is not a mesh axis "
            f"{mesh.axis_names} — a typo here would silently all-gather the "
            f"batch and lose data parallelism")
    one_spec = P()
    xspec = jax.tree_util.tree_map(lambda _: one_spec, xs)
    fn = shard_map(partial(_pipe_local, stage_fn=stage_fn, axis=axis),
                   mesh, in_specs=(pspec, xspec), out_specs=one_spec,
                   axis_names={axis})
    return fn(stacked_params, xs)


def _1f1b_local(params, x, caps, *, stage_fn, loss_fn, axis, n, m):
    """Per-device 1F1B schedule (reference section_worker.cc:141's concurrent
    sections, rebuilt as one lax.scan): each tick runs one forward microbatch
    AND one backward microbatch (different indices), so at most 2n−1
    microbatch activations are ever live per device — the 1F1B memory bound —
    instead of the GPipe-through-autodiff O(m) carry.

    Backward recomputes the stage forward from the saved stage INPUT
    (activation recompute, the standard trade), so only ring inputs are
    buffered. Timeline: device i fwds microbatch f at tick t=f+i and bwds
    microbatch b at t=b+n+(n−1−i); total ticks m+2n−1.
    """
    tmap = jax.tree_util.tree_map
    idx = lax.axis_index(axis)
    K = 2 * n - 1                       # in-flight residual slots
    params1 = tmap(lambda p: jnp.squeeze(p, 0), params)

    def at(tree, i):
        return tmap(lambda a: lax.dynamic_index_in_dim(a, i, 0,
                                                       keepdims=False), tree)

    def stage_x(p, xleaf, cap):
        return stage_fn(p, (xleaf, *cap))[0]

    mb_shape = jax.eval_shape(lambda a: at(a, 0), x)

    def tick(carry, t):
        fwd_in, cot_in, prev_out, resid, grads, loss_acc = carry

        # ---- last stage turns yesterday's forward into a cotangent ----
        lmb = t - n                       # prev_out's microbatch at stage n-1
        lvalid = jnp.logical_and(lmb >= 0, lmb < m)
        lval, dout = jax.value_and_grad(loss_fn)(prev_out)
        loss_acc = loss_acc + jnp.where(
            jnp.logical_and(idx == n - 1, lvalid), lval / m, 0.0)

        # ---- backward of microbatch b = t - n - (n-1-idx) ----
        # (reads its residual BEFORE this tick's forward overwrites the
        # slot: at device 0, microbatch f and f-K share a slot on the same
        # tick — read-before-write keeps K at 2n-1)
        b = t - n - (n - 1 - idx)
        bvalid = jnp.logical_and(b >= 0, b < m)
        bc = jnp.clip(b, 0, m - 1)
        inp_b = lax.dynamic_index_in_dim(resid, bc % K, 0, keepdims=False)
        cap_b = at(caps, bc)
        cot = jnp.where(idx == n - 1, dout / m, cot_in)
        _, vjp_fn = jax.vjp(stage_x, params1, inp_b, cap_b)
        dparams, dinp, _ = vjp_fn(cot)
        grads = tmap(lambda g, d: g + jnp.where(bvalid, d, 0.0),
                     grads, dparams)

        # ---- forward of microbatch f = t - idx ----
        f = t - idx
        fvalid = jnp.logical_and(f >= 0, f < m)
        fc = jnp.clip(f, 0, m - 1)
        inp = jnp.where(idx == 0, at(x, fc), fwd_in)
        cap_f = at(caps, fc)
        out = stage_x(params1, inp, cap_f)
        upd = lax.dynamic_update_index_in_dim(resid, inp, fc % K, 0)
        resid = jnp.where(fvalid, upd, resid)

        # ---- rings: activations forward, cotangents backward ----
        fwd_next = lax.ppermute(out, axis, [(i, (i + 1) % n)
                                            for i in range(n)])
        cot_next = lax.ppermute(dinp, axis, [(i, (i - 1) % n)
                                             for i in range(n)])
        return (fwd_next, cot_next, out, resid, grads, loss_acc), None

    zeros_mb = jnp.zeros(mb_shape.shape, mb_shape.dtype)
    init = (zeros_mb, zeros_mb, zeros_mb,
            jnp.zeros((K,) + mb_shape.shape, mb_shape.dtype),
            tmap(jnp.zeros_like, params1),
            jnp.float32(0.0))
    carry, _ = lax.scan(tick, init, jnp.arange(m + 2 * n - 1))
    grads, loss_acc = carry[4], carry[5]
    loss = lax.psum(jnp.where(idx == n - 1, loss_acc, 0.0), axis)
    grads = tmap(lambda g: jnp.expand_dims(g, 0), grads)
    return loss, grads


def pipeline_1f1b(stage_fn, stacked_params, xs, loss_fn, mesh: Mesh,
                  axis: str = "pp"):
    """1F1B pipelined train step: returns (loss, grads, info).

    stage_fn(params, payload) -> payload, payload = (x, *captures) with x
    the [mb, ...] ring value (stage output shape == input shape, as for
    GPipe). xs: payload pytree of [m, mb, ...] microbatch arrays — the
    first leaf rides the ppermute ring; the remaining leaves (masks etc.)
    are indexed per microbatch and do not travel. loss_fn maps the last
    stage's [mb, ...] output to a scalar; total loss is the mean over
    microbatches, and grads match stacked_params' [n_stages, ...] layout.

    info reports the schedule: ticks = m+2n−1; every tick runs one masked
    fwd + one masked bwd, so the bubble fraction is (2n−1)/(m+2n−1) and at
    most 2n−1 microbatch inputs are resident per device (the 1F1B point —
    GPipe-through-autodiff buffers all m).
    """
    n = mesh.shape[axis]
    leaves = jax.tree_util.tree_leaves(xs)
    m = leaves[0].shape[0]
    x, caps = leaves[0], tuple(leaves[1:])
    pspec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    fn = shard_map(
        partial(_1f1b_local, stage_fn=stage_fn, loss_fn=loss_fn, axis=axis,
                n=n, m=m),
        mesh,
        in_specs=(pspec, P(), jax.tree_util.tree_map(lambda _: P(), caps)),
        out_specs=(P(), pspec),
        axis_names={axis})
    loss, grads = fn(stacked_params, x, caps)
    info = {"ticks": m + 2 * n - 1,
            "bubble_fraction": (2 * n - 1) / (m + 2 * n - 1),
            "max_inflight_microbatches": 2 * n - 1}
    return loss, grads, info


def _flat_pad(v, pay):
    """[mb, ...] -> [mb, pay] (zero-padded flat payload)."""
    f = v.reshape(v.shape[0], -1)
    return jnp.pad(f, ((0, 0), (0, pay - f.shape[1])))


def _hetero_local(all_params, x, caps, *, stage_fns, in_shapes, out_shape,
                  axis, n, m, pay):
    """Per-device GPipe ring over NON-isomorphic stages: lax.switch picks
    this device's stage; the ring payload is a flat zero-padded [mb, pay]
    buffer so stages with different boundary shapes share one ppermute.

    Reference analog: heterogeneous trainer sections with per-section
    programs (section_worker.cc:141, trainer_desc.proto:66-84)."""
    idx = lax.axis_index(axis)

    def branch(i):
        shp = in_shapes[i]
        size = int(np.prod(shp[1:])) if len(shp) > 1 else 1

        def run(operand):
            buf, fc = operand
            xin = buf[:, :size].reshape(shp)
            cap_i = jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, fc, 0, keepdims=False),
                caps[i])
            y = stage_fns[i](all_params[i], xin, cap_i)
            return _flat_pad(y, pay)
        return run

    branches = [branch(i) for i in range(n)]

    def tick(carry, t):
        buf_in, outbuf = carry
        fc = jnp.clip(t - idx, 0, m - 1)
        x_t = lax.dynamic_index_in_dim(x, fc, 0, keepdims=False)
        inp = jnp.where(idx == 0, _flat_pad(x_t, pay), buf_in)
        out = lax.switch(idx, branches, (inp, fc))
        pos = t - (n - 1)
        osz = int(np.prod(out_shape[1:]))
        write = jnp.logical_and(idx == n - 1, pos >= 0)
        upd = lax.dynamic_update_index_in_dim(
            outbuf, out[:, :osz].reshape(out_shape),
            jnp.clip(pos, 0, m - 1), 0)
        outbuf = jnp.where(write, upd, outbuf)
        nxt = lax.ppermute(out, axis, [(i, (i + 1) % n) for i in range(n)])
        return (nxt, outbuf), None

    init = (jnp.zeros((in_shapes[0][0], pay), x.dtype),
            jnp.zeros((m,) + out_shape, x.dtype))
    (_, outbuf), _ = lax.scan(tick, init, jnp.arange(m + n - 1))
    return lax.psum(jnp.where(idx == n - 1, outbuf,
                              jnp.zeros_like(outbuf)), axis)


def pipeline_hetero(stage_fns, per_stage_params, xs, mesh: Mesh,
                    axis: str = "pp", caps=None):
    """GPipe over heterogeneous stages (different ops, params, and boundary
    shapes per stage — the reference's per-section programs).

    stage_fns[i](params_i, x, caps_i) -> y; boundary shapes are inferred by
    shape-chaining eval_shape through the stages. xs: [m, mb, ...]
    microbatches of stage 0's input; caps (optional): per-stage pytrees of
    [m, ...] per-microbatch side inputs (indexed, not ring-carried). All
    boundary tensors must share xs' dtype (the flat ring payload). Params
    ride replicated over the pipeline axis (capability over memory:
    heterogeneous trees cannot be stage-stacked); other mesh axes stay
    automatic, so dp/tp sharding still applies inside stages.

    Differentiable end-to-end: grads of every stage's params flow through
    the switch + ring via ordinary autodiff. The shard_map is FULLY manual
    (transposes of partial-manual shard_maps with replicated params deadlock
    XLA-CPU collectives as of jax 0.9), so non-pipeline mesh axes see
    replicated compute here — compose dp by batching microbatches instead."""
    n = len(stage_fns)
    if n != mesh.shape[axis]:
        raise ValueError(
            f"pipeline_hetero: {n} stages but mesh axis {axis!r} has "
            f"{mesh.shape[axis]} devices")
    m = xs.shape[0]
    if caps is None:
        caps = tuple(() for _ in range(n))
    mb_shape = tuple(xs.shape[1:])
    shapes = [mb_shape]
    for i in range(n):
        cap0 = jax.tree_util.tree_map(
            lambda a: jax.eval_shape(lambda v: v[0], a), caps[i])
        out = jax.eval_shape(
            lambda p, v, c, _i=i: stage_fns[_i](p, v, c),
            per_stage_params[i],
            jax.ShapeDtypeStruct(shapes[-1], xs.dtype), cap0)
        if out.dtype != xs.dtype:
            raise ValueError(
                f"pipeline_hetero: stage {i} output dtype {out.dtype} != "
                f"payload dtype {xs.dtype}")
        if out.shape[0] != mb_shape[0]:
            raise ValueError(
                f"pipeline_hetero: stage {i} changed the microbatch dim "
                f"({out.shape[0]} vs {mb_shape[0]})")
        shapes.append(tuple(out.shape))
    pay = max(int(np.prod(s[1:])) for s in shapes)
    in_shapes = shapes[:-1]
    out_shape = shapes[-1]

    pspec = jax.tree_util.tree_map(lambda _: P(), per_stage_params)
    cspec = jax.tree_util.tree_map(lambda _: P(), caps)
    fn = shard_map(
        partial(_hetero_local, stage_fns=stage_fns, in_shapes=in_shapes,
                out_shape=out_shape, axis=axis, n=n, m=m, pay=pay),
        mesh,
        in_specs=(pspec, P(), cspec),
        out_specs=P())
    return fn(per_stage_params, xs, caps)


class GPipe:
    """PipelineOptimizer-parity convenience wrapper.

    Usage::

        pipe = GPipe(block_fn, mesh, axis="pp")
        loss = pipe.loss(stacked_params, x_microbatches, loss_fn)
        grads = jax.grad(pipe.loss)(stacked_params, ...)
    """

    def __init__(self, stage_fn: Callable, mesh: Mesh, axis: str = "pp"):
        self.stage_fn = stage_fn
        self.mesh = mesh
        self.axis = axis

    def __call__(self, stacked_params, xs):
        return pipeline_step(self.stage_fn, stacked_params, xs, self.mesh, self.axis)

    def loss(self, stacked_params, xs, loss_fn):
        out = self(stacked_params, xs)
        return loss_fn(out)
