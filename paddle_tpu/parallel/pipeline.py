"""Pipeline parallelism — GPipe over a `pp` mesh axis.

Reference analog: PipelineOptimizer (optimizer.py:2677 — program cut into
sections) + PipelineTrainer/SectionWorker (section_worker.cc:141 — scopes
flowing through CPU queues between device sections).

TPU-native redesign: scope-queues don't exist under XLA; instead every device
holds one stage's parameters (stage-stacked pytree sharded on `pp`), and a
`lax.scan` over M + n - 1 ticks moves activations along the ring with
`ppermute` — the whole schedule compiles into one XLA program,
differentiable end-to-end (grads of ppermute are the reverse permute, so the
backward pipeline falls out of autodiff).
"""
from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .collective import shard_map


def _pipe_local(params, xs, stage_fn, axis: str):
    """Per-device GPipe schedule. params: this stage's params (leading stage
    dim already sliced to 1 by shard_map — squeezed here). xs: [M, mb, ...]
    microbatches (replicated)."""
    n = lax.psum(1, axis)
    idx = lax.axis_index(axis)
    params = jax.tree_util.tree_map(lambda p: jnp.squeeze(p, 0), params)
    m = xs.shape[0]

    def step(carry, t):
        buf_in, outbuf = carry
        x_t = lax.dynamic_index_in_dim(xs, jnp.clip(t, 0, m - 1), 0, keepdims=False)
        inp = jnp.where(idx == 0, x_t, buf_in)
        out = stage_fn(params, inp)
        pos = t - (n - 1)
        write = jnp.logical_and(idx == n - 1, pos >= 0)
        upd = lax.dynamic_update_index_in_dim(outbuf, out, jnp.clip(pos, 0, m - 1), 0)
        outbuf = jnp.where(write, upd, outbuf)
        perm = [(i, (i + 1) % n) for i in range(n)]
        nxt = lax.ppermute(out, axis, perm)
        return (nxt, outbuf), None

    out_shape = jax.eval_shape(stage_fn, params, xs[0])
    init = (jnp.zeros(out_shape.shape, out_shape.dtype),
            jnp.zeros((m,) + out_shape.shape, out_shape.dtype))
    (_, outbuf), _ = lax.scan(step, init, jnp.arange(m + n - 1))
    # only the last stage holds real outputs; replicate via masked psum
    outbuf = lax.psum(jnp.where(idx == n - 1, outbuf, jnp.zeros_like(outbuf)), axis)
    return outbuf


def pipeline_step(stage_fn: Callable, stacked_params, xs, mesh: Mesh,
                  axis: str = "pp"):
    """Run microbatches [M, mb, ...] through n_stages = mesh.shape[axis]
    identical-signature stages. stacked_params: pytree with leading stage dim
    == n_stages. Returns outputs [M, mb, ...].

    Constraint (GPipe over a ring): every stage's output shape must equal its
    input shape (standard for transformer blocks)."""
    pspec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    fn = shard_map(partial(_pipe_local, stage_fn=stage_fn, axis=axis),
                   mesh, in_specs=(pspec, P()), out_specs=P())
    return fn(stacked_params, xs)


class GPipe:
    """PipelineOptimizer-parity convenience wrapper.

    Usage::

        pipe = GPipe(block_fn, mesh, axis="pp")
        loss = pipe.loss(stacked_params, x_microbatches, loss_fn)
        grads = jax.grad(pipe.loss)(stacked_params, ...)
    """

    def __init__(self, stage_fn: Callable, mesh: Mesh, axis: str = "pp"):
        self.stage_fn = stage_fn
        self.mesh = mesh
        self.axis = axis

    def __call__(self, stacked_params, xs):
        return pipeline_step(self.stage_fn, stacked_params, xs, self.mesh, self.axis)

    def loss(self, stacked_params, xs, loss_fn):
        out = self(stacked_params, xs)
        return loss_fn(out)
