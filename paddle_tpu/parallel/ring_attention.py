"""Ring attention — sequence/context parallelism over the ICI ring.

No reference analog: the reference's "sequence" machinery is LoDTensor
batching, not parallelism (SURVEY §5). This is the new first-class axis the
TPU build adds: Q/K/V sharded along the sequence dim over the `sp` mesh axis;
K/V blocks rotate around the ring via `lax.ppermute` while each device
accumulates flash-style (running max / denominator) partial attention —
compute overlaps the permute, max context scales linearly with ring size.

Also provides Ulysses-style all-to-all head-parallel attention as the
alternative decomposition.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .collective import shard_map

_NEG = -1e9


def _ring_attn_local(q, k, v, axis: str, causal: bool):
    """Per-device body under shard_map. q,k,v: [B, H, Tl, D] local shards.

    Numerics (VERDICT r3 weak #3): the running max / denominator / output
    accumulate in FLOAT32 regardless of q.dtype — a bf16 softmax
    accumulator loses digits over long rings — and under ``causal`` the
    fully-masked future blocks (src > idx) SKIP their compute through
    lax.cond instead of computing-then-masking. The next block's K/V
    permute is issued BEFORE the block compute so XLA's async
    collective-permute can overlap the ICI hop with the matmuls."""
    n = lax.psum(1, axis)
    idx = lax.axis_index(axis)
    tl = q.shape[2]
    scale = 1.0 / math.sqrt(q.shape[-1])
    q_pos = idx * tl + jnp.arange(tl)
    qf = q.astype(jnp.float32)

    def block(k_cur, v_cur, src, diag):
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_cur.astype(jnp.float32))
        s = s * scale
        if diag:
            k_pos = src * tl + jnp.arange(tl)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG)
        m_b = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m_b)
        l_b = jnp.sum(p, axis=-1, keepdims=True)
        o_b = jnp.einsum("bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32))
        return m_b, l_b, o_b

    def step(carry, t):
        m, l, o, k_cur, v_cur = carry
        src = (idx - t) % n  # whose K/V block we hold this step
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = lax.ppermute(k_cur, axis, perm)   # overlaps block compute
        v_nxt = lax.ppermute(v_cur, axis, perm)
        if causal:
            zero = (jnp.full_like(m, _NEG), jnp.zeros_like(l),
                    jnp.zeros_like(o))
            m_b, l_b, o_b = lax.cond(
                src == idx,
                lambda _: block(k_cur, v_cur, src, True),
                lambda _: lax.cond(
                    src < idx,
                    lambda __: block(k_cur, v_cur, src, False),
                    lambda __: zero, None),
                None)
        else:
            m_b, l_b, o_b = block(k_cur, v_cur, src, False)
        m_new = jnp.maximum(m, m_b)
        corr = jnp.exp(m - m_new)
        corr_b = jnp.exp(m_b - m_new)
        l_new = l * corr + l_b * corr_b
        o_new = o * corr + o_b * corr_b
        return (m_new, l_new, o_new, k_nxt, v_nxt), None

    b, h, _, d = q.shape
    init = (jnp.full((b, h, tl, 1), _NEG, jnp.float32),
            jnp.zeros((b, h, tl, 1), jnp.float32),
            jnp.zeros((b, h, tl, d), jnp.float32), k, v)
    # remat the step: the vjp then RECOMPUTES each [Tl,Tl] score block in
    # the backward instead of storing n of them — O(Tl^2) live at a time,
    # linear in total T, which is the memory contract ring attention
    # exists for (the pallas path's backward reuses this oracle vjp)
    (m, l, o, _, _), _ = lax.scan(jax.checkpoint(step), init,
                                  jnp.arange(n))
    return (o / jnp.maximum(l, 1e-20)).astype(q.dtype)


def _ring_attn_flash_local(q, k, v, axis: str, causal: bool):
    """Pallas-kernel ring body (VERDICT r3 #5): each ring step runs the
    flash-attention forward kernel on the resident K/V block and merges
    the block's normalized output into the running result by
    log-sum-exp weights — all merge state in f32. The diagonal block runs
    the kernel's causal variant, earlier blocks the dense variant, and
    future blocks skip compute entirely (lax.cond). The K/V ppermute for
    the next step is issued before the kernel call so the ICI hop can
    overlap the block's matmuls (XLA async collective-permute; single-chip
    environments can't measure the overlap — the ordering enables it)."""
    from ..ops.pallas_kernels.flash_attention import _flash_fwd_dispatch

    n = lax.psum(1, axis)
    idx = lax.axis_index(axis)
    b, h, tl, d = q.shape
    scale = 1.0 / math.sqrt(d)

    def fold(x):
        return x.reshape(b * h, tl, d)

    qf = fold(q)

    def block(k_cur, v_cur, diag: bool):
        o_b, lse_b = _flash_fwd_dispatch(qf, fold(k_cur), fold(v_cur),
                                         None, None, scale, diag, 0.0)
        return o_b.astype(jnp.float32), lse_b.astype(jnp.float32)

    def step(carry, t):
        o_acc, lse_acc, k_cur, v_cur = carry
        src = (idx - t) % n
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = lax.ppermute(k_cur, axis, perm)   # overlaps kernel compute
        v_nxt = lax.ppermute(v_cur, axis, perm)
        if causal:
            skip = (jnp.zeros_like(o_acc), jnp.full_like(lse_acc, _NEG))
            o_b, lse_b = lax.cond(
                src == idx,
                lambda _: block(k_cur, v_cur, True),
                lambda _: lax.cond(
                    src < idx,
                    lambda __: block(k_cur, v_cur, False),
                    lambda __: skip, None),
                None)
        else:
            o_b, lse_b = block(k_cur, v_cur, False)
        # merge by lse weights: o_b is block-normalized, so the exact
        # combination is o = Σ_b o_b · exp(lse_b − lse_total); the running
        # form keeps o_acc normalized w.r.t. lse_acc, so each merge is the
        # CONVEX combination with weights w/(w_acc+w_b)
        m = jnp.maximum(lse_acc, lse_b)
        w_acc = jnp.exp(lse_acc - m)
        w_b = jnp.exp(lse_b - m)
        denom = w_acc + w_b
        o = (o_acc * w_acc[..., None] + o_b * w_b[..., None]) \
            / denom[..., None]
        lse = m + jnp.log(denom)
        return (o, lse, k_nxt, v_nxt), None

    init = (jnp.zeros((b * h, tl, d), jnp.float32),
            jnp.full((b * h, tl), _NEG, jnp.float32), k, v)
    (o, lse, _, _), _ = lax.scan(step, init, jnp.arange(n))
    return (o.reshape(b, h, tl, d).astype(q.dtype),
            lse.reshape(b, h, tl))


def _ring_flash_bwd_local(q, k, v, o, lse, g, axis: str, causal: bool):
    """Per-device ring BACKWARD (VERDICT r4 #3): reuses the Pallas
    dq/dkv kernels per ring block with f32 dq and rotating f32 dk/dv
    accumulators. The decomposition is exact: with the GLOBAL lse and
    delta=Σ dO·o as residuals, every (q-shard, kv-block) pair's
    contribution is independent — dq sums locally over blocks, dk/dv for
    each K/V block accumulate as the block (and its accumulator) rotate
    around the ring, arriving home after n hops. Future blocks under
    `causal` skip compute entirely (lax.cond), mirroring the forward."""
    from ..ops.pallas_kernels.flash_attention import _flash_bwd_block_dispatch

    n = lax.psum(1, axis)
    idx = lax.axis_index(axis)
    b, h, tl, d = q.shape
    scale = 1.0 / math.sqrt(d)

    def fold(x):
        return x.reshape(b * h, tl, x.shape[-1])

    qf, of, gf = fold(q), fold(o), fold(g.astype(q.dtype))
    lse_f = lse.reshape(b * h, tl)

    def block(k_cur, v_cur, diag: bool):
        dqb, dkb, dvb = _flash_bwd_block_dispatch(
            qf, fold(k_cur), fold(v_cur), gf, lse_f, of, scale, diag)
        return (dqb.astype(jnp.float32), dkb.astype(jnp.float32),
                dvb.astype(jnp.float32))

    def step(carry, t):
        dq_acc, k_cur, v_cur, dk_acc, dv_acc = carry
        src = (idx - t) % n
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = lax.ppermute(k_cur, axis, perm)  # overlaps kernel compute
        v_nxt = lax.ppermute(v_cur, axis, perm)
        if causal:
            zero = (jnp.zeros_like(dq_acc), jnp.zeros((b * h, tl, d),
                                                      jnp.float32),
                    jnp.zeros((b * h, tl, d), jnp.float32))
            dqb, dkb, dvb = lax.cond(
                src == idx,
                lambda _: block(k_cur, v_cur, True),
                lambda _: lax.cond(
                    src < idx,
                    lambda __: block(k_cur, v_cur, False),
                    lambda __: zero, None),
                None)
        else:
            dqb, dkb, dvb = block(k_cur, v_cur, False)
        dk_new = dk_acc + dkb.reshape(b, h, tl, d)
        dv_new = dv_acc + dvb.reshape(b, h, tl, d)
        # accumulators travel WITH their K/V block: after n hops each
        # block's grads arrive back at its home device
        return (dq_acc + dqb, k_nxt, v_nxt,
                lax.ppermute(dk_new, axis, perm),
                lax.ppermute(dv_new, axis, perm)), None

    init = (jnp.zeros((b * h, tl, d), jnp.float32), k, v,
            jnp.zeros((b, h, tl, d), jnp.float32),
            jnp.zeros((b, h, tl, d), jnp.float32))
    (dq, _, _, dk, dv), _ = lax.scan(step, init, jnp.arange(n))
    return (dq.reshape(b, h, tl, d).astype(q.dtype),
            dk.astype(k.dtype), dv.astype(v.dtype))


def _ring_flash_fwd_value(q, k, v, mesh, axis, causal):
    spec = P(None, None, axis, None)
    fn = shard_map(partial(_ring_attn_flash_local, axis=axis, causal=causal),
                   mesh, in_specs=(spec, spec, spec),
                   out_specs=(spec, P(None, None, axis)))
    return fn(q, k, v)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q, k, v, mesh, axis, causal):
    o, _ = _ring_flash_fwd_value(q, k, v, mesh, axis, causal)
    return o


def _ring_flash_fwd(q, k, v, mesh, axis, causal):
    o, lse = _ring_flash_fwd_value(q, k, v, mesh, axis, causal)
    return o, (q, k, v, o, lse)


def _ring_flash_bwd(mesh, axis, causal, res, g):
    q, k, v, o, lse = res
    spec = P(None, None, axis, None)
    lspec = P(None, None, axis)
    fn = shard_map(
        partial(_ring_flash_bwd_local, axis=axis, causal=causal),
        mesh, in_specs=(spec, spec, spec, spec, lspec, spec),
        out_specs=(spec, spec, spec))
    return fn(q, k, v, o, lse, g)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_self_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                        causal: bool = False, impl: str = "auto"):
    """Array-level entry: q/k/v [B, H, T, D] with T sharded on `axis`.

    impl: "jnp" (scan of einsums — the correctness oracle), "pallas"
    (flash kernel per ring block, jnp-oracle backward), or "auto"
    (pallas when the kernel supports the local block shape)."""
    if impl == "auto":
        from ..ops.pallas_kernels.flash_attention import _pallas_ok
        tl = q.shape[2] // mesh.shape[axis]
        impl = ("pallas" if _pallas_ok(tl, q.shape[-1]) else "jnp")
    if impl == "pallas":
        return _ring_flash(q, k, v, mesh, axis, causal)
    spec = P(None, None, axis, None)
    fn = shard_map(partial(_ring_attn_local, axis=axis, causal=causal),
                   mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


ring_attention = ring_self_attention


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                      causal: bool = False):
    """Ulysses decomposition: all-to-all converts seq-sharding into
    head-sharding, full attention runs locally, then back. Needs
    num_heads % axis_size == 0."""
    spec = P(None, None, axis, None)

    def local(qs, ks, vs):
        # [B, H, Tl, D] → exchange: heads scatter, seq gather → [B, H/n, T, D]
        def a2a(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

        qg, kg, vg = a2a(qs), a2a(ks), a2a(vs)
        scale = 1.0 / math.sqrt(qg.shape[-1])
        s = jnp.einsum("bhqd,bhkd->bhqk", qg, kg) * scale
        if causal:
            t = s.shape[-1]
            mask = jnp.tril(jnp.ones((t, t), bool))
            s = jnp.where(mask[None, None], s, _NEG)
        p = jax.nn.softmax(s, axis=-1)
        og = jnp.einsum("bhqk,bhkd->bhqd", p, vg)
        return lax.all_to_all(og, axis, split_axis=2, concat_axis=1, tiled=True)

    fn = shard_map(local, mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
