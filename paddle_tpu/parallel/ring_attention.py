"""Ring attention — sequence/context parallelism over the ICI ring.

No reference analog: the reference's "sequence" machinery is LoDTensor
batching, not parallelism (SURVEY §5). This is the new first-class axis the
TPU build adds: Q/K/V sharded along the sequence dim over the `sp` mesh axis;
K/V blocks rotate around the ring via `lax.ppermute` while each device
accumulates flash-style (running max / denominator) partial attention —
compute overlaps the permute, max context scales linearly with ring size.

Also provides Ulysses-style all-to-all head-parallel attention as the
alternative decomposition.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .collective import shard_map

_NEG = -1e9


def _ring_attn_local(q, k, v, axis: str, causal: bool):
    """Per-device body under shard_map. q,k,v: [B, H, Tl, D] local shards."""
    n = lax.psum(1, axis)
    idx = lax.axis_index(axis)
    tl = q.shape[2]
    scale = 1.0 / math.sqrt(q.shape[-1])
    q_pos = idx * tl + jnp.arange(tl)

    def step(carry, t):
        m, l, o, k_cur, v_cur = carry
        src = (idx - t) % n  # whose K/V block we hold this step
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur) * scale
        if causal:
            k_pos = src * tl + jnp.arange(tl)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o * corr + jnp.einsum("bhqk,bhkd->bhqd", p, v_cur)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        return (m_new, l_new, o_new, k_nxt, v_nxt), None

    b, h, _, d = q.shape
    init = (jnp.full((b, h, tl, 1), _NEG, q.dtype),
            jnp.zeros((b, h, tl, 1), q.dtype),
            jnp.zeros((b, h, tl, d), q.dtype), k, v)
    (m, l, o, _, _), _ = lax.scan(step, init, jnp.arange(n))
    return o / jnp.maximum(l, 1e-20)


def ring_self_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                        causal: bool = False):
    """Array-level entry: q/k/v [B, H, T, D] with T sharded on `axis`."""
    spec = P(None, None, axis, None)
    fn = shard_map(partial(_ring_attn_local, axis=axis, causal=causal),
                   mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


ring_attention = ring_self_attention


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                      causal: bool = False):
    """Ulysses decomposition: all-to-all converts seq-sharding into
    head-sharding, full attention runs locally, then back. Needs
    num_heads % axis_size == 0."""
    spec = P(None, None, axis, None)

    def local(qs, ks, vs):
        # [B, H, Tl, D] → exchange: heads scatter, seq gather → [B, H/n, T, D]
        def a2a(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

        qg, kg, vg = a2a(qs), a2a(ks), a2a(vs)
        scale = 1.0 / math.sqrt(qg.shape[-1])
        s = jnp.einsum("bhqd,bhkd->bhqk", qg, kg) * scale
        if causal:
            t = s.shape[-1]
            mask = jnp.tril(jnp.ones((t, t), bool))
            s = jnp.where(mask[None, None], s, _NEG)
        p = jax.nn.softmax(s, axis=-1)
        og = jnp.einsum("bhqk,bhkd->bhqd", p, vg)
        return lax.all_to_all(og, axis, split_axis=2, concat_axis=1, tiled=True)

    fn = shard_map(local, mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
