"""Tensor (model) parallelism — Megatron-style param sharding rules.

Reference status: TP was absent (SURVEY §2.2 row "Tensor/model parallel —
partial": only pserver-sharded embeddings via parameter_prefetch.cc). This is
a first-class capability here: parameters get PartitionSpec annotations and
GSPMD inserts the all-reduces a hand-written Megatron implementation would.

Rules map param-name regexes → PartitionSpec tuples. Column-parallel weights
shard the output dim, row-parallel shard the input dim; GSPMD then emits one
psum per transformer block (after attn-out and ffn2), exactly the Megatron
communication pattern, riding ICI.
"""
from __future__ import annotations

import re
from typing import Dict, Optional, Sequence, Tuple

from ..core.program import Parameter, Program

# rule: regex on param name → spec template with 'tp' marking the sharded dim
MEGATRON_RULES: Sequence[Tuple[str, Tuple]] = (
    (r".*\.qkv\.w$", (None, "tp")),      # column parallel
    (r".*\.qkv\.b$", ("tp",)),
    (r".*\.attn_out\.w$", ("tp", None)),  # row parallel
    (r".*\.ffn1\.w$", (None, "tp")),
    (r".*\.ffn1\.b$", ("tp",)),
    (r".*\.ffn2\.w$", ("tp", None)),
    (r"word_embedding$", ("tp", None)),   # vocab-sharded embedding
    (r"mlm_out\.w$", (None, "tp")),
    (r"mlm_out\.b$", ("tp",)),
)


def annotate_tp(program: Program, rules: Sequence[Tuple[str, Tuple]] = MEGATRON_RULES,
                axis: str = "tp") -> int:
    """Attach shard_spec to matching parameters. Returns #annotated.
    CompiledProgram.with_mesh then places them (compiler.py _state_sharding)."""
    count = 0
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    for p in program.all_parameters():
        for pat, spec in compiled:
            if pat.match(p.name):
                p.shard_spec = tuple(axis if s == "tp" else s for s in spec)
                count += 1
                break
    return count


def embedding_shard_spec(axis: str = "tp"):
    """Row(vocab)-sharded embedding table spec — the TPU replacement for the
    reference's distributed_lookup_table pserver path (SURVEY §2.2)."""
    return (axis, None)
